"""Continuous-batching scheduler: retire-and-refill at chunk boundaries.

PR 5's batched engines freeze a finished lane in place until the whole
batch drains — fine for a fixed workload, wrong for a server, where a
converged lane is a free slot someone is queueing for. This scheduler
generalizes the in-loop freeze-out mask of ``batch.batched_pcg`` from
*freeze* to *swap-in*: between chunks (the only place the host touches
the carry anyway — the resilience chunk stance), a finished lane's
slice of the carry is re-initialised with the next queued request's
embedded operands, and the same compiled bucket executable keeps
running — **no recompile**, because shapes are the only compile-time
facts (every per-request number — h1, h2, δ, the mask, the RHS — is a
traced operand, the ``runtime.compile_cache`` embedding made per-lane).
This is Orca-style iteration-level scheduling (Yu et al., OSDI '22)
with PCG chunks in place of decode steps.

The robustness envelope around the packing loop:

- **Admission** — bounded queue, backpressure, deadline-aware shedding
  (``serve.queue``); every rejection carries ``retry_after_s``.
- **Deadlines** — enforced at chunk granularity: expiry while queued is
  shed un-dispatched; expiry mid-solve cancels at the chunk boundary
  with a partial result (the ``run_report_partial`` stance per
  request); a request that converges at the same boundary its deadline
  passes gets its result (converged lanes retire *first* — no spurious
  miss).
- **Retries** — a per-request budget with exponential backoff walking
  the degradation ladder: quarantined/broken lane → resubmit on a
  fresh lane → guarded single solve (``resilience.guard``) as the final
  rung; whatever the ladder ends in is a classified outcome.
- **Durability** — a crash-safe request journal (``serve.journal``):
  admissions are journaled before they are acknowledged, so a killed
  server replays every admitted-but-unfinished request on restart.
- **Observability** — every admission/refill/retirement/shed/retry/
  replay is a request-addressed ``obs.trace`` event (schema v3) and an
  ``obs.metrics`` counter/histogram (``queue_depth``,
  ``time_in_queue_seconds``, ``deadline_miss_total``, ``shed_total``),
  exported via the ``--metrics`` OpenMetrics path.

Refill only targets the **classical** batched engine: a refilled lane
must be bit-identical to the same request solved on a fresh lane
(pinned in ``tests/test_batched.py``), and only the classical carry
round-trips exactly through ``init_state`` — the pipelined recurrence
seeds a multi-term history a mid-stream re-init would perturb.
"""

from __future__ import annotations

import functools
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from poisson_ellipse_tpu.batch import batched_pcg
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs import metrics as obs_metrics
from poisson_ellipse_tpu.obs import trace as obs_trace
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.resilience.errors import SolveError
from poisson_ellipse_tpu.resilience.faultinject import Fault, FaultPlan
from poisson_ellipse_tpu.runtime.compile_cache import grid_bucket
from poisson_ellipse_tpu.runtime.solvecache import SolveCache, solve_key
from poisson_ellipse_tpu.serve.journal import RequestJournal
from poisson_ellipse_tpu.serve.queue import AdmissionQueue
from poisson_ellipse_tpu.serve.request import ServeRequest, ServeResult

# the serve carry's global iteration ceiling: requests come and go, the
# batch's clock only moves forward — per-request caps are enforced
# host-side against each lane's swap-in offset
ITER_CEILING = 1 << 30

# classical batched carry layout (mirrors batch.driver._LAYOUT["batched"])
_IDX = {
    "k": 0, "w": 1, "r": 2, "p": 3, "zr": 4, "diff": 5,
    "conv": 6, "bd": 7, "quar": 8, "iters": 9,
}
_FIELDS = {"w": 1, "r": 2, "p": 3, "zr": 4}

DEFAULT_LANES = 4
DEFAULT_CHUNK = 16


@functools.lru_cache(maxsize=32)
def _bucket_advance(Mb: int, Nb: int, dtype_name: str, norm: str):
    """The bucket executable: ONE jitted chunk-advance per (bucket,
    dtype, norm), shared by every scheduler in the process. Operands,
    per-lane h/δ, masks, carry and bound are all traced arguments, so
    retire/refill/replay never retrace — the TPU010 stance, per bucket.
    """
    proto = Problem(M=Mb, N=Nb, norm=norm, max_iter=ITER_CEILING)

    def fn(a3, b3, mask, h1, h2, delta, state, limit):
        # the rhs positional only supplies a dtype to advance(); the
        # carry's own w plays that role here (rhs lives in r at init)
        return batched_pcg.advance(
            proto, a3, b3, state[1], state, limit=limit, mask=mask,
            h1=h1, h2=h2, delta=delta,
        )

    # no donation: the carry is re-read at every boundary for the
    # retire/refill host work
    return jax.jit(fn), proto  # tpulint: disable=TPU004


# no donation, matching _bucket_advance: the host re-reads the carry
# at every boundary, and CPU/CI backends would only warn
@jax.jit
# tpulint: disable=TPU004
def _refill_scatter(a3, b3, mask, h1, h2, delta, state, unit,
                    a_p, b_p, m_p, h1v, h2v, dv, lane):
    """One dispatch per refill: every operand slice and carry field of
    the lane scattered together. The serving target regime is
    dispatch-bound TPUs, where fifteen per-refill ``.at[].set`` round
    trips would eat the continuous-batching win; ``lane`` is traced, so
    shapes are the only compile keys (one build per bucket). Pure
    copies — bit-identical to the unfused form by construction."""
    a3 = a3.at[lane].set(a_p)
    b3 = b3.at[lane].set(b_p)
    mask = mask.at[lane].set(m_p)
    h1 = h1.at[lane].set(h1v)
    h2 = h2.at[lane].set(h2v)
    delta = delta.at[lane].set(dv)
    state = tuple(
        s if i == _IDX["k"] else s.at[lane].set(u[0])
        for i, (s, u) in enumerate(zip(state, unit))
    )
    return a3, b3, mask, h1, h2, delta, state


def embed_operands(problem: Problem, bucket: tuple[int, int], np_dtype,
                   a, b, rhs):
    """THE pad-and-mask bucket embedding: zero-padded operands plus the
    interior mask of the true problem (the ``runtime.compile_cache``
    layout, sliced per lane). One definition — ordinary requests
    (``_embed_request``) and grad-kind stages (``diff.serving.GradJob.
    embed``) must stay layout-identical by construction, not by
    parallel maintenance."""
    Mb, Nb = bucket
    g1, g2 = problem.M + 1, problem.N + 1
    pad2 = ((0, Mb + 1 - g1), (0, Nb + 1 - g2))
    mask = np.zeros((Mb + 1, Nb + 1), np_dtype)
    mask[1 : problem.M, 1 : problem.N] = 1.0
    return (
        np.pad(a, pad2).astype(np_dtype),
        np.pad(b, pad2).astype(np_dtype),
        np.pad(rhs, pad2).astype(np_dtype),
        mask,
    )


def _embed_request(problem: Problem, bucket: tuple[int, int], np_dtype,
                   geometry=None, theta=None):
    """Pad-and-mask one request into a bucket via ``embed_operands``.
    ``geometry``/``theta`` select the SDF quadrature assembly — a
    host-side operand fact, so an arbitrary domain rides the SAME
    bucket executable (shapes are the only compile keys)."""
    a, b, r = assembly.assemble_numpy(problem, geometry=geometry,
                                      theta=theta)
    return embed_operands(problem, bucket, np_dtype, a, b, r)


class _InFlight:
    """One dispatched request: which lane hosts it and at which global
    iteration it swapped in (``base_k`` — per-request iteration counts
    are ``iters[lane] - base_k``)."""

    __slots__ = ("req", "lane", "base_k", "t_dispatch", "cache_key",
                 "rhs_pad")

    def __init__(self, req: ServeRequest, lane: int, base_k: int,
                 t_dispatch: float):
        self.req = req
        self.lane = lane
        self.base_k = base_k
        self.t_dispatch = t_dispatch
        # warm-start bookkeeping (None when the pool was not consulted):
        # the request's solve-cache key and its EMBEDDED rhs — what the
        # retirement path needs to deposit the converged lane back into
        # the bucket's pool
        self.cache_key: Optional[str] = None
        self.rhs_pad = None


class _BatchCtx:
    """One grid bucket's live batch: the compiled advance, the carry,
    the per-lane operand stack, and the slot table."""

    def __init__(self, bucket: tuple[int, int], lanes: int, dtype, norm: str,
                 mesh=None):
        self.bucket = bucket
        self.norm = norm
        if mesh is not None:
            from poisson_ellipse_tpu.parallel.batched_sharded import (
                build_sharded_chunk_advance,
            )

            self.fn, self.proto = build_sharded_chunk_advance(
                bucket, mesh=mesh, lanes=lanes, norm=norm,
                iter_ceiling=ITER_CEILING,
            )
        else:
            self.fn, self.proto = _bucket_advance(
                bucket[0], bucket[1], jnp.dtype(dtype).name, norm
            )
        g = (lanes, bucket[0] + 1, bucket[1] + 1)
        zeros3 = jnp.zeros(g, dtype)
        self.a3 = zeros3
        self.b3 = zeros3
        self.mask = zeros3
        self.h1 = jnp.ones((lanes,), dtype)
        self.h2 = jnp.ones((lanes,), dtype)
        self.delta = jnp.full((lanes,), 1e-6, dtype)
        state = list(batched_pcg.init_state(
            self.proto, self.a3, self.b3, zeros3, mask=self.mask,
            h1=self.h1, h2=self.h2,
        ))
        # every lane starts parked: the breakdown flag freezes it until
        # a refill swaps a request in (zero-RHS lanes would otherwise
        # burn one iteration reaching the same flag)
        state[_IDX["bd"]] = jnp.ones((lanes,), bool)
        self.state = tuple(state)
        self.slots: list[Optional[_InFlight]] = [None] * lanes
        # per-bucket chunk override (None = the scheduler-wide default);
        # set at admission from the autotune registry (Scheduler._ctx_for)
        self.chunk: Optional[int] = None
        # the bucket's recycle pool (``runtime.solvecache``): bounded on
        # both axes, owned by THIS context — a mesh degrade/rejoin drops
        # the context (_degrade_mesh's _ctxs.clear()) and the pool dies
        # with it, so rebuilt batches never warm-start from state that
        # predates the event. None when the scheduler runs cache-off.
        self.pool: Optional[SolveCache] = None

    @property
    def active(self) -> bool:
        return any(s is not None for s in self.slots)

    def free_lane(self) -> Optional[int]:
        for lane, slot in enumerate(self.slots):
            if slot is None:
                return lane
        return None


class Scheduler:
    """The continuous-batching serve loop (see module docstring).

    ``clock`` is injectable (monotonic seconds) so deadline semantics
    are deterministically testable; ``idle`` is what ``drain`` calls
    when every queued request is waiting out a retry backoff (default
    ``time.sleep`` — pass the fake clock's ``advance`` in tests).
    ``faults`` takes request-addressed injections
    (``Fault(request_id=...)``); ``mesh`` routes the chunk advance
    through the lane-sharded composition (1 psum/iter, jaxpr-pinned).
    """

    def __init__(
        self,
        lanes: int = DEFAULT_LANES,
        chunk: int = DEFAULT_CHUNK,
        queue_capacity: int = 64,
        dtype=jnp.float32,
        max_retries: int = 1,
        backoff_base_s: float = 0.01,
        journal: RequestJournal | str | None = None,
        clock: Callable[[], float] = time.monotonic,
        idle: Callable[[float], None] = time.sleep,
        faults: Optional[FaultPlan] = None,
        keep_solutions: bool = True,
        mesh=None,
        class_quotas: Optional[dict] = None,
        starvation_after_s: Optional[float] = None,
        warm_start: bool = False,
    ):
        if chunk < 1:
            raise ValueError("chunk must be >= 1")
        if lanes < 1:
            raise ValueError("lanes must be >= 1")
        self.lanes = lanes
        self.chunk = chunk
        self.dtype = dtype
        self.max_retries = max_retries
        self.backoff_base_s = backoff_base_s
        self.clock = clock
        self.idle = idle
        self.faults = faults if faults is not None else FaultPlan()
        self.keep_solutions = keep_solutions
        self.mesh = mesh
        # warm_start=True turns on the per-bucket recycle pools: fresh
        # attempt-0 requests consult their bucket's SolveCache for a
        # nearest-neighbour x0 (the semantic cache), converged lanes
        # deposit back. OFF by default: a warm-started lane's solution
        # bits legitimately differ from a cold solve's (same l2, fewer
        # iterations), so the bit-parity pins of the cold serving path
        # stay the default contract and recycling is an explicit opt-in.
        self.warm_start = warm_start
        self.journal = (
            RequestJournal(journal) if isinstance(journal, (str, bytes))
            or hasattr(journal, "__fspath__") else journal
        )
        self.queue = AdmissionQueue(
            queue_capacity, lanes, clock=clock,
            class_quotas=class_quotas,
            starvation_after_s=starvation_after_s,
        )
        self.results: dict[str, ServeResult] = {}
        self._ctxs: dict[tuple, _BatchCtx] = {}
        # grad-kind lifecycle state (diff.serving.GradJob) keyed by
        # request id: host-only, NEVER journaled — a replayed grad
        # request rebuilds its job deterministically, which is what
        # makes the replayed gradient identical (chaos invariant)
        self._grad_jobs: dict[str, object] = {}
        self._np_dtype = assembly.numpy_dtype(dtype)
        # journaled requests recovered by replay() that exceeded queue
        # capacity: fed back into the queue in waves as it drains —
        # never terminally shed (the write-ahead promise outlives one
        # queue's worth of backlog)
        self._replay_backlog: list[ServeRequest] = []
        # graceful-drain latch (begin_drain): a draining scheduler
        # refuses NEW admissions with a redirectable shed but finishes
        # everything already admitted — the fleet's replica-drain hook
        # and the harness's SIGTERM path both flip it
        self.draining = False
        # how many redirect sheds the draining latch issued: those
        # results are deliberately NOT recorded (begin_drain docstring),
        # so without this count a replica killed mid-drain would leave
        # them invisible to the chaos report's zero-lost accounting
        self.drain_sheds = 0

    # -- admission -----------------------------------------------------------

    def submit(self, problem: Problem, deadline_s: float | None = None,
               max_retries: int | None = None,
               request_id: str | None = None,
               tenant: str = "default",
               priority: int = 1) -> Optional[ServeResult]:
        """Admit one request. Returns ``None`` on acceptance, or the
        terminal ``shed`` result (with ``retry_after_s``) when the
        admission policy rejects it."""
        req = ServeRequest(
            problem=problem,
            deadline=(
                None if deadline_s is None else self.clock() + deadline_s
            ),
            max_retries=(
                self.max_retries if max_retries is None else max_retries
            ),
            tenant=tenant,
            priority=priority,
        )
        if request_id is not None:
            req.request_id = request_id
        return self.submit_request(req)

    def _apply_admission_faults(self, req: ServeRequest) -> None:
        """Fire request-addressed ADMISSION faults (``malformed_spec`` /
        ``degenerate_geometry``): the request's geometry spec is swapped
        BEFORE validation, so the drill exercises the real gate."""
        from poisson_ellipse_tpu.resilience import faultinject

        for fault in self.faults.faults:
            if (fault.fired or fault.request_id != req.request_id
                    or fault.kind not in faultinject.ADMISSION_KINDS):
                continue
            fault.fired = True
            obs_trace.event(
                "serve:fault", request_id=req.request_id, lane=None,
                kind=fault.kind, at_iter=0,
            )
            if fault.kind == "malformed_spec":
                req.geometry = dict(faultinject.MALFORMED_SPEC)
            else:
                req.geometry = faultinject.sliver_spec()
                req.theta = fault.theta
            req._geom_obj = None

    def _validate_geometry(self, req: ServeRequest) -> Optional[ServeResult]:
        """The admission rung of the geometry gate: a request carrying a
        geometry spec is validated host-side AT ADMISSION — a bad one
        ends in the terminal classified ``invalid`` outcome (exit 8)
        without ever being journaled or dispatched. Mid-solve geometry
        failure is structurally impossible: no lane sees operands that
        did not pass this gate. Runs AFTER the bounded queue's capacity
        check: validation is real host work (quadrature assembly + the
        Lanczos probe), and overload must hit the cheap backpressure
        reject first, not an unmetered validation grinder."""
        if req.geometry is None:
            return None
        from poisson_ellipse_tpu.geom import validate as geom_validate
        from poisson_ellipse_tpu.resilience.errors import (
            InvalidGeometryError,
        )

        try:
            geom_validate.validate(
                req.problem, req.geometry_sdf(), theta=req.theta
            )
        except InvalidGeometryError as e:
            result = ServeResult(
                request_id=req.request_id, outcome="invalid",
                detail=e.reason,
            )
            self.results[req.request_id] = result
            obs_metrics.counter("invalid_geometry_total").inc()
            obs_trace.event(
                "serve:invalid-geometry", request_id=req.request_id,
                reason=e.reason,
            )
            return result
        return None

    def _validate_objective(self, req: ServeRequest) -> Optional[ServeResult]:
        """The grad kind's admission rung: a malformed objective spec
        ends terminally ``invalid`` at the door — same stance as the
        geometry gate, so no lane ever hosts a request whose cotangent
        evaluation would throw at a chunk boundary."""
        if not req.grad:
            return None
        from poisson_ellipse_tpu.diff.objectives import objective_from_spec

        try:
            objective_from_spec(req.objective, req.problem)
        except (ValueError, TypeError) as e:
            # TypeError belt: the objectives layer classifies malformed
            # payloads as ValueError, but an admission gate must never
            # let a client payload crash the scheduler step
            result = ServeResult(
                request_id=req.request_id, outcome="invalid",
                detail=f"objective: {e}",
            )
            self.results[req.request_id] = result
            obs_trace.event(
                "serve:invalid-objective", request_id=req.request_id,
                reason=str(e),
            )
            return result
        return None

    def begin_drain(self) -> None:
        """The graceful-shutdown hook: stop admitting, keep working.

        New submissions are refused with a shed carrying the projected
        wait as ``retry_after_s`` (and detail ``draining``) WITHOUT
        being recorded as this scheduler's terminal outcome — the
        rejection is a redirect for the caller (the fleet router's next
        replica, a SIGTERM'd CLI's client), not a lifecycle event of a
        request this scheduler never owned. Everything already admitted
        (queued, backlogged, in flight) still runs to a classified
        terminal state through the normal ``drain()``."""
        if not self.draining:
            self.draining = True
            obs_trace.event(
                "serve:drain-begin",
                queued=len(self.queue),
                in_flight=sum(
                    1 for c in self._ctxs.values()
                    for s in c.slots if s is not None
                ),
            )

    def adopt_request(self, req: ServeRequest) -> None:
        """Adopt a handed-off request from a dead peer's journal
        (``fleet.handoff``): journal-first (the write-ahead promise
        transfers to THIS scheduler before anything acknowledges the
        handoff), then the replay backlog's wave machinery — an adopted
        request is never terminally shed on capacity, exactly like a
        replayed one."""
        if self.journal is not None:
            self.journal.record_admit(req)
        req.replayed = True
        self._replay_backlog.append(req)
        self._admit_replay_wave()

    def submit_request(self, req: ServeRequest) -> Optional[ServeResult]:
        if self.draining:
            # the redirect shed stays unrecorded (begin_drain), but it
            # is COUNTED: drain_sheds is what keeps the chaos report's
            # zero-lost accounting provable when this replica is killed
            # mid-drain
            self.drain_sheds += 1
            return ServeResult(
                request_id=req.request_id, outcome="shed",
                detail="draining",
                retry_after_s=self.queue.projected_wait(),
            )
        prior = self.results.get(req.request_id)
        if prior is not None and prior.outcome == "shed" and not prior.dispatched:
            # shed-at-admission is "safe to resubmit after retry_after_s"
            # (the request.py outcome table): the resubmission supersedes
            # the rejection record instead of reading as a duplicate —
            # nothing was journaled or dispatched, so nothing can double
            del self.results[req.request_id]
        if self._knows(req.request_id):
            # a second live (or already-terminal) submission under the
            # same id can never get its own outcome slot — refuse it at
            # the door WITHOUT touching the original's lifecycle (no
            # results entry, no journal write: recording it would
            # overwrite or double-complete the first)
            return ServeResult(
                request_id=req.request_id, outcome="shed",
                detail="duplicate-request-id",
            )
        self._apply_admission_faults(req)
        accepted, retry_after, reason = self.queue.admit(req)
        self._classify_evicted()
        if not accepted:
            result = ServeResult(
                request_id=req.request_id, outcome="shed", detail=reason,
                retry_after_s=retry_after,
            )
            self.results[req.request_id] = result
            return result
        invalid = self._validate_geometry(req)
        if invalid is not None:
            # compensate the admit: the request leaves the queue before
            # anything durable (journal) or dispatchable sees it
            self.queue.retract(req, "invalid-geometry")
            return invalid
        invalid = self._validate_objective(req)
        if invalid is not None:
            self.queue.retract(req, "invalid-objective")
            return invalid
        if self.journal is not None:
            # write-ahead: the admission is acknowledged only once the
            # journal holds it; a failed journal write un-queues the
            # request and surfaces the error instead of promising
            # durability the disk refused
            try:
                self.journal.record_admit(req)
            except BaseException:
                self.queue.retract(req, "journal-write-failed")
                raise
        return None

    def _knows(self, request_id: str) -> bool:
        """Whether an id is already spoken for: queued, backlogged,
        in flight, terminal in the result buffer, or journaled (a
        collected-and-evicted result keeps its journal trail)."""
        return (
            request_id in self.results
            or self.owns_request(request_id)
        )

    def owns_request(self, request_id: str) -> bool:
        """Whether this scheduler owns the id's LIFECYCLE: queued,
        backlogged, in flight, journaled, or terminal — except a
        recorded shed that was never dispatched, which is a rejection
        the outcome table promises is safe to resubmit, not ownership.
        The fleet router's duplicate gate reads this (dead replicas
        included: a since-killed replica's journal still remembers what
        it finished, which is exactly what blocks a client retry from
        double-completing an already-delivered request)."""
        prior = self.results.get(request_id)
        if (prior is not None and prior.outcome == "shed"
                and not prior.dispatched):
            prior = None
        return (
            prior is not None
            or self.queue.holds(request_id)
            or any(r.request_id == request_id for r in self._replay_backlog)
            or self._slot_of(request_id) is not None
            or (
                self.journal is not None
                and self.journal.state_of(request_id) is not None
            )
        )

    def owned_live_ids(self) -> set[str]:
        """Ids whose lifecycle is LIVE here — queued, backlogged, in a
        lane, or journal-admitted (terminal/compacted records excluded).
        The fleet router's cross-epoch co-ownership audit intersects
        these sets across live replicas; any overlap is the split-brain
        the fencing machinery exists to prevent."""
        ids = set(self.queue.request_ids())
        ids.update(r.request_id for r in self._replay_backlog)
        ids.update(
            slot.req.request_id
            for ctx in self._ctxs.values()
            for slot in ctx.slots
            if slot is not None
        )
        if self.journal is not None:
            ids.update(self.journal.admitted_ids())
        return ids

    def prewarm(self, problem: Problem) -> None:
        """Build (or touch) the batch context for ``problem``'s compile
        bucket WITHOUT admitting anything: the warm-pool pre-warming
        hook a fleet rejoin uses to hand a fresh incarnation the
        router's observed shape mix before it takes traffic, so its
        first real requests land on warm contexts instead of paying
        cold compiles on the serving path."""
        self._ctx_for(ServeRequest(problem=problem))

    def replay(self) -> int:
        """Recover every journaled admitted-but-unfinished request (a
        restarted server's first act). Requests beyond the bounded
        queue's capacity wait in a replay backlog and re-enter in waves
        as the queue drains — an acknowledged admission is never
        terminally shed just because the restart arrived with more
        backlog than one queue's worth (the write-ahead promise).
        Returns the number of requests recovered."""
        if self.journal is None:
            raise ValueError("replay needs a journal-backed scheduler")
        reqs = self.journal.unfinished(self.clock())
        for req in reqs:
            # replays run cold (ServeRequest.replayed): the cache is
            # never journaled, so a replayed outcome must not depend on
            # what it held — bit-identical regardless of cache state
            req.replayed = True
            obs_trace.event(
                "serve:replay", request_id=req.request_id,
                grid=[req.problem.M, req.problem.N],
            )
        self._replay_backlog.extend(reqs)
        self._admit_replay_wave()
        return len(reqs)

    def _admit_replay_wave(self) -> None:
        """Move backlogged replay requests into the queue while it has
        room. A request whose restarted deadline budget is already
        infeasible ends ``deadline-miss`` — NOT ``shed``: shed means
        "never admitted, safe to resubmit", and these were durably
        acknowledged (a resubmit under the same id would be refused as
        a duplicate). Capacity overflow is deferred, never terminal."""
        while self._replay_backlog and len(self.queue) < self.queue.capacity:
            req = self._replay_backlog.pop(0)
            accepted, retry_after, reason = self.queue.admit(
                req, record_shed=False
            )
            self._classify_evicted()
            if not accepted:
                self._finish_queued(
                    req, "deadline-miss", detail=f"replay-{reason}",
                    retry_after=retry_after,
                )

    def _classify_evicted(self) -> None:
        """Give every queue-preemption victim (``AdmissionQueue``'s
        ``take_evicted``) its classified terminal: ``shed`` with detail
        ``preempted-by-priority``. The victim WAS journaled at its own
        admission, so the terminal is journaled too (the admit record
        must not replay as a lost request after a crash) — which means
        a preempted id cannot be resubmitted into the same process;
        clients retry with a fresh id, exactly as for any journaled
        terminal."""
        for victim in self.queue.take_evicted():
            self._finish_queued(
                victim, "shed", detail="preempted-by-priority",
                retry_after=self.queue.projected_wait(),
            )

    # -- the serve loop ------------------------------------------------------

    def step(self) -> bool:
        """One chunk across every active bucket: shed expired queued
        requests, refill free lanes, inject due faults, advance, retire.
        Returns True while work remains (in flight or queued)."""
        now = self.clock()
        for req in self.queue.expire(now):
            self._finish_queued(
                req, "deadline-miss", detail="expired-in-queue"
            )
        self._admit_replay_wave()
        self._fill_lanes()
        # lanes just drained the queue — top it back up so the next
        # boundary dispatches from a full line, not a replay-starved one
        self._admit_replay_wave()
        for ctx in list(self._ctxs.values()):
            if not ctx.active or ctx not in self._ctxs.values():
                continue
            self._apply_faults(ctx)
            if not ctx.active or ctx not in self._ctxs.values():
                continue
            k = int(ctx.state[0])
            # the chunk stops early at the nearest per-request iteration
            # cap (the FaultPlan.next_stop idiom): caps land exactly,
            # not at the next multiple of `chunk`
            limit_val = min(k + (ctx.chunk or self.chunk), ITER_CEILING)
            for slot in ctx.slots:
                if slot is not None:
                    limit_val = min(
                        limit_val,
                        slot.base_k + slot.req.problem.max_iterations,
                    )
            limit = jnp.asarray(max(limit_val, k + 1), jnp.int32)
            try:
                ctx.state = ctx.fn(
                    ctx.a3, ctx.b3, ctx.mask, ctx.h1, ctx.h2, ctx.delta,
                    ctx.state, limit,
                )
            except Exception as e:  # noqa: BLE001 — classified; unknowns re-raised
                from poisson_ellipse_tpu.resilience.errors import (
                    classify_error,
                )

                if classify_error(e) != "device-loss":
                    raise
                self._degrade_mesh("device-loss", getattr(e, "device", None))
                continue
            self._boundary(ctx)
        return bool(
            len(self.queue) or self._replay_backlog
        ) or any(c.active for c in self._ctxs.values())

    def drain(self, max_steps: int = 100_000) -> dict[str, ServeResult]:
        """Step until every admitted request is terminal. When the only
        remaining work is backoff-parked retries, waits them out via
        ``idle``. ``max_steps`` is a runaway backstop, not a policy."""
        steps = 0
        while True:
            in_flight = any(c.active for c in self._ctxs.values())
            if (not in_flight and not len(self.queue)
                    and not self._replay_backlog):
                return self.results
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"drain exceeded {max_steps} steps with work pending"
                )
            progressed_work = self.step()
            if progressed_work and not any(
                c.active for c in self._ctxs.values()
            ) and len(self.queue):
                wait = self.queue.next_ready_in(self.clock())
                if wait is not None:
                    self.idle(wait)

    def collect(self) -> dict[str, ServeResult]:
        """Hand off and evict every terminal result recorded so far.

        ``results`` (and the solution arrays it retains under
        ``keep_solutions``) otherwise grows for the scheduler's
        lifetime — the unbounded-memory failure mode the admission
        queue exists to prevent, reintroduced at the exit. A long-lived
        server must drain results through here (the ``harness serve``
        loop does); ``drain()`` keeps returning the accumulated dict
        for one-shot callers that read it after the stream ends."""
        out = self.results
        self.results = {}
        return out

    # -- refill --------------------------------------------------------------

    def _ctx_for(self, req: ServeRequest) -> _BatchCtx:
        bucket = grid_bucket(req.problem.M, req.problem.N)
        key = (bucket, req.problem.norm)
        ctx = self._ctxs.get(key)
        if ctx is None:
            ctx = _BatchCtx(
                bucket, self.lanes, self.dtype, req.problem.norm,
                mesh=self.mesh,
            )
            # warm-pool admission is where the autotuner's persisted
            # knobs land on the serving path: a tuned per-shape chunk
            # (sized ~4 refill boundaries per predicted solve) overrides
            # the scheduler-wide default for this bucket's context; no
            # registry → ctx.chunk stays None and nothing changes
            from poisson_ellipse_tpu.runtime import autotune

            # keyed on the request's geometry too: a tuned config is
            # never consulted for a domain it was not tuned for
            tuned = autotune.lookup(req.problem, self.dtype,
                                    geometry=req.geometry)
            if tuned is not None and tuned.knobs.get("chunk"):
                ctx.chunk = int(tuned.knobs["chunk"])
                obs_trace.event(
                    "autotune:serve-chunk", bucket=list(bucket),
                    chunk=ctx.chunk,
                )
            if self.warm_start:
                ctx.pool = SolveCache()
            self._ctxs[key] = ctx
        return ctx

    def _fill_lanes(self) -> None:
        now = self.clock()
        deferred = []
        while True:
            req = self.queue.pop_ready(now)
            if req is None:
                break
            ctx = self._ctx_for(req)
            lane = ctx.free_lane()
            if lane is None:
                deferred.append(req)
                continue
            self._refill_lane(ctx, lane, req)
        for req in reversed(deferred):
            self.queue.push_front(req)

    def _refill_lane(self, ctx: _BatchCtx, lane: int,
                     req: ServeRequest) -> None:
        """Swap a request into a free lane between chunks: embed its
        operands into the lane's slices and re-initialise the lane's
        carry from ``init_state`` — the freeze mask generalized to
        swap-in. Per-lane arithmetic is lane-decoupled, so the refilled
        lane's trajectory is bit-identical to a fresh lane-0 solve of
        the same embedding (pinned in ``tests/test_batched.py``)."""
        p = req.problem
        x0_p, cache_key = None, None
        if req.grad:
            # grad kind: the job's differentiably-assembled operands
            # (primal stage) or the normalised cotangent RHS over the
            # same operator (adjoint stage) — still just a lane
            a_p, b_p, r_p, m_p = self._grad_job(req).embed(
                ctx.bucket, self._np_dtype
            )
        else:
            a_p, b_p, r_p, m_p = _embed_request(
                p, ctx.bucket, self._np_dtype,
                geometry=req.geometry_sdf(), theta=req.theta,
            )
            if self.warm_start:
                x0_p, cache_key = self._consult_pool(
                    ctx, req, a_p, b_p, r_p
                )
        # the lane's fresh carry comes from the same eager init_state
        # every other entry path uses (the bit-parity pin's reference);
        # the scatter into the batch is one fused dispatch
        unit = batched_pcg.init_state(
            ctx.proto, jnp.asarray(a_p)[None], jnp.asarray(b_p)[None],
            jnp.asarray(r_p)[None], mask=jnp.asarray(m_p)[None],
            h1=p.h1, h2=p.h2,
            x0=None if x0_p is None else jnp.asarray(x0_p)[None],
        )
        (ctx.a3, ctx.b3, ctx.mask, ctx.h1, ctx.h2, ctx.delta,
         ctx.state) = _refill_scatter(
            ctx.a3, ctx.b3, ctx.mask, ctx.h1, ctx.h2, ctx.delta,
            ctx.state, unit, a_p, b_p, m_p,
            jnp.asarray(p.h1, ctx.h1.dtype), jnp.asarray(p.h2, ctx.h2.dtype),
            jnp.asarray(p.delta, ctx.delta.dtype),
            jnp.asarray(lane, jnp.int32),
        )
        base_k = int(ctx.state[_IDX["k"]])
        now = self.clock()
        slot = _InFlight(req, lane, base_k, now)
        if cache_key is not None:
            # remember what the retirement deposit needs: the key and
            # the embedded rhs the pool sketches on
            slot.cache_key = cache_key
            slot.rhs_pad = r_p
        ctx.slots[lane] = slot
        req.dispatched = True
        if req.enqueued_t is not None:
            obs_metrics.histogram("time_in_queue_seconds").observe(
                now - req.enqueued_t
            )
        obs_metrics.counter("serve_refills_total").inc()
        obs_trace.event(
            "serve:refill", request_id=req.request_id, lane=lane,
            base_k=base_k, attempt=req.attempt,
            bucket=list(ctx.bucket),
        )

    def _consult_pool(self, ctx: _BatchCtx, req: ServeRequest,
                      a_p, b_p, r_p):
        """The warm-start consult (``warm_start=True`` refills only):
        look the request up in its bucket's recycle pool and admit the
        nearest-neighbour hit through the true-residual check. Returns
        ``(x0 or None, cache_key)`` — the key always, so the retirement
        deposit works even on a miss.

        Only FRESH work consults: attempt-0 (a retried request's lane
        already went bad once — run it cold), never replays (the journal
        contract: a replayed outcome must not depend on cache state).
        Everything downstream is defensive — ``check_warm_start`` drops
        non-finite seeds and flags bad ones (``recycle:bad-hit``), and
        the batched init verifies by true residual — so the worst any
        entry (including a ``cache_poison``-injected one) costs is
        iterations."""
        from poisson_ellipse_tpu.solver import recycle

        p = req.problem
        key = solve_key(p, self.dtype, geometry=req.geometry)
        x0, dist = None, None
        if (ctx.pool is not None and req.attempt == 0
                and not req.replayed):
            x0, dist = ctx.pool.lookup(key, r_p)
        poisoned = self._cache_poison_fault(req)
        if poisoned:
            from poisson_ellipse_tpu.resilience import faultinject

            x0 = faultinject.poisoned_guess(r_p.shape, self._np_dtype)
        if x0 is None:
            return None, key
        # validate on the TRUE grid (the zero-extension pad slices off
        # exactly): the ratio is measured against the request's own
        # operator and spacings, not the bucket's
        g1, g2 = p.M + 1, p.N + 1
        x0 = np.asarray(x0, self._np_dtype)
        checked, ratio = recycle.check_warm_start(
            p, a_p[:g1, :g2], b_p[:g1, :g2], r_p[:g1, :g2],
            jnp.asarray(x0[:g1, :g2]), source="solvecache",
            request_id=req.request_id,
        )
        if checked is None:
            return None, key
        out = np.zeros_like(r_p)
        out[:g1, :g2] = np.asarray(checked)
        obs_metrics.counter("solvecache_hit_total").inc()
        obs_trace.event(
            "recycle:hit", request_id=req.request_id,
            distance=dist, ratio=ratio, poisoned=poisoned,
        )
        return out, key

    def _cache_poison_fault(self, req: ServeRequest) -> bool:
        """Fire a pending ``cache_poison`` fault addressed to ``req``
        (one-shot, like every injection): the consult's answer gets
        replaced with a deliberately wrong solution."""
        from poisson_ellipse_tpu.resilience import faultinject

        for fault in self.faults.faults:
            if (fault.fired or fault.request_id != req.request_id
                    or fault.kind not in faultinject.CACHE_KINDS):
                continue
            fault.fired = True
            obs_trace.event(
                "serve:fault", request_id=req.request_id, lane=None,
                kind=fault.kind, at_iter=0,
            )
            return True
        return False

    def _park_lane(self, ctx: _BatchCtx, lane: int) -> None:
        """Return a lane to the parked pool: zeroed state, breakdown
        flag raised so the loop freezes it until the next refill."""
        state = list(ctx.state)
        for name in ("w", "r", "p"):
            idx = _IDX[name]
            state[idx] = state[idx].at[lane].set(
                jnp.zeros(state[idx].shape[1:], state[idx].dtype)
            )
        state[_IDX["zr"]] = state[_IDX["zr"]].at[lane].set(0.0)
        state[_IDX["conv"]] = state[_IDX["conv"]].at[lane].set(False)
        state[_IDX["bd"]] = state[_IDX["bd"]].at[lane].set(True)
        state[_IDX["quar"]] = state[_IDX["quar"]].at[lane].set(False)
        ctx.state = tuple(state)
        ctx.slots[lane] = None

    # -- retirement ----------------------------------------------------------

    def _boundary(self, ctx: _BatchCtx) -> None:
        """The chunk-boundary host read: retire finished lanes.
        Ordering is the deadline contract — converged lanes first (a
        result beats a miss at the same boundary), then fault
        retirement into the retry ladder, then deadline cancels, then
        per-request iteration caps."""
        conv = np.asarray(ctx.state[_IDX["conv"]])
        bd = np.asarray(ctx.state[_IDX["bd"]])
        quar = np.asarray(ctx.state[_IDX["quar"]])
        iters = np.asarray(ctx.state[_IDX["iters"]])
        diffs = np.asarray(ctx.state[_IDX["diff"]])
        now = self.clock()
        for lane, slot in enumerate(ctx.slots):
            if slot is None:
                continue
            req = slot.req
            req_iters = int(iters[lane]) - slot.base_k
            diff = float(diffs[lane])
            if conv[lane]:
                if req.grad:
                    self._grad_boundary(ctx, lane, slot, req_iters, diff)
                else:
                    self._finish(
                        ctx, lane, slot, "completed", iters=req_iters,
                        diff=diff, converged=True,
                    )
            elif quar[lane] or bd[lane]:
                cause = "lane-quarantine" if quar[lane] else "breakdown"
                self._park_lane(ctx, lane)
                self._retry_or_fallback(slot, cause)
            elif req.deadline is not None and now > req.deadline:
                self._finish(
                    ctx, lane, slot, "deadline-miss", iters=req_iters,
                    diff=diff, partial=True, detail="expired-mid-solve",
                )
            elif req_iters >= req.problem.max_iterations:
                self._finish(
                    ctx, lane, slot, "cap", iters=req_iters, diff=diff
                )
        # rebase the batch's global clock: k only moves forward, and a
        # hot bucket on a long-lived server would otherwise walk it
        # into ITER_CEILING (~2^30 iterations ≈ 2M solves) and wedge —
        # limit could no longer exceed k, so no lane would ever advance
        # or retire again. The shift is uniform across k / per-lane
        # iters / slot base_k (iters tracks global k for active lanes),
        # so every per-request count and cap is invariant under it.
        if ctx.active:
            base = min(s.base_k for s in ctx.slots if s is not None)
        else:
            base = int(ctx.state[_IDX["k"]])
        if base > 0:
            state = list(ctx.state)
            state[_IDX["k"]] = state[_IDX["k"]] - base
            state[_IDX["iters"]] = state[_IDX["iters"]] - base
            ctx.state = tuple(state)
            for s in ctx.slots:
                if s is not None:
                    s.base_k -= base

    # -- the grad kind (diff.serving) ----------------------------------------

    def _grad_job(self, req: ServeRequest):
        """The request's GradJob, built on first dispatch (and rebuilt
        deterministically after a replay — the job is host state, the
        journal holds only the request spec)."""
        job = self._grad_jobs.get(req.request_id)
        if job is None:
            from poisson_ellipse_tpu.diff.serving import GradJob

            job = GradJob(req)
            self._grad_jobs[req.request_id] = job
        return job

    def _grad_boundary(self, ctx: _BatchCtx, lane: int, slot: _InFlight,
                       req_iters: int, diff: float) -> None:
        """A grad request's lane converged: either stage the adjoint
        (primal done — the cotangent becomes the next lane's RHS) or
        terminally complete with (value, grad) (adjoint done)."""
        req = slot.req
        job = self._grad_job(req)
        g1, g2 = req.problem.M + 1, req.problem.N + 1
        u = np.asarray(ctx.state[_IDX["w"]][lane])[:g1, :g2].copy()
        if job.stage == "primal":
            pending = job.absorb_primal(u, req_iters)
            self._park_lane(ctx, lane)
            if pending:
                obs_trace.event(
                    "diff:adjoint-dispatch", request_id=req.request_id,
                    lane=lane, primal_iters=req_iters,
                    value=job.value,
                )
                # the adjoint is an ordinary queued dispatch: it lands
                # on whatever lane frees next (retire-and-refill), and
                # deadline expiry still applies while it waits. Re-entry
                # goes through the replay-backlog waves, NOT push_front:
                # the request holds no queue slot right now, so a full
                # queue's maxlen backstop would silently evict someone
                # else's admission — the backlog is the never-shed lane
                # for work the scheduler already owns
                self._replay_backlog.append(req)
                self._admit_replay_wave()
            else:
                # zero cotangent — the gradient is exactly zero; no
                # second solve to pay for
                self._grad_finish(req, slot, job, job.zero_grad(),
                                  iters=req_iters, diff=diff, lane=lane)
            return
        grad = job.finish(u, req_iters)
        self._park_lane(ctx, lane)
        self._grad_finish(req, slot, job, grad,
                          iters=job.primal_iters + req_iters, diff=diff,
                          lane=lane)

    def _grad_finish(self, req: ServeRequest, slot: _InFlight, job,
                     grad, iters: int, diff: float, lane: int) -> None:
        now = self.clock()
        self.queue.observe_service(now - slot.t_dispatch)
        result = ServeResult(
            request_id=req.request_id, outcome="completed", iters=iters,
            diff=diff, converged=True, dispatched=True,
            attempts=req.attempt + 1,
            time_in_queue_s=(
                slot.t_dispatch - req.enqueued_t
                if req.enqueued_t is not None else 0.0
            ),
            total_s=self._span_s(req, now),
            detail="grad",
            w=(np.asarray(job.u).copy()
               if self.keep_solutions and job.u is not None else None),
            value=job.value,
            grad=np.asarray(grad, np.float64).tolist(),
        )
        obs_metrics.counter("grad_completed_total").inc()
        self._record_terminal(result, lane=lane)

    @staticmethod
    def _span_s(req: ServeRequest, now: float) -> float:
        """End-to-end seconds since the request's FIRST admission:
        ``admitted_t`` survives retry requeues, which re-stamp
        ``enqueued_t`` for the per-visit queue-wait histogram."""
        anchor = (
            req.admitted_t if req.admitted_t is not None else req.enqueued_t
        )
        return now - anchor if anchor is not None else 0.0

    def _finish(self, ctx: _BatchCtx, lane: int, slot: _InFlight,
                outcome: str, iters: int = 0, diff: float = float("inf"),
                converged: bool = False, partial: bool = False,
                detail: str | None = None) -> None:
        req = slot.req
        now = self.clock()
        w = None
        if self.keep_solutions and (converged or partial):
            g1, g2 = req.problem.M + 1, req.problem.N + 1
            w = np.asarray(ctx.state[_IDX["w"]][lane])[:g1, :g2].copy()
        if (converged and slot.cache_key is not None
                and ctx.pool is not None):
            # the deposit half of the recycle pool: a converged lane's
            # EMBEDDED solution under its cache key, sketched on the
            # same embedded rhs a future consult will sketch on
            ctx.pool.put(
                slot.cache_key, slot.rhs_pad,
                np.asarray(ctx.state[_IDX["w"]][lane]).copy(),
                iters=iters,
            )
        self._park_lane(ctx, lane)
        self.queue.observe_service(now - slot.t_dispatch)
        result = ServeResult(
            request_id=req.request_id, outcome=outcome, iters=iters,
            diff=diff, converged=converged, partial=partial,
            dispatched=True, attempts=req.attempt + 1,
            time_in_queue_s=(
                slot.t_dispatch - req.enqueued_t
                if req.enqueued_t is not None else 0.0
            ),
            total_s=self._span_s(req, now),
            detail=detail, w=w,
        )
        self._record_terminal(result, lane=lane)

    def _finish_queued(self, req: ServeRequest, outcome: str,
                       detail: str | None = None,
                       retry_after: float | None = None) -> None:
        """Terminate a request while it is off-lane (queued expiry,
        replay shed, a failed fallback). ``dispatched`` reports the
        request's history, not this moment: a fresh expired-in-queue
        request was never dispatched (the satellite contract), while a
        retried or fallen-back one really did run on a lane first."""
        now = self.clock()
        result = ServeResult(
            request_id=req.request_id, outcome=outcome,
            dispatched=req.dispatched,
            attempts=req.attempt,
            time_in_queue_s=(
                now - req.enqueued_t if req.enqueued_t is not None else 0.0
            ),
            total_s=self._span_s(req, now),
            detail=detail, retry_after_s=retry_after,
        )
        self._record_terminal(result)

    def _record_terminal(self, result: ServeResult,
                         lane: int | None = None) -> None:
        # journal FIRST: the terminal record lives where the durability
        # promise does, and a fenced journal (fleet.replica) rejecting a
        # zombie's stale write must abort the completion BEFORE it lands
        # in the result buffer a harvester could read
        if self.journal is not None:
            self.journal.record_outcome(
                result.request_id, result.outcome, detail=result.detail
            )
        self.results[result.request_id] = result
        # a terminal grad request's host lifecycle state goes with it
        # (deadline-miss/cap/failed included — replay rebuilds)
        self._grad_jobs.pop(result.request_id, None)
        if result.outcome == "deadline-miss":
            obs_metrics.counter("deadline_miss_total").inc()
        elif result.outcome == "completed":
            obs_metrics.counter("serve_completed_total").inc()
        obs_trace.event(
            "serve:retire", request_id=result.request_id, lane=lane,
            outcome=result.outcome, iters=result.iters,
            attempts=result.attempts, partial=result.partial,
            detail=result.detail,
        )

    # -- the retry ladder ----------------------------------------------------

    def _retry_or_fallback(self, slot: _InFlight, cause: str) -> None:
        """Walk the degradation ladder for a request whose lane went
        bad: within budget, back off exponentially and resubmit on a
        fresh lane; past it, fall to the guarded single solve — the
        rung where the full recovery machinery of ``resilience.guard``
        takes over. Every rung ends in a classified outcome."""
        req = slot.req
        req.attempt += 1
        if req.grad:
            # the lane's carry is gone; a grad request restarts its
            # two-stage lifecycle from the primal (deterministic, so
            # the eventual gradient is unchanged)
            job = self._grad_jobs.get(req.request_id)
            if job is not None:
                job.reset()
        if req.attempt <= req.max_retries:
            backoff = self.backoff_base_s * (2 ** (req.attempt - 1))
            req.not_before = self.clock() + backoff
            obs_metrics.counter("serve_retries_total").inc()
            obs_trace.event(
                "serve:retry", request_id=req.request_id, cause=cause,
                attempt=req.attempt, backoff_s=round(backoff, 4),
            )
            if not self.queue.requeue(req):
                self._finish_queued(
                    req, "failed", detail="requeue-shed-under-overload"
                )
            return
        self._guarded_fallback(req, cause)

    def _guarded_fallback(self, req: ServeRequest, cause: str) -> None:
        """The ladder's last rung: one guarded single solve of the true
        (un-embedded) problem, with the remaining deadline budget as the
        guard's timeout."""
        from poisson_ellipse_tpu.resilience.guard import guarded_solve

        # the fallback's dispatch instant: queue-wait accounting stops
        # here — the solve itself must not read as time spent queued
        t_dispatch = self.clock()
        timeout = None
        if req.deadline is not None:
            timeout = req.deadline - t_dispatch
            if timeout <= 0:
                self._finish_queued(
                    req, "deadline-miss",
                    detail=f"expired-before-fallback ({cause})",
                )
                return
        obs_trace.event(
            "serve:fallback", request_id=req.request_id, cause=cause,
            attempt=req.attempt,
        )
        if req.grad:
            # the grad kind's last rung: the un-laned implicit solve
            # (diff.serving.solve_grad_direct) — deterministic, so the
            # fallback quotes the same (value, grad) a lane pair would
            from poisson_ellipse_tpu.diff.serving import solve_grad_direct

            try:
                value, grad, iters = solve_grad_direct(req)
            except Exception:  # tpulint: disable=TPU009 — classified below
                self._finish_queued(
                    req, "failed", detail=f"grad-fallback-error ({cause})"
                )
                return
            now = self.clock()
            if req.deadline is not None and now > req.deadline:
                # the implicit solve is not chunk-cancellable (yet), so
                # the deadline is enforced at its granularity: a late
                # gradient is classified, never delivered as completed
                self._finish_queued(
                    req, "deadline-miss",
                    detail=f"grad-fallback-exceeded-deadline ({cause})",
                )
                return
            self._record_terminal(ServeResult(
                request_id=req.request_id, outcome="completed",
                iters=iters, diff=0.0, converged=True, dispatched=True,
                attempts=req.attempt + 1,
                time_in_queue_s=(
                    t_dispatch - req.enqueued_t
                    if req.enqueued_t is not None else 0.0
                ),
                total_s=self._span_s(req, now),
                detail="grad-guarded-fallback",
                value=value, grad=np.asarray(grad).tolist(),
            ))
            return
        try:
            guarded = guarded_solve(
                req.problem, "xla", self.dtype, chunk=self.chunk,
                timeout=timeout, geometry=req.geometry_sdf(),
                theta=req.theta,
                # already validated at admission; never re-gate mid-ladder
                validate_geometry=False,
            )
        except SolveError as e:
            outcome = (
                "deadline-miss" if e.classification == "timeout" else
                "failed"
            )
            self._finish_queued(
                req, outcome,
                detail=f"guarded-fallback-{e.classification}",
            )
            return
        result = guarded.result
        now = self.clock()
        res = ServeResult(
            request_id=req.request_id,
            outcome="completed" if bool(result.converged) else "cap",
            iters=int(result.iters), diff=float(result.diff),
            converged=bool(result.converged), dispatched=True,
            attempts=req.attempt + 1,
            time_in_queue_s=(
                t_dispatch - req.enqueued_t
                if req.enqueued_t is not None else 0.0
            ),
            total_s=self._span_s(req, now),
            detail="guarded-fallback",
            w=(
                np.asarray(result.w).copy()
                if self.keep_solutions and bool(result.converged) else None
            ),
        )
        self._record_terminal(res)

    # -- mesh degradation ----------------------------------------------------

    def _degrade_mesh(self, cause: str, device: int | None) -> None:
        """A device under the batch died: every live batch carry died
        with it (the mesh's arrays are unrecoverable), but no REQUEST
        does — each in-flight request re-enters through the same
        journal-backed retry ladder a lane fault uses, so the chaos
        invariants (zero lost / zero double) hold across a device kill
        exactly as they do across a process kill. A sharded scheduler
        also shrinks its mesh (``parallel.elastic``) so rebuilt batch
        contexts land on the survivors; shapes are compile keys, so the
        rebuilds warm naturally."""
        in_flight = [
            slot
            for ctx in self._ctxs.values()
            for slot in ctx.slots
            if slot is not None
        ]
        obs_trace.event(
            "degrade:mesh",
            cause=cause,
            lost_devices=[device] if device is not None else [],
            in_flight=len(in_flight),
        )
        obs_metrics.counter("mesh_degrade_total").inc()
        # the carries are gone: drop every batch context; _ctx_for
        # rebuilds on demand (on the shrunk mesh, when sharded)
        self._ctxs.clear()
        if self.mesh is not None and device is not None:
            from poisson_ellipse_tpu.parallel.elastic import shrink_mesh
            from poisson_ellipse_tpu.resilience.errors import (
                DeviceLossError,
            )

            try:
                self.mesh = shrink_mesh(self.mesh, [device])
            except DeviceLossError:
                # no mesh left: the single-device path still serves
                self.mesh = None
        for slot in in_flight:
            self._retry_or_fallback(slot, cause)

    # -- fault injection -----------------------------------------------------

    def _slot_of(self, request_id: str):
        for ctx in self._ctxs.values():
            for slot in ctx.slots:
                if slot is not None and slot.req.request_id == request_id:
                    return ctx, slot
        return None

    def _apply_faults(self, ctx: _BatchCtx) -> None:
        """Fire request-addressed faults due at this boundary.
        ``at_iter`` counts the request's own iterations; injection lands
        at the first chunk boundary at or past it (the chunk-granular
        form of the guard's exact-iteration injection). ``oom`` is a
        dispatch-level failure — the lane is freed and the request walks
        the retry ladder; carry faults corrupt the lane slice and let
        the in-loop quarantine detect them."""
        if not self.faults:
            return
        iters = None
        for fault in list(self.faults.faults):
            if fault.fired or fault.request_id is None:
                continue
            located = self._slot_of(fault.request_id)
            if located is None or located[0] is not ctx:
                continue
            _, slot = located
            if iters is None:
                iters = np.asarray(ctx.state[_IDX["iters"]])
            req_iters = int(iters[slot.lane]) - slot.base_k
            if req_iters < fault.at_iter:
                continue
            if not fault.persistent:
                fault.fired = True
            obs_trace.event(
                "serve:fault", request_id=fault.request_id,
                lane=slot.lane, kind=fault.kind, at_iter=fault.at_iter,
            )
            if fault.kind == "oom":
                # what a real RESOURCE_EXHAUSTED on the dispatch looks
                # like to the scheduler: the lane is lost, the request
                # is not — straight onto the retry ladder
                self._park_lane(ctx, slot.lane)
                self._retry_or_fallback(slot, "oom")
                continue
            if fault.kind == "device_loss":
                # a whole device under the batch: every in-flight
                # request (this context's and the others') re-enters;
                # the addressed request only picks WHEN the kill lands
                self._degrade_mesh("device-loss", fault.device)
                return
            lane_fault = Fault(
                fault.kind, at_iter=fault.at_iter, field=fault.field,
                rows=fault.rows, lane=slot.lane,
            )
            from poisson_ellipse_tpu.resilience import faultinject

            ctx.state = faultinject._corrupt(
                list(ctx.state), lane_fault, _FIELDS, _IDX["bd"],
                _IDX["zr"],
            )
