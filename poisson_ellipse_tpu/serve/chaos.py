"""Chaos harness: the serving invariants proven under injected failure.

A serving layer's correctness claims are global, not per-request —
*zero lost* (every admitted request reaches a terminal state, across
kills), *zero double-completed* (no request finishes twice, across
replays), *all classified* (every terminal state is one of the named
outcomes). None of those can be unit-tested one code path at a time;
they have to survive a hostile stream. This module drives one: a
seeded Poisson arrival process of mixed shapes through the scheduler
while ``resilience.faultinject`` poisons lanes (request-addressed NaN),
fakes ``RESOURCE_EXHAUSTED`` on dispatch, and kills the server
mid-stream — the restarted scheduler replays the journal and the
stream keeps going. Everything is deterministic in ``seed``: the same
chaos reproduces bit-for-bit, which is what makes a failing run
debuggable instead of an anecdote.

``run_chaos`` is the single entry shared by ``tests/test_serve.py``,
the ``harness chaos`` subcommand, and the ``bench.py`` serving key's
sanity half.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Optional, Sequence

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs import trace as obs_trace
from poisson_ellipse_tpu.resilience.faultinject import Fault, FaultPlan
from poisson_ellipse_tpu.serve.journal import RequestJournal
from poisson_ellipse_tpu.serve.request import OUTCOMES, ServeRequest
from poisson_ellipse_tpu.serve.scheduler import Scheduler

DEFAULT_GRIDS = ((10, 10), (12, 12), (8, 8))


@dataclasses.dataclass
class ChaosReport:
    """One chaos run's verdict: the invariant booleans plus the
    evidence behind them."""

    n_requests: int
    outcomes: dict            # request_id -> outcome
    counts: dict              # outcome -> count
    lost: list                # submitted ids with no terminal outcome
    double_completed: list    # ids with >1 terminal outcome
    unclassified: list        # ids whose outcome is not in OUTCOMES
    replayed: int
    killed: bool
    faults_fired: int
    wall_s: float
    mesh_killed: bool = False  # a device-loss drill ran mid-stream
    # the fleet drill's evidence (replicas > 1): handoffs executed,
    # requests adopted by survivors, fenced zombie writes observed and
    # rejected, and whether the zombie-resurrection drill ran
    replicas: int = 1
    handoffs: int = 0
    adopted: int = 0
    stale_writes_rejected: int = 0
    zombie_drill: bool = False
    # the grad-kind drill (differentiable serving): grad requests in
    # the stream, and ids that completed WITHOUT a gradient — a grad
    # completion missing its payload is a classification failure
    grad_requests: int = 0
    grad_missing_payload: list = dataclasses.field(default_factory=list)
    # the survivability drills' evidence: rejoins executed, redirect
    # sheds issued by draining schedulers (unrecorded by design —
    # counted so zero-lost stays provable across a kill-mid-drain),
    # ids co-owned by two live replicas at ANY boundary (must stay
    # empty: the cross-epoch co-ownership violation), starvation
    # episodes observed and the tenants whose episodes outnumbered
    # their announcements (starved SILENTLY — must stay empty), and
    # per-tenant outcome counts for the mixed-tenant stream
    rejoins: int = 0
    drain_shed: int = 0
    co_owned: list = dataclasses.field(default_factory=list)
    starvation_events: int = 0
    starved_silent: list = dataclasses.field(default_factory=list)
    tenants: dict = dataclasses.field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not (self.lost or self.double_completed or self.unclassified
                    or self.grad_missing_payload or self.co_owned
                    or self.starved_silent)

    def json_dict(self) -> dict:
        out = dataclasses.asdict(self)
        out["ok"] = self.ok
        return out


def _chaos_id(i: int) -> str:
    return f"chaos-{i:04d}"


def run_chaos(
    n_requests: int = 50,
    seed: int = 0,
    grids: Sequence[tuple[int, int]] = DEFAULT_GRIDS,
    rate_per_s: float = 400.0,
    lanes: int = 4,
    chunk: int = 8,
    queue_capacity: int = 128,
    journal_path=None,
    kill_after: Optional[int] = None,
    nan_request: Optional[int] = 2,
    oom_request: Optional[int] = 5,
    deadline_s: Optional[float] = None,
    max_retries: int = 2,
    mesh_kill_request: Optional[int] = None,
    malformed_request: Optional[int] = None,
    degenerate_request: Optional[int] = None,
    grad_requests: Sequence[int] = (),
    replicas: int = 1,
    replica_kill: Optional[int] = None,
    kill_during_handoff: bool = False,
    zombie: bool = False,
    lease_s: float = 0.25,
    replica_rejoin: Optional[int] = None,
    replica_kill_again: Optional[int] = None,
    lease_store_outage: Optional[int] = None,
    lease_store_outage_s: float = 0.05,
    tenant_mix: Optional[Sequence[tuple[str, int]]] = None,
    class_quotas: Optional[dict] = None,
    starvation_after_s: Optional[float] = None,
    warm_start: bool = False,
    poison_request: Optional[int] = None,
) -> ChaosReport:
    """Drive one seeded chaos stream; see the module docstring.

    ``kill_after`` (default: ``n_requests // 2``) is the request index
    after which the server is killed — the Scheduler object is dropped
    with requests queued and in flight, exactly what SIGKILL leaves
    behind — and a fresh scheduler on the same journal replays.
    ``nan_request`` / ``oom_request`` pick which request indices get a
    request-addressed NaN-poisoned lane and a fake RESOURCE_EXHAUSTED
    (None disables either). Requires ``journal_path`` when a kill is
    scheduled (the replay is the point).

    ``mesh_kill_request`` arms the DEVICE-kill drill (the ``harness
    chaos --mesh`` flag): when that request is in flight, a simulated
    device loss takes out every live batch carry at once — every
    in-flight request re-enters through the journal/retry ladder
    (``Scheduler._degrade_mesh``) — and the zero-lost/zero-double/
    all-classified invariants are asserted across a device kill, not
    just a process kill.

    ``grad_requests`` names arrival indices that become ``grad=True``
    requests (differentiable serving, ``diff.serving``): each runs two
    consecutive lane solves (primal + IFT adjoint over the same
    operator) and must terminally complete WITH its ``(value, grad)``
    payload — a completed grad request missing the gradient fails the
    report (``grad_missing_payload``). Kill/replay interleaves with the
    two-stage lifecycle like any other request: the replayed recompute
    is deterministic, so the invariants extend unchanged (the
    mid-adjoint kill → identical-gradient pin lives in
    ``tests/test_diff.py``, where the kill instant is surgical).

    ``malformed_request`` / ``degenerate_request`` arm the GEOMETRY
    drill: the named request's geometry spec is swapped at admission
    (``faultinject.malformed_spec`` / ``degenerate_geometry``). The
    malformed one must end in the terminal classified ``invalid``
    outcome without ever touching a lane; the degenerate (sliver-cut)
    one must pass the gate and SOLVE cleanly under the clamp — and in
    both cases every OTHER request's lane runs clean (zero poisoning,
    asserted by the same global invariants).

    ``replicas > 1`` switches the stream onto a FLEET
    (``fleet.FleetRouter``): same seeded arrivals, same invariant
    triple, but the failures are replica-scale. ``replica_kill`` names
    the arrival index at which replica 0 is SIGKILLed (its journal
    hands off to the survivors); ``kill_during_handoff`` additionally
    kills replica 1 at the same boundary — the adopted-but-not-yet-run
    requests must survive the second kill because adoption is
    journal-first; ``zombie`` arms the replica-hang drill instead of a
    kill (lease expires while the process lives, work is handed off,
    and the resurrected zombie's completion attempt MUST be rejected
    by its fenced journal — the observed-and-rejected stale write is
    part of the report). The per-request NaN/OOM faults keep firing on
    whichever replica hosts their victims — one plan, fleet-wide.

    The SURVIVABILITY drills (all fleet-only, all opt-in — the default
    drill set is unchanged): ``replica_rejoin`` names the arrival index
    at which the killed/fenced replica 0 re-enters as a fresh
    incarnation (``FleetRouter.rejoin_replica`` — archived-journal
    replay, warm-pool pre-warm, new epoch); ``replica_kill_again``
    kills the REJOINED incarnation at a later index, proving the
    kill→rejoin→kill-again ladder keeps zero-lost/zero-double;
    ``lease_store_outage`` arms a coordination-service outage of
    ``lease_store_outage_s`` seconds starting at that arrival index
    (deaths inside the window defer their fence+handoff; admissions
    past the grace window shed classified ``fleet-unavailable``);
    ``tenant_mix`` is a sequence of ``(tenant, priority)`` classes the
    seeded stream draws from (with optional ``class_quotas`` /
    ``starvation_after_s`` passed to every replica's queue) — the
    report adds per-tenant outcome counts and pins that no tenant
    starved silently. At EVERY boundary the router's co-ownership
    audit runs; any id live-owned by two replicas fails the report.

    ``warm_start`` runs the whole drill with the per-bucket recycle
    pools ON (``runtime.solvecache``) — the zero-lost/zero-double/
    all-classified triple must hold unchanged with recycling enabled,
    and replays still run cold (the journal contract).
    ``poison_request`` names the arrival index whose solve-cache
    consult is replaced with a deliberately wrong entry
    (``faultinject.cache_poison``): the victim must still terminate
    classified — extra iterations are the only allowed cost.
    """
    if n_requests < 1:
        raise ValueError("need at least one request")
    if rate_per_s <= 0:
        raise ValueError("rate_per_s must be > 0")
    if replicas > 1:
        # the single-scheduler drills do not arm on the fleet path —
        # refuse them LOUDLY rather than report invariants a drill
        # that never ran cannot have tested (replica_kill is the
        # fleet's kill; mesh/geometry drills are single-scheduler)
        dropped = {
            "kill_after": kill_after,
            "mesh_kill_request": mesh_kill_request,
            "malformed_request": malformed_request,
            "degenerate_request": degenerate_request,
            "grad_requests": tuple(grad_requests) or None,
        }
        armed = [k for k, v in dropped.items() if v is not None]
        if armed:
            raise ValueError(
                f"{', '.join(armed)} are single-scheduler drills the "
                "fleet path (replicas > 1) does not run — use "
                "replica_kill/kill_during_handoff/zombie for fleet "
                "failure modes, or replicas=1 for these"
            )
        return _run_fleet_chaos(
            n_requests=n_requests, seed=seed, grids=grids,
            rate_per_s=rate_per_s, lanes=lanes, chunk=chunk,
            queue_capacity=queue_capacity, journal_path=journal_path,
            nan_request=nan_request, oom_request=oom_request,
            deadline_s=deadline_s, max_retries=max_retries,
            replicas=replicas, replica_kill=replica_kill,
            kill_during_handoff=kill_during_handoff, zombie=zombie,
            lease_s=lease_s, replica_rejoin=replica_rejoin,
            replica_kill_again=replica_kill_again,
            lease_store_outage=lease_store_outage,
            lease_store_outage_s=lease_store_outage_s,
            tenant_mix=tenant_mix, class_quotas=class_quotas,
            starvation_after_s=starvation_after_s,
        )
    fleet_only = {
        "replica_rejoin": replica_rejoin,
        "replica_kill_again": replica_kill_again,
        "lease_store_outage": lease_store_outage,
        "tenant_mix": tenant_mix,
        "class_quotas": class_quotas,
        "starvation_after_s": starvation_after_s,
    }
    armed_fleet = [k for k, v in fleet_only.items() if v is not None]
    if armed_fleet:
        raise ValueError(
            f"{', '.join(armed_fleet)} are fleet drills the "
            "single-scheduler path (replicas == 1) does not run — "
            "use replicas > 1"
        )
    if kill_after is None:
        kill_after = n_requests // 2
    kill = kill_after is not None and 0 < kill_after < n_requests
    if kill and journal_path is None:
        raise ValueError(
            "a kill/restart chaos run needs journal_path (replay is the "
            "invariant under test)"
        )
    rng = random.Random(seed)
    faults = []
    if nan_request is not None and nan_request < n_requests:
        faults.append(Fault(
            "nan", at_iter=4, field="r", request_id=_chaos_id(nan_request),
        ))
    if oom_request is not None and oom_request < n_requests:
        faults.append(Fault(
            "oom", at_iter=2, request_id=_chaos_id(oom_request),
        ))
    if mesh_kill_request is not None and mesh_kill_request < n_requests:
        faults.append(Fault(
            "device_loss", at_iter=1, device=0,
            request_id=_chaos_id(mesh_kill_request),
        ))
    if malformed_request is not None and malformed_request < n_requests:
        faults.append(Fault(
            "malformed_spec", request_id=_chaos_id(malformed_request),
        ))
    if degenerate_request is not None and degenerate_request < n_requests:
        faults.append(Fault(
            "degenerate_geometry",
            request_id=_chaos_id(degenerate_request),
        ))
    if poison_request is not None and poison_request < n_requests:
        if not warm_start:
            raise ValueError(
                "poison_request targets the solve-cache consult; it needs "
                "warm_start=True (a cache-off drill has no consult to "
                "poison)"
            )
        faults.append(Fault(
            "cache_poison", request_id=_chaos_id(poison_request),
        ))

    def make_scheduler():
        return Scheduler(
            lanes=lanes, chunk=chunk, queue_capacity=queue_capacity,
            max_retries=max_retries, backoff_base_s=0.001,
            journal=(
                RequestJournal(journal_path) if journal_path is not None
                else None
            ),
            faults=FaultPlan(*faults),
            keep_solutions=False,
            warm_start=warm_start,
        )

    t0 = time.monotonic()
    sched = make_scheduler()
    results: dict[str, object] = {}
    completions_seen: dict[str, int] = {}

    def harvest(s: Scheduler):
        for rid, res in s.results.items():
            if rid in results:
                completions_seen[rid] = completions_seen.get(rid, 1) + 1
            results[rid] = res

    replayed = 0
    # the arrival stream: exponential gaps, mixed shapes; between
    # arrivals the scheduler keeps chewing chunks. Gaps are capped so a
    # low rate cannot stall the harness; outcomes stay deterministic in
    # the seed (arrival order and fault addressing are seed-driven, the
    # sleep only paces the wall clock)
    for i in range(n_requests):
        if kill and i == kill_after:
            # SIGKILL semantics: harvest what the dead server already
            # finished (its journal has it), drop it mid-flight, replay
            harvest(sched)
            obs_trace.event("serve:chaos-kill", at_request=i)
            sched = make_scheduler()
            replayed = sched.replay()
        time.sleep(min(rng.expovariate(rate_per_s), 0.01))
        M, N = rng.choice(list(grids))
        is_grad = i in grad_requests
        req = ServeRequest(
            problem=Problem(M=M, N=N),
            deadline=(
                None if deadline_s is None
                else sched.clock() + deadline_s
            ),
            max_retries=max_retries,
            # the grad kind rides the same stream: two lane solves
            # (primal + IFT adjoint) ending in (value, grad) — the
            # invariants extend to it unchanged, plus payload presence
            grad=is_grad,
            geometry=(
                {"kind": "ellipse", "cx": 0.05, "cy": -0.02, "rx": 0.9,
                 "ry": 0.45} if is_grad else None
            ),
            objective={"kind": "energy"} if is_grad else None,
        )
        req.request_id = _chaos_id(i)
        sched.submit_request(req)
        # a couple of chunks between arrivals, like a busy server
        sched.step()
    sched.drain()
    harvest(sched)

    submitted = [_chaos_id(i) for i in range(n_requests)]
    outcomes = {
        rid: results[rid].outcome for rid in submitted if rid in results
    }
    lost = [rid for rid in submitted if rid not in outcomes]
    unclassified = [
        rid for rid, out in outcomes.items() if out not in OUTCOMES
    ]
    double = sorted(rid for rid, n in completions_seen.items() if n > 1)
    counts: dict[str, int] = {}
    for out in outcomes.values():
        counts[out] = counts.get(out, 0) + 1
    grad_missing = [
        _chaos_id(i) for i in grad_requests
        if i < n_requests
        and outcomes.get(_chaos_id(i)) == "completed"
        and getattr(results[_chaos_id(i)], "grad", None) is None
    ]
    report = ChaosReport(
        n_requests=n_requests,
        outcomes=outcomes,
        counts=counts,
        lost=lost,
        double_completed=double,
        unclassified=unclassified,
        replayed=replayed,
        killed=kill,
        faults_fired=sum(1 for f in faults if f.fired),
        wall_s=time.monotonic() - t0,
        mesh_killed=any(
            f.kind == "device_loss" and f.fired for f in faults
        ),
        grad_requests=sum(1 for i in grad_requests if i < n_requests),
        grad_missing_payload=grad_missing,
        drain_shed=sched.drain_sheds,
    )
    obs_trace.event("serve:chaos-report", **report.json_dict())
    return report


def _run_fleet_chaos(
    n_requests: int,
    seed: int,
    grids,
    rate_per_s: float,
    lanes: int,
    chunk: int,
    queue_capacity: int,
    journal_path,
    nan_request: Optional[int],
    oom_request: Optional[int],
    deadline_s: Optional[float],
    max_retries: int,
    replicas: int,
    replica_kill: Optional[int],
    kill_during_handoff: bool,
    zombie: bool,
    lease_s: float,
    replica_rejoin: Optional[int],
    replica_kill_again: Optional[int],
    lease_store_outage: Optional[int],
    lease_store_outage_s: float,
    tenant_mix,
    class_quotas: Optional[dict],
    starvation_after_s: Optional[float],
) -> ChaosReport:
    """The fleet half of :func:`run_chaos` (see its docstring).

    ``journal_path`` names the fleet's journal DIRECTORY (one ledger per
    replica) and is mandatory — the handoff under test IS the journals.
    The kill/hang indices are seed-independent constants of the call,
    so the whole drill is deterministic per (seed, parameters): same
    arrivals, same victim, same handoff boundary, same outcomes.
    """
    from poisson_ellipse_tpu.fleet import FleetRouter, StaleLeaseError
    from poisson_ellipse_tpu.resilience import faultinject
    from poisson_ellipse_tpu.resilience.errors import FleetUnavailableError
    from poisson_ellipse_tpu.serve.request import ServeResult

    if journal_path is None:
        raise ValueError(
            "fleet chaos needs journal_path (a directory: the "
            "journal-backed handoff is the invariant under test)"
        )
    if kill_during_handoff and replicas < 3:
        raise ValueError(
            "kill_during_handoff kills TWO replicas at one boundary; "
            "the drill needs replicas >= 3 so an adopter survives "
            "(with 2 the stream would just hit the exit-9 total-loss "
            "path, which is its own drill)"
        )
    if kill_during_handoff and zombie and replica_kill is None:
        raise ValueError(
            "kill_during_handoff rides the replica_kill drill's "
            "handoff boundary; combining it with zombie needs an "
            "explicit replica_kill index (zombie alone arms no kill)"
        )
    if replica_kill is None and not zombie:
        replica_kill = n_requests // 2
    victim_boundary = replica_kill if replica_kill is not None else (
        max(n_requests // 3, 1) if zombie else None
    )
    if replica_rejoin is not None:
        if victim_boundary is None:
            raise ValueError(
                "replica_rejoin needs a victim: arm replica_kill or "
                "zombie so there is a dead incarnation to rejoin"
            )
        if not victim_boundary < replica_rejoin < n_requests:
            raise ValueError(
                f"replica_rejoin={replica_rejoin} must land strictly "
                f"after the victim boundary ({victim_boundary}) and "
                f"before the stream ends ({n_requests})"
            )
    if replica_kill_again is not None:
        if replica_rejoin is None:
            raise ValueError(
                "replica_kill_again kills the REJOINED incarnation: it "
                "needs replica_rejoin"
            )
        if not replica_rejoin < replica_kill_again < n_requests:
            raise ValueError(
                f"replica_kill_again={replica_kill_again} must land "
                f"strictly after replica_rejoin ({replica_rejoin}) and "
                f"before the stream ends ({n_requests})"
            )
    rng = random.Random(seed)
    faults = []
    if nan_request is not None and nan_request < n_requests:
        faults.append(Fault(
            "nan", at_iter=4, field="r", request_id=_chaos_id(nan_request),
        ))
    if oom_request is not None and oom_request < n_requests:
        faults.append(Fault(
            "oom", at_iter=2, request_id=_chaos_id(oom_request),
        ))
    if replica_kill is not None and 0 < replica_kill < n_requests:
        faults.append(faultinject.replica_kill(
            at_request=replica_kill, replica=0,
        ))
    hang_at = None
    if zombie:
        hang_at = max(n_requests // 3, 1)
        faults.append(faultinject.replica_hang(
            delay_s=float("inf"), at_request=hang_at, replica=0,
        ))
    if lease_store_outage is not None and \
            0 < lease_store_outage < n_requests:
        faults.append(faultinject.lease_store_outage(
            lease_store_outage_s, at_request=lease_store_outage,
        ))
    plan = FaultPlan(*faults)

    t0 = time.monotonic()
    router = FleetRouter(
        replicas=replicas,
        journal_dir=journal_path,
        lease_s=lease_s,
        faults=plan,
        lanes=lanes,
        chunk=chunk,
        queue_capacity=queue_capacity,
        max_retries=max_retries,
        backoff_base_s=0.001,
        keep_solutions=False,
        class_quotas=class_quotas,
        starvation_after_s=starvation_after_s,
        # the per-replica schedulers share the ONE plan, so the
        # request-addressed faults fire on whichever replica hosts
        # their victim — and fire once, fleet-wide
    )
    results: dict[str, object] = {}

    def harvest():
        # double detection lives in the ROUTER's delivery ledger
        # (FleetRouter.harvest: each terminal record passes exactly
        # once, so a second delivery per id IS the bug), not in an
        # object-identity heuristic that a dict merge could launder
        results.update(router.harvest())

    stale_rejected = 0
    second_killed = False
    killed_again = False
    rejoin_due = replica_rejoin
    tenant_of: dict[str, str] = {}
    co_owned: set[str] = set()
    for i in range(n_requests):
        time.sleep(min(rng.expovariate(rate_per_s), 0.01))
        M, N = rng.choice(list(grids))
        tenant, priority = (
            ("default", 1) if tenant_mix is None
            else rng.choice(list(tenant_mix))
        )
        tenant_of[_chaos_id(i)] = tenant
        req_kw = dict(
            deadline_s=deadline_s, max_retries=max_retries,
            request_id=_chaos_id(i), tenant=tenant, priority=priority,
        )
        try:
            router.submit(Problem(M=M, N=N), **req_kw)
        except FleetUnavailableError as e:
            # total loss mid-stream must stay CLASSIFIED inside the
            # report (the invariant is "all classified", and a crashed
            # harness asserts nothing): the refused request records as
            # a shed — it was never admitted anywhere, loudly
            results[_chaos_id(i)] = ServeResult(
                request_id=_chaos_id(i), outcome="shed",
                detail="fleet-unavailable",
                retry_after_s=e.retry_after_s,
            )
        if kill_during_handoff and replica_kill is not None and \
                i >= replica_kill and not second_killed:
            # the second kill lands at the SAME boundary the first
            # handoff finished on: the adopted-but-not-yet-run requests
            # are owned only by replica 1's journal — journal-first
            # adoption is what keeps them alive through this
            second_killed = True
            router.kill_replica(1)
        if zombie and hang_at is not None and i == hang_at:
            # fast-forward the HUNG replica's lease into the past (the
            # deterministic stand-in for "its renewals stopped a lease
            # ago") — sleeping the wall clock instead would also lapse
            # the healthy replicas' leases and turn the drill racy; the
            # honest wall-clock expiry path is pinned with a FakeClock
            # in tests/test_fleet.py
            hung = router._by_id(0)
            if hung is not None and hung.live:
                hung.lease.deadline = router.clock() - 1.0
        router.step()
        harvest()
        if rejoin_due is not None and i >= rejoin_due:
            victim = router._by_id(0)
            if victim is not None and not victim.live:
                try:
                    router.rejoin_replica(0)
                    rejoin_due = None
                except FleetUnavailableError:
                    # a lease-store outage refuses the rejoin (minting
                    # an incarnation needs the store): retry at the
                    # next boundary — recovery re-arms it
                    pass
        if (replica_kill_again is not None and i >= replica_kill_again
                and rejoin_due is None and not killed_again):
            # the second kill hits the REJOINED incarnation: the ladder
            # under test is kill → rejoin → kill-again, with zero
            # lost/double across BOTH epochs of replica 0
            killed_again = True
            router.kill_replica(0)
        # the cross-epoch co-ownership audit, every boundary: any id
        # live-owned twice at ANY instant is evidence, even if a later
        # completion would hide it from an end-of-run check
        co_owned.update(router.audit_ownership())
    # zombie resurrection: the hang clears, the dead-but-alive replica
    # runs its own serve loop again — every completion it attempts must
    # be rejected by its fenced journal, never delivered
    zombie_rep = router.zombies.get(0)
    if zombie and zombie_rep is not None:
        zombie_rep.hung_until = 0.0
        for _ in range(500):
            try:
                if not zombie_rep.resurrect_step():
                    break
            except StaleLeaseError:
                stale_rejected += 1
                break
    if rejoin_due is not None:
        # the stream ended with the rejoin still owed (a long outage):
        # one last attempt after a store probe, so the drill is judged
        # on the recovered fleet rather than a mid-outage snapshot
        victim = router._by_id(0)
        if victim is not None and not victim.live:
            try:
                router.rejoin_replica(0)
            except FleetUnavailableError:
                pass
    try:
        router.drain()
    except FleetUnavailableError:
        # every replica died with admitted work stranded: the report —
        # not an exception — is the verdict, and the stranded ids show
        # up in `lost`, which is exactly what that scenario IS
        pass
    harvest()
    co_owned.update(router.audit_ownership())

    submitted = [_chaos_id(i) for i in range(n_requests)]
    outcomes = {
        rid: results[rid].outcome for rid in submitted if rid in results
    }
    lost = [rid for rid in submitted if rid not in outcomes]
    unclassified = [
        rid for rid, out in outcomes.items() if out not in OUTCOMES
    ]
    double = sorted(set(router.double_delivered))
    counts: dict[str, int] = {}
    for out in outcomes.values():
        counts[out] = counts.get(out, 0) + 1
    episodes, announced = router.starvation_counts()
    starved_silent = sorted(
        t for t, n in episodes.items() if n > announced.get(t, 0)
    )
    tenants: dict[str, dict] = {}
    if tenant_mix is not None:
        for rid, out in outcomes.items():
            per = tenants.setdefault(tenant_of.get(rid, "default"), {})
            per[out] = per.get(out, 0) + 1
    report = ChaosReport(
        n_requests=n_requests,
        outcomes=outcomes,
        counts=counts,
        lost=lost,
        double_completed=double,
        unclassified=unclassified,
        replayed=router.adopted_total,
        killed=any(
            f.kind == "replica_kill" and f.fired for f in faults
        ) or second_killed or killed_again,
        faults_fired=sum(1 for f in faults if f.fired),
        wall_s=time.monotonic() - t0,
        replicas=replicas,
        handoffs=router.handoffs,
        adopted=router.adopted_total,
        stale_writes_rejected=stale_rejected,
        zombie_drill=zombie,
        rejoins=router.rejoins,
        drain_shed=router.drain_shed_total(),
        co_owned=sorted(co_owned),
        starvation_events=sum(episodes.values()),
        starved_silent=starved_silent,
        tenants=tenants,
    )
    obs_trace.event("serve:fleet-chaos-report", **report.json_dict())
    return report
