"""Chaos harness: the serving invariants proven under injected failure.

A serving layer's correctness claims are global, not per-request —
*zero lost* (every admitted request reaches a terminal state, across
kills), *zero double-completed* (no request finishes twice, across
replays), *all classified* (every terminal state is one of the named
outcomes). None of those can be unit-tested one code path at a time;
they have to survive a hostile stream. This module drives one: a
seeded Poisson arrival process of mixed shapes through the scheduler
while ``resilience.faultinject`` poisons lanes (request-addressed NaN),
fakes ``RESOURCE_EXHAUSTED`` on dispatch, and kills the server
mid-stream — the restarted scheduler replays the journal and the
stream keeps going. Everything is deterministic in ``seed``: the same
chaos reproduces bit-for-bit, which is what makes a failing run
debuggable instead of an anecdote.

``run_chaos`` is the single entry shared by ``tests/test_serve.py``,
the ``harness chaos`` subcommand, and the ``bench.py`` serving key's
sanity half.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Optional, Sequence

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs import trace as obs_trace
from poisson_ellipse_tpu.resilience.faultinject import Fault, FaultPlan
from poisson_ellipse_tpu.serve.journal import RequestJournal
from poisson_ellipse_tpu.serve.request import OUTCOMES, ServeRequest
from poisson_ellipse_tpu.serve.scheduler import Scheduler

DEFAULT_GRIDS = ((10, 10), (12, 12), (8, 8))


@dataclasses.dataclass
class ChaosReport:
    """One chaos run's verdict: the invariant booleans plus the
    evidence behind them."""

    n_requests: int
    outcomes: dict            # request_id -> outcome
    counts: dict              # outcome -> count
    lost: list                # submitted ids with no terminal outcome
    double_completed: list    # ids with >1 terminal outcome
    unclassified: list        # ids whose outcome is not in OUTCOMES
    replayed: int
    killed: bool
    faults_fired: int
    wall_s: float
    mesh_killed: bool = False  # a device-loss drill ran mid-stream

    @property
    def ok(self) -> bool:
        return not (self.lost or self.double_completed or self.unclassified)

    def json_dict(self) -> dict:
        out = dataclasses.asdict(self)
        out["ok"] = self.ok
        return out


def _chaos_id(i: int) -> str:
    return f"chaos-{i:04d}"


def run_chaos(
    n_requests: int = 50,
    seed: int = 0,
    grids: Sequence[tuple[int, int]] = DEFAULT_GRIDS,
    rate_per_s: float = 400.0,
    lanes: int = 4,
    chunk: int = 8,
    queue_capacity: int = 128,
    journal_path=None,
    kill_after: Optional[int] = None,
    nan_request: Optional[int] = 2,
    oom_request: Optional[int] = 5,
    deadline_s: Optional[float] = None,
    max_retries: int = 2,
    mesh_kill_request: Optional[int] = None,
    malformed_request: Optional[int] = None,
    degenerate_request: Optional[int] = None,
) -> ChaosReport:
    """Drive one seeded chaos stream; see the module docstring.

    ``kill_after`` (default: ``n_requests // 2``) is the request index
    after which the server is killed — the Scheduler object is dropped
    with requests queued and in flight, exactly what SIGKILL leaves
    behind — and a fresh scheduler on the same journal replays.
    ``nan_request`` / ``oom_request`` pick which request indices get a
    request-addressed NaN-poisoned lane and a fake RESOURCE_EXHAUSTED
    (None disables either). Requires ``journal_path`` when a kill is
    scheduled (the replay is the point).

    ``mesh_kill_request`` arms the DEVICE-kill drill (the ``harness
    chaos --mesh`` flag): when that request is in flight, a simulated
    device loss takes out every live batch carry at once — every
    in-flight request re-enters through the journal/retry ladder
    (``Scheduler._degrade_mesh``) — and the zero-lost/zero-double/
    all-classified invariants are asserted across a device kill, not
    just a process kill.

    ``malformed_request`` / ``degenerate_request`` arm the GEOMETRY
    drill: the named request's geometry spec is swapped at admission
    (``faultinject.malformed_spec`` / ``degenerate_geometry``). The
    malformed one must end in the terminal classified ``invalid``
    outcome without ever touching a lane; the degenerate (sliver-cut)
    one must pass the gate and SOLVE cleanly under the clamp — and in
    both cases every OTHER request's lane runs clean (zero poisoning,
    asserted by the same global invariants).
    """
    if n_requests < 1:
        raise ValueError("need at least one request")
    if rate_per_s <= 0:
        raise ValueError("rate_per_s must be > 0")
    if kill_after is None:
        kill_after = n_requests // 2
    kill = kill_after is not None and 0 < kill_after < n_requests
    if kill and journal_path is None:
        raise ValueError(
            "a kill/restart chaos run needs journal_path (replay is the "
            "invariant under test)"
        )
    rng = random.Random(seed)
    faults = []
    if nan_request is not None and nan_request < n_requests:
        faults.append(Fault(
            "nan", at_iter=4, field="r", request_id=_chaos_id(nan_request),
        ))
    if oom_request is not None and oom_request < n_requests:
        faults.append(Fault(
            "oom", at_iter=2, request_id=_chaos_id(oom_request),
        ))
    if mesh_kill_request is not None and mesh_kill_request < n_requests:
        faults.append(Fault(
            "device_loss", at_iter=1, device=0,
            request_id=_chaos_id(mesh_kill_request),
        ))
    if malformed_request is not None and malformed_request < n_requests:
        faults.append(Fault(
            "malformed_spec", request_id=_chaos_id(malformed_request),
        ))
    if degenerate_request is not None and degenerate_request < n_requests:
        faults.append(Fault(
            "degenerate_geometry",
            request_id=_chaos_id(degenerate_request),
        ))

    def make_scheduler():
        return Scheduler(
            lanes=lanes, chunk=chunk, queue_capacity=queue_capacity,
            max_retries=max_retries, backoff_base_s=0.001,
            journal=(
                RequestJournal(journal_path) if journal_path is not None
                else None
            ),
            faults=FaultPlan(*faults),
            keep_solutions=False,
        )

    t0 = time.monotonic()
    sched = make_scheduler()
    results: dict[str, object] = {}
    completions_seen: dict[str, int] = {}

    def harvest(s: Scheduler):
        for rid, res in s.results.items():
            if rid in results:
                completions_seen[rid] = completions_seen.get(rid, 1) + 1
            results[rid] = res

    replayed = 0
    # the arrival stream: exponential gaps, mixed shapes; between
    # arrivals the scheduler keeps chewing chunks. Gaps are capped so a
    # low rate cannot stall the harness; outcomes stay deterministic in
    # the seed (arrival order and fault addressing are seed-driven, the
    # sleep only paces the wall clock)
    for i in range(n_requests):
        if kill and i == kill_after:
            # SIGKILL semantics: harvest what the dead server already
            # finished (its journal has it), drop it mid-flight, replay
            harvest(sched)
            obs_trace.event("serve:chaos-kill", at_request=i)
            sched = make_scheduler()
            replayed = sched.replay()
        time.sleep(min(rng.expovariate(rate_per_s), 0.01))
        M, N = rng.choice(list(grids))
        req = ServeRequest(
            problem=Problem(M=M, N=N),
            deadline=(
                None if deadline_s is None
                else sched.clock() + deadline_s
            ),
            max_retries=max_retries,
        )
        req.request_id = _chaos_id(i)
        sched.submit_request(req)
        # a couple of chunks between arrivals, like a busy server
        sched.step()
    sched.drain()
    harvest(sched)

    submitted = [_chaos_id(i) for i in range(n_requests)]
    outcomes = {
        rid: results[rid].outcome for rid in submitted if rid in results
    }
    lost = [rid for rid in submitted if rid not in outcomes]
    unclassified = [
        rid for rid, out in outcomes.items() if out not in OUTCOMES
    ]
    double = sorted(rid for rid, n in completions_seen.items() if n > 1)
    counts: dict[str, int] = {}
    for out in outcomes.values():
        counts[out] = counts.get(out, 0) + 1
    report = ChaosReport(
        n_requests=n_requests,
        outcomes=outcomes,
        counts=counts,
        lost=lost,
        double_completed=double,
        unclassified=unclassified,
        replayed=replayed,
        killed=kill,
        faults_fired=sum(1 for f in faults if f.fired),
        wall_s=time.monotonic() - t0,
        mesh_killed=any(
            f.kind == "device_loss" and f.fired for f in faults
        ),
    )
    obs_trace.event("serve:chaos-report", **report.json_dict())
    return report
