"""serve — the continuous-batching serving front-end (ISSUE 7).

The "millions of users" layer over the batched engines: an async
request scheduler that packs queued requests into the warm-pool grid/
lane buckets and **retires and refills lanes at chunk boundaries** —
the in-loop freeze-out mask of ``batch.batched_pcg`` generalized to
swap-in, with no recompile (shapes are the only compile keys). Around
it, the robustness envelope a service needs: bounded admission with
backpressure and load-shedding (:mod:`.queue`), per-request deadlines
enforced at chunk granularity, a retry budget walking the resilience
degradation ladder (:mod:`.scheduler`), a crash-safe temp-then-rename
request journal with restart replay (:mod:`.journal`), classified
terminal outcomes mapped onto the exit-code contract (:mod:`.request`),
and a seeded chaos harness that proves zero-lost / zero-double /
all-classified under injected faults, overload and kills
(:mod:`.chaos`). Every lifecycle transition is a request-addressed
``obs.trace`` event (schema v3) and an ``obs.metrics`` series.
"""

from poisson_ellipse_tpu.serve.chaos import ChaosReport, run_chaos
from poisson_ellipse_tpu.serve.journal import (
    DoubleCompletionError,
    RequestJournal,
)
from poisson_ellipse_tpu.serve.queue import AdmissionQueue
from poisson_ellipse_tpu.serve.request import (
    EXIT_BY_OUTCOME,
    OUTCOMES,
    ServeRequest,
    ServeResult,
)
from poisson_ellipse_tpu.serve.scheduler import Scheduler

__all__ = [
    "AdmissionQueue",
    "ChaosReport",
    "DoubleCompletionError",
    "EXIT_BY_OUTCOME",
    "OUTCOMES",
    "RequestJournal",
    "Scheduler",
    "ServeRequest",
    "ServeResult",
    "run_chaos",
]
