"""Bounded admission: backpressure and load-shedding at the front door.

An unbounded queue converts overload into silent latency: every request
is "accepted" and then misses its deadline anyway, after holding memory
the whole time (the failure mode tpulint TPU012 fences structurally).
This queue is bounded twice — an explicit capacity check that *rejects*
(the backpressure contract: the caller learns now, with a
``retry_after_s`` hint) and a ``deque(maxlen=...)`` backstop that can
never silently drop because the check runs first.

Shedding is deadline-aware: when the projected wait (an EWMA of recent
per-request service time, scaled by queue depth over lane width)
already overruns a request's deadline, admitting it would only burn a
lane on a guaranteed miss — reject-with-retry-after instead. A shed
whose terminal outcome IS shed emits a ``serve:shed`` trace event
(request-addressed, schema v3) and bumps the ``shed_total`` counter;
rejections the scheduler classifies under another outcome (replay
``deadline-miss``, retry-overflow ``failed``) stay silent here so the
counter always equals the number of shed outcomes. Depth is published
as the ``queue_depth`` gauge on every transition.

Multi-tenant admission classes ride on the same bound: every request
carries ``tenant``/``priority`` (``serve.request``), and three policies
apply when they differ —

- **per-class quotas** (``class_quotas={tenant: max_queued}``): a
  tenant at its quota sheds with reason ``tenant-quota`` even while the
  queue has room, so one chatty tenant cannot monopolise the bound;
- **queue-full preemption**: a full queue admits a HIGHER-priority
  arrival by evicting the lowest-priority (most recently enqueued)
  queued request instead of shedding the arrival — low-priority work
  sheds first under pressure, never the other way around. Victims land
  in ``take_evicted()`` for the scheduler to classify (terminal
  ``shed`` with detail ``preempted-by-priority``), never dropped;
- **priority-first dispatch**: ``pop_ready`` serves the highest
  priority among ready requests, FIFO within a class.

Priority scheduling can starve: a class that stays ready-but-unserved
past ``starvation_after_s`` is a LOUD ``fleet:starvation`` event (and
``fleet_starvation_total`` count) once per episode — never silent. The
queue tracks detection (``starvation_episodes``) and announcement
(``starvation_announced``) separately so the chaos report can prove no
episode went unannounced.
"""

from __future__ import annotations

import collections
import time
from typing import Callable, Optional

from poisson_ellipse_tpu.obs import metrics as obs_metrics
from poisson_ellipse_tpu.obs import trace as obs_trace
from poisson_ellipse_tpu.serve.request import ServeRequest

# starting estimate of per-request service seconds, before the EWMA has
# seen a completion (deliberately small: the first requests of a cold
# server should not be shed on a pessimistic guess)
_INITIAL_SERVICE_S = 0.05
_EWMA_ALPHA = 0.2


class AdmissionQueue:
    """FIFO admission with backpressure and deadline-aware shedding.

    ``lanes`` is the scheduler's concurrent lane width (the divisor of
    the projected-wait estimate); ``clock`` the scheduler's monotonic
    clock (injectable for deterministic deadline tests).
    """

    def __init__(self, capacity: int, lanes: int,
                 clock: Callable[[], float] = time.monotonic,
                 class_quotas: Optional[dict] = None,
                 starvation_after_s: Optional[float] = None):
        if capacity < 1:
            raise ValueError("queue capacity must be >= 1")
        if lanes < 1:
            raise ValueError("lanes must be >= 1")
        self.capacity = capacity
        self.lanes = lanes
        self.clock = clock
        # maxlen is the structural backstop (TPU012's bound); admit()'s
        # explicit capacity check rejects BEFORE append, so the deque's
        # silent-drop-on-full behaviour is unreachable
        self._q: collections.deque = collections.deque(maxlen=capacity)
        self._service_ewma = _INITIAL_SERVICE_S
        # multi-tenant policy state (module docstring): quotas, the
        # preemption victim hand-off, and per-episode starvation
        # bookkeeping (detection and announcement counted separately so
        # "never silent" is checkable, not asserted)
        self.class_quotas = dict(class_quotas) if class_quotas else None
        self.starvation_after_s = starvation_after_s
        self._evicted: list[ServeRequest] = []
        self.starvation_episodes: dict[str, int] = {}
        self.starvation_announced: dict[str, int] = {}
        self._starving: set[str] = set()

    def __len__(self) -> int:
        return len(self._q)

    def holds(self, request_id: str) -> bool:
        """Whether a queued request carries this id (the scheduler's
        duplicate-admission guard)."""
        return any(r.request_id == request_id for r in self._q)

    # -- load model ---------------------------------------------------------

    def observe_service(self, seconds: float) -> None:
        """Fold one completed request's service time into the EWMA the
        projected-wait shed policy reads."""
        self._service_ewma = (
            (1 - _EWMA_ALPHA) * self._service_ewma + _EWMA_ALPHA * seconds
        )

    def projected_wait(self) -> float:
        """Expected queueing delay for a request admitted now: queued
        work ahead of it, spread over the lane width."""
        return self._service_ewma * (len(self._q) + 1) / self.lanes

    # -- admission ----------------------------------------------------------

    def admit(self, request: ServeRequest,
              record_shed: bool = True) -> tuple[bool, Optional[float],
                                                 Optional[str]]:
        """Admit or shed; returns ``(accepted, retry_after_s, reason)``.

        Shed reasons: ``queue-full`` (depth at capacity) and
        ``deadline-infeasible`` (projected wait alone overruns the
        request's deadline). ``retry_after_s`` estimates when capacity
        should free up — the backpressure hint a client honours instead
        of hammering. ``record_shed=False`` suppresses the shed
        counter/event for callers that classify the rejection under a
        different terminal outcome (the scheduler's replay path records
        ``deadline-miss``) — ``shed_total`` must equal the number of
        requests whose *outcome* is shed.
        """
        now = self.clock()
        if self.class_quotas is not None:
            quota = self.class_quotas.get(request.tenant)
            queued = sum(
                1 for r in self._q if r.tenant == request.tenant
            )
            if quota is not None and queued >= quota:
                retry_after = self.projected_wait()
                if record_shed:
                    self._shed(request, "tenant-quota", retry_after)
                return False, retry_after, "tenant-quota"
        if len(self._q) >= self.capacity:
            if not self._preempt_for(request):
                retry_after = self.projected_wait()
                if record_shed:
                    self._shed(request, "queue-full", retry_after)
                return False, retry_after, "queue-full"
        if request.deadline is not None:
            wait = self.projected_wait()
            if now + wait > request.deadline:
                retry_after = wait
                if record_shed:
                    self._shed(request, "deadline-infeasible", retry_after)
                return False, retry_after, "deadline-infeasible"
        request.enqueued_t = now
        if request.admitted_t is None:
            request.admitted_t = now
        self._q.append(request)
        obs_metrics.gauge("queue_depth").set(len(self._q))
        obs_trace.event(
            "serve:admit", request_id=request.request_id,
            depth=len(self._q), grid=[request.problem.M, request.problem.N],
        )
        return True, None, None

    def _preempt_for(self, request: ServeRequest) -> bool:
        """Queue-full arbitration: evict the lowest-priority (most
        recently enqueued among ties) queued request STRICTLY below the
        arrival's priority, or report False (equal priority never
        preempts — FIFO fairness within a class). The victim moves to
        the ``take_evicted()`` hand-off for the scheduler to classify
        terminally; it is never silently dropped."""
        victim_i = None
        victim = None
        for i, req in enumerate(self._q):
            if req.priority >= request.priority:
                continue
            if victim is None or req.priority < victim.priority or (
                req.priority == victim.priority
                and req.enqueued_t >= victim.enqueued_t
            ):
                victim_i, victim = i, req
        if victim is None:
            return False
        del self._q[victim_i]
        self._evicted.append(victim)
        obs_metrics.counter("preempted_total").inc()
        obs_metrics.gauge("queue_depth").set(len(self._q))
        obs_trace.event(
            "serve:preempt", request_id=victim.request_id,
            tenant=victim.tenant, priority=victim.priority,
            by=request.request_id, by_priority=request.priority,
            depth=len(self._q),
        )
        # the victim's terminal outcome IS shed (the scheduler
        # classifies it from take_evicted), so the shed counter/event
        # fire here to keep shed_total == shed outcomes
        self._shed(victim, "preempted-by-priority", self.projected_wait())
        return True

    def take_evicted(self) -> list[ServeRequest]:
        """Drain the preemption victims accumulated since the last call
        (the scheduler classifies each as a terminal ``shed``)."""
        victims, self._evicted = self._evicted, []
        return victims

    def retract(self, request: ServeRequest, reason: str) -> None:
        """Undo an admission that cannot be honoured after all (the
        scheduler's write-ahead journal refused it): remove the request,
        republish the depth gauge, and emit the compensating
        ``serve:retract`` event so the earlier ``serve:admit`` does not
        read as a live request in the trace."""
        self._q.remove(request)
        obs_metrics.gauge("queue_depth").set(len(self._q))
        obs_trace.event(
            "serve:retract", request_id=request.request_id, reason=reason,
            depth=len(self._q),
        )

    def requeue(self, request: ServeRequest) -> bool:
        """Put a retried request back (backpressure still applies: a
        full queue rejects the retry — overload must not be hidden
        inside the retry ladder; the scheduler classifies the rejection
        ``failed``, so no shed event fires here). Returns whether it
        was accepted."""
        if len(self._q) >= self.capacity:
            return False
        # a retry starts a NEW queue visit: re-stamp so its histogram
        # sample measures this wait, not this wait plus the failed
        # attempt's solve time (admitted_t keeps the end-to-end anchor)
        request.enqueued_t = self.clock()
        self._q.append(request)
        obs_metrics.gauge("queue_depth").set(len(self._q))
        return True

    def _shed(self, request: ServeRequest, reason: str,
              retry_after: float) -> None:
        obs_metrics.counter("shed_total").inc()
        obs_trace.event(
            "serve:shed", request_id=request.request_id, reason=reason,
            retry_after_s=round(retry_after, 4), depth=len(self._q),
        )

    # -- dispatch side ------------------------------------------------------

    def pop_ready(self, now: float) -> Optional[ServeRequest]:
        """The highest-priority request whose retry backoff has elapsed
        (``not_before <= now``), FIFO within a priority class, removed;
        None when none is ready. Every pop also runs the starvation
        scan: a class left ready-but-unserved past
        ``starvation_after_s`` announces loudly (module docstring)."""
        best_i = None
        best = None
        for i, req in enumerate(self._q):
            if req.not_before <= now and (
                best is None or req.priority > best.priority
            ):
                best_i, best = i, req
        if best is None:
            return None
        del self._q[best_i]
        obs_metrics.gauge("queue_depth").set(len(self._q))
        self._scan_starvation(now, served=best.tenant)
        return best

    def _scan_starvation(self, now: float, served: str) -> None:
        """Detect-and-announce, once per episode: any tenant with a
        ready request older than ``starvation_after_s`` while ANOTHER
        tenant gets served is starving. Detection
        (``starvation_episodes``) and the ``fleet:starvation`` event /
        counter (``starvation_announced``) are bumped in the same
        breath — the chaos report cross-checks the two so a refactor
        cannot keep detecting but stop announcing."""
        if self.starvation_after_s is None:
            return
        oldest: dict[str, float] = {}
        for req in self._q:
            if req.not_before <= now and req.enqueued_t is not None:
                wait = now - req.enqueued_t
                if wait > oldest.get(req.tenant, -1.0):
                    oldest[req.tenant] = wait
        # a served or drained tenant's episode is over; it may starve
        # (and announce) again later
        self._starving &= set(oldest)
        self._starving.discard(served)
        for tenant, wait in sorted(oldest.items()):
            if tenant == served or wait <= self.starvation_after_s:
                continue
            if tenant in self._starving:
                continue
            self._starving.add(tenant)
            self.starvation_episodes[tenant] = (
                self.starvation_episodes.get(tenant, 0) + 1
            )
            self.starvation_announced[tenant] = (
                self.starvation_announced.get(tenant, 0) + 1
            )
            obs_metrics.counter("fleet_starvation_total").inc()
            obs_trace.event(
                "fleet:starvation", tenant=tenant,
                waited_s=round(wait, 4), depth=len(self._q),
            )

    def request_ids(self) -> set[str]:
        """Ids currently queued (the fleet's co-ownership audit reads
        this alongside lanes, backlog and journal)."""
        return {r.request_id for r in self._q}

    def expire(self, now: float) -> list[ServeRequest]:
        """Remove and return every queued request whose deadline has
        passed — they are shed *from the queue* (never dispatched); the
        scheduler classifies them ``deadline-miss``."""
        expired = [
            r for r in self._q
            if r.deadline is not None and now > r.deadline
        ]
        if expired:
            for r in expired:
                self._q.remove(r)
            obs_metrics.gauge("queue_depth").set(len(self._q))
        return expired

    def push_front(self, request: ServeRequest) -> None:
        """Return a popped-but-undispatchable request to the head of the
        line (its bucket had no free lane this boundary) — FIFO order is
        preserved, and the slot it vacated moments ago bounds the depth,
        so the maxlen backstop cannot trip."""
        self._q.appendleft(request)
        obs_metrics.gauge("queue_depth").set(len(self._q))

    def next_ready_in(self, now: float) -> Optional[float]:
        """Seconds until the earliest backoff elapses (None when empty
        or something is ready now) — the drain loop's idle-wait hint."""
        if not self._q:
            return None
        waits = [r.not_before - now for r in self._q]
        soonest = min(waits)
        return None if soonest <= 0 else soonest
