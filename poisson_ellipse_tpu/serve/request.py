"""The serving request model: one solve ask, one classified outcome.

A production front-end never loses a request in an unnamed state: every
request admitted into the scheduler (:mod:`.scheduler`) carries an id,
an absolute deadline, and a retry budget, and every request *ends* in
exactly one of the :data:`OUTCOMES` — the terminal-state contract the
chaos harness (:mod:`.chaos`) asserts over the whole stream. Outcomes
map onto the process exit-code contract of ``resilience.errors``
(:data:`EXIT_BY_OUTCOME`), extended by the serving layer's shed code:

  ===============  ====  =====================================================
  outcome          exit  meaning
  ===============  ====  =====================================================
  completed        0     converged solution returned (possibly via the
                         guarded-fallback rung of the retry ladder)
  cap              1     iteration cap reached without convergence — the
                         harness's pre-existing exit-1 contract, per request
  failed           2     retry budget exhausted AND the guarded fallback
                         classified the solve diverged (or an unrecoverable
                         classified error)
  deadline-miss    4     the deadline passed — while queued (never dispatched)
                         or mid-solve (chunk-boundary cancel, partial result)
  shed             5     rejected at admission (queue full / projected
                         deadline miss); never queued, safe to resubmit after
                         ``retry_after_s``
  invalid          8     the request's geometry spec failed the admissibility
                         gate (``geom.validate``) AT ADMISSION — malformed,
                         empty, under-resolved, or operator-inadmissible; the
                         request was never journaled or dispatched (retracted
                         from the queue before anything durable saw it), so a
                         bad geometry can never poison a lane mid-batch
  ===============  ====  =====================================================

The wire/journal form of a request (:meth:`ServeRequest.spec`) is a flat
JSON object so the crash-safe journal (:mod:`.journal`) can persist and
replay it without pickling.
"""

from __future__ import annotations

import dataclasses
import uuid
from typing import Optional

import numpy as np

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.resilience.errors import (
    EXIT_DIVERGED,
    EXIT_INVALID_GEOMETRY,
    EXIT_SHED,
    EXIT_TIMEOUT,
)

OUTCOMES = ("completed", "cap", "failed", "deadline-miss", "shed", "invalid")

EXIT_BY_OUTCOME = {
    "completed": 0,
    "cap": 1,
    "failed": EXIT_DIVERGED,
    "deadline-miss": EXIT_TIMEOUT,
    "shed": EXIT_SHED,
    "invalid": EXIT_INVALID_GEOMETRY,
}


def new_request_id() -> str:
    return uuid.uuid4().hex[:12]


@dataclasses.dataclass
class ServeRequest:
    """One queued solve: a problem plus its serving envelope.

    ``deadline`` is absolute on the scheduler's clock (``None`` = no
    deadline); ``max_retries`` bounds the degradation ladder's
    resubmissions (the final rung — the guarded single solve — rides on
    top of them). ``not_before`` / ``attempt`` are the retry-backoff
    bookkeeping the scheduler maintains; ``enqueued_t`` stamps the
    *current* queue visit (reset on every retry requeue — it feeds the
    per-wait ``time_in_queue_seconds`` histogram), ``admitted_t`` the
    first admission (it anchors the end-to-end ``total_s``).
    """

    problem: Problem
    deadline: Optional[float] = None
    max_retries: int = 1
    request_id: str = dataclasses.field(default_factory=new_request_id)
    # the JSON SDF spec of an arbitrary domain (None = the hard-coded
    # ellipse) and its degenerate-cut clamp threshold — validated at
    # ADMISSION (never mid-solve) against ``geom.validate``
    geometry: Optional[dict] = None
    theta: Optional[float] = None
    # the differentiable-solving kind (``diff.serving``): grad=True asks
    # for (value, gradient) of ``objective`` (a ``diff.objectives`` JSON
    # spec; None = Dirichlet energy) w.r.t. the geometry's parameters —
    # served as two consecutive lane solves (primal, then the IFT
    # adjoint with the same operator), terminally completing with
    # ``ServeResult.value``/``ServeResult.grad``
    grad: bool = False
    objective: Optional[dict] = None
    # multi-tenant admission class (``serve.queue``): ``tenant`` names
    # the accounting class (per-class queue quotas), ``priority`` its
    # weight — HIGHER is more important. Under pressure low-priority
    # work sheds first (queue-full preemption), ``pop_ready`` serves
    # priority-first, and a dying replica's high-priority work is
    # adopted first (``fleet.handoff``). Journal-round-tripped so a
    # replayed request keeps its class.
    tenant: str = "default"
    priority: int = 1
    # scheduler bookkeeping (not part of the wire spec)
    enqueued_t: Optional[float] = None
    admitted_t: Optional[float] = None
    not_before: float = 0.0
    attempt: int = 0
    dispatched: bool = False
    # journal-replayed (or peer-adopted) requests run COLD: the solve
    # cache is in-memory host state the journal never records, so a
    # replay's outcome must not depend on what it held — skipping the
    # warm-start consult is what pins replayed outcomes bit-identical
    # regardless of cache state (the chaos invariant)
    replayed: bool = False
    # the parsed SDF tree, cached after admission validation
    _geom_obj: object = dataclasses.field(
        default=None, repr=False, compare=False
    )

    def geometry_sdf(self):
        """The parsed SDF tree of ``geometry`` (None for the default
        ellipse); parsing classifies a malformed spec
        (``InvalidGeometryError``), and the result is cached so replayed
        requests parse once."""
        if self.geometry is None:
            return None
        if self._geom_obj is None:
            from poisson_ellipse_tpu.geom import sdf as geom_sdf

            self._geom_obj = geom_sdf.from_spec(self.geometry)
        return self._geom_obj

    def spec(self) -> dict:
        """The flat JSON form the journal persists and replay rebuilds.

        Deadlines are journaled as *remaining seconds at admission*
        (``deadline_left_s``): the scheduler clock is monotonic and does
        not survive a process restart, so an absolute value would be
        meaningless to the replaying process.
        """
        p = self.problem
        return {
            "request_id": self.request_id,
            "M": p.M,
            "N": p.N,
            "delta": p.delta,
            "eps": p.eps,
            "norm": p.norm,
            "max_iter": p.max_iter,
            "deadline_left_s": (
                None if self.deadline is None or self.enqueued_t is None
                else max(self.deadline - self.enqueued_t, 0.0)
            ),
            "max_retries": self.max_retries,
            "geometry": self.geometry,
            "theta": self.theta,
            "grad": self.grad,
            "objective": self.objective,
            "tenant": self.tenant,
            "priority": self.priority,
        }

    @classmethod
    def from_spec(cls, spec: dict, now: float) -> "ServeRequest":
        """Rebuild a journaled request; the journaled remaining-deadline
        budget restarts from ``now`` (replay grants the request the time
        it had left when first admitted)."""
        left = spec.get("deadline_left_s")
        return cls(
            problem=Problem(
                M=spec["M"], N=spec["N"], delta=spec["delta"],
                eps=spec.get("eps"), norm=spec.get("norm", "weighted"),
                max_iter=spec.get("max_iter"),
            ),
            deadline=None if left is None else now + left,
            max_retries=spec.get("max_retries", 1),
            request_id=spec["request_id"],
            geometry=spec.get("geometry"),
            theta=spec.get("theta"),
            grad=bool(spec.get("grad", False)),
            objective=spec.get("objective"),
            tenant=spec.get("tenant", "default"),
            priority=int(spec.get("priority", 1)),
        )


@dataclasses.dataclass
class ServeResult:
    """One request's terminal state — every field host-side and final.

    ``partial`` marks a mid-solve deadline cancel: ``iters``/``diff``
    (and ``w`` when kept) describe the last chunk boundary reached, the
    ``run_report_partial`` stance applied per request. ``detail`` names
    the path that produced the outcome (``guarded-fallback``,
    ``expired-in-queue``, a shed reason, …).
    """

    request_id: str
    outcome: str
    iters: int = 0
    diff: float = float("inf")
    converged: bool = False
    partial: bool = False
    dispatched: bool = False
    attempts: int = 0
    time_in_queue_s: float = 0.0
    total_s: float = 0.0
    detail: Optional[str] = None
    retry_after_s: Optional[float] = None
    w: Optional[np.ndarray] = None
    # grad-kind terminals (``grad=True`` requests): the objective value
    # and the gradient w.r.t. the geometry's parameter vector
    value: Optional[float] = None
    grad: Optional[list] = None

    def __post_init__(self):
        if self.outcome not in OUTCOMES:
            raise ValueError(
                f"outcome {self.outcome!r} not one of {OUTCOMES}"
            )

    @property
    def exit_code(self) -> int:
        return EXIT_BY_OUTCOME[self.outcome]

    def json_dict(self) -> dict:
        """The loggable form (solution array elided — it belongs to the
        caller, not a trace line)."""
        out = dataclasses.asdict(self)
        out.pop("w")
        return out
