"""IFT adjoints through the converged solve: the ``custom_vjp`` wrapper.

At convergence the solve is the implicit function u(θ) of
``A(θ) u = b(θ)`` with A symmetric positive definite, so reverse-mode
differentiation needs exactly one more solve WITH THE SAME OPERATOR:

    ū = ∂L/∂u   →   A λ = ū   →   θ̄ = −λᵀ(∂A/∂θ · u − ∂b/∂θ) .

The ``jax.custom_vjp`` is registered at the linear-solve level,
``_core(a, b, rhs) -> u``:

- the FORWARD is any registered engine's converged solve over supplied
  operands — classical xla/pallas, the pipelined recurrence, mg-pcg /
  cheb-pcg (the ``precond`` hook reused, hierarchy resolved once at
  build time), or the 1×2+ sharded composition;
- the BACKWARD calls ``_core`` AGAIN on the cotangent (the adjoint PCG
  — Christianson's fixed-point adjoint: the adjoint of the adjoint is
  the same operator, so it is served by the same solve), then contracts
  λ against the operand cotangents of ``A(·) u`` via ``jax.vjp`` —
  plain smooth ops;
- the θ-chain ∂(a, b, rhs)/∂θ is ordinary JAX autodiff through the
  traceable assembly (``diff.assembly``), so one ``jax.grad`` over
  ``ImplicitSolver.solve`` yields SDF-parameter, source-field and ε
  gradients together.

**Tolerance contract** — quoted, not hoped for: every ``_core`` solve
normalises its RHS to unit euclidean norm (the weighting factor is a
scalar that cancels by linearity) and runs the engine at the primal δ
(times ``delta_scale``), then rescales. The
adjoint therefore converges to the same RELATIVE tolerance as the
primal regardless of the cotangent's magnitude, and the gradient error
is O(δ)·‖θ̄‖ — ``last`` records each solve's iterations and final
step-norm so the quote is inspectable per call.

``adjoint="linear"`` swaps the wrapper for ``lax.custom_linear_solve``
(symmetric=True): the same engine solve as the callback, but as a
primitive with BOTH a JVP and a transpose rule, composable to any
order — forward-over-reverse HVPs (the efficient recipe) and
grad-of-grad both work, each extra order costing one extra PCG solve.
``jax.custom_vjp`` is differentiated at most once by JAX's protocol
(its residuals re-expose the while_loop at second order), so the
``"vjp"`` mode is the first-order reverse workhorse — it is what works
with host-orchestrated forwards (the sharded runner) — and ``"linear"``
is the higher-order surface (traced engines only).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from poisson_ellipse_tpu.diff import assembly as diff_assembly
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops.stencil import apply_a

# engines the implicit wrapper can run its forward/adjoint solves on
ENGINES = ("xla", "pallas", "pipelined", "mg-pcg", "cheb-pcg", "sharded")

# floor for the RHS-normalisation divisor: a zero cotangent divides by
# this instead of 0 (0/tiny = 0 exactly), and the where-mask on the
# rescale pins the result to the exact zero adjoint λ = A⁻¹·0 = 0
_NORM_TINY = 1e-300


class ImplicitSolver:
    """One problem's differentiable solve surface.

    Build once (hierarchies, spectral probes, sharded executables are
    resolved here), differentiate many: ``solve(params)`` is the
    ``jax.grad``-able map from the diff parameter pytree (see
    ``diff.assembly.operands_of``) to the converged solution grid.

    ``template`` is the ``geom.sdf`` tree whose numeric leaves the
    ``"shape"`` parameter vector re-binds (``geom.sdf.with_params``);
    the default is the reference ellipse. Host-level entry: ``solve``
    itself orchestrates engine dispatch (the guard stance) — wrap only
    the traced engines in an outer ``jit`` if you must, and use
    ``adjoint="linear"`` for forward-mode/HVP composition.
    """

    def __init__(self, problem: Problem, template=None, engine: str = "xla",
                 dtype=None, samples: int = diff_assembly.DEFAULT_SAMPLES,
                 mesh=None, adjoint: str = "vjp", delta_scale: float = 1.0):
        from poisson_ellipse_tpu.geom import sdf as geom_sdf

        if engine not in ENGINES:
            raise ValueError(
                f"engine {engine!r} not in {ENGINES} — the implicit "
                "wrapper runs the solves itself; batched/guarded "
                "orchestration belongs to serve/ (GradJob) and the "
                "guard ladder"
            )
        if adjoint not in ("vjp", "linear"):
            raise ValueError(f"adjoint must be 'vjp' or 'linear', got "
                             f"{adjoint!r}")
        if adjoint == "linear" and engine == "sharded":
            raise ValueError(
                "adjoint='linear' traces the solve into the autodiff "
                "graph; the sharded runner is host-orchestrated — use "
                "adjoint='vjp'"
            )
        self.problem = problem
        self.template = template if template is not None else geom_sdf.Ellipse()
        self.engine = engine
        self.dtype = (
            dtype if dtype is not None else diff_assembly.default_dtype()
        )
        self.samples = samples
        self.delta_scale = float(delta_scale)
        # per-call solve log: [{"iters", "diff", "converged"}, ...] —
        # entry 0 is the primal, entry 1 the adjoint (reverse-over-
        # reverse appends one more per extra order). Host-eager calls
        # only; traced calls skip the log.
        self.last: list[dict] = []

        if self.delta_scale != 1.0:
            import dataclasses

            problem = dataclasses.replace(
                problem, delta=problem.delta * self.delta_scale
            )
        self._solve_problem = problem

        self._runner = self._build_runner(mesh)
        self._core = self._build_core(adjoint)

    # -- engine runners ------------------------------------------------------

    def _build_runner(self, mesh):
        """(a, b, rhs) -> PCGResult on the selected engine, operands
        supplied (never re-assembled): the reuse surface of the whole
        design — the adjoint is served by the same machinery as the
        primal because both are just solves with these operands."""
        problem = self._solve_problem
        dtype = self.dtype
        if self.engine in ("xla", "pallas"):
            from poisson_ellipse_tpu.solver.pcg import pcg

            stencil = self.engine
            # build-once-call-many: the forward, the adjoint, and every
            # FD probe of a gradient check re-dispatch this one
            # executable (no donation for the same reason)
            return jax.jit(  # tpulint: disable=TPU004
                lambda a, b, rhs: pcg(problem, a, b, rhs, stencil=stencil)
            )
        if self.engine == "pipelined":
            from poisson_ellipse_tpu.ops.pipelined_pcg import pcg_pipelined

            return jax.jit(  # tpulint: disable=TPU004
                lambda a, b, rhs: pcg_pipelined(problem, a, b, rhs)
            )
        if self.engine in ("mg-pcg", "cheb-pcg"):
            from poisson_ellipse_tpu.mg.engine import make_precond
            from poisson_ellipse_tpu.solver.engine import (
                PRECOND_KIND_BY_ENGINE,
            )
            from poisson_ellipse_tpu.solver.pcg import pcg

            # hierarchy + Lanczos interval resolved ONCE on the
            # template's operands; the factory re-binds the caller's
            # fine operands per solve (the guard's operand-reuse path)
            ops0 = diff_assembly.operands_of(
                problem, self.template, None, samples=self.samples,
                dtype=dtype,
            )
            factory, _cfg = make_precond(
                problem, dtype, PRECOND_KIND_BY_ENGINE[self.engine],
                operands=ops0, geometry=self.template,
            )
            return jax.jit(  # tpulint: disable=TPU004
                lambda a, b, rhs: pcg(
                    problem, a, b, rhs, precond=factory(a, b)
                )
            )
        # sharded: the host-orchestrated mesh composition — pad the
        # operands to the mesh's even-shard dims and feed the one
        # compiled shard_map executable (built once here)
        from poisson_ellipse_tpu.parallel.mesh import make_mesh, padded_dims
        from poisson_ellipse_tpu.parallel.pcg_sharded import (
            AXIS_X,
            AXIS_Y,
            NamedSharding,
            P,
            build_sharded_solver,
        )

        mesh = mesh if mesh is not None else make_mesh()
        solver, _args = build_sharded_solver(problem, mesh, dtype, "host")
        g1p, g2p = padded_dims(problem.node_shape, mesh)
        sharding = NamedSharding(mesh, P(AXIS_X, AXIS_Y))

        def run(a, b, rhs):
            arrs = tuple(
                jax.device_put(
                    jnp.pad(v, ((0, g1p - v.shape[0]), (0, g2p - v.shape[1]))),
                    sharding,
                )
                for v in (a, b, rhs)
            )
            return solver(*arrs)

        return run

    def _run_normalised(self, a, b, rhs):
        """One engine solve at the quoted relative tolerance: solve
        ``A x = rhs/‖rhs‖`` at the primal δ and rescale by linearity —
        the tolerance contract (module docstring). Returns the rescaled
        solution grid."""
        nrm = jnp.sqrt(jnp.sum(rhs * rhs))
        safe = jnp.maximum(nrm, _NORM_TINY)
        res = self._runner(a.astype(self.dtype), b.astype(self.dtype),
                           (rhs / safe).astype(self.dtype))
        try:  # host-eager call: quote the solve; traced call: skip
            self.last.append({
                "iters": int(res.iters),
                "diff": float(res.diff),
                "converged": bool(res.converged),
            })
        except (jax.errors.TracerArrayConversionError,
                jax.errors.ConcretizationTypeError):
            pass
        return jnp.where(nrm > 0.0, res.w * nrm, jnp.zeros_like(res.w))

    # -- the custom_vjp / custom_linear_solve core ---------------------------

    def _build_core(self, adjoint: str):
        problem = self._solve_problem
        h1 = jnp.asarray(problem.h1, self.dtype)
        h2 = jnp.asarray(problem.h2, self.dtype)

        if adjoint == "linear":
            def core(a, b, rhs):
                def matvec(x):
                    return apply_a(x, a, b, h1, h2)

                def solve(_mv, rhs_in):
                    return self._run_normalised(a, b, rhs_in)

                return lax.custom_linear_solve(
                    matvec, rhs, solve, symmetric=True
                )

            return core

        @jax.custom_vjp
        def core(a, b, rhs):
            return self._run_normalised(a, b, rhs)

        def fwd(a, b, rhs):
            u = self._run_normalised(a, b, rhs)
            return u, (a, b, u)

        def bwd(res, ubar):
            a, b, u = res
            # the adjoint PCG: same operator (A symmetric), same engine,
            # same preconditioner, same quoted tolerance
            lam = core(a, b, ubar)
            # θ̄ chain: cotangents of (a, b) through A(a, b)·u at fixed
            # u, and of rhs directly — dL = λᵀ(db − dA·u)
            _, pull = jax.vjp(
                lambda aa, bb: apply_a(u, aa, bb, h1, h2), a, b
            )
            abar, bbar = pull(-lam)
            return (abar, bbar, lam)

        core.defvjp(fwd, bwd)
        return core

    # -- public surface ------------------------------------------------------

    def operands(self, params: dict | None):
        """(a, b, rhs) of the diff parameter pytree (traceable)."""
        return diff_assembly.operands_of(
            self.problem, self.template, params, samples=self.samples,
            dtype=self.dtype,
        )

    def solve(self, params: dict | None = None):
        """The converged solution grid u(params); ``jax.grad``-able in
        ``params`` (dict with any of ``"shape"``/``"source"``/
        ``"eps"`` — see ``diff.assembly.operands_of``)."""
        self.last = []
        a, b, rhs = self.operands(params)
        return self._core(a, b, rhs)

    def solve_operands(self, a, b, rhs):
        """The differentiable solve over already-assembled operands —
        the serving layer's contraction surface."""
        self.last = []
        return self._core(a, b, rhs)


def solve_implicit(problem: Problem, params: dict | None = None,
                   template=None, engine: str = "xla", dtype=None,
                   samples: int = diff_assembly.DEFAULT_SAMPLES, mesh=None,
                   adjoint: str = "vjp"):
    """One-shot form of :class:`ImplicitSolver`: the ``custom_vjp``-
    wrapped converged solve of ``params`` (build + solve; build once
    via the class when differentiating many times)."""
    return ImplicitSolver(
        problem, template=template, engine=engine, dtype=dtype,
        samples=samples, mesh=mesh, adjoint=adjoint,
    ).solve(params)
