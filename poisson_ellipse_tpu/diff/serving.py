"""The ``grad=True`` request kind: IFT adjoints as ordinary lanes.

A gradient request is TWO solves with the same operator — the primal
and the adjoint — so the scheduler runs it as two consecutive lane
occupancies of the continuous-batching machinery it already has:

  1. **primal** — the request's differentiably-assembled operands
     (``diff.assembly``) are pad-and-mask embedded into a bucket lane
     exactly like any other request; retire-and-refill applies.
  2. at the primal's converged chunk boundary the host evaluates the
     objective's value and cotangent ū = ∂L/∂u (one ``jax.value_and_
     grad`` of the functional — no solve), normalises it (the adjoint
     tolerance contract of ``diff.adjoint``), and re-queues the request
     as its **adjoint** stage: same (a, b), RHS = ū/‖ū‖ — an ordinary
     lane again, on whatever lane frees up next.
  3. at the adjoint's converged boundary the host contracts
     λ = ‖ū‖·(lane solution) against ∂(A u − b)/∂θ via ``jax.vjp`` of
     the traceable assembly, and the request terminally completes with
     ``(value, grad)``.

Durability: nothing about a half-done gradient is journaled — the
admit record IS the promise. A kill mid-primal or mid-adjoint replays
the request from scratch on restart; the recompute is deterministic
(fixed params → fixed operands → fixed solves), so the replayed
gradient is IDENTICAL — the chaos invariant of the grad kind. A lane
fault / retry resets the stage to primal the same way.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from poisson_ellipse_tpu.diff import assembly as diff_assembly
from poisson_ellipse_tpu.diff.objectives import objective_from_spec
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops.stencil import apply_a


class GradJob:
    """Host-side lifecycle state of one grad request (never journaled;
    rebuilt deterministically from the request spec on replay)."""

    def __init__(self, req, samples: int = diff_assembly.DEFAULT_SAMPLES):
        from poisson_ellipse_tpu.geom import sdf as geom_sdf

        self.problem: Problem = req.problem
        self.samples = samples
        shape = req.geometry_sdf()
        self.template = shape if shape is not None else geom_sdf.Ellipse()
        self.params = {
            "shape": jnp.asarray(geom_sdf.params_of(self.template))
        }
        self.objective = objective_from_spec(req.objective, self.problem)
        a, b, rhs = diff_assembly.operands_of(
            self.problem, self.template, self.params, samples=samples,
            dtype=diff_assembly.default_dtype(),
        )
        self.a = np.asarray(a)
        self.b = np.asarray(b)
        self.rhs = np.asarray(rhs)
        self.stage = "primal"
        self.u: np.ndarray | None = None
        self.value: float | None = None
        self.ubar_norm: float | None = None
        self.adj_rhs: np.ndarray | None = None
        self.primal_iters = 0
        self.adjoint_iters = 0

    def reset(self) -> None:
        """Back to the primal stage (a retried/faulted lane's carry is
        gone; the recompute is deterministic either way)."""
        self.stage = "primal"
        self.u = None
        self.value = None
        self.ubar_norm = None
        self.adj_rhs = None
        self.primal_iters = 0
        self.adjoint_iters = 0

    def embed(self, bucket: tuple[int, int], np_dtype):
        """The current stage's pad-and-mask bucket embedding — the ONE
        layout (``serve.scheduler.embed_operands``) every lane uses,
        with the stage's RHS (primal load / normalised cotangent)."""
        from poisson_ellipse_tpu.serve.scheduler import embed_operands

        rhs = self.rhs if self.stage == "primal" else self.adj_rhs
        return embed_operands(self.problem, bucket, np_dtype,
                              self.a, self.b, rhs)

    def absorb_primal(self, u: np.ndarray, iters: int) -> bool:
        """Record the converged primal; compute the objective value and
        its cotangent. Returns True when an adjoint solve is pending
        (False: zero cotangent — the gradient is exactly zero and the
        request can complete without a second solve)."""
        self.u = np.asarray(u, np.float64)
        self.primal_iters = iters
        value, ubar = jax.value_and_grad(
            lambda uu: self.objective(
                uu, jnp.asarray(self.a), jnp.asarray(self.b),
                jnp.asarray(self.rhs),
            )
        )(jnp.asarray(self.u))
        self.value = float(value)
        ubar = np.asarray(ubar, np.float64)
        nrm = float(np.sqrt(np.sum(ubar * ubar)))
        if nrm == 0.0:
            self.ubar_norm = 0.0
            return False
        self.ubar_norm = nrm
        self.adj_rhs = ubar / nrm
        self.stage = "adjoint"
        return True

    def zero_grad(self):
        """The gradient vector of a zero cotangent."""
        return np.zeros_like(np.asarray(self.params["shape"]))

    def finish(self, lam_unit: np.ndarray, iters: int) -> np.ndarray:
        """Contract the converged adjoint lane solution into the
        gradient w.r.t. the request's shape parameters: one ``jax.grad``
        of the Lagrangian L(u, θ) − λᵀ(A(θ)u − b(θ)) at FIXED (u, λ) —
        the λ-contraction of the IFT plus the objective's explicit
        θ-dependence (the Dirichlet energy reads A(θ) directly)."""
        self.adjoint_iters = iters
        dtype = diff_assembly.default_dtype()
        lam = jnp.asarray(
            np.asarray(lam_unit, np.float64) * self.ubar_norm, dtype
        )
        problem = self.problem
        h1 = jnp.asarray(problem.h1, dtype)
        h2 = jnp.asarray(problem.h2, dtype)
        u = jnp.asarray(self.u, dtype)

        def lagrangian(params):
            a2, b2, r2 = diff_assembly.operands_of(
                problem, self.template, params, samples=self.samples,
                dtype=dtype,
            )
            residual = apply_a(u, a2, b2, h1, h2) - r2
            return (
                self.objective(u, a2, b2, r2)
                - jnp.sum(lam * residual)
            )

        pbar = jax.grad(lagrangian)(self.params)
        return np.asarray(pbar["shape"], np.float64)


def solve_grad_direct(req, samples: int = diff_assembly.DEFAULT_SAMPLES,
                      dtype=None):
    """The un-laned fallback: value and gradient via ``diff.adjoint``'s
    implicit solver on the xla engine — the grad request's analogue of
    the scheduler's guarded single solve (the retry ladder's last
    rung). Deterministic, so a fallback completion quotes the same
    gradient a lane completion would (up to the engines' documented
    ±ulp reduction-order differences)."""
    from poisson_ellipse_tpu.diff.adjoint import ImplicitSolver
    from poisson_ellipse_tpu.geom import sdf as geom_sdf

    shape = req.geometry_sdf()
    template = shape if shape is not None else geom_sdf.Ellipse()
    solver = ImplicitSolver(req.problem, template, engine="xla",
                            dtype=dtype, samples=samples)
    objective = objective_from_spec(req.objective, req.problem)
    params = {"shape": jnp.asarray(geom_sdf.params_of(template))}

    def loss(p):
        a, b, rhs = solver.operands(p)
        u = solver.solve_operands(a, b, rhs)
        return objective(u, a, b, rhs)

    value, grad = jax.value_and_grad(loss)(params)
    iters = sum(e.get("iters", 0) for e in solver.last)
    return float(value), np.asarray(grad["shape"], np.float64), iters
