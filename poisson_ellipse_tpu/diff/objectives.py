"""Reference functionals of the converged solution, and their wire form.

Each functional is a plain differentiable ``jnp`` expression of the
solution grid (and, where stated, the operands), so ``jax.grad`` chains
it with :mod:`diff.adjoint`'s implicit solve — the cotangent ∂L/∂u it
produces is exactly the adjoint solve's right-hand side.

The JSON spec form (:func:`objective_from_spec`) is what a
``ServeRequest(grad=True)`` carries and the journal replays: a flat
dict with a ``kind`` and kind-specific fields, rebuilt into a closure
``fn(u, a, b, rhs) -> scalar`` at dispatch time.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops.reduction import grid_dot
from poisson_ellipse_tpu.ops.stencil import apply_a

OBJECTIVE_KINDS = ("energy", "flux", "l2", "mean")


def dirichlet_energy(problem: Problem, u, a, b):
    """½ ⟨u, A u⟩ (h1·h2-weighted) — the Dirichlet energy of the
    discrete solution; at convergence equal to ½ ⟨u, b⟩ (compliance/2),
    the canonical shape-optimisation objective."""
    h1 = jnp.asarray(problem.h1, u.dtype)
    h2 = jnp.asarray(problem.h2, u.dtype)
    return 0.5 * grid_dot(u, apply_a(u, a, b, h1, h2), h1, h2)


def boundary_flux(problem: Problem, u, a, b, weight):
    """−⟨A u, w⟩ (h1·h2-weighted) for a fixed window field ``w``: the
    adjoint-consistent evaluation of the flux of u through the support
    boundary of ``w`` (w ≡ 1 on a subregion measures the net flux out
    of it — integration by parts moves the normal derivative onto the
    window's edge)."""
    h1 = jnp.asarray(problem.h1, u.dtype)
    h2 = jnp.asarray(problem.h2, u.dtype)
    return -grid_dot(apply_a(u, a, b, h1, h2), weight, h1, h2)


def l2_misfit(problem: Problem, u, target, mask=None):
    """½ Σ mask·(u − target)² · h1·h2 — the data-misfit functional of
    the inverse problems (``mask=None`` weighs every node; iterates are
    zero off-interior so this is the interior misfit)."""
    d = u - target
    if mask is not None:
        d = d * mask
    return 0.5 * jnp.sum(d * d) * problem.h1 * problem.h2


def mean_value(problem: Problem, u):
    """Mean of u over the interior nodes — the cheapest smooth probe
    functional (serving's default-adjacent choice for drills)."""
    return jnp.mean(u[1:-1, 1:-1])


def _grid_of(value, field: str) -> np.ndarray:
    """A spec field as a finite float64 array, every malformation
    classified as ``ValueError`` — numpy raises ``TypeError`` for
    non-numeric nested payloads, which would escape the admission
    gate's classification otherwise."""
    try:
        arr = np.asarray(value, np.float64)
    except (TypeError, ValueError) as e:
        raise ValueError(f"objective {field!r} must be a numeric grid: {e}")
    if not np.all(np.isfinite(arr)):
        raise ValueError(f"objective {field!r} must be finite")
    return arr


def objective_from_spec(spec: dict | None, problem: Problem):
    """Build ``fn(u, a, b, rhs) -> scalar`` from a request's objective
    spec. ``None`` defaults to the Dirichlet energy. Malformed specs
    raise ``ValueError`` (the serving layer classifies at admission).

    Kinds:
      - ``{"kind": "energy"}`` — :func:`dirichlet_energy`
      - ``{"kind": "flux", "weight": [[...]]}`` — :func:`boundary_flux`
        (weight defaults to the all-ones interior window)
      - ``{"kind": "l2", "target": [[...]]}`` — :func:`l2_misfit`
      - ``{"kind": "mean"}`` — :func:`mean_value`
    """
    if spec is None:
        spec = {"kind": "energy"}
    if not isinstance(spec, dict):
        raise ValueError(f"objective spec must be a dict, got {type(spec)}")
    kind = spec.get("kind", "energy")
    if kind == "energy":
        return lambda u, a, b, rhs: dirichlet_energy(problem, u, a, b)
    if kind == "flux":
        w = spec.get("weight")
        if w is None:
            weight = jnp.zeros(problem.node_shape).at[1:-1, 1:-1].set(1.0)
        else:
            weight = jnp.asarray(_grid_of(w, "weight"))
            if weight.shape != problem.node_shape:
                raise ValueError(
                    f"flux weight shape {weight.shape} != node grid "
                    f"{problem.node_shape}"
                )
        return lambda u, a, b, rhs: boundary_flux(problem, u, a, b,
                                                  weight.astype(u.dtype))
    if kind == "l2":
        t = spec.get("target")
        if t is None:
            raise ValueError("objective kind 'l2' needs a 'target' grid")
        target = jnp.asarray(_grid_of(t, "target"))
        if target.shape != problem.node_shape:
            raise ValueError(
                f"l2 target shape {target.shape} != node grid "
                f"{problem.node_shape}"
            )
        return lambda u, a, b, rhs: l2_misfit(problem, u,
                                              target.astype(u.dtype))
    if kind == "mean":
        return lambda u, a, b, rhs: mean_value(problem, u)
    raise ValueError(
        f"unknown objective kind {kind!r} (choose from {OBJECTIVE_KINDS})"
    )
