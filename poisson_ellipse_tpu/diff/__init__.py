"""Differentiable solving: IFT adjoints through the converged PCG solve.

The stack's second mathematical capability: gradients of scalar
functionals of the converged solution ``u(θ)`` with respect to problem
parameters θ — SDF geometry parameters, the source field, the
penetration parameter ε — obtained NOT by backpropagating through
thousands of PCG iterations (unbounded memory, no reverse rule for
``lax.while_loop``) but via the implicit function theorem: at
convergence ``A(θ) u = b(θ)`` with A symmetric positive definite
(PAPER.md §0), so for a loss L(u)

    dL/dθ = −λᵀ (∂A/∂θ · u − ∂b/∂θ),    A λ = ∂L/∂u,

i.e. **one extra PCG solve with the exact same operator** — every
engine, preconditioner (``mg``), guard and sharded form is reused
as-is (Christianson's fixed-point adjoint; Blondel et al.'s modular
implicit differentiation, as in ``jaxopt``).

- :mod:`.assembly` — the θ→(a, b, rhs) assembly path made traceable
  end-to-end: a differentiable linear-interpolation face quadrature
  over any ``geom.sdf`` composition (the closed-form ellipse is
  differentiable today via ``models.ellipse.safe_sqrt``).
- :mod:`.adjoint` — :class:`~poisson_ellipse_tpu.diff.adjoint.
  ImplicitSolver` / :func:`~poisson_ellipse_tpu.diff.adjoint.
  solve_implicit`: the ``jax.custom_vjp`` wrapper whose forward is a
  registered engine's converged solve and whose backward runs the
  adjoint PCG (same operator, same ``precond`` hook, tolerance tied to
  the primal δ), plus a ``lax.custom_linear_solve`` mode for
  forward-over-reverse HVPs.
- :mod:`.objectives` — reference functionals (Dirichlet energy,
  boundary flux, L2 misfit) and their JSON spec form for serving.
- :mod:`.optimize` — gradient descent / L-BFGS over parameter vectors,
  shipping the two acceptance workloads: ellipse-recovers-itself
  inverse geometry and inverse-source recovery.
- :mod:`.serving` — the ``ServeRequest(grad=True)`` request kind: the
  primal and adjoint solves scheduled as ordinary chunked lanes
  (retire-and-refill applies), terminally completing with
  ``(value, grad)``; journal replay reproduces the identical gradient.
"""

from poisson_ellipse_tpu.diff.adjoint import (  # noqa: F401
    ImplicitSolver,
    solve_implicit,
)
from poisson_ellipse_tpu.diff.assembly import assemble_theta  # noqa: F401
