"""The θ→(a, b, rhs) assembly path, traceable end-to-end.

The production assembly (``ops.assembly``) is host-f64 by design: the
adaptive bisection quadrature (``geom.quadrature``) runs 60 bisection
steps of data-dependent host control flow per sign change — exact, but
opaque to ``jax.grad``. Differentiable solving needs the OTHER trade:
face fractions whose dependence on the SDF parameters is traced, so the
adjoint's contraction λᵀ ∂(A u − b)/∂θ can be evaluated by ``jax.vjp``
of this module.

The differentiable counterpart is the classic linear cut rule: sample
the level set at ``samples``+1 points along each face and, on each
subinterval, take the inside fraction of the LINEAR interpolant between
the endpoint values — for a crossing pair (φ_a < 0 ≤ φ_b) the crossing
sits at t* = φ_a/(φ_a − φ_b), a smooth function of the parameters
through the sampled values. Exact where φ is linear along the face
(half-planes, and any SDF locally), O((1/samples)²) quadrature error at
curved crossings, and differentiable almost everywhere — gradients flow
through t*, which is precisely the shape-derivative boundary term. The
``where`` guards follow the ``safe_sqrt`` discipline (both branches
finite) so no masked branch can poison a cotangent with NaN.

The RHS indicator ``1[φ < 0]`` stays a step function — its θ-derivative
is a boundary delta the grid cannot represent, and central finite
differences of THIS forward see the same (a.e. zero) derivative, so
adjoint and FD agree by construction. The gradient signal w.r.t.
geometry lives in the cut-face coefficients, where it belongs; the
source-field and ε dependencies are smooth and exact.

Values are deliberately quoted per this quadrature, not the bisection
one: a grad workload optimises THE SAME forward it differentiates. The
two agree to the linear rule's O((1/samples)²) on curved boundaries
(and exactly on straight ones).
"""

from __future__ import annotations

import jax.numpy as jnp

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops.assembly import _blend

# subintervals per cell face for the linear cut rule: 8 keeps the
# quadrature error (curved crossings only) at ~1e-3·h of a face while
# costing a (M+1, N+1, 9) broadcast evaluation — trivial next to one
# PCG iteration
DEFAULT_SAMPLES = 8


def default_dtype():
    """float64 when x64 is enabled — the diff/ accuracy contract (the
    rtol-1e-4 gradient acceptance is an f64 fact) — else float32: the
    serving degradation, resolved once instead of warning per cast."""
    import jax

    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


def _linear_inside_fraction(phi):
    """Inside fraction (in [0, 1]) of a face from its sampled level
    values ``phi`` (..., samples+1), by the linear cut rule per
    subinterval. Differentiable in ``phi`` wherever no sample sits
    exactly on the zero level set."""
    fa = phi[..., :-1]
    fb = phi[..., 1:]
    a_in = fa < 0.0
    b_in = fb < 0.0
    crossing = a_in != b_in
    # t* = fa/(fa − fb) on crossing subintervals; the double-where keeps
    # the untaken branch's denominator away from 0 so its (discarded)
    # cotangent stays finite — the safe_sqrt discipline
    denom = jnp.where(crossing, fa - fb, 1.0)
    tstar = jnp.where(crossing, fa / denom, 0.0)
    frac = jnp.where(
        a_in & b_in,
        1.0,
        jnp.where(crossing, jnp.where(a_in, tstar, 1.0 - tstar), 0.0),
    )
    return jnp.mean(frac, axis=-1)


def face_lengths_theta(problem: Problem, shape, samples: int = DEFAULT_SAMPLES,
                       dtype=None):
    """(la, lb) face-intersection lengths, (M+1, N+1), traced through
    the SDF — the differentiable twin of ``geom.quadrature.
    segment_lengths``. ``shape`` may carry traced parameters (built via
    ``geom.sdf.with_params``)."""
    if dtype is None:
        dtype = default_dtype()
    M, N = problem.M, problem.N
    h1 = jnp.asarray(problem.h1, dtype)
    h2 = jnp.asarray(problem.h2, dtype)
    x = problem.a1 + jnp.arange(M + 1, dtype=dtype) * h1
    y = problem.a2 + jnp.arange(N + 1, dtype=dtype) * h2
    t = jnp.linspace(0.0, 1.0, samples + 1, dtype=dtype)

    # vertical faces: x fixed at x_i − h1/2, y sweeps [y_j − h2/2, +h2/2]
    xv = (x - 0.5 * h1)[:, None, None]
    yv = (y - 0.5 * h2)[None, :, None] + h2 * t[None, None, :]
    la = _linear_inside_fraction(shape(xv, yv, jnp)) * h2
    # horizontal faces: y fixed at y_j − h2/2, x sweeps [x_i − h1/2, +h1/2]
    xh = (x - 0.5 * h1)[:, None, None] + h1 * t[None, None, :]
    yh = (y - 0.5 * h2)[None, :, None]
    lb = _linear_inside_fraction(shape(xh, yh, jnp)) * h1
    return la, lb


def assemble_theta(problem: Problem, shape, source=None, eps=None,
                   samples: int = DEFAULT_SAMPLES, dtype=None):
    """Differentiable (a, b, rhs) from a (possibly traced-parameter)
    SDF ``shape``, an optional traced ``source`` field, and an optional
    traced ``eps``.

    - ``shape``: a ``geom.sdf`` tree; parameters may be tracers
      (``with_params``). The coefficient blend law is the production
      one (``ops.assembly._blend``) over the linear-cut face lengths.
    - ``source``: per-node source values, shape (M+1, N+1) or a scalar;
      the RHS is ``source · 1[inside ∧ interior]`` (``None`` keeps the
      reference's constant ``problem.f_val``).
    - ``eps``: the fictitious-domain penetration parameter as a traced
      scalar (``None`` keeps ``problem.eps_value``).

    Same masking contract as ``ops.assembly.assemble``: rows/cols 0 of
    a, b are zero, the RHS is interior-only.
    """
    if dtype is None:
        dtype = default_dtype()
    M, N = problem.M, problem.N
    h1 = jnp.asarray(problem.h1, dtype)
    h2 = jnp.asarray(problem.h2, dtype)
    if eps is None:
        eps = problem.eps_value
    eps = jnp.asarray(eps, dtype)

    la, lb = face_lengths_theta(problem, shape, samples=samples, dtype=dtype)
    a = _blend(la, h2, eps, jnp)
    b = _blend(lb, h1, eps, jnp)

    gi = jnp.arange(M + 1)
    gj = jnp.arange(N + 1)
    valid = (
        ((gi >= 1) & (gi <= M))[:, None] & ((gj >= 1) & (gj <= N))[None, :]
    )
    zero = jnp.asarray(0.0, dtype)
    a = jnp.where(valid, a, zero)
    b = jnp.where(valid, b, zero)

    x = problem.a1 + jnp.arange(M + 1, dtype=dtype) * h1
    y = problem.a2 + jnp.arange(N + 1, dtype=dtype) * h2
    inside = shape(x[:, None], y[None, :], jnp) < 0.0
    interior = (
        ((gi >= 1) & (gi <= M - 1))[:, None]
        & ((gj >= 1) & (gj <= N - 1))[None, :]
    )
    if source is None:
        source = jnp.asarray(problem.f_val, dtype)
    source = jnp.asarray(source, dtype)
    rhs = jnp.where(inside & interior, source, zero)
    # a scalar source broadcasts; a field source must already be the
    # node grid — broadcast_to makes either land on (M+1, N+1)
    rhs = jnp.broadcast_to(rhs, (M + 1, N + 1))
    return a, b, rhs


def operands_of(problem: Problem, template, params: dict | None,
                samples: int = DEFAULT_SAMPLES, dtype=None):
    """(a, b, rhs) from the diff parameter pytree ``params``.

    ``params`` is a dict with any subset of:

    - ``"shape"``  — parameter vector for ``template`` (``geom.sdf.
      with_params`` order); absent means the template's own values.
    - ``"source"`` — per-node source field (or scalar).
    - ``"eps"``    — the penetration parameter.

    Differentiating through this function w.r.t. ``params`` is exactly
    the ∂(A u − b)/∂θ contraction surface of ``diff.adjoint``.
    """
    from poisson_ellipse_tpu.geom import sdf as geom_sdf

    params = params or {}
    shape = template
    if params.get("shape") is not None:
        shape = geom_sdf.with_params(template, params["shape"])
    return assemble_theta(
        problem, shape, source=params.get("source"),
        eps=params.get("eps"), samples=samples, dtype=dtype,
    )
