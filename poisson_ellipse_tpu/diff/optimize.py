"""First-order optimisation over solve parameters + the two acceptance
workloads.

The optimisers are deliberately small and dependency-free (the
container has no scipy contract): a backtracking gradient descent and
a two-loop-recursion L-BFGS with Armijo line search, both operating on
flat float64 numpy vectors via ``jax.flatten_util.ravel_pytree`` —
every iterate is a concrete host vector, so a step can be projected
(radii kept positive) and re-serialised to a valid JSON spec
(``geom.sdf.with_params`` → ``to_spec``) without drift.

Workloads (both seeded-deterministic; the acceptance criteria of
ROADMAP item 1):

- :func:`recover_ellipse` — ellipse-recovers-itself inverse geometry:
  observations are the converged solution of a reference ellipse; a
  randomly perturbed parameter vector is optimised under the L2 misfit
  until the true parameters are recovered (≤1e-3 relative).
- :func:`recover_source` — inverse-source recovery: the source field
  (one value per interior node) is recovered from the solution it
  produced, the misfit dropping ≥100×.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

from poisson_ellipse_tpu.diff.adjoint import ImplicitSolver
from poisson_ellipse_tpu.diff.objectives import l2_misfit
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs import trace as obs_trace

# Armijo backtracking: accept f(x + t·d) ≤ f(x) + C1·t·⟨g, d⟩, halving
# t at most BACKTRACK_MAX times before declaring the direction dead
_C1 = 1e-4
_BACKTRACK_MAX = 30


@dataclasses.dataclass
class OptResult:
    """One optimisation run's outcome (vectors are float64 numpy)."""

    x: np.ndarray
    value: float
    n_iters: int
    n_evals: int
    converged: bool
    history: list


def _minimize(value_and_grad: Callable, x0: np.ndarray, steps: int,
              method: str = "lbfgs", project: Optional[Callable] = None,
              gtol: float = 1e-10, memory: int = 10) -> OptResult:
    """Minimise a flat-vector objective by L-BFGS (two-loop recursion)
    or projected gradient descent, Armijo-backtracked either way."""
    x = np.asarray(x0, np.float64).copy()
    if project is not None:
        x = project(x)
    evals = [0]

    def vg(z):
        evals[0] += 1
        v, g = value_and_grad(z)
        return float(v), np.asarray(g, np.float64)

    f, g = vg(x)
    history = [f]
    s_list: list[np.ndarray] = []
    y_list: list[np.ndarray] = []
    converged = False
    it = 0
    for it in range(1, steps + 1):
        if np.linalg.norm(g) <= gtol:
            converged = True
            break
        if method == "lbfgs" and s_list:
            d = _two_loop(g, s_list, y_list)
        else:
            # first step / plain GD: scale so the initial trial is O(1)
            # in parameter space, not O(‖g‖)
            d = -g / max(np.linalg.norm(g), 1e-30)
        gd = float(g @ d)
        if gd >= 0.0:  # stale curvature pairs: reset to steepest descent
            s_list.clear()
            y_list.clear()
            d = -g / max(np.linalg.norm(g), 1e-30)
            gd = float(g @ d)
        t = 1.0
        f_new, g_new, x_new = f, g, x
        ok = False
        for _ in range(_BACKTRACK_MAX):
            x_try = x + t * d
            if project is not None:
                x_try = project(x_try)
            f_try, g_try = vg(x_try)
            if np.isfinite(f_try) and f_try <= f + _C1 * t * gd:
                f_new, g_new, x_new = f_try, g_try, x_try
                ok = True
                break
            t *= 0.5
        if not ok:
            converged = np.linalg.norm(g) <= max(gtol, 1e-8 * abs(f) + 1e-12)
            break
        if method == "lbfgs":
            s = x_new - x
            y = g_new - g
            if float(s @ y) > 1e-14 * np.linalg.norm(s) * np.linalg.norm(y):
                s_list.append(s)
                y_list.append(y)
                if len(s_list) > memory:
                    s_list.pop(0)
                    y_list.pop(0)
        x, f, g = x_new, f_new, g_new
        history.append(f)
    return OptResult(x=x, value=f, n_iters=it, n_evals=evals[0],
                     converged=converged, history=history)


def _two_loop(g: np.ndarray, s_list, y_list) -> np.ndarray:
    """The L-BFGS two-loop recursion: H·(−g) from the stored (s, y)."""
    q = g.copy()
    alphas = []
    for s, y in zip(reversed(s_list), reversed(y_list)):
        rho = 1.0 / float(y @ s)
        a = rho * float(s @ q)
        alphas.append((a, rho, s, y))
        q -= a * y
    s, y = s_list[-1], y_list[-1]
    q *= float(s @ y) / float(y @ y)
    for a, rho, s, y in reversed(alphas):
        beta = rho * float(y @ q)
        q += (a - beta) * s
    return -q


def minimize_params(loss_fn: Callable, p0: dict, steps: int = 50,
                    method: str = "lbfgs",
                    project: Optional[Callable] = None) -> OptResult:
    """Minimise ``loss_fn(params)`` (params the diff pytree) from
    ``p0``: ravel, optimise the flat vector, return the
    :class:`OptResult` (``res.x`` in ``ravel_pytree`` order).
    ``project`` acts on the raveled vector (e.g. positivity of
    radii)."""
    flat0, unravel = ravel_pytree(jax.tree.map(jnp.asarray, p0))
    vg = jax.value_and_grad(lambda z: loss_fn(unravel(z)))

    def value_and_grad(z):
        v, g = vg(jnp.asarray(z))
        return v, ravel_pytree(g)[0]

    return _minimize(value_and_grad, np.asarray(flat0), steps=steps,
                     method=method, project=project)


# --------------------------------------------------------------------------
# acceptance workloads
# --------------------------------------------------------------------------


def recover_ellipse(grid: tuple[int, int] = (24, 24), engine: str = "xla",
                    seed: int = 0, perturb: float = 0.04, steps: int = 60,
                    delta: float = 1e-11, samples: int = 8) -> dict:
    """Ellipse-recovers-itself: perturbed (cx, cy, rx, ry) optimised
    back to the reference ellipse under the L2 misfit of the solution.

    Returns a JSON-able report: the true/initial/recovered parameter
    vectors, relative recovery error (acceptance ≤ 1e-3), misfit drop,
    and the recovered shape re-serialised as a valid JSON spec (the
    ``params_of``/``with_params`` round trip under load).
    """
    from poisson_ellipse_tpu.geom import sdf as geom_sdf

    problem = Problem(M=grid[0], N=grid[1], delta=delta)
    template = geom_sdf.Ellipse()
    solver = ImplicitSolver(problem, template, engine=engine,
                            samples=samples)
    true = geom_sdf.params_of(template)
    target = np.asarray(solver.solve({"shape": jnp.asarray(true)}))

    rng = np.random.default_rng(seed)
    scale = np.maximum(np.abs(true), 0.25)
    x0 = true + perturb * scale * rng.uniform(-1.0, 1.0, size=true.shape)

    def loss(params):
        u = solver.solve(params)
        return l2_misfit(problem, u, jnp.asarray(target))

    def project(z):
        z = z.copy()
        z[2:] = np.maximum(z[2:], 0.05)  # radii stay positive
        return z

    res = minimize_params(loss, {"shape": x0}, steps=steps,
                          method="lbfgs", project=project)
    rel_err = float(np.max(np.abs(res.x - true) / scale))
    spec = geom_sdf.to_spec(geom_sdf.with_params(template, res.x))
    report = {
        "workload": "recover-ellipse",
        "grid": list(grid),
        "engine": engine,
        "seed": seed,
        "true": true.tolist(),
        "initial": x0.tolist(),
        "recovered": res.x.tolist(),
        "recovered_spec": spec,
        "rel_err": rel_err,
        "misfit_initial": res.history[0],
        "misfit_final": res.value,
        "n_iters": res.n_iters,
        "n_evals": res.n_evals,
        "ok": bool(rel_err <= 1e-3),
    }
    obs_trace.event("diff:recover-ellipse", **{
        k: report[k] for k in ("grid", "engine", "seed", "rel_err", "ok")
    })
    return report


def recover_source(grid: tuple[int, int] = (16, 16), engine: str = "xla",
                   seed: int = 0, steps: int = 80,
                   delta: float = 1e-11, samples: int = 8) -> dict:
    """Inverse-source recovery: the per-node source field behind an
    observed solution, recovered from a flat initial guess; acceptance
    is the L2 misfit dropping ≥ 100×."""
    from poisson_ellipse_tpu.geom import sdf as geom_sdf

    problem = Problem(M=grid[0], N=grid[1], delta=delta)
    template = geom_sdf.Ellipse()
    solver = ImplicitSolver(problem, template, engine=engine,
                            samples=samples)

    # the hidden truth: a smooth off-centre blob over the constant load
    rng = np.random.default_rng(seed)
    cx, cy = rng.uniform(-0.3, 0.3), rng.uniform(-0.15, 0.15)
    x = problem.a1 + np.arange(problem.M + 1) * problem.h1
    y = problem.a2 + np.arange(problem.N + 1) * problem.h2
    xx, yy = x[:, None], y[None, :]
    s_true = 1.0 + 2.0 * np.exp(-(((xx - cx) / 0.3) ** 2
                                  + ((yy - cy) / 0.2) ** 2))
    target = np.asarray(solver.solve({"source": jnp.asarray(s_true)}))

    def loss(params):
        u = solver.solve(params)
        return l2_misfit(problem, u, jnp.asarray(target))

    s0 = np.ones_like(s_true)
    res = minimize_params(loss, {"source": s0}, steps=steps,
                          method="lbfgs")
    drop = float(res.history[0] / max(res.value, 1e-300))
    report = {
        "workload": "recover-source",
        "grid": list(grid),
        "engine": engine,
        "seed": seed,
        "misfit_initial": res.history[0],
        "misfit_final": res.value,
        "misfit_drop": drop,
        "n_iters": res.n_iters,
        "n_evals": res.n_evals,
        "ok": bool(drop >= 100.0),
    }
    obs_trace.event("diff:recover-source", **{
        k: report[k] for k in ("grid", "engine", "seed", "misfit_drop", "ok")
    })
    return report
