"""CLI: ``python -m poisson_ellipse_tpu.lint [paths ...]``.

Exit status: 0 clean, 1 findings, 2 unparseable input or bad usage —
the same contract as the pytest gate, so CI needs no extra wiring.

Beyond the flake8-style text report: ``--format sarif`` emits the same
SARIF 2.1.0 subset as the contract matrix (one writer,
``analysis.sarif``); ``--baseline FILE`` adopts existing debt then
ratchets it down; ``--audit-suppressions`` reports stale
``# tpulint: disable`` annotations instead of lint findings.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys

from poisson_ellipse_tpu.lint import (
    AUDIT_CODE,
    RULES,
    apply_baseline,
    audit_paths,
    lint_paths,
    load_config,
)
from poisson_ellipse_tpu.lint.report import exit_code, render_report


def _codes(value: str) -> frozenset[str]:
    codes = frozenset(c.strip().upper() for c in value.split(",") if c.strip())
    unknown = codes - RULES.keys()
    if unknown:
        # a typo'd --select must not turn the gate into a silent no-op
        raise argparse.ArgumentTypeError(
            f"unknown rule code(s): {', '.join(sorted(unknown))} "
            f"(known: {', '.join(sorted(RULES))})"
        )
    return codes


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m poisson_ellipse_tpu.lint",
        description="TPU-aware static analysis for the kernel zoo "
        "(rules TPU001-TPU020; suppress with `# tpulint: disable=CODE`).",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="files or directories (default: [tool.tpulint] paths)",
    )
    parser.add_argument(
        "--select", type=_codes, default=None,
        help="comma-separated codes to run exclusively (e.g. TPU002,TPU005)",
    )
    parser.add_argument(
        "--ignore", type=_codes, default=None,
        help="comma-separated codes to skip",
    )
    parser.add_argument(
        "--statistics", action="store_true",
        help="append a per-code finding tally",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule table and exit",
    )
    parser.add_argument(
        "--format", choices=("text", "sarif"), default="text",
        help="report format (sarif: the same 2.1.0 subset the contract "
        "matrix emits)",
    )
    parser.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="accept-then-ratchet: a missing FILE swallows today's "
        "findings and is written; an existing one silences accepted "
        "keys, fails anything new, and sheds fixed entries once clean",
    )
    parser.add_argument(
        "--audit-suppressions", action="store_true",
        help="report stale `# tpulint: disable` annotations "
        f"({AUDIT_CODE}) instead of lint findings",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for code, rule in sorted(RULES.items()):
            print(f"{code} {rule.name:18s} {rule.summary}")
        return 0

    config = load_config()
    if args.select is not None:
        config = dataclasses.replace(config, select=args.select)
    if args.ignore is not None:
        config = dataclasses.replace(
            config, ignore=config.ignore | args.ignore
        )
    paths = args.paths or list(config.paths)
    runner = audit_paths if args.audit_suppressions else lint_paths
    findings, errors = runner(paths, config)
    for err in errors:
        print(err.render(), file=sys.stderr)
    note = None
    if args.baseline:
        findings, note = apply_baseline(args.baseline, findings, errors)
    if args.format == "sarif":
        from poisson_ellipse_tpu.analysis.sarif import findings_to_sarif

        rules = {code: r.summary for code, r in sorted(RULES.items())}
        if args.audit_suppressions:
            rules = {AUDIT_CODE: "unused-suppression: a disable "
                     "annotation that suppresses nothing"}
        print(json.dumps(
            findings_to_sarif(findings, rules=rules), indent=2,
            sort_keys=True,
        ))
    elif findings:
        print(render_report(findings, statistics=args.statistics))
    rc = exit_code(findings, errors)
    if rc == 0 and args.format != "sarif":
        what = (
            "0 stale suppressions" if args.audit_suppressions
            else f"{len(list(RULES))} rules, 0 findings"
        )
        print(f"tpulint: {what} — clean")
    if note:
        print(note, file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
