"""CLI: ``python -m poisson_ellipse_tpu.lint [paths ...]``.

Exit status: 0 clean, 1 findings, 2 unparseable input or bad usage —
the same contract as the pytest gate, so CI needs no extra wiring.
"""

from __future__ import annotations

import argparse
import dataclasses
import sys

from poisson_ellipse_tpu.lint import (
    RULES,
    lint_paths,
    load_config,
)
from poisson_ellipse_tpu.lint.report import exit_code, render_report


def _codes(value: str) -> frozenset[str]:
    codes = frozenset(c.strip().upper() for c in value.split(",") if c.strip())
    unknown = codes - RULES.keys()
    if unknown:
        # a typo'd --select must not turn the gate into a silent no-op
        raise argparse.ArgumentTypeError(
            f"unknown rule code(s): {', '.join(sorted(unknown))} "
            f"(known: {', '.join(sorted(RULES))})"
        )
    return codes


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m poisson_ellipse_tpu.lint",
        description="TPU-aware static analysis for the kernel zoo "
        "(rules TPU001-TPU013; suppress with `# tpulint: disable=CODE`).",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="files or directories (default: [tool.tpulint] paths)",
    )
    parser.add_argument(
        "--select", type=_codes, default=None,
        help="comma-separated codes to run exclusively (e.g. TPU002,TPU005)",
    )
    parser.add_argument(
        "--ignore", type=_codes, default=None,
        help="comma-separated codes to skip",
    )
    parser.add_argument(
        "--statistics", action="store_true",
        help="append a per-code finding tally",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule table and exit",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for code, rule in sorted(RULES.items()):
            print(f"{code} {rule.name:18s} {rule.summary}")
        return 0

    config = load_config()
    if args.select is not None:
        config = dataclasses.replace(config, select=args.select)
    if args.ignore is not None:
        config = dataclasses.replace(
            config, ignore=config.ignore | args.ignore
        )
    paths = args.paths or list(config.paths)
    findings, errors = lint_paths(paths, config)
    for err in errors:
        print(err.render(), file=sys.stderr)
    if findings:
        print(render_report(findings, statistics=args.statistics))
    rc = exit_code(findings, errors)
    if rc == 0:
        print(f"tpulint: {len(list(RULES))} rules, 0 findings — clean")
    return rc


if __name__ == "__main__":
    sys.exit(main())
