"""AST context shared by the tpulint rules.

One :class:`Module` per source file carries everything a rule needs and
nothing JAX-runtime: import-alias resolution (``jnp.float64`` and
``jax.numpy.float64`` are the same symbol to a rule), suppression
comments, parent links, the set of *traced functions* (jit-decorated
defs, ``jax.jit(...)`` call sites, ``lax.while_loop``/``scan``/
``fori_loop``/``cond`` bodies), and a shallow traced-value taint over a
function's parameters. Everything is computed from ``ast`` alone — the
linter never imports the code it analyses, so it runs (and fails) the
same with or without an accelerator attached.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from typing import Iterator, Optional

# Attribute reads that produce Python-static facts even on a traced
# array; a branch on `x.ndim` is trace-safe, a branch on `x` is not.
STATIC_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "itemsize"})

# Builtins whose call result is static regardless of argument taint.
STATIC_CALLS = frozenset({"len", "isinstance", "type", "hasattr", "getattr"})

_SUPPRESS_RE = re.compile(r"#\s*tpulint:\s*disable=([A-Za-z0-9_,\s]+)")

# lax loop/control constructs and which of their arguments are traced
# callables (positional index); keyword names accepted as well.
TRACED_CALLABLE_ARGS = {
    "jax.lax.while_loop": ((0, "cond_fun"), (1, "body_fun")),
    "jax.lax.fori_loop": ((2, "body_fun"),),
    "jax.lax.scan": ((0, "f"),),
    "jax.lax.cond": ((1, "true_fun"), (2, "false_fun")),
    "jax.lax.switch": (),  # branches arrive as a list; handled specially
}


def _iter_suppression_comments(
    source: str,
) -> Iterator[tuple[int, bool, frozenset[str]]]:
    """Yield ``(lineno, standalone, codes)`` for every real
    ``# tpulint: disable=`` COMMENT token.

    Tokenising (not line-scanning) means docstrings, help strings, and
    test fixtures that merely *mention* the annotation syntax are never
    treated as live suppressions — and never audited as stale ones.
    """
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            codes = frozenset(
                c.strip().upper()
                for c in m.group(1).split(",")
                if c.strip()
            )
            if not codes:
                continue
            standalone = tok.line[: tok.start[1]].strip() == ""
            yield tok.start[0], standalone, codes
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # the caller already ast-parsed this source, so a tokenizer
        # failure is a stdlib edge case: no comments beats a crash
        return


def _parse_suppressions(source: str) -> dict[int, frozenset[str]]:
    """line number -> codes disabled on that line.

    A suppression comment covers its own line; when the line holds
    nothing but the comment, it also covers the next line (so long
    expressions can carry the annotation above rather than trailing).
    ``disable=all`` disables every rule.
    """
    out: dict[int, set[str]] = {}
    for lineno, standalone, codes in _iter_suppression_comments(source):
        out.setdefault(lineno, set()).update(codes)
        if standalone:  # standalone: covers the line below too
            out.setdefault(lineno + 1, set()).update(codes)
    return {k: frozenset(v) for k, v in out.items()}


def _import_aliases(tree: ast.AST) -> dict[str, str]:
    """local name -> canonical dotted prefix (``jnp`` -> ``jax.numpy``)."""
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


@dataclasses.dataclass
class TracedFn:
    """A function whose body is traced by JAX (so Python control flow on
    its array arguments is a staging hazard, not ordinary code)."""

    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    kind: str  # "jit-def" | "jit-call" | "loop-body"
    static_params: frozenset[str] = frozenset()

    @property
    def params(self) -> list[str]:
        a = self.node.args
        names = [p.arg for p in getattr(a, "posonlyargs", [])]
        names += [p.arg for p in a.args]
        names += [p.arg for p in a.kwonlyargs]
        return [n for n in names if n not in self.static_params]


class Module:
    """Parsed source + the derived facts every rule reads."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source)
        self.suppressions = _parse_suppressions(source)
        self.aliases = _import_aliases(self.tree)
        self._attach_parents()
        # every def in the file, by (possibly shadowed) name — shallow
        # same-module call resolution for the reachability rules
        self.functions: dict[str, ast.FunctionDef] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.setdefault(node.name, node)
        self.traced_fns = list(self._find_traced_fns())

    # -- structure ----------------------------------------------------------

    def _attach_parents(self) -> None:
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                child._tpulint_parent = parent  # type: ignore[attr-defined]

    @staticmethod
    def parent(node: ast.AST) -> Optional[ast.AST]:
        return getattr(node, "_tpulint_parent", None)

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return anc
        return None

    def nearest_statement(self, node: ast.AST) -> Optional[ast.stmt]:
        cur: Optional[ast.AST] = node
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = self.parent(cur)
        return cur

    # -- names --------------------------------------------------------------

    def qualname(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted name of an expression, through import aliases
        (``jnp.zeros`` -> ``jax.numpy.zeros``); None when not a name."""
        if isinstance(node, ast.Name):
            return self.aliases.get(node.id, node.id)
        if isinstance(node, ast.Attribute):
            base = self.qualname(node.value)
            return f"{base}.{node.attr}" if base else None
        return None

    def suppressed(self, line: int, code: str) -> bool:
        codes = self.suppressions.get(line, frozenset())
        return code.upper() in codes or "ALL" in codes

    # -- jit discovery ------------------------------------------------------

    def is_jit_name(self, node: ast.AST) -> bool:
        return self.qualname(node) in ("jax.jit", "jax.pjit", "jit")

    def jit_construction(self, call: ast.Call) -> Optional[ast.AST]:
        """If ``call`` constructs a jitted callable, the wrapped callee
        expression; otherwise None. Handles ``jax.jit(f)`` and
        ``functools.partial(jax.jit, ...)(f)``-free ``partial(jax.jit,
        f)`` spellings; a ``jax.shard_map``/``shard_map`` wrapper is
        looked through (the jit still closes over its callable)."""
        fn: Optional[ast.AST] = None
        if self.is_jit_name(call.func) and call.args:
            fn = call.args[0]
        elif (
            self.qualname(call.func) in ("functools.partial", "partial")
            and len(call.args) >= 2
            and self.is_jit_name(call.args[0])
        ):
            fn = call.args[1]
        if fn is None:
            return None
        if isinstance(fn, ast.Call):
            q = self.qualname(fn.func) or ""
            if q.endswith("shard_map") and (fn.args or fn.keywords):
                inner = fn.args[0] if fn.args else fn.keywords[0].value
                return inner
        return fn

    def resolve_callable(self, node: ast.AST) -> Optional[ast.AST]:
        """Lambda/FunctionDef behind a callable expression, or None."""
        if isinstance(node, ast.Lambda):
            return node
        if isinstance(node, ast.Name):
            return self.functions.get(node.id)
        return None

    @staticmethod
    def _literal_int_tuple(node: ast.AST) -> Optional[tuple[int, ...]]:
        if isinstance(node, (ast.Tuple, ast.List)) and all(
            isinstance(e, ast.Constant) and isinstance(e.value, int)
            for e in node.elts
        ):
            return tuple(e.value for e in node.elts)
        return None

    def _jit_static_params(self, call_or_dec: ast.AST, fn: ast.AST) -> frozenset[str]:
        """Parameter names made static by literal static_argnums/names."""
        if not isinstance(call_or_dec, ast.Call):
            return frozenset()
        args = fn.args if hasattr(fn, "args") else None
        if args is None:
            return frozenset()
        pos = [p.arg for p in getattr(args, "posonlyargs", [])] + [
            p.arg for p in args.args
        ]
        static: set[str] = set()
        for kw in call_or_dec.keywords:
            if kw.arg == "static_argnums":
                nums = self._literal_int_tuple(kw.value)
                if nums is None and isinstance(kw.value, ast.Constant):
                    nums = (kw.value.value,) if isinstance(kw.value.value, int) else None
                for i in nums or ():
                    if 0 <= i < len(pos):
                        static.add(pos[i])
            elif kw.arg == "static_argnames":
                vals = kw.value
                elts = vals.elts if isinstance(vals, (ast.Tuple, ast.List)) else [vals]
                for e in elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, str):
                        static.add(e.value)
        return frozenset(static)

    def _find_traced_fns(self) -> Iterator[TracedFn]:
        seen: set[int] = set()

        def emit(node, kind, static=frozenset()):
            if node is not None and id(node) not in seen and hasattr(node, "args"):
                seen.add(id(node))
                yield TracedFn(node, kind, static)

        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    if self.is_jit_name(target) or (
                        isinstance(dec, ast.Call)
                        and self.qualname(dec.func)
                        in ("functools.partial", "partial")
                        and dec.args
                        and self.is_jit_name(dec.args[0])
                    ):
                        yield from emit(
                            node, "jit-def", self._jit_static_params(dec, node)
                        )
            elif isinstance(node, ast.Call):
                wrapped = self.jit_construction(node)
                if wrapped is not None:
                    fn = self.resolve_callable(wrapped)
                    if fn is not None:
                        yield from emit(
                            fn, "jit-call", self._jit_static_params(node, fn)
                        )
                    continue
                q = self.qualname(node.func)
                spec = TRACED_CALLABLE_ARGS.get(q or "")
                if spec is None:
                    continue
                if q == "jax.lax.switch":
                    branches = node.args[1] if len(node.args) > 1 else None
                    elts = (
                        branches.elts
                        if isinstance(branches, (ast.Tuple, ast.List))
                        else []
                    )
                    for e in elts:
                        yield from emit(self.resolve_callable(e), "loop-body")
                    continue
                for idx, kwname in spec:
                    arg = None
                    if idx < len(node.args):
                        arg = node.args[idx]
                    else:
                        for kw in node.keywords:
                            if kw.arg == kwname:
                                arg = kw.value
                    fn = self.resolve_callable(arg) if arg is not None else None
                    yield from emit(fn, "loop-body")

    # -- taint --------------------------------------------------------------

    def expr_mentions(self, node: ast.AST, names: set[str]) -> bool:
        """Does ``node`` read any name in ``names`` in a way that yields a
        traced value? Reads of static facts (``x.shape``, ``len(x)``,
        ``isinstance(x, ...)``) do not count."""
        for sub in ast.walk(node):
            if not (isinstance(sub, ast.Name) and sub.id in names):
                continue
            parent = self.parent(sub)
            if (
                isinstance(parent, ast.Attribute)
                and parent.value is sub
                and parent.attr in STATIC_ATTRS
            ):
                continue
            if (
                isinstance(parent, ast.Call)
                and sub in parent.args
                and isinstance(parent.func, ast.Name)
                and parent.func.id in STATIC_CALLS
            ):
                continue
            return True
        return False

    def tainted_names(self, fn: TracedFn) -> set[str]:
        """Parameters of ``fn`` plus names derived from them by simple
        assignment/tuple-unpacking, in statement order (shallow forward
        taint — no fixpoint; loops rarely launder a trace)."""
        tainted: set[str] = set(fn.params)
        body = fn.node.body
        stmts = body if isinstance(body, list) else []
        for stmt in stmts:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Assign) and self.expr_mentions(
                    node.value, tainted
                ):
                    for target in node.targets:
                        for leaf in ast.walk(target):
                            if isinstance(leaf, ast.Name):
                                tainted.add(leaf.id)
                elif isinstance(node, ast.AugAssign) and self.expr_mentions(
                    node.value, tainted
                ):
                    if isinstance(node.target, ast.Name):
                        tainted.add(node.target.id)
        return tainted
