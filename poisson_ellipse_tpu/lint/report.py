"""Finding container and report rendering for ``tpulint``.

One finding = one (path, line, col, code, message). Rendering follows the
``flake8`` convention (``path:line:col: CODE message``) so editors and CI
annotators that already parse that shape pick tpulint up for free.
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from typing import Iterable, Sequence


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """A single rule violation at a source location."""

    path: str
    line: int
    col: int
    code: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}"


@dataclasses.dataclass(frozen=True)
class ParseError:
    """A file the linter could not parse (reported, exit code 2)."""

    path: str
    message: str

    def render(self) -> str:
        return f"{self.path}: cannot parse: {self.message}"


def render_report(
    findings: Sequence[Finding],
    *,
    statistics: bool = False,
) -> str:
    """The human-facing report: one line per finding, sorted by location,
    plus an optional per-code tally (``--statistics``)."""
    lines = [f.render() for f in sorted(findings)]
    if statistics and findings:
        lines.append("")
        for code, n in sorted(Counter(f.code for f in findings).items()):
            lines.append(f"{code}: {n}")
    return "\n".join(lines)


def exit_code(findings: Iterable[Finding], errors: Iterable[ParseError]) -> int:
    """0 clean, 1 findings, 2 unparseable input (trumps findings)."""
    if list(errors):
        return 2
    return 1 if list(findings) else 0
