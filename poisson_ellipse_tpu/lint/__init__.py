"""tpulint — a JAX/Pallas-aware static-analysis pass for the kernel zoo.

The repo carries seven PCG engine variants whose failure modes (silent
dtype drift, traced-value branches, host syncs in hot loops, per-call
recompilation, VMEM-overflowing Pallas tiles) the reference project
caught by hand across five rewrites. tpulint catches them mechanically:

    python -m poisson_ellipse_tpu.lint              # paths from pyproject
    python -m poisson_ellipse_tpu.lint poisson_ellipse_tpu/ops --statistics

Rules are TPU001–TPU020 (see :mod:`.rules`); any finding can be waived
in place with a trailing or preceding-line comment::

    x = jnp.zeros(n, jnp.float64)  # tpulint: disable=TPU001

Configuration lives in ``pyproject.toml`` under ``[tool.tpulint]`` and
is shared by this CLI and the pytest gate (``tests/test_lint_clean.py``),
so "lints clean" means the same thing on a laptop and in CI.

Public API: :func:`load_config`, :func:`lint_paths`, :func:`lint_file`,
:func:`lint_source` (the test harness entry), :data:`RULES`, plus the
hygiene surfaces: :func:`audit_suppressions`/:func:`audit_paths` (stale
``disable`` annotations) and :func:`apply_baseline` (accept-then-ratchet
``--baseline`` files).
"""

from __future__ import annotations

import dataclasses
import fnmatch
import json
import os
import re
from typing import Iterable, Optional

from poisson_ellipse_tpu.lint.report import Finding, ParseError
from poisson_ellipse_tpu.lint.rules import RULES, LintConfig
from poisson_ellipse_tpu.lint.visitor import (
    Module,
    _iter_suppression_comments,
)

__all__ = [
    "AUDIT_CODE",
    "Finding",
    "LintConfig",
    "ParseError",
    "RULES",
    "apply_baseline",
    "audit_paths",
    "audit_suppressions",
    "finding_key",
    "lint_file",
    "lint_paths",
    "lint_source",
    "load_config",
]


# -- configuration ----------------------------------------------------------


def _parse_toml_subset(text: str) -> dict:
    """Minimal TOML reader for the ``[tool.tpulint]`` table.

    This interpreter ships neither ``tomllib`` (3.11+) nor ``tomli``, and
    the repo vendors nothing, so the loader falls back to a subset
    parser: ``[section]`` headers, ``key = value`` with string / integer /
    flat string-array values, ``#`` comments. Exactly the shapes the
    tpulint table uses; anything fancier should go through ``tomllib``.
    """
    data: dict = {}
    section = data
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("[") and line.endswith("]"):
            section = data
            for part in line[1:-1].strip().strip('"').split("."):
                section = section.setdefault(part.strip().strip('"'), {})
            continue
        if "=" not in line:
            continue
        key, _, value = line.partition("=")
        key = key.strip().strip('"')
        value = value.strip()
        if value.startswith("[") and value.endswith("]"):
            items = re.findall(r'"((?:[^"\\]|\\.)*)"', value)
            section[key] = list(items)
        elif value.startswith('"') and value.endswith('"'):
            section[key] = value[1:-1]
        elif value in ("true", "false"):
            section[key] = value == "true"
        else:
            try:
                section[key] = int(value)
            except ValueError:
                section[key] = value
    return data


def _read_pyproject(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        text = f.read()
    try:
        import tomllib  # Python 3.11+

        return tomllib.loads(text)
    except ImportError:
        return _parse_toml_subset(text)


def load_config(root: Optional[str] = None) -> LintConfig:
    """The shared CLI/pytest-gate configuration.

    ``root`` is the directory holding ``pyproject.toml``; defaults to the
    repo root two levels above this package. A missing file or table
    yields the built-in defaults, so the linter works on any checkout.
    """
    if root is None:
        root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    pyproject = os.path.join(root, "pyproject.toml")
    table: dict = {}
    if os.path.exists(pyproject):
        table = _read_pyproject(pyproject).get("tool", {}).get("tpulint", {})
    cfg = LintConfig()
    select = table.get("select")
    ignore = table.get("ignore", [])
    unknown = (
        frozenset(c.upper() for c in (select or []))
        | frozenset(c.upper() for c in ignore)
    ) - RULES.keys()
    if unknown:
        # mirror the CLI's check: a typo'd code in pyproject must not
        # silently weaken (select) or widen (ignore) the gate
        raise SystemExit(
            f"[tool.tpulint] names unknown rule code(s): "
            f"{', '.join(sorted(unknown))} (known: {', '.join(sorted(RULES))})"
        )
    return dataclasses.replace(
        cfg,
        paths=tuple(table.get("paths", cfg.paths)),
        exclude=tuple(table.get("exclude", cfg.exclude)),
        select=frozenset(select) if select else None,
        ignore=frozenset(ignore),
        per_path_ignores={
            pat: tuple(codes)
            for pat, codes in table.get("per-path-ignores", {}).items()
        },
        min_donate_params=table.get(
            "min-donate-params", cfg.min_donate_params
        ),
        jit_factory_patterns=tuple(
            table.get("jit-factory-patterns", cfg.jit_factory_patterns)
        ),
        assumed_itemsize=table.get("assumed-itemsize", cfg.assumed_itemsize),
        reduction_roots=tuple(
            table.get("reduction-roots", cfg.reduction_roots)
        ),
        host_sync_fns=tuple(
            table.get("host-sync-fns", cfg.host_sync_fns)
        ),
        reraise_fns=tuple(
            table.get("reraise-fns", cfg.reraise_fns)
        ),
        aot_warmup_fns=tuple(
            table.get("aot-warmup-fns", cfg.aot_warmup_fns)
        ),
        retry_backoff_fns=tuple(
            table.get("retry-backoff-fns", cfg.retry_backoff_fns)
        ),
        loop_solver_fns=tuple(
            table.get("loop-solver-fns", cfg.loop_solver_fns)
        ),
        implicit_solver_fns=tuple(
            table.get("implicit-solver-fns", cfg.implicit_solver_fns)
        ),
        mixed_accum_fns=tuple(
            table.get("mixed-accum-fns", cfg.mixed_accum_fns)
        ),
        tunable_fns=tuple(
            table.get("tunable-fns", cfg.tunable_fns)
        ),
        collective_modules=tuple(
            table.get("collective-modules", cfg.collective_modules)
        ),
    )


# -- running ----------------------------------------------------------------


def _norm(path: str) -> str:
    return path.replace(os.sep, "/")


def _path_ignored_codes(path: str, config: LintConfig) -> frozenset[str]:
    codes: set[str] = set()
    norm = _norm(path)
    for pattern, pat_codes in config.per_path_ignores.items():
        # patterns are repo-relative; the leading-`*/` retry makes them
        # match when the runner was handed absolute paths (pytest gate)
        if (
            fnmatch.fnmatch(norm, pattern)
            or fnmatch.fnmatch(norm, f"*/{pattern}")
            or fnmatch.fnmatch(os.path.basename(norm), pattern)
        ):
            codes.update(c.upper() for c in pat_codes)
    return frozenset(codes)


def _active_rules(config: LintConfig, extra_ignore: frozenset[str] = frozenset()):
    for code, rule in sorted(RULES.items()):
        if config.select is not None and code not in config.select:
            continue
        if code in config.ignore or code in extra_ignore:
            continue
        yield rule


def lint_source(
    source: str,
    path: str = "<snippet>",
    config: Optional[LintConfig] = None,
) -> list[Finding]:
    """Lint a source string — the fixture-snippet entry the tests use."""
    config = config or LintConfig()
    module = Module(path, source)
    findings: list[Finding] = []
    for rule in _active_rules(config, _path_ignored_codes(path, config)):
        for f in rule.check(module, config):
            if not module.suppressed(f.line, f.code):
                findings.append(f)
    return sorted(findings)


def lint_file(path: str, config: Optional[LintConfig] = None) -> list[Finding]:
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), path=path, config=config)


def _iter_py_files(paths: Iterable[str], config: LintConfig):
    for path in paths:
        if os.path.isfile(path):
            yield path
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(
                d for d in dirnames if d not in ("__pycache__", ".git")
            )
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                full = os.path.join(dirpath, name)
                if any(
                    fnmatch.fnmatch(_norm(full), pat)
                    for pat in config.exclude
                ):
                    continue
                yield full


def lint_paths(
    paths: Iterable[str],
    config: Optional[LintConfig] = None,
) -> tuple[list[Finding], list[ParseError]]:
    """Lint files/trees; returns (findings, parse errors), both sorted."""
    config = config or LintConfig()
    paths = list(paths)
    findings: list[Finding] = []
    errors: list[ParseError] = []
    for path in paths:
        if not os.path.exists(path):
            # a typo'd path must not read as "lints clean"
            errors.append(ParseError(path=path, message="no such file or directory"))
    for path in _iter_py_files(paths, config):
        try:
            findings.extend(lint_file(path, config))
        except (SyntaxError, ValueError, UnicodeDecodeError) as e:
            errors.append(ParseError(path=path, message=str(e)))
        except OSError as e:
            errors.append(ParseError(path=path, message=str(e)))
    return sorted(findings), sorted(errors, key=lambda e: e.path)


# -- suppression audit -------------------------------------------------------

# The audit's pseudo-code: findings about the *annotations*, not the
# linted source, so it lives outside RULES (select/ignore never touch
# it) and stays hyphen-free so the suppression-comment grammar could
# address it.
AUDIT_CODE = "TPU000"


def audit_suppressions(
    source: str,
    path: str = "<snippet>",
    config: Optional[LintConfig] = None,
) -> list[Finding]:
    """Report ``# tpulint: disable=...`` annotations that suppress
    nothing — the annotation ratchet.

    Every active rule is re-run WITHOUT suppression filtering; a
    disable code (or ``all``) whose covered line carries no matching
    raw finding is stale: it reads as a waiver for a hazard that no
    longer exists, and it would silently swallow the next genuine
    finding that lands on that line. Codes whose rules are not active
    under ``config`` (select/ignore/per-path) are skipped — the audit
    cannot judge them; codes unknown to the registry are always flagged
    (they never suppressed anything).
    """
    config = config or LintConfig()
    module = Module(path, source)
    active = list(_active_rules(config, _path_ignored_codes(path, config)))
    active_codes = {r.code for r in active}
    fired_by_line: dict[int, set[str]] = {}
    for rule in active:
        for f in rule.check(module, config):
            fired_by_line.setdefault(f.line, set()).add(f.code.upper())
    out: list[Finding] = []
    for lineno, standalone, codes in _iter_suppression_comments(source):
        covered = {lineno}
        if standalone:  # standalone: covers the line below too
            covered.add(lineno + 1)
        fired: set[str] = set()
        for n in covered:
            fired |= fired_by_line.get(n, set())
        for code in sorted(codes):
            if code == "ALL":
                if not fired:
                    out.append(Finding(
                        path=path, line=lineno, col=1, code=AUDIT_CODE,
                        message="unused suppression: `disable=all` "
                        "covers no finding — remove the annotation",
                    ))
                continue
            if code not in RULES:
                out.append(Finding(
                    path=path, line=lineno, col=1, code=AUDIT_CODE,
                    message=f"unused suppression: `disable={code}` names "
                    "no registered rule — it has never suppressed "
                    "anything (typo?)",
                ))
                continue
            if code not in active_codes:
                continue  # rule not running here: nothing to judge
            if code not in fired:
                out.append(Finding(
                    path=path, line=lineno, col=1, code=AUDIT_CODE,
                    message=f"unused suppression: `disable={code}` "
                    "matches no finding on the line it covers — the "
                    "hazard is gone; remove the annotation",
                ))
    return sorted(out)


def audit_paths(
    paths: Iterable[str],
    config: Optional[LintConfig] = None,
) -> tuple[list[Finding], list[ParseError]]:
    """:func:`audit_suppressions` over files/trees — same walking,
    exclusion and error contract as :func:`lint_paths`."""
    config = config or LintConfig()
    paths = list(paths)
    findings: list[Finding] = []
    errors: list[ParseError] = []
    for path in paths:
        if not os.path.exists(path):
            errors.append(
                ParseError(path=path, message="no such file or directory")
            )
    for path in _iter_py_files(paths, config):
        try:
            with open(path, encoding="utf-8") as f:
                findings.extend(
                    audit_suppressions(f.read(), path=path, config=config)
                )
        except (SyntaxError, ValueError, UnicodeDecodeError, OSError) as e:
            errors.append(ParseError(path=path, message=str(e)))
    return sorted(findings), sorted(errors, key=lambda e: e.path)


# -- baseline (accept-then-ratchet) ------------------------------------------


def finding_key(f: Finding) -> str:
    """The baseline identity of a finding — deliberately message-free,
    so rewording a rule does not re-open accepted debt."""
    return f"{f.path}:{f.line}:{f.code}"


def apply_baseline(
    baseline_path: str,
    findings: list[Finding],
    errors: list[ParseError],
) -> tuple[list[Finding], Optional[str]]:
    """Accept-then-ratchet: filter ``findings`` through a baseline file.

    Missing file: every current finding is accepted into a fresh
    baseline and the run reads clean — the adoption step. Existing
    file: accepted keys stay silent, anything new fails; and once a run
    is otherwise clean, accepted keys that no longer match a finding
    are ratcheted OUT of the file, so the debt can only shrink. Returns
    ``(new_findings, note)`` — the note narrates what the baseline did.
    """
    keys = sorted({finding_key(f) for f in findings})
    if not os.path.exists(baseline_path):
        with open(baseline_path, "w", encoding="utf-8") as fh:
            json.dump(
                {"tool": "tpulint", "version": 1, "accepted": keys},
                fh, indent=2,
            )
            fh.write("\n")
        return [], (
            f"baseline: accepted {len(keys)} finding(s) into "
            f"{baseline_path}"
        )
    with open(baseline_path, encoding="utf-8") as fh:
        accepted = set(json.load(fh).get("accepted", []))
    new = [f for f in findings if finding_key(f) not in accepted]
    stale = sorted(accepted - set(keys))
    if not stale:
        return new, None
    if new or errors:
        return new, (
            f"baseline: {len(stale)} stale entr"
            f"{'y' if len(stale) == 1 else 'ies'} (ratchet deferred "
            "until the run is clean)"
        )
    kept = sorted(accepted & set(keys))
    with open(baseline_path, "w", encoding="utf-8") as fh:
        json.dump(
            {"tool": "tpulint", "version": 1, "accepted": kept},
            fh, indent=2,
        )
        fh.write("\n")
    return new, (
        f"baseline: ratcheted {len(stale)} fixed entr"
        f"{'y' if len(stale) == 1 else 'ies'} out of {baseline_path} "
        f"({len(kept)} remain)"
    )
