"""The tpulint rule registry: TPU001–TPU022.

Each rule is a generator over a :class:`~poisson_ellipse_tpu.lint.visitor.
Module`, yielding :class:`~poisson_ellipse_tpu.lint.report.Finding`s.
Suppression (``# tpulint: disable=CODE``) and select/ignore filtering are
applied by the runner, not here. Rules are deliberately conservative:
when a shape, dtype or callee cannot be resolved statically they stay
silent — a lint gate that cries wolf gets deleted from CI.

| code   | name               | hazard                                        |
|--------|--------------------|-----------------------------------------------|
| TPU001 | f64-literal        | float64 dtype silently downcast w/o x64       |
| TPU002 | traced-branch      | Python if/while on a traced value             |
| TPU003 | host-sync          | host sync reachable from a jitted hot loop    |
| TPU004 | missing-donation   | jit with large-array params, no donate_argnums|
| TPU005 | pallas-tile        | BlockSpec off the (8, 128) grid / VMEM budget |
| TPU006 | jit-per-call       | jax.jit rebuilt per loop step / per call      |
| TPU007 | unfused-reductions | adjacent independent global reductions in one |
|        |                    | loop body that could share a stacked collective|
| TPU008 | host-sync-in-loop  | host sync / host callback inside a traced loop|
|        |                    | body, or a fence-wrapper sync in a per-dispatch|
|        |                    | Python measurement loop                        |
| TPU009 | swallowed-exception| bare/broad `except` whose handler neither     |
|        |                    | re-raises nor hands off to a configured       |
|        |                    | classify-and-re-raise helper — device-runtime |
|        |                    | errors silently eaten                         |
| TPU010 | recompile-hazard   | `.lower().compile()` AOT chains inside Python |
|        |                    | loop bodies, and calls of static-argnum jitted|
|        |                    | callables whose static argument varies with a |
|        |                    | loop — a fresh trace+compile per iteration    |
| TPU011 | unfenced-timing    | a `time.time()`/`perf_counter()` span closing |
|        |                    | over a jitted dispatch with no fence between  |
|        |                    | the dispatch and the clock read — async       |
|        |                    | dispatch means the bracket timed the queue,   |
|        |                    | not the work                                  |
| TPU012 | unbounded-queue    | a module/class-level list or deque grown by   |
|        |                    | append with no maxlen and no draining bound — |
|        |                    | a long-lived serving process's memory leak    |
|        |                    | (the backpressure rule: bound it or shed)     |
| TPU013 | retraced-levels    | host-side recursion/loops that rebuild traced |
|        |                    | callables per call — a recursive fn holding a |
|        |                    | jit/AOT construction, or a jit-factory call   |
|        |                    | whose argument varies with a Python loop —    |
|        |                    | the MG-level recompile hazard: level count    |
|        |                    | must be static per grid bucket (TPU010's      |
|        |                    | factory-call sibling)                         |
| TPU014 | retry-without-     | an unbounded `while True` retry loop whose    |
|        | backoff            | exception handler swallows-and-loops with     |
|        |                    | neither a backoff/sleep call nor an attempt   |
|        |                    | cap in sight — the hot-spin retry storm that  |
|        |                    | turns one failing dispatch into a pegged host |
|        |                    | and a hammered runtime                        |
| TPU015 | host-roundtrip     | `float()`/`int()`/`bool()`/`.item()` on a     |
|        |                    | value derived from the array parameters of a  |
|        |                    | traced function or an `xp=`-dual geometry     |
|        |                    | function — a host round-trip that breaks the  |
|        |                    | traced path (ConcretizationTypeError on jit)  |
|        |                    | and silently downcasts the host-f64 one;      |
|        |                    | validation runs on host arrays, the traced    |
|        |                    | path stays pure                               |
| TPU016 | wall-clock-deadline| `time.time()` feeding a comparison used as a  |
|        |                    | lease/deadline/timeout — wall clocks step     |
|        |                    | under NTP, so a wall-clock lease fires early  |
|        |                    | or never; deadline arithmetic must read       |
|        |                    | `time.monotonic()` (timestamps that are only  |
|        |                    | recorded, never compared, stay silent)        |
| TPU017 | backprop-through-  | `jax.grad`/`jax.vjp` applied to a function    |
|        | loop               | that binds a `lax.while_loop`-based solver    |
|        |                    | entry without going through the implicit      |
|        |                    | (`custom_vjp`) wrapper — while_loop has no    |
|        |                    | reverse rule (trace error), and an unrolled   |
|        |                    | workaround stores thousands of iterates; the  |
|        |                    | IFT adjoint (`diff.adjoint.solve_implicit`)   |
|        |                    | is one extra solve with the same operator     |
| TPU018 | silent-downcast    | a bf16/f16 value (`.astype(bfloat16)` result  |
|        |                    | or arithmetic over such values) flows into a  |
|        |                    | reduction with no f32/f64 accumulator route — |
|        |                    | 8-mantissa-bit accumulation loses digits      |
|        |                    | linearly in n; upcast first, pass a wide      |
|        |                    | `dtype=`, or route via `mixed-accum-fns` (the |
|        |                    | storage-vs-compute fence of `ops.precision`)  |
| TPU019 | hardcoded-tunable  | a bare numeric literal bound to a tunable     |
|        |                    | knob keyword (Chebyshev degree, MG depth/ν,   |
|        |                    | s-step s, chunk size) at a solver-builder     |
|        |                    | call site (`tunable-fns`) — the autotuner     |
|        |                    | (`runtime.autotune`) can neither see nor      |
|        |                    | overrule it; route the value through the      |
|        |                    | engine-capability table, a named constant, or |
|        |                    | the tuned-config registry                     |
| TPU020 | raw-collective     | a raw jax.lax collective (psum / ppermute /   |
|        |                    | all_gather / ...) issued outside the blessed  |
|        |                    | communication modules (`collective-modules`,  |
|        |                    | default parallel/) — the contract matrix's    |
|        |                    | cadence budgets (analysis/, ENGINE_CAPS) only |
|        |                    | sweep that layer, so a stray collective       |
|        |                    | drifts the count invisibly; deliberate        |
|        |                    | exceptions carry a justified disable          |
| TPU021 | wall-clock-lease   | wall-clock reads (`time.time()`,              |
|        |                    | `datetime.now()`) used in lease/deadline      |
|        |                    | ARITHMETIC (`t0 + lease_s`, `now - started`) —|
|        |                    | TPU016's comparison prong extended: a duration|
|        |                    | or deadline COMPUTED from the wall clock is   |
|        |                    | stepped by NTP before any comparison happens; |
|        |                    | bare record-only timestamps stay silent       |
| TPU022 | unbounded-cache    | a module/class-level cache-named dict (name   |
|        |                    | contains cache/memo/pool) grown by key        |
|        |                    | assignment or setdefault with no eviction     |
|        |                    | route (pop/popitem/clear/del/rebind) — the    |
|        |                    | cache grows with the key space, not the       |
|        |                    | working set; TPU012's mapping sibling (the    |
|        |                    | solvecache LRU-cap discipline, fenced)        |
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import functools
import os
from typing import Callable, Iterator, Optional

from poisson_ellipse_tpu.lint.report import Finding
from poisson_ellipse_tpu.lint.visitor import Module, TracedFn


@dataclasses.dataclass
class LintConfig:
    """Knobs shared by the CLI and the pytest gate (``[tool.tpulint]``)."""

    paths: tuple[str, ...] = ("poisson_ellipse_tpu",)
    exclude: tuple[str, ...] = ()
    select: Optional[frozenset[str]] = None
    ignore: frozenset[str] = frozenset()
    per_path_ignores: dict[str, tuple[str, ...]] = dataclasses.field(
        default_factory=dict
    )
    # TPU004: only jit sites whose callee has at least this many
    # non-static positional params are assumed to carry "large" operands.
    min_donate_params: int = 3
    # TPU006: functions matching these names are jit *factories* (build
    # once, call many — the repo-wide contract); construction inside them
    # is not a per-call hazard.
    jit_factory_patterns: tuple[str, ...] = ("build_*", "make_*")
    # TPU005: itemsize assumed for tiles whose dtype cannot be resolved.
    assumed_itemsize: int = 4
    # TPU007: additional reduction-rooted callables (fnmatch patterns
    # over resolved qualnames) beyond the built-in jax.lax.psum /
    # jax.numpy.sum — a project names its own grid_dot-style wrappers
    # here so the rule sees through them.
    reduction_roots: tuple[str, ...] = ()
    # TPU008: fence-style sync wrappers (resolved-qualname fnmatch
    # patterns) — functions that block the host on device work. Calls to
    # them inside Python for/while loops are per-iteration host syncs:
    # justified exactly at timing-protocol fences, which carry an
    # annotation saying so.
    host_sync_fns: tuple[str, ...] = ("*.timing.fence", "fence")
    # TPU009: classify-and-re-raise helpers (resolved-qualname fnmatch
    # patterns). A broad handler that hands the exception to one of
    # these is compliant — the helper raises the classified SolveError
    # on the caller's behalf, so the handler body carries no literal
    # `raise` of its own.
    reraise_fns: tuple[str, ...] = ()
    # TPU010: functions matching these names are deliberate AOT warm-up
    # sites (cache fills, capacity probes) — a lower().compile() chain
    # in a loop there is the *fix* for recompile hazards, not one.
    # jit_factory_patterns are exempt as well (build-once contract).
    aot_warmup_fns: tuple[str, ...] = ("warmup*", "precompile*")
    # TPU014: backoff-style callables (leaf-name/qualname fnmatch
    # patterns). A retry loop that calls one of these between attempts
    # is pacing itself; one that calls none AND carries no attempt cap
    # is the hot-spin retry storm the rule exists to fence.
    retry_backoff_fns: tuple[str, ...] = (
        "*sleep*", "*backoff*", "idle", "*.idle", "wait", "*.wait",
    )
    # TPU017: `lax.while_loop`-based solver entries (leaf-name/qualname
    # fnmatch patterns). Applying reverse-mode autodiff to a function
    # that binds one of these — without going through the implicit
    # (custom_vjp) wrapper — either trace-errors (while_loop has no
    # reverse rule) or, via a naive unroll, backpropagates through
    # thousands of iterations.
    loop_solver_fns: tuple[str, ...] = (
        "pcg", "pcg_pipelined", "pcg_batched", "pcg_batched_pipelined",
        "guarded_solve", "solve_batched", "solve_sharded", "elastic_solve",
    )
    # TPU017: the implicit-differentiation wrappers whose presence in
    # the same target means the gradient is routed correctly (the IFT
    # adjoint of ``diff.adjoint``, one extra solve — not a backprop
    # through the loop).
    implicit_solver_fns: tuple[str, ...] = (
        "solve_implicit", "solve_operands", "*ImplicitSolver*",
        "custom_linear_solve",
    )
    # TPU018: sanctioned mixed-precision reducers (fnmatch patterns) —
    # callables that take narrow (bf16/f16) operands but accumulate at
    # f32/f64 internally (the mixed Pallas kernels, ops.precision's
    # helpers). A narrow value flowing into one of these is the
    # designed route, not a silent downcast.
    mixed_accum_fns: tuple[str, ...] = (
        "*_mixed_pallas", "*.precision.load", "*.precision.store",
    )
    # TPU019: solver-builder callables (leaf-name/qualname fnmatch
    # patterns) whose tunable-knob keyword arguments must come from the
    # autotune registry / engine-capability table / named constants —
    # a bare numeric literal at one of these call sites is a hardcoded
    # tunable the autotuner can never see or overrule.
    tunable_fns: tuple[str, ...] = (
        "build_solver", "build_*_solver", "build_*_stepper",
        "make_precond", "make_vcycle", "make_fcycle", "guarded_solve",
        "solve_batched", "pcg_sstep", "resolve_fmg_config",
    )
    # TPU020: the modules licensed to issue raw jax.lax collectives
    # ("/"-normalized path fnmatch patterns). Every cadence the contract
    # matrix (analysis/) pins — psums per body, halo ppermute budgets —
    # is counted over the communication layer; a collective issued
    # outside it is invisible to those budgets until it breaks one.
    collective_modules: tuple[str, ...] = (
        "*/parallel/*", "parallel/*",
    )
    # TPU021: the wall-clock sources whose results must not feed
    # lease/deadline/duration arithmetic (resolved-qualname fnmatch
    # patterns — a project wrapping another stepping clock, e.g.
    # `arrow.utcnow`, extends the set here). time.monotonic() and
    # perf_counter() are immune by construction and never listed.
    wall_clock_fns: tuple[str, ...] = (
        "time.time", "datetime.datetime.now", "datetime.datetime.utcnow",
        "datetime.now", "datetime.utcnow",
    )


@dataclasses.dataclass(frozen=True)
class Rule:
    code: str
    name: str
    summary: str
    check: Callable[[Module, LintConfig], Iterator[Finding]]


RULES: dict[str, Rule] = {}


def rule(code: str, name: str, summary: str):
    def deco(fn):
        RULES[code] = Rule(code, name, summary, fn)
        return fn

    return deco


def _finding(module: Module, node: ast.AST, code: str, message: str) -> Finding:
    return Finding(
        path=module.path,
        line=getattr(node, "lineno", 1),
        col=getattr(node, "col_offset", 0) + 1,
        code=code,
        message=message,
    )


# --------------------------------------------------------------------------
# TPU001 — float64 literals that silently downcast under disabled x64
# --------------------------------------------------------------------------

_F64_NAMES = frozenset(
    {"jax.numpy.float64", "jax.numpy.double", "numpy.float64", "numpy.double"}
)
_F64_STRINGS = frozenset({"float64", "double", "f8", "<f8"})
# positional index of the dtype parameter for common jnp constructors
_DTYPE_POS = {
    "array": 1, "asarray": 1, "zeros": 1, "ones": 1, "empty": 1, "full": 2,
}


def _is_f64_dtype_expr(module: Module, node: ast.AST) -> bool:
    q = module.qualname(node)
    if q == "float" or q in _F64_NAMES:
        return True
    return isinstance(node, ast.Constant) and node.value in _F64_STRINGS


@rule(
    "TPU001",
    "f64-literal",
    "float64/`float` dtypes under jnp silently downcast to float32 when "
    "jax_enable_x64 is off",
)
def check_f64_literal(module: Module, config: LintConfig) -> Iterator[Finding]:
    flagged: set[tuple[int, int]] = set()

    def flag(node, msg):
        key = (node.lineno, node.col_offset)
        if key not in flagged:
            flagged.add(key)
            yield _finding(module, node, "TPU001", msg)

    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call):
            q = module.qualname(node.func) or ""
            if not q.startswith("jax.numpy."):
                continue
            dtype_expr = None
            for kw in node.keywords:
                if kw.arg == "dtype":
                    dtype_expr = kw.value
            pos = _DTYPE_POS.get(q.rsplit(".", 1)[1])
            if dtype_expr is None and pos is not None and pos < len(node.args):
                dtype_expr = node.args[pos]
            if dtype_expr is not None and _is_f64_dtype_expr(module, dtype_expr):
                yield from flag(
                    dtype_expr,
                    f"`{q.removeprefix('jax.')}` built with a float64/"
                    "`float` dtype: silently becomes float32 under disabled "
                    "x64 — spell the narrow dtype you mean, or gate on "
                    "`jax.config.jax_enable_x64`",
                )
        elif isinstance(node, (ast.Attribute, ast.Name)):
            if module.qualname(node) in ("jax.numpy.float64", "jax.numpy.double"):
                parent = Module.parent(node)
                if isinstance(parent, ast.Attribute):
                    continue  # the inner part of a longer dotted name
                yield from flag(
                    node,
                    "`jnp.float64` is float32 under disabled x64 — this "
                    "reference silently changes meaning with the flag",
                )


# --------------------------------------------------------------------------
# TPU002 — Python control flow on traced values
# --------------------------------------------------------------------------


@rule(
    "TPU002",
    "traced-branch",
    "Python `if`/`while` on a traced value inside a jit/loop-body function",
)
def check_traced_branch(module: Module, config: LintConfig) -> Iterator[Finding]:
    for fn in module.traced_fns:
        tainted = module.tainted_names(fn)
        if not tainted:
            continue
        for node in ast.walk(fn.node):
            if isinstance(node, (ast.If, ast.While)) and module.expr_mentions(
                node.test, tainted
            ):
                kw = "while" if isinstance(node, ast.While) else "if"
                yield _finding(
                    module,
                    node,
                    "TPU002",
                    f"Python `{kw}` on a traced value in a {fn.kind} "
                    "function: fails at trace time or silently bakes one "
                    "branch into the compile — use `jax.lax.cond`/"
                    "`jnp.where` (or mark the argument static)",
                )


# --------------------------------------------------------------------------
# TPU003 — host syncs reachable from jitted hot loops
# --------------------------------------------------------------------------

_HOST_SYNC_METHODS = frozenset({"block_until_ready", "item", "tolist"})
_HOST_SYNC_CALLS = frozenset(
    {"jax.block_until_ready", "jax.device_get", "numpy.asarray", "numpy.array"}
)
_HOST_CAST_BUILTINS = frozenset({"float", "int", "bool"})


def _host_sync_site(module: Module, node: ast.Call, tainted: set[str]):
    """Classify one Call as a host-sync construct, or None.

    The single source of the matcher + taint semantics shared by TPU003
    and TPU008 (two copies drifted once — the numpy taint guard — so the
    classification lives here exactly once). Returns (kind, label):
    kind "method" (``x.item()``-style), "call" (``jax.device_get`` /
    host-numpy materialisation of a traced value), or "cast"
    (``float(x)`` on a traced value).
    """
    q = module.qualname(node.func) or ""
    if (
        isinstance(node.func, ast.Attribute)
        and node.func.attr in _HOST_SYNC_METHODS
        and q not in _HOST_SYNC_CALLS
    ):
        return "method", node.func.attr
    if q in _HOST_SYNC_CALLS:
        # numpy.asarray/array only sync when fed a traced value; on host
        # constants they are trace-time constant folding, not a sync
        needs_taint = q.startswith("numpy.")
        if not needs_taint or (
            node.args and module.expr_mentions(node.args[0], tainted)
        ):
            return "call", q
        return None
    if (
        isinstance(node.func, ast.Name)
        and node.func.id in _HOST_CAST_BUILTINS
        and q == node.func.id  # not shadowed by an import
        and node.args
        and module.expr_mentions(node.args[0], tainted)
    ):
        return "cast", node.func.id
    return None


def _host_sync_findings(
    module: Module,
    fn_node: ast.AST,
    tainted: set[str],
    origin: str,
    seen: set[tuple[int, frozenset[str]]],
    depth: int = 0,
) -> Iterator[Finding]:
    key = (id(fn_node), frozenset(tainted))
    if key in seen or depth > 8:
        return
    seen.add(key)
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Call):
            continue
        site = _host_sync_site(module, node, tainted)
        if site is not None:
            kind, label = site
            message = {
                "method": (
                    f"`.{label}()` is a host sync reachable from "
                    f"{origin}: the loop stalls on a device round-trip "
                    "every dispatch — hoist it out of the hot path"
                ),
                "call": (
                    f"`{label}` forces a device→host transfer reachable "
                    f"from {origin} — keep the hot loop device-resident"
                ),
                "cast": (
                    f"`{label}()` on a traced value reachable from "
                    f"{origin}: blocks on the device to produce a Python "
                    "scalar — keep the value on device or move the cast "
                    "out of the traced path"
                ),
            }[kind]
            yield _finding(module, node, "TPU003", message)
            continue
        if isinstance(node.func, ast.Attribute) or (
            module.qualname(node.func) or ""
        ) in _HOST_SYNC_CALLS:
            # a classified-negative sync-shaped call (e.g. untainted
            # numpy.asarray): don't descend into it as a local callee
            continue
        # shallow same-module reachability: follow calls to local defs,
        # mapping argument taint onto their parameters
        if isinstance(node.func, ast.Name):
            callee = module.functions.get(node.func.id)
            if callee is not None and callee is not fn_node:
                params = [p.arg for p in callee.args.args]
                callee_tainted = {
                    params[i]
                    for i, arg in enumerate(node.args)
                    if i < len(params) and module.expr_mentions(arg, tainted)
                }
                yield from _host_sync_findings(
                    module, callee, callee_tainted, origin, seen, depth + 1
                )


@rule(
    "TPU003",
    "host-sync",
    "host-sync call (`.block_until_ready()`, `float(x)`, `np.asarray`) "
    "reachable from a jitted hot loop",
)
def check_host_sync(module: Module, config: LintConfig) -> Iterator[Finding]:
    """Division of labour with TPU008: syncs lexically inside a
    ``while_loop``/``scan``/``fori_loop`` body are that rule's territory
    (one defect, one code, one suppression) — this rule covers the
    jit-def/jit-call surface and its same-module reachability."""
    seen: set[tuple[int, frozenset[str]]] = set()
    emitted: set[tuple[int, int]] = set()
    loop_spans = [
        (fn.node.lineno, getattr(fn.node, "end_lineno", fn.node.lineno))
        for fn in module.traced_fns
        if fn.kind == "loop-body"
    ]
    for fn in module.traced_fns:
        if fn.kind == "loop-body":
            continue  # TPU008 reports these, with the loop-specific fix
        name = getattr(fn.node, "name", "<lambda>")
        origin = f"{fn.kind} `{name}`"
        for f in _host_sync_findings(
            module, fn.node, module.tainted_names(fn), origin, seen
        ):
            if any(a <= f.line <= b for a, b in loop_spans):
                continue  # lexically inside a loop body nested in a jit fn
            if (f.line, f.col) not in emitted:
                emitted.add((f.line, f.col))
                yield f


# --------------------------------------------------------------------------
# TPU004 — jit call sites with large-array params missing donate_argnums
# --------------------------------------------------------------------------


@rule(
    "TPU004",
    "missing-donation",
    "jax.jit over a many-array-param callable without donate_argnums/"
    "donate_argnames",
)
def check_missing_donation(module: Module, config: LintConfig) -> Iterator[Finding]:
    for node in ast.walk(module.tree):
        target = None
        jit_call = None
        if isinstance(node, ast.Call):
            wrapped = module.jit_construction(node)
            if wrapped is None:
                continue
            jit_call, target = node, module.resolve_callable(wrapped)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                head = dec.func if isinstance(dec, ast.Call) else dec
                if module.is_jit_name(head) or (
                    isinstance(dec, ast.Call)
                    and dec.args
                    and module.is_jit_name(dec.args[0])
                ):
                    jit_call, target = (
                        dec if isinstance(dec, ast.Call) else None
                    ), node
        if target is None or not hasattr(target, "args"):
            continue
        if target.args.vararg is not None:
            continue  # arity unknowable
        static = (
            module._jit_static_params(jit_call, target)
            if jit_call is not None
            else frozenset()
        )
        n_params = len(
            [
                p.arg
                for p in (
                    list(getattr(target.args, "posonlyargs", []))
                    + list(target.args.args)
                )
                if p.arg not in static and p.arg not in ("self", "cls")
            ]
        )
        if n_params < config.min_donate_params:
            continue
        kwargs = {kw.arg for kw in jit_call.keywords} if jit_call is not None else set()
        if kwargs & {"donate_argnums", "donate_argnames"}:
            continue
        site = node if isinstance(node, ast.Call) else (jit_call or node)
        name = getattr(target, "name", "<lambda>")
        yield _finding(
            module,
            site,
            "TPU004",
            f"jax.jit over `{name}` ({n_params} array-like params) without "
            "donate_argnums/donate_argnames: every dispatch keeps all "
            "inputs alive alongside the outputs — donate consumed operands, "
            "or suppress with a note when callers reuse them",
        )


# --------------------------------------------------------------------------
# TPU005 — Pallas BlockSpec tiles off the (8, 128) grid / over VMEM budget
# --------------------------------------------------------------------------

_SUBLANE, _LANE = 8, 128
_ITEMSIZE_BY_DTYPE = {
    "float64": 8, "int64": 8, "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "int8": 1, "uint8": 1,
    "bool_": 1, "bool": 1, "float8_e4m3fn": 1, "float8_e5m2": 1,
}
_MIN_VMEM_FALLBACK = 128 * 1024 * 1024


@functools.lru_cache(maxsize=1)
def _min_vmem_capacity() -> int:
    """Smallest per-core VMEM across the supported parts, read statically
    from ``utils/device.py``'s ``_VMEM_CAPACITY`` table (no jax import:
    the linter must run identically with no accelerator runtime)."""
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "utils", "device.py"
    )
    try:
        with open(path) as f:
            tree = ast.parse(f.read())
        namespace: dict[str, object] = {}
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
                if isinstance(target, ast.Name) and target.id in (
                    "_MIB",
                    "_VMEM_CAPACITY",
                ):
                    code = compile(ast.Expression(node.value), path, "eval")
                    namespace[target.id] = eval(code, {}, dict(namespace))
        table = namespace.get("_VMEM_CAPACITY")
        if isinstance(table, dict) and table:
            return min(int(v) for v in table.values())
    except (OSError, SyntaxError, ValueError, NameError, TypeError):
        pass
    return _MIN_VMEM_FALLBACK


def _itemsize_of(module: Module, node: Optional[ast.AST], fallback: int) -> int:
    if node is None:
        return fallback
    q = module.qualname(node) or ""
    return _ITEMSIZE_BY_DTYPE.get(q.rsplit(".", 1)[-1], fallback)


def _blockspec_shape(module: Module, call: ast.Call):
    """(shape tuple of int-or-None, memory_space qualname) of a BlockSpec."""
    shape_expr = call.args[0] if call.args else None
    memspace = None
    for kw in call.keywords:
        if kw.arg == "block_shape":
            shape_expr = kw.value
        elif kw.arg == "memory_space":
            memspace = module.qualname(kw.value) or ""
    if not isinstance(shape_expr, (ast.Tuple, ast.List)):
        return None, memspace
    dims = tuple(
        e.value if isinstance(e, ast.Constant) and isinstance(e.value, int) else None
        for e in shape_expr.elts
    )
    return dims, memspace


def _is_vmem_space(memspace: Optional[str]) -> bool:
    return memspace is None or memspace.endswith(".VMEM")


@rule(
    "TPU005",
    "pallas-tile",
    "Pallas BlockSpec tile off the (8, 128) sublane/lane grid, or a "
    "kernel VMEM working set over the smallest supported part's budget",
)
def check_pallas_tile(module: Module, config: LintConfig) -> Iterator[Finding]:
    min_vmem = _min_vmem_capacity()
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        q = module.qualname(node.func) or ""
        if q.endswith(".BlockSpec") and q.startswith("jax.experimental.pallas"):
            dims, memspace = _blockspec_shape(module, node)
            if dims is None or not _is_vmem_space(memspace):
                continue
            checks = []
            if len(dims) >= 1 and dims[-1] is not None:
                checks.append((dims[-1], _LANE, "lane (minor)"))
            if len(dims) >= 2 and dims[-2] is not None:
                checks.append((dims[-2], _SUBLANE, "sublane (second-minor)"))
            for value, mult, which in checks:
                if value % mult != 0:
                    yield _finding(
                        module,
                        node,
                        "TPU005",
                        f"BlockSpec {which} dim {value} is not a multiple "
                        f"of {mult}: Mosaic pads every tile to the "
                        f"({_SUBLANE}, {_LANE}) grid, silently wasting "
                        "VMEM and lanes — pick an aligned tile",
                    )
        elif q.endswith(".pallas_call"):
            total = 0
            for kw in node.keywords:
                if kw.arg != "scratch_shapes":
                    continue
                entries = (
                    kw.value.elts
                    if isinstance(kw.value, (ast.Tuple, ast.List))
                    else []
                )
                for entry in entries:
                    if not isinstance(entry, ast.Call):
                        continue
                    eq = module.qualname(entry.func) or ""
                    if not eq.endswith(".VMEM"):
                        continue
                    shape = entry.args[0] if entry.args else None
                    if not isinstance(shape, (ast.Tuple, ast.List)):
                        continue
                    dims = [
                        e.value
                        if isinstance(e, ast.Constant) and isinstance(e.value, int)
                        else None
                        for e in shape.elts
                    ]
                    if any(d is None for d in dims):
                        total = None  # unknowable statically: stay silent
                        break
                    n = 1
                    for d in dims:
                        n *= d
                    itemsize = _itemsize_of(
                        module,
                        entry.args[1] if len(entry.args) > 1 else None,
                        config.assumed_itemsize,
                    )
                    total += n * itemsize
                if total is None:
                    break
            if total and total > min_vmem:
                yield _finding(
                    module,
                    node,
                    "TPU005",
                    f"pallas_call VMEM scratch working set ≈{total // 1024 // 1024} "
                    f"MiB exceeds the smallest supported part's "
                    f"{min_vmem // 1024 // 1024} MiB budget "
                    "(utils/device.py capability table) — tile smaller or "
                    "gate the kernel on `utils.device.vmem_capacity_bytes`",
                )


# --------------------------------------------------------------------------
# TPU007 — adjacent un-fused global reductions in one jitted loop body
# --------------------------------------------------------------------------

# reductions every jax project has; projects add their own wrappers via
# LintConfig.reduction_roots ([tool.tpulint] reduction-roots)
_REDUCTION_ROOTS = ("jax.lax.psum", "jax.numpy.sum")


def _statement_targets(stmt: ast.stmt) -> set[str]:
    names: set[str] = set()
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
        # a def whose body holds the reduction: its influence flows
        # through the bound name (callers of the closure)
        names.add(stmt.name)
    else:
        # compound statement (if/for/with/try...) holding the reduction:
        # every name it stores is a potential carrier — over-approximate
        # so a dependent follow-up reduction stays silent
        names |= {
            n.id
            for n in ast.walk(stmt)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)
        }
    for target in targets:
        names |= {n.id for n in ast.walk(target) if isinstance(n, ast.Name)}
    return names


def _reads_any(module: Module, stmt: ast.stmt, names: set[str]) -> bool:
    """Does the statement read any of ``names``? Assignments are tested
    on their value expression; compound statements (a nested ``def``
    whose body consumes a reduction-derived scalar, a loop, a ``with``)
    on the whole node — over-approximating reads keeps the rule quiet
    exactly when the dependence question gets murky."""
    node = getattr(stmt, "value", None)
    if node is None:
        node = stmt
    return module.expr_mentions(node, names)


def _reduction_sites(module: Module, stmt: ast.stmt, roots) -> list[ast.Call]:
    """Calls in ``stmt`` whose callee resolves to a global-reduction root.

    ``jnp.sum`` with an explicit ``axis=`` is a partial reduction (stays
    an array), not a scalar collective candidate — skipped.
    """
    out = []
    for node in ast.walk(stmt):
        if not isinstance(node, ast.Call):
            continue
        q = module.qualname(node.func) or ""
        if not any(fnmatch.fnmatch(q, pat) for pat in roots):
            continue
        if q.rsplit(".", 1)[-1] == "sum" and (
            len(node.args) > 1  # positional axis: jnp.sum(a, 0)
            or any(kw.arg in ("axis", "axes") for kw in node.keywords)
        ):
            continue
        out.append(node)
    return out


@rule(
    "TPU007",
    "unfused-reductions",
    "two adjacent independent global reductions (psum / jnp.sum-rooted "
    "dots) in one jitted loop body that could share a single stacked "
    "collective",
)
def check_unfused_reductions(module: Module, config: LintConfig) -> Iterator[Finding]:
    """Inside a ``lax.while_loop``/``scan``/``fori_loop`` body, two
    reduction-rooted statements with no data dependence between them
    serialize the loop on two reduce→broadcast latencies where a single
    stacked reduction (``jnp.stack`` of the partials → one ``psum`` /
    one fused sum pass) would pay one. Reductions that are genuinely
    sequential — the second reads a value derived from the first — are
    the algorithm's critical path, not a fusion miss, and stay silent;
    so do multiple reductions already stacked into one statement.
    """
    roots = _REDUCTION_ROOTS + tuple(config.reduction_roots)
    for fn in module.traced_fns:
        if fn.kind != "loop-body":
            continue
        body = fn.node.body
        if not isinstance(body, list):
            continue  # lambda body: a single expression, one statement
        prev_line: Optional[int] = None
        taint: set[str] = set()
        for stmt in body:
            sites = _reduction_sites(module, stmt, roots)
            if not sites:
                # propagate the previous reduction's influence forward
                if prev_line is not None and _reads_any(module, stmt, taint):
                    taint |= _statement_targets(stmt)
                continue
            if prev_line is not None and not _reads_any(module, stmt, taint):
                yield _finding(
                    module,
                    sites[0],
                    "TPU007",
                    "global reduction independent of the one at line "
                    f"{prev_line} in the same loop body: the two "
                    "serialize on separate reduce->broadcast latencies "
                    "(2 collectives on a mesh) — stack the partials and "
                    "issue one fused reduction (the grid_dots / stacked-"
                    "psum idiom), or suppress with a note when the "
                    "ordering is load-bearing",
                )
            prev_line = stmt.lineno
            taint = _statement_targets(stmt)


# --------------------------------------------------------------------------
# TPU006 — jax.jit constructed per loop step / per call
# --------------------------------------------------------------------------


@rule(
    "TPU006",
    "jit-per-call",
    "jax.jit constructed inside a Python loop or per-call closure "
    "(recompilation hazard)",
)
def check_jit_per_call(module: Module, config: LintConfig) -> Iterator[Finding]:
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        if module.jit_construction(node) is None:
            continue
        in_loop = any(
            isinstance(anc, (ast.For, ast.While, ast.AsyncFor))
            for anc in module.ancestors(node)
        )
        if in_loop:
            yield _finding(
                module,
                node,
                "TPU006",
                "jax.jit constructed inside a Python loop: every iteration "
                "builds a fresh callable with an empty dispatch cache — "
                "hoist the jit out of the loop",
            )
            continue
        parent = Module.parent(node)
        if isinstance(parent, ast.Call) and parent.func is node:
            yield _finding(
                module,
                node,
                "TPU006",
                "jax.jit(...)(...) constructs and calls in one expression: "
                "the traced cache dies with the expression, so every "
                "evaluation recompiles — bind the jitted callable once",
            )
            continue
        enclosing = module.enclosing_function(node)
        if enclosing is None:
            continue  # module scope: constructed once at import
        name = getattr(enclosing, "name", "<lambda>")
        if any(
            fnmatch.fnmatch(name, pat) for pat in config.jit_factory_patterns
        ):
            continue
        stmt = module.nearest_statement(node)
        if isinstance(stmt, ast.Return):
            continue  # a factory by shape: the jit object is the product
        yield _finding(
            module,
            node,
            "TPU006",
            f"jax.jit constructed per call of `{name}` (neither returned "
            "nor in a recognised factory): callers re-entering this "
            "function retrace from scratch — hoist the jit, return it, or "
            "suppress with a note when single-shot construction is the "
            "point",
        )


# --------------------------------------------------------------------------
# TPU008 — host syncs / host callbacks inside loop bodies
# --------------------------------------------------------------------------

# per-iteration host callback registrars: each invocation inside a loop
# body is a device->host round-trip every iteration (jax.debug.print is
# asynchronous and deliberately not listed)
_CALLBACK_REGISTRARS = frozenset(
    {
        "jax.debug.callback",
        "jax.pure_callback",
        "jax.experimental.io_callback",
    }
)


def _is_fence_wrapper(q: str, config: LintConfig) -> bool:
    return bool(q) and any(
        fnmatch.fnmatch(q, pat) for pat in config.host_sync_fns
    )


@rule(
    "TPU008",
    "host-sync-in-loop",
    "host sync or per-iteration host callback inside a traced loop body, "
    "or a fence-wrapper sync inside a per-dispatch Python loop",
)
def check_host_sync_in_loop(module: Module, config: LintConfig) -> Iterator[Finding]:
    """The stage4 anti-pattern, fenced off structurally: the reference
    synchronises host and device every PCG iteration (3 device→host
    round-trips + 6 syncs, ``poisson_mpi_cuda2.cu:846-939``), and the
    single design inversion this framework is built on is that nothing
    inside the iteration ever touches the host. Two prongs:

    - *traced loop bodies* (``lax.while_loop``/``scan``/``fori_loop``
      bodies): any host-sync construct (``.item()``, ``.tolist()``,
      ``.block_until_ready()``, ``jax.device_get``, ``float()``/``int()``/
      ``bool()`` on a traced value, a configured fence wrapper) or any
      host-callback registration (``jax.pure_callback``,
      ``jax.experimental.io_callback``, ``jax.debug.callback``) — the
      convergence-telemetry layer exists precisely so nobody needs these
      (``obs.convergence``: on-device ring buffers instead of per-
      iteration callbacks).
    - *host measurement loops*: a call to a fence-style wrapper
      (``host-sync-fns`` config; ``utils.timing.fence`` by default)
      inside a Python ``for``/``while`` loop blocks the host once per
      pass. At a timing-protocol fence that IS the measurement —
      annotate the site; anywhere else it is a dispatch-pipeline stall.
    """
    emitted: set[tuple[int, int]] = set()

    def once(finding):
        key = (finding.line, finding.col)
        if key not in emitted:
            emitted.add(key)
            yield finding

    # prong 1: traced loop bodies (nested defs included — a helper defined
    # in the body runs under the same trace)
    for fn in module.traced_fns:
        if fn.kind != "loop-body":
            continue
        tainted = module.tainted_names(fn)
        name = getattr(fn.node, "name", "<lambda>")
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            q = module.qualname(node.func) or ""
            site = _host_sync_site(module, node, tainted)
            if site is not None:
                kind, label = site
                message = {
                    "method": (
                        f"`.{label}()` inside loop body `{name}`: a host "
                        "sync EVERY iteration — the stage4 anti-pattern; "
                        "record per-iteration scalars on device instead "
                        "(obs.convergence ring buffers)"
                    ),
                    "call": (
                        f"`{label}` inside loop body `{name}`: a "
                        "device→host round-trip every iteration — keep "
                        "the loop device-resident (obs.convergence "
                        "captures per-iteration series without leaving "
                        "the chip)"
                    ),
                    "cast": (
                        f"`{label}()` on a traced value inside loop body "
                        f"`{name}`: blocks for a Python scalar every "
                        "iteration — keep the value on device"
                    ),
                }[kind]
                yield from once(_finding(module, node, "TPU008", message))
            elif _is_fence_wrapper(q, config):
                yield from once(_finding(
                    module, node, "TPU008",
                    f"`{q}` inside loop body `{name}`: a device→host "
                    "round-trip every iteration — keep the loop device-"
                    "resident (obs.convergence captures per-iteration "
                    "series without leaving the chip)",
                ))
            elif q in _CALLBACK_REGISTRARS:
                yield from once(_finding(
                    module, node, "TPU008",
                    f"`{q}` inside loop body `{name}`: registers a host "
                    "callback that fires every iteration — per-iteration "
                    "telemetry belongs in on-device buffers "
                    "(obs.convergence), not callbacks",
                ))

    # prong 2: fence wrappers inside host-level Python loops
    loop_body_fns = {
        id(fn.node) for fn in module.traced_fns if fn.kind == "loop-body"
    }
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        q = module.qualname(node.func) or ""
        if not _is_fence_wrapper(q, config):
            continue
        in_host_loop = False
        for anc in module.ancestors(node):
            if id(anc) in loop_body_fns:
                in_host_loop = False  # prong 1 territory
                break
            if isinstance(anc, (ast.For, ast.While, ast.AsyncFor)):
                in_host_loop = True
        if in_host_loop:
            yield from once(_finding(
                module, node, "TPU008",
                f"`{q}` inside a Python loop: one host↔device sync per "
                "pass. A timing-protocol fence is the one justified case "
                "— annotate it with a note; otherwise hoist the sync out "
                "and let dispatches pipeline",
            ))


# --------------------------------------------------------------------------
# TPU009 — bare/broad except blocks that swallow device-runtime errors
# --------------------------------------------------------------------------

_BROAD_EXCEPTION_NAMES = frozenset(
    {"Exception", "BaseException", "builtins.Exception",
     "builtins.BaseException"}
)


def _is_broad_handler(module: Module, handler: ast.ExceptHandler) -> bool:
    """Bare ``except:``, ``except Exception/BaseException``, or a tuple
    containing either. A *narrow* class the code chose deliberately
    (ValueError, XlaRuntimeError, ...) is a stated intent and stays
    silent — the hazard is the catch-all that eats whatever the device
    runtime throws."""
    if handler.type is None:
        return True
    types = (
        handler.type.elts
        if isinstance(handler.type, (ast.Tuple, ast.List))
        else [handler.type]
    )
    for t in types:
        if (module.qualname(t) or "") in _BROAD_EXCEPTION_NAMES:
            return True
    return False


def _handler_reraises(module: Module, handler: ast.ExceptHandler,
                      config: LintConfig) -> bool:
    """Does the handler body itself re-raise (or call a reraise-fn)?

    Scope-aware: a ``raise`` inside a nested ``def``/``lambda``/class is
    merely *defined* in the handler, never executed by it — descending
    into those scopes would let ``except Exception: def f(): raise``
    pass, which is exactly the swallow the rule fences (same stance as
    the other rules' traced-scope walks)."""
    stack: list[ast.AST] = list(handler.body)
    while stack:
        node = stack.pop()
        if isinstance(
            node,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef),
        ):
            continue
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            q = module.qualname(node.func) or ""
            if q and any(
                fnmatch.fnmatch(q, pat) for pat in config.reraise_fns
            ):
                return True
        stack.extend(ast.iter_child_nodes(node))
    return False


# --------------------------------------------------------------------------
# TPU010 — recompilation hazards: AOT chains in loops, loop-varying statics
# --------------------------------------------------------------------------


def _is_lower_compile_chain(node: ast.Call) -> bool:
    """``<expr>.lower(...).compile(...)`` — the AOT compile chain."""
    f = node.func
    return (
        isinstance(f, ast.Attribute)
        and f.attr == "compile"
        and isinstance(f.value, ast.Call)
        and isinstance(f.value.func, ast.Attribute)
        and f.value.func.attr == "lower"
    )


def _in_python_loop(module: Module, node: ast.AST) -> bool:
    return any(
        isinstance(anc, (ast.For, ast.While, ast.AsyncFor))
        for anc in module.ancestors(node)
    )


def _enclosing_is_exempt(module: Module, node: ast.AST,
                         config: LintConfig) -> bool:
    """Deliberate-AOT carve-out: warm-up fns and jit factories may
    compile in loops — that IS the warm pool being filled once."""
    enclosing = module.enclosing_function(node)
    if enclosing is None:
        return False
    name = getattr(enclosing, "name", "<lambda>")
    patterns = config.aot_warmup_fns + config.jit_factory_patterns
    return any(fnmatch.fnmatch(name, pat) for pat in patterns)


def _static_jit_bindings(module: Module):
    """name → (static positional indices, static keyword names) for every
    ``f = jax.jit(g, static_argnums=…/static_argnames=…)`` binding whose
    static spec is a literal. Non-literal specs stay silent (the rule's
    conservative stance)."""
    out: dict[str, tuple[frozenset[int], frozenset[str]]] = {}
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        call = node.value
        if not isinstance(call, ast.Call) or module.jit_construction(call) is None:
            continue
        nums: set[int] = set()
        names: set[str] = set()
        literal = True
        for kw in call.keywords:
            if kw.arg == "static_argnums":
                lit = Module._literal_int_tuple(kw.value)
                if lit is None and isinstance(kw.value, ast.Constant) and \
                        isinstance(kw.value.value, int):
                    lit = (kw.value.value,)
                if lit is None:
                    literal = False
                    break
                nums.update(lit)
            elif kw.arg == "static_argnames":
                vals = (
                    kw.value.elts
                    if isinstance(kw.value, (ast.Tuple, ast.List))
                    else [kw.value]
                )
                if not all(
                    isinstance(v, ast.Constant) and isinstance(v.value, str)
                    for v in vals
                ):
                    literal = False
                    break
                names.update(v.value for v in vals)
        if not literal or not (nums or names):
            continue
        out[target.id] = (frozenset(nums), frozenset(names))
    return out


def _loop_targets(loop: ast.AST) -> set[str]:
    """Names a loop rebinds per iteration: ``for`` targets, plus names
    assigned anywhere in a ``while`` body (over-approximate)."""
    if isinstance(loop, (ast.For, ast.AsyncFor)):
        return {
            n.id for n in ast.walk(loop.target) if isinstance(n, ast.Name)
        }
    names: set[str] = set()
    for stmt in loop.body:
        for n in ast.walk(stmt):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                names.add(n.id)
    return names


@rule(
    "TPU010",
    "recompile-hazard",
    "`.lower().compile()` inside a Python loop body, or a static-argnum "
    "jitted call whose static argument varies with the loop — a fresh "
    "trace+compile per iteration/request",
)
def check_recompile_hazard(module: Module, config: LintConfig) -> Iterator[Finding]:
    """The serving-path cold-start hazard, fenced structurally.

    Two prongs (TPU006 owns the third recompile shape — ``jax.jit``
    *construction* in loops/per-call closures — so it is not repeated
    here):

    - *AOT chains in loops*: ``f.lower(args).compile()`` inside a Python
      ``for``/``while`` compiles a fresh executable every iteration —
      per-request latency in the hundreds of ms to minutes. Deliberate
      warm-up sites (a pool being filled once, a capacity probe walking
      an engine ladder) live in functions named per ``aot-warmup-fns`` /
      ``jit-factory-patterns`` and stay silent; everything else should
      bucket its shapes (``runtime.compile_cache``) or hoist.
    - *Loop-varying statics*: calling a ``jax.jit(g, static_argnums=…)``
      binding with a static-position argument that mentions a name the
      loop rebinds keys the trace cache on a fresh Python value per
      iteration — every call retraces and recompiles. Pass the value as
      a traced operand (the solvers' traced ``limit`` bound is the house
      pattern), or hoist the call.
    """
    statics = _static_jit_bindings(module)
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        if _is_lower_compile_chain(node):
            if _in_python_loop(module, node) and not _enclosing_is_exempt(
                module, node, config
            ):
                yield _finding(
                    module,
                    node,
                    "TPU010",
                    ".lower().compile() inside a Python loop: a fresh "
                    "XLA compile every iteration — bucket the shapes and "
                    "AOT once (runtime.compile_cache), hoist the compile, "
                    "or move it into a warm-up function (aot-warmup-fns) "
                    "if this loop IS the one-time pool fill",
                )
            continue
        if not (isinstance(node.func, ast.Name) and node.func.id in statics):
            continue
        nums, names = statics[node.func.id]
        for loop in module.ancestors(node):
            if not isinstance(loop, (ast.For, ast.While, ast.AsyncFor)):
                continue
            varying = _loop_targets(loop)
            hot_args = [
                arg
                for i, arg in enumerate(node.args)
                if i in nums and module.expr_mentions(arg, varying)
            ] + [
                kw.value
                for kw in node.keywords
                if kw.arg in names
                and module.expr_mentions(kw.value, varying)
            ]
            if hot_args:
                yield _finding(
                    module,
                    hot_args[0],
                    "TPU010",
                    f"static argument of jitted `{node.func.id}` varies "
                    "with the enclosing loop: the dispatch cache keys on "
                    "its Python value, so every iteration retraces and "
                    "recompiles — pass it as a traced operand (the "
                    "solvers' traced `limit` pattern) or hoist the call",
                )
                break


# --------------------------------------------------------------------------
# TPU011 — unfenced timing spans around jitted dispatches
# --------------------------------------------------------------------------

# wall-clock sources whose bracket defines a timing span
_TIMER_CALLS = frozenset({"time.time", "time.perf_counter", "time.monotonic"})


def _is_timer_call(module: Module, node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and (module.qualname(node.func) or "") in _TIMER_CALLS
    )


def _jitted_names(module: Module, config: LintConfig) -> frozenset[str]:
    """Names statically known to hold dispatchable compiled callables:
    bound from a ``jax.jit(...)`` construction, from a
    ``.lower().compile()`` AOT chain, or (tuple-unpacked) from a call to
    a jit factory (``jit-factory-patterns`` — the repo's ``build_*``
    return their jitted solver). Over-approximate on tuple targets: the
    non-callable elements are never *called*, so they cannot fire."""
    out: set[str] = set()
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        if not isinstance(value, ast.Call):
            continue
        leaf = (module.qualname(value.func) or "").rsplit(".", 1)[-1]
        if not (
            module.jit_construction(value) is not None
            or _is_lower_compile_chain(value)
            or any(
                fnmatch.fnmatch(leaf, pat)
                for pat in config.jit_factory_patterns
            )
        ):
            continue
        for target in node.targets:
            out.update(
                n.id for n in ast.walk(target) if isinstance(n, ast.Name)
            )
    return frozenset(out)


def _is_fence_call(module: Module, node: ast.Call, config: LintConfig) -> bool:
    """A call that blocks the host on device work: a configured fence
    wrapper (``host-sync-fns`` — the same allowlist TPU008 treats as a
    per-iteration sync), ``jax.block_until_ready``, or any
    ``.block_until_ready()`` method."""
    q = module.qualname(node.func) or ""
    if _is_fence_wrapper(q, config) or q == "jax.block_until_ready":
        return True
    return (
        isinstance(node.func, ast.Attribute)
        and node.func.attr == "block_until_ready"
    )


@rule(
    "TPU011",
    "unfenced-timing",
    "time.time()/perf_counter() span closing over a jitted dispatch with "
    "no block_until_ready/fence between the dispatch and the clock read",
)
def check_unfenced_timing(module: Module, config: LintConfig) -> Iterator[Finding]:
    """JAX dispatch is asynchronous: ``t0 = perf_counter(); out =
    solver(x); t = perf_counter() - t0`` times the enqueue, not the
    solve — a number that *looks* plausible and is off by the whole
    device execution (the bug class every fenced timing site in
    ``harness.run`` exists to avoid). The rule finds a span —
    ``NAME = <timer>()`` later read as ``<timer>() - NAME`` in the same
    scope — containing a call to a statically-known jitted callable
    (:func:`_jitted_names`) with no fence (``host-sync-fns`` config,
    ``jax.block_until_ready``, or a ``.block_until_ready()`` method —
    the TPU008 fence allowlist, reused) between the LAST such dispatch
    and the closing clock read. Deadline checks (``timer() - t0`` in a
    different function, the guard's pattern) and compile/host-only
    brackets stay silent by construction."""
    jitted = _jitted_names(module, config)
    if not jitted:
        return

    def scope_nodes(scope):
        """Nodes belonging to ``scope`` itself — nested function/lambda
        bodies are their own span scopes (a start in one function and a
        clock read in another is not a span) and are not descended into."""
        skip = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        stack = [n for n in scope.body if not isinstance(n, skip)]
        while stack:
            node = stack.pop()
            yield node
            stack.extend(
                c
                for c in ast.iter_child_nodes(node)
                if not isinstance(c, skip)
            )

    scopes: list[ast.AST] = [module.tree]
    scopes += [
        n
        for n in ast.walk(module.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    emitted: set[tuple[int, int]] = set()
    for scope in scopes:
        starts: dict[str, list[int]] = {}
        closes: list[tuple[int, str, ast.AST]] = []
        jit_lines: list[int] = []
        fence_lines: list[int] = []
        for node in scope_nodes(scope):
            if isinstance(node, ast.Assign) and _is_timer_call(
                module, node.value
            ):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        starts.setdefault(target.id, []).append(node.lineno)
            elif (
                isinstance(node, ast.BinOp)
                and isinstance(node.op, ast.Sub)
                and _is_timer_call(module, node.left)
                and isinstance(node.right, ast.Name)
            ):
                closes.append((node.lineno, node.right.id, node))
            elif isinstance(node, ast.Call):
                if _is_fence_call(module, node, config):
                    fence_lines.append(node.lineno)
                elif (
                    isinstance(node.func, ast.Name)
                    and node.func.id in jitted
                ):
                    jit_lines.append(node.lineno)
        for close_line, name, close_node in closes:
            opened = [ln for ln in starts.get(name, []) if ln < close_line]
            if not opened:
                continue
            start_line = max(opened)
            dispatches = [
                ln for ln in jit_lines if start_line < ln < close_line
            ]
            if not dispatches:
                continue
            last = max(dispatches)
            if any(last <= ln <= close_line for ln in fence_lines):
                continue
            key = (close_node.lineno, close_node.col_offset)
            if key in emitted:
                continue
            emitted.add(key)
            yield _finding(
                module,
                close_node,
                "TPU011",
                f"timing span `{name}` closes over the jitted dispatch at "
                f"line {last} with no fence: dispatch is asynchronous, so "
                "this bracket measured the enqueue, not the device work — "
                "fence the result (utils.timing.fence / "
                "jax.block_until_ready) before reading the clock, or "
                "suppress with a note if the enqueue itself is the "
                "measurement",
            )


# --------------------------------------------------------------------------
# TPU012 — unbounded module/class-level queues in serving/driver code
# --------------------------------------------------------------------------

# container mutations that grow / that bound a queue-shaped binding
_QUEUE_GROW = frozenset(
    {"append", "appendleft", "extend", "extendleft", "insert"}
)
_QUEUE_BOUND = frozenset({"pop", "popleft", "clear", "remove"})


def _queue_ctor(module: Module, node: ast.AST) -> Optional[str]:
    """"list"/"deque" when ``node`` constructs an unbounded growable
    container — ``[]``, ``list()``, ``deque(...)`` with no ``maxlen``,
    or ``dataclasses.field(default_factory=list|deque)`` — else None.
    A ``maxlen`` keyword (or deque's second positional) is the bound
    and silences the rule at the source."""
    if isinstance(node, ast.List) and not node.elts:
        return "list"
    if not isinstance(node, ast.Call):
        return None
    leaf = (module.qualname(node.func) or "").rsplit(".", 1)[-1]
    if leaf == "list" and not node.args and not node.keywords:
        return "list"
    if leaf == "deque":
        if len(node.args) >= 2 or any(
            kw.arg == "maxlen" for kw in node.keywords
        ):
            return None
        return "deque"
    if leaf == "field":
        for kw in node.keywords:
            if (
                kw.arg == "default_factory"
                and isinstance(kw.value, ast.Name)
                and kw.value.id in ("list", "deque")
            ):
                return kw.value.id
    return None


def _attr_is_self(node: ast.AST, attr: str) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and node.attr == attr
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    )


def _shadowing_functions(root: ast.AST, name: str) -> set:
    """Function subtrees within ``root`` where ``name`` is a *local* —
    a parameter or a bare-name assignment target without a ``global``
    declaration. Usage of the bare name inside them refers to the
    local, not the module-level candidate, and must not be smeared
    onto it (a local ``q.append`` is not a leak of the global ``q``,
    and a local ``q.pop`` is not its bound)."""
    shadowing: set = set()
    for fn in ast.walk(root):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        a = fn.args
        params = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            params.append(a.vararg.arg)
        if a.kwarg:
            params.append(a.kwarg.arg)
        rebinds = name in params
        declared_global = False
        # nested defs are classified on their own: prune their whole
        # subtrees, not just the def node — ast.walk would keep yielding
        # their bodies, smearing an inner local rebinding onto this
        # function and silencing real growth in it
        nested = {
            n for n in ast.walk(fn)
            if n is not fn
            and isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        for node in _walk_excluding(fn, nested):
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                declared_global |= name in node.names
            elif isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                rebinds |= any(
                    isinstance(t, ast.Name) and t.id == name
                    for t in targets
                )
        if rebinds and not declared_global:
            shadowing.add(fn)
    return shadowing


def _walk_excluding(root: ast.AST, exclude: set):
    """``ast.walk`` that does not descend into the ``exclude`` nodes."""
    stack = [root]
    while stack:
        node = stack.pop()
        if node in exclude and node is not root:
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _empty_container_expr(node: ast.AST) -> bool:
    """An expression that builds a fresh empty container — the value
    side of the swap-and-reset drain idiom (``out, q = q, []``)."""
    if isinstance(node, (ast.List, ast.Tuple, ast.Set)) and not node.elts:
        return True
    if isinstance(node, ast.Dict) and not node.keys:
        return True
    if isinstance(node, ast.Call) and not node.args:
        leaf = (
            node.func.attr if isinstance(node.func, ast.Attribute)
            else node.func.id if isinstance(node.func, ast.Name)
            else None
        )
        return leaf in ("list", "deque", "set", "dict")
    return False


def _queue_usage(scope: ast.AST, matches,
                 exclude: set = frozenset(),
                 defining: ast.AST | None = None) -> tuple[bool, bool]:
    """(grows, bounded) for a candidate binding within ``scope``.
    ``matches(expr)`` tests whether an expression references the
    binding (a module-level name or a ``self.attr``); ``exclude``
    subtrees (shadowing scopes) are not descended into. Bounds: any
    shrinking method call, ``del q[...]``, a slice/index assignment
    (the windowed-drain idiom), or a rebinding to a fresh empty
    container (the swap-and-reset drain idiom) — ``defining`` is the
    candidate's own initialiser, which must not count as that bound."""
    grows = bounded = False
    for node in _walk_excluding(scope, exclude):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if matches(node.func.value):
                if node.func.attr in _QUEUE_GROW:
                    grows = True
                elif node.func.attr in _QUEUE_BOUND:
                    bounded = True
        elif isinstance(node, ast.Delete):
            for target in node.targets:
                if isinstance(target, ast.Subscript) and matches(
                    target.value
                ):
                    bounded = True
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            # pair each target with its value, unpacking same-length
            # tuple assignments so `out, q = q, []` sees (q, [])
            pairs: list[tuple[ast.AST, ast.AST]] = []
            for target in targets:
                if (
                    isinstance(target, (ast.Tuple, ast.List))
                    and isinstance(node.value, (ast.Tuple, ast.List))
                    and len(target.elts) == len(node.value.elts)
                ):
                    pairs.extend(zip(target.elts, node.value.elts))
                else:
                    pairs.append((target, node.value))
            for target, value in pairs:
                if isinstance(target, ast.Subscript) and matches(
                    target.value
                ):
                    bounded = True
                elif (
                    isinstance(node, ast.Assign)
                    and node is not defining
                    and matches(target)
                    and _empty_container_expr(value)
                ):
                    bounded = True
    return grows, bounded


@rule(
    "TPU012",
    "unbounded-queue",
    "module/class-level list or deque grown by append with no maxlen and "
    "no draining bound — a long-lived serving process leaks memory",
)
def check_unbounded_queue(module: Module, config: LintConfig) -> Iterator[Finding]:
    """The backpressure rule, fenced structurally.

    A request queue, event buffer or result list that lives at module
    or instance scope and only ever grows is fine in a batch job and a
    memory leak in a server: admission without a bound converts
    overload into latency and then into an OOM kill (the failure mode
    ``serve.queue`` exists to prevent — reject loudly with
    ``retry_after`` instead of buffering forever). Candidates are
    *long-lived* bindings only — module-level names and ``self``
    attributes (including ``dataclasses.field(default_factory=list)``)
    initialised to ``[]``/``list()``/``deque()`` without ``maxlen`` —
    that some function then grows (``append``/``extend``/…). Function
    locals are scoped to one call and stay silent. Any visible bound —
    ``deque(maxlen=…)``, a shrinking call (``pop``/``popleft``/
    ``clear``/``remove``), a ``del q[…]`` window trim, or a slice
    assignment — silences the finding: the rule wants *a* bound, not a
    particular one (``obs.metrics.Histogram``'s windowed ``del`` is the
    house pattern)."""
    # module-level names
    for stmt in module.tree.body:
        target = value = None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and (
            isinstance(stmt.targets[0], ast.Name)
        ):
            target, value = stmt.targets[0], stmt.value
        elif isinstance(stmt, ast.AnnAssign) and isinstance(
            stmt.target, ast.Name
        ) and stmt.value is not None:
            target, value = stmt.target, stmt.value
        if target is None:
            continue
        kind = _queue_ctor(module, value)
        if kind is None:
            continue
        name = target.id

        def matches(expr, name=name):
            return isinstance(expr, ast.Name) and expr.id == name

        grows, bounded = _queue_usage(
            module.tree, matches,
            exclude=_shadowing_functions(module.tree, name),
            defining=stmt,
        )
        if grows and not bounded:
            yield _finding(
                module,
                stmt,
                "TPU012",
                f"module-level {kind} `{name}` grows via append with no "
                "bound: a long-lived serving process leaks memory here — "
                "bound it (deque(maxlen=...), a windowed del, a drain) "
                "or shed at admission (serve.queue's backpressure "
                "contract)",
            )
    # class-level / instance attributes
    for cls in ast.walk(module.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        candidates: dict[str, tuple[ast.AST, str]] = {}
        for stmt in cls.body:
            target = value = None
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and (
                isinstance(stmt.targets[0], ast.Name)
            ):
                target, value = stmt.targets[0], stmt.value
            elif isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ) and stmt.value is not None:
                target, value = stmt.target, stmt.value
            if target is None:
                continue
            kind = _queue_ctor(module, value)
            if kind is not None:
                candidates[target.id] = (stmt, kind)
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign):
                pairs = [(t, node.value) for t in node.targets]
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                # `self.q: deque = deque()` — an annotation must not
                # exempt the exact initialiser the rule exists to catch
                pairs = [(node.target, node.value)]
            else:
                continue
            for target, value in pairs:
                if (
                    isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                ):
                    kind = _queue_ctor(module, value)
                    if kind is not None and target.attr not in candidates:
                        candidates[target.attr] = (node, kind)
        for attr, (site, kind) in candidates.items():

            def matches(expr, attr=attr, cls_name=cls.name):
                # self.attr or ClassName.attr — a bare method-local
                # name sharing the attribute's spelling is a different
                # binding and must not be smeared onto it
                return _attr_is_self(expr, attr) or (
                    isinstance(expr, ast.Attribute)
                    and expr.attr == attr
                    and isinstance(expr.value, ast.Name)
                    and expr.value.id == cls_name
                )

            grows, bounded = _queue_usage(cls, matches, defining=site)
            if grows and not bounded:
                yield _finding(
                    module,
                    site,
                    "TPU012",
                    f"instance-level {kind} `{attr}` of class "
                    f"`{cls.name}` grows via append with no bound: every "
                    "request leaves a residue a long-lived server never "
                    "frees — bound it (deque(maxlen=...), a windowed del "
                    "like obs.metrics.Histogram, a drain) or shed at "
                    "admission",
                )


# --------------------------------------------------------------------------
# TPU013 — traced callables rebuilt by host recursion / loop-varying factories
# --------------------------------------------------------------------------


@rule(
    "TPU013",
    "retraced-levels",
    "host-side Python recursion holding a jit/AOT construction, or a "
    "jit-factory call whose argument varies with an enclosing Python "
    "loop — a fresh trace+compile per recursion level / iteration",
)
def check_retraced_levels(module: Module, config: LintConfig) -> Iterator[Finding]:
    """The multigrid-levels recompile hazard, fenced structurally.

    A V-cycle written as host recursion that jits per level — or a
    driver looping over level/engine configurations through a
    ``build_*``/``make_*`` factory — keys a fresh trace on every call,
    so what reads as an O(levels) loop compiles O(levels) executables
    per *solve*. The house contract is the opposite: level count is a
    STATIC config per grid bucket, the recursion unrolls inside ONE
    traced computation (``mg.vcycle``), and factories are called once
    at build time. Two prongs (TPU010 owns the raw ``.lower().compile()``
    -in-loop and static-argnum shapes; TPU006 the jit-construction-in-
    loop shape — neither is repeated here):

    - *recursive trace construction*: a function that calls itself AND
      constructs ``jax.jit`` / a ``.lower().compile()`` chain in its
      body — recursion depth is a runtime value, so each level builds
      its own traced callable with its own empty cache.
    - *loop-varying factory calls*: a call to a jit factory
      (``jit-factory-patterns`` — the names whose return value is a
      compiled callable) inside a Python loop, with an argument that
      mentions a name the loop rebinds: one fresh solver build (trace +
      compile) per iteration. Deliberate build-per-rung sites (warm-up
      pools, capacity/degradation ladders) live in exempt functions
      (``aot-warmup-fns`` / factories) or carry an annotation saying
      why the rebuild IS the point.
    """
    exempt_pats = config.aot_warmup_fns + config.jit_factory_patterns
    for node in ast.walk(module.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if any(fnmatch.fnmatch(node.name, pat) for pat in exempt_pats):
            # a factory's JOB is construction: bounded build-time
            # recursion (the auto-engine chain) is not the hot path
            continue
        calls_self = any(
            isinstance(c, ast.Call)
            and isinstance(c.func, ast.Name)
            and c.func.id == node.name
            for c in ast.walk(node)
        )
        if not calls_self:
            continue
        for c in ast.walk(node):
            if isinstance(c, ast.Call) and (
                module.jit_construction(c) is not None
                or _is_lower_compile_chain(c)
            ):
                yield _finding(
                    module,
                    c,
                    "TPU013",
                    f"recursive `{node.name}` builds a traced callable "
                    "per recursion level: the level count becomes a "
                    "runtime value and every call re-traces — make the "
                    "level list static and unroll the recursion inside "
                    "one traced function (the mg.vcycle pattern)",
                )
                break

    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Name):
            leaf = node.func.id
        elif isinstance(node.func, ast.Attribute):
            leaf = node.func.attr
        else:
            continue
        if not any(
            fnmatch.fnmatch(leaf, pat)
            for pat in config.jit_factory_patterns
        ):
            continue
        # the patterns name PROJECT factories; jax's own make_*/build_*
        # helpers (pltpu.make_async_copy & co.) are in-trace primitives,
        # not trace factories
        if (module.qualname(node.func) or "").startswith("jax."):
            continue
        if _enclosing_is_exempt(module, node, config):
            continue
        for loop in module.ancestors(node):
            if not isinstance(loop, (ast.For, ast.While, ast.AsyncFor)):
                continue
            varying = _loop_targets(loop)
            hot = [
                arg
                for arg in list(node.args)
                + [kw.value for kw in node.keywords]
                if module.expr_mentions(arg, varying)
            ]
            if hot:
                yield _finding(
                    module,
                    hot[0],
                    "TPU013",
                    f"jit factory `{leaf}` called with a loop-varying "
                    "argument: every iteration traces and compiles a "
                    "fresh solver — hoist the build, make the varying "
                    "config static per bucket (runtime.compile_cache), "
                    "or suppress with a note when the per-rung rebuild "
                    "is deliberate (degradation ladders, warm-up fills)",
                )
                break


@rule(
    "TPU009",
    "swallowed-exception",
    "bare/broad `except` whose handler neither re-raises nor calls a "
    "configured classify-and-re-raise helper",
)
def check_swallowed_exception(module: Module, config: LintConfig) -> Iterator[Finding]:
    """A compiled dispatch fails through exactly one channel: the
    exception. XLA's RESOURCE_EXHAUSTED, a Mosaic compile error, a
    poisoned-carry assertion — all arrive as a ``RuntimeError`` a bare
    ``except`` will happily eat, turning a classifiable failure into a
    silently wrong or missing result (the reference's CUDA stages check
    no return codes at all — SURVEY §5; this rule is the regression
    fence for the opposite stance). A broad handler is compliant when
    its body re-raises (anything — the classified ``SolveError``
    taxonomy in ``resilience.errors`` is the house idiom) or hands the
    exception to a ``reraise-fns``-configured helper; genuinely
    deliberate swallows (best-effort accounting, report-the-failure
    rows) carry a ``# tpulint: disable=TPU009`` with a note, exactly
    like every other waived finding."""
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Try):
            continue
        for handler in node.handlers:
            if not _is_broad_handler(module, handler):
                continue
            if _handler_reraises(module, handler, config):
                continue
            label = (
                "bare `except:`"
                if handler.type is None
                else f"`except {ast.unparse(handler.type)}`"
            )
            yield _finding(
                module,
                handler,
                "TPU009",
                f"{label} swallows device-runtime errors: OOM, compile "
                "failures and poisoned-solve exceptions all arrive here "
                "and vanish — re-raise a classified error "
                "(resilience.errors.SolveError), call a reraise-fns "
                "helper, or suppress with a note when the swallow is "
                "deliberate",
            )


# --------------------------------------------------------------------------
# TPU014 — unbounded retry loops with neither backoff nor an attempt cap
# --------------------------------------------------------------------------


def _walk_same_scope(root: ast.AST):
    """Walk a subtree WITHOUT descending into nested function/class
    definitions — their loops and handlers belong to their own scope."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _handler_retries(handler: ast.ExceptHandler) -> bool:
    """True when the handler swallows and lets the loop spin again: no
    raise, no return, no break anywhere in its body (a `continue` or a
    plain fall-through both re-enter the loop)."""
    for node in ast.walk(handler):
        if isinstance(node, (ast.Raise, ast.Return, ast.Break)):
            return False
    return True


def _is_backoff_call(module: Module, node: ast.Call,
                     config: LintConfig) -> bool:
    if isinstance(node.func, ast.Name):
        leaf = node.func.id
    elif isinstance(node.func, ast.Attribute):
        leaf = node.func.attr
    else:
        return False
    q = module.qualname(node.func) or leaf
    return any(
        fnmatch.fnmatch(leaf, pat) or fnmatch.fnmatch(q, pat)
        for pat in config.retry_backoff_fns
    )


def _has_capped_exit(loop: ast.While) -> bool:
    """True when the loop carries a recognizable attempt cap: an `if`
    whose test is a comparison and whose body OR else-arm exits the
    loop (raise / return / break) — both the `if attempt > budget:
    raise` shape and its inverted `if attempt <= budget: continue /
    else: raise` spelling."""
    for node in _walk_same_scope(loop):
        if not isinstance(node, ast.If):
            continue
        if not isinstance(node.test, ast.Compare):
            continue
        for stmt in node.body + node.orelse:
            for sub in ast.walk(stmt):
                if isinstance(sub, (ast.Raise, ast.Return, ast.Break)):
                    return True
    return False


@rule(
    "TPU014",
    "retry-without-backoff",
    "unbounded `while True` retry loop whose handler swallows-and-loops "
    "with neither a backoff call nor an attempt cap",
)
def check_retry_without_backoff(module: Module, config: LintConfig) -> Iterator[Finding]:
    """The retry-storm fence. A serving stack retries by design — the
    scheduler's ladder, the guard's recovery budget — but every one of
    those sites is *paced* (exponential backoff through a sleep/idle
    callable) or *capped* (`attempt > budget` raising a classified
    error). A `while True:` whose `except` swallows the failure and
    loops again with neither is the pattern that turns one failing
    dispatch into a pegged host core and a hammered device runtime —
    and, at pod scale, one sick worker into a thundering herd.

    Conservative by construction: only constant-true `while` loops are
    considered (a tested loop condition is itself a bound); a handler
    "retries" only when its body has no raise/return/break at all; any
    call matching ``retry-backoff-fns`` (``[tool.tpulint]``) counts as
    pacing, and any compare-guarded raise/return/break as a cap.
    Worklist-draining loops whose retry consumes state (the checkpoint
    quarantine walk) carry an annotation saying so, like every other
    waived finding.
    """
    for loop in ast.walk(module.tree):
        if not isinstance(loop, ast.While):
            continue
        test = loop.test
        if not (isinstance(test, ast.Constant) and bool(test.value)):
            continue  # a real condition is a bound; out of scope
        retrying = [
            handler
            for node in _walk_same_scope(loop)
            if isinstance(node, ast.Try)
            for handler in node.handlers
            if _handler_retries(handler)
        ]
        if not retrying:
            continue
        paced = any(
            isinstance(node, ast.Call)
            and _is_backoff_call(module, node, config)
            for node in _walk_same_scope(loop)
        )
        if paced or _has_capped_exit(loop):
            continue
        yield _finding(
            module,
            retrying[0],
            "TPU014",
            "`while True` retry: this handler swallows the failure and "
            "loops again with no backoff call and no attempt cap — a "
            "failing dispatch becomes a hot spin. Pace it (retry-"
            "backoff-fns), cap it (`if attempt > budget: raise`), or "
            "suppress with a note when the retry consumes a finite "
            "worklist",
        )


# --------------------------------------------------------------------------
# TPU015 — host round-trips on traced / xp-dual geometry values
# --------------------------------------------------------------------------

_ROUNDTRIP_CALLS = frozenset({"float", "int", "bool"})
_ROUNDTRIP_METHODS = frozenset({"item", "tolist"})


def _xp_dual_fns(module: Module) -> Iterator[TracedFn]:
    """Functions following the repo's ``xp=`` array-module convention
    (``models.ellipse`` / ``geom.sdf``): one body serving BOTH the
    host-f64 numpy path and the traced jnp path. Their array parameters
    get the same taint treatment as a jitted function's — a host
    round-trip in one breaks the traced half of the contract."""
    for fn in module.functions.values():
        a = fn.args
        names = [p.arg for p in getattr(a, "posonlyargs", [])]
        names += [p.arg for p in a.args] + [p.arg for p in a.kwonlyargs]
        if "xp" not in names:
            continue
        # xp itself (and self) are module/instance handles, not data;
        # default-valued parameters are config scalars (samples=16), not
        # the coordinate arrays the dual-path contract is about
        static = {"xp", "self"}
        pos = [p.arg for p in getattr(a, "posonlyargs", [])] + [
            p.arg for p in a.args
        ]
        if a.defaults:
            static.update(pos[len(pos) - len(a.defaults):])
        for p, d in zip(a.kwonlyargs, a.kw_defaults):
            if d is not None:
                static.add(p.arg)
        yield TracedFn(fn, "xp-dual", frozenset(static))


@rule(
    "TPU015",
    "host-roundtrip",
    "float()/int()/bool()/.item() on a value derived from a traced or "
    "xp-dual function's array parameters — a host round-trip where the "
    "computation must stay pure",
)
def check_host_roundtrip(module: Module, config: LintConfig) -> Iterator[Finding]:
    """The geometry-purity fence. Admissibility validation runs on HOST
    float64 arrays by contract (``geom.validate``), and the traced
    assembly/solve path must stay pure — so any ``float(x)`` /
    ``int(x)`` / ``bool(x)`` / ``x.item()`` / ``x.tolist()`` applied to
    a value derived from the array parameters of a *traced* function
    (jit-decorated, jit-wrapped, or a lax loop body) or of an
    ``xp=``-dual geometry function is a bug by construction: under jit
    it raises ``ConcretizationTypeError`` at best (and forces a silent
    device sync at worst), and on the host path it silently collapses
    an f64 array fact into one Python scalar.

    Conservative by the registry's standing rules: only direct calls on
    expressions whose taint is established by the shallow forward taint
    of ``Module.tainted_names`` — static facts (``x.shape``,
    ``len(x)``) never taint, and untraced host drivers (the guard's
    chunk loop, the harness) are out of scope. Lax loop BODIES are
    TPU008's domain (one defect, one code): this rule keeps the
    jit-def/jit-call surface and the xp-dual geometry functions.
    """
    fns = [f for f in module.traced_fns if f.kind != "loop-body"]
    fns += list(_xp_dual_fns(module))
    seen_nodes: set[int] = set()
    for fn in fns:
        if id(fn.node) in seen_nodes:
            continue
        seen_nodes.add(id(fn.node))
        tainted = module.tainted_names(fn)
        if not tainted:
            continue
        body = fn.node.body if isinstance(fn.node.body, list) else [fn.node.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                if (
                    isinstance(node.func, ast.Name)
                    and node.func.id in _ROUNDTRIP_CALLS
                    and len(node.args) == 1
                    and module.expr_mentions(node.args[0], tainted)
                ):
                    name = getattr(fn.node, "name", "<lambda>")
                    yield _finding(
                        module,
                        node,
                        "TPU015",
                        f"`{node.func.id}(...)` on a value derived from "
                        f"the array parameters of `{name}` — a host "
                        "round-trip inside a traced/xp-dual computation. "
                        "Keep the computation in array ops; do host "
                        "conversions in the (untraced) caller on host "
                        "arrays",
                    )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _ROUNDTRIP_METHODS
                    and not node.args
                    and module.expr_mentions(node.func.value, tainted)
                ):
                    name = getattr(fn.node, "name", "<lambda>")
                    yield _finding(
                        module,
                        node,
                        "TPU015",
                        f"`.{node.func.attr}()` on a value derived from "
                        f"the array parameters of `{name}` — a host "
                        "round-trip inside a traced/xp-dual computation. "
                        "Keep the computation in array ops; do host "
                        "conversions in the (untraced) caller on host "
                        "arrays",
                    )


# --------------------------------------------------------------------------
# TPU016 — wall-clock time feeding lease/deadline/timeout comparisons
# --------------------------------------------------------------------------


def _wall_clock_calls(module: Module, root: ast.AST) -> list[ast.Call]:
    """Every ``time.time()`` call in ``root``'s subtree."""
    return [
        node
        for node in ast.walk(root)
        if isinstance(node, ast.Call)
        and (module.qualname(node.func) or "") == "time.time"
    ]


def _is_ordering_compare(node: ast.Compare) -> bool:
    """A deadline check is an ORDERING comparison (<, <=, >, >=): an
    identity/equality/membership test (``is None`` lazy-init guards,
    ``rid in finished``) reads a value, not a clock order, and must not
    turn a record-only timestamp into a finding."""
    return any(
        isinstance(op, (ast.Lt, ast.LtE, ast.Gt, ast.GtE))
        for op in node.ops
    )


def _name_compared_in(scope_root: ast.AST, name: str,
                      exclude: set = frozenset()) -> bool:
    """Is ``name`` read inside any ORDERING comparison within
    ``scope_root``, excluding the ``exclude`` subtrees (scopes where
    the spelling is a different local binding — the TPU012 shadowing
    discipline, reused)?"""
    for node in _walk_excluding(scope_root, exclude):
        if not isinstance(node, ast.Compare) or not _is_ordering_compare(node):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id == name:
                return True
    return False


def _self_attr_compared(scope_root: ast.AST, attr: str) -> bool:
    """Is ``self.<attr>`` read inside any comparison within
    ``scope_root`` — the assignment's enclosing CLASS (methods share
    the instance, so attribute deadlines are class-wide), or the module
    for a classless ``self`` oddity? Another class's same-named
    attribute is a different instance's slot and must not be smeared
    onto a record-only timestamp here."""
    for node in ast.walk(scope_root):
        if not isinstance(node, ast.Compare) or not _is_ordering_compare(
            node
        ):
            continue
        for sub in ast.walk(node):
            if _attr_is_self(sub, attr):
                return True
    return False


def _enclosing_class(module: Module, node: ast.AST):
    """The innermost ClassDef enclosing ``node`` (None outside one)."""
    innermost = None
    for anc in module.ancestors(node):
        if isinstance(anc, ast.ClassDef):
            if innermost is None or anc.lineno >= innermost.lineno:
                innermost = anc
    return innermost


@rule(
    "TPU016",
    "wall-clock-deadline",
    "time.time() feeding a comparison used as a lease/deadline/timeout — "
    "NTP steps make wall-clock deadlines fire early or never; use "
    "time.monotonic()",
)
def check_wall_clock_deadline(module: Module, config: LintConfig) -> Iterator[Finding]:
    """The lease-correctness fence the fleet layer is built on
    (``fleet.replica``): a lease, deadline or timeout computed from
    ``time.time()`` is one NTP step away from firing years early (a
    backward step fences a healthy replica and hands its work off
    twice) or never (a forward step keeps a dead one's lease alive
    forever). ``time.monotonic()`` is immune by construction, which is
    why every clock in ``serve``/``fleet`` is injectable monotonic.

    Two conservative prongs — a wall-clock read that is merely
    *recorded* (a ``t_admit_unix`` journal field, a trace record's
    ``unix_time``) is a timestamp, not a deadline, and stays silent:

    - **compared directly** — a ``time.time()`` call anywhere inside a
      comparison (``if time.time() > deadline``, ``time.time() - t0 >
      timeout``): the comparison IS the deadline check.
    - **bound then compared** — a name (or ``self`` attribute) assigned
      an expression containing ``time.time()`` (``deadline =
      time.time() + lease_s``) that is later read inside some
      comparison in the same module: the binding feeds a deadline even
      though the compare sits elsewhere.
    """
    emitted: set[tuple[int, int]] = set()

    def once(finding):
        key = (finding.line, finding.col)
        if key not in emitted:
            emitted.add(key)
            yield finding

    # prong 1: time.time() inside an ORDERING comparison (equality/
    # membership/identity tests read values, not clock order)
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Compare) or not _is_ordering_compare(
            node
        ):
            continue
        for call in _wall_clock_calls(module, node):
            yield from once(_finding(
                module,
                call,
                "TPU016",
                "`time.time()` inside a comparison: this is a "
                "wall-clock deadline/timeout check, and an NTP step "
                "makes it fire early or never — use `time.monotonic()` "
                "for every lease/deadline/timeout comparison "
                "(timestamps that are only recorded may stay on the "
                "wall clock)",
            ))

    # prong 2: NAME/self.ATTR = <expr containing time.time()>, with the
    # binding later read inside a comparison the binding is VISIBLE to —
    # a function-local `t0` compared in some other function's scope is a
    # different binding and must not be smeared onto this one (the
    # TPU012 shadowing discipline, reused)
    for node in ast.walk(module.tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            continue
        value = node.value
        if value is None:
            continue
        calls = _wall_clock_calls(module, value)
        if not calls:
            continue
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        # direct Name / self.ATTR targets only (tuple-unpacked included):
        # a subscript target (`records[rid] = {..., time.time()}`) binds
        # a container ITEM no comparison can read by name — walking its
        # index expression would smear unrelated compared names (a
        # `rid in finished` membership test) onto a record-only
        # timestamp, which is exactly the false positive that gets a
        # lint gate deleted from CI
        flat: list[ast.AST] = []
        for target in targets:
            if isinstance(target, (ast.Tuple, ast.List)):
                flat.extend(target.elts)
            else:
                flat.append(target)
        enclosing = module.enclosing_function(node)
        hot = False
        for t in flat:
            if isinstance(t, ast.Name):
                scope = enclosing if enclosing is not None else module.tree
                if _name_compared_in(
                    scope, t.id, _shadowing_functions(scope, t.id)
                ):
                    hot = True
            elif _attr_is_self(t, getattr(t, "attr", "")):
                cls = _enclosing_class(module, node)
                if _self_attr_compared(
                    cls if cls is not None else module.tree, t.attr
                ):
                    hot = True
        if not hot:
            continue
        for call in calls:
            yield from once(_finding(
                module,
                call,
                "TPU016",
                "`time.time()` feeds a binding later used in a "
                "comparison: a wall-clock lease/deadline — an NTP step "
                "makes it fire early or never. Compute deadlines from "
                "`time.monotonic()`; keep wall-clock reads for "
                "record-only timestamps",
            ))


# --------------------------------------------------------------------------
# TPU017 — reverse-mode autodiff over a while_loop-based solver entry
# --------------------------------------------------------------------------

# the reverse-mode entries: these stage a backward pass over their
# target. jax.jvp/jacfwd are forward-mode (while_loop supports them)
# and stay out of scope.
_REVERSE_AD_ENTRIES = frozenset({
    "jax.grad", "jax.value_and_grad", "jax.vjp", "jax.jacrev",
    "jax.hessian",
})


def _matches_fn(module: Module, node: ast.AST,
                patterns: tuple[str, ...]) -> bool:
    """Does a callee expression match any pattern — by resolved
    qualname or by leaf name (``solver.pcg`` matches ``pcg``)?"""
    q = module.qualname(node) or ""
    leaf = ""
    if isinstance(node, ast.Name):
        leaf = node.id
    elif isinstance(node, ast.Attribute):
        leaf = node.attr
    return any(
        fnmatch.fnmatch(q, pat) or fnmatch.fnmatch(leaf, pat)
        for pat in patterns
    )


def _resolve_grad_target(module: Module, target: ast.AST):
    """What reverse-mode will differentiate through, when statically
    visible: ``("direct", node)`` for a bare callee reference (an
    imported/attribute solver name — checked against the patterns by
    name), ``("body", ast)`` for a lambda or locally-defined function
    (checked by walking the body), recursing through a
    ``functools.partial``'s first argument either way. None when the
    target is opaque (a computed expression) — the registry's
    conservative stance."""
    if isinstance(target, ast.Lambda):
        return ("body", target.body)
    if isinstance(target, ast.Name):
        fn = module.functions.get(target.id)
        if fn is not None:
            return ("body", fn)
        return ("direct", target)
    if isinstance(target, ast.Attribute):
        return ("direct", target)
    if isinstance(target, ast.Call) and target.args:
        q = module.qualname(target.func) or ""
        if q in ("functools.partial", "partial"):
            return _resolve_grad_target(module, target.args[0])
    return None


@rule(
    "TPU017",
    "backprop-through-loop",
    "reverse-mode autodiff (jax.grad/jax.vjp/...) applied to a "
    "while_loop-based solver entry without the implicit custom_vjp "
    "wrapper — no reverse rule for while_loop, and an unroll "
    "backpropagates through thousands of iterations",
)
def check_backprop_through_loop(module: Module,
                                config: LintConfig) -> Iterator[Finding]:
    """The differentiable-solving fence. Every solver entry in this
    repo binds its iteration as a fused ``lax.while_loop`` — which has
    NO reverse-mode rule: ``jax.grad`` over one either raises at trace
    time (dynamic trip count) or, rewritten to a scanned/unrolled loop,
    stores every iterate of a thousand-iteration solve. The correct
    route is the implicit-function-theorem wrapper
    (``diff.adjoint.solve_implicit`` / ``ImplicitSolver``): one extra
    PCG solve with the same operator.

    Conservative per the registry's standing rules: a finding needs a
    reverse-mode entry (``jax.grad``/``value_and_grad``/``vjp``/
    ``jacrev``/``hessian``) whose target is statically visible (a
    lambda, a local def, a direct solver-entry reference, or a
    ``functools.partial`` of one) and binds a configured
    ``loop-solver-fns`` callee; a target that also touches one of the
    ``implicit-solver-fns`` is routing through the wrapper and stays
    silent. Opaque targets are skipped, not guessed at.
    """
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        q = module.qualname(node.func) or ""
        if q not in _REVERSE_AD_ENTRIES:
            continue
        resolved = _resolve_grad_target(module, node.args[0])
        if resolved is None:
            continue
        kind, payload = resolved
        if kind == "direct":
            # bare callee reference, possibly through a partial:
            # jax.grad(pcg) / jax.vjp(functools.partial(pcg, problem))
            if not _matches_fn(module, payload, config.loop_solver_fns):
                continue
            solver_name = (
                payload.id if isinstance(payload, ast.Name)
                else payload.attr
            )
        else:
            hits = []
            routed = False
            for sub in ast.walk(payload):
                if not isinstance(sub, ast.Call):
                    continue
                if _matches_fn(module, sub.func, config.implicit_solver_fns):
                    routed = True
                    break
                if _matches_fn(module, sub.func, config.loop_solver_fns):
                    hits.append(sub)
            if routed or not hits:
                continue
            first = hits[0].func
            solver_name = (
                first.id if isinstance(first, ast.Name)
                else getattr(first, "attr", "<solver>")
            )
        entry = q.rsplit(".", 1)[1]
        yield _finding(
            module,
            node,
            "TPU017",
            f"`jax.{entry}` over `{solver_name}` backpropagates through "
            "a `lax.while_loop` solver iteration — no reverse rule "
            "(trace error) or an unbounded-memory unroll. Differentiate "
            "through the IFT wrapper instead "
            "(`diff.adjoint.solve_implicit` / `ImplicitSolver.solve`: "
            "the adjoint is one extra solve with the same operator)",
        )


# --------------------------------------------------------------------------
# TPU018 — half-width values flowing into a reduction without a wide
# accumulator route
# --------------------------------------------------------------------------

# dtype spellings that mean "16-bit float" — the storage widths whose
# accumulation error grows like n·2⁻⁸ instead of n·2⁻²⁴
_NARROW_DTYPE_LEAVES = frozenset({"bfloat16", "float16"})
_NARROW_DTYPE_STRINGS = frozenset({"bfloat16", "float16", "bf16", "f16"})
_WIDE_DTYPE_LEAVES = frozenset({"float32", "float64"})
_WIDE_DTYPE_STRINGS = frozenset({"float32", "float64", "f32", "f64"})

# built-in reduction sinks (the TPU007 reduction_roots knob extends the
# set with a project's own grid_dot-style wrappers)
_REDUCTION_SINKS = frozenset({
    "jax.numpy.sum", "jax.numpy.mean", "jax.numpy.dot", "jax.numpy.vdot",
    "jax.numpy.einsum", "jax.numpy.matmul", "jax.numpy.tensordot",
    "jax.numpy.inner", "jax.lax.psum", "numpy.sum", "numpy.dot",
    "numpy.einsum",
})


def _dtype_class(module: Module, node: ast.AST) -> Optional[str]:
    """"narrow" / "wide" / None for a dtype expression, when statically
    visible (an attribute like jnp.bfloat16, or a string literal)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        if node.value in _NARROW_DTYPE_STRINGS:
            return "narrow"
        if node.value in _WIDE_DTYPE_STRINGS:
            return "wide"
        return None
    leaf = None
    if isinstance(node, ast.Attribute):
        leaf = node.attr
    elif isinstance(node, ast.Name):
        leaf = node.id
    if leaf in _NARROW_DTYPE_LEAVES:
        return "narrow"
    if leaf in _WIDE_DTYPE_LEAVES:
        return "wide"
    return None


def _astype_class(module: Module, node: ast.AST) -> Optional[str]:
    """The dtype class of an ``x.astype(...)`` call, else None."""
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "astype"
        and node.args
    ):
        return _dtype_class(module, node.args[0])
    return None


def _expr_is_narrow(module: Module, node: ast.AST,
                    narrow_names: set) -> bool:
    """Does this expression statically carry a 16-bit float value all
    the way to its root? Conservative: anything unresolvable reads as
    not-narrow (the registry's stay-silent stance). An inner
    ``.astype(f32/f64)`` re-widens the value and stops the flow."""
    cls = _astype_class(module, node)
    if cls == "narrow":
        return True
    if cls == "wide":
        return False
    if isinstance(node, ast.Name):
        return node.id in narrow_names
    if isinstance(node, ast.BinOp):
        left = _expr_is_narrow(module, node.left, narrow_names)
        right = _expr_is_narrow(module, node.right, narrow_names)
        if left and right:
            return True
        # narrow ∘ python-scalar stays narrow under weak-type promotion;
        # narrow ∘ wide promotes wide (not a finding)
        if left and isinstance(node.right, ast.Constant):
            return True
        if right and isinstance(node.left, ast.Constant):
            return True
        return False
    if isinstance(node, ast.UnaryOp):
        return _expr_is_narrow(module, node.operand, narrow_names)
    if isinstance(node, ast.Subscript):
        return _expr_is_narrow(module, node.value, narrow_names)
    if isinstance(node, ast.Call):
        # abs/negative-style elementwise wrappers keep the dtype; treat
        # only jnp.abs / abs conservatively, everything else opaque
        q = module.qualname(node.func) or ""
        if q in ("jax.numpy.abs", "abs") and node.args:
            return _expr_is_narrow(module, node.args[0], narrow_names)
        return False
    return False


def _scan_scope_tpu018(module: Module, config: LintConfig, body,
                       mixed_fns: tuple[str, ...]):
    """Walk one scope's statements in order, tracking names bound to
    narrow values, yielding reductions fed by them."""
    reduction_roots = tuple(_REDUCTION_SINKS) + tuple(config.reduction_roots)
    narrow_names: set = set()
    for stmt in body:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            q = module.qualname(node.func) or ""
            leaf = (
                node.func.attr if isinstance(node.func, ast.Attribute)
                else getattr(node.func, "id", "")
            )
            if _matches_fn(module, node.func, mixed_fns):
                continue  # a blessed wide-accumulator route
            is_sink = any(
                fnmatch.fnmatch(q, pat) or fnmatch.fnmatch(leaf, pat)
                for pat in reduction_roots
            ) or q in _REDUCTION_SINKS
            if not is_sink:
                continue
            # an explicit wide accumulator silences the sink
            if any(
                kw.arg == "dtype"
                and _dtype_class(module, kw.value) == "wide"
                for kw in node.keywords
            ):
                continue
            for arg in node.args:
                if _expr_is_narrow(module, arg, narrow_names):
                    yield node, leaf or q
                    break
        # statement-order narrowness tracking (after scanning: a
        # reduction inside the RHS sees the PRE-assignment bindings)
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name):
            name = stmt.targets[0].id
            if _expr_is_narrow(module, stmt.value, narrow_names):
                narrow_names.add(name)
            else:
                narrow_names.discard(name)


@rule(
    "TPU018",
    "silent-downcast",
    "a bf16/f16 value (an .astype(bfloat16/float16) result, or "
    "arithmetic over such values) flows into a reduction with no "
    "f32/f64 accumulator route — the sum accumulates at 8 mantissa "
    "bits and loses digits linearly in n",
)
def check_silent_downcast(module: Module,
                          config: LintConfig) -> Iterator[Finding]:
    """The storage-vs-compute precision fence (``ops.precision``). The
    bf16-storage contract is narrow in HBM, WIDE in every accumulator:
    a reduction whose operand tree is statically 16-bit (an
    ``.astype(jnp.bfloat16)``/"bf16" result, a name bound to one, or
    arithmetic over such values) accumulates at 8 mantissa bits —
    round-off grows like n·2⁻⁸ and a grid-sized sum is wrong in the
    third digit. The route out is an upcast before the reduction
    (``.astype(jnp.float32)``, fused by XLA into the consumer — free on
    the HBM side), an explicit ``dtype=jnp.float32`` accumulator on the
    reduction itself, or one of the configured ``mixed-accum-fns`` —
    the project's sanctioned mixed-precision reducers (the Pallas mixed
    kernels, ``ops.precision``'s load/store helpers).

    Conservative per the registry's standing rules: dtypes must be
    statically visible (attribute or string literal), unresolvable
    expressions read as not-narrow, and only same-scope, statement-
    ordered name bindings propagate narrowness.
    """
    mixed_fns = config.mixed_accum_fns
    scopes = [module.tree.body]
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scopes.append(node.body)
    seen: set = set()
    for body in scopes:
        for call, sink in _scan_scope_tpu018(module, config, body,
                                             mixed_fns):
            key = (call.lineno, call.col_offset)
            if key in seen:
                continue
            seen.add(key)
            yield _finding(
                module,
                call,
                "TPU018",
                f"`{sink}` reduces a bf16/f16-typed operand with no "
                "f32/f64 accumulator route — 8 mantissa bits lose "
                "digits linearly in element count. Upcast first "
                "(`.astype(jnp.float32)` fuses into the consumer: the "
                "HBM read stays narrow), pass `dtype=jnp.float32` to "
                "the reduction, or route through a `mixed-accum-fns` "
                "helper (ops.precision / the mixed Pallas kernels)",
            )


# --------------------------------------------------------------------------
# TPU019 — numeric literals hardcoding tunable solver knobs at call sites
# --------------------------------------------------------------------------

# the knob vocabulary: keyword names that select engine configurations
# the autotuner owns (solver.engine.ENGINE_CAPS tunables + the serve
# chunk axis). A literal bound to one of these at a builder call site
# freezes a choice the closed loop exists to make.
_TUNABLE_KWARGS = frozenset({
    "cheb_degree", "coarse_degree", "nu", "levels", "n_vcycles",
    "sstep_s", "chunk", "degree",
})

# enclosing-function shapes where a knob literal IS the registry: the
# static defaults the tuner scores against (default_*/resolve_*_config
# constructors) and the tuner's own candidate sweeps (tune*/candidates)
_TUNABLE_EXEMPT_FNS = ("default_*", "resolve_*_config", "tune*",
                       "candidates", "*_config")


def _enclosing_fn_name(module: Module, node: ast.AST) -> str:
    """Name of the innermost enclosing function definition, or ''
    (the visitor's parent links; lambdas are anonymous, keep walking)."""
    for anc in module.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc.name
    return ""


@rule(
    "TPU019",
    "hardcoded-tunable",
    "a bare numeric literal bound to a tunable knob keyword at a "
    "solver-builder call site — the autotune registry can neither see "
    "nor overrule it",
)
def check_hardcoded_tunable(module: Module,
                            config: LintConfig) -> Iterator[Finding]:
    """The autotuning fence (``runtime.autotune``). The engine zoo's
    knobs — Chebyshev degree, MG depth/ν/coarse degree, F-cycle
    correction count, s-step block size, serve chunk — are selected per
    shape by the closed-loop tuner and recorded once in the
    engine-capability table (``solver.engine.ENGINE_CAPS``). A numeric
    literal bound to one of those keywords at a builder call site
    (``tunable-fns``) silently pins the choice where neither the table
    nor the registry can reach it: the tuned config loads, the literal
    wins, and the regression gate blames the wrong layer.

    Compliant routes: a named constant (module UPPERCASE or a config
    dataclass field), the capability table's ``tunables`` row, or a
    value threaded from the tuned-config registry. Exemptions keep the
    registry definable at all: the autotune module itself, and
    default-config constructors / tuner candidate sweeps
    (``default_*`` / ``resolve_*_config`` / ``tune*`` / ``candidates``)
    — the one place a static default's literal must live.
    """
    norm_path = module.path.replace(os.sep, "/")
    if norm_path.endswith("runtime/autotune.py"):
        return  # the registry itself: candidate sweeps ARE literals
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        if not _matches_fn(module, node.func, config.tunable_fns):
            continue
        hits = [
            kw for kw in node.keywords
            if kw.arg in _TUNABLE_KWARGS
            and isinstance(kw.value, ast.Constant)
            and isinstance(kw.value.value, (int, float))
            and not isinstance(kw.value.value, bool)
        ]
        if not hits:
            continue
        enclosing = _enclosing_fn_name(module, node)
        if any(fnmatch.fnmatch(enclosing, pat)
               for pat in _TUNABLE_EXEMPT_FNS):
            continue
        leaf = (
            node.func.attr if isinstance(node.func, ast.Attribute)
            else getattr(node.func, "id", "<call>")
        )
        for kw in hits:
            yield _finding(
                module,
                kw.value,
                "TPU019",
                f"`{leaf}(... {kw.arg}={kw.value.value!r})` hardcodes a "
                "tunable knob at a builder call site — the autotuner "
                "(runtime.autotune) and the engine-capability table "
                "(solver.engine.ENGINE_CAPS) can neither see nor "
                "overrule it. Route the value through a named "
                "constant, the table's tunables row, or the tuned-"
                "config registry",
            )


# --------------------------------------------------------------------------
# TPU020 — raw collectives outside the blessed communication modules
# --------------------------------------------------------------------------

_COLLECTIVE_FNS = frozenset({
    "jax.lax.psum", "jax.lax.pmean", "jax.lax.pmax", "jax.lax.pmin",
    "jax.lax.ppermute", "jax.lax.pshuffle", "jax.lax.psum_scatter",
    "jax.lax.all_gather", "jax.lax.all_to_all",
})


@rule(
    "TPU020",
    "raw-collective",
    "a raw jax.lax collective issued outside the blessed communication "
    "modules (`collective-modules`) — the contract matrix's cadence "
    "budgets cannot account for it",
)
def check_raw_collective(module: Module,
                         config: LintConfig) -> Iterator[Finding]:
    """The communication-layer fence. The engine zoo's collective
    cadences — 2 psums per classical body, ONE per pipelined body, the
    ``halos_per_precond`` ppermute budgets — are declared in
    ``ENGINE_CAPS`` and pinned by the contract matrix (``analysis/``)
    over the builders in ``parallel/``. A ``lax.psum``/``lax.ppermute``
    issued from any other module joins a traced computation those
    budgets never swept: the count drifts, the matrix stays green, and
    the regression surfaces as a multichip perf mystery instead of a
    lint line.

    ``collective-modules`` (path fnmatch patterns) names the licensed
    layer — ``parallel/`` by default. Deliberate exceptions (a
    bandwidth probe measuring the collective itself) carry a
    ``# tpulint: disable=TPU020`` with the justification. Anonymous
    sources (``<snippet>``) are skipped: a path-classified rule cannot
    place them in a layer.
    """
    if module.path == "<snippet>":
        return
    norm_path = module.path.replace(os.sep, "/")
    if any(
        fnmatch.fnmatch(norm_path, pat)
        for pat in config.collective_modules
    ):
        return
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        q = module.qualname(node.func)
        if q in _COLLECTIVE_FNS:
            yield _finding(
                module,
                node,
                "TPU020",
                f"raw `{q.removeprefix('jax.')}` outside the "
                "communication layer — the contract matrix's cadence "
                "budgets (analysis/, ENGINE_CAPS) only sweep "
                "`collective-modules`; route the exchange through "
                "parallel/ or annotate the deliberate exception",
            )


# --------------------------------------------------------------------------
# TPU021 — wall-clock reads feeding lease/deadline/duration ARITHMETIC
# --------------------------------------------------------------------------

# the arithmetic operators that turn a clock read into a deadline or a
# duration (unary ops and bit ops read as something else entirely)
_ARITH_OPS = (ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv, ast.Mod)


def _config_wall_clock_calls(module: Module, root: ast.AST,
                             config: LintConfig) -> list[ast.Call]:
    """Every call of a configured wall-clock source (`wall-clock-fns`)
    in ``root``'s subtree."""
    out = []
    for node in ast.walk(root):
        if not isinstance(node, ast.Call):
            continue
        q = module.qualname(node.func) or ""
        if any(fnmatch.fnmatch(q, pat) for pat in config.wall_clock_fns):
            out.append(node)
    return out


def _arith_ancestor(module: Module, node: ast.AST) -> Optional[ast.BinOp]:
    for anc in module.ancestors(node):
        if isinstance(anc, ast.BinOp) and isinstance(anc.op, _ARITH_OPS):
            return anc
    return None


def _inside_ordering_compare(module: Module, node: ast.AST) -> bool:
    return any(
        isinstance(anc, ast.Compare) and _is_ordering_compare(anc)
        for anc in module.ancestors(node)
    )


def _name_in_arith(scope_root: ast.AST, name: str,
                   exclude: set = frozenset()) -> bool:
    """Is ``name`` read as an operand of arithmetic within
    ``scope_root`` (same shadowing discipline as TPU016's
    :func:`_name_compared_in`)?"""
    for node in _walk_excluding(scope_root, exclude):
        if not isinstance(node, ast.BinOp) or not isinstance(
            node.op, _ARITH_OPS
        ):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id == name:
                return True
    return False


def _self_attr_in_arith(scope_root: ast.AST, attr: str) -> bool:
    for node in ast.walk(scope_root):
        if not isinstance(node, ast.BinOp) or not isinstance(
            node.op, _ARITH_OPS
        ):
            continue
        for sub in ast.walk(node):
            if _attr_is_self(sub, attr):
                return True
    return False


@rule(
    "TPU021",
    "wall-clock-lease",
    "a wall-clock read (time.time()/datetime.now()) feeding lease/"
    "deadline/duration ARITHMETIC — NTP steps the clock mid-computation; "
    "compute spans and deadlines from time.monotonic()",
)
def check_wall_clock_lease(module: Module,
                           config: LintConfig) -> Iterator[Finding]:
    """TPU016's arithmetic sibling. TPU016 fires when a wall-clock read
    reaches a COMPARISON (the deadline check itself); this rule fires
    one step earlier, when the read feeds lease/deadline/duration
    ARITHMETIC — ``deadline = time.time() + lease_s``,
    ``elapsed = datetime.now() - started`` — whether or not the result
    is ever compared in this module. The computed value is already
    wrong the instant NTP steps the clock: handed to a peer process, a
    trace record used for pacing, or a retry budget, it fires early or
    never with no comparison in sight for TPU016 to catch. The scopes
    are disjoint by construction: a read inside an ordering comparison
    is TPU016's finding and skipped here.

    Two prongs, mirroring TPU016's, same conservative stance — a bare
    recorded timestamp (``"t_admit_unix": time.time()``, a trace
    record's ``unix_time``) touches no arithmetic and stays silent:

    - **arithmetic directly** — a configured wall-clock call
      (`wall-clock-fns`: ``time.time``, ``datetime.now``/``utcnow`` by
      default) that is an operand of ``+ - * / // %``.
    - **bound then arithmetic** — a name (or ``self`` attribute)
      assigned from a wall-clock read, later used as an arithmetic
      operand visible to that binding (the TPU012 shadowing
      discipline, reused via TPU016's machinery).
    """
    emitted: set[tuple[int, int]] = set()

    def once(finding):
        key = (finding.line, finding.col)
        if key not in emitted:
            emitted.add(key)
            yield finding

    # prong 1: the wall-clock call itself is an arithmetic operand —
    # unless the whole expression sits inside an ordering comparison,
    # which is TPU016's finding (the scopes stay disjoint)
    for call in _config_wall_clock_calls(module, module.tree, config):
        if _arith_ancestor(module, call) is None:
            continue
        if _inside_ordering_compare(module, call):
            continue
        q = module.qualname(call.func)
        yield from once(_finding(
            module,
            call,
            "TPU021",
            f"`{q}()` feeds lease/deadline/duration arithmetic: an NTP "
            "step lands inside the computed value — compute spans and "
            "deadlines from `time.monotonic()` and keep wall-clock "
            "reads for record-only timestamps",
        ))

    # prong 2: NAME/self.ATTR = <wall-clock read>, with the binding
    # later an arithmetic operand in a scope the binding is visible to
    for node in ast.walk(module.tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            continue
        value = node.value
        if value is None:
            continue
        calls = _config_wall_clock_calls(module, value, config)
        if not calls:
            continue
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        flat: list[ast.AST] = []
        for target in targets:
            if isinstance(target, (ast.Tuple, ast.List)):
                flat.extend(target.elts)
            else:
                flat.append(target)
        enclosing = module.enclosing_function(node)
        hot = False
        for t in flat:
            if isinstance(t, ast.Name):
                scope = enclosing if enclosing is not None else module.tree
                if _name_in_arith(
                    scope, t.id, _shadowing_functions(scope, t.id)
                ):
                    hot = True
            elif _attr_is_self(t, getattr(t, "attr", "")):
                cls = _enclosing_class(module, node)
                if _self_attr_in_arith(
                    cls if cls is not None else module.tree, t.attr
                ):
                    hot = True
        if not hot:
            continue
        for call in calls:
            yield from once(_finding(
                module,
                call,
                "TPU021",
                "wall-clock read bound to a name later used in "
                "arithmetic: the computed lease/deadline/duration is "
                "stepped by NTP before anything compares it — bind "
                "`time.monotonic()` for anything that feeds arithmetic",
            ))


# --------------------------------------------------------------------------
# TPU022 — unbounded dict caches in long-lived serving/runtime code
# --------------------------------------------------------------------------

# bindings whose NAME declares cache intent — the conservative gate: a
# dict that is not named like a cache is somebody's data structure, not
# this rule's business (a lint gate that cries wolf gets deleted)
_CACHE_NAME_MARKERS = ("cache", "memo", "pool")

# dict mutations that grow / that evict
_CACHE_GROW = frozenset({"setdefault", "update"})
_CACHE_EVICT = frozenset({"pop", "popitem", "clear"})


def _cache_named(name: str) -> bool:
    low = name.lower()
    return any(m in low for m in _CACHE_NAME_MARKERS)


def _dict_ctor(module: Module, node: ast.AST) -> Optional[str]:
    """"dict"/"OrderedDict" when ``node`` constructs an empty mapping —
    ``{}``, ``dict()``, ``OrderedDict()``, or ``dataclasses.field(
    default_factory=dict|OrderedDict)`` — else None."""
    if isinstance(node, ast.Dict) and not node.keys:
        return "dict"
    if not isinstance(node, ast.Call):
        return None
    leaf = (module.qualname(node.func) or "").rsplit(".", 1)[-1]
    if leaf in ("dict", "OrderedDict") and not node.args and not node.keywords:
        return leaf
    if leaf == "field":
        for kw in node.keywords:
            if (
                kw.arg == "default_factory"
                and isinstance(kw.value, ast.Name)
                and kw.value.id in ("dict", "OrderedDict")
            ):
                return kw.value.id
    return None


def _cache_usage(scope: ast.AST, matches,
                 exclude: set = frozenset(),
                 defining: ast.AST | None = None) -> tuple[bool, bool]:
    """(grows, evicts) for a candidate cache binding within ``scope``.

    Grows: ``c[k] = v`` subscript assignment, ``c.setdefault(...)``,
    ``c.update(...)``. Evicts: ``c.pop/popitem/clear``, ``del c[k]``,
    or a rebinding to a fresh empty container (the drop-the-pool
    idiom). The same visibility discipline as TPU012's
    :func:`_queue_usage` — ``exclude`` subtrees (shadowing scopes) are
    not descended into — but with the subscript-assignment polarity
    FLIPPED: for a list, ``q[i] = x`` is the windowed-drain bound; for
    a dict, ``c[k] = v`` is exactly the unbounded admission this rule
    exists to fence."""
    grows = evicts = False
    for node in _walk_excluding(scope, exclude):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if matches(node.func.value):
                if node.func.attr in _CACHE_GROW:
                    grows = True
                elif node.func.attr in _CACHE_EVICT:
                    evicts = True
        elif isinstance(node, ast.Delete):
            for target in node.targets:
                if isinstance(target, ast.Subscript) and matches(
                    target.value
                ):
                    evicts = True
        elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            value = node.value
            for target in targets:
                if isinstance(target, ast.Subscript) and matches(
                    target.value
                ):
                    grows = True
                elif (
                    node is not defining
                    and matches(target)
                    and value is not None
                    and _empty_container_expr(value)
                ):
                    evicts = True
    return grows, evicts


@rule(
    "TPU022",
    "unbounded-cache",
    "a module/class-level cache-named dict grown by key assignment with "
    "no eviction route — every distinct key a long-lived server sees "
    "stays resident forever",
)
def check_unbounded_cache(module: Module,
                          config: LintConfig) -> Iterator[Finding]:
    """TPU012's mapping sibling: the cache-discipline rule.

    A compile cache, solve cache or warm pool that lives at module or
    instance scope and admits entries (``c[key] = value``,
    ``setdefault``) without any eviction route grows with the *key
    space*, not the working set — in a serving process where keys carry
    request-derived content (grid buckets are finite; RHS sketches are
    not), that is an OOM with a delay fuse. The repo's own discipline
    is the fix this rule points at: ``runtime.solvecache.SolveCache``
    (LRU key cap + per-key ring), ``runtime.compile_cache`` (bounded
    bucketing), or a drop-and-rebuild (``_ctxs.clear()`` on mesh
    degrade).

    Deliberately conservative, mirroring TPU012's machinery:

    - candidates are long-lived bindings only — module-level names and
      ``self`` attributes (incl. ``field(default_factory=dict)``)
      initialised to ``{}``/``dict()``/``OrderedDict()`` — whose NAME
      declares cache intent (contains ``cache``/``memo``/``pool``); a
      dict not named like a cache is a data structure, not a finding;
    - any visible eviction silences it: ``pop``/``popitem``/``clear``,
      ``del c[key]``, or a rebinding to a fresh empty container;
      function-local caches are scoped to one call and stay silent
      (TPU012's shadowing discipline, reused verbatim).
    """
    # module-level names
    for stmt in module.tree.body:
        target = value = None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and (
            isinstance(stmt.targets[0], ast.Name)
        ):
            target, value = stmt.targets[0], stmt.value
        elif isinstance(stmt, ast.AnnAssign) and isinstance(
            stmt.target, ast.Name
        ) and stmt.value is not None:
            target, value = stmt.target, stmt.value
        if target is None or not _cache_named(target.id):
            continue
        kind = _dict_ctor(module, value)
        if kind is None:
            continue
        name = target.id

        def matches(expr, name=name):
            return isinstance(expr, ast.Name) and expr.id == name

        grows, evicts = _cache_usage(
            module.tree, matches,
            exclude=_shadowing_functions(module.tree, name),
            defining=stmt,
        )
        if grows and not evicts:
            yield _finding(
                module,
                stmt,
                "TPU022",
                f"module-level {kind} cache `{name}` admits entries with "
                "no eviction route: every distinct key stays resident "
                "for the life of the process — bound it (LRU cap like "
                "runtime.solvecache.SolveCache, a popitem ring, a "
                "clear() on rebuild) or key it by a finite bucket space",
            )
    # class-level / instance attributes
    for cls in ast.walk(module.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        candidates: dict[str, tuple[ast.AST, str]] = {}
        for stmt in cls.body:
            target = value = None
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and (
                isinstance(stmt.targets[0], ast.Name)
            ):
                target, value = stmt.targets[0], stmt.value
            elif isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ) and stmt.value is not None:
                target, value = stmt.target, stmt.value
            if target is None or not _cache_named(target.id):
                continue
            kind = _dict_ctor(module, value)
            if kind is not None:
                candidates[target.id] = (stmt, kind)
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign):
                pairs = [(t, node.value) for t in node.targets]
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                pairs = [(node.target, node.value)]
            else:
                continue
            for target, value in pairs:
                if (
                    isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                    and _cache_named(target.attr)
                ):
                    kind = _dict_ctor(module, value)
                    if kind is not None and target.attr not in candidates:
                        candidates[target.attr] = (node, kind)
        for attr, (site, kind) in candidates.items():

            def matches(expr, attr=attr, cls_name=cls.name):
                return _attr_is_self(expr, attr) or (
                    isinstance(expr, ast.Attribute)
                    and expr.attr == attr
                    and isinstance(expr.value, ast.Name)
                    and expr.value.id == cls_name
                )

            grows, evicts = _cache_usage(cls, matches, defining=site)
            if grows and not evicts:
                yield _finding(
                    module,
                    site,
                    "TPU022",
                    f"instance-level {kind} cache `{attr}` of class "
                    f"`{cls.name}` admits entries with no eviction "
                    "route: the cache grows with the key space, not the "
                    "working set — bound it (LRU cap + per-key ring "
                    "like runtime.solvecache.SolveCache) or drop and "
                    "rebuild it at a lifecycle boundary",
                )
