"""poisson_ellipse_tpu — TPU-native fictitious-domain Poisson/PCG framework.

A ground-up JAX/XLA/Pallas re-design of the reference project
``mxy-kit/poisson-ellipse-openmp-mpi-cuda`` (mounted at ``/root/reference``):
the 2D Poisson equation ``-Δu = f`` on the elliptic domain ``x² + 4y² < 1``
embedded in ``Ω = [-1,1]×[-0.6,0.6]``, solved by the fictitious-domain method
with a diagonally preconditioned conjugate-gradient (PCG) solver.

Where the reference climbs through five hand-written parallel stages
(sequential C++ → OpenMP → MPI 2D decomposition → MPI+OpenMP → MPI+CUDA),
this framework expresses the same numerics once, TPU-first:

- vectorised coefficient assembly (no loops; ``ops.assembly``),
- 5-point variable-coefficient stencil + diagonal preconditioner as fused
  XLA ops (``ops.stencil``), with Pallas kernel variants in ``ops.pallas``,
- the full PCG loop on-device inside ``lax.while_loop`` — α, β and the
  convergence decision never leave the chip (``solver.pcg``),
- 2D spatial domain decomposition over a ``jax.sharding.Mesh`` with
  explicit 1-cell halo exchange via ``lax.ppermute`` over ICI and global
  reductions via ``lax.psum`` (``parallel``), replacing the reference's
  ``MPI_Sendrecv`` / ``MPI_Allreduce`` backend,
- a native C++/OpenMP host runtime for CPU-side work (``runtime``),
  covering the reference's stage0/stage1 capabilities natively.

(Consult each subpackage's module list for what has landed; this docstring
describes the framework's architecture.)

Stage parity map (reference → here):
  stage0 sequential  → ``runtime`` C++ solver (1 thread) / single-chip JAX
  stage1 OpenMP      → ``runtime`` C++ solver (OMP_NUM_THREADS)
  stage2 MPI         → ``parallel`` sharded solver over a device mesh
  stage3 MPI+OpenMP  → mesh sharding × XLA intra-chip parallelism
  stage4 MPI+CUDA    → single/multi-chip TPU path with Pallas kernels
"""

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.solver.pcg import PCGResult, pcg, solve
from poisson_ellipse_tpu.utils.error import l2_error_vs_analytic

__version__ = "0.1.0"

__all__ = [
    "Problem",
    "PCGResult",
    "pcg",
    "solve",
    "l2_error_vs_analytic",
]
