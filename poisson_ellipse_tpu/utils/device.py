"""Device capability table: VMEM capacity by ``device_kind``.

The engine capacity gates (``ops.resident_pcg.fits_resident``,
``ops.streamed_pcg.StreamPlan``) were measured on a 128 MiB-VMEM part;
this module keys those budgets off the actual device the solve will run
on — the same pattern ``harness.roofline`` uses for HBM peak bandwidth —
so ``select_engine`` keeps picking correctly on parts with different
VMEM sizes instead of silently under-selecting on a larger-VMEM chip
(or over-selecting on a smaller one). ``Device.memory_stats()`` exposes
no VMEM figure on this runtime (verified: it returns None under the
tunnel plugin), so a published-capacity table with the measured bench
part as fallback is the honest source.
"""

from __future__ import annotations

import contextlib

import jax

_MIB = 1024 * 1024

# Published per-core VMEM capacity by device kind. Every currently
# deployed TPU generation the framework targets ships 128 MiB; the table
# exists so a future part with a different size is a one-line entry.
_VMEM_CAPACITY = {
    "TPU v4": 128 * _MIB,
    "TPU v5 lite": 128 * _MIB,
    "TPU v5e": 128 * _MIB,
    "TPU v5": 128 * _MIB,
    "TPU v5p": 128 * _MIB,
    "TPU v6 lite": 128 * _MIB,
    "TPU v6e": 128 * _MIB,
}

# The part the repo's budgets were measured on (see resident_pcg /
# streamed_pcg): unknown kinds — including CPU interpret runs — fall
# back to it, reproducing the measured behaviour exactly.
_MEASURED_CAPACITY = 128 * _MIB


# Fault-injection hook (resilience.faultinject.simulated_vmem): when set,
# every device reports this capacity, so the engine capacity gates
# (fits_resident / fits_streamed) and select_engine can be driven through
# their degradation paths deterministically, with no real OOM required.
_CAPACITY_OVERRIDE: int | None = None


@contextlib.contextmanager
def vmem_capacity_override(capacity_bytes: int):
    """Pretend every device ships ``capacity_bytes`` of VMEM while the
    context is active. Test/chaos harness hook — the production tables
    above stay the only real source."""
    global _CAPACITY_OVERRIDE
    prev = _CAPACITY_OVERRIDE
    _CAPACITY_OVERRIDE = int(capacity_bytes)
    try:
        yield
    finally:
        _CAPACITY_OVERRIDE = prev


def vmem_capacity_bytes(device=None) -> int:
    """VMEM capacity of ``device`` (default: the first default-backend
    device), from the published table; measured-part fallback."""
    if _CAPACITY_OVERRIDE is not None:
        return _CAPACITY_OVERRIDE
    if device is None:
        devices = jax.devices()
        device = devices[0] if devices else None
    kind = getattr(device, "device_kind", "")
    return _VMEM_CAPACITY.get(kind, _MEASURED_CAPACITY)


def scaled_vmem_budget(measured_bytes: int, device=None) -> int:
    """Scale a budget measured on the 128 MiB bench part to ``device``.

    Proportional scaling: the measured budgets encode what fraction of
    capacity is usable once Mosaic's own reserves are paid (e.g.
    125/128 resident, 114/128 streamed); that fraction, not the byte
    count, is the transferable fact. Unknown kinds scale by 1.0.
    """
    return int(
        measured_bytes * vmem_capacity_bytes(device) / _MEASURED_CAPACITY
    )
