"""Shared utilities: grids, error norms vs the analytic control solution."""

from poisson_ellipse_tpu.utils.error import l2_error_vs_analytic, residual_norm

__all__ = ["l2_error_vs_analytic", "residual_norm"]
