"""Phase timers reproducing the reference's benchmark taxonomy (layer L6).

The reference's richest timing model is stage4's five accumulators
``T_gpu, T_copy, T_mpi, T_prec, T_dot`` (``poisson_mpi_cuda2.cu:696-700``)
incremented around every kernel launch / memcpy / collective and
``MPI_Reduce(MAX)``-aggregated to rank 0 (``:962-979``), with ``main``
splitting program wall-clock into init/solver/finalize (``:992-1034``).

On TPU the fast path is one fused ``lax.while_loop`` — instrumenting inside
it would destroy the very fusion being measured. So timing splits in two:

- ``PhaseTimer``: host-side wall-clock accumulator for the *coarse* phases
  (assembly/init, solve, finalize) — the analog of stage4's ``main`` split.
  Every region is fenced with ``jax.block_until_ready`` plus a scalar
  device→host fetch, because under tunneled platforms ``block_until_ready``
  alone has been observed to return before completion.

- ``profile_phases`` (harness.profile): a *segmented replay* of the PCG
  iteration that times each constituent op (halo, stencil, dot, precond,
  update) in isolation over k repetitions — the analog of stage4's
  per-phase accumulators, measured without slowing the production loop.

``PhaseTimer`` is a thin shim over the structured trace layer
(``obs.trace``): every region it closes is also emitted as a ``span``
record (``phase:<name>``) into the ambient JSONL trace when one is
active, so the human report and the machine trace come from the same
measurement.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp

from poisson_ellipse_tpu.obs import trace as _trace


def fence(tree) -> None:
    """Synchronise host with device work producing ``tree``.

    ``block_until_ready`` plus a 1-scalar device→host transfer: the
    transfer is the only sync observed to be reliable on every backend
    this framework targets (see module docstring).
    """
    tree = jax.block_until_ready(tree)
    leaves = jax.tree_util.tree_leaves(tree)
    if leaves:
        leaf = leaves[-1]
        if hasattr(leaf, "ravel") and leaf.size:
            float(jnp.asarray(leaf).ravel()[-1])


@dataclass
class PhaseTimer:
    """Named wall-clock accumulators, reference-style.

    >>> t = PhaseTimer()
    >>> with t.phase("init"):   ...
    >>> with t.phase("solver"): ...
    >>> t.report()
    """

    totals: dict[str, float] = field(default_factory=dict)

    def phase(self, name: str):
        return _Region(self, name)

    def add(self, name: str, seconds: float) -> None:
        self.totals[name] = self.totals.get(name, 0.0) + seconds
        # the shim half: the same measurement lands in the JSONL trace
        # (no-op when tracing is inactive)
        _trace.span_event(f"phase:{name}", seconds)

    def report(self, out=None) -> str:
        """Name-sorted rows with a share-of-total column.

        Stable column order (sorted by phase name, not insertion) and a
        guarded percentage — 0 phases or an all-zero total must render,
        not divide by zero — so reports derived from two traces of the
        same run diff cleanly.
        """
        total = sum(self.totals.values())
        lines = [
            f"  T_{name:<10s} {self.totals[name]:10.4f} s  "
            f"{(100.0 * self.totals[name] / total) if total > 0 else 0.0:5.1f}%"
            for name in sorted(self.totals)
        ]
        text = "\n".join(lines)
        if out is not None:
            print(text, file=out)
        return text


class _Region:
    def __init__(self, timer: PhaseTimer, name: str):
        self.timer = timer
        self.name = name

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.timer.add(self.name, time.perf_counter() - self.t0)
        return False
