"""Accuracy instrumentation vs the analytic control solution.

The reference *states* u = (1 − x² − 4y²)/10 as its accuracy control
(``README.md:38-42``) but no stage ever computes an error against it
(verified: no error computation exists in any source). BASELINE.json makes
"L2 error vs analytic" a first-class metric of this framework, so it lives
here: the discrete L2 norm h1·h2-weighted over interior nodes strictly
inside D (the analytic solution is only meaningful inside the ellipse; the
fictitious exterior carries O(eps) garbage by design).
"""

from __future__ import annotations

import jax.numpy as jnp

from poisson_ellipse_tpu.models import ellipse
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops.reduction import grid_dot
from poisson_ellipse_tpu.ops.stencil import apply_a


def _interior_coords(problem: Problem, dtype):
    gi = jnp.arange(problem.M + 1, dtype=dtype)
    gj = jnp.arange(problem.N + 1, dtype=dtype)
    x = problem.a1 + gi * jnp.asarray(problem.h1, dtype)
    y = problem.a2 + gj * jnp.asarray(problem.h2, dtype)
    return x[:, None], y[None, :]


def l2_error_vs_analytic(problem: Problem, w):
    """sqrt( h1·h2 · Σ_{nodes in D} (w_ij − u(x_i, y_j))² )."""
    dtype = w.dtype
    x, y = _interior_coords(problem, dtype)
    u = ellipse.analytic_solution(x, y)
    in_d = ellipse.is_in_d(x, y)
    err2 = jnp.where(in_d, (w - u) ** 2, 0.0)
    return jnp.sqrt(jnp.sum(err2) * problem.h1 * problem.h2)


def residual_norm(problem: Problem, w, a, b, rhs):
    """‖B − A·w‖ in the grid-weighted norm — a solver-independent check."""
    dtype = w.dtype
    h1 = jnp.asarray(problem.h1, dtype)
    h2 = jnp.asarray(problem.h2, dtype)
    r = rhs - apply_a(w, a, b, h1, h2)
    return jnp.sqrt(grid_dot(r, r, h1, h2))
