"""Problem definitions (reference layer L0): domain geometry, constants,
analytic control solution."""

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.models import ellipse

__all__ = ["Problem", "ellipse"]
