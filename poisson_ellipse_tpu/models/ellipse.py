"""Elliptic domain D = {x² + 4y² < 1} (reference "variant 9") — vectorised.

The reference implements this geometry as scalar functions called per cell
inside OpenMP/CUDA loops (``stage0/Withoutopenmp1.cpp:14-16`` membership,
``:19-39`` closed-form segment∩ellipse length). Here the same closed forms
are written as broadcast ``jnp`` expressions over whole coordinate arrays —
one fused XLA kernel assembles the entire grid, no loops.

All branches become ``where``; square roots are clamped at zero before
evaluation so the gradients/values are well-defined everywhere.

Every function takes an ``xp`` array-module argument (default ``jax.numpy``)
so the *same* closed forms serve both the traced on-device path and the
float64 host-assembly path (``xp=numpy``) — the geometry exists exactly once.
"""

from __future__ import annotations

import jax.numpy as jnp


def safe_sqrt(v, xp=jnp):
    """sqrt clamped at zero with a reverse-mode-safe zero branch.

    ``sqrt(maximum(0, v))`` is value-correct but its cotangent at exactly
    v = 0 is 1/(2·sqrt(0)) = inf, which ``jax.grad`` propagates as NaN —
    the classic ``where``-free sqrt hazard. Clamping *inside* a ``where``
    on both branches keeps the primal identical and pins the gradient to
    0 on the clamped branch, so differentiable solves (ROADMAP item 3's
    shape-optimisation workload) can differentiate through the geometry.
    """
    positive = v > 0.0
    return xp.where(positive, xp.sqrt(xp.where(positive, v, 1.0)), 0.0)


def is_in_d(x, y):
    """Membership mask of the open ellipse x² + 4y² < 1.

    Reference: ``stage0/Withoutopenmp1.cpp:14-16``.
    """
    return x * x + 4.0 * y * y < 1.0


def analytic_solution(x, y):
    """The exact solution u = (1 − x² − 4y²)/10 of -Δu = 1 on D with u|∂D = 0.

    Stated as the accuracy control in the reference (``README.md:38-42``)
    but never evaluated by its code; here it is first-class.
    """
    return (1.0 - x * x - 4.0 * y * y) / 10.0


def segment_length_vertical(x0, y_start, y_end, xp=jnp):
    """Length of {x0} × [y_start, y_end] ∩ D.

    Closed form: for |x0| < 1 the ellipse spans |y| ≤ sqrt((1-x0²)/4).
    Reference: ``stage0/Withoutopenmp1.cpp:21-28`` (is_ver branch).
    """
    y_max = safe_sqrt((1.0 - x0 * x0) / 4.0, xp)
    length = xp.maximum(
        0.0, xp.minimum(y_end, y_max) - xp.maximum(y_start, -y_max)
    )
    return xp.where(xp.abs(x0) >= 1.0, 0.0, length)


def segment_length_horizontal(y0, x_start, x_end, xp=jnp):
    """Length of [x_start, x_end] × {y0} ∩ D.

    Closed form: for |2·y0| < 1 the ellipse spans |x| ≤ sqrt(1-4y0²).
    Reference: ``stage0/Withoutopenmp1.cpp:29-37`` (horizontal branch).
    """
    x_max = safe_sqrt(1.0 - 4.0 * y0 * y0, xp)
    length = xp.maximum(
        0.0, xp.minimum(x_end, x_max) - xp.maximum(x_start, -x_max)
    )
    return xp.where(xp.abs(2.0 * y0) >= 1.0, 0.0, length)
