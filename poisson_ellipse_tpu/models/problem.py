"""Problem specification for the fictitious-domain Poisson solve.

Mirrors the reference's compile-time constants and derived quantities
(``stage0/Withoutopenmp1.cpp:9-11`` for A1/B1/A2/B2/F_VAL,
``:107-108`` for h1/h2/eps, ``:182`` for max_iter=(M-1)(N-1),
``:178`` for delta=1e-6) as one frozen, hashable dataclass so it can be
closed over by jitted functions as a static argument.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class Problem:
    """Discretisation of -Δu = f on D = {x² + 4y² < 1} ⊂ Ω = [a1,b1]×[a2,b2].

    M, N       : number of grid cells in x / y; nodes are 0..M × 0..N.
    norm       : convergence-norm convention for ‖w^{k+1} − w^k‖:
                 "weighted"   → sqrt(Σ dw² · h1·h2)  (stages 1-4,
                                 ``stage1-openmp/Withopenmp1.cpp:182-189``,
                                 ``stage4 poisson_mpi_cuda2.cu:626-660``)
                 "unweighted" → sqrt(Σ dw²)          (stage0 variant 1,
                                 ``stage0/Withoutopenmp1.cpp:149-154``)
                 Iteration-count oracles (committed reference code, verified
                 by compiling/running it): unweighted 17/31/61 at
                 10²/20²/40²; weighted 50 at 40².
    delta      : stopping threshold on the norm above (1e-6 in all stages).
    eps        : fictitious-domain penetration parameter; default
                 max(h1,h2)² as in ``stage0/Withoutopenmp1.cpp:108``.
    max_iter   : PCG iteration cap; default (M-1)(N-1).
    """

    M: int
    N: int
    a1: float = -1.0
    b1: float = 1.0
    a2: float = -0.6
    b2: float = 0.6
    f_val: float = 1.0
    delta: float = 1e-6
    norm: str = "weighted"
    eps: Optional[float] = None
    max_iter: Optional[int] = None

    def __post_init__(self) -> None:
        if self.M < 2 or self.N < 2:
            raise ValueError("need M >= 2 and N >= 2 for a nonempty interior")
        if self.norm not in ("weighted", "unweighted"):
            raise ValueError(f"unknown norm convention: {self.norm!r}")
        # a non-positive eps would silently select the native runtime's
        # default while the JAX path used the literal value — reject it
        # here so every backend sees the same problem
        if self.eps is not None and self.eps <= 0:
            raise ValueError("eps must be positive (or None for the default)")

    @property
    def h1(self) -> float:
        return (self.b1 - self.a1) / self.M

    @property
    def h2(self) -> float:
        return (self.b2 - self.a2) / self.N

    @property
    def eps_value(self) -> float:
        if self.eps is not None:
            return self.eps
        h = max(self.h1, self.h2)
        return h * h

    @property
    def max_iterations(self) -> int:
        if self.max_iter is not None:
            return self.max_iter
        return (self.M - 1) * (self.N - 1)

    @property
    def node_shape(self) -> tuple[int, int]:
        """Shape of the full node grid including the Dirichlet boundary."""
        return (self.M + 1, self.N + 1)
