"""CLI: ``python -m poisson_ellipse_tpu.harness M N [options]``.

Argv contract extends the reference executables' (``argv[1]=M argv[2]=N``,
``stage2-mpi/poisson_mpi_decomp.cpp:470-474``,
``poisson_mpi_cuda2.cu:995-999``; process grid from mpirun → here
``--mesh``). Multiple grids sweep like stage0/1's built-in loops
(``stage0/Withoutopenmp1.cpp:176-196``). ``--eps-sweep`` runs the
fictitious-domain stiffness study of BASELINE.json config 5.

Two observability entries ride the same prog:

- ``--trace FILE`` (or ``POISSON_TRACE=FILE`` in the environment) streams
  the run as structured JSONL — phase spans, per-run report events,
  counters — in the ``obs.trace`` schema.
- ``inspect <engine>`` is a subcommand: static cost accounting for one
  engine (psum/ppermute per iteration from the jaxpr, XLA-estimated
  FLOPs/HBM bytes, the roofline traffic model's columns) with no solve
  executed — ``python -m poisson_ellipse_tpu.harness inspect pipelined
  --mode sharded --mesh 1 2``.
- ``diagnose <engine>`` runs the measured half: one history-enabled
  solve read through ``obs.spectrum`` (Ritz values, κ(M⁻¹A), CG rate,
  predicted iterations, plateaus — verified bit-identical to a plain
  solve), the fenced compile/H2D/solve/D2H phase profile with
  measured-vs-modeled roofline columns (``obs.profile``), and an
  optional OpenMetrics snapshot (``--metrics FILE``) —
  ``python -m poisson_ellipse_tpu.harness diagnose xla --grid 400x600``.
- ``--metrics FILE`` on the main prog exports the run's counters/
  gauges/histograms as a periodically rewritten OpenMetrics snapshot
  (``obs.export``).

The serving surface:

- ``--lanes N`` runs N independent solves inside ONE dispatch via the
  lane-batched engines (real batching — ``--batch`` is only the chained
  TIMING protocol and never puts more work on the chip); reports carry
  aggregate solves/sec and per-lane quarantine counts.
- ``--recycle [CAP]`` / ``--warm-start`` run the Krylov-recycling
  protocol (``solver.recycle`` / ``runtime.solvecache``): one untimed
  ring-carrying capture solve harvests the extremal Ritz deflation
  basis, then the timed solve restarts deflated and/or seeded with the
  capture solution (the semantic-cache-hit shape) — the report's
  ``iters`` is the deflated count, its l2 still checked vs analytic.
- ``warmup`` is the cache subcommand: wire the persistent XLA
  compilation cache and AOT-compile bucketed batched executables so
  arbitrary request sizes hit a warm executable —
  ``python -m poisson_ellipse_tpu.harness warmup --grids 400x600
  --lanes 1,8 --engine both``.
- ``tune`` is the autotuner subcommand (``runtime.autotune``): probe
  the shape's telemetry, score every candidate engine configuration,
  print the chosen config vs the static default with predicted-vs-
  measured columns, and (``--persist``) write the winner next to the
  XLA compile cache for ``--engine auto`` and the serve warm pool to
  consult — ``python -m poisson_ellipse_tpu.harness tune --grid
  400x600 --measure --persist``.
- ``serve`` drives a synthetic request stream through the
  continuous-batching scheduler (``serve.scheduler``): seeded Poisson
  arrivals of mixed shapes, bounded admission with backpressure,
  deadlines at chunk granularity, lane retirement/refill, retry
  ladder, optional crash-safe journal — ``python -m
  poisson_ellipse_tpu.harness serve --requests 20 --grids 10x10,12x12
  --deadline 5 --journal /tmp/journal.json``.
- ``chaos`` is the serving chaos drill (``serve.chaos``): the same
  stream with an injected NaN lane, a fake RESOURCE_EXHAUSTED and a
  kill/restart with journal replay, asserting zero lost / zero
  double-completed / all outcomes classified — ``python -m
  poisson_ellipse_tpu.harness chaos --requests 50 --seed 0``.
- ``fleet`` is the replicated-serving drill (``fleet.FleetRouter``):
  the stream routed over ``--replicas`` scheduler replicas by
  compile-bucket affinity, with lease health checks and
  ``--kill-replica-at`` arming a mid-stream SIGKILL whose journal
  hands off to the survivors — ``python -m poisson_ellipse_tpu.harness
  fleet --replicas 3 --requests 24 --kill-replica-at 8``. SIGTERM
  drains ``serve``/``fleet`` gracefully: stop admitting, finish
  in-flight, flush the trace, exit 0.
- ``grad`` is the differentiable-solving drill (``diff/``): an
  end-to-end inverse workload — ``--workload ellipse`` recovers
  perturbed ellipse parameters from the solution they produced,
  ``--workload source`` a per-node source field — driven by
  implicit-function-theorem adjoints (one extra PCG per gradient) —
  ``python -m poisson_ellipse_tpu.harness grad --workload ellipse
  --engine mg-pcg``. Exit 0 iff the workload's acceptance holds.

And the resilience surface:

- ``--guard`` routes the solve through ``resilience.guard`` (chunked
  execution, per-chunk health word, recovery ladder); ``--timeout S``
  implies it and cancels gracefully at a chunk boundary, emitting the
  partial trace instead of hanging.
- ``inject <fault>`` is the chaos subcommand: run a guarded solve with a
  deterministic fault (nan / breakdown / stagnation / halo / oom)
  injected at an exact iteration and report the recovery —
  ``python -m poisson_ellipse_tpu.harness inject nan 40 40 --at 10``.
- Exit codes are a contract: 0 converged, 1 iteration cap without
  convergence, 2 diverged (breakdown / recovery exhausted; also invalid
  invocations, per argparse convention), 3 device out-of-memory with no
  engine left to degrade to, 4 ``--timeout`` exceeded, 5 shed at
  admission by the serving layer (backpressure; retry after the hint),
  8 geometry rejected by the admissibility gate (``--geometry`` with a
  malformed/empty/under-resolved spec or an inadmissible operator —
  classified before any device dispatch), 9 every fleet replica down
  or draining (``FleetUnavailableError`` — no admission path left).
"""

from __future__ import annotations

import argparse
import contextlib
import json
import sys

from poisson_ellipse_tpu.harness.run import (
    DTYPES,
    resolve_dtype,
    resolve_mesh,
    run_once,
)
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs import metrics as obs_metrics
from poisson_ellipse_tpu.obs import trace as obs_trace
from poisson_ellipse_tpu.resilience.errors import SolveError
from poisson_ellipse_tpu.runtime.native import NativeBuildError
from poisson_ellipse_tpu.solver.engine import ENGINES

EXIT_CODES_HELP = (
    "exit codes (contract): 0 converged; 1 iteration cap reached without "
    "convergence; 2 diverged — breakdown or recovery budget exhausted "
    "(also invalid invocations, per argparse convention); 3 device "
    "out-of-memory with no engine left to degrade to; 4 --timeout "
    "exceeded (partial trace artifact emitted); 5 shed at admission by "
    "the serving layer (backpressure — resubmit after retry_after_s); "
    "6 silent data corruption detected by the ABFT checks and not "
    "cleared by rollback-and-rerun (persistent SDC source); 7 mesh "
    "device lost with no degraded mesh left to resume on; 8 geometry "
    "rejected by the admissibility gate (malformed/empty/under-resolved "
    "spec or inadmissible operator — classified BEFORE any device "
    "dispatch); 9 every serving-fleet replica down or draining — no "
    "admission path left (FleetUnavailableError; resubmit after "
    "retry_after_s once a replica rejoins)."
)


class _SigtermDrain:
    """SIGTERM → graceful drain for the serving subcommands.

    The handler only sets a flag; the serve loop checks it between
    arrivals and switches to drain mode (stop admitting, finish or
    journal in-flight, flush metrics/trace, exit 0) instead of dying
    mid-stream with the trace tail unflushed. Installed around the
    loop and restored on exit; a non-main-thread caller (tests driving
    ``main()`` from a worker) simply gets no handler, never an error.
    """

    def __init__(self):
        self.requested = False
        self._prev = None
        self._installed = False

    def _handle(self, signum, frame):
        self.requested = True

    def __enter__(self):
        import signal

        try:
            self._prev = signal.signal(signal.SIGTERM, self._handle)
            self._installed = True
        except ValueError:  # not the main thread: no handler, no error
            pass
        return self

    def __exit__(self, *exc):
        import signal

        if self._installed:
            signal.signal(signal.SIGTERM, self._prev)
        return False


def _parse_grid(spec: str | None, default=(40, 40)) -> tuple[int, int]:
    """One ``MxN`` grid spec (the sweep syntax's single-grid form), or
    ``default`` when the flag was not given at all. Raises ValueError on
    malformed input — an EMPTY spec included (a trailing comma in a
    --grids list must error, not silently inject the default grid) —
    which the subcommands catch into their curated exit-2 path."""
    if spec is None:
        return default
    m, _, n = spec.lower().partition("x")
    return (int(m), int(n or m))


def _parse_grids(args) -> list[tuple[int, int]]:
    if args.M is not None:
        return [(args.M, args.N if args.N is not None else args.M)]
    if args.grids:
        return [_parse_grid(spec) for spec in args.grids.split(",")]
    return [(40, 40)]


def _run_threads_sweep(
    problem: Problem, counts: list[int], repeat: int, as_json: bool
) -> int:
    """The stage1 in-run OpenMP sweep: one native solve per thread count,
    reported as the reference's table 2 (threads / iters / T / speedup vs
    the sweep's first count; ``stage1-openmp/Withopenmp1.cpp:205-229``
    loops ``omp_set_num_threads(t)`` around the same solve)."""
    if not counts:
        raise ValueError("--threads-sweep needs at least one thread count")
    reports = [
        run_once(problem, mode="native", threads=t, repeat=repeat)
        for t in counts
    ]
    base = reports[0].t_solver
    if as_json:
        for rep in reports:
            rec = rep.json_dict()
            rec["speedup_vs_first"] = round(base / rep.t_solver, 3)
            print(json.dumps(rec))
    else:
        print(
            f"Threads sweep {problem.M}x{problem.N} (native f64, "
            f"delta={problem.delta:g}):"
        )
        print("  threads    iters    T_solver(s)   speedup")
        for t, rep in zip(counts, reports):
            print(
                f"  {t:7d}  {rep.iters:7d}  {rep.t_solver:12.4f}  "
                f"{base / rep.t_solver:8.2f}"
            )
        print()
    return 0 if all(r.converged for r in reports) else 1


def _run_inspect(argv: list[str]) -> int:
    """The ``inspect`` subcommand: static cost accounting per engine."""
    ap = argparse.ArgumentParser(
        prog="python -m poisson_ellipse_tpu.harness inspect",
        description="Static cost accounting for one solver engine: "
        "collectives per iteration read from the jaxpr, XLA-estimated "
        "FLOPs/HBM bytes, and the roofline traffic model side by side. "
        "No solve is executed.",
    )
    ap.add_argument(
        "engine",
        help=f"engine to inspect (single-chip: {', '.join(ENGINES[1:])}; "
        "sharded via --mode sharded: xla, pallas, fused, pipelined, "
        "sstep — sstep reports per-BODY counts (1 psum + 4 ppermute per "
        "s iterations) alongside the per-iteration division",
    )
    ap.add_argument(
        "--mode", choices=("single", "sharded"), default="single",
        help="single-device engine or the mesh-sharded composition",
    )
    ap.add_argument(
        "--mesh", type=int, nargs=2, metavar=("PX", "PY"),
        help="mesh shape for --mode sharded (default: all devices)",
    )
    ap.add_argument("--grid", help="MxN grid to trace at (default 40x40)")
    ap.add_argument("--dtype", choices=sorted(DTYPES), default="f32")
    ap.add_argument(
        "--storage-dtype", choices=("bf16", "f16", "f32"), default=None,
        help="trace the narrow-storage build: the modeled HBM bytes/iter "
        "column shows the storage-width byte bill (bf16 under f32 = the "
        "~2x cut)",
    )
    ap.add_argument(
        "--sstep-s", type=int, choices=(2, 4), default=4,
        help="s-step block size for the sstep engines",
    )
    ap.add_argument(
        "--no-xla-cost", action="store_true",
        help="skip the XLA compile + cost analysis (jaxpr counts only)",
    )
    ap.add_argument("--json", action="store_true", help="one JSON line")
    args = ap.parse_args(argv)

    from poisson_ellipse_tpu.obs import static_cost

    try:
        grid = _parse_grid(args.grid)
        report = static_cost.engine_report(
            Problem(M=grid[0], N=grid[1]),
            engine=args.engine,
            dtype=resolve_dtype(args.dtype),
            mode=args.mode,
            mesh_shape=tuple(args.mesh) if args.mesh else None,
            with_xla_cost=not args.no_xla_cost,
            storage_dtype=args.storage_dtype,
            sstep_s=args.sstep_s,
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report))
    else:
        print(static_cost.render_report(report))
    obs_trace.event("inspect", **report)
    return 0


def _run_inject(argv: list[str]) -> int:
    """The ``inject`` subcommand: one guarded solve with a deterministic
    fault, reporting the recovery — the recovery paths stay exercised
    from the command line, not only from the test matrix."""
    from poisson_ellipse_tpu.resilience import faultinject
    from poisson_ellipse_tpu.resilience.guard import guarded_solve

    ap = argparse.ArgumentParser(
        prog="python -m poisson_ellipse_tpu.harness inject",
        description="Fault-injection harness: run a guarded solve with "
        "one deterministic fault (resilience.faultinject) and report the "
        "recovery ladder's actions. " + EXIT_CODES_HELP,
    )
    ap.add_argument(
        "fault",
        # device_loss/straggler are mesh-level dispatch faults — they
        # belong to the meshguard/chaos drills, not the single-solve
        # guard this subcommand runs
        choices=sorted(
            set(faultinject.FAULT_KINDS) - {"device_loss", "straggler"}
        ),
        help="fault class to inject (see resilience.faultinject)",
    )
    ap.add_argument("M", type=int, nargs="?", default=40)
    ap.add_argument("N", type=int, nargs="?", default=None)
    ap.add_argument(
        "--at", type=int, default=10, metavar="K",
        help="iteration to inject at (guard chunks stop exactly there)",
    )
    ap.add_argument(
        "--field", default=None,
        help="carry field to corrupt (nan/halo faults; default r)",
    )
    ap.add_argument(
        "--persistent", action="store_true",
        help="re-fire the fault on every visit instead of one-shot — "
        "forces the guard up the ladder and into the classified error",
    )
    ap.add_argument(
        "--engine", default="xla",
        choices=("xla", "pallas", "pipelined", "pipelined-pallas",
                 "mg-pcg", "cheb-pcg", "fmg"),
        help="chunk-steppable engine to guard (carry faults need one); "
        "the multigrid engines walk the mg->cheb->diag fallback ladder, "
        "and fmg chunk-steps its verification handoff loop",
    )
    ap.add_argument("--dtype", choices=sorted(DTYPES), default="f32")
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--max-recoveries", type=int, default=3)
    ap.add_argument("--timeout", type=float, default=None)
    ap.add_argument("--delta", type=float, default=1e-6)
    ap.add_argument("--trace", metavar="FILE", help="JSONL trace sink")
    ap.add_argument("--json", action="store_true", help="one JSON line")
    args = ap.parse_args(argv)

    if args.trace:
        obs_trace.start(args.trace)
    # everything past tracer start sits under the finally that stops it:
    # an invalid fault/problem spec must not leak the process-global
    # tracer or exit with a raw traceback instead of the contract's 2
    try:
        try:
            problem = Problem(
                M=args.M, N=args.N if args.N is not None else args.M,
                delta=args.delta,
            )
            plan = faultinject.FaultPlan(faultinject.Fault(
                args.fault, at_iter=args.at, field=args.field,
                persistent=args.persistent,
            ))
            guarded = guarded_solve(
                problem, args.engine, resolve_dtype(args.dtype),
                chunk=args.chunk, max_recoveries=args.max_recoveries,
                timeout=args.timeout, faults=plan,
            )
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        except SolveError as e:
            record = {
                "fault": args.fault, "at": args.at, "engine": args.engine,
                "aborted": e.classification, "iters": e.iters,
            }
            obs_trace.event("inject_report", **record)
            if args.json:
                print(json.dumps(record))
            else:
                print(
                    f"fault {args.fault}@{args.at}: solve aborted — "
                    f"{e.classification} ({e}); exit {e.exit_code}",
                    file=sys.stderr,
                )
            return e.exit_code
        return _report_inject(args, guarded)
    finally:
        # stop LAST: every inject_report above must land in the trace
        if args.trace:
            obs_trace.stop()


def _report_inject(args, guarded) -> int:
    result = guarded.result
    record = {
        "fault": args.fault, "at": args.at,
        "engine_requested": args.engine, "engine_final": guarded.engine,
        "dtype_final": guarded.dtype,
        "iters": int(result.iters), "converged": bool(result.converged),
        "recoveries": [e.kind for e in guarded.recoveries],
    }
    obs_trace.event("inject_report", **record)
    if args.json:
        print(json.dumps(record))
    else:
        kinds = ", ".join(e.kind for e in guarded.recoveries) or "none"
        print(
            f"fault {args.fault}@{args.at} on {args.engine}: "
            f"{'converged' if record['converged'] else 'NOT converged'} "
            f"after {record['iters']} iterations "
            f"(recoveries: {kinds}; finished on {guarded.engine}"
            + (f", {guarded.dtype}" if guarded.dtype else "")
            + ")"
        )
    return 0 if record["converged"] else 1


def _run_diagnose(argv: list[str]) -> int:
    """The ``diagnose`` subcommand: spectrum + profile + export, one report.

    Runs one history-enabled solve (``obs.convergence``) and reads the
    spectral story out of it (``obs.spectrum``: Ritz values, κ(M⁻¹A),
    CG rate, predicted iterations, plateaus), next to a plain solve that
    pins the telemetry's zero-perturbation contract (bit-identical
    iterates — diagnosing a solver must not change it), plus the fenced
    compile/H2D/solve/D2H phase profile with the measured-vs-modeled
    roofline columns (``obs.profile``), and optionally an OpenMetrics
    snapshot (``--metrics FILE``) so the numbers land where a scraper
    can find them.
    """
    import numpy as np

    from poisson_ellipse_tpu.solver.engine import (
        HISTORY_ENGINES,
        build_solver,
    )

    ap = argparse.ArgumentParser(
        prog="python -m poisson_ellipse_tpu.harness diagnose",
        description="Solver diagnostics in one report: Lanczos spectral "
        "estimates (kappa, CG rate, predicted iterations, plateaus) from "
        "the on-device convergence trace, fenced compile/H2D/solve/D2H "
        "phase profiling with measured-vs-modeled roofline columns, and "
        "OpenMetrics export. The history solve is verified bit-identical "
        "to a plain solve: diagnosing never changes the solver.",
    )
    ap.add_argument(
        "engine", nargs="?", default="auto",
        help="history-capable engine to diagnose "
        f"({', '.join(HISTORY_ENGINES)}; auto resolves to xla)",
    )
    ap.add_argument("--grid", help="MxN grid (default 40x40)")
    ap.add_argument("--dtype", choices=sorted(DTYPES), default="f32")
    ap.add_argument("--delta", type=float, default=1e-6)
    ap.add_argument(
        "--repeat", type=int, default=3,
        help="solve-phase repetitions for the profile median",
    )
    ap.add_argument(
        "--no-profile", action="store_true",
        help="skip the phase profile (spectrum + contract check only)",
    )
    ap.add_argument(
        "--no-xla-cost", action="store_true",
        help="skip the XLA cost analysis columns of the profile",
    )
    ap.add_argument(
        "--metrics", metavar="FILE",
        help="write the diagnostic numbers as an OpenMetrics snapshot "
        "(obs.export; atomic write)",
    )
    ap.add_argument("--trace", metavar="FILE", help="JSONL trace sink")
    ap.add_argument("--json", action="store_true", help="one JSON line")
    args = ap.parse_args(argv)

    if args.trace:
        obs_trace.start(args.trace)
    try:
        from poisson_ellipse_tpu.obs import profile as obs_profile
        from poisson_ellipse_tpu.obs import spectrum as obs_spectrum

        try:
            grid = _parse_grid(args.grid)
            problem = Problem(M=grid[0], N=grid[1], delta=args.delta)
            jdtype = resolve_dtype(args.dtype)
            if args.repeat < 1:
                # checked HERE, not after two solves have been paid for:
                # profile_engine would reject it with the same message
                raise ValueError("repeat must be >= 1")
            if args.engine not in HISTORY_ENGINES:
                raise ValueError(
                    f"engine {args.engine!r} records no history; diagnose "
                    f"covers {', '.join(HISTORY_ENGINES)}"
                )
            if args.metrics:
                from poisson_ellipse_tpu.obs.export import MetricsExporter

                # fail FAST on an unwritable path — same exit-2 contract
                # as the main prog's --metrics, checked BEFORE the
                # solves below are paid for (overwritten with the real
                # snapshot at the end)
                err = MetricsExporter(
                    args.metrics, registry=obs_metrics.MetricsRegistry()
                ).try_write()
                if err is not None:
                    raise ValueError(
                        f"cannot write --metrics {args.metrics}: {err}"
                    )
            # the contract half: history must not perturb one bit
            solver, solver_args, engine = build_solver(
                problem, args.engine, jdtype, history=True
            )
            result, trace = solver(*solver_args)
            plain_solver, plain_args, _ = build_solver(
                problem, engine, jdtype
            )
            plain = plain_solver(*plain_args)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        bit_identical = bool(
            int(plain.iters) == int(result.iters)
            and float(plain.diff) == float(result.diff)
            and np.array_equal(np.asarray(plain.w), np.asarray(result.w))
        )
        spec = obs_spectrum.spectrum_report(
            trace, delta=problem.delta, actual_iters=int(result.iters)
        )
        # the widened Lanczos interval — exactly what mg.cheby's setup
        # consumes (one shared helper, obs.spectrum.eigenvalue_bounds)
        bounds = obs_spectrum.eigenvalue_bounds(trace)
        spec["eigenvalue_bounds"] = list(bounds) if bounds else None
        diag_spec = None
        if engine in ("mg-pcg", "cheb-pcg"):
            # the yardstick: the preconditioner's kappa(M^-1 A) is only
            # meaningful NEXT TO the diagonal baseline it displaced
            diag_solver, diag_args, _ = build_solver(
                problem, "xla", jdtype, history=True
            )
            diag_result, diag_trace = diag_solver(*diag_args)
            diag_spec = obs_spectrum.spectrum_report(
                diag_trace, delta=problem.delta,
                actual_iters=int(diag_result.iters),
            )
        prof = None
        if not args.no_profile:
            prof = obs_profile.profile_engine(
                problem, engine, jdtype, repeat=args.repeat,
                with_xla_cost=not args.no_xla_cost,
            )
        record = {
            "engine": engine,
            "grid": list(grid),
            "dtype": args.dtype,
            "iters": int(result.iters),
            "converged": bool(result.converged),
            "bit_identical": bit_identical,
            "spectrum": spec,
            "profile": prof,
        }
        if diag_spec is not None:
            record["diag_spectrum"] = diag_spec
        if args.metrics:
            from poisson_ellipse_tpu.obs.export import MetricsExporter

            reg = obs_metrics.MetricsRegistry()
            reg.gauge("diagnose_iters").set(record["iters"])
            if spec.get("available"):
                reg.gauge("diagnose_kappa").set(spec["kappa"])
                reg.gauge("diagnose_cg_rate").set(spec["cg_rate"])
                if spec.get("predicted_iters") is not None:
                    reg.gauge("diagnose_predicted_iters").set(
                        spec["predicted_iters"]
                    )
            if prof is not None:
                hist = reg.histogram("diagnose_solve_seconds")
                hist.observe(prof["t_solve_s"])
                reg.gauge("diagnose_compile_seconds").set(
                    prof["t_compile_s"]
                )
                if prof.get("hbm_gbps") is not None:
                    reg.gauge("diagnose_hbm_gbps").set(prof["hbm_gbps"])
            record["metrics_path"] = MetricsExporter(
                args.metrics, registry=reg
            ).write()
        obs_trace.event("diagnose_report", **record)
        if args.json:
            print(json.dumps(record))
        else:
            print(
                f"diagnose {engine} {grid[0]}x{grid[1]} ({args.dtype}): "
                f"{record['iters']} iterations, "
                f"{'converged' if record['converged'] else 'NOT converged'}; "
                "history-enabled iterates "
                + (
                    "BIT-IDENTICAL to the plain solve"
                    if bit_identical
                    else "DIFFER from the plain solve (contract violation)"
                )
            )
            print(obs_spectrum.render_report(spec))
            if spec.get("eigenvalue_bounds"):
                lo, hi = spec["eigenvalue_bounds"]
                print(
                    f"  chebyshev interval    [{lo:.6g}, {hi:.6g}]  "
                    "(widened Lanczos bounds — what mg.cheby consumes)"
                )
            if diag_spec is not None and diag_spec.get("available"):
                line = (
                    f"  vs diag-PCG           kappa {diag_spec['kappa']:.6g}"
                    f" in {diag_spec['iters']} iterations"
                )
                if spec.get("available"):
                    line += (
                        f" -> {diag_spec['kappa'] / spec['kappa']:.1f}x "
                        "kappa reduction"
                    )
                print(line)
            if prof is not None:
                print(obs_profile.render_profile(prof))
            if args.metrics:
                print(f"metrics snapshot: {record['metrics_path']}")
        if not bit_identical:
            return 2
        return 0 if record["converged"] else 1
    finally:
        if args.trace:
            obs_trace.stop()


def _run_tune(argv: list[str]) -> int:
    """The ``tune`` subcommand: the closed-loop autotuner for one shape.

    Runs ``runtime.autotune`` end to end — telemetry probe (κ and
    Ritz-predicted iterations via ``obs.spectrum``, measured GB/s via
    ``obs.profile``), candidate scoring, winner selection with the
    static default as the anchor it must beat — and prints the chosen
    config against the static default with predicted-vs-measured
    columns. ``--persist`` writes the winner into the registry next to
    the XLA compile cache, where ``build_solver(engine="auto")`` and
    the serve warm pool consult it at admission.
    """
    ap = argparse.ArgumentParser(
        prog="python -m poisson_ellipse_tpu.harness tune",
        description="Telemetry-driven autotuning for one shape: score "
        "engine configurations from measured telemetry (obs.spectrum "
        "Ritz-predicted iterations, obs.profile GB/s), pick a winner "
        "that provably does not lose to the static default, and "
        "optionally persist it next to the XLA compile cache for "
        "engine='auto' and the serve warm pool to consult.",
    )
    ap.add_argument("--grid", help="MxN grid to tune (default 40x40)")
    ap.add_argument("--dtype", choices=sorted(DTYPES), default="f32")
    ap.add_argument(
        "--storage-dtype", choices=("bf16", "f16", "f32"), default=None,
        help="tune the narrow-storage key (separate registry entry: a "
        "narrow executable is a different accuracy contract)",
    )
    ap.add_argument("--delta", type=float, default=1e-6)
    ap.add_argument(
        "--geometry", metavar="SPEC",
        help="tune for an SDF domain (JSON spec file or inline JSON); "
        "the key carries the geometry fingerprint",
    )
    ap.add_argument(
        "--measure", action="store_true",
        help="wall-clock the winner against the static default and "
        "demote a loser before persisting (the measured half of the "
        "never-loses contract; predictions alone decide otherwise)",
    )
    ap.add_argument(
        "--persist", action="store_true",
        help="write the winner into the tuned-config registry "
        "(autotune.json next to the XLA compile cache)",
    )
    ap.add_argument(
        "--registry", metavar="FILE", default=None,
        help="registry path override (default: next to the XLA cache)",
    )
    ap.add_argument("--trace", metavar="FILE", help="JSONL trace sink")
    ap.add_argument("--json", action="store_true", help="one JSON line")
    args = ap.parse_args(argv)

    from poisson_ellipse_tpu.runtime import autotune

    if args.trace:
        obs_trace.start(args.trace)
    try:
        try:
            grid = _parse_grid(args.grid)
            problem = Problem(M=grid[0], N=grid[1], delta=args.delta)
            jdtype = resolve_dtype(args.dtype)
            geometry = _geometry_spec(args.geometry)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        except OSError as e:
            print(f"error: cannot read --geometry: {e}", file=sys.stderr)
            return 2
        except SolveError as e:
            print(f"error: {e.classification}: {e}", file=sys.stderr)
            return e.exit_code
        try:
            registry = (
                autotune.TuneRegistry(args.registry).load()
                if args.registry else None
            )
            report = autotune.tune(
                problem, jdtype, storage_dtype=args.storage_dtype,
                geometry=geometry, registry=registry, persist=args.persist,
                measure=args.measure,
            )
        except SolveError as e:
            # classified failures inside the loop itself (geometry
            # assembly, telemetry probe, measurement solves) exit with
            # the same curated contract as `harness run`
            print(f"error: {e.classification}: {e}", file=sys.stderr)
            return e.exit_code
        if args.json:
            print(json.dumps(report))
            return 0
        chosen = report["chosen"]
        tel = report["telemetry"]
        print(
            f"tune {grid[0]}x{grid[1]} ({args.dtype}"
            + (f", storage {args.storage_dtype}" if args.storage_dtype
               else "")
            + f"): key {report['key']}"
        )
        kappa = tel.get("kappa")
        print(
            "telemetry: kappa "
            + (f"{kappa:.6g}" if kappa is not None else "n/a")
            + f", Ritz-predicted diag iters {tel.get('predicted_iters')}"
            + (f", measured {tel['gbps']:.0f} GB/s" if tel.get("gbps")
               else "")
        )
        print(
            "  candidate            knobs                         "
            "pred iters   pred T(s)    meas T(s)"
        )
        for row in report["candidates"]:
            # the chosen knobs carry the serve chunk on top of the
            # candidate's own — subset match identifies the winner row
            marker = "->" if (
                row["engine"] == chosen["engine"]
                and all(chosen["knobs"].get(k) == v
                        for k, v in row["knobs"].items())
            ) else "  "
            measured = ""
            if row["engine"] == chosen["engine"] and chosen.get(
                    "measured_t_s") is not None:
                measured = f"{chosen['measured_t_s']:12.5f}"
            elif row["engine"] == chosen.get("static_engine") and chosen.get(
                    "static_measured_t_s") is not None:
                measured = f"{chosen['static_measured_t_s']:12.5f}"
            knobs = ",".join(f"{k}={v}" for k, v in row["knobs"].items())
            print(
                f"{marker} {row['engine']:18s} {knobs:28s} "
                f"{row['predicted_iters']:10.1f} "
                f"{row['predicted_t_s']:11.6f} {measured}"
            )
        static = chosen.get("static_engine")
        if chosen["engine"] == static:
            print(
                f"chosen: the static default ({static}) stands"
                + ("; predicted winner DEMOTED after measurement"
                   if report["demoted_to_static"] else "")
            )
        else:
            print(
                f"chosen: {chosen['engine']} over static default "
                f"{static}"
                + (" (measured winner)" if chosen.get("measured_t_s")
                   is not None else " (predicted winner)")
                + ("; DEMOTED to static after measurement"
                   if report["demoted_to_static"] else "")
            )
        if report.get("registry_path"):
            print(f"persisted: {report['registry_path']}")
        return 0
    finally:
        obs_metrics.REGISTRY.emit()
        obs_metrics.REGISTRY.reset()
        if args.trace:
            obs_trace.stop()


def _run_warmup(argv: list[str]) -> int:
    """The ``warmup`` subcommand: pre-fill the compilation caches.

    Wires up the persistent XLA cache and AOT-compiles the batched
    engines' bucket executables for the requested grids/lane counts
    (``runtime.compile_cache``), so a serving worker's first real
    request is a cache hit instead of a cold compile. Hit/miss counts
    land on the trace (``cache:hit`` / ``cache:miss`` events).
    """
    from poisson_ellipse_tpu.runtime import compile_cache

    ap = argparse.ArgumentParser(
        prog="python -m poisson_ellipse_tpu.harness warmup",
        description="Warm the compilation caches: enable the persistent "
        "XLA cache and AOT-compile bucketed executables for the batched "
        "engines, keyed (engine, grid-bucket, dtype, lane-bucket). "
        "Arbitrary request sizes then hit a warm executable by "
        "pad-and-mask embedding.",
    )
    ap.add_argument(
        "--grids", default="40x40",
        help="comma list of MxN grids to warm buckets for",
    )
    ap.add_argument(
        "--lanes", default="1,8",
        help="comma list of lane counts (each rounds up to its bucket)",
    )
    ap.add_argument(
        "--engine", default="batched",
        choices=("batched", "batched-pipelined", "both"),
    )
    ap.add_argument("--dtype", choices=sorted(DTYPES), default="f32")
    ap.add_argument(
        "--cache-dir", default=None,
        help="persistent XLA cache directory (default: "
        "$POISSON_COMPILE_CACHE or ~/.cache/poisson_ellipse_tpu/xla)",
    )
    ap.add_argument(
        "--no-persistent", action="store_true",
        help="skip the persistent XLA cache wiring (in-process pool only)",
    )
    ap.add_argument("--trace", metavar="FILE", help="JSONL trace sink")
    ap.add_argument("--json", action="store_true", help="one JSON line")
    args = ap.parse_args(argv)

    if args.trace:
        obs_trace.start(args.trace)
    try:
        if not args.no_persistent:
            cache_dir = compile_cache.enable_persistent_cache(args.cache_dir)
        else:
            cache_dir = None
        engines = (
            ("batched", "batched-pipelined")
            if args.engine == "both"
            else (args.engine,)
        )
        try:
            grids = [_parse_grid(spec) for spec in args.grids.split(",")]
            lane_counts = [int(x) for x in args.lanes.split(",")]
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        pool = compile_cache.warm_pool()
        rows = []
        dtype = resolve_dtype(args.dtype)
        for engine in engines:
            for grid in grids:
                for lanes in lane_counts:
                    entry = pool.warmup(engine, grid, dtype, lanes)
                    rows.append({
                        "engine": engine,
                        "grid": list(grid),
                        "bucket": list(entry.bucket),
                        "lanes": lanes,
                        "lane_bucket": entry.lanes,
                        "compile_s": round(entry.compile_s, 4),
                    })
        record = {
            "persistent_dir": cache_dir,
            "warmed": rows,
            "hits": pool.hits,
            "misses": pool.misses,
        }
        obs_trace.event("warmup_report", **record)
        if args.json:
            print(json.dumps(record))
        else:
            for row in rows:
                print(
                    f"warm {row['engine']:18s} {row['grid'][0]}x"
                    f"{row['grid'][1]} -> bucket {row['bucket'][0]}x"
                    f"{row['bucket'][1]} lanes {row['lanes']} -> "
                    f"{row['lane_bucket']}  compile "
                    + (
                        f"{row['compile_s']:.3f}s"
                        if row["compile_s"] else "cached"
                    )
                )
            print(
                f"warm pool: {pool.misses} compiled, {pool.hits} already "
                "warm"
                + (f"; persistent cache at {cache_dir}" if cache_dir else "")
            )
        return 0
    finally:
        if args.trace:
            obs_trace.stop()


def _run_serve(argv: list[str]) -> int:
    """The ``serve`` subcommand: a synthetic arrival stream through the
    continuous-batching scheduler — the serving layer exercised from
    the command line, lifecycle events on the trace, latency quantiles
    in the report."""
    import random
    import time as _time

    from poisson_ellipse_tpu.serve import Scheduler

    ap = argparse.ArgumentParser(
        prog="python -m poisson_ellipse_tpu.harness serve",
        description="Continuous-batching serve drill: drive a seeded "
        "Poisson arrival stream of mixed shapes through the scheduler "
        "(bounded admission, chunk-boundary lane retirement/refill, "
        "deadlines, retry ladder, optional crash-safe journal). "
        "exit code = the WORST per-request outcome of the stream "
        "(numerically highest of the per-request contract): 0 every "
        "request completed; 1 iteration cap; 2 failed/diverged (also "
        "invalid invocations, per argparse convention); 4 deadline "
        "missed; 5 shed at admission (backpressure — resubmit after "
        "retry_after_s).",
    )
    ap.add_argument("--requests", type=int, default=12)
    ap.add_argument(
        "--grids", default="10x10,12x12",
        help="comma list of MxN request shapes, mixed by the seeded RNG",
    )
    ap.add_argument(
        "--rate", type=float, default=200.0,
        help="Poisson arrival rate (requests/second of wall clock)",
    )
    ap.add_argument("--lanes", type=int, default=4)
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--queue-capacity", type=int, default=64)
    ap.add_argument(
        "--deadline", type=float, default=None, metavar="SECONDS",
        help="per-request deadline (admission sheds infeasible asks; "
        "mid-solve expiry cancels at a chunk boundary, partial result)",
    )
    ap.add_argument("--retries", type=int, default=1)
    ap.add_argument(
        "--journal", metavar="FILE",
        help="crash-safe request journal; admitted-but-unfinished "
        "requests replay on the next start (see --replay)",
    )
    ap.add_argument(
        "--replay", action="store_true",
        help="replay the journal's unfinished requests before the new "
        "stream (requires --journal)",
    )
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--dtype", choices=sorted(DTYPES), default="f32")
    ap.add_argument(
        "--warm-start", action="store_true",
        help="per-bucket solve-cache pools (runtime.solvecache): "
        "consult on admission, deposit on retirement; replays always "
        "run cold (solvecache_hit_total / recycle:hit on the trace)",
    )
    ap.add_argument("--trace", metavar="FILE", help="JSONL trace sink")
    ap.add_argument(
        "--metrics", metavar="FILE",
        help="OpenMetrics snapshot of the serving counters/histograms",
    )
    ap.add_argument("--json", action="store_true", help="one JSON line")
    args = ap.parse_args(argv)

    if args.trace:
        obs_trace.start(args.trace)
    try:
        try:
            if args.replay and not args.journal:
                raise ValueError("--replay needs --journal")
            grids = [_parse_grid(spec) for spec in args.grids.split(",")]
            if args.requests < (0 if args.replay else 1):
                # --requests 0 is the pure-replay restart: drain the
                # journal's unfinished admissions, admit nothing new
                raise ValueError(
                    "--requests must be >= 1 (0 allowed with --replay)"
                )
            if args.rate <= 0:
                raise ValueError("--rate must be > 0 requests/second")
            sched = Scheduler(
                lanes=args.lanes, chunk=args.chunk,
                queue_capacity=args.queue_capacity,
                dtype=resolve_dtype(args.dtype),
                max_retries=args.retries, journal=args.journal,
                keep_solutions=False, warm_start=args.warm_start,
            )
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        replayed = sched.replay() if args.replay else 0
        rng = random.Random(args.seed)
        t0 = _time.monotonic()
        # results are harvested through collect() as the stream runs —
        # the eviction hand-off a long-lived server needs (the
        # scheduler's buffer stays bounded by the in-flight window)
        results: dict = {}
        drained_early = False
        with _SigtermDrain() as term:
            for _ in range(args.requests):
                if term.requested:
                    # SIGTERM: stop admitting, finish (or journal) the
                    # in-flight work, flush, exit 0 — the trace tail
                    # survives the shutdown instead of dying with it
                    drained_early = True
                    sched.begin_drain()
                    obs_trace.event(
                        "serve:sigterm-drain", queued=len(sched.queue),
                    )
                    break
                M, N = rng.choice(grids)
                sched.submit(
                    Problem(M=M, N=N), deadline_s=args.deadline,
                )
                _time.sleep(min(rng.expovariate(args.rate), 0.05))
                sched.step()
                results.update(sched.collect())
            sched.drain()
            results.update(sched.collect())
        wall = _time.monotonic() - t0
        counts: dict[str, int] = {}
        for res in results.values():
            counts[res.outcome] = counts.get(res.outcome, 0) + 1
        completed = counts.get("completed", 0)
        lat = obs_metrics.REGISTRY.histogram("time_in_queue_seconds")
        record = {
            "requests": args.requests,
            "replayed": replayed,
            "outcomes": counts,
            "solves_per_sec": round(completed / wall, 2) if wall else None,
            "queue_p50_s": lat.quantile(0.5),
            "queue_p99_s": lat.quantile(0.99),
            "wall_s": round(wall, 4),
            "drained_on_sigterm": drained_early,
        }
        obs_trace.event("serve_report", **record)
        if args.metrics:
            from poisson_ellipse_tpu.obs.export import MetricsExporter

            err = MetricsExporter(args.metrics).try_write()
            if err is not None:
                print(
                    f"warning: metrics snapshot failed: {err}",
                    file=sys.stderr,
                )
        if args.json:
            print(json.dumps(record))
        else:
            outcome_str = ", ".join(
                f"{k}={v}" for k, v in sorted(counts.items())
            )
            print(
                f"serve: {args.requests} requests (+{replayed} replayed) "
                f"in {wall:.2f}s — {outcome_str}; "
                f"{record['solves_per_sec']} solves/sec sustained"
            )
        # the documented contract: exit with the worst (numerically
        # highest) per-request outcome, so a gate scripting on the
        # help text classifies deadline misses and sheds as themselves
        # rather than as convergence failures. A SIGTERM'd run that
        # drained cleanly exits 0 — graceful shutdown is a success,
        # not the worst outcome of a stream it cut short.
        from poisson_ellipse_tpu.serve import EXIT_BY_OUTCOME

        if drained_early:
            return 0
        return max((EXIT_BY_OUTCOME[o] for o in counts), default=0)
    finally:
        obs_metrics.REGISTRY.emit()
        obs_metrics.REGISTRY.reset()
        if args.trace:
            obs_trace.stop()


def _run_grad(argv: list[str]) -> int:
    """The ``grad`` subcommand: the differentiable-solving workloads
    (``diff.optimize``) end-to-end — ellipse-recovers-itself inverse
    geometry or inverse-source recovery, driven by IFT adjoints through
    the converged solve (``diff.adjoint``)."""
    ap = argparse.ArgumentParser(
        prog="python -m poisson_ellipse_tpu.harness grad",
        description="Differentiable solving (diff/): gradients of a "
        "functional of the converged solution via implicit-function-"
        "theorem adjoints — one extra PCG solve with the same operator "
        "per gradient. Workloads: 'ellipse' recovers randomly perturbed "
        "ellipse parameters from the solution they produced (acceptance "
        "rel err <= 1e-3); 'source' recovers a per-node source field "
        "(acceptance misfit drop >= 100x). Exit 0 on acceptance, 2 "
        "otherwise.",
    )
    ap.add_argument("--workload", choices=("ellipse", "source"),
                    default="ellipse")
    ap.add_argument("--grid", default=None, metavar="MxN",
                    help="grid (default 24x24 ellipse / 16x16 source)")
    ap.add_argument("--engine", choices=("xla", "pipelined", "mg-pcg",
                                         "cheb-pcg"), default="xla")
    ap.add_argument("--steps", type=int, default=None,
                    help="optimizer step cap (workload defaults)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--trace", metavar="FILE", help="JSONL trace sink")
    ap.add_argument("--json", action="store_true", help="one JSON line")
    args = ap.parse_args(argv)

    import jax

    from poisson_ellipse_tpu.diff import optimize as diff_optimize

    # the diff/ contract is f64 (gradient accuracy is quoted against
    # the solve tolerance) — flip x64 like the menu's f64 entry does
    # (harness.run.resolve_dtype): a process-global flag, set before
    # any trace is built
    if not jax.config.jax_enable_x64:
        jax.config.update("jax_enable_x64", True)
    if args.trace:
        obs_trace.start(args.trace)
    try:
        kwargs = {"engine": args.engine, "seed": args.seed}
        if args.grid is not None:
            try:
                kwargs["grid"] = _parse_grid(args.grid)
            except ValueError as e:
                print(f"error: {e}", file=sys.stderr)
                return 2
        if args.steps is not None:
            kwargs["steps"] = args.steps
        if args.workload == "ellipse":
            report = diff_optimize.recover_ellipse(**kwargs)
        else:
            report = diff_optimize.recover_source(**kwargs)
        if args.json:
            print(json.dumps(report))
        elif args.workload == "ellipse":
            print(
                f"grad/{report['workload']}: grid "
                f"{report['grid'][0]}x{report['grid'][1]} engine "
                f"{report['engine']} — rel err {report['rel_err']:.2e} "
                f"(acceptance 1e-3), misfit "
                f"{report['misfit_initial']:.3e} -> "
                f"{report['misfit_final']:.3e}, "
                f"{report['n_evals']} value+grad evals — "
                f"{'OK' if report['ok'] else 'NOT CONVERGED'}"
            )
        else:
            print(
                f"grad/{report['workload']}: grid "
                f"{report['grid'][0]}x{report['grid'][1]} engine "
                f"{report['engine']} — misfit drop "
                f"{report['misfit_drop']:.1f}x (acceptance 100x), "
                f"{report['n_evals']} value+grad evals — "
                f"{'OK' if report['ok'] else 'NOT CONVERGED'}"
            )
        return 0 if report["ok"] else 2
    finally:
        obs_metrics.REGISTRY.emit()
        obs_metrics.REGISTRY.reset()
        if args.trace:
            obs_trace.stop()


def _run_chaos(argv: list[str]) -> int:
    """The ``chaos`` subcommand: the serving invariants under injected
    lane NaN, fake OOM and a kill/restart — zero lost, zero
    double-completed, every outcome classified."""
    import os
    import tempfile

    from poisson_ellipse_tpu.serve import run_chaos

    ap = argparse.ArgumentParser(
        prog="python -m poisson_ellipse_tpu.harness chaos",
        description="Serving chaos drill (serve.chaos): a seeded Poisson "
        "stream of mixed shapes with an injected NaN-poisoned lane, a "
        "fake RESOURCE_EXHAUSTED dispatch, and one mid-stream "
        "kill/restart with journal replay. Exit 0 iff zero requests "
        "were lost, none double-completed, and every terminal state is "
        "a classified outcome; exit 2 otherwise.",
    )
    ap.add_argument("--requests", type=int, default=50)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--grids", default="10x10,12x12,8x8")
    ap.add_argument("--lanes", type=int, default=4)
    ap.add_argument("--chunk", type=int, default=8)
    ap.add_argument(
        "--no-kill", action="store_true",
        help="skip the kill/restart (fault injection only)",
    )
    ap.add_argument(
        "--mesh", action="store_true",
        help="add the mesh-kill drill: a simulated device loss takes "
        "out every live batch carry mid-stream and every in-flight "
        "request must re-enter through the journal/retry ladder — the "
        "zero-lost/zero-double invariants asserted across a DEVICE "
        "kill, not just a process kill",
    )
    ap.add_argument(
        "--journal", metavar="FILE",
        help="journal path (default: a temp file, removed after)",
    )
    ap.add_argument(
        "--deadline", type=float, default=None, metavar="SECONDS",
        help="per-request deadline for the stream",
    )
    ap.add_argument(
        "--warm-start", action="store_true",
        help="run the drill with the per-bucket recycle pools ON "
        "(runtime.solvecache) and a cache_poison fault armed on one "
        "request: the zero-lost/zero-double/all-classified triple must "
        "hold unchanged with recycling enabled, and the poisoned "
        "consult may only cost iterations",
    )
    ap.add_argument("--trace", metavar="FILE", help="JSONL trace sink")
    ap.add_argument("--json", action="store_true", help="one JSON line")
    args = ap.parse_args(argv)

    if args.trace:
        obs_trace.start(args.trace)
    try:
        try:
            grids = tuple(
                _parse_grid(spec) for spec in args.grids.split(",")
            )
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        tmp_dir = None
        journal = args.journal
        if journal is None and not args.no_kill:
            tmp_dir = tempfile.TemporaryDirectory()
            journal = os.path.join(tmp_dir.name, "chaos-journal.json")
        try:
            report = run_chaos(
                n_requests=args.requests, seed=args.seed, grids=grids,
                lanes=args.lanes, chunk=args.chunk,
                journal_path=journal,
                kill_after=None if not args.no_kill else 0,
                deadline_s=args.deadline,
                mesh_kill_request=(
                    max(args.requests // 3, 1) if args.mesh else None
                ),
                warm_start=args.warm_start,
                poison_request=(
                    max(args.requests // 4, 1) if args.warm_start else None
                ),
            )
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        finally:
            if tmp_dir is not None:
                tmp_dir.cleanup()
        if args.json:
            print(json.dumps(report.json_dict()))
        else:
            verdict = "OK" if report.ok else "INVARIANT VIOLATION"
            mesh_note = (
                "; mesh-kill drill fired" if report.mesh_killed else ""
            )
            print(
                f"chaos: {report.n_requests} requests, seed {args.seed} — "
                f"{verdict}; outcomes {report.counts}; "
                f"replayed {report.replayed} after kill; "
                f"{report.faults_fired} faults fired{mesh_note}; "
                f"lost {len(report.lost)}, doubled "
                f"{len(report.double_completed)} ({report.wall_s:.2f}s)"
            )
        return 0 if report.ok else 2
    finally:
        obs_metrics.REGISTRY.emit()
        obs_metrics.REGISTRY.reset()
        if args.trace:
            obs_trace.stop()


def _run_fleet(argv: list[str]) -> int:
    """The ``fleet`` subcommand: an N-replica Poisson drill through the
    replicated serving layer (``fleet.FleetRouter``) — shape-affinity
    routing, lease-checked replicas, optional mid-stream replica kill
    with journal-backed handoff, optional REJOIN of the killed replica
    as a fresh incarnation, a pluggable (memory/file) lease store,
    SIGTERM-graceful drain."""
    import os as _os
    import random
    import tempfile
    import time as _time

    from poisson_ellipse_tpu.fleet import FileLeaseStore, FleetRouter
    from poisson_ellipse_tpu.resilience import faultinject
    from poisson_ellipse_tpu.resilience.errors import FleetUnavailableError
    from poisson_ellipse_tpu.serve import EXIT_BY_OUTCOME

    ap = argparse.ArgumentParser(
        prog="python -m poisson_ellipse_tpu.harness fleet",
        description="Replicated-serving drill: a seeded Poisson stream "
        "of mixed shapes routed over N scheduler replicas "
        "(compile-bucket affinity, per-replica backpressure, lease "
        "health checks). --kill-replica-at SIGKILLs replica 0 at that "
        "arrival index: its journal hands off to the survivors with "
        "remaining-deadline budgets preserved, and the stream "
        "continues. SIGTERM drains gracefully (stop admitting, finish "
        "in-flight, flush, exit 0). exit code = the worst per-request "
        "outcome; 9 when every replica is down "
        "(FleetUnavailableError). " + EXIT_CODES_HELP,
    )
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--requests", type=int, default=12)
    ap.add_argument(
        "--kill-replica-at", type=int, default=None, metavar="INDEX",
        help="SIGKILL replica 0 when arrival INDEX lands (journal "
        "handoff drill); default: no kill",
    )
    ap.add_argument(
        "--rejoin-at", type=int, default=None, metavar="INDEX",
        help="re-enter the killed replica 0 as a FRESH incarnation "
        "when arrival INDEX lands (fresh epoch, archived-journal "
        "replay, warm-pool pre-warm); needs --kill-replica-at earlier "
        "in the stream",
    )
    ap.add_argument(
        "--lease-store", choices=("memory", "file"), default="memory",
        help="the fleet's lease/fencing store: 'memory' is the "
        "in-process default; 'file' persists epochs to "
        "<journal-dir>/lease-store.json (atomic rename, fsync) so a "
        "restarted driver fences against the previous run's epochs",
    )
    ap.add_argument("--grids", default="10x10,12x12")
    ap.add_argument("--rate", type=float, default=200.0)
    ap.add_argument("--lanes", type=int, default=2,
                    help="lanes per replica")
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--queue-capacity", type=int, default=64)
    ap.add_argument("--deadline", type=float, default=None,
                    metavar="SECONDS")
    ap.add_argument("--retries", type=int, default=1)
    ap.add_argument(
        "--journal-dir", metavar="DIR",
        help="fleet journal directory, one ledger per replica "
        "(default: a temp dir, removed after)",
    )
    ap.add_argument(
        "--lease", type=float, default=0.5, metavar="SECONDS",
        help="replica lease length (monotonic-clock heartbeat)",
    )
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--trace", metavar="FILE", help="JSONL trace sink")
    ap.add_argument(
        "--metrics", metavar="FILE",
        help="OpenMetrics snapshot of the fleet counters/histograms",
    )
    ap.add_argument("--json", action="store_true", help="one JSON line")
    args = ap.parse_args(argv)

    if args.trace:
        obs_trace.start(args.trace)
    tmp_dir = None
    try:
        try:
            grids = [_parse_grid(spec) for spec in args.grids.split(",")]
            if args.replicas < 1:
                raise ValueError("--replicas must be >= 1")
            if args.requests < 1:
                raise ValueError("--requests must be >= 1")
            if args.rate <= 0:
                raise ValueError("--rate must be > 0 requests/second")
            if args.rejoin_at is not None:
                if args.kill_replica_at is None:
                    raise ValueError(
                        "--rejoin-at needs --kill-replica-at: only a "
                        "dead replica can rejoin"
                    )
                if args.rejoin_at <= args.kill_replica_at:
                    raise ValueError(
                        "--rejoin-at must land strictly after "
                        "--kill-replica-at"
                    )
            journal_dir = args.journal_dir
            if journal_dir is None:
                tmp_dir = tempfile.TemporaryDirectory()
                journal_dir = tmp_dir.name
            faults = []
            if args.kill_replica_at is not None:
                faults.append(faultinject.replica_kill(
                    at_request=args.kill_replica_at, replica=0,
                ))
            lease_store = None
            if args.lease_store == "file":
                lease_store = FileLeaseStore(
                    _os.path.join(journal_dir, "lease-store.json"),
                )
            router = FleetRouter(
                replicas=args.replicas,
                journal_dir=journal_dir,
                lease_s=args.lease,
                lease_store=lease_store,
                faults=faultinject.FaultPlan(*faults),
                lanes=args.lanes,
                chunk=args.chunk,
                queue_capacity=args.queue_capacity,
                max_retries=args.retries,
                keep_solutions=False,
            )
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        rng = random.Random(args.seed)
        t0 = _time.monotonic()
        results: dict = {}
        drained_early = False
        try:
            with _SigtermDrain() as term:
                for i in range(args.requests):
                    if term.requested:
                        drained_early = True
                        obs_trace.event("serve:sigterm-drain")
                        results.update(router.shutdown())
                        break
                    M, N = rng.choice(grids)
                    router.submit(
                        Problem(M=M, N=N), deadline_s=args.deadline,
                    )
                    _time.sleep(min(rng.expovariate(args.rate), 0.05))
                    router.step()
                    if (args.rejoin_at is not None
                            and i >= args.rejoin_at
                            and not router.rejoins):
                        victim = router._by_id(0)
                        if victim is not None and not victim.live:
                            router.rejoin_replica(0)
                    results.update(router.collect())
                else:
                    results.update(router.drain())
                    results.update(router.collect())
        except FleetUnavailableError as e:
            print(
                f"error: fleet unavailable — {e}",
                file=sys.stderr,
            )
            return e.exit_code
        wall = _time.monotonic() - t0
        counts: dict[str, int] = {}
        for res in results.values():
            counts[res.outcome] = counts.get(res.outcome, 0) + 1
        completed = counts.get("completed", 0)
        handoff = obs_metrics.REGISTRY.histogram(
            obs_metrics.HANDOFF_LATENCY_SECONDS
        )
        record = {
            "replicas": args.replicas,
            "requests": args.requests,
            "outcomes": counts,
            "solves_per_sec": round(completed / wall, 2) if wall else None,
            "handoffs": router.handoffs,
            "adopted": router.adopted_total,
            "handoff_p99_s": handoff.quantile(0.99),
            "rejoins": router.rejoins,
            "rejoin_p99_s": obs_metrics.REGISTRY.histogram(
                obs_metrics.REJOIN_LATENCY_SECONDS
            ).quantile(0.99),
            "lease_store": args.lease_store,
            "live_replicas": [r.replica_id for r in router.live_replicas()],
            "wall_s": round(wall, 4),
            "drained_on_sigterm": drained_early,
        }
        obs_trace.event("fleet_report", **record)
        if args.metrics:
            from poisson_ellipse_tpu.obs.export import MetricsExporter

            err = MetricsExporter(args.metrics).try_write()
            if err is not None:
                print(
                    f"warning: metrics snapshot failed: {err}",
                    file=sys.stderr,
                )
        if args.json:
            print(json.dumps(record))
        else:
            outcome_str = ", ".join(
                f"{k}={v}" for k, v in sorted(counts.items())
            )
            print(
                f"fleet: {args.requests} requests over {args.replicas} "
                f"replicas in {wall:.2f}s — {outcome_str}; "
                f"{record['solves_per_sec']} solves/sec aggregate; "
                f"{router.handoffs} handoff(s), {router.adopted_total} "
                "request(s) adopted"
            )
        if drained_early:
            return 0
        return max((EXIT_BY_OUTCOME[o] for o in counts), default=0)
    finally:
        obs_metrics.REGISTRY.emit()
        obs_metrics.REGISTRY.reset()
        if tmp_dir is not None:
            tmp_dir.cleanup()
        if args.trace:
            obs_trace.stop()


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "inspect":
        return _run_inspect(argv[1:])
    if argv and argv[0] == "inject":
        return _run_inject(argv[1:])
    if argv and argv[0] == "warmup":
        return _run_warmup(argv[1:])
    if argv and argv[0] == "tune":
        return _run_tune(argv[1:])
    if argv and argv[0] == "diagnose":
        return _run_diagnose(argv[1:])
    if argv and argv[0] == "serve":
        return _run_serve(argv[1:])
    if argv and argv[0] == "fleet":
        return _run_fleet(argv[1:])
    if argv and argv[0] == "chaos":
        return _run_chaos(argv[1:])
    if argv and argv[0] == "grad":
        return _run_grad(argv[1:])
    ap = argparse.ArgumentParser(
        prog="python -m poisson_ellipse_tpu.harness",
        description="Fictitious-domain Poisson PCG on TPU",
        epilog=EXIT_CODES_HELP,
    )
    ap.add_argument("M", type=int, nargs="?", help="grid cells in x")
    ap.add_argument("N", type=int, nargs="?", help="grid cells in y")
    ap.add_argument(
        "--grids", help="comma list of MxN grids to sweep, e.g. 400x600,800x1200"
    )
    ap.add_argument(
        "--mode",
        choices=("auto", "single", "sharded", "native"),
        default="auto",
    )
    ap.add_argument(
        "--engine",
        choices=ENGINES,
        default="auto",
        help="solver engine. Single-device: auto picks the fastest whose "
        "capacity regime applies (resident -> streamed -> xl; f64 takes "
        "xla); fused is the two-kernel "
        "HBM iteration, pallas the per-op stencil kernel, pipelined the "
        "one-fused-reduction-per-iteration recurrence (pipelined-pallas: "
        "same loop through the fused stencil+partials kernel); batched/"
        "batched-pipelined run --lanes independent solves per dispatch "
        "(the throughput engines, per-lane results); sstep/sstep-pallas "
        "run the s-step communication-avoiding recurrence (--sstep-s "
        "iterations per matrix-powers round); fmg runs ONE full-"
        "multigrid F-cycle (O(N) work, constant per grid point) plus "
        "the verified mg-pcg handoff against delta. Sharded "
        "mode: xla (default), pallas (the per-shard stencil kernel), "
        "fused (the two-kernel per-shard iteration, f32/bf16), "
        "pipelined (one stacked psum per iteration), sstep (ONE psum + "
        "one s-deep halo per s iterations), fmg (per-level halo "
        "discipline, classical psum cadence in the handoff), mg-pcg/"
        "cheb-pcg, or batched/batched-pipelined with --lanes sharded "
        "over the mesh",
    )
    ap.add_argument(
        "--threads",
        type=int,
        default=0,
        help="OpenMP thread count for --mode native (0 = default)",
    )
    ap.add_argument(
        "--threads-sweep",
        help="comma list of OpenMP thread counts to sweep with --mode "
        "native, printing the stage1 table (T per count + speedup vs the "
        "first count; Этап1.pdf table 2's in-run sweep)",
    )
    ap.add_argument(
        "--mesh",
        type=int,
        nargs=2,
        metavar=("PX", "PY"),
        help="device mesh shape (default: near-square over all devices)",
    )
    ap.add_argument(
        "--dtype",
        choices=sorted(DTYPES),
        default="f32",
        help="f64 flips jax_enable_x64 for the whole process (a global "
        "JAX switch: later runs in the same process stay x64-enabled)",
    )
    ap.add_argument("--delta", type=float, default=1e-6)
    ap.add_argument(
        "--storage-dtype",
        choices=("bf16", "f16", "f32"),
        default=None,
        metavar="DT",
        help="HBM storage width for state/operand streams, separate from "
        "the compute dtype (ops.precision): bf16 halves the loop's HBM "
        "bytes while every stencil/reduction upcasts to --dtype "
        "tile-locally. The raw engines converge to the storage floor; "
        "with --guard the escalation ladder (bf16 -> f32 -> f64) "
        "promotes the solve to full width before accepting convergence "
        "— the accuracy-recovered product path. Covers engines "
        "xla/pallas/pipelined*/sstep*/streamed/xl/batched (sharded: "
        "sstep)",
    )
    ap.add_argument(
        "--sstep-s",
        type=int,
        choices=(2, 4),
        default=4,
        metavar="S",
        help="block size of the s-step engines (--engine sstep/"
        "sstep-pallas): S iterations per matrix-powers round — sharded, "
        "ONE psum + one S-deep halo per S iterations",
    )
    ap.add_argument("--eps", type=float, default=None)
    ap.add_argument(
        "--eps-sweep",
        help="comma list of eps values to sweep (overrides --eps)",
    )
    ap.add_argument(
        "--norm", choices=("weighted", "unweighted"), default="weighted"
    )
    ap.add_argument("--max-iter", type=int, default=None)
    ap.add_argument(
        "--geometry",
        metavar="SPEC",
        help="solve on an arbitrary SDF domain: a path to a JSON "
        "geometry spec file, or the inline JSON itself (geom.sdf "
        "primitives + union/intersection/difference/translate). The "
        "admissibility gate (geom.validate) runs before any device "
        "dispatch — a bad spec is the classified exit 8, never a hung "
        "solve. The default (no flag) is the closed-form ellipse, "
        "bit-identical to previous releases",
    )
    ap.add_argument(
        "--theta",
        type=float,
        default=None,
        metavar="FRAC",
        help="degenerate-cut clamp threshold for --geometry: face "
        "fractions within theta of empty/full snap to empty/full, each "
        "clamp reported as a geom:degenerate-cut trace event (default: "
        "geom.quadrature.DEFAULT_THETA; 0 disables the defense)",
    )
    ap.add_argument("--repeat", type=int, default=1, help="timing repetitions")
    ap.add_argument(
        "--batch",
        type=int,
        default=1,
        help="TIMING protocol: dispatches chained per repetition so the "
        "fixed host<->device RTT cancels out of T_solver. This does NOT "
        "batch solves onto the chip — that is --lanes",
    )
    ap.add_argument(
        "--lanes",
        type=int,
        default=1,
        help="REAL lane batching: run N independent solves inside one "
        "dispatch via the batched engines (--engine batched/"
        "batched-pipelined; auto resolves to batched when N > 1). "
        "Reports per-dispatch T_solver plus aggregate solves/sec. "
        "Distinct from --batch, which only chains dispatches to time "
        "them",
    )
    ap.add_argument(
        "--recycle",
        type=int,
        nargs="?",
        const=-1,  # bare flag → solver.recycle.RECYCLE_CAP, resolved below
        default=None,
        metavar="CAP",
        help="Krylov recycling (solver.recycle): one untimed ring-"
        "carrying capture solve harvests the extremal Ritz deflation "
        "basis, then the timed solve restarts deflated (x0 = the "
        "Galerkin projection of the rhs) — the report's iters is the "
        "deflated count. CAP is the Lanczos-vector ring capacity "
        "(default: solver.recycle.RECYCLE_CAP); rides the single-device "
        "xla engine. Correctness never depends on the basis: any x0 is "
        "verified by its TRUE residual at init",
    )
    ap.add_argument(
        "--warm-start",
        action="store_true",
        help="seed the timed solve with a prior solve's solution — the "
        "semantic-cache-hit shape (runtime.solvecache); stacks on "
        "--recycle (the hit is deflated against its true residual). "
        "Warm-started solution bits legitimately differ from cold",
    )
    ap.add_argument(
        "--checkpoint-dir",
        help="persist the PCG carry here every --chunk iterations and "
        "resume from it after a kill (single and sharded modes; sharded "
        "carries are saved with their mesh shardings)",
    )
    ap.add_argument(
        "--chunk",
        type=int,
        default=500,
        help="iterations between checkpoints (with --checkpoint-dir)",
    )
    ap.add_argument(
        "--timeout",
        type=float,
        default=None,
        metavar="SECONDS",
        help="per-solve deadline, enforced at guard chunk boundaries "
        "(graceful cancel: the in-flight chunk completes, a partial "
        "schema-valid trace is emitted, exit code 4); implies --guard",
    )
    ap.add_argument(
        "--guard",
        action="store_true",
        help="run through resilience.guard: chunked execution with a "
        "per-chunk device-side health word (breakdown/NaN/stagnation), "
        "the recovery ladder (residual restart -> f32->f64 escalation "
        "-> engine fallback), and classified errors instead of NaN "
        "results",
    )
    ap.add_argument(
        "--max-recoveries",
        type=int,
        default=3,
        help="recovery-action budget for guarded runs before the solve "
        "is classified diverged (exit code 2)",
    )
    ap.add_argument(
        "--profile",
        action="store_true",
        help="segmented per-phase iteration profile (stage4 timer taxonomy)",
    )
    ap.add_argument(
        "--trace-dir",
        help="capture a jax.profiler trace of the solve into this directory "
        "(open with TensorBoard / xprof)",
    )
    ap.add_argument(
        "--trace",
        metavar="FILE",
        help="append a structured JSONL run trace (phase spans, run-report "
        "events, counters; obs.trace schema) to FILE; POISSON_TRACE=FILE "
        "in the environment does the same without the flag",
    )
    ap.add_argument(
        "--metrics",
        metavar="FILE",
        help="export the run's counters/gauges/histograms as an "
        "OpenMetrics snapshot to FILE (obs.export; written periodically "
        "while running — point a scraper at it — and once at exit)",
    )
    ap.add_argument(
        "--metrics-interval",
        type=float,
        default=30.0,
        metavar="SECONDS",
        help="periodic snapshot cadence for --metrics",
    )
    ap.add_argument("--json", action="store_true", help="one JSON line per run")
    args = ap.parse_args(argv)

    if args.metrics and args.metrics_interval <= 0:
        print(
            "error: --metrics-interval must be positive (a zero cadence "
            "would busy-spin the exporter thread)",
            file=sys.stderr,
        )
        return 2
    if args.trace:
        obs_trace.start(args.trace)
    obs_trace.event("cli-args", argv=list(argv))
    exporter = None
    if args.metrics:
        from poisson_ellipse_tpu.obs.export import MetricsExporter

        exporter = MetricsExporter(
            args.metrics, interval_s=args.metrics_interval
        )
        # fail FAST on an unwritable path: a snapshot that can only
        # fail at exit would crash the finally block after a good
        # run — bad input is the up-front exit-2 contract
        err = exporter.try_write()
        if err is not None:
            print(
                f"error: cannot write --metrics {args.metrics}: {err}",
                file=sys.stderr,
            )
            if args.trace:
                obs_trace.stop()
            return 2
        exporter.start()
    rc = None
    try:
        rc = _run_cli(args)
        return rc
    finally:
        # emit/reset unconditionally (crashed runs included): per-run
        # aggregates — a later main() in the same process must not
        # report this run's counts as its own. The metrics snapshot
        # flushes BEFORE the reset, or the exported file would be empty.
        obs_metrics.REGISTRY.emit()
        if exporter is not None:
            # the path was validated up front, but a filesystem can
            # still die mid-run: report it, never mask the solve's
            # result or skip the reset/stop cleanup below
            exporter.stop(final_write=False)
            err = exporter.try_write()
            if err is not None:
                print(
                    f"warning: metrics snapshot failed: {err}",
                    file=sys.stderr,
                )
        obs_metrics.REGISTRY.reset()
        obs_trace.event("cli-exit", rc="error" if rc is None else rc)
        if args.trace:
            obs_trace.stop()


def _geometry_spec(arg: str | None):
    """The --geometry value as a parsed JSON object: a file path or the
    inline JSON itself. An unreadable path is an invocation error
    (exit 2); unparseable JSON is a *content* defect and classifies as
    the gate's ``malformed-spec`` (exit 8) like every other bad spec."""
    if arg is None:
        return None
    from poisson_ellipse_tpu.resilience.errors import InvalidGeometryError

    text = arg
    if not arg.lstrip().startswith("{"):
        with open(arg, "r", encoding="utf-8") as fh:
            text = fh.read()
    try:
        return json.loads(text)
    except json.JSONDecodeError as e:
        raise InvalidGeometryError(
            f"malformed geometry spec: not valid JSON ({e})",
            reason="malformed-spec",
        ) from e


def _run_cli(args) -> int:
    """The measured-run body of ``main`` (post-parse, post-trace-setup)."""
    eps_values = (
        [float(e) for e in args.eps_sweep.split(",")]
        if args.eps_sweep
        else [args.eps]
    )
    try:
        geometry = _geometry_spec(args.geometry)
    except OSError as e:
        print(f"error: cannot read --geometry: {e}", file=sys.stderr)
        return 2
    except SolveError as e:
        print(f"error: {e.classification}: {e}", file=sys.stderr)
        return e.exit_code
    if args.geometry is None and args.theta is not None:
        print("error: --theta needs --geometry", file=sys.stderr)
        return 2
    recycle_cap = args.recycle
    if recycle_cap is not None and recycle_cap < 0:
        # bare --recycle: the product default ring capacity
        from poisson_ellipse_tpu.solver.recycle import RECYCLE_CAP

        recycle_cap = RECYCLE_CAP

    if args.threads_sweep:
        if args.mode != "native":
            print(
                "error: --threads-sweep is the OpenMP runtime's in-run "
                "sweep; it requires --mode native",
                file=sys.stderr,
            )
            return 2
        if args.threads:
            print(
                "error: --threads conflicts with --threads-sweep (the "
                "sweep list is the thread counts)",
                file=sys.stderr,
            )
            return 2
        if args.checkpoint_dir:
            print(
                "error: checkpointing covers the JAX paths, not native "
                "runs; drop --checkpoint-dir or --threads-sweep",
                file=sys.stderr,
            )
            return 2

    try:
        grids = _parse_grids(args)
    except ValueError as e:
        print(f"error: invalid --grids: {e}", file=sys.stderr)
        return 2
    # a sweep re-fingerprints the checkpoint each run, so a shared directory
    # would refuse every run after the first — key per-run subdirectories
    sweeping = len(grids) * len(eps_values) > 1

    rc = 0
    for M, N in grids:
        for eps in eps_values:
            ck_dir = args.checkpoint_dir
            if ck_dir is not None and sweeping:
                import os

                ck_dir = os.path.join(
                    ck_dir,
                    f"{M}x{N}" + (f"_eps{eps:g}" if eps is not None else ""),
                )
            problem = Problem(
                M=M,
                N=N,
                delta=args.delta,
                eps=eps,
                norm=args.norm,
                max_iter=args.max_iter,
            )
            if args.threads_sweep:
                try:
                    rc = max(
                        rc,
                        _run_threads_sweep(
                            problem,
                            [int(t) for t in args.threads_sweep.split(",")],
                            repeat=args.repeat,
                            as_json=args.json,
                        ),
                    )
                except (ValueError, NativeBuildError) as e:
                    print(f"error: {e}", file=sys.stderr)
                    return 2
                continue
            try:
                import jax

                # jax.profiler trace around the measured solve — the TPU
                # analog of the reference's per-phase timers beyond what
                # the fenced PhaseTimer's coarse split covers (SURVEY §5)
                trace_cm = (
                    jax.profiler.trace(args.trace_dir)
                    if args.trace_dir
                    else contextlib.nullcontext()
                )
                with trace_cm:
                    report = run_once(
                        problem,
                        mode=args.mode,
                        mesh_shape=tuple(args.mesh) if args.mesh else None,
                        dtype=args.dtype,
                        engine=args.engine,
                        repeat=args.repeat,
                        batch=args.batch,
                        lanes=args.lanes,
                        threads=args.threads,
                        checkpoint_dir=ck_dir,
                        chunk=args.chunk,
                        timeout=args.timeout,
                        guard=args.guard,
                        max_recoveries=args.max_recoveries,
                        geometry=geometry,
                        theta=args.theta,
                        storage_dtype=args.storage_dtype,
                        sstep_s=args.sstep_s,
                        recycle=recycle_cap,
                        warm_start=args.warm_start,
                    )
            except SolveError as e:
                # the classified exit contract: the trace keeps every
                # event flushed before the abort (recovery:* included),
                # plus this partial report — an artifact, not a hang
                record = {
                    "M": M, "N": N, "dtype": args.dtype,
                    "engine": args.engine,
                    "aborted": e.classification,
                    "iters": e.iters,
                }
                obs_trace.event("run_report_partial", **record)
                if args.json:
                    print(json.dumps(record))
                print(
                    f"error: solve aborted — {e.classification}: {e}",
                    file=sys.stderr,
                )
                return e.exit_code
            except (ValueError, NativeBuildError) as e:
                # NativeBuildError = g++ missing or the C++ build failed —
                # an environment problem to report, not a traceback. Other
                # RuntimeErrors (incl. jax XlaRuntimeError) stay loud.
                print(f"error: {e}", file=sys.stderr)
                return 2
            # the structured twin of the human summary below: one event
            # per run, same fields as --json's line
            obs_trace.event("run_report", **report.json_dict())
            obs_metrics.counter("runs").inc()
            if report.converged:
                obs_metrics.counter("runs_converged").inc()
            obs_metrics.gauge("last_iters").set(report.iters)
            # latency distribution across the run/sweep: the p50/p90/p99
            # the --metrics OpenMetrics snapshot renders as a summary
            obs_metrics.histogram("solve_seconds").observe(report.t_solver)
            phases = None
            if args.profile and args.mode == "native":
                print(
                    "note: --profile covers the JAX paths; skipped for "
                    "--mode native",
                    file=sys.stderr,
                )
            elif args.profile:
                from poisson_ellipse_tpu.harness.profile import (
                    profile_single,
                    profile_sharded,
                )

                jdtype = resolve_dtype(args.dtype)
                if report.mesh_shape == (1, 1):
                    phases = profile_single(problem, jdtype)
                else:
                    phases = profile_sharded(
                        problem,
                        mesh=resolve_mesh(
                            tuple(args.mesh) if args.mesh else None
                        ),
                        dtype=jdtype,
                    )
                # the stage4 taxonomy as spans: halo/stencil/dot/... per
                # iteration, from the segmented replay
                for name, secs in sorted(phases.items()):
                    obs_trace.span_event(f"profile:{name}", secs)
            if args.json:
                # keep stdout one JSON line per run: phases ride inside it
                record = report.json_dict()
                if phases is not None:
                    record["phase_s"] = phases
                print(json.dumps(record))
            else:
                from poisson_ellipse_tpu.harness.profile import format_phases

                print(report.summary())
                if phases is not None:
                    print(format_phases(phases, report.iters))
                if (
                    args.batch == 1
                    and args.mode != "native"
                    and args.checkpoint_dir is None
                ):
                    import jax

                    if jax.default_backend() != "cpu":
                        print(
                            "note: single-dispatch T_solver includes the "
                            "fixed host<->device round-trip; pass e.g. "
                            "--repeat 3 --batch 5 for the amortised "
                            "protocol bench.py uses",
                            file=sys.stderr,
                        )
                print()
            if report.breakdown:
                rc = max(rc, 2)  # diverged, per the exit-code contract
            elif not report.converged:
                rc = max(rc, 1)
    return rc


if __name__ == "__main__":
    sys.exit(main())
