"""Bytes-per-iteration roofline model: achieved HBM bandwidth per engine.

The reference's stage4 report attributes time to named phases (T_gpu,
T_copy, T_mpi, T_prec, T_dot — ``poisson_mpi_cuda2.cu:696-700``) but never
relates them to what the hardware could do. Here every run carries the
next level: modelled HBM array-passes per PCG iteration for the engine
that executed, the achieved streaming bandwidth they imply, and the
fraction of the chip's HBM roofline that represents. A resident-engine
row showing ~0 passes/iter is the point: that engine left the HBM
roofline entirely (its iterations are VMEM/VPU-bound), which is why it
outruns the XLA path several-fold.

The pass counts are a traffic *model* (array reads + writes the
iteration must stream from/to HBM, assuming perfect fusion of
elementwise consumers), not a measurement; they use unpadded node-array
bytes, so the implied GB/s slightly understates true traffic on padded
layouts. Small grids report low roofline fractions because fixed
per-iteration overheads (kernel launch, loop bookkeeping) dominate —
the number quantifies exactly how far from streaming-bound a
configuration is.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from poisson_ellipse_tpu.models.problem import Problem

# Published peak HBM bandwidth by device kind (bytes/s).
_HBM_PEAK = {
    "TPU v4": 1_228e9,
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5": 2_765e9,
    "TPU v5p": 2_765e9,
    "TPU v6 lite": 1_640e9,
    "TPU v6e": 1_640e9,
}


def hbm_peak_bytes_per_s(device=None) -> Optional[float]:
    """Peak HBM bandwidth of the (default) device, or None if unknown."""
    if device is None:
        devices = jax.devices()
        if not devices:
            return None
        device = devices[0]
    return _HBM_PEAK.get(getattr(device, "device_kind", ""), None)


def passes_per_iter(problem: Problem, engine: str, dtype=jnp.float32,
                    sstep_s: int = 4, storage_dtype=None) -> float:
    """Modelled HBM array-passes per PCG iteration for one engine.

    One "pass" = one full node-array read or write against HBM.

      xla / pallas — every iterate and operand streams each use:
        stencil (read p, a, b; write ap)                      4
        denom dot (read ap, p — assume fused into stencil)    0
        w/r update (read w, r, p, ap; write w, r)             6
        z = r * dinv (read r?, dinv; write z — r fused)       2
        zr dot (fused into z)                                 0
        p = z + beta*p (read z?, p; write p — z fused)        1
        => ~13 passes (matches the measured HBM-bound regime)
      fused — K1 reads z, p, 5 coefficient arrays, writes pn, ap (9);
        K2 reads w, r, pn, ap, dinv, writes w, r, z (8) => 17
        (more traffic than xla — why it only wins while compute-bound)
      pipelined / pipelined-pallas — bundle+stencil pass reads
        r, u, w, s, p, dinv, a, b and writes n (9); the seven-vector
        update pass reads n, z, s, p, u, w, r, x, dinv and writes
        z, s, p, x, r, u, w (16); + the 4-stencil residual replacement
        amortised over its cadence => ~25.6. Twice xla's traffic —
        the price of halving the reductions; the engine's payoff is
        collective latency on the mesh, not HBM economy.
      resident — HBM touched twice per *solve*, not per iteration => 0
      streamed — state is VMEM-resident; only non-resident operands
        stream (``StreamPlan.streamed_passes_per_iter``)
    """
    if engine in ("xla", "pallas"):
        return 13.0
    if engine in ("mg-pcg", "cheb-pcg", "fmg"):
        # the classical loop's 13 plus the preconditioner's modeled
        # extra traffic (V-cycle levels geometrically discounted /
        # Chebyshev degree; mg.engine.modeled_extra_passes). More
        # bytes per iteration, ~order-of-magnitude fewer iterations —
        # the trade the bench "precond" key measures end to end. fmg's
        # reported iterations are its verification-handoff iterations
        # (the same V-cycle-preconditioned loop), so the per-iteration
        # figure is mg-pcg's; the F-cycle prelude's fixed O(N) bytes
        # are the work-unit model's column (mg.fmg.work_units_per_point),
        # not a per-iteration quantity.
        from poisson_ellipse_tpu.mg.engine import modeled_extra_passes

        return 13.0 + modeled_extra_passes(problem, engine, dtype)
    if engine == "fused":
        return 17.0
    if engine in ("pipelined", "pipelined-pallas"):
        from poisson_ellipse_tpu.ops.precision import replace_every

        # the replacement amortisation follows the EFFECTIVE cadence:
        # 32 at full width, 8 under sub-compute storage (4× the rebuild
        # passes — the narrow build's model must carry them)
        return 25.0 + 4.0 * 5.0 / replace_every(storage_dtype, dtype)
    if engine in ("sstep", "sstep-pallas"):
        # per BLOCK of s iterations: 2s−1 Â = D⁻¹A applications (read
        # v/a/b/dinv, write out: ~6 passes each), one Gram pass over the
        # K = 2s+1 basis arrays (d rides fused), one reconstruction pass
        # over the basis + 3 writes; replacement (1 stencil ≈ 5 passes)
        # amortised over its storage-effective cadence. More bytes/iter
        # than classical — the engine's win is 1/s collectives, and with
        # bf16 storage the whole bill halves
        # (modeled_hbm_bytes_per_iter's storage itemsize).
        from poisson_ellipse_tpu.ops.precision import replace_every

        s = sstep_s
        K = 2 * s + 1
        return ((2 * s - 1) * 6.0 + 2 * K + 3.0) / s + 5.0 / replace_every(
            storage_dtype, dtype
        )
    if engine == "xl":
        from poisson_ellipse_tpu.ops.xl_pcg import XLPlan

        return XLPlan(problem, dtype).passes_per_iter()
    if engine == "resident":
        return 0.0
    if engine == "streamed":
        from poisson_ellipse_tpu.ops.streamed_pcg import StreamPlan

        return StreamPlan(problem, dtype).streamed_passes_per_iter()
    raise ValueError(f"no traffic model for engine {engine!r}")


def modeled_hbm_bytes_per_iter(problem: Problem, engine: str,
                               dtype=jnp.float32, storage_dtype=None,
                               sstep_s: int = 4) -> float:
    """The traffic model's HBM bytes per iteration for one engine —
    ``passes_per_iter`` × unpadded node-array bytes. This is the
    "modeled" column ``obs.static_cost`` sets next to XLA's own
    bytes-accessed estimate (the "measured" static column), so model
    drift against the compiler's accounting is visible per engine in
    ``harness inspect`` instead of only as a bench-day surprise.

    ``storage_dtype`` models the narrow-storage byte bill: the loop
    engines stream state AND operands at storage width, so every
    modeled pass narrows by the storage/compute itemsize ratio — bf16
    under f32 is exactly the ~2× cut the ``bandwidth`` bench key
    measures. (streamed/xl narrow their operand share only; their
    modeled figure with storage set is therefore a lower bound for
    them, labelled as the loop-engine model.)
    """
    from poisson_ellipse_tpu.ops.precision import storage_itemsize

    g1, g2 = problem.node_shape
    return (
        passes_per_iter(problem, engine, dtype, sstep_s=sstep_s,
                        storage_dtype=storage_dtype)
        * g1 * g2 * storage_itemsize(dtype, storage_dtype)
    )


def roofline(
    problem: Problem,
    engine: str,
    iters: int,
    t_solver: float,
    dtype=jnp.float32,
    device=None,
    n_devices: int = 1,
    storage_dtype=None,
    sstep_s: int = 4,
) -> dict:
    """Achieved per-device GB/s + fraction-of-HBM-peak for a measured solve.

    Returns {"passes_per_iter", "hbm_gbps", "hbm_peak_frac"} —
    hbm_peak_frac is None when the device's peak is unknown (CPU runs).
    For sharded runs (n_devices > 1) the global traffic divides over the
    mesh, so the figures are per-chip utilisation against one chip's
    peak; halo-exchange bytes (ICI, not HBM) are not modelled.
    """
    from poisson_ellipse_tpu.ops.precision import storage_itemsize

    g1, g2 = problem.node_shape
    array_bytes = g1 * g2 * storage_itemsize(dtype, storage_dtype)
    passes = passes_per_iter(problem, engine, dtype, sstep_s=sstep_s,
                             storage_dtype=storage_dtype)
    bytes_per_dev = passes * array_bytes * max(iters, 1) / max(n_devices, 1)
    gbps = bytes_per_dev / t_solver / 1e9 if t_solver > 0 else 0.0
    peak = hbm_peak_bytes_per_s(device)
    return {
        "passes_per_iter": passes,
        "hbm_gbps": round(gbps, 2),
        "hbm_peak_frac": (
            round(bytes_per_dev / t_solver / peak, 4)
            if peak and t_solver > 0
            else None
        ),
    }
