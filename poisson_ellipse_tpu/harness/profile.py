"""Segmented per-phase profiling: the stage4 timer taxonomy, TPU-style.

Stage4 wraps every kernel launch, memcpy and collective in accumulators
``T_gpu / T_copy / T_mpi / T_prec / T_dot``
(``poisson_mpi_cuda2.cu:696-700,855-979``) — it can, because its loop is
fully synchronous. The TPU loop is one fused XLA computation, and a
per-dispatch replay would be swamped by host↔device round-trip latency
(measured ~4 ms under tunneled backends vs ~20 µs of actual op time), so
each phase is measured by *chaining the op k times inside an on-device
``lax.fori_loop``* — one dispatch, k data-dependent applications. Phase map:

  reference          here               what is timed
  T_gpu (stencil)  → t_stencil          apply_A chained on the iterate
  T_prec           → t_precond          z = D⁻¹ r chained
  T_dot            → t_dot              inner product (+1 elementwise pass
                                        to carry the data dependency — a
                                        slight overestimate)
  (update kernels) → t_update           fused w/r axpy + ‖Δw‖² partial
  T_copy + T_mpi   → t_halo             halo ppermutes (sharded; ≡0 single)

There is no T_copy analog on the fast path at all: state never leaves the
device (the copies stage4 pays per iteration are exactly what this design
eliminates — BASELINE.json north star).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
from jax import lax

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.parallel.compat import shard_map
from poisson_ellipse_tpu.ops.reduction import grid_dot
from poisson_ellipse_tpu.ops.stencil import apply_a, apply_dinv, diag_d
from poisson_ellipse_tpu.utils.timing import fence


def _time_chain(step, x0, reps: int) -> float:
    """Seconds per application of ``step``.

    Times two on-device ``fori_loop`` chains of k and 5k data-dependent
    applications and returns (t_5k − t_k)/4k: the difference cancels the
    constant dispatch + fence overhead (≈0.2 s RTT under tunneled
    backends) that would otherwise swamp ops costing tens of µs.
    """

    def timed(n: int) -> float:
        # the chain length n is baked into the trace, so a fresh jit per
        # timed(n) is the protocol, not a leak: exactly two builds per
        # phase (k and 5k), each dispatched twice
        looped = jax.jit(  # tpulint: disable=TPU006
            lambda x: lax.fori_loop(0, n, lambda _, s: step(s), x)
        )
        out = looped(x0)  # compile + warm-up
        fence(out)
        t0 = time.perf_counter()
        out = looped(x0)
        fence(out)
        return time.perf_counter() - t0

    return max(timed(5 * reps) - timed(reps), 0.0) / (4 * reps)


def profile_single(problem: Problem, dtype=jnp.float32, reps: int = 200):
    """Per-op phase costs of one PCG iteration on one device."""
    h1 = jnp.asarray(problem.h1, dtype)
    h2 = jnp.asarray(problem.h2, dtype)
    a, b, rhs = assembly.assemble(problem, dtype)
    d = diag_d(a, b, h1, h2)
    r = rhs
    z = apply_dinv(r, d)
    p = z
    ap = apply_a(p, a, b, h1, h2)
    alpha = jnp.asarray(1e-3, dtype)
    w = jnp.zeros_like(rhs)

    def update_step(state):
        w, r, s = state
        w_new = w + alpha * p
        r_new = r - alpha * ap
        dw = w_new - w
        return w_new, r_new, s + jnp.sum(dw * dw)

    phases = {
        "stencil": _time_chain(
            lambda u: apply_a(u, a, b, h1, h2), p, reps
        ),
        # scalar carry keeps the chain data-dependent; costs one extra
        # elementwise pass over the dot itself
        "dot": _time_chain(
            lambda s: grid_dot(p + s, p, h1, h2), jnp.asarray(0.0, dtype), reps
        ),
        "precond": _time_chain(lambda u: apply_dinv(u, d), r, reps),
        "update": _time_chain(
            update_step, (w, r, jnp.asarray(0.0, dtype)), reps
        ),
        "halo": 0.0,
    }
    return phases


def profile_sharded(
    problem: Problem, mesh=None, dtype=jnp.float32, reps: int = 200
):
    """Phase costs on the device mesh, including the halo ppermutes."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from poisson_ellipse_tpu.parallel.halo import halo_extend
    from poisson_ellipse_tpu.parallel.mesh import (
        AXIS_X,
        AXIS_Y,
        make_mesh,
        padded_dims,
    )
    from poisson_ellipse_tpu.parallel.pcg_sharded import _pad_to
    from poisson_ellipse_tpu.ops.stencil import apply_a_block, diag_d_block

    if mesh is None:
        mesh = make_mesh()
    px = mesh.shape[AXIS_X]
    py = mesh.shape[AXIS_Y]
    g1p, g2p = padded_dims(problem.node_shape, mesh)
    spec = P(AXIS_X, AXIS_Y)
    sharding = NamedSharding(mesh, spec)

    h1 = jnp.asarray(problem.h1, dtype)
    h2 = jnp.asarray(problem.h2, dtype)
    a_np, b_np, rhs_np = assembly.assemble_numpy(problem)
    np_dtype = assembly.numpy_dtype(dtype)
    a, b, rhs = (
        jax.device_put(_pad_to(arr, g1p, g2p).astype(np_dtype), sharding)
        for arr in (a_np, b_np, rhs_np)
    )

    def chained(step_of_blocks, n: int):
        """shard_map a per-block step chained n times on device."""

        def blk_fn(u_blk, a_blk, b_blk):
            a_ext = halo_extend(a_blk, px, py)
            b_ext = halo_extend(b_blk, px, py)
            return lax.fori_loop(
                0, n, lambda _, s: step_of_blocks(s, a_ext, b_ext), u_blk
            )

        # no donation: the operands are re-fed on the second timed
        # dispatch of the (t_5k - t_k) protocol, so every input outlives
        # its call by design
        return jax.jit(  # tpulint: disable=TPU004
            shard_map(
                blk_fn,
                mesh=mesh,
                in_specs=(spec, spec, spec),
                out_specs=spec,
            )
        )

    def time_fn(step_of_blocks, x0) -> float:
        # same overhead-cancelling (t_5k − t_k)/4k protocol as _time_chain
        def timed(n: int) -> float:
            fn = chained(step_of_blocks, n)
            out = fn(x0, a, b)
            fence(out)
            t0 = time.perf_counter()
            out = fn(x0, a, b)
            fence(out)
            return time.perf_counter() - t0

        return max(timed(5 * reps) - timed(reps), 0.0) / (4 * reps)

    def halo_step(u_blk, a_ext, b_ext):
        return halo_extend(u_blk, px, py)[1:-1, 1:-1]

    def stencil_step(u_blk, a_ext, b_ext):
        u_ext = halo_extend(u_blk, px, py)
        return apply_a_block(u_ext, a_ext, b_ext, h1, h2)

    def precond_step(u_blk, a_ext, b_ext):
        d = diag_d_block(a_ext, b_ext, h1, h2)
        return apply_dinv(u_blk, d)

    def dot_step(u_blk, a_ext, b_ext):
        # the probe times the collective ITSELF (t_dot's psum leg), so
        # it must issue one raw — outside the parallel/ cadence budgets
        # by design, never part of a pinned solver loop
        # tpulint: disable=TPU020
        s = lax.psum(jnp.sum(u_blk * u_blk), (AXIS_X, AXIS_Y)) * h1 * h2
        # rescale to keep the chain alive and the magnitude bounded
        return u_blk * (s / jnp.where(s == 0.0, 1.0, s))

    def time_update() -> float:
        """The per-shard w/r axpy + realised ‖Δw‖² partial (the stage4
        ``update_w_r_kernel`` analog). The partial's psum rides with the
        zr collective in the real loop (one batched psum —
        ``parallel.pcg_sharded._shard_advance``), so only the local
        reduction is timed here; a/b stand in for p/ap (same shapes,
        same sharding). The chain stays data-dependent through a ~1.0
        rescale by the partial, costing one extra elementwise pass —
        a slight overestimate, exactly like the dot phase's carry."""
        alpha = jnp.asarray(1e-3, dtype)

        def make(n: int):
            def blk_fn(w_blk, r_blk, a_blk, b_blk):
                def step(_, st):
                    w, r = st
                    w_new = w + alpha * a_blk
                    r_new = r - alpha * b_blk
                    dw = w_new - w
                    dw2 = jnp.sum(dw * dw)
                    w_new = w_new * (
                        dw2 / jnp.where(dw2 == 0.0, 1.0, dw2)
                    )
                    return (w_new, r_new)

                return lax.fori_loop(0, n, step, (w_blk, r_blk))

            # no donation: same re-fed operands as chained() above
            return jax.jit(  # tpulint: disable=TPU004
                shard_map(
                    blk_fn,
                    mesh=mesh,
                    in_specs=(spec, spec, spec, spec),
                    out_specs=(spec, spec),
                )
            )

        def timed(n: int) -> float:
            fn = make(n)
            out = fn(rhs, rhs, a, b)
            fence(out)
            t0 = time.perf_counter()
            out = fn(rhs, rhs, a, b)
            fence(out)
            return time.perf_counter() - t0

        return max(timed(5 * reps) - timed(reps), 0.0) / (4 * reps)

    phases = {
        "halo": time_fn(halo_step, rhs),
        "stencil": time_fn(stencil_step, rhs),
        "precond": time_fn(precond_step, rhs),
        "dot": time_fn(dot_step, rhs),
        "update": time_update(),
    }
    # the stencil phase includes its own halo exchange (as stage4's T_gpu
    # excludes but T_copy/T_mpi include theirs); subtract for the pure part
    phases["stencil_pure"] = max(phases["stencil"] - phases["halo"], 0.0)
    return phases


def format_phases(phases: dict[str, float], iters: int | None = None) -> str:
    lines = ["Per-iteration phase costs (on-device chained replay):"]
    for name, secs in sorted(phases.items(), key=lambda kv: -kv[1]):
        if secs == 0.0 and name != "halo":
            # the (t_5k - t_k) subtraction clamps at 0 when the phase
            # costs less than the dispatch-time noise (tunneled chips)
            lines.append(f"  t_{name:<12s}      below noise floor")
            continue
        line = f"  t_{name:<12s} {secs * 1e6:10.1f} us"
        if iters:
            line += f"   (x{iters} iters = {secs * iters:8.4f} s)"
        lines.append(line)
    return "\n".join(lines)
