"""One measured solve: the TPU analog of the reference's ``main`` drivers.

Reproduces the reference's wall-clock segmentation (program = init +
solver + finalize, ``poisson_mpi_cuda2.cu:992-1034``) with fenced phase
timers, and its rank-0 result summary (config echo, "converged after k",
iteration count, total time, phase breakdown,
``poisson_mpi_cuda2.cu:1000-1003,1026-1034``) — plus the L2-error-vs-
analytic metric the reference states but never computes (README.md:38-42;
no stage computes it — SURVEY §4.1).
"""

from __future__ import annotations

import math
import statistics
import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
from jax import lax

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs import trace as obs_trace
from poisson_ellipse_tpu.parallel.mesh import AXIS_X, AXIS_Y, make_mesh
from poisson_ellipse_tpu.parallel.pcg_sharded import build_sharded_solver
from poisson_ellipse_tpu.resilience.errors import (
    OutOfMemoryError,
    is_oom_error,
)
from poisson_ellipse_tpu.solver.engine import (
    BATCHED_ENGINES,
    CAPACITY_LADDER,
    build_solver,
)
from poisson_ellipse_tpu.utils.error import l2_error_vs_analytic
from poisson_ellipse_tpu.utils.timing import PhaseTimer, fence

# runtime degradation ladder for `--engine auto`: RESOURCE_EXHAUSTED on
# the first (compile + warm-up) dispatch walks down one rung per retry;
# xla has no capacity gate, so the ladder always terminates. The rungs
# are the engine-capability table's (solver.engine.ENGINE_CAPS) — one
# source for the ladder here, in build_solver and in the autotuner.
_DEGRADE_LADDER = CAPACITY_LADDER
# seconds before re-dispatching after an OOM: gives the allocator a beat
# to release the failed attempt's buffers before the smaller engine asks
_DEGRADE_BACKOFF_S = 0.25

DTYPES = {
    "f32": jnp.float32,
    # deliberate f64 menu entry: resolve_dtype below flips jax_enable_x64
    # on before this dtype is ever applied, so it cannot downcast
    "f64": jnp.float64,  # tpulint: disable=TPU001
    "bf16": jnp.bfloat16,
}


def resolve_dtype(dtype: str):
    """Map a dtype name to the jnp dtype, enabling x64 when required.

    Without ``jax_enable_x64``, jnp silently downcasts f64 arrays to f32 —
    a run labelled f64 would actually produce f32 results. The reference
    is entirely double precision, so honouring a f64 request means
    flipping the config switch, not mislabelling.
    """
    if dtype == "f64" and not jax.config.jax_enable_x64:
        jax.config.update("jax_enable_x64", True)
    return DTYPES[dtype]


def resolve_mesh(mesh_shape: tuple[int, int] | None):
    """A 2D ('x','y') device mesh: explicit PX×PY, or near-square over all
    devices (the reference's ``choose_process_grid`` policy)."""
    if mesh_shape is None:
        return make_mesh()
    px, py = mesh_shape
    devices = jax.devices()
    if px * py > len(devices):
        raise ValueError(
            f"mesh {px}x{py} needs {px * py} devices, have {len(devices)}"
        )
    import numpy as np

    return jax.sharding.Mesh(
        np.asarray(devices[: px * py]).reshape(px, py), (AXIS_X, AXIS_Y)
    )


@dataclass
class RunReport:
    """Everything the reference's rank-0 summary prints, plus L2 error."""

    problem: Problem
    mesh_shape: tuple[int, int]
    dtype: str
    engine: str
    iters: int
    converged: bool
    breakdown: bool
    diff: float
    l2_error: float
    t_init: float
    t_solver: float
    times: list[float] = field(default_factory=list)
    # bytes-per-iteration roofline (harness.roofline): modelled HBM
    # passes/iter for the engine, the achieved GB/s they imply, and the
    # fraction of the chip's HBM peak (None when the peak is unknown)
    passes_per_iter: float = 0.0
    hbm_gbps: float = 0.0
    hbm_peak_frac: float | None = None
    # OpenMP thread count of a native run (0 = runtime default; the
    # stage1 sweep tables key on this — Этап1.pdf table 2)
    threads: int = 0
    # iterations covered by t_solver when it differs from ``iters`` — a
    # resumed checkpointed run times only the iterations it ran, while
    # ``iters`` stays the solver's cumulative (oracle-checked) count
    timed_iters: int | None = None
    # recovery actions a guarded run applied (resilience.guard event
    # kinds, in order); empty = the healthy path ran start to finish
    recoveries: list[str] = field(default_factory=list)
    # lane width of a batched run (--lanes; 1 = the single-solve
    # protocol) and the aggregate throughput it achieved: lanes divided
    # by the per-dispatch T_solver. quarantined counts lanes masked out
    # after a non-finite carry (batch.batched_pcg)
    lanes: int = 1
    solves_per_sec: float | None = None
    quarantined: int = 0
    # HBM storage width of the state/operand streams when it differs
    # from the compute dtype ("bf16": the bandwidth axis, ops.precision);
    # None = storage == compute, the historical single-dtype run
    storage_dtype: str | None = None

    def summary(self) -> str:
        p = self.problem
        lines = [
            f"Grid: {p.M} x {p.N}  (h1={p.h1:.6g}, h2={p.h2:.6g}, "
            f"eps={p.eps_value:.6g}, delta={p.delta:g}, norm={p.norm})",
            f"Mesh: {self.mesh_shape[0]} x {self.mesh_shape[1]}  "
            f"dtype={self.dtype}"
            + (
                f" (storage {self.storage_dtype})"
                if self.storage_dtype else ""
            )
            + f"  engine={self.engine}",
            (
                f"Converged after {self.iters} iterations (diff={self.diff:.3e})"
                if self.converged
                else (
                    f"BREAKDOWN after {self.iters} iterations"
                    if self.breakdown
                    else f"NOT converged after {self.iters} iterations "
                    f"(diff={self.diff:.3e})"
                )
            ),
            f"T_init   {self.t_init:10.4f} s",
            f"T_solver {self.t_solver:10.4f} s"
            + (
                f"  (median of {len(self.times)}: "
                + ", ".join(f"{t:.4f}" for t in self.times)
                + ")"
                if len(self.times) > 1
                else ""
            ),
            f"L2 error vs analytic: {self.l2_error:.6e}",
        ]
        if self.lanes > 1:
            lines.append(
                f"Lanes: {self.lanes}  "
                f"throughput {self.solves_per_sec:.2f} solves/s"
                + (
                    f"  ({self.quarantined} lane(s) quarantined)"
                    if self.quarantined
                    else ""
                )
            )
        if self.recoveries:
            lines.append(
                f"Recoveries: {len(self.recoveries)} "
                f"({', '.join(self.recoveries)})"
            )
        line = self.roofline_line()
        if line:
            lines.append(line)
        return "\n".join(lines)

    def roofline_line(self) -> str:
        """One-line roofline summary, '' when the model does not apply
        (native host runs, zero timed iterations)."""
        n = self.timed_iters if self.timed_iters is not None else self.iters
        if not n or self.engine == "native" or self.lanes > 1:
            # lane-batched runs report throughput (solves/sec), not the
            # single-solve HBM traffic model
            return ""
        if self.passes_per_iter == 0:
            # the engine left the HBM roofline entirely: its working set is
            # VMEM-resident, so "0 GB/s" would read as broken when it is
            # the design goal (harness.roofline module docstring)
            return (
                f"Roofline: {self.t_solver / n * 1e6:.1f} us/iter, "
                "VMEM-resident (no per-iteration HBM traffic)"
            )
        frac = (
            f"  ({self.hbm_peak_frac:.1%} of HBM peak)"
            if self.hbm_peak_frac is not None
            else ""
        )
        return (
            f"Roofline: {self.t_solver / n * 1e6:.1f} us/iter, "
            f"{self.passes_per_iter:g} HBM passes/iter -> "
            f"{self.hbm_gbps:.0f} GB/s{frac}"
        )

    def json_dict(self) -> dict:
        p = self.problem
        return {
            "M": p.M,
            "N": p.N,
            "mesh": list(self.mesh_shape),
            "dtype": self.dtype,
            "engine": self.engine,
            "eps": p.eps_value,
            "delta": p.delta,
            "iters": self.iters,
            "converged": self.converged,
            "diff": self.diff,
            # NaN (the --geometry runs' "analytic metric undefined")
            # must serialize as null: a literal NaN token is not RFC
            # JSON and strict consumers reject the whole record
            "l2_error": (
                self.l2_error if math.isfinite(self.l2_error) else None
            ),
            "t_init_s": self.t_init,
            "t_solver_s": self.t_solver,
            "passes_per_iter": self.passes_per_iter,
            "hbm_gbps": self.hbm_gbps,
            "hbm_peak_frac": self.hbm_peak_frac,
            **({"threads": self.threads} if self.engine == "native" else {}),
            **({"recoveries": self.recoveries} if self.recoveries else {}),
            **(
                {"storage_dtype": self.storage_dtype}
                if self.storage_dtype else {}
            ),
            **(
                {
                    "lanes": self.lanes,
                    "solves_per_sec": self.solves_per_sec,
                    "quarantined": self.quarantined,
                }
                if self.lanes > 1
                else {}
            ),
        }


def run_once(
    problem: Problem,
    mode: str = "auto",
    mesh_shape: tuple[int, int] | None = None,
    dtype: str = "f32",
    engine: str = "auto",
    repeat: int = 1,
    batch: int = 1,
    lanes: int = 1,
    threads: int = 0,
    checkpoint_dir: str | None = None,
    chunk: int = 500,
    timeout: float | None = None,
    guard: bool = False,
    max_recoveries: int = 3,
    geometry=None,
    theta: float | None = None,
    storage_dtype: str | None = None,
    sstep_s: int = 4,
    recycle: int | None = None,
    warm_start: bool = False,
) -> RunReport:
    """Assemble + solve with fenced init/solver timing.

    ``geometry`` (a ``geom.sdf`` shape or its JSON spec) selects an
    arbitrary SDF domain: the admissibility gate runs before any build
    (classified ``InvalidGeometryError``, exit 8 in the CLI), operands
    come from the bisection quadrature with the degenerate-cut clamp at
    ``theta``, and — since the analytic solution is an ellipse fact —
    the report's ``l2_error`` is NaN (convergence + the maximum
    principle are the checks for arbitrary domains).

    mode:  "single" — single-device solver (stage0/1/4-1GPU analog);
           "sharded" — mesh-sharded solver (stage2/3/4 analog);
           "native" — the C++/OpenMP host runtime (stage0/1 natively;
                      always f64; ``threads`` selects the OpenMP count;
                      T_solver includes assembly, exactly as the
                      reference's stage0 chrono wraps its whole solve());
           "auto" — sharded iff >1 device or an explicit mesh is requested.
    engine: single-device solver engine (``solver.engine.ENGINES``) —
           "auto" picks the fastest whose capacity regime applies
           (resident → streamed → xl; f64 takes xla).
    repeat/batch: timing protocol. For single mode with batch>1, each of
    the ``repeat`` measurements times one plain dispatch and one chained
    dispatch of ``batch`` data-dependent solves, and T_solver is the
    median *marginal* solve cost (t_chained − t_single)/(batch − 1) —
    the fixed per-dispatch host↔device RTT cancels out (see
    ``_chain_solver``). Otherwise ``repeat`` measurements of ``batch``
    back-to-back dispatches each; T_solver is the median per-dispatch
    time.

    lanes: lane width for the batched engines — ``lanes`` independent
    solves ride ONE dispatch (``--lanes``; distinct from ``batch``,
    which chains *dispatches* purely as a timing protocol). With
    lanes > 1 the engine must be ``batched``/``batched-pipelined``
    (``auto`` resolves to ``batched``) and the report carries per-lane
    aggregates plus ``solves_per_sec = lanes / T_solver``.

    timeout/guard/max_recoveries: the resilience surface. ``guard=True``
    (or any ``timeout``) routes the solve through
    ``resilience.guard.guarded_solve`` — chunked execution, per-chunk
    health word, the recovery ladder, classified ``SolveError``s instead
    of NaN results — with plain wall-clock timing (restartable solves
    are not the bench protocol, same stance as checkpointed runs).
    ``timeout`` is seconds per solve, cancelled gracefully at a chunk
    boundary (``SolveTimeout``, exit code 4 in the CLI).

    recycle/warm_start: the Krylov-recycling surface (``--recycle`` /
    ``--warm-start``). ``recycle`` (a ring capacity; the CLI default is
    ``solver.recycle.RECYCLE_CAP``) runs one untimed ring-carrying
    capture solve during init, harvests the extremal Ritz deflation
    basis host-side, and times the deflated restart of the same system
    (``x0 = W(WᵀAW)⁻¹Wᵀ·rhs`` — the reported iteration count is the
    deflated one). ``warm_start`` seeds the timed solve with the capture
    solve's solution — the semantic-cache-hit shape (on top of deflation
    when both are set); warm-started solution bits legitimately differ
    from cold, which is why the report stays honest about ``iters`` and
    ``l2_error`` instead of claiming bit-parity. Both ride the xla
    single-device engine (the one with the ``recycle`` contract row) and
    correctness never depends on the basis: ``init_state`` verifies any
    x0 by its TRUE residual.
    """
    if lanes < 1:
        raise ValueError("lanes must be >= 1")
    if storage_dtype is not None:
        if mode == "native":
            raise ValueError(
                "--storage-dtype rides the JAX engines; the native host "
                "runtime is f64 end to end"
            )
        if checkpoint_dir is not None:
            raise ValueError(
                "checkpoint fingerprints do not cover a storage dtype "
                "yet; drop --checkpoint-dir or --storage-dtype"
            )
    if geometry is not None and mode == "native":
        raise ValueError(
            "--geometry rides the JAX assembly paths; the native host "
            "runtime implements the closed-form ellipse only"
        )
    if geometry is not None and checkpoint_dir is not None:
        raise ValueError(
            "checkpoint fingerprints do not cover a geometry spec yet; "
            "drop --checkpoint-dir or --geometry"
        )
    if lanes > 1 or engine in BATCHED_ENGINES:
        if mode == "native":
            raise ValueError(
                "--lanes rides the JAX batched engines; the native host "
                "runtime solves one problem at a time"
            )
        if checkpoint_dir is not None:
            raise ValueError(
                "checkpointing persists the single-solve PCG carry; "
                "drop --checkpoint-dir or --lanes"
            )
        if engine == "auto":
            engine = "batched"
        if engine not in BATCHED_ENGINES:
            raise ValueError(
                f"engine {engine!r} runs one solve per dispatch; "
                "--lanes needs --engine batched or batched-pipelined"
            )
        if mode == "auto":
            # lane batching is the single-chip throughput engine; the
            # lane-sharded mesh composition is opt-in (--mode sharded /
            # --mesh), not inferred from the device count
            mode = "sharded" if mesh_shape is not None else "single"
        lanes = max(lanes, 1)
    if mode == "native":
        if checkpoint_dir is not None:
            raise ValueError("checkpointing covers the JAX paths, not native")
        if timeout is not None or guard:
            raise ValueError(
                "--timeout/--guard cover the JAX paths (chunked guarded "
                "solves); the native host runtime has no chunk boundary "
                "to cancel or recover at"
            )
        return _run_native(problem, repeat=repeat, threads=threads)
    jdtype = resolve_dtype(dtype)
    if mode == "auto":
        mode = (
            "sharded"
            if mesh_shape is not None or len(jax.devices()) > 1
            else "single"
        )
    if mode not in ("single", "sharded"):
        raise ValueError(f"unknown mode: {mode!r}")
    if recycle is not None or warm_start:
        # the recycling surface rides the single-device xla loop — the
        # engine whose ENGINE_CAPS row carries the `recycle` contract
        # (ring-extended carry, recycle=None jaxpr-pinned byte-identical)
        if recycle is not None and recycle < 1:
            raise ValueError("--recycle ring capacity must be >= 1")
        if mode != "single" or engine not in ("auto", "xla"):
            raise ValueError(
                "--recycle/--warm-start ride the single-device xla loop "
                "(the engine with the recycle contract row); sharded "
                "recycling is the serve scheduler's per-bucket pool"
            )
        if lanes > 1:
            raise ValueError(
                "--recycle/--warm-start time one deflated solve; lane "
                "batching takes recycling through the serve scheduler's "
                "per-bucket pools (drop --lanes)"
            )
        if timeout is not None or guard or checkpoint_dir is not None:
            raise ValueError(
                "--recycle/--warm-start are a timing protocol (capture + "
                "deflated restart); drop --guard/--timeout/--checkpoint-dir"
            )
        if geometry is not None or storage_dtype is not None:
            raise ValueError(
                "--recycle/--warm-start cover the full-width analytic "
                "ellipse path (the harvest and the l2 report are ellipse "
                "facts); drop --geometry/--storage-dtype"
            )
        return _run_recycled(
            problem, dtype, jdtype, repeat=repeat, batch=batch,
            recycle=recycle, warm_start=warm_start,
        )
    if (storage_dtype is not None and mode == "sharded"
            and engine not in ("sstep", "sstep-pallas") and not guard
            and timeout is None):
        raise ValueError(
            "sharded --storage-dtype covers the sstep engine (whose "
            "deep-halo exchange ships the narrow state); the classical/"
            "pipelined/batched sharded forms run full width"
        )
    if geometry is not None:
        # the gate runs ONCE here for every JAX path (the sharded
        # builders assemble without re-validating, and build_solver is
        # told the gate already passed)
        from poisson_ellipse_tpu.geom import sdf as geom_sdf
        from poisson_ellipse_tpu.geom import validate as geom_validate

        if isinstance(geometry, dict):
            geometry = geom_sdf.from_spec(geometry)
        geom_validate.validate(problem, geometry, theta=theta)
        if mode == "sharded" and engine in BATCHED_ENGINES:
            raise ValueError(
                "lane-sharded batched runs take per-request geometry "
                "through the serve scheduler; drop --geometry or use a "
                "single-solve engine"
            )
    if timeout is not None or guard:
        if checkpoint_dir is not None:
            raise ValueError(
                "guarded/timeout runs and checkpointed runs are separate "
                "chunked drivers; drop --checkpoint-dir or --timeout/--guard"
            )
        if repeat > 1 or batch > 1:
            raise ValueError(
                "guarded/timeout runs are one wall-clocked chunked solve; "
                "the repeat/batch timing protocol does not apply"
            )
        if engine in BATCHED_ENGINES:
            if mode == "sharded":
                raise ValueError(
                    "guarded batched solves run the single-device chunked "
                    "lane driver (batch.driver); drop --mesh/--mode sharded"
                )
            if geometry is not None:
                raise ValueError(
                    "guarded lane-batched runs take per-request geometry "
                    "through the serve scheduler; drop --geometry or "
                    "--guard/--lanes"
                )
            return _run_batched_guarded(
                problem, dtype, jdtype, engine, lanes, timeout=timeout,
            )
        return _run_guarded(
            problem, mode, mesh_shape, dtype, jdtype, engine,
            timeout=timeout, max_recoveries=max_recoveries,
            geometry=geometry, theta=theta, storage_dtype=storage_dtype,
            sstep_s=sstep_s,
        )
    if checkpoint_dir is not None:
        if repeat > 1 or batch > 1:
            raise ValueError(
                "checkpointed runs are one wall-clocked chunked solve; "
                "the repeat/batch timing protocol does not apply "
                "(drop --repeat/--batch or --checkpoint-dir)"
            )
        return _run_checkpointed(
            problem, mode, mesh_shape, dtype, jdtype, engine,
            checkpoint_dir, chunk,
        )

    timer = PhaseTimer()
    requested_auto = engine == "auto"
    if mode == "single":
        with timer.phase("init"):
            solver, args, engine = build_solver(
                problem, engine, jdtype, lanes=lanes, geometry=geometry,
                theta=theta, validate_geometry=False,
                storage_dtype=storage_dtype, sstep_s=sstep_s,
            )
            fence(args)
        shape = (1, 1)
    elif mode == "sharded" and engine in BATCHED_ENGINES:
        from poisson_ellipse_tpu.parallel.batched_sharded import (
            build_batched_sharded_solver,
        )

        with timer.phase("init"):
            mesh = resolve_mesh(mesh_shape)
            solver, args = build_batched_sharded_solver(
                problem, mesh, lanes, jdtype,
                pipelined=engine == "batched-pipelined",
            )
            fence(args)
        shape = (mesh.shape[AXIS_X], mesh.shape[AXIS_Y])
    elif mode == "sharded" and engine in ("mg-pcg", "cheb-pcg"):
        from poisson_ellipse_tpu.parallel.mg_sharded import (
            build_mg_sharded_solver,
        )
        from poisson_ellipse_tpu.solver.engine import PRECOND_KIND_BY_ENGINE

        with timer.phase("init"):
            mesh = resolve_mesh(mesh_shape)
            solver, args = build_mg_sharded_solver(
                problem, mesh, jdtype,
                kind=PRECOND_KIND_BY_ENGINE[engine],
                geometry=geometry, theta=theta,
            )
            fence(args)
        shape = (mesh.shape[AXIS_X], mesh.shape[AXIS_Y])
    elif mode == "sharded" and engine == "fmg":
        from poisson_ellipse_tpu.parallel.mg_sharded import (
            build_fmg_sharded_solver,
        )

        with timer.phase("init"):
            mesh = resolve_mesh(mesh_shape)
            solver, args = build_fmg_sharded_solver(
                problem, mesh, jdtype, geometry=geometry, theta=theta,
            )
            fence(args)
        shape = (mesh.shape[AXIS_X], mesh.shape[AXIS_Y])
    elif mode == "sharded" and engine in ("sstep", "sstep-pallas"):
        from poisson_ellipse_tpu.parallel.sstep_sharded import (
            build_sstep_sharded_solver,
        )

        with timer.phase("init"):
            mesh = resolve_mesh(mesh_shape)
            solver, args = build_sstep_sharded_solver(
                problem, mesh, jdtype, s=sstep_s,
                storage_dtype=storage_dtype, geometry=geometry,
                theta=theta,
            )
            engine = "sstep"
            fence(args)
        shape = (mesh.shape[AXIS_X], mesh.shape[AXIS_Y])
    elif mode == "sharded":
        if engine not in ("auto", "xla", "pallas", "fused", "pipelined"):
            raise ValueError(
                f"engine {engine!r} is single-device only; sharded mode "
                "runs the XLA block stencil ('xla', default), the "
                "per-shard Pallas stencil kernel ('pallas'), the "
                "two-kernel fused per-shard iteration ('fused', f32/bf16), "
                "the one-psum-per-iteration pipelined recurrence "
                "('pipelined'), the one-psum-per-s-iterations s-step "
                "form ('sstep'), or the preconditioned forms ('mg-pcg' / "
                "'cheb-pcg': V-cycle/Chebyshev per shard, halo-ppermute "
                "only — the scalar-collective cadence stays classical)"
            )
        # (narrow-storage sharded requests were already rejected by the
        # mode-level check above — sstep is the one sharded storage form)
        engine = "xla" if engine == "auto" else engine
        with timer.phase("init"):
            mesh = resolve_mesh(mesh_shape)
            solver, args = build_sharded_solver(
                problem, mesh, jdtype, stencil_impl=engine,
                geometry=geometry, theta=theta,
            )
            fence(args)
        shape = (mesh.shape[AXIS_X], mesh.shape[AXIS_Y])
    else:  # unreachable: mode validated above
        raise ValueError(f"unknown mode: {mode!r}")

    # compile + warm-up outside the timed region (the reference likewise
    # excludes MPI_Init / cudaMalloc from T_solver via its barrier fences).
    # For --engine auto this is also where runtime RESOURCE_EXHAUSTED
    # degrades down the capacity ladder: the gates are budgets, the
    # allocator is the judge.
    if mode == "single":
        solver, args, engine, result = _warm_with_degradation(
            problem, jdtype, solver, args, engine, auto=requested_auto,
            geometry=geometry, theta=theta,
        )
    else:
        result = solver(*args)
        fence(result)

    if batch > 1 and mode == "single":
        # Chained differential protocol: one jitted dispatch runs `batch`
        # data-dependent solves (an opaque but value-exact perturbation of
        # the RHS defeats CSE without changing any f.p. value); T_solver is
        # the marginal cost (t_batch - t_single)/(batch - 1). This isolates
        # the solve from the fixed per-dispatch host<->device RTT — the
        # reference's MPI_Wtime brackets a locally attached GPU and pays no
        # such tunnel cost (poisson_mpi_cuda2.cu:1009-1015).
        chained = _chain_solver(solver, args, batch)
        out = chained(*args)
        fence(out)
        t1s, tbs = [], []
        for _ in range(repeat):
            t0 = time.perf_counter()
            result = solver(*args)
            # timing-protocol fences: the sync IS the measurement — each
            # perf_counter bracket must close on completed device work
            fence(result)  # tpulint: disable=TPU008
            t1s.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            out = chained(*args)
            fence(out)  # tpulint: disable=TPU008
            tbs.append(time.perf_counter() - t0)
        t1 = statistics.median(t1s)
        # Noise floor: under host-load jitter a chained dispatch can
        # measure FASTER than the single one (tb ≤ t1), collapsing the
        # marginal estimate to 0 — a meaningless T_solver that poisons
        # every derived rate (solves/sec → None, GB/s → inf). Fall back
        # to the chained per-dispatch cost for those samples: an upper
        # bound on the marginal cost, strictly positive, and exactly
        # equal in the noise-free regime the protocol targets.
        times = [
            (tb - t1) / (batch - 1) if tb > t1 else tb / batch
            for tb in tbs
        ]
    else:
        times = []
        for _ in range(repeat):
            t0 = time.perf_counter()
            for _ in range(batch):
                result = solver(*args)
            # one fence per measurement (after the batch, not per
            # dispatch): the timing protocol's justified sync
            fence(result)  # tpulint: disable=TPU008
            times.append((time.perf_counter() - t0) / batch)
    timer.add("solver", statistics.median(times))

    return _finish_report(
        problem, shape, dtype, jdtype, engine, result, timer, times,
        lanes=lanes, analytic=geometry is None,
        storage_dtype=storage_dtype, sstep_s=sstep_s,
    )


def _run_recycled(
    problem: Problem,
    dtype: str,
    jdtype,
    repeat: int = 1,
    batch: int = 1,
    recycle: int | None = None,
    warm_start: bool = False,
) -> RunReport:
    """One timed deflated/warm-started solve (``--recycle/--warm-start``).

    Init phase: assembly + (with ``recycle``) one ring-carrying capture
    solve, the host-side Ritz harvest, and the Galerkin projection that
    seeds x0 — the serve shape, where the first request of a bucket pays
    full price and its basis is what later requests deflate against.
    Solver phase: the plain repeat/batch timing protocol over the
    deflated restart. The harvest can decline (ill-conditioned Gram,
    short trace) — the run falls back to the undeflated start and the
    report simply shows cold iterations: basis quality buys iterations,
    never correctness.
    """
    from poisson_ellipse_tpu.ops import assembly
    from poisson_ellipse_tpu.solver import recycle as rec
    from poisson_ellipse_tpu.solver.pcg import pcg

    timer = PhaseTimer()
    with timer.phase("init"):
        a, b, rhs = assembly.assemble(problem, jdtype)
        x0 = None
        if recycle is not None:
            res0, trace0, ring = pcg(
                problem, a, b, rhs, history=True, recycle=int(recycle)
            )
            fence(res0)
            basis = rec.harvest(problem, a, b, trace0, ring)
            seed = res0.w if warm_start else None
            if basis is not None:
                if seed is not None:
                    from poisson_ellipse_tpu.ops.stencil import apply_a

                    h1 = jnp.asarray(problem.h1, rhs.dtype)
                    h2 = jnp.asarray(problem.h2, rhs.dtype)
                    residual = rhs - apply_a(seed, a, b, h1, h2)
                    x0 = rec.deflated_x0(basis, rhs, x0=seed,
                                         residual=residual)
                else:
                    x0 = rec.deflated_x0(basis, rhs)
            if x0 is None:  # declined harvest/projection: undeflated start
                x0 = seed
        elif warm_start:
            res0 = pcg(problem, a, b, rhs)
            fence(res0)
            x0 = res0.w
        # one jit per protocol run, operands re-dispatched every repeat:
        # no donation (timing reuses the inputs), no hoisting (the x0
        # closure IS the capture result this run exists to time)
        solver = jax.jit(  # tpulint: disable=TPU004,TPU006
            lambda a_, b_, rhs_: pcg(problem, a_, b_, rhs_, x0=x0)
        )
        args = (a, b, rhs)
        result = solver(*args)  # compile + warm-up inside init, like every
        fence(result)           # other untimed first dispatch

    times = []
    for _ in range(repeat):
        t0 = time.perf_counter()
        for _ in range(batch):
            result = solver(*args)
        # one fence per measurement: the timing protocol's justified sync
        fence(result)  # tpulint: disable=TPU008
        times.append((time.perf_counter() - t0) / batch)
    timer.add("solver", statistics.median(times))
    return _finish_report(
        problem, (1, 1), dtype, jdtype, "xla", result, timer, times,
    )


def _warm_with_degradation(problem, jdtype, solver, args, engine: str,
                           auto: bool, geometry=None, theta=None):
    """The first (compile + warm-up) dispatch, with the runtime OOM
    ladder for auto-selected engines.

    The capacity gates are *budgets measured on the bench part*; the
    allocator on the actual device is the judge. When it rules
    RESOURCE_EXHAUSTED on an auto pick, the next-smaller engine is built
    and retried after a short backoff (releasing the failed attempt's
    buffers first), down to xla — which has no capacity gate. An
    explicitly requested engine stays loud, but classified: the CLI maps
    :class:`OutOfMemoryError` to exit code 3.
    """
    while True:
        try:
            result = solver(*args)
            # warm-up fence: the sync marks the end of compile+first
            # dispatch, outside every timed region
            fence(result)  # tpulint: disable=TPU008
            return solver, args, engine, result
        except Exception as e:  # noqa: BLE001 — OOM classified, rest re-raised
            if not is_oom_error(e):
                raise
            if not (auto and engine in _DEGRADE_LADDER[:-1]):
                raise OutOfMemoryError(
                    f"engine {engine!r} hit RESOURCE_EXHAUSTED at "
                    f"warm-up: {e}"
                ) from e
            nxt = _DEGRADE_LADDER[_DEGRADE_LADDER.index(engine) + 1]
            obs_trace.note(
                f"engine {engine} hit RESOURCE_EXHAUSTED at warm-up; "
                f"degrading to {nxt} (backoff {_DEGRADE_BACKOFF_S:g}s)",
                _event="degrade:engine",
                from_engine=engine,
                to_engine=nxt,
            )
            del solver, args  # release the failed attempt before rebuilding
            time.sleep(_DEGRADE_BACKOFF_S)
            # the rebuild IS the degradation ladder: one build per OOM
            # rung, bounded by the ladder length
            solver, args, engine = build_solver(
                # tpulint: disable=TPU013 — one build per OOM rung
                problem, nxt, jdtype, geometry=geometry, theta=theta,
                validate_geometry=False,
            )


def _run_guarded(
    problem: Problem,
    mode: str,
    mesh_shape,
    dtype: str,
    jdtype,
    engine: str,
    timeout: float | None,
    max_recoveries: int,
    geometry=None,
    theta=None,
    storage_dtype: str | None = None,
    sstep_s: int = 4,
) -> RunReport:
    """One guarded (and/or deadlined) solve through
    ``resilience.guard.guarded_solve``. Timing is a plain wall clock
    around the chunked run — resilience trades peak dispatch efficiency
    for survivability, so this is not the protocol the bench numbers
    use (the checkpointed driver takes the same stance)."""
    from poisson_ellipse_tpu.resilience.guard import guarded_solve

    timer = PhaseTimer()
    with timer.phase("init"):
        mesh = resolve_mesh(mesh_shape) if mode == "sharded" else None
        if mode == "sharded" and engine == "auto":
            engine = "xla"
    shape = (
        (mesh.shape[AXIS_X], mesh.shape[AXIS_Y]) if mesh is not None else (1, 1)
    )
    t0 = time.perf_counter()
    guarded = guarded_solve(
        problem, engine, jdtype, mesh=mesh, timeout=timeout,
        max_recoveries=max_recoveries, geometry=geometry, theta=theta,
        storage_dtype=storage_dtype, sstep_s=sstep_s,
    )
    fence(guarded.result)
    t_solve = time.perf_counter() - t0
    timer.add("solver", t_solve)
    report = _finish_report(
        problem, shape, dtype, jdtype, guarded.engine, guarded.result,
        timer, [t_solve], analytic=geometry is None,
        storage_dtype=storage_dtype, sstep_s=sstep_s,
    )
    report.recoveries = [event.kind for event in guarded.recoveries]
    return report


def _run_batched_guarded(
    problem: Problem,
    dtype: str,
    jdtype,
    engine: str,
    lanes: int,
    timeout: float | None,
) -> RunReport:
    """One guarded lane-batched solve through the chunked lane driver
    (``batch.driver.solve_batched``): per-chunk lane health, quarantine
    events on the trace, graceful chunk-boundary timeout. Plain
    wall-clock timing — the resilience stance of ``_run_guarded``."""
    from poisson_ellipse_tpu.batch import solve_batched

    timer = PhaseTimer()
    with timer.phase("init"):
        pass
    t0 = time.perf_counter()
    guarded = solve_batched(
        problem, lanes, engine, jdtype, timeout=timeout,
    )
    fence(guarded.result)
    t_solve = time.perf_counter() - t0
    timer.add("solver", t_solve)
    report = _finish_report(
        problem, (1, 1), dtype, jdtype, engine, guarded.result, timer,
        [t_solve], lanes=lanes,
    )
    report.recoveries = [event.kind for event in guarded.recoveries]
    return report


def _chain_solver(solver, args, n: int):
    """One jitted dispatch running n data-dependent solves.

    Relies on the ``build_solver`` contract that the last arg is the RHS.
    The RHS of solve k+1 is multiplied by (1 + tiny*acc_k) where tiny is
    far below the dtype's machine epsilon relative to any reachable acc,
    so the product is bit-identical to the RHS (iteration counts and
    solutions are unchanged — verified against the published oracles) while
    the data dependence stops XLA deduplicating the solves.
    """
    rhs = args[-1]
    tiny = 1e-30 if jnp.dtype(rhs.dtype).itemsize >= 8 else 1e-12

    def chained(*a):
        r0 = a[-1]

        def one(_i, acc):
            res = solver(*a[:-1], r0 * (1.0 + tiny * acc))
            # jnp.sum: a lane-batched result carries (B,) diffs — the
            # perturbation only needs *a* data-dependent scalar
            return acc + jnp.sum(res.diff).astype(acc.dtype)

        acc = lax.fori_loop(0, n - 1, one, jnp.zeros((), r0.dtype))
        return solver(*a[:-1], r0 * (1.0 + tiny * acc))

    return jax.jit(chained)


def _finish_report(
    problem: Problem,
    shape: tuple[int, int],
    dtype: str,
    jdtype,
    engine: str,
    result,
    timer: PhaseTimer,
    times: list[float],
    timed_iters: int | None = None,
    lanes: int = 1,
    quarantined: int = 0,
    analytic: bool = True,
    storage_dtype: str | None = None,
    sstep_s: int = 4,
) -> RunReport:
    """Shared report tail: L2-vs-analytic, roofline, RunReport assembly.

    timed_iters — iterations the solver phase actually covered when that
    differs from the cumulative count (resumed checkpointed runs); the
    roofline is computed over it, and it is suppressed entirely for a
    resume that had nothing left to run.

    A lane-batched ``result`` (BatchedPCGResult) is reduced to the
    report's scalars — worst-lane iters/diff, all-lanes converged,
    lane-0 L2 — plus the aggregate solves/sec; the single-solve HBM
    roofline does not apply to it.
    """
    solves_per_sec = None
    if hasattr(result, "quarantined"):  # a per-lane BatchedPCGResult
        quarantined = int(jnp.sum(result.quarantined))
        iters = int(jnp.max(result.iters))
        converged = bool(jnp.all(result.converged))
        breakdown = bool(jnp.any(result.breakdown))
        diff = float(jnp.max(result.diff))
        w0 = result.w[0]
        if timer.totals["solver"] > 0:
            solves_per_sec = lanes / timer.totals["solver"]
    else:
        iters = int(result.iters)
        converged = bool(result.converged)
        breakdown = bool(result.breakdown)
        diff = float(result.diff)
        w0 = result.w
    with timer.phase("finalize"):
        # the analytic solution is an ellipse fact; for an arbitrary SDF
        # domain the metric is undefined — reported NaN, never a number
        # that silently compares a different domain's solution to it
        l2 = (
            float(l2_error_vs_analytic(problem, w0)) if analytic
            else float("nan")
        )

    from poisson_ellipse_tpu.harness.roofline import roofline

    n = timed_iters if timed_iters is not None else iters
    roof = (
        roofline(
            problem, engine, n, timer.totals["solver"], jdtype,
            n_devices=shape[0] * shape[1], storage_dtype=storage_dtype,
            sstep_s=sstep_s,
        )
        if n > 0 and lanes == 1 and engine not in BATCHED_ENGINES
        else {"passes_per_iter": 0.0, "hbm_gbps": 0.0, "hbm_peak_frac": None}
    )
    return RunReport(
        problem=problem,
        mesh_shape=shape,
        dtype=dtype,
        engine=engine,
        iters=iters,
        converged=converged,
        breakdown=breakdown,
        diff=diff,
        l2_error=l2,
        t_init=timer.totals["init"],
        t_solver=timer.totals["solver"],
        times=times,
        timed_iters=timed_iters,
        lanes=lanes,
        solves_per_sec=solves_per_sec,
        quarantined=quarantined,
        storage_dtype=storage_dtype,
        **roof,
    )


def _run_checkpointed(
    problem: Problem,
    mode: str,
    mesh_shape,
    dtype: str,
    jdtype,
    engine: str,
    directory: str,
    chunk: int,
) -> RunReport:
    """One checkpointed solve (resumes from ``directory`` if it holds a
    matching checkpoint). Timing here is a plain wall clock around the
    chunked run — a checkpointed solve trades peak dispatch efficiency for
    restartability, so it is not the protocol the bench numbers use."""
    from poisson_ellipse_tpu.solver.checkpoint import CheckpointingSolver

    if engine == "auto":
        engine = "xla"
    if engine not in ("xla", "pallas"):
        raise ValueError(
            "checkpointed runs persist the XLA-loop PCG carry; "
            "--engine must be xla or pallas (the per-op/per-shard stencil "
            f"kernel), got {engine!r}"
        )
    timer = PhaseTimer()
    with timer.phase("init"):
        mesh = resolve_mesh(mesh_shape) if mode == "sharded" else None
        solver = CheckpointingSolver(
            problem, directory, chunk=chunk, dtype=jdtype, stencil=engine,
            mesh=mesh,
        )
    shape = (
        (mesh.shape[AXIS_X], mesh.shape[AXIS_Y]) if mesh is not None else (1, 1)
    )
    with solver:
        # a resume timed from iteration start_k covers only the remaining
        # iterations — the roofline must not divide resumed wall-clock by
        # the cumulative count
        start_k = solver.latest_step() or 0
        t0 = time.perf_counter()
        result = solver.run()
        fence(result)
        t_solve = time.perf_counter() - t0
    timer.add("solver", t_solve)
    return _finish_report(
        problem, shape, dtype, jdtype, engine, result, timer, [t_solve],
        timed_iters=int(result.iters) - start_k,
    )


def _run_native(problem: Problem, repeat: int, threads: int) -> RunReport:
    import jax.numpy as jnp

    from poisson_ellipse_tpu.runtime import solve_native

    times = []
    result = None
    for _ in range(max(repeat, 1)):
        t0 = time.perf_counter()
        result = solve_native(problem, threads=threads)
        times.append(time.perf_counter() - t0)
    l2 = float(l2_error_vs_analytic(problem, jnp.asarray(result.w)))
    return RunReport(
        problem=problem,
        mesh_shape=(1, 1),
        dtype="f64",
        engine="native",
        iters=result.iters,
        converged=result.converged,
        breakdown=result.breakdown,
        diff=result.diff,
        l2_error=l2,
        t_init=0.0,
        t_solver=statistics.median(times),
        times=times,
        threads=threads,
    )
