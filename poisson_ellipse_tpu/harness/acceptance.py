"""On-accelerator acceptance gate: every engine compiles and hits the oracles.

``python -m poisson_ellipse_tpu.harness.acceptance`` runs each solver
engine on the small reference grids and asserts the published weighted
iteration counts (15/26/50 @ 10²/20²/40², from the compiled reference
stage1 code), plus the sharded path over whatever device mesh exists.
On a TPU this is the real-compile gate the CPU test suite cannot be
(tests/conftest.py pins the CPU backend; the Pallas engines interpret
there) — run it on the chip to prove the Mosaic kernels still build and
agree with the reference before trusting a bench number. The reference
has no automated tests at all (SURVEY §4); its manual oracle — identical
iteration counts across implementations (Этап1-4 tables) — is exactly
what this gate automates across *engines*.

The preconditioner engines (``mg-pcg``/``cheb-pcg``) exist to *change*
the iteration count, so the reference oracle cannot apply to them; their
rows gate on the ROADMAP's pivot instead — converged, strictly fewer
iterations than the diagonal oracle, and l2-vs-analytic no more than
10% above the diagonal solve's (one-sided: more accurate never fails).

``--headline`` adds the 400×600 row (546 iterations) with the auto
engine. Exit code 0 iff every row passes.
"""

from __future__ import annotations

import argparse
import sys

import jax
import jax.numpy as jnp

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.solver.engine import (
    ENGINES,
    PRECOND_ENGINES,
    build_solver,
)

# (M, N) -> weighted-norm oracle iterations (reference stage1 code,
# compiled and run; see BASELINE.md "Iteration counts")
SMALL_ORACLES = {(10, 10): 15, (20, 20): 26, (40, 40): 50}
HEADLINE = ((400, 600), 546)


def _diag_l2(M: int, N: int, _cache={}) -> float:
    """l2-vs-analytic of the diagonal-preconditioned reference solve —
    the parity yardstick for the preconditioner engines (cached: one
    extra small solve per grid, not per engine)."""
    from poisson_ellipse_tpu.utils.error import l2_error_vs_analytic

    if (M, N) not in _cache:
        problem = Problem(M=M, N=N)
        solver, args, _ = build_solver(problem, "xla", jnp.float32)
        _cache[(M, N)] = float(
            l2_error_vs_analytic(problem, solver(*args).w)
        )
    return _cache[(M, N)]


def _row(engine: str, M: int, N: int, oracle: int) -> tuple[bool, str]:
    problem = Problem(M=M, N=N)
    # the pipelined recurrence is a documented reordering: its contract
    # is the oracle ±2, not equality (ops.pipelined_pcg accuracy note)
    slack = 2 if engine.startswith("pipelined") else 0
    # the batched engines gate at 2 lanes (the lane plumbing must build,
    # not just the degenerate single-lane case); lane 0 is bit-identical
    # to the classical solve, so the classical oracle applies exactly —
    # and ±2 for the batched-pipelined reordering
    lanes = 2 if engine.startswith("batched") else 1
    slack = 2 if engine == "batched-pipelined" else slack
    try:
        solver, args, resolved = build_solver(
            problem, engine, jnp.float32, lanes=lanes
        )
        result = solver(*args)
        if lanes > 1:  # per-lane result: every lane must hit the oracle
            iters = int(jnp.max(result.iters))
            converged = bool(jnp.all(result.converged))
        else:
            iters = int(result.iters)
            converged = bool(result.converged)
        if engine in PRECOND_ENGINES:
            # the preconditioner engines exist to CHANGE the iteration
            # count, so the reference oracle pivots to the analytic
            # solution (ROADMAP item 1): converged, strictly fewer
            # iterations than the diagonal oracle, and l2-vs-analytic
            # no worse than +10% of the diagonal solve — the rule the
            # bench `precond` key enforces at the published grids.
            # (fmg never reaches this matrix: run_acceptance filters
            # it out below — its gates live in tests/test_fmg, the
            # graft-entry smoke check and the bench `fmg` key.)
            from poisson_ellipse_tpu.utils.error import (
                l2_error_vs_analytic,
            )

            l2 = float(l2_error_vs_analytic(problem, result.w))
            ref = _diag_l2(M, N)
            # one-sided: at equal δ the V-cycle often lands BELOW diag's
            # algebraic error — only worse-than-diag (>10%) is a miss
            ok = (
                converged and iters < oracle
                and ref > 0 and l2 <= ref * 1.10
            )
            note = (
                f"iters={iters} (< diag {oracle}) "
                f"l2={l2:.2e} (diag {ref:.2e})"
            )
            return ok, note
        ok = converged and abs(iters - oracle) <= slack
        note = f"iters={iters} (oracle {oracle}" + (
            f"±{slack})" if slack else ")"
        )
        if lanes > 1:
            note += f" [{lanes} lanes]"
        if resolved != engine:
            note += f" [auto->{resolved}]"
    except Exception as e:  # tpulint: disable=TPU009 — a build/compile failure IS the finding (reported as the row)
        ok, note = False, f"{type(e).__name__}: {e}"
    return ok, note


def _sharded_row(
    M: int, N: int, oracle: int, stencil_impl: str = "xla"
) -> tuple[bool, str]:
    from poisson_ellipse_tpu.parallel.pcg_sharded import solve_sharded

    slack = 2 if stencil_impl == "pipelined" else 0
    try:
        result = solve_sharded(
            Problem(M=M, N=N), dtype=jnp.float32, stencil_impl=stencil_impl
        )
        iters = int(result.iters)
        ok = bool(result.converged) and abs(iters - oracle) <= slack
        note = (
            f"iters={iters} (oracle {oracle}"
            + (f"±{slack})" if slack else ")")
            + f" over {len(jax.devices())} device(s)"
        )
    except Exception as e:  # tpulint: disable=TPU009 — the failure becomes the report row
        ok, note = False, f"{type(e).__name__}: {e}"
    return ok, note


def run_acceptance(headline: bool = False, out=sys.stderr) -> bool:
    print(f"backend: {jax.default_backend()}  devices: {jax.devices()}",
          file=out)
    all_ok = True
    # fmg is gated elsewhere, not by the oracle matrix: its iteration
    # count is the verification-handoff count (not an oracle fact), and
    # each row would pay a Lanczos probe + F-cycle build per grid —
    # tests/test_fmg pins its l2 parity, __graft_entry__'s fmg smoke
    # check drives it through the real CLI, and the bench `fmg` key
    # gates it on the chip
    engines = [e for e in ENGINES if e not in ("auto", "fmg")]
    for (M, N), oracle in SMALL_ORACLES.items():
        for engine in engines:
            ok, note = _row(engine, M, N, oracle)
            all_ok &= ok
            print(f"  {'ok ' if ok else 'FAIL'} {M}x{N} {engine:9s} {note}",
                  file=out)
    for (M, N), oracle in list(SMALL_ORACLES.items())[-1:]:
        for impl in ("xla", "pallas", "fused", "pipelined"):
            ok, note = _sharded_row(M, N, oracle, stencil_impl=impl)
            all_ok &= ok
            print(
                f"  {'ok ' if ok else 'FAIL'} {M}x{N} "
                f"{'sharded/' + impl:14s} {note}",
                file=out,
            )
    if headline:
        (M, N), oracle = HEADLINE
        ok, note = _row("auto", M, N, oracle)
        all_ok &= ok
        print(f"  {'ok ' if ok else 'FAIL'} {M}x{N} {'auto':9s} {note}",
              file=out)
    print("ACCEPTANCE " + ("PASS" if all_ok else "FAIL"), file=out)
    return all_ok


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m poisson_ellipse_tpu.harness.acceptance"
    )
    ap.add_argument(
        "--headline", action="store_true",
        help="also run 400x600 (546-iteration oracle) with the auto engine",
    )
    args = ap.parse_args(argv)
    return 0 if run_acceptance(headline=args.headline) else 1


if __name__ == "__main__":
    sys.exit(main())
