"""Harness layer (reference L6): CLI, phase profiling, result reporting.

The reference's per-stage ``main`` functions are the model: argv ``M N``
(``stage2-mpi/poisson_mpi_decomp.cpp:463-502``,
``stage4-mpi+cuda/poisson_mpi_cuda2.cu:985-1038``), barrier-fenced
wall-clock segmentation, rank-0 result summary, and (stage0/1) built-in
grid/thread sweep loops (``stage0/Withoutopenmp1.cpp:176-196``).
"""

from poisson_ellipse_tpu.harness.run import RunReport, run_once

__all__ = ["RunReport", "run_once"]
