"""Multi-chip scaling tables in the reference stage4 report format.

The stage4 report's table 1 (Этап_4_1213.pdf p.11; BASELINE.md "Stage 4")
rows are (grid, config, iters, T_solver, speedup-vs-reference-config);
its weak-scaling discussion compares per-device-constant workloads. This
module emits the same tables for a TPU mesh:

  strong scaling — one grid, growing mesh; speedup vs the first row,
    parallel efficiency = speedup / devices.
  weak scaling — per-device block constant: mesh (px, py) solves the
    (M0*px, N0*py) grid; efficiency = T(first row) / T(row) (ideal 1.0).

BASELINE.json configs 3/4 are one weak series from base 2048x2048:
mesh 1x1 -> 2048², 2x2 -> 4096², 4x4 -> 8192². On hardware:
``python bench_multichip.py --kind weak --grid 2048x2048 --meshes
1x1,2x2,4x4``. The same tables run on the virtual CPU mesh (scaled-down
grids) for CI — the reference analogously sanity-runs 40x40 at 1/2/4
mpirun ranks (Этап2.pdf table 1).

Iteration counts must be mesh-invariant (the reference's
cross-implementation oracle): every emitted table carries
``iters_consistent`` so a parity break is machine-visible.
"""

from __future__ import annotations

from poisson_ellipse_tpu.harness.run import run_once
from poisson_ellipse_tpu.models.problem import Problem

# the exact per-row key set (pinned by tests; downstream parsers rely on it)
ROW_SCHEMA = frozenset(
    {
        "grid",
        "mesh",
        "devices",
        "iters",
        "converged",
        "t_solver_s",
        "l2_error",
        "speedup",
        "efficiency",
        "hbm_gbps",
    }
)


def _row(report, t_first: float | None, devices_first: int, weak: bool) -> dict:
    t = report.t_solver
    devices = report.mesh_shape[0] * report.mesh_shape[1]
    if t_first is None or t <= 0:
        speedup, efficiency = 1.0, 1.0
    else:
        # both columns are relative to the table's FIRST row (which need
        # not be 1 device — a grid may not fit one chip): ideal strong
        # scaling from d0 to d devices is speedup d/d0, efficiency 1.0
        speedup = t_first / t
        efficiency = speedup if weak else speedup * devices_first / devices
    p = report.problem
    return {
        "grid": f"{p.M}x{p.N}",
        "mesh": list(report.mesh_shape),
        "devices": devices,
        "iters": report.iters,
        "converged": report.converged,
        "t_solver_s": round(t, 6),
        "l2_error": report.l2_error,
        "speedup": round(speedup, 3),
        "efficiency": round(efficiency, 3),
        "hbm_gbps": report.hbm_gbps,
    }


def scaling_table(
    kind: str,
    base_grid: tuple[int, int],
    meshes: list[tuple[int, int]],
    dtype: str = "f32",
    stencil_impl: str = "xla",
    repeat: int = 1,
    batch: int = 1,
) -> dict:
    """Run one scaling series and emit the stage4-format table.

    kind "strong": every mesh solves base_grid. kind "weak": mesh
    (px, py) solves (M0*px, N0*py) — constant per-device block.
    """
    if kind not in ("strong", "weak"):
        raise ValueError(f"kind must be 'strong' or 'weak', got {kind!r}")
    weak = kind == "weak"
    M0, N0 = base_grid
    rows = []
    t_first = None
    devices_first = meshes[0][0] * meshes[0][1]
    for px, py in meshes:
        problem = Problem(
            M=M0 * px if weak else M0, N=N0 * py if weak else N0
        )
        report = run_once(
            problem,
            mode="sharded",
            mesh_shape=(px, py),
            dtype=dtype,
            engine=stencil_impl,
            repeat=repeat,
            batch=batch,
        )
        rows.append(_row(report, t_first, devices_first, weak))
        if t_first is None:
            t_first = report.t_solver
    return {
        "kind": kind,
        "base_grid": f"{M0}x{N0}",
        "dtype": dtype,
        "stencil_impl": stencil_impl,
        "rows": rows,
        # the reference's oracle: same grid -> same iteration count on
        # every mesh (only meaningful for strong scaling, where the grid
        # is fixed across rows)
        "iters_consistent": (
            len({r["iters"] for r in rows}) <= 1 if not weak else None
        ),
        # static collective accounting for this engine (jaxpr-derived
        # psum/ppermute per iteration on the table's first mesh) — the
        # property the pipelined series exists to demonstrate, carried
        # in the artifact instead of prose
        "collectives_per_iter": _static_collectives(
            base_grid, meshes[0], dtype, stencil_impl
        ),
    }


def _static_collectives(base_grid, mesh_shape, dtype: str, stencil_impl: str):
    """{psum, ppermute} per iteration from ``obs.static_cost``, or None
    when the mesh cannot be traced (e.g. single-device CI shards)."""
    from poisson_ellipse_tpu.harness.run import resolve_dtype
    from poisson_ellipse_tpu.obs import static_cost

    try:
        rep = static_cost.engine_report(
            Problem(M=base_grid[0], N=base_grid[1]),
            engine=stencil_impl,
            dtype=resolve_dtype(dtype),
            mode="sharded",
            mesh_shape=tuple(mesh_shape),
            with_xla_cost=False,
        )
    except Exception:  # tpulint: disable=TPU009 — accounting must never fail a bench
        return None
    return {
        "psum": rep["psum_per_iter"],
        "ppermute": rep["ppermute_per_iter"],
    }


def throughput_table(
    base_grid: tuple[int, int],
    meshes: list[tuple[int, int]],
    lanes_per_device: int = 2,
    dtype: str = "f32",
    pipelined: bool = False,
    repeat: int = 1,
) -> dict:
    """Lane-sharded throughput series: solves/sec as the mesh grows.

    Each mesh (px, py) solves the SAME grid with ``lanes_per_device``
    whole lanes per device (``parallel.batched_sharded``) — the serving
    scale-out axis, where ideal scaling is aggregate solves/sec
    proportional to the device count at exactly 1 psum/iteration.
    ``scaling`` is solves/sec relative to the first row; ``efficiency``
    divides that by the device-count ratio (ideal 1.0).
    """
    M0, N0 = base_grid
    engine = "batched-pipelined" if pipelined else "batched"
    rows = []
    sps_first = None
    first_row = True
    devices_first = meshes[0][0] * meshes[0][1]
    for px, py in meshes:
        devices = px * py
        lanes = lanes_per_device * devices
        report = run_once(
            Problem(M=M0, N=N0),
            mode="sharded",
            mesh_shape=(px, py),
            dtype=dtype,
            engine=engine,
            lanes=lanes,
            repeat=repeat,
        )
        sps = report.solves_per_sec or 0.0
        # relative columns stay honest when the first row failed: later
        # rows carry None rather than silently rebasing on themselves
        if first_row:
            scaling = 1.0 if sps else None
        else:
            scaling = sps / sps_first if sps_first else None
        rows.append({
            "grid": f"{M0}x{N0}",
            "mesh": [px, py],
            "devices": devices,
            "lanes": lanes,
            "iters": report.iters,
            "converged": report.converged,
            "t_solver_s": round(report.t_solver, 6),
            "solves_per_sec": round(sps, 3),
            "scaling": round(scaling, 3) if scaling is not None else None,
            "efficiency": (
                round(scaling * devices_first / devices, 3)
                if scaling is not None
                else None
            ),
        })
        if first_row:
            sps_first = sps or None
            first_row = False
    return {
        "kind": "throughput",
        "base_grid": f"{M0}x{N0}",
        "dtype": dtype,
        "engine": engine,
        "lanes_per_device": lanes_per_device,
        "rows": rows,
        "iters_consistent": len({r["iters"] for r in rows}) <= 1,
        "collectives_per_iter": _static_collectives_batched(
            base_grid, meshes[0], lanes_per_device, dtype, pipelined
        ),
    }


def _static_collectives_batched(base_grid, mesh_shape, lanes_per_device,
                                dtype: str, pipelined: bool):
    """psum/ppermute per while-body of the lane-sharded solver — the
    1-psum-per-iteration property carried in the artifact (None when the
    mesh cannot be traced)."""
    from poisson_ellipse_tpu.harness.run import resolve_dtype, resolve_mesh
    from poisson_ellipse_tpu.obs import static_cost
    from poisson_ellipse_tpu.parallel.batched_sharded import (
        build_batched_sharded_solver,
    )

    try:
        mesh = resolve_mesh(tuple(mesh_shape))
        solver, args = build_batched_sharded_solver(
            Problem(M=base_grid[0], N=base_grid[1]),
            mesh,
            lanes_per_device * mesh_shape[0] * mesh_shape[1],
            resolve_dtype(dtype),
            pipelined=pipelined,
        )
        counts = static_cost.loop_primitive_counts(
            solver, args, static_cost.COLLECTIVE_PRIMS
        )
    except Exception:  # tpulint: disable=TPU009 — accounting must never fail a bench
        return None
    return {
        "psum": counts["psum"] + counts["psum_invariant"],
        "ppermute": counts["ppermute"],
    }


def parse_meshes(spec: str) -> list[tuple[int, int]]:
    """'1x1,2x2,2x4' -> [(1,1), (2,2), (2,4)]."""
    out = []
    for part in spec.split(","):
        px, _, py = part.lower().partition("x")
        out.append((int(px), int(py or px)))
    return out
