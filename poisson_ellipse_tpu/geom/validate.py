"""The pre-solve admissibility gate: reject bad problems BEFORE dispatch.

A serving stack cannot afford to discover mid-batch that a request's
geometry was garbage — a poisoned lane stalls its whole bucket. So every
way an arbitrary SDF can make the fictitious-domain problem unsolvable
is checked here, on HOST float64 arrays (the purity contract tpulint
TPU015 fences: validation never round-trips a traced value because it
never touches one), and failure is the classified
:class:`~poisson_ellipse_tpu.resilience.errors.InvalidGeometryError`
(exit 8) with a machine-readable ``reason`` — raised before any device
loop runs.

The checks, in rejection order (each reason documented on the error
class):

1. **spec** — a dict geometry parses through ``sdf.from_spec``
   (``malformed-spec``).
2. **level set** — finite on Ω (``sdf-nonfinite``).
3. **existence/resolution** — the domain has interior at 4×-refined
   sampling (``empty-domain``); every such region is visible to the
   node lattice (``under-resolved``: a feature thinner than h would
   make the discrete solve silently answer a different question — the
   gate refuses instead).
4. **containment** — D must not poke through the Dirichlet ring of Ω
   (``boundary-contact``; tangency, like the reference ellipse's
   (±1, 0), is allowed — strict interior crossing is not).
5. **operator** — the assembled coefficients are finite
   (``operator-nonfinite``), carry the 5-point M-matrix sign structure
   (``operator-not-m-matrix``), define a symmetric form
   (``operator-asymmetric``), and the preconditioned operator D⁻¹A is
   SPD by a short host Lanczos probe read through the EXISTING
   ``obs.spectrum`` reconstruction (``operator-not-spd``).

``validate`` returns a JSON-able report on acceptance so callers
(serving admission, ``harness --geometry``, the bench) can log what was
checked, including the probe's Ritz interval.
"""

from __future__ import annotations

import numpy as np

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.ops.stencil import apply_a_block, diag_d_block
from poisson_ellipse_tpu.resilience.errors import InvalidGeometryError

# fine-sampling refinement per cell for the existence/resolution/
# containment checks; 4 subsamples see any feature wider than h/4
RESOLUTION_REFINE = 4

# host Lanczos probe length (diag-PCG steps recorded for obs.spectrum);
# enough for the extremal Ritz values to certify sign-definiteness
LANCZOS_STEPS = 24

# numeric slack for the symmetry probe: f64 round-off over two stencil
# applications and two O(MN) reductions
_SYMMETRY_RTOL = 1e-10


def _fail(reason: str, msg: str):
    raise InvalidGeometryError(f"{msg} [{reason}]", reason=reason)


def _apply_a_np(w, a, b, h1, h2):
    """Host-numpy A·w on the full node grid: ``apply_a_block`` is pure
    slicing arithmetic, so it serves numpy exactly as it serves jnp."""
    return np.pad(apply_a_block(w, a, b, h1, h2), 1)


def _fine_points(problem: Problem, refine: int):
    """Cell-interior sample coordinates at ``refine``× resolution:
    (refine·M,) x and (refine·N,) y, each point strictly inside its cell."""
    off = (np.arange(refine, dtype=np.float64) + 0.5) / refine
    xi = problem.a1 + (
        np.arange(problem.M, dtype=np.float64)[:, None] + off[None, :]
    ).ravel() * problem.h1
    yj = problem.a2 + (
        np.arange(problem.N, dtype=np.float64)[:, None] + off[None, :]
    ).ravel() * problem.h2
    return xi, yj


def _dilate(cells: np.ndarray) -> np.ndarray:
    """3×3 binary dilation by shifted ORs (no scipy dependency)."""
    out = cells.copy()
    out[1:, :] |= cells[:-1, :]
    out[:-1, :] |= cells[1:, :]
    grown = out.copy()
    grown[:, 1:] |= out[:, :-1]
    grown[:, :-1] |= out[:, 1:]
    return grown


def _lanczos_probe(problem: Problem, a, b, rhs, steps: int):
    """A short host-f64 diagonal-PCG on the assembled operator,
    recording (zr, diff, α, β) in exactly the ``obs.convergence`` trace
    convention — so the EXISTING Lanczos reconstruction of
    ``obs.spectrum`` turns it into Ritz values of D⁻¹A. Returns
    ``(trace_dict, failure_reason_or_None)``.

    A breakdown pivot ((Ap, p) ≤ 0 with p ≠ 0) or a non-positive
    preconditioned energy (z, r) ≤ 0 before convergence is a direct
    indefiniteness witness, reported without waiting for the Ritz pass.
    """
    h1, h2 = problem.h1, problem.h2
    d = np.pad(diag_d_block(a, b, h1, h2), 1)
    dinv = np.where(d != 0.0, 1.0 / np.where(d != 0.0, d, 1.0), 0.0)
    w = np.zeros_like(rhs)
    r = rhs.copy()
    z = r * dinv
    zr = float((z * r).sum() * h1 * h2)
    p = z.copy()
    hist = {"zr": [], "diff": [], "alpha": [], "beta": []}
    for _ in range(steps):
        ap = _apply_a_np(p, a, b, h1, h2)
        denom = float((ap * p).sum() * h1 * h2)
        pp = float((p * p).sum())
        if pp == 0.0:
            break  # converged exactly; nothing more to learn
        if denom <= 0.0:
            return hist, (
                f"(Ap, p) = {denom:g} on a nonzero direction — an "
                "indefinite pivot"
            )
        alpha = zr / denom
        w = w + alpha * p
        r = r - alpha * ap
        z = r * dinv
        zr_new = float((z * r).sum() * h1 * h2)
        diff = abs(alpha) * np.sqrt(pp * h1 * h2)
        beta = zr_new / zr
        hist["zr"].append(zr_new)
        hist["diff"].append(diff)
        hist["alpha"].append(alpha)
        hist["beta"].append(beta)
        if diff < problem.delta or zr_new == 0.0:
            break
        if zr_new < 0.0:
            return hist, (
                f"(z, r) = {zr_new:g} before convergence — the "
                "preconditioned energy went negative"
            )
        zr = zr_new
        p = z + beta * p
    return hist, None


def validate(problem: Problem, geometry, theta=None,
             spd_probe: bool = True, operands=None) -> dict:
    """Run the full admissibility gate; raise classified
    :class:`InvalidGeometryError` on the first failure, return the
    JSON-able acceptance report otherwise.

    ``geometry`` may be an ``sdf`` shape or its JSON spec (parsed —
    and rejected — here, the gate's first rung). ``operands`` lets a
    caller that already assembled (a, b, rhs) f64 arrays share them;
    ``spd_probe=False`` skips the Lanczos rung (the other operator
    checks still run).
    """
    from poisson_ellipse_tpu.geom import quadrature, sdf as geom_sdf

    if isinstance(geometry, dict):
        geometry = geom_sdf.from_spec(geometry)  # raises malformed-spec
    elif not callable(geometry):
        _fail(
            "malformed-spec",
            f"geometry must be an SDF shape or its JSON spec, got "
            f"{type(geometry).__name__}",
        )
    if theta is None:
        theta = quadrature.DEFAULT_THETA

    M, N = problem.M, problem.N
    x = problem.a1 + np.arange(M + 1, dtype=np.float64) * problem.h1
    y = problem.a2 + np.arange(N + 1, dtype=np.float64) * problem.h2
    phi = np.asarray(geometry(x[:, None], y[None, :], np), dtype=np.float64)
    if not np.isfinite(phi).all():
        _fail("sdf-nonfinite", "the level set evaluates non-finite on Ω")
    node_inside = phi < 0.0

    xf, yf = _fine_points(problem, RESOLUTION_REFINE)
    fine_inside = np.asarray(
        geometry(xf[:, None], yf[None, :], np), dtype=np.float64
    ) < 0.0
    if not fine_inside.any():
        _fail(
            "empty-domain",
            f"no point of Omega is inside the domain at "
            f"{RESOLUTION_REFINE}x-refined sampling — the grid would "
            "solve on an empty region",
        )

    # containment: the Dirichlet ring itself must not be strictly inside
    # (tangency passes — the reference ellipse touches (+-1, 0))
    ring_x = np.concatenate([x, x, np.full(N + 1, x[0]), np.full(N + 1, x[-1])])
    ring_y = np.concatenate([np.full(M + 1, y[0]), np.full(M + 1, y[-1]), y, y])
    ring_phi = np.asarray(geometry(ring_x, ring_y, np), dtype=np.float64)
    if (ring_phi < 0.0).any():
        _fail(
            "boundary-contact",
            "the domain crosses the Dirichlet ring of Omega — the "
            "fictitious-domain penalty band needs D contained in Omega",
        )

    # resolution: every region with interior must be visible to the node
    # lattice. A cell holding inside samples whose 1-cell-dilated corner
    # neighborhood holds NO inside node is a feature the grid cannot see.
    fine_cells = fine_inside.reshape(
        M, RESOLUTION_REFINE, N, RESOLUTION_REFINE
    ).any(axis=(1, 3))
    cell_seen = (
        node_inside[:-1, :-1] | node_inside[1:, :-1]
        | node_inside[:-1, 1:] | node_inside[1:, 1:]
    )
    invisible = fine_cells & ~_dilate(cell_seen)
    if invisible.any():
        n_bad = int(invisible.sum())
        _fail(
            "under-resolved",
            f"{n_bad} cell(s) contain domain interior invisible to the "
            f"node lattice — a feature thinner than h ~ "
            f"{max(problem.h1, problem.h2):g}; refine the grid or drop "
            "the feature",
        )
    if not node_inside.any():
        _fail(
            "under-resolved",
            "the domain has interior but no grid node falls inside it",
        )

    # operator checks on the f64 host assembly (rounded-once fidelity)
    if operands is None:
        a, b, rhs = assembly.assemble_numpy(
            problem, geometry=geometry, theta=theta
        )
    else:
        a, b, rhs = (np.asarray(o, dtype=np.float64) for o in operands)
    if not (np.isfinite(a).all() and np.isfinite(b).all()
            and np.isfinite(rhs).all()):
        _fail(
            "operator-nonfinite",
            "assembled coefficients carry non-finite entries",
        )
    valid_a = a[1:M + 1, 1:N + 1]
    valid_b = b[1:M + 1, 1:N + 1]
    if valid_a.min() <= 0.0 or valid_b.min() <= 0.0:
        _fail(
            "operator-not-m-matrix",
            "a face coefficient is <= 0 on the valid face range — the "
            "5-point operator loses its M-matrix sign structure (and "
            "with it the discrete maximum principle)",
        )

    rng = np.random.default_rng(0)
    u = np.zeros_like(a)
    v = np.zeros_like(a)
    u[1:M, 1:N] = rng.standard_normal((M - 1, N - 1))
    v[1:M, 1:N] = rng.standard_normal((M - 1, N - 1))
    au = _apply_a_np(u, a, b, problem.h1, problem.h2)
    av = _apply_a_np(v, a, b, problem.h1, problem.h2)
    uv_scale = max(abs(float((au * u).sum())), abs(float((av * v).sum())),
                   1e-30)
    asym = abs(float((au * v).sum()) - float((u * av).sum()))
    if asym > _SYMMETRY_RTOL * uv_scale:
        _fail(
            "operator-asymmetric",
            f"<Au, v> != <u, Av> (relative defect {asym / uv_scale:.2e})",
        )

    report: dict = {
        "ok": True,
        "theta": theta,
        "inside_nodes": int(node_inside.sum()),
        "checks": [
            "spec", "sdf-finite", "non-empty", "containment",
            "resolution", "operator-finite", "m-matrix", "symmetry",
        ],
    }
    if spd_probe:
        from poisson_ellipse_tpu.obs import spectrum

        steps = min(LANCZOS_STEPS, max((M - 1) * (N - 1), 1))
        hist, witness = _lanczos_probe(problem, a, b, rhs, steps)
        if witness is not None:
            _fail("operator-not-spd", f"Lanczos probe: {witness}")
        trace = {k: np.asarray(vv, dtype=np.float64)
                 for k, vv in hist.items()}
        ritz = spectrum.ritz_values(trace)
        if ritz.size and float(ritz[0]) <= 0.0:
            _fail(
                "operator-not-spd",
                f"non-positive Ritz value {float(ritz[0]):g} — the "
                "preconditioned operator is not SPD",
            )
        report["checks"].append("spd-lanczos")
        report["lanczos_steps"] = int(
            np.asarray(trace["alpha"]).size
        )
        bounds = spectrum.eigenvalue_bounds(trace)
        if bounds is not None:
            report["ritz_interval"] = [bounds[0], bounds[1]]
    return report
