"""JSON-serializable signed-distance primitives and their composition.

Each shape is a frozen dataclass callable as ``shape(x, y, xp=jnp)``
over broadcast coordinate arrays, returning a level-set value that is
**negative strictly inside** the domain, positive outside, ~0 on the
boundary — the same ``xp=`` array-module convention as
``models.ellipse``, so one definition serves the float64 host assembly
path (``xp=numpy``) and any traced consumer (``xp=jnp``). Values near
the boundary scale like geometric distance (exact for circle/half-plane,
a monotone proxy for ellipse/rectangle), which is all the bisection
quadrature (:mod:`.quadrature`) and the resolution checks
(:mod:`.validate`) need: a *sign-correct, Lipschitz-on-faces* implicit
function.

The wire form (``to_spec``/``from_spec``) is a flat JSON tree — the
shape a serving request can carry, a journal can replay, and a fuzzer
can mutate. ``from_spec`` is the FIRST rung of the admissibility gate:
a malformed tree raises the classified
:class:`~poisson_ellipse_tpu.resilience.errors.InvalidGeometryError`
(reason ``malformed-spec``, exit 8) instead of a raw KeyError a serving
lane would have to guess at.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Tuple

import jax.numpy as jnp

from poisson_ellipse_tpu.models.ellipse import safe_sqrt
from poisson_ellipse_tpu.resilience.errors import InvalidGeometryError

# recursion guard for from_spec: a hostile/buggy spec must fail fast,
# not blow the interpreter stack
MAX_SPEC_DEPTH = 16


@dataclasses.dataclass(frozen=True)
class Ellipse:
    """{((x-cx)/rx)² + ((y-cy)/ry)² < 1}; the default is the reference
    domain D = {x² + 4y² < 1} (rx=1, ry=1/2)."""

    cx: float = 0.0
    cy: float = 0.0
    rx: float = 1.0
    ry: float = 0.5

    def __call__(self, x, y, xp=jnp):
        dx = (x - self.cx) / self.rx
        dy = (y - self.cy) / self.ry
        return safe_sqrt(dx * dx + dy * dy, xp) - 1.0

    def to_spec(self) -> dict:
        return {"kind": "ellipse", "cx": self.cx, "cy": self.cy,
                "rx": self.rx, "ry": self.ry}


@dataclasses.dataclass(frozen=True)
class Circle:
    """Exact SDF of the disc of radius r at (cx, cy)."""

    cx: float = 0.0
    cy: float = 0.0
    r: float = 0.25

    def __call__(self, x, y, xp=jnp):
        dx = x - self.cx
        dy = y - self.cy
        return safe_sqrt(dx * dx + dy * dy, xp) - self.r

    def to_spec(self) -> dict:
        return {"kind": "circle", "cx": self.cx, "cy": self.cy, "r": self.r}


@dataclasses.dataclass(frozen=True)
class HalfPlane:
    """{nx·x + ny·y + offset < 0} — exact SDF for a unit normal (the
    constructor spec normalises)."""

    nx: float = 1.0
    ny: float = 0.0
    offset: float = 0.0

    def __call__(self, x, y, xp=jnp):
        norm = math.hypot(self.nx, self.ny)
        return (self.nx * x + self.ny * y + self.offset) / norm

    def to_spec(self) -> dict:
        return {"kind": "halfplane", "nx": self.nx, "ny": self.ny,
                "offset": self.offset}


@dataclasses.dataclass(frozen=True)
class Rectangle:
    """The axis-aligned box (x0, x1) × (y0, y1), max-norm level set."""

    x0: float = -0.5
    y0: float = -0.25
    x1: float = 0.5
    y1: float = 0.25

    def __call__(self, x, y, xp=jnp):
        return xp.maximum(
            xp.maximum(self.x0 - x, x - self.x1),
            xp.maximum(self.y0 - y, y - self.y1),
        )

    def to_spec(self) -> dict:
        return {"kind": "rectangle", "x0": self.x0, "y0": self.y0,
                "x1": self.x1, "y1": self.y1}


@dataclasses.dataclass(frozen=True, init=False)
class Union:
    """min over children: inside any."""

    shapes: Tuple[object, ...]

    def __init__(self, *shapes):
        # accept both Union(a, b) and Union((a, b))
        if len(shapes) == 1 and isinstance(shapes[0], tuple):
            shapes = shapes[0]
        object.__setattr__(self, "shapes", tuple(shapes))

    def __call__(self, x, y, xp=jnp):
        out = self.shapes[0](x, y, xp)
        for s in self.shapes[1:]:
            out = xp.minimum(out, s(x, y, xp))
        return out

    def to_spec(self) -> dict:
        return {"kind": "union", "shapes": [s.to_spec() for s in self.shapes]}


@dataclasses.dataclass(frozen=True, init=False)
class Intersection:
    """max over children: inside all."""

    shapes: Tuple[object, ...]

    def __init__(self, *shapes):
        # accept both Intersection(a, b) and Intersection((a, b))
        if len(shapes) == 1 and isinstance(shapes[0], tuple):
            shapes = shapes[0]
        object.__setattr__(self, "shapes", tuple(shapes))

    def __call__(self, x, y, xp=jnp):
        out = self.shapes[0](x, y, xp)
        for s in self.shapes[1:]:
            out = xp.maximum(out, s(x, y, xp))
        return out

    def to_spec(self) -> dict:
        return {
            "kind": "intersection",
            "shapes": [s.to_spec() for s in self.shapes],
        }


@dataclasses.dataclass(frozen=True)
class Difference:
    """a minus b: max(a, −b)."""

    a: object
    b: object

    def __call__(self, x, y, xp=jnp):
        return xp.maximum(self.a(x, y, xp), -self.b(x, y, xp))

    def to_spec(self) -> dict:
        return {"kind": "difference", "a": self.a.to_spec(),
                "b": self.b.to_spec()}


@dataclasses.dataclass(frozen=True)
class Translate:
    """The child shape shifted by (dx, dy)."""

    shape: object
    dx: float = 0.0
    dy: float = 0.0

    def __call__(self, x, y, xp=jnp):
        return self.shape(x - self.dx, y - self.dy, xp)

    def to_spec(self) -> dict:
        return {"kind": "translate", "shape": self.shape.to_spec(),
                "dx": self.dx, "dy": self.dy}


def is_inside(shape, x, y, xp=jnp):
    """Open-domain membership: level set strictly negative."""
    return shape(x, y, xp) < 0.0


# --------------------------------------------------------------------------
# spec ↔ parameter-vector round trip (the differentiable-solving surface)
# --------------------------------------------------------------------------
#
# Every numeric leaf of a shape tree, in a DETERMINISTIC order (the
# dataclass field order of each node, children in composition order —
# exactly the order ``to_spec`` serialises). ``params_of`` reads them out
# as a float64 vector; ``with_params`` rebuilds the same tree around new
# values, which may be traced scalars (``jax.grad`` over geometry walks
# through here) or plain numbers (an optimizer step re-serialising to a
# valid JSON spec — plain values are coerced to built-in ``float`` so
# ``json.dumps(to_spec(...))`` never sees a numpy scalar).

_PARAM_FIELDS = {
    Ellipse: ("cx", "cy", "rx", "ry"),
    Circle: ("cx", "cy", "r"),
    HalfPlane: ("nx", "ny", "offset"),
    Rectangle: ("x0", "y0", "x1", "y1"),
    Translate: ("dx", "dy"),
}


def _as_param(v):
    """Coerce concrete numbers to built-in float (JSON-serialisable via
    ``to_spec``); traced/abstract values pass through untouched so the
    same rebuild path serves ``jax.grad``."""
    if isinstance(v, (bool,)):
        raise _malformed(f"parameter must be a number, got {v!r}")
    if isinstance(v, (int, float)):
        return float(v)
    import numpy as _np

    if isinstance(v, _np.generic) or (
        isinstance(v, _np.ndarray) and v.ndim == 0
    ):
        return float(v)
    return v


def n_params(shape) -> int:
    """Number of numeric leaves ``params_of``/``with_params`` traverse."""
    cls = type(shape)
    if cls in (Union, Intersection):
        return sum(n_params(s) for s in shape.shapes)
    if cls is Difference:
        return n_params(shape.a) + n_params(shape.b)
    count = len(_PARAM_FIELDS.get(cls, ()))
    if cls is Translate:
        count += n_params(shape.shape)
    if cls not in _PARAM_FIELDS and cls not in (Union, Intersection,
                                                Difference):
        raise _malformed(f"unknown shape node {cls.__name__!r}")
    return count


def params_of(shape):
    """The shape tree's numeric leaves as a float64 numpy vector, in
    ``to_spec`` order — the optimisation variable of the shape-
    optimisation workload (``diff/``)."""
    import numpy as _np

    out: list[float] = []

    def walk(s):
        cls = type(s)
        if cls in (Union, Intersection):
            for child in s.shapes:
                walk(child)
            return
        if cls is Difference:
            walk(s.a)
            walk(s.b)
            return
        fields = _PARAM_FIELDS.get(cls)
        if fields is None:
            raise _malformed(f"unknown shape node {cls.__name__!r}")
        for f in fields:
            out.append(float(getattr(s, f)))
        if cls is Translate:
            walk(s.shape)

    walk(shape)
    return _np.asarray(out, dtype=_np.float64)


def with_params(shape, values):
    """Rebuild ``shape``'s tree with its numeric leaves replaced by
    ``values`` (any sequence/array of length ``n_params(shape)``).

    The round trip ``with_params(s, params_of(s))`` reproduces ``s``
    exactly (``to_spec`` byte-equal after ``json`` round-trip — fuzzed
    in ``geom.fuzz``); traced ``values`` produce a shape whose level
    set is differentiable w.r.t. them, which is how ``diff.assembly``
    makes the θ→(a, b, rhs) path traceable end-to-end."""
    values = list(values)
    if len(values) != n_params(shape):
        raise _malformed(
            f"expected {n_params(shape)} parameters for this tree, got "
            f"{len(values)}"
        )
    it = iter(values)

    def rebuild(s):
        cls = type(s)
        if cls in (Union, Intersection):
            return cls(*[rebuild(child) for child in s.shapes])
        if cls is Difference:
            return Difference(a=rebuild(s.a), b=rebuild(s.b))
        fields = _PARAM_FIELDS[cls]
        kwargs = {f: _as_param(next(it)) for f in fields}
        if cls is Translate:
            return Translate(shape=rebuild(s.shape), **kwargs)
        return cls(**kwargs)

    return rebuild(shape)


def to_spec(shape) -> dict:
    """The JSON tree of ``shape`` (the serving/journal wire form)."""
    return shape.to_spec()


def _malformed(msg: str) -> InvalidGeometryError:
    return InvalidGeometryError(
        f"malformed geometry spec: {msg}", reason="malformed-spec"
    )


def _number(spec: dict, key: str, default=None) -> float:
    if key not in spec:
        if default is None:
            raise _malformed(f"{spec.get('kind')!r} is missing {key!r}")
        return float(default)
    v = spec[key]
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise _malformed(f"{key!r} must be a number, got {v!r}")
    v = float(v)
    if not math.isfinite(v):
        raise _malformed(f"{key!r} must be finite, got {v!r}")
    return v


def _positive(spec: dict, key: str, default=None) -> float:
    v = _number(spec, key, default)
    if v <= 0:
        raise _malformed(f"{key!r} must be > 0, got {v!r}")
    return v


def from_spec(spec, _depth: int = 0):
    """Rebuild an SDF tree from its JSON form; the gate's first rung.

    Every structural defect — not a dict, unknown ``kind``, missing or
    non-finite parameters, zero radii, degenerate boxes, empty
    composites, over-deep nesting — raises the classified
    :class:`InvalidGeometryError` (reason ``malformed-spec``). Nothing
    past this function ever sees a half-parsed geometry.
    """
    if _depth > MAX_SPEC_DEPTH:
        raise _malformed(f"nesting deeper than {MAX_SPEC_DEPTH}")
    if not isinstance(spec, dict):
        raise _malformed(f"expected an object, got {type(spec).__name__}")
    kind = spec.get("kind")
    if kind == "ellipse":
        return Ellipse(
            cx=_number(spec, "cx", 0.0), cy=_number(spec, "cy", 0.0),
            rx=_positive(spec, "rx", 1.0), ry=_positive(spec, "ry", 0.5),
        )
    if kind == "circle":
        return Circle(
            cx=_number(spec, "cx", 0.0), cy=_number(spec, "cy", 0.0),
            r=_positive(spec, "r", 0.25),
        )
    if kind == "halfplane":
        nx = _number(spec, "nx", 1.0)
        ny = _number(spec, "ny", 0.0)
        if nx == 0.0 and ny == 0.0:
            raise _malformed("halfplane normal must be nonzero")
        return HalfPlane(nx=nx, ny=ny, offset=_number(spec, "offset", 0.0))
    if kind == "rectangle":
        x0, x1 = _number(spec, "x0", -0.5), _number(spec, "x1", 0.5)
        y0, y1 = _number(spec, "y0", -0.25), _number(spec, "y1", 0.25)
        if x0 >= x1 or y0 >= y1:
            raise _malformed(
                f"rectangle needs x0 < x1 and y0 < y1, got "
                f"({x0}, {y0})..({x1}, {y1})"
            )
        return Rectangle(x0=x0, y0=y0, x1=x1, y1=y1)
    if kind in ("union", "intersection"):
        shapes = spec.get("shapes")
        if not isinstance(shapes, (list, tuple)) or not shapes:
            raise _malformed(f"{kind!r} needs a non-empty 'shapes' list")
        children = tuple(from_spec(s, _depth + 1) for s in shapes)
        return (Union if kind == "union" else Intersection)(*children)
    if kind == "difference":
        if "a" not in spec or "b" not in spec:
            raise _malformed("'difference' needs 'a' and 'b'")
        return Difference(
            a=from_spec(spec["a"], _depth + 1),
            b=from_spec(spec["b"], _depth + 1),
        )
    if kind == "translate":
        if "shape" not in spec:
            raise _malformed("'translate' needs 'shape'")
        return Translate(
            shape=from_spec(spec["shape"], _depth + 1),
            dx=_number(spec, "dx", 0.0), dy=_number(spec, "dy", 0.0),
        )
    raise _malformed(f"unknown kind {kind!r}")
