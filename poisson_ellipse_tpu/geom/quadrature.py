"""Face fractions by adaptive 1-D bisection of the SDF along cell faces.

The reference's assembly needs exactly one geometric fact per face: the
length of the face's intersection with the domain D
(``stage0/Withoutopenmp1.cpp:19-39`` computes it in closed form for the
ellipse). For an arbitrary SDF composition no closed form exists, so
this module replaces it with 1-D root finding along each face:

1. sample the level set at ``samples``+1 points along the face;
2. bracket every sign change and bisect it to ~2⁻⁶⁰ of the face length
   (below f64 resolution of O(1) coordinates — the ellipse through this
   path matches ``models.ellipse.segment_length_*`` to ≤1e-12 relative);
3. subintervals whose endpoints agree in sign but whose level values are
   small enough to hide a crossing pair (the |φ| < Lipschitz·Δt test)
   are re-sampled at ``refine``× resolution first — the *adaptive* part,
   which catches near-tangent faces and thin walls/slivers a uniform
   sweep would mis-measure.

Everything runs on the HOST in float64 over vectorised numpy — the same
rounded-once fidelity stance as ``ops.assembly.assemble_numpy`` (the
cut-face blend amplifies fraction noise by 1/ε), and the purity contract
tpulint TPU015 fences: no traced values are round-tripped here because
nothing here is traced.

The degenerate-cut defense lives at this layer too:
:func:`clamp_lengths` snaps cut fractions within θ of the empty/full
endpoints to exactly empty/full. A sliver cut (fraction → 0 under a
weak-penalty ε) couples two regions through a conductance ~fraction,
putting a λ ~ fraction eigenvalue into D⁻¹A — κ ~ 1/fraction, and
diag-PCG stalls (the CutFEM small-cut pathology; Burman–Hansbo's ghost
penalty solves it variationally, clamping is the finite-volume
equivalent). The clamp is *reported*, never silent: ``ops.assembly``
emits a ``geom:degenerate-cut`` trace event with the counts, and the
κ(M⁻¹A) impact is measurable through ``obs.spectrum``.
"""

from __future__ import annotations

import numpy as np

from poisson_ellipse_tpu.models.problem import Problem

# cut-fraction clamp threshold: fractions in (0, θ) snap to empty,
# (1−θ, 1) snap to full. 1e-6 of a face is far below any feature the
# admissibility gate's resolution check admits, so the clamp only ever
# removes slivers the discretisation could not represent anyway.
DEFAULT_THETA = 1e-6

# initial uniform samples per face; the suspicious-subinterval pass
# refines by REFINE where the level values could hide a crossing pair
DEFAULT_SAMPLES = 16
REFINE = 32
BISECT_ITERS = 60

# host-memory bound for the vectorised sweep: faces are processed in
# chunks of this many level-set evaluations
_CHUNK_EVALS = 2_000_000


def _bisect(sdf, x0, y0, ux, uy, seg_len, tlo, thi, lo_inside):
    """Bisect the bracketed sign change of φ along t ∈ [tlo, thi] (face
    parameter) to ~(thi−tlo)·2⁻⁶⁰; all arrays are per-crossing."""
    tlo = tlo.copy()
    thi = thi.copy()
    for _ in range(BISECT_ITERS):
        tm = 0.5 * (tlo + thi)
        mid_inside = (
            sdf(x0 + ux * seg_len * tm, y0 + uy * seg_len * tm, np) < 0.0
        )
        same = mid_inside == lo_inside
        tlo = np.where(same, tm, tlo)
        thi = np.where(same, thi, tm)
    return 0.5 * (tlo + thi)


def _piece_lengths(sdf, x0, y0, ux, uy, seg_len, t, phi):
    """Inside-length (in t units, face ∈ [0, 1]) from sampled level
    values ``phi`` (n, K+1) at face parameters ``t`` (K+1,)."""
    inside = phi < 0.0
    dt = t[1] - t[0]
    left, right = inside[:, :-1], inside[:, 1:]
    contrib = np.where(left & right, dt, 0.0)

    rows, cols = np.nonzero(left != right)
    if rows.size:
        tstar = _bisect(
            sdf, x0[rows], y0[rows], ux, uy, seg_len,
            t[cols], t[cols + 1], left[rows, cols],
        )
        contrib[rows, cols] = np.where(
            left[rows, cols], tstar - t[cols], t[cols + 1] - tstar
        )
    return contrib


def _lengths_along(sdf, x0, y0, ux, uy, seg_len,
                   samples: int = DEFAULT_SAMPLES) -> np.ndarray:
    """Length of {face_k} ∩ D for n parallel faces of length ``seg_len``
    starting at (x0[k], y0[k]) along unit direction (ux, uy)."""
    if seg_len <= 0:
        return np.zeros_like(x0)
    t = np.linspace(0.0, 1.0, samples + 1)
    phi = sdf(
        x0[:, None] + ux * seg_len * t[None, :],
        y0[:, None] + uy * seg_len * t[None, :],
        np,
    )
    phi = np.asarray(phi, dtype=np.float64)
    contrib = _piece_lengths(sdf, x0, y0, ux, uy, seg_len, t, phi)

    # adaptive pass: a same-sign subinterval can hide an even number of
    # crossings only if the level set dips through zero between samples —
    # which needs |φ| at both endpoints below ~the subinterval's length
    # (the primitives scale like distance near their boundary; 2× covers
    # composition slack). Those are re-resolved at REFINE× resolution.
    dt = t[1] - t[0]
    inside = phi < 0.0
    same_sign = inside[:, :-1] == inside[:, 1:]
    small = np.minimum(np.abs(phi[:, :-1]), np.abs(phi[:, 1:])) < (
        2.0 * seg_len * dt
    )
    rows, cols = np.nonzero(same_sign & small)
    if rows.size:
        tf = np.linspace(0.0, 1.0, REFINE + 1)
        sub_t = t[cols][:, None] + dt * tf[None, :]
        phi_f = np.asarray(
            sdf(
                x0[rows][:, None] + ux * seg_len * sub_t,
                y0[rows][:, None] + uy * seg_len * sub_t,
                np,
            ),
            dtype=np.float64,
        )
        # per-suspicious-subinterval inside length via the same machinery
        # on the refined grid (absolute t values vary per row, so pass
        # per-row offsets through the coordinate arrays instead)
        fine = np.zeros(rows.size)
        f_inside = phi_f < 0.0
        fl, fr = f_inside[:, :-1], f_inside[:, 1:]
        fdt = dt / REFINE
        fine += (fl & fr).sum(axis=1) * fdt
        crows, ccols = np.nonzero(fl != fr)
        if crows.size:
            tstar = _bisect(
                sdf, x0[rows][crows], y0[rows][crows], ux, uy, seg_len,
                sub_t[crows, ccols], sub_t[crows, ccols + 1],
                fl[crows, ccols],
            )
            piece = np.where(
                fl[crows, ccols],
                tstar - sub_t[crows, ccols],
                sub_t[crows, ccols + 1] - tstar,
            )
            np.add.at(fine, crows, piece)
        contrib[rows, cols] = fine
    return contrib.sum(axis=1) * seg_len


def _chunked(fn, x0, y0, samples):
    """Apply a per-face sweep in host-memory-bounded chunks."""
    n = x0.size
    step = max(1, _CHUNK_EVALS // (samples + 1))
    if n <= step:
        return fn(x0, y0)
    out = np.empty(n)
    for lo in range(0, n, step):
        hi = min(lo + step, n)
        out[lo:hi] = fn(x0[lo:hi], y0[lo:hi])
    return out


def segment_lengths(problem: Problem, sdf,
                    samples: int = DEFAULT_SAMPLES):
    """(la, lb) float64 (M+1, N+1): the quadrature twin of the ellipse
    closed forms, for any SDF.

    ``la[i, j]`` is the length of the vertical face x = x_i − h1/2,
    y ∈ [y_j − h2/2, y_j + h2/2] inside D; ``lb[i, j]`` the horizontal
    face's — exactly the face layout ``ops.assembly`` blends
    (``stage0/Withoutopenmp1.cpp:49-54``). The whole node grid is
    evaluated; the caller masks the valid range, as the closed-form path
    does.
    """
    M, N = problem.M, problem.N
    h1, h2 = problem.h1, problem.h2
    gi = np.arange(M + 1, dtype=np.float64)
    gj = np.arange(N + 1, dtype=np.float64)
    x = problem.a1 + gi * h1
    y = problem.a2 + gj * h2

    shape = (M + 1, N + 1)
    # vertical faces: start at (x_i − h1/2, y_j − h2/2), run along +y
    xv = np.broadcast_to((x - 0.5 * h1)[:, None], shape).ravel()
    yv = np.broadcast_to((y - 0.5 * h2)[None, :], shape).ravel()
    la = _chunked(
        lambda a, b: _lengths_along(sdf, a, b, 0.0, 1.0, h2, samples),
        xv, yv, samples,
    ).reshape(shape)
    # horizontal faces: start at (x_i − h1/2, y_j − h2/2), run along +x
    lb = _chunked(
        lambda a, b: _lengths_along(sdf, a, b, 1.0, 0.0, h1, samples),
        xv, yv, samples,
    ).reshape(shape)
    return la, lb


def clamp_lengths(lengths: np.ndarray, h: float, theta: float):
    """The degenerate-cut defense: snap fractions in (0, θ) to empty and
    (1−θ, 1) to full. Returns ``(clamped, n_to_empty, n_to_full)`` so
    the caller can *report* every clamp (``geom:degenerate-cut``);
    ``theta=0`` disables (and reports zero)."""
    frac = lengths / h
    to_empty = (frac > 0.0) & (frac < theta)
    to_full = (frac < 1.0) & (frac > 1.0 - theta)
    clamped = np.where(to_empty, 0.0, np.where(to_full, h, lengths))
    return clamped, int(to_empty.sum()), int(to_full.sum())
