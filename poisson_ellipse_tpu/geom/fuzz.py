"""Seeded property-based geometry fuzzing: the gate earns its keep.

"Handles arbitrary geometry" is unfalsifiable until something hostile
is thrown at it. This harness generates random SDF compositions (and
deliberately malformed specs) from one seed and checks *metamorphic*
invariants — properties that must hold for ANY admissible domain, no
oracle required:

- **classification totality** — every generated case either passes the
  admissibility gate or raises the classified ``InvalidGeometryError``;
  nothing escapes as a raw exception, nothing hangs.
- **discrete maximum principle** — for f ≥ 0 and an M-matrix operator,
  the solution satisfies u ≥ 0 (to round-off). The gate's M-matrix
  check is exactly what makes this theorem apply; fuzzing closes the
  loop by testing the theorem's conclusion.
- **reflection symmetry** — a domain symmetric under x → −x on the
  symmetric grid must produce a solution symmetric to round-off.
- **refinement convergence** — halving h must move the solution toward
  a limit: ‖u_h − u_{h/2}‖ is small and shrinks.
- **guard recoverability** — with validation *bypassed* (the belt-and-
  suspenders drill), an inadmissible operator handed to
  ``resilience.guard`` must end in a classified ``SolveError`` or a
  finite result — never an unclassified crash, never a NaN returned as
  converged.

Deterministic in ``seed``: a failing case number is a reproducible bug
report, not an anecdote (the ``serve.chaos`` stance, applied to
geometry). CLI: ``python -m poisson_ellipse_tpu.geom.fuzz --cases 30``.
"""

from __future__ import annotations

import functools
import random

import jax
import jax.numpy as jnp
import numpy as np

from poisson_ellipse_tpu.geom import sdf as geom_sdf
from poisson_ellipse_tpu.geom import validate as geom_validate
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.resilience.errors import (
    InvalidGeometryError,
    SolveError,
)

DEFAULT_GRID = (12, 12)
DEFAULT_CASES = 30


def random_shape(rng: random.Random, depth: int = 0, symmetric: bool = False):
    """One random SDF tree, sized to sit inside Ω with clearance from
    the Dirichlet ring (so most cases are admissible and the rejections
    exercised are the *interesting* ones: slivers, empty intersections,
    under-resolved spikes). ``symmetric=True`` restricts to shapes even
    under x → −x (the reflection-invariant corpus)."""
    cx = 0.0 if symmetric else rng.uniform(-0.25, 0.25)
    cy = rng.uniform(-0.1, 0.1)
    kind = rng.randrange(7 if depth < 2 else 4)
    if kind == 0:
        return geom_sdf.Ellipse(
            cx=cx, cy=cy,
            rx=rng.uniform(0.3, 0.65), ry=rng.uniform(0.15, 0.3),
        )
    if kind == 1:
        return geom_sdf.Circle(cx=cx, cy=cy, r=rng.uniform(0.15, 0.3))
    if kind == 2:
        hw = rng.uniform(0.2, 0.6)
        hh = rng.uniform(0.12, 0.3)
        return geom_sdf.Rectangle(
            x0=cx - hw, y0=cy - hh, x1=cx + hw, y1=cy + hh
        )
    if kind == 3:
        base = geom_sdf.Ellipse(
            cx=cx, cy=cy,
            rx=rng.uniform(0.4, 0.7), ry=rng.uniform(0.18, 0.3),
        )
        hole = geom_sdf.Circle(
            cx=cx, cy=cy, r=rng.uniform(0.05, 0.12)
        )
        return geom_sdf.Difference(base, hole)
    if kind == 4:
        a = random_shape(rng, depth + 1, symmetric)
        b = random_shape(rng, depth + 1, symmetric)
        return geom_sdf.Union(a, b)
    if kind == 5:
        a = random_shape(rng, depth + 1, symmetric)
        b = random_shape(rng, depth + 1, symmetric)
        return geom_sdf.Intersection(a, b)
    return geom_sdf.Translate(
        random_shape(rng, depth + 1, symmetric),
        dx=0.0 if symmetric else rng.uniform(-0.15, 0.15),
        dy=rng.uniform(-0.08, 0.08),
    )


def malformed_spec(rng: random.Random) -> dict:
    """One deliberately broken JSON spec (the admission fuzz corpus —
    every one must be rejected as ``malformed-spec``)."""
    choice = rng.randrange(6)
    if choice == 0:
        return {"kind": "dodecahedron"}
    if choice == 1:
        return {"kind": "circle", "r": -0.2}
    if choice == 2:
        return {"kind": "ellipse", "rx": float("nan")}
    if choice == 3:
        return {"kind": "union", "shapes": []}
    if choice == 4:
        return {"kind": "rectangle", "x0": 0.5, "x1": -0.5}
    spec: dict = {"kind": "translate", "dx": 0.0, "dy": 0.0}
    leaf = spec
    for _ in range(geom_sdf.MAX_SPEC_DEPTH + 2):
        leaf["shape"] = {"kind": "translate", "dx": 0.0, "dy": 0.0}
        leaf = leaf["shape"]
    leaf["shape"] = {"kind": "circle"}
    return spec


def inadmissible_shape(rng: random.Random):
    """One structurally valid but *inadmissible* shape (empty, escaping
    Ω, or thinner than any grid) — the gate-rejection corpus."""
    choice = rng.randrange(3)
    if choice == 0:  # disjoint intersection -> empty
        return geom_sdf.Intersection(
            geom_sdf.Circle(cx=-0.5, cy=0.0, r=0.15),
            geom_sdf.Circle(cx=0.5, cy=0.0, r=0.15),
        )
    if choice == 1:  # pokes through the Dirichlet ring
        return geom_sdf.Circle(cx=0.9, cy=0.0, r=0.3)
    # a hair: thinner than h on any tier-1 grid
    return geom_sdf.Rectangle(x0=-0.5, y0=1e-4, x1=0.5, y1=2.1e-4)


# no donation: the refinement check re-feeds the same operands, and the
# fuzz sweep's grids are tiny
@functools.partial(jax.jit, static_argnums=0)  # tpulint: disable=TPU004
def _solve_operands(problem: Problem, a, b, rhs):
    # one compile per (problem, shape/dtype) across the whole fuzz run —
    # the jit cache keys on the static problem + operand shapes
    from poisson_ellipse_tpu.solver.pcg import pcg

    return pcg(problem, a, b, rhs)


def _solve(problem: Problem, shape, theta=None):
    # the metamorphic invariants are f64 statements (x64 is on in every
    # harness that runs the fuzz — conftest, the CLI's default CPU run)
    a, b, rhs = assembly.assemble(
        # tpulint: disable=TPU001 — f64-on-purpose, see above
        problem, jnp.float64, geometry=shape, theta=theta
    )
    return _solve_operands(problem, a, b, rhs)


def check_solution_invariants(problem: Problem, shape, theta=None,
                              symmetric: bool = False) -> dict:
    """Solve one admissible case and assert the metamorphic properties
    (maximum principle; reflection symmetry when claimed)."""
    result = _solve(problem, shape, theta)
    w = np.asarray(result.w)
    if not bool(result.converged):
        raise AssertionError(
            f"admissible domain did not converge in {int(result.iters)} "
            "iterations"
        )
    floor = float(w.min())
    if floor < -1e-8:
        raise AssertionError(
            f"discrete maximum principle violated: min u = {floor:g} < 0 "
            "for f >= 0 on an M-matrix operator"
        )
    out = {"iters": int(result.iters), "min_u": floor,
           "max_u": float(w.max())}
    if symmetric:
        asym = float(np.abs(w - w[::-1, :]).max())
        scale = max(float(np.abs(w).max()), 1e-30)
        if asym > 1e-8 * scale:
            raise AssertionError(
                f"reflection symmetry violated: max |u - u_mirror| = "
                f"{asym:g} on a symmetric domain"
            )
        out["mirror_defect"] = asym
    return out


def check_refinement(problem: Problem, shape, theta=None) -> dict:
    """‖u_h − u_{h/2}‖ must be small and shrink under refinement."""
    coarse = np.asarray(_solve(problem, shape, theta).w)
    fine_p = Problem(
        M=2 * problem.M, N=2 * problem.N, delta=problem.delta,
        norm=problem.norm,
    )
    fine = np.asarray(_solve(fine_p, shape, theta).w)
    scale = max(float(np.abs(fine).max()), 1e-30)
    d1 = float(np.abs(fine[::2, ::2] - coarse).max()) / scale
    if d1 > 0.5:
        raise AssertionError(
            f"refinement divergence: relative coarse-vs-fine gap {d1:g}"
        )
    return {"rel_gap": d1}


def check_guard_recoverability(problem: Problem, shape) -> str:
    """Bypass the gate and hand the (inadmissible) operator to the
    guard: the outcome must be a classified SolveError or a finite
    result — the drill for a validation layer that was skipped."""
    from poisson_ellipse_tpu.resilience.guard import guarded_solve

    try:
        guarded = guarded_solve(
            # tpulint: disable=TPU001 — f64-on-purpose (see _solve)
            problem, "xla", jnp.float64, geometry=shape,
            validate_geometry=False,
        )
    except SolveError as e:
        return f"classified:{e.classification}"
    w = np.asarray(guarded.result.w)
    if bool(guarded.result.converged) and not np.isfinite(w).all():
        raise AssertionError(
            "guard returned a non-finite iterate as converged"
        )
    return "finite-result"


def check_param_roundtrip(shape) -> int:
    """The spec↔pytree round-trip invariant of the diff/ surface: the
    parameter vector read out of a shape tree (``params_of``) rebuilds
    THE SAME tree (``with_params``) — spec-equal after a JSON wire
    round trip, so an optimizer step re-serialises without drift — and
    perturbed parameters still produce a valid, re-parseable JSON spec.
    Returns the parameter count."""
    import json as _json

    params = geom_sdf.params_of(shape)
    if params.shape != (geom_sdf.n_params(shape),):
        raise AssertionError(
            f"params_of length {params.shape} != n_params "
            f"{geom_sdf.n_params(shape)}"
        )
    rebuilt = geom_sdf.with_params(shape, params)
    spec0 = _json.dumps(geom_sdf.to_spec(shape), sort_keys=True)
    spec1 = _json.dumps(geom_sdf.to_spec(rebuilt), sort_keys=True)
    if spec0 != spec1:
        raise AssertionError(
            f"params round trip drifted:\n  {spec0}\n  {spec1}"
        )
    # a perturbed vector must still serialise to RFC JSON and re-parse
    # through the gate's first rung (from_spec) without structural loss
    bumped = geom_sdf.with_params(shape, params + 1e-3)
    wire = _json.loads(_json.dumps(geom_sdf.to_spec(bumped)))
    reparsed = geom_sdf.from_spec(wire)
    if not (geom_sdf.params_of(reparsed) == geom_sdf.params_of(bumped)).all():
        raise AssertionError("perturbed spec re-parse lost parameters")
    return int(params.size)


def run_fuzz(n_cases: int = DEFAULT_CASES, seed: int = 0,
             grid: tuple[int, int] = DEFAULT_GRID,
             solve_budget: int = 4) -> dict:
    """The full seeded sweep; returns a JSON-able report and raises
    AssertionError on the first violated invariant.

    Case mix per 6: one malformed spec, one inadmissible shape, four
    random shapes (one forced symmetric). Solves are bounded by
    ``solve_budget`` admissible cases (+1 refinement pair, +1 guard
    drill) so the sweep stays tier-1-sized; classification runs on
    every case.
    """
    rng = random.Random(seed)
    problem = Problem(M=grid[0], N=grid[1])
    report: dict = {
        "seed": seed, "cases": n_cases, "grid": list(grid),
        "accepted": 0, "rejected": {}, "solved": 0, "roundtrips": 0,
        "details": [],
    }
    solves_left = solve_budget
    refinement_done = False
    guard_done = False
    for i in range(n_cases):
        slot = i % 6
        entry: dict = {"case": i}
        if slot == 0:
            spec = malformed_spec(rng)
            try:
                geom_validate.validate(problem, spec)
            except InvalidGeometryError as e:
                entry["outcome"] = f"rejected:{e.reason}"
                if e.reason != "malformed-spec":
                    raise AssertionError(
                        f"case {i}: malformed spec classified {e.reason}, "
                        "expected malformed-spec"
                    )
                report["rejected"][e.reason] = (
                    report["rejected"].get(e.reason, 0) + 1
                )
            else:
                raise AssertionError(
                    f"case {i}: malformed spec passed the gate: {spec}"
                )
            report["details"].append(entry)
            continue
        symmetric = slot == 2
        shape = (
            inadmissible_shape(rng) if slot == 1
            else random_shape(rng, symmetric=symmetric)
        )
        entry["spec"] = geom_sdf.to_spec(shape)
        # every structurally-valid tree must survive the diff/ surface's
        # spec↔pytree round trip (params_of/with_params), admissible or
        # not — inadmissibility is a domain fact, not a wire-form one
        entry["n_params"] = check_param_roundtrip(shape)
        report["roundtrips"] += 1
        try:
            geom_validate.validate(problem, shape)
        except InvalidGeometryError as e:
            entry["outcome"] = f"rejected:{e.reason}"
            report["rejected"][e.reason] = (
                report["rejected"].get(e.reason, 0) + 1
            )
            if slot == 1 and not guard_done and solve_budget > 0:
                entry["guard"] = check_guard_recoverability(problem, shape)
                guard_done = True
        else:
            report["accepted"] += 1
            entry["outcome"] = "accepted"
            if slot == 1:
                raise AssertionError(
                    f"case {i}: inadmissible shape passed the gate: "
                    f"{entry['spec']}"
                )
            if solves_left > 0:
                entry.update(check_solution_invariants(
                    problem, shape, symmetric=symmetric
                ))
                report["solved"] += 1
                solves_left -= 1
                if not refinement_done:
                    entry["refinement"] = check_refinement(problem, shape)
                    refinement_done = True
        report["details"].append(entry)
    return report


def main(argv=None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(
        prog="python -m poisson_ellipse_tpu.geom.fuzz",
        description="Seeded geometry fuzzing: random SDF compositions "
        "through the admissibility gate + metamorphic solve invariants "
        "(maximum principle, reflection symmetry, refinement "
        "convergence, guard recoverability). Exit 0 iff every invariant "
        "holds.",
    )
    ap.add_argument("--cases", type=int, default=DEFAULT_CASES)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--grid", default="12x12", help="MxN fuzz grid")
    ap.add_argument("--solve-budget", type=int, default=4)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    # the metamorphic tolerances are f64 statements (see _solve); the
    # standalone CLI must flip x64 itself — pytest gets it from conftest
    jax.config.update("jax_enable_x64", True)
    M, _, N = args.grid.partition("x")
    try:
        report = run_fuzz(
            n_cases=args.cases, seed=args.seed,
            grid=(int(M), int(N or M)), solve_budget=args.solve_budget,
        )
    except AssertionError as e:
        print(f"FUZZ FAILURE: {e}")
        return 1
    if args.json:
        print(json.dumps(report))
    else:
        print(
            f"fuzz: {report['cases']} cases, {report['accepted']} "
            f"accepted ({report['solved']} solved, all invariants held), "
            f"rejections: {report['rejected']}"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
