"""Arbitrary level-set geometry for the fictitious-domain assembly.

PRs 1–9 hardened every layer around a single hard-coded ellipse whose
face fractions come from a closed form (``models/ellipse.py``). This
package is the generality — and, inseparably, the defense layer the
generality makes necessary:

- :mod:`.sdf` — JSON-serializable signed-distance primitives (ellipse,
  circle, half-plane, rectangle) and boolean/translation composition,
  evaluated as broadcast array expressions with the same ``xp=`` module
  convention as ``models.ellipse`` (one geometry, host f64 AND traced).
- :mod:`.quadrature` — face fractions by adaptive 1-D bisection of the
  SDF sign change along each cell face, replacing the closed form for
  arbitrary domains (and matching it to ≤1e-12 relative for the
  ellipse), plus the **degenerate-cut defense**: fractions within θ of
  the full/empty endpoints are clamped, reported as
  ``geom:degenerate-cut`` trace events.
- :mod:`.validate` — the pre-solve admissibility gate: domain
  non-empty, resolved by the grid, clear of the Dirichlet ring, and an
  assembled operator that is finite, symmetric, M-matrix-signed and SPD
  (host Lanczos probe through ``obs.spectrum``) — failing with the
  classified :class:`~poisson_ellipse_tpu.resilience.errors.
  InvalidGeometryError` (exit 8) BEFORE any device loop runs.
- :mod:`.fuzz` — a seeded property-based harness generating random SDF
  compositions and checking metamorphic invariants (refinement
  convergence, discrete maximum principle, reflection symmetry,
  guard-recoverability when validation is bypassed).
"""

from poisson_ellipse_tpu.geom.sdf import (  # noqa: F401
    Circle,
    Difference,
    Ellipse,
    HalfPlane,
    Intersection,
    Rectangle,
    Translate,
    Union,
    from_spec,
    to_spec,
)
from poisson_ellipse_tpu.geom.quadrature import (  # noqa: F401
    DEFAULT_THETA,
    segment_lengths,
)
# the validate/fuzz modules stay addressable as submodules
# (``geom.validate.validate(...)``): re-exporting the function here
# would shadow the module attribute of the same name
from poisson_ellipse_tpu.geom import validate  # noqa: F401
