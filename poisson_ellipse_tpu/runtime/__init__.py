"""Native C++/OpenMP host runtime (reference stage0/stage1 parity).

``native.solve_native`` runs the full fictitious-domain PCG in C++ —
sequential with ``threads=1`` (stage0) or OpenMP-parallel (stage1) — and
serves as an independent host oracle for the JAX/TPU path.
"""

from poisson_ellipse_tpu.runtime.native import (
    NativeBuildError,
    NativeResult,
    assemble_native,
    native_available,
    num_threads,
    solve_native,
)

__all__ = [
    "NativeBuildError",
    "NativeResult",
    "assemble_native",
    "native_available",
    "num_threads",
    "solve_native",
]
