"""ctypes binding + on-demand build of the C++ runtime (pe_runtime.cpp).

The reference ships one Makefile for its CUDA stage only
(``stage4-mpi+cuda/Makefile``) and builds stage0/1 ad hoc; here the
native library is built on first use with g++ (-O3 -fopenmp, falling
back to no-OpenMP if unavailable) and cached next to the source. No
pybind11 in this environment — the C ABI + ctypes keeps the binding
dependency-free.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import NamedTuple, Optional

import numpy as np

from poisson_ellipse_tpu.models.problem import Problem

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "pe_runtime.cpp")
_LIB = os.path.join(_DIR, "libpe_runtime.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


class NativeBuildError(RuntimeError):
    """The C++ runtime could not be built or loaded (g++ missing, build
    failure) — an environment problem, distinct from solver errors."""


class NativeResult(NamedTuple):
    w: np.ndarray
    iters: int
    diff: float
    converged: bool
    breakdown: bool


def _build() -> Optional[str]:
    """Compile the shared library; returns an error string on failure.

    Compiles to a process-unique temp name and os.rename()s onto the
    final path: rename is atomic, so a concurrent process never dlopens
    a half-written library (the in-module lock is process-local only).
    """
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    # attempt order: drop -march=native (not every g++/arch accepts it)
    # and -fopenmp independently so losing one flag never costs the other
    attempts = (
        ["-march=native", "-fopenmp"],
        ["-fopenmp"],
        ["-march=native"],
        [],
    )
    for flags in attempts:
        cmd = [
            "g++",
            "-O3",
            "-std=c++17",
            "-shared",
            "-fPIC",
            *flags,
            _SRC,
            "-o",
            tmp,
        ]
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=120
            )
        except (OSError, subprocess.TimeoutExpired) as e:
            return f"g++ invocation failed: {e}"
        if proc.returncode == 0:
            os.replace(tmp, _LIB)
            return None
        err = proc.stderr
    return f"g++ failed:\n{err}"


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        if not os.path.exists(_LIB) or os.path.getmtime(
            _LIB
        ) < os.path.getmtime(_SRC):
            _build_error = _build()
            if _build_error is not None:
                return None
        lib = ctypes.CDLL(_LIB)
        d = ctypes.c_double
        dp = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
        lib.pe_solve.restype = ctypes.c_int
        lib.pe_solve.argtypes = [
            ctypes.c_int, ctypes.c_int, d, d, d, d, d, d, d,
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            dp, ctypes.POINTER(ctypes.c_int), ctypes.POINTER(d),
        ]
        lib.pe_assemble.restype = ctypes.c_int
        lib.pe_assemble.argtypes = [
            ctypes.c_int, ctypes.c_int, d, d, d, d, d, d, dp, dp, dp,
        ]
        lib.pe_num_threads.restype = ctypes.c_int
        lib.pe_num_threads.argtypes = []
        _lib = lib
        return _lib


def native_available() -> bool:
    """True if the C++ runtime could be built and loaded."""
    return _load() is not None


def build_error() -> Optional[str]:
    _load()
    return _build_error


def num_threads() -> int:
    lib = _load()
    if lib is None:
        raise NativeBuildError(f"native runtime unavailable: {_build_error}")
    return lib.pe_num_threads()


def solve_native(problem: Problem, threads: int = 0) -> NativeResult:
    """Full C++ PCG solve. threads=1 → stage0 analog; >1 → stage1 analog;
    0 → OpenMP default."""
    lib = _load()
    if lib is None:
        raise NativeBuildError(f"native runtime unavailable: {_build_error}")
    w = np.zeros(problem.node_shape, np.float64)
    iters = ctypes.c_int(0)
    diff = ctypes.c_double(0.0)
    status = lib.pe_solve(
        problem.M,
        problem.N,
        problem.a1,
        problem.b1,
        problem.a2,
        problem.b2,
        problem.f_val,
        problem.delta,
        -1.0 if problem.eps is None else problem.eps,
        -1 if problem.max_iter is None else problem.max_iter,
        1 if problem.norm == "weighted" else 0,
        threads,
        w.reshape(-1),
        ctypes.byref(iters),
        ctypes.byref(diff),
    )
    if status < 0:
        raise ValueError(f"pe_solve rejected arguments (status {status})")
    return NativeResult(
        w=w,
        iters=iters.value,
        diff=diff.value,
        converged=status == 0,
        breakdown=status == 2,
    )


def assemble_native(problem: Problem):
    """C++ assembly of (a, b, rhs) — golden cross-check for ops.assembly."""
    lib = _load()
    if lib is None:
        raise NativeBuildError(f"native runtime unavailable: {_build_error}")
    shape = problem.node_shape
    a = np.zeros(shape, np.float64)
    b = np.zeros(shape, np.float64)
    rhs = np.zeros(shape, np.float64)
    status = lib.pe_assemble(
        problem.M,
        problem.N,
        problem.a1,
        problem.b1,
        problem.a2,
        problem.b2,
        problem.f_val,
        -1.0 if problem.eps is None else problem.eps,
        a.reshape(-1),
        b.reshape(-1),
        rhs.reshape(-1),
    )
    if status != 0:
        raise ValueError(f"pe_assemble rejected arguments (status {status})")
    return a, b, rhs
