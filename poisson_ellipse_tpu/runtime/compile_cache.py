"""Compilation caching: every request shape hits a warm executable.

A serving stack dies on cold starts twice: once per *process* (XLA
recompiles everything a fresh worker ever traces) and once per *shape*
(a new (M, N, lanes) request retraces and recompiles even in a warm
worker). Two layers here, one per failure mode:

- **Persistent XLA compilation cache** (:func:`enable_persistent_cache`)
  — ``jax_compilation_cache_dir`` wiring with the min-compile-time gate
  dropped to zero, so every compiled solver (any engine) lands on disk
  and a restarted worker deserialises instead of recompiling. Ambient
  activation via ``POISSON_COMPILE_CACHE=DIR``.

- **In-process AOT warm pool** (:class:`WarmPool`) — bucketed
  ahead-of-time executables for the *batched* engines, keyed by
  ``(engine, grid-bucket, dtype, lane-bucket, norm)``. Request shapes
  are rounded up to the nearest bucket and **pad-and-mask embedded**:
  operands are zero-padded to the bucket's node grid, an interior mask
  pins every node outside the true problem to zero, and all
  size-dependent *numbers* (h1, h2, δ, the iteration cap) enter the
  executable as runtime scalars — so one ``jit(...).lower().compile()``
  per bucket serves every smaller request with **zero retrace, zero
  recompile** (the second request for a bucketed shape returns the same
  executable object; hit-count asserted in ``tests/test_batched.py``).
  Lane counts round up to powers of two; surplus lanes carry a zero RHS
  and exit on the breakdown guard after one iteration, then are cropped
  from the result.

  Embedding note: the masked arithmetic adds only ``×1``/``+0`` on the
  true interior and exact zeros outside, but XLA's reduction tiling
  over the *bucket* shape may group partial sums differently from the
  exact-shape solve — bucketed results are value-equivalent within the
  usual reordering ulps (the pallas-vs-xla contract), not bitwise, and
  iteration counts may differ by a step on ill-conditioned grids.

Every pool lookup emits a ``cache:hit`` / ``cache:miss`` trace event and
bumps the ``compile_cache_hits`` / ``compile_cache_misses`` counters
(``obs``), so serving dashboards see cold-start behaviour directly.
``python -m poisson_ellipse_tpu.harness warmup`` pre-fills both layers.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs import metrics as obs_metrics
from poisson_ellipse_tpu.obs import trace as obs_trace

ENV_CACHE_DIR = "POISSON_COMPILE_CACHE"
DEFAULT_CACHE_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "poisson_ellipse_tpu", "xla"
)

_persistent_dir: str | None = None


def enable_persistent_cache(path: str | None = None) -> str:
    """Point XLA's persistent compilation cache at ``path`` (default:
    ``$POISSON_COMPILE_CACHE`` or ``~/.cache/poisson_ellipse_tpu/xla``).

    Drops the min-compile-time gate to zero so even millisecond compiles
    persist — the solver zoo is many small computations, and a restarted
    serving worker wants all of them back. Idempotent; returns the
    directory in use.
    """
    global _persistent_dir
    path = path or os.environ.get(ENV_CACHE_DIR) or DEFAULT_CACHE_DIR
    if _persistent_dir == path:
        return path
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    try:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    except AttributeError:  # older jax spells it differently / lacks it
        pass
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except AttributeError:
        pass
    _persistent_dir = path
    obs_trace.event("cache:persistent-enabled", dir=path)
    return path


# -- shape bucketing ---------------------------------------------------------

# grid-dimension ladder: powers of two and their 1.5× midpoints — at
# most 2 buckets per octave bounds pad waste at ≤ 50% per dim while
# keeping the executable population logarithmic in served sizes
_MAX_DIM = 1 << 20


def _ladder():
    k = 3
    while (1 << k) <= _MAX_DIM:
        yield 1 << k
        yield 3 << (k - 1)
        k += 1


def bucket_dim(n: int) -> int:
    """Smallest ladder value ≥ n (cells per grid dimension)."""
    if n < 2:
        raise ValueError("need at least 2 cells per dimension")
    for v in _ladder():
        if v >= n:
            return v
    raise ValueError(f"dimension {n} exceeds the bucket ladder")


def grid_bucket(M: int, N: int) -> tuple[int, int]:
    """The (Mb, Nb) cell-count bucket an (M, N) request embeds into."""
    return bucket_dim(M), bucket_dim(N)


def lane_bucket(lanes: int) -> int:
    """Smallest power of two ≥ lanes."""
    if lanes < 1:
        raise ValueError("lanes must be >= 1")
    return 1 << (lanes - 1).bit_length()


def warm_affinity_key(M: int, N: int, norm: str = "weighted") -> tuple:
    """The compile-bucket affinity key a request of grid (M, N) lands
    in: ``(grid_bucket, norm)`` — exactly the key the serve scheduler's
    batch contexts (``serve.scheduler._ctxs``) and this pool's bucketed
    executables share. The fleet router (``fleet.router``) routes by it:
    a request sent to a replica already holding this key's live batch
    context runs on an executable that is ALREADY warm — zero retrace,
    zero recompile, no cold-start tax on the unlucky replica."""
    return (grid_bucket(M, N), norm)


# -- the AOT warm pool -------------------------------------------------------


@dataclass
class _Entry:
    """One bucketed executable plus the bucket geometry it serves."""

    compiled: object
    engine: str
    bucket: tuple[int, int]
    lanes: int
    dtype: str
    norm: str
    compile_s: float
    # HBM storage width of the lane fields ("" = storage == compute).
    # A storage component in the cache key is load-bearing: a bf16-
    # storage executable and a full-width one trace DIFFERENT programs
    # for the same shapes, and serving one for the other would silently
    # change the accuracy contract of every request in the bucket.
    storage: str = ""


@dataclass
class WarmPool:
    """AOT executables for the batched engines, keyed by bucket.

    One pool per process is the intended shape (:func:`warm_pool`); the
    class is separate so tests can build throwaway pools.
    """

    entries: dict = field(default_factory=dict)
    hits: int = 0
    misses: int = 0

    @staticmethod
    def key(engine: str, grid: tuple[int, int], dtype, lanes: int,
            norm: str = "weighted", storage_dtype=None):
        # the storage-dtype component ("" when storage == compute): a
        # narrow-storage executable is a DIFFERENT traced program with a
        # different accuracy contract — it must never be served for a
        # full-width request (or vice versa)
        from poisson_ellipse_tpu.ops.precision import resolve_storage_dtype

        st = resolve_storage_dtype(storage_dtype, dtype)
        storage = "" if st is None else jnp.dtype(st).name
        return (
            engine, grid_bucket(*grid), jnp.dtype(dtype).name,
            lane_bucket(lanes), norm, storage,
        )

    def warmup(self, engine: str, grid: tuple[int, int], dtype=jnp.float32,
               lanes: int = 1, norm: str = "weighted",
               storage_dtype=None) -> _Entry:
        """The bucket executable for (engine, grid, dtype, lanes, norm,
        storage), AOT-compiling on miss — the pool's single (and
        deliberate) ``lower().compile()`` site.

        Emits ``cache:hit``/``cache:miss`` and bumps the obs counters;
        a hit returns the *same executable object* as the miss that
        created it (asserted in tests — the no-recompile contract).

        ``engine="auto"`` resolves to ``batched`` — the only lane
        engine with retire-and-refill + storage support. The tuned-
        config consult on the serving path lives at the scheduler's
        batch contexts (``Scheduler._ctx_for`` applies the registry's
        per-shape chunk at warm-pool admission); the tuner never
        scores lane engines, so there is no per-shape lane-engine
        choice to consult here.
        """
        if engine == "auto":
            engine = "batched"
        key = self.key(engine, grid, dtype, lanes, norm, storage_dtype)
        entry = self.entries.get(key)
        _, bucket, dtype_name, lb, _, storage = key
        if entry is not None:
            self.hits += 1
            obs_metrics.counter("compile_cache_hits").inc()
            obs_trace.event(
                "cache:hit", engine=engine, bucket=list(bucket),
                lanes=lb, dtype=dtype_name,
            )
            return entry
        self.misses += 1
        obs_metrics.counter("compile_cache_misses").inc()
        t0 = time.perf_counter()
        compiled = _compile_bucket(engine, bucket, dtype, lb, norm,
                                   storage_dtype=storage_dtype)
        compile_s = time.perf_counter() - t0
        obs_trace.event(
            "cache:miss", engine=engine, bucket=list(bucket), lanes=lb,
            dtype=dtype_name, compile_s=round(compile_s, 4),
        )
        entry = _Entry(
            compiled=compiled, engine=engine, bucket=bucket, lanes=lb,
            dtype=dtype_name, norm=norm, compile_s=compile_s,
            storage=storage,
        )
        self.entries[key] = entry
        return entry

    def solve(self, problem: Problem, lanes: int, engine: str = "batched",
              dtype=jnp.float32, rhs=None):
        """Serve one request from the pool: embed, dispatch, crop.

        ``rhs`` optionally supplies the (lanes, M+1, N+1) stack (default:
        the problem's RHS tiled). Returns a per-lane
        :class:`~poisson_ellipse_tpu.batch.BatchedPCGResult` cropped to
        the request's true shape and lane count.
        """
        from poisson_ellipse_tpu.batch.batched_pcg import BatchedPCGResult

        entry = self.warmup(
            engine, (problem.M, problem.N), dtype, lanes, problem.norm
        )
        args = _embed(problem, lanes, entry, dtype, rhs)
        out = entry.compiled(*args)
        result = BatchedPCGResult(*out)
        g1, g2 = problem.M + 1, problem.N + 1
        return BatchedPCGResult(
            w=result.w[:lanes, :g1, :g2],
            iters=result.iters[:lanes],
            diff=result.diff[:lanes],
            converged=result.converged[:lanes],
            breakdown=result.breakdown[:lanes],
            quarantined=result.quarantined[:lanes],
        )


def _compile_bucket(engine: str, bucket: tuple[int, int], dtype, lanes: int,
                    norm: str, storage_dtype=None):
    """AOT-compile one bucket-generic batched solver.

    The traced function takes every size-dependent number (h1, h2, δ,
    iteration cap) as a runtime scalar and the interior mask as a
    runtime array, so the compiled executable is reusable for every
    (M ≤ Mb, N ≤ Nb, lanes ≤ Lb) request — shapes are the only
    compile-time facts.
    """
    from poisson_ellipse_tpu.batch import batched_pcg, batched_pipelined

    if engine == "batched":
        mod = batched_pcg
    elif engine == "batched-pipelined":
        mod = batched_pipelined
    else:
        raise ValueError(
            f"the warm pool serves the batched engines; got {engine!r}"
        )
    if storage_dtype is not None and engine != "batched":
        raise ValueError(
            "narrow-storage bucket executables cover the 'batched' "
            f"engine; got {engine!r}"
        )
    Mb, Nb = bucket
    proto = Problem(M=Mb, N=Nb, norm=norm)

    def run(a, b, rhs, mask, h1, h2, delta, limit):
        kw = (
            {"storage_dtype": storage_dtype}
            if storage_dtype is not None else {}
        )
        state = mod.init_state(proto, a, b, rhs, mask=mask, h1=h1, h2=h2,
                               **kw)
        state = mod.advance(
            proto, a, b, rhs, state, limit=limit, mask=mask, h1=h1, h2=h2,
            delta=delta, **kw,
        )
        return tuple(mod.result_of(state))

    shape2 = jax.ShapeDtypeStruct((Mb + 1, Nb + 1), jnp.dtype(dtype))
    shape3 = jax.ShapeDtypeStruct((lanes, Mb + 1, Nb + 1), jnp.dtype(dtype))
    scalar = jax.ShapeDtypeStruct((), jnp.dtype(dtype))
    # the deliberate AOT site (tpulint TPU010's aot-warmup-fns carve-out
    # names this function's callers): compile NOW, off the request path
    return jax.jit(run).lower(  # tpulint: disable=TPU004
        shape2, shape2, shape3, shape2, scalar, scalar, scalar,
        jax.ShapeDtypeStruct((), jnp.int32),
    ).compile()


def _embed(problem: Problem, lanes: int, entry: _Entry, dtype, rhs=None):
    """Pad-and-mask a request into ``entry``'s bucket: zero-padded
    operands, interior mask over the true problem, surplus lanes zero
    (they exit on the breakdown guard at iteration 1 and are cropped)."""
    from poisson_ellipse_tpu.ops import assembly

    Mb, Nb = entry.bucket
    Lb = entry.lanes
    np_dtype = assembly.numpy_dtype(dtype)
    a, b, r = assembly.assemble_numpy(problem)
    g1, g2 = problem.M + 1, problem.N + 1
    pad2 = ((0, Mb + 1 - g1), (0, Nb + 1 - g2))
    a_p = np.pad(a, pad2).astype(np_dtype)
    b_p = np.pad(b, pad2).astype(np_dtype)
    if rhs is None:
        rhs_p = np.broadcast_to(np.pad(r, pad2), (Lb, Mb + 1, Nb + 1))
        rhs_p = rhs_p.astype(np_dtype)
    else:
        rhs = np.asarray(rhs)
        if rhs.shape != (lanes, g1, g2):
            raise ValueError(
                f"rhs shape {rhs.shape} != {(lanes, g1, g2)}"
            )
        rhs_p = np.zeros((Lb, Mb + 1, Nb + 1), np_dtype)
        rhs_p[:lanes, :g1, :g2] = rhs
    mask = np.zeros((Mb + 1, Nb + 1), np_dtype)
    mask[1 : problem.M, 1 : problem.N] = 1.0
    return (
        jnp.asarray(a_p), jnp.asarray(b_p), jnp.asarray(rhs_p),
        jnp.asarray(mask),
        jnp.asarray(problem.h1, dtype), jnp.asarray(problem.h2, dtype),
        jnp.asarray(problem.delta, dtype),
        jnp.asarray(problem.max_iterations, jnp.int32),
    )


# -- the process-wide pool ---------------------------------------------------

_POOL: Optional[WarmPool] = None


def warm_pool() -> WarmPool:
    """The process's shared warm pool (created on first use)."""
    global _POOL
    if _POOL is None:
        _POOL = WarmPool()
    return _POOL
