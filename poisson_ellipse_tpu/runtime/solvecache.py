"""Semantic solve cache: warm starts for the request mix that repeats.

The fleet's traffic is correlated — the same geometry family, grid
bucket and ε recur — and ``runtime.autotune`` already buckets exactly
that recurrence for *executables*. This module applies the same key to
*solutions*: a bounded map from :func:`runtime.autotune.tune_key`-style
shape keys to recent (RHS sketch, solution) pairs, consulted at
admission for a nearest-neighbour warm start ``x0``.

Two design facts carry the whole correctness story:

- **A hit is a hint, never an answer.** The solver's init verifies any
  ``x0`` by TRUE residual (``solver.pcg.init_state``: r = rhs − A·x0),
  so the worst a wrong cache entry can do is cost iterations —
  ``solver.recycle.check_warm_start`` measures the hit's residual ratio
  at admission and flags ``recycle:bad-hit`` when it is worse than
  cold. Correctness never depends on cache state, which is also what
  keeps the serve journal replayable (replays run cold; outcomes are
  journaled, cache contents never are).
- **The sketch is deterministic and seeded.** Nearest-neighbour needs a
  cheap distance between full-grid RHS fields; :func:`rhs_sketch`
  samples a seed-fixed index set plus two global moments, so the same
  RHS sketches identically in every process and the cache's decisions
  replay bit-for-bit from its inputs.

The map itself is bounded on BOTH axes (keys via LRU eviction, entries
per key via a ring) — the tpulint TPU022 ``unbounded-cache`` discipline
this module exists to exemplify, not just pass.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.runtime.autotune import tune_key

# sketch size: enough samples that distinct bench RHS families separate
# by orders of magnitude, small enough that a lookup is microseconds
SKETCH_DIM = 32
SKETCH_SEED = 0

# a hit farther than this (relative sketch distance) is declined: an
# unrelated RHS warm start is pure wasted iterations, and the distance
# is the only cheap signal admission has
MAX_DISTANCE = 0.5

# bounded on both axes — see module docstring
DEFAULT_KEYS = 16
DEFAULT_PER_KEY = 4


def solve_key(problem: Problem, dtype=jnp.float32, storage_dtype=None,
              geometry=None) -> str:
    """The cache key — ``runtime.autotune.tune_key`` verbatim: (grid
    bucket, geometry fingerprint, dtype, storage dtype, norm). A
    solution is only ever offered to a solve whose operator matches the
    one that produced it; the RHS axis is the sketch's job."""
    return tune_key(problem, dtype, storage_dtype=storage_dtype,
                    geometry=geometry)


def rhs_sketch(rhs, dim: int = SKETCH_DIM, seed: int = SKETCH_SEED,
               ) -> np.ndarray:
    """The deterministic RHS fingerprint: ``dim`` seed-fixed point
    samples plus the field's (mean, RMS) moments, as float64.

    The index set depends only on (shape, dim, seed) — the same RHS
    sketches identically across processes and replays — and the two
    moments catch what sparse sampling can miss (a global rescale, a
    sign flip). Moments are per-node (mean/RMS, not sum/norm) so they
    sit on the same scale as the point samples and can't compress the
    distance between unrelated fields that merely share a norm.
    Distances between sketches track relative RHS distance well enough
    to rank cache entries; admission never *trusts* the ranking (the
    true-residual check is downstream).
    """
    flat = np.asarray(rhs, dtype=np.float64).ravel()
    if flat.size == 0:
        return np.zeros(int(dim) + 2)
    rng = np.random.default_rng(
        np.random.SeedSequence([int(seed), flat.size])
    )
    idx = rng.choice(flat.size, size=min(int(dim), flat.size),
                     replace=False)
    samples = flat[idx]
    if samples.size < dim:
        samples = np.pad(samples, (0, int(dim) - samples.size))
    return np.concatenate([
        samples, [flat.mean(), np.sqrt(np.mean(flat * flat))]
    ])


def sketch_distance(s1: np.ndarray, s2: np.ndarray) -> float:
    """Relative distance between two sketches (0 = identical): the
    Euclidean gap over the larger magnitude, so the same-family check
    is scale-free."""
    n1 = float(np.linalg.norm(s1))
    n2 = float(np.linalg.norm(s2))
    denom = max(n1, n2)
    if denom == 0.0:
        return 0.0
    return float(np.linalg.norm(s1 - s2)) / denom


class CacheEntry(NamedTuple):
    """One cached solution: the sketch it answers to and the solution
    field offered as ``x0`` (held as the device/host array the caller
    stored — the cache never copies a full grid)."""

    sketch: np.ndarray
    x0: object
    iters: int | None


class CacheStats(NamedTuple):
    hits: int
    misses: int
    declined: int  # nearest neighbour existed but was too far
    evicted: int
    keys: int
    entries: int


class SolveCache:
    """Bounded per-shape solution cache with nearest-neighbour lookup.

    ``max_keys`` shape keys (LRU-evicted), ``per_key`` entries per key
    (oldest-evicted ring) — both hard bounds, so a serving process's
    memory is capped at ``max_keys × per_key`` grids no matter what the
    traffic does. Host-side and unlocked by design: every consumer owns
    its instance (the scheduler's batch contexts hold one per bucket),
    so there is no cross-thread sharing to lock against.
    """

    def __init__(self, max_keys: int = DEFAULT_KEYS,
                 per_key: int = DEFAULT_PER_KEY,
                 max_distance: float = MAX_DISTANCE,
                 sketch_dim: int = SKETCH_DIM,
                 sketch_seed: int = SKETCH_SEED):
        if max_keys < 1 or per_key < 1:
            raise ValueError("cache bounds must be >= 1")
        self.max_keys = int(max_keys)
        self.per_key = int(per_key)
        self.max_distance = float(max_distance)
        self.sketch_dim = int(sketch_dim)
        self.sketch_seed = int(sketch_seed)
        # key -> list[CacheEntry]; bounded: LRU over keys (move_to_end +
        # popitem), oldest-out ring per key (del [0]) — the TPU022
        # eviction routes, load-bearing not decorative
        self._entries: OrderedDict[str, list[CacheEntry]] = OrderedDict()
        self._hits = 0
        self._misses = 0
        self._declined = 0
        self._evicted = 0

    def _sketch(self, rhs) -> np.ndarray:
        return rhs_sketch(rhs, dim=self.sketch_dim, seed=self.sketch_seed)

    def put(self, key: str, rhs, solution, iters: int | None = None
            ) -> None:
        """Store one solved (rhs, solution) under ``key``, evicting as
        the bounds require."""
        ring = self._entries.get(key)
        if ring is None:
            while len(self._entries) >= self.max_keys:
                self._entries.popitem(last=False)
                self._evicted += 1
            ring = []
            self._entries[key] = ring
        self._entries.move_to_end(key)
        ring.append(CacheEntry(
            sketch=self._sketch(rhs), x0=solution,
            iters=None if iters is None else int(iters),
        ))
        if len(ring) > self.per_key:
            del ring[0]
            self._evicted += 1

    def lookup(self, key: str, rhs):
        """The admission consult: ``(x0, distance)`` of the nearest
        cached neighbour under ``key``, or ``(None, None)`` on a miss
        (unknown key, or nearest too far — see ``max_distance``)."""
        ring = self._entries.get(key)
        if not ring:
            self._misses += 1
            return None, None
        self._entries.move_to_end(key)
        sketch = self._sketch(rhs)
        best = min(
            ring, key=lambda e: sketch_distance(sketch, e.sketch)
        )
        dist = sketch_distance(sketch, best.sketch)
        if dist > self.max_distance:
            self._declined += 1
            return None, dist
        self._hits += 1
        return best.x0, dist

    def drop(self, key: str) -> None:
        """Forget one shape's entries (a poisoned family, a retired
        bucket)."""
        self._entries.pop(key, None)

    def clear(self) -> None:
        """Forget everything — the mesh-degrade/rejoin path: a rebuilt
        fleet rebuilds its cache from live traffic, never from state
        that predates the event."""
        self._entries.clear()

    def stats(self) -> CacheStats:
        return CacheStats(
            hits=self._hits, misses=self._misses, declined=self._declined,
            evicted=self._evicted, keys=len(self._entries),
            entries=sum(len(r) for r in self._entries.values()),
        )

    def __len__(self) -> int:
        return sum(len(r) for r in self._entries.values())
