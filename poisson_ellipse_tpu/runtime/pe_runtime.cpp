// Native host runtime: fictitious-domain Poisson PCG on CPU.
//
// Covers the reference's stage0 (sequential C++) and stage1 (OpenMP)
// capabilities natively — same numerics as the JAX/TPU path of this
// framework, so it doubles as an independent host-side oracle:
//   geometry        ~ stage0/Withoutopenmp1.cpp:14-39
//   assembly        ~ stage0/Withoutopenmp1.cpp:42-61
//   stencil/precond ~ stage0/Withoutopenmp1.cpp:75-103
//   PCG driver      ~ stage0/Withoutopenmp1.cpp:106-172
//   OpenMP layer    ~ stage1-openmp/Withopenmp1.cpp (collapse(2) loops,
//                     reduction dots)
// (Citations document behavioural parity; the implementation is this
// framework's own: flat row-major arrays, one translation unit, a C ABI
// for ctypes, no per-iteration allocation — the reference's stage0
// allocates an M×N matrix every iteration, a known perf bug not copied.)
//
// Build: g++ -O3 -march=native -fopenmp -shared -fPIC (see build_native.py).

#include <cmath>
#include <cstdint>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

struct Grid {
  int M, N;            // cells in x / y; nodes 0..M x 0..N
  double a1, b1, a2, b2;
  double h1, h2;
  double eps;
  std::int64_t cols;   // N + 1 (row-major pitch)
  std::int64_t idx(int i, int j) const { return i * cols + j; }
  double x(int i) const { return a1 + i * h1; }
  double y(int j) const { return a2 + j * h2; }
};

// --- L0 geometry: ellipse D = {x^2 + 4 y^2 < 1} ---------------------------

inline bool in_domain(double x, double y) {
  return x * x + 4.0 * y * y < 1.0;
}

// Length of {x fixed} x [y0, y1] inside D (closed form).
inline double vertical_len_in_d(double x, double y0, double y1) {
  double disc = 1.0 - x * x;
  if (disc <= 0.0) return 0.0;
  double half = 0.5 * std::sqrt(disc);  // |y| < half inside
  double lo = y0 > -half ? y0 : -half;
  double hi = y1 < half ? y1 : half;
  return hi > lo ? hi - lo : 0.0;
}

// Length of [x0, x1] x {y fixed} inside D.
inline double horizontal_len_in_d(double y, double x0, double x1) {
  double disc = 1.0 - 4.0 * y * y;
  if (disc <= 0.0) return 0.0;
  double half = std::sqrt(disc);  // |x| < half inside
  double lo = x0 > -half ? x0 : -half;
  double hi = x1 < half ? x1 : half;
  return hi > lo ? hi - lo : 0.0;
}

// --- L1 assembly: per-face diffusion coefficients + indicator RHS ---------

inline double blend(double len, double h, double eps) {
  if (std::fabs(len - h) < 1e-9) return 1.0;
  if (len < 1e-9) return 1.0 / eps;
  double frac = len / h;
  return frac + (1.0 - frac) / eps;
}

void assemble(const Grid& g, double f_val, std::vector<double>& a,
              std::vector<double>& b, std::vector<double>& rhs) {
#ifdef _OPENMP
#pragma omp parallel for collapse(2) schedule(static)
#endif
  for (int i = 1; i <= g.M; ++i)
    for (int j = 1; j <= g.N; ++j) {
      double xf = g.x(i) - 0.5 * g.h1;
      double yf = g.y(j) - 0.5 * g.h2;
      a[g.idx(i, j)] =
          blend(vertical_len_in_d(xf, yf, yf + g.h2), g.h2, g.eps);
      b[g.idx(i, j)] =
          blend(horizontal_len_in_d(yf, xf, xf + g.h1), g.h1, g.eps);
    }
#ifdef _OPENMP
#pragma omp parallel for collapse(2) schedule(static)
#endif
  for (int i = 1; i < g.M; ++i)
    for (int j = 1; j < g.N; ++j)
      rhs[g.idx(i, j)] = in_domain(g.x(i), g.y(j)) ? f_val : 0.0;
}

// --- L3 operators ---------------------------------------------------------

// out = A.v on the interior (boundary ring untouched = 0).
void apply_a(const Grid& g, const std::vector<double>& a,
             const std::vector<double>& b, const std::vector<double>& v,
             std::vector<double>& out) {
#ifdef _OPENMP
#pragma omp parallel for collapse(2) schedule(static)
#endif
  for (int i = 1; i < g.M; ++i)
    for (int j = 1; j < g.N; ++j) {
      std::int64_t c = g.idx(i, j);
      double vc = v[c];
      double dx = a[g.idx(i + 1, j)] * (v[g.idx(i + 1, j)] - vc) / g.h1 -
                  a[c] * (vc - v[g.idx(i - 1, j)]) / g.h1;
      double dy = b[g.idx(i, j + 1)] * (v[g.idx(i, j + 1)] - vc) / g.h2 -
                  b[c] * (vc - v[g.idx(i, j - 1)]) / g.h2;
      out[c] = -dx / g.h1 - dy / g.h2;
    }
}

// z = r / diag(A), guarded; diag = (a_{i+1,j}+a_ij)/h1^2 + (b_{i,j+1}+b_ij)/h2^2.
void apply_dinv(const Grid& g, const std::vector<double>& a,
                const std::vector<double>& b, const std::vector<double>& r,
                std::vector<double>& z) {
#ifdef _OPENMP
#pragma omp parallel for collapse(2) schedule(static)
#endif
  for (int i = 1; i < g.M; ++i)
    for (int j = 1; j < g.N; ++j) {
      std::int64_t c = g.idx(i, j);
      double d = (a[g.idx(i + 1, j)] + a[c]) / (g.h1 * g.h1) +
                 (b[g.idx(i, j + 1)] + b[c]) / (g.h2 * g.h2);
      z[c] = d != 0.0 ? r[c] / d : 0.0;
    }
}

// Grid-weighted inner product h1 h2 sum(u v) over the interior.
double dot(const Grid& g, const std::vector<double>& u,
           const std::vector<double>& v) {
  double s = 0.0;
#ifdef _OPENMP
#pragma omp parallel for collapse(2) schedule(static) reduction(+ : s)
#endif
  for (int i = 1; i < g.M; ++i)
    for (int j = 1; j < g.N; ++j) s += u[g.idx(i, j)] * v[g.idx(i, j)];
  return s * g.h1 * g.h2;
}

}  // namespace

// --- L5/L6: C ABI solver entry -------------------------------------------

extern "C" {

// Solve -Lap(u) = f on D (fictitious domain) with diagonal PCG.
//   norm_weighted: 1 -> ||dw|| = sqrt(sum dw^2 * h1 h2) (stages 1-4),
//                  0 -> sqrt(sum dw^2)                  (stage0 v1).
//   eps <= 0 or max_iter <= 0 select the defaults max(h1,h2)^2 and
//   (M-1)(N-1). n_threads <= 0 keeps the OpenMP default.
// Returns 0 converged, 1 not converged, 2 PCG breakdown, -1 bad args.
int pe_solve(int M, int N, double a1, double b1, double a2, double b2,
             double f_val, double delta, double eps, int max_iter,
             int norm_weighted, int n_threads, double* w_out,
             int* iters_out, double* diff_out) {
  if (M < 2 || N < 2 || !w_out || !iters_out || !diff_out) return -1;
#ifdef _OPENMP
  // omp_set_num_threads is process-global and sticky: save and restore so
  // threads=0 ("OpenMP default") still means the default after a call with
  // an explicit count
  int prev_threads = omp_get_max_threads();
  if (n_threads > 0) omp_set_num_threads(n_threads);
#else
  (void)n_threads;
#endif
  Grid g;
  g.M = M; g.N = N;
  g.a1 = a1; g.b1 = b1; g.a2 = a2; g.b2 = b2;
  g.h1 = (b1 - a1) / M;
  g.h2 = (b2 - a2) / N;
  double h = g.h1 > g.h2 ? g.h1 : g.h2;
  g.eps = eps > 0.0 ? eps : h * h;
  g.cols = N + 1;
  if (max_iter <= 0) max_iter = (M - 1) * (N - 1);

  std::int64_t n = static_cast<std::int64_t>(M + 1) * (N + 1);
  std::vector<double> a(n, 0.0), b(n, 0.0), rhs(n, 0.0);
  assemble(g, f_val, a, b, rhs);

  std::vector<double> w(n, 0.0), r(rhs), z(n, 0.0), p(n, 0.0), ap(n, 0.0);
  apply_dinv(g, a, b, r, z);
  p = z;
  double zr = dot(g, z, r);

  int k = 0;
  int status = 1;
  double diff = 0.0;
  while (k < max_iter) {
    ++k;
    apply_a(g, a, b, p, ap);
    double denom = dot(g, ap, p);
    if (denom < 1e-15) { status = 2; break; }
    double alpha = zr / denom;

    double dw2 = 0.0;
#ifdef _OPENMP
#pragma omp parallel for collapse(2) schedule(static) reduction(+ : dw2)
#endif
    for (int i = 1; i < M; ++i)
      for (int j = 1; j < N; ++j) {
        std::int64_t c = g.idx(i, j);
        double w_old = w[c];
        w[c] = w_old + alpha * p[c];
        r[c] -= alpha * ap[c];
        // realised increment (w_new - w_old), not alpha*p: the two differ
        // in FP and the convergence oracle counts depend on it
        double step = w[c] - w_old;
        dw2 += step * step;
      }

    apply_dinv(g, a, b, r, z);
    double zr_new = dot(g, z, r);

    diff = norm_weighted ? std::sqrt(dw2 * g.h1 * g.h2) : std::sqrt(dw2);
    if (diff < delta) { status = 0; break; }

    double beta = zr_new / zr;
    zr = zr_new;
#ifdef _OPENMP
#pragma omp parallel for collapse(2) schedule(static)
#endif
    for (int i = 1; i < M; ++i)
      for (int j = 1; j < N; ++j) {
        std::int64_t c = g.idx(i, j);
        p[c] = z[c] + beta * p[c];
      }
  }

  for (std::int64_t t = 0; t < n; ++t) w_out[t] = w[t];
  *iters_out = k;
  *diff_out = diff;
#ifdef _OPENMP
  omp_set_num_threads(prev_threads);
#endif
  return status;
}

// Assemble-only entry for cross-checking the JAX assembly (golden tests).
int pe_assemble(int M, int N, double a1, double b1, double a2, double b2,
                double f_val, double eps, double* a_out, double* b_out,
                double* rhs_out) {
  if (M < 2 || N < 2 || !a_out || !b_out || !rhs_out) return -1;
  Grid g;
  g.M = M; g.N = N;
  g.a1 = a1; g.b1 = b1; g.a2 = a2; g.b2 = b2;
  g.h1 = (b1 - a1) / M;
  g.h2 = (b2 - a2) / N;
  double h = g.h1 > g.h2 ? g.h1 : g.h2;
  g.eps = eps > 0.0 ? eps : h * h;
  g.cols = N + 1;
  std::int64_t n = static_cast<std::int64_t>(M + 1) * (N + 1);
  std::vector<double> a(n, 0.0), b(n, 0.0), rhs(n, 0.0);
  assemble(g, f_val, a, b, rhs);
  for (std::int64_t t = 0; t < n; ++t) {
    a_out[t] = a[t];
    b_out[t] = b[t];
    rhs_out[t] = rhs[t];
  }
  return 0;
}

int pe_num_threads() {
#ifdef _OPENMP
  return omp_get_max_threads();
#else
  return 1;
#endif
}

}  // extern "C"
