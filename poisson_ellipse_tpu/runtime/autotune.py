"""Telemetry-driven engine autotuning: measured configs, not folklore.

The repo already measures everything a tuner needs — κ(M⁻¹A) and
Ritz-replay iteration prediction from the Lanczos-of-CG reconstruction
(``obs.spectrum``, exact on the published grids), measured streaming
bandwidth (``obs.profile``), and the per-engine traffic models
(``harness.roofline`` / ``mg.engine.modeled_extra_passes``). This
module closes the loop: score every candidate engine configuration for
a shape from that telemetry, pick a winner that provably does not lose
to the static default, persist it next to the XLA compile cache, and
let ``solver.engine.build_solver(engine="auto")`` and the serve
scheduler's batch contexts (``Scheduler._ctx_for``, the per-bucket
tuned chunk) consult the persisted registry at admission.

Three invariants, enforced in code rather than hoped for:

- **The static default is always a candidate** and the winner must beat
  it by a margin (:data:`SELECT_MARGIN`) on the predicted-cost model —
  a coin-flip prediction keeps the default. With ``measure=True`` the
  winner is additionally wall-clocked against the default and demoted
  on a loss (and ``tools/bench_compare.py``'s ``autotune-pct`` gate
  fails any published round where a tuned config loses anyway).
- **Determinism**: :func:`select` is a pure function of the telemetry
  dict — the same telemetry always yields the same config (pinned in
  ``tests/test_fmg.py``), so a persisted registry is reproducible from
  its recorded telemetry.
- **Keys are complete**: (grid bucket, geometry fingerprint, dtype,
  storage dtype, norm) — the same components that make a warm-pool
  executable reusable. A tuned config is never consulted for a shape
  it was not tuned for.

The candidate knob space comes from ``solver.engine.ENGINE_CAPS`` — the
one engine-capability table — so a newly registered engine exposes its
tunables to the tuner in the same row that registers everything else.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import os
import tempfile
import time
from typing import Optional

import jax.numpy as jnp

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs import trace as obs_trace

SCHEMA_VERSION = 1
ENV_DISABLE = "POISSON_AUTOTUNE"

# a candidate must beat the static default's predicted cost by this
# fraction to displace it — the model's noise floor; anything closer is
# a coin flip and the default (known-good, oracle-checked) keeps the slot
SELECT_MARGIN = 0.10

# modeled HBM passes per stencil application / per diagonal-PCG
# iteration — the same constants mg.engine.modeled_extra_passes and
# harness.roofline quote, kept here as named facts of the cost model
PASSES_PER_APPLY = 7.0
PASSES_PER_DIAG_ITER = 13.0
# of those, the fine-array passes the classical recurrence spends on
# its separate reduction/dot reads; the s-step block fuses them into
# ONE Gram round over its (2s+1)-vector basis per s iterations (PR
# 14's communication-avoiding trade), i.e. (2s+1)/s passes/iteration
PASSES_PER_DIAG_REDUCE = 4.0

# V-cycle-preconditioned CG contracts the error by a grid-independent
# factor per iteration (the whole point of PR 8); ρ = 0.3 is the
# conservative end of the measured band on the published grids
MG_RATE = 0.3
# verification/polish iterations the FMG handoff budget assumes
FMG_HANDOFF_ITERS = 2.0
# telemetry probe budget (iterations of the capped history solve)
PROBE_ITERS = 48
# fallback streaming bandwidth when no profile measurement is available
# (CPU test runs); only relative candidate ranking survives it anyway
FALLBACK_GBPS = 100.0


@dataclasses.dataclass(frozen=True)
class TunedConfig:
    """One shape's tuned engine configuration (the registry's value)."""

    engine: str
    knobs: dict = dataclasses.field(default_factory=dict)
    predicted_iters: float | None = None
    predicted_t_s: float | None = None
    static_engine: str | None = None
    static_predicted_t_s: float | None = None
    measured_t_s: float | None = None
    static_measured_t_s: float | None = None
    # Krylov-recycling verdict for the serve lanes (``solver.recycle``):
    # True when the deflated Ritz replay predicts a warm start cuts the
    # diagonal iteration count by at least SELECT_MARGIN for this shape.
    # Advisory — the scheduler's ``warm_start`` stays an explicit opt-in
    # because warm-started solution bits legitimately differ from cold.
    recycle: bool = False
    predicted_iters_recycled: float | None = None

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, rec: dict) -> "TunedConfig":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in rec.items() if k in fields})


# -- keys --------------------------------------------------------------------


def geometry_fingerprint(geometry) -> str:
    """A stable content fingerprint of the domain: "ellipse" for the
    closed-form default, else the sha1 of the canonical JSON spec —
    byte-stable across processes, which is what lets a persisted config
    be consulted by a different worker than the one that tuned it."""
    if geometry is None:
        return "ellipse"
    if not isinstance(geometry, dict):
        from poisson_ellipse_tpu.geom import sdf as geom_sdf

        geometry = geom_sdf.to_spec(geometry)
    canon = json.dumps(geometry, sort_keys=True, separators=(",", ":"))
    return hashlib.sha1(canon.encode()).hexdigest()[:16]


def tune_key(problem: Problem, dtype=jnp.float32, storage_dtype=None,
             geometry=None) -> str:
    """The registry key: (grid bucket, geometry fingerprint, dtype,
    storage dtype, norm) — the compile-cache bucketing reused, so one
    tuned config covers exactly the shapes one warm executable covers."""
    from poisson_ellipse_tpu.ops.precision import resolve_storage_dtype
    from poisson_ellipse_tpu.runtime.compile_cache import grid_bucket

    Mb, Nb = grid_bucket(problem.M, problem.N)
    st = resolve_storage_dtype(storage_dtype, dtype)
    storage = "" if st is None else jnp.dtype(st).name
    return "|".join((
        f"{Mb}x{Nb}", geometry_fingerprint(geometry),
        jnp.dtype(dtype).name, storage, problem.norm,
    ))


# -- persistence -------------------------------------------------------------


def registry_path(cache_dir: str | None = None) -> str:
    """``autotune.json`` next to the persistent XLA compile cache
    directory (``runtime.compile_cache``): the same lifecycle — wiped
    together, shipped together, warmed together."""
    from poisson_ellipse_tpu.runtime import compile_cache

    base = cache_dir or os.environ.get(
        compile_cache.ENV_CACHE_DIR
    ) or compile_cache.DEFAULT_CACHE_DIR
    return os.path.join(os.path.dirname(base.rstrip(os.sep)),
                        "autotune.json")


class TuneRegistry:
    """The persisted key → :class:`TunedConfig` map.

    Writes are atomic (tempfile + rename) so a crashed tuner never
    leaves a torn registry for ``build_solver`` to trip over; loads
    tolerate a missing file (empty registry) and refuse a wrong schema
    version (forward-compatibility: better untuned than mistuned).
    """

    def __init__(self, path: str | None = None):
        self.path = path or registry_path()
        self.entries: dict[str, TunedConfig] = {}
        self._loaded = False

    def load(self) -> "TuneRegistry":
        self._loaded = True
        try:
            with open(self.path, "r", encoding="utf-8") as fh:
                rec = json.load(fh)
        except (OSError, json.JSONDecodeError):
            return self
        if rec.get("version") != SCHEMA_VERSION:
            return self
        for key, val in (rec.get("entries") or {}).items():
            try:
                self.entries[key] = TunedConfig.from_json(val)
            except (TypeError, ValueError):
                continue  # one bad entry must not poison the registry
        return self

    def save(self) -> str:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        rec = {
            "version": SCHEMA_VERSION,
            "entries": {k: v.to_json() for k, v in self.entries.items()},
        }
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(self.path) or ".", suffix=".tmp"
        )
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as fh:
                json.dump(rec, fh, sort_keys=True)
            os.replace(tmp, self.path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return self.path

    def get(self, key: str) -> Optional[TunedConfig]:
        if not self._loaded:
            self.load()
        return self.entries.get(key)

    def put(self, key: str, cfg: TunedConfig) -> None:
        self.entries[key] = cfg


_REGISTRY: Optional[TuneRegistry] = None


def default_registry() -> TuneRegistry:
    """The process-wide registry (loaded lazily from the default path)."""
    global _REGISTRY
    if _REGISTRY is None:
        _REGISTRY = TuneRegistry().load()
    return _REGISTRY


def lookup(problem: Problem, dtype=jnp.float32, storage_dtype=None,
           geometry=None, registry: TuneRegistry | None = None,
           ) -> Optional[TunedConfig]:
    """The admission-time consult: the persisted tuned config for this
    shape, or None (which leaves every caller on its static default).

    Cheap by construction — one dict lookup against the lazily loaded
    registry; a missing file, a disabled tuner
    (``POISSON_AUTOTUNE=off``) or an unknown key all answer None, so
    untuned processes behave byte-identically to the pre-tuner release.
    """
    if os.environ.get(ENV_DISABLE, "").lower() in ("0", "off", "false"):
        return None
    reg = registry if registry is not None else default_registry()
    if registry is None and not os.path.exists(reg.path):
        return None
    return reg.get(tune_key(problem, dtype, storage_dtype=storage_dtype,
                            geometry=geometry))


# -- telemetry ---------------------------------------------------------------


def collect_telemetry(problem: Problem, dtype=jnp.float32, geometry=None,
                      theta=None, probe_iters: int = PROBE_ITERS,
                      measure_gbps: bool = True) -> dict:
    """The measured facts the scoring model consumes, in one dict.

    One capped history-enabled diagonal solve feeds ``obs.spectrum``
    (κ, eigenvalue bounds, Ritz-replay predicted iterations — the same
    single Lanczos path ``harness diagnose`` and ``mg.engine`` use);
    ``measure_gbps=True`` adds one ``obs.profile`` phase profile for the
    achieved streaming bandwidth. Everything downstream
    (:func:`select`) is a pure function of this dict — record it, and
    the tuning decision replays exactly.
    """
    import dataclasses as _dc

    from poisson_ellipse_tpu.obs import spectrum as obs_spectrum
    from poisson_ellipse_tpu.solver.engine import build_solver
    from poisson_ellipse_tpu.solver.recycle import RECYCLE_K

    probe = _dc.replace(
        problem, max_iter=min(probe_iters, problem.max_iterations)
    )
    solver, args, _ = build_solver(probe, "xla", dtype, history=True,
                                   geometry=geometry, theta=theta)
    result, trace = solver(*args)
    # deflated_k marks the report as ALSO predicting the k-mode
    # recycled warm start — predicted_iters_cold/-_recycled arrive as a
    # pair, and scoring below keeps the cold figure as predicted_iters
    # so the per-solve cost model's semantics are unchanged
    spec = obs_spectrum.spectrum_report(
        trace, delta=problem.delta, actual_iters=int(result.iters),
        deflated_k=RECYCLE_K,
    )
    gbps = None
    if measure_gbps:
        from poisson_ellipse_tpu.obs import profile as obs_profile

        try:
            # the profile runs the ellipse form of the grid — bandwidth
            # is a shape fact, not a geometry fact
            prof = obs_profile.profile_engine(
                probe, "xla", dtype, repeat=1, with_xla_cost=False,
            )
            gbps = prof.get("hbm_gbps")
        except (TypeError, ValueError):
            gbps = None
    return {
        "grid": [problem.M, problem.N],
        "delta": problem.delta,
        "kappa": spec.get("kappa") if spec.get("available") else None,
        "predicted_iters": (
            spec.get("predicted_iters_cold")
            if spec.get("available") else None
        ),
        "predicted_iters_recycled": (
            spec.get("predicted_iters_recycled")
            if spec.get("available") else None
        ),
        "probe_iters": int(result.iters),
        "probe_converged": bool(result.converged),
        "gbps": gbps,
    }


# -- the scoring model -------------------------------------------------------


def _diag_iters(problem: Problem, telemetry: dict) -> float:
    """Ritz-predicted diagonal-PCG iterations, with the κ-model and the
    probe's own count as graceful fallbacks (in that order)."""
    pred = telemetry.get("predicted_iters")
    if pred:
        return float(pred)
    kappa = telemetry.get("kappa")
    if kappa and kappa > 1.0:
        # the CG error bound: iters ≈ ½√κ ln(2/δ)
        return 0.5 * math.sqrt(kappa) * math.log(2.0 / problem.delta)
    return float(max(telemetry.get("probe_iters") or 1, 1))


def _recycled_iters(problem: Problem,
                    telemetry: dict) -> Optional[float]:
    """Ritz-predicted diagonal iterations AFTER the k-mode deflated warm
    start (``solver.recycle``), or None when the probe's trace could not
    support the deflated replay — there is deliberately no κ fallback
    here: a recycling win must be predicted from the measured spectrum
    or not claimed at all."""
    pred = telemetry.get("predicted_iters_recycled")
    return float(pred) if pred else None


def _mg_iters(problem: Problem) -> float:
    """V-cycle-preconditioned iteration budget: the grid-independent
    contraction ρ = MG_RATE gives iters ≈ ln(1/δ)/ln(1/ρ)."""
    return max(
        math.log(1.0 / problem.delta) / math.log(1.0 / MG_RATE), 4.0
    )


def candidates(problem: Problem, dtype=jnp.float32,
               storage_dtype=None) -> list[TunedConfig]:
    """The candidate set for one shape: the static default first (the
    anchor every winner must beat), then the iteration-count engines
    with their ENGINE_CAPS tunables swept over a small static menu."""
    from poisson_ellipse_tpu.mg import coarsen
    from poisson_ellipse_tpu.solver.engine import (
        ENGINE_CAPS,
        select_engine,
    )

    default = select_engine(problem, dtype)
    out = [TunedConfig(engine=default)]
    if storage_dtype is not None:
        # narrow-storage shapes: only storage-capable engines may enter
        return out + [
            TunedConfig(engine="sstep", knobs={"sstep_s": s})
            for s in (2, 4)
            if ENGINE_CAPS["sstep"]["storage"]
        ]
    levels = coarsen.num_levels(problem.M, problem.N)
    mg_tun = dict(ENGINE_CAPS["mg-pcg"]["tunables"], levels=levels)
    fmg_tun = dict(ENGINE_CAPS["fmg"]["tunables"], levels=levels)
    out.append(TunedConfig(engine="mg-pcg", knobs=mg_tun))
    for k in (8, 12, 16):
        out.append(TunedConfig(engine="cheb-pcg",
                               knobs={"cheb_degree": k}))
    for nv in (1, 2):
        out.append(TunedConfig(
            engine="fmg", knobs=dict(fmg_tun, n_vcycles=nv)
        ))
    return out


def predicted_cost(problem: Problem, cand: TunedConfig, telemetry: dict,
                   dtype=jnp.float32) -> tuple[float, float]:
    """(predicted fine-array HBM passes, predicted iterations) for one
    candidate — a pure function of (candidate, telemetry), which is what
    makes :func:`select` deterministic and replayable."""
    from poisson_ellipse_tpu.mg.engine import modeled_extra_passes
    from poisson_ellipse_tpu.mg.fmg import work_units_per_point

    if cand.engine == "mg-pcg":
        iters = _mg_iters(problem)
        passes = iters * (
            PASSES_PER_DIAG_ITER
            + modeled_extra_passes(problem, "mg-pcg", dtype)
        )
    elif cand.engine == "cheb-pcg":
        k = int(cand.knobs.get("cheb_degree", 12))
        # each iteration's polynomial buys ~k× fewer iterations (the
        # measured first-rung trade; bench `precond` validates it)
        iters = max(_diag_iters(problem, telemetry) / max(k, 1), 4.0)
        passes = iters * (
            PASSES_PER_DIAG_ITER + PASSES_PER_APPLY * (k - 1) + 2.0
        )
    elif cand.engine == "fmg":
        levels = int(cand.knobs.get("levels") or 1)
        iters = FMG_HANDOFF_ITERS
        passes = PASSES_PER_APPLY * work_units_per_point(
            levels,
            nu=int(cand.knobs.get("nu", 2)),
            coarse_degree=int(cand.knobs.get("coarse_degree", 24)),
            n_vcycles=int(cand.knobs.get("n_vcycles", 2)),
        ) + iters * (
            PASSES_PER_DIAG_ITER
            + modeled_extra_passes(problem, "mg-pcg", dtype)
        )
    elif cand.engine in ("sstep", "sstep-pallas"):
        # same iteration count as the diagonal recurrence, but the
        # separate reduction reads collapse into one Gram round over
        # the (2s+1)-vector basis per s iterations — without this the
        # storage-dtype sweep scores sstep identical to the default
        # and can never select it
        iters = _diag_iters(problem, telemetry)
        s = max(int(cand.knobs.get("sstep_s", 4)), 1)
        passes = iters * (
            PASSES_PER_DIAG_ITER - PASSES_PER_DIAG_REDUCE
            + (2.0 * s + 1.0) / s
        )
    else:
        # the diagonal-recurrence engines (the static-default family):
        # same iteration count, per-iteration byte bills differing only
        # in residency — modeled at the loop figure, which ranks them
        # conservatively AGAINST the iteration-count engines
        iters = _diag_iters(problem, telemetry)
        passes = iters * PASSES_PER_DIAG_ITER
    return passes, iters


def select(problem: Problem, telemetry: dict, dtype=jnp.float32,
           storage_dtype=None) -> tuple[TunedConfig, list[dict]]:
    """Score every candidate from the telemetry and pick the winner.

    Pure in the telemetry (determinism pin: same dict in, same config
    out). The static default anchors the comparison: a candidate must
    beat its predicted cost by :data:`SELECT_MARGIN`, so the tuner can
    only ever *match or improve* the static policy by construction —
    the in-model half of the never-loses acceptance (the measured half
    is ``measure=True`` below and the bench ``autotune`` gate).
    """
    g1, g2 = problem.node_shape
    array_gb = g1 * g2 * jnp.dtype(dtype).itemsize / 1e9
    gbps = telemetry.get("gbps") or FALLBACK_GBPS
    scored = []
    for cand in candidates(problem, dtype, storage_dtype):
        passes, iters = predicted_cost(problem, cand, telemetry, dtype)
        t_pred = passes * array_gb / gbps
        scored.append({
            "engine": cand.engine, "knobs": dict(cand.knobs),
            "predicted_iters": round(iters, 2),
            "predicted_passes": round(passes, 2),
            "predicted_t_s": t_pred,
        })
    default_row = scored[0]
    best = min(scored, key=lambda row: row["predicted_t_s"])
    if best["predicted_t_s"] > default_row["predicted_t_s"] * (
            1.0 - SELECT_MARGIN):
        best = default_row
    # the serve-layer knob rides the same entry: chunk sized to ~4
    # retire-and-refill boundaries per solve (granularity for
    # deadlines/refill vs per-chunk dispatch overhead), clamped to the
    # scheduler's sane band — consulted by Scheduler._ctx_for at
    # warm-pool admission. Sized from the DIAGONAL prediction, not the
    # winner's: the scheduler's lanes run the batched diag engine
    # regardless of the single-solve winner, and an fmg winner's ~2
    # handoff iterations would floor the chunk at 8 and double the
    # lanes' per-chunk host round-trips on a 546-iteration solve
    serve_chunk = int(min(128, max(
        8, round(_diag_iters(problem, telemetry) / 4)
    )))
    # the recycling verdict rides the DIAGONAL prediction pair, same
    # reasoning as the chunk: the scheduler's lanes run the batched diag
    # engine regardless of the single-solve winner, so the warm-start
    # payoff is the cold-vs-deflated gap of that engine, not the
    # winner's. Recycling must clear the same noise-floor margin a
    # candidate engine must — a marginal predicted cut keeps cold.
    cold_iters = _diag_iters(problem, telemetry)
    rec_iters = _recycled_iters(problem, telemetry)
    recycle = bool(
        rec_iters is not None
        and rec_iters < cold_iters * (1.0 - SELECT_MARGIN)
    )
    chosen = TunedConfig(
        engine=best["engine"], knobs=dict(best["knobs"], chunk=serve_chunk),
        predicted_iters=best["predicted_iters"],
        predicted_t_s=best["predicted_t_s"],
        static_engine=default_row["engine"],
        static_predicted_t_s=default_row["predicted_t_s"],
        recycle=recycle,
        predicted_iters_recycled=(
            None if rec_iters is None else round(rec_iters, 2)
        ),
    )
    return chosen, scored


# -- the closed loop ---------------------------------------------------------


def _measure_once(problem: Problem, engine: str, dtype, geometry=None,
                  theta=None, knobs: dict | None = None) -> float:
    """One warmed, fenced dispatch's wall clock (the tune-time check,
    not the bench protocol — bench.py owns the amortised numbers).
    ``knobs`` is the candidate's knob dict: the measured configuration
    must BE the scored configuration (levels/ν/degrees/n_vcycles via
    ``tuned_knobs``, s via ``sstep_s``), or the persisted record would
    attest a wall clock the selected config never produced."""
    from poisson_ellipse_tpu.solver.engine import build_solver
    from poisson_ellipse_tpu.utils.timing import fence

    knobs = knobs or {}
    sstep_kwargs = (
        {"sstep_s": int(knobs["sstep_s"])} if "sstep_s" in knobs else {}
    )
    solver, args, _ = build_solver(problem, engine, dtype,
                                   geometry=geometry, theta=theta,
                                   tuned_knobs=knobs, **sstep_kwargs)
    fence(solver(*args))  # compile + warm-up, untimed
    t0 = time.perf_counter()
    # the sync IS the measurement — the bracket closes on device work
    fence(solver(*args))
    return time.perf_counter() - t0


def tune(problem: Problem, dtype=jnp.float32, storage_dtype=None,
         geometry=None, theta=None, registry: TuneRegistry | None = None,
         persist: bool = True, measure: bool = False,
         telemetry: dict | None = None) -> dict:
    """Run the closed loop for one shape: telemetry → score → select →
    (optionally measure) → persist. Returns the full report (the
    ``harness tune`` subcommand prints it; the measured columns are
    None unless ``measure=True``).

    ``telemetry`` overrides collection (replay/testing); ``registry``
    overrides the default persisted registry (tests use throwaways).
    With ``measure=True`` the chosen config and the static default are
    each wall-clocked once and a losing winner is DEMOTED to the
    default before persisting — a tuned registry can then only contain
    configs that beat (or are) the static default as measured on the
    tuning machine.
    """
    tel = telemetry if telemetry is not None else collect_telemetry(
        problem, dtype, geometry=geometry, theta=theta
    )
    chosen, scored = select(problem, tel, dtype, storage_dtype)
    key = tune_key(problem, dtype, storage_dtype=storage_dtype,
                   geometry=geometry)
    demoted = False
    if measure and chosen.engine != chosen.static_engine:
        t_tuned = _measure_once(problem, chosen.engine, dtype,
                                geometry=geometry, theta=theta,
                                knobs=chosen.knobs)
        t_static = _measure_once(problem, chosen.static_engine, dtype,
                                 geometry=geometry, theta=theta)
        if t_tuned > t_static:
            demoted = True
            chosen = dataclasses.replace(
                chosen, engine=chosen.static_engine, knobs={},
                measured_t_s=t_static, static_measured_t_s=t_static,
            )
        else:
            chosen = dataclasses.replace(
                chosen, measured_t_s=t_tuned, static_measured_t_s=t_static,
            )
    reg = registry if registry is not None else default_registry()
    if persist:
        reg.put(key, chosen)
        reg.save()
    obs_trace.event(
        "autotune:select", key=key, engine=chosen.engine,
        static_engine=chosen.static_engine, demoted=demoted,
        predicted_t_s=chosen.predicted_t_s,
        static_predicted_t_s=chosen.static_predicted_t_s,
        recycle=chosen.recycle,
        predicted_iters_recycled=chosen.predicted_iters_recycled,
    )
    return {
        "key": key,
        "telemetry": tel,
        "candidates": scored,
        "chosen": chosen.to_json(),
        "demoted_to_static": demoted,
        "registry_path": reg.path if persist else None,
    }
