"""Distributed layer (reference layers L2 partitioning + L4 communication).

The reference's distribution machinery — ``choose_process_grid`` /
``decompose_2d`` (2D block partition of the interior,
``stage2-mpi/poisson_mpi_decomp.cpp:60-111``), nonblocking/blocking halo
exchange (``:241-347``, ``poisson_mpi_cuda2.cu:331-500``) and
``MPI_Allreduce`` scalar reductions — becomes here:

- ``mesh``:   device-mesh factorisation (= choose_process_grid) and global
              grid padding to even shards (= decompose_2d, with the uneven
              remainder handled by zero-padding instead of ±1 block sizes),
- ``halo``:   1-cell halo ring exchange via ``lax.ppermute`` over ICI,
              corners riding along in the second round exactly as the
              reference's edge buffers include corner cells,
- ``pcg_sharded``: the whole PCG solve as ONE ``shard_map``-ped program —
              per iteration: one halo exchange (4 ppermutes) + two ``psum``
              collectives, vs the reference's 4 MPI_Sendrecv (with
              host-staged D2H/H2D copies) + 3 MPI_Allreduce + ≥3
              device-host partial-sum round-trips,
- ``pipelined_sharded``: the Ghysels–Vanroose reordering of the same
              solve — ONE stacked ``psum`` per iteration (all dot
              partials together), overlapped by XLA with the halo
              exchange + stencil; the collective-latency engine,
- ``compat``: the jax-version shim every sharding call site routes
              through (``shard_map`` location/checker kwarg, ``pcast``,
              vma-annotated ShapeDtypeStructs, Mosaic compiler params),
- ``multihost``: ``jax.distributed.initialize`` lifecycle (= MPI_Init/
              Finalize) and the all-hosts global mesh — the same solver
              code rides ICI within a slice and DCN across hosts.
"""

from poisson_ellipse_tpu.parallel.mesh import choose_process_grid, make_mesh
from poisson_ellipse_tpu.parallel.halo import halo_extend
from poisson_ellipse_tpu.parallel.multihost import (
    global_mesh,
    initialize_multihost,
    process_info,
    shutdown_multihost,
)
from poisson_ellipse_tpu.parallel.pcg_sharded import (
    build_sharded_solver,
    solve_sharded,
)
from poisson_ellipse_tpu.parallel.pipelined_sharded import (
    build_pipelined_sharded_solver,
    solve_pipelined_sharded,
)

__all__ = [
    "choose_process_grid",
    "make_mesh",
    "halo_extend",
    "build_sharded_solver",
    "build_pipelined_sharded_solver",
    "solve_sharded",
    "solve_pipelined_sharded",
    "global_mesh",
    "initialize_multihost",
    "process_info",
    "shutdown_multihost",
]
