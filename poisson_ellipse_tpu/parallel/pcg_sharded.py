"""Distributed PCG: the whole solve as one shard_map-ped on-device program.

TPU-native redesign of the reference's distributed drivers (``solve_mpi``,
``stage2-mpi/poisson_mpi_decomp.cpp:356-460``; ``gradient_solver_mpi``,
``stage4-mpi+cuda/poisson_mpi_cuda2.cu:687-982``). Structural comparison,
per PCG iteration:

  reference stage4 (per iteration)          here (per iteration)
  ---------------------------------------   ---------------------------------
  4× (D2H memcpy → MPI_Sendrecv → H2D)      1 halo_extend = 4 lax.ppermute
  3× (dot kernel → D2H 256KiB partials      2 lax.psum collectives (denom;
      → host sum → MPI_Allreduce)              [zr, ‖Δw‖²] batched as one)
  α/β/convergence on host                   α/β/convergence on device in
  6 kernel launches + 6 device syncs          lax.while_loop — zero host
                                              round-trips, zero syncs

The decomposition itself (``choose_process_grid`` + ``decompose_2d``)
becomes a ``Mesh`` + zero-padding to even shards (see ``parallel.mesh``);
per-rank local assembly with a halo ring (``fictitious_regions_setup_local``,
``poisson_mpi_cuda2.cu:146-192``) is available as ``assembly_mode="device"``
— each device assembles its own halo-extended coefficient block from global
indices with no communication at all, exactly the reference's contract.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs.convergence import (
    history_init,
    history_record,
    trace_of,
)
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.ops.stencil import apply_a_block, apply_dinv, diag_d_block
from poisson_ellipse_tpu.parallel.compat import pcast_varying, shard_map
from poisson_ellipse_tpu.parallel.halo import halo_extend
from poisson_ellipse_tpu.parallel.mesh import AXIS_X, AXIS_Y, make_mesh, padded_dims
from poisson_ellipse_tpu.solver.pcg import DENOM_GUARD, PCGResult


def _shard_ops(problem: Problem, px: int, py: int, bm: int, bn: int,
               a_ext, b_ext, dtype, stencil_impl: str = "xla",
               interpret: bool = False):
    """(stencil, pdot, d, maskd) closures for one shard — shared by the
    whole-solve and chunked-advance paths. ``maskd`` is the shard's
    interior mask in ``dtype`` (the ABFT checksum field is one stencil
    application over it).

    stencil_impl "pallas" runs the explicit VMEM-tiled stencil kernel
    (``ops.pallas_kernels.apply_a_block_pallas``) on each shard every
    iteration — the reference stage4's structure exactly: a device kernel
    per rank in the hot loop, ringed by halo exchange and scalar
    collectives (``apply_A_kernel`` inside ``gradient_solver_mpi``,
    ``poisson_mpi_cuda2.cu:507-536``, ``:846-939``). "xla" leaves the
    stencil to XLA's fusion (the default; same math, same FP form)."""
    h1 = jnp.asarray(problem.h1, dtype)
    h2 = jnp.asarray(problem.h2, dtype)

    ix = lax.axis_index(AXIS_X)
    iy = lax.axis_index(AXIS_Y)
    gi = ix * bm + jnp.arange(bm, dtype=jnp.int32)
    gj = iy * bn + jnp.arange(bn, dtype=jnp.int32)
    interior = assembly.interior_mask(problem, gi, gj)

    # Diagonal, zeroed outside the global interior so apply_dinv's guard
    # keeps every iterate exactly zero there (boundary ring + shard padding).
    d = jnp.where(interior, diag_d_block(a_ext, b_ext, h1, h2), 0.0)
    maskd = interior.astype(dtype)

    if stencil_impl == "pallas":
        from poisson_ellipse_tpu.ops.pallas_kernels import apply_a_block_pallas

        def stencil(p):
            p_ext = halo_extend(p, px, py)
            # grid spacings as python floats: the kernel bakes them in as
            # compile-time constants (they never reach SMEM)
            return (
                apply_a_block_pallas(
                    p_ext, a_ext, b_ext, problem.h1, problem.h2,
                    interpret=interpret,
                    vma=(AXIS_X, AXIS_Y),
                )
                * maskd
            )

    elif stencil_impl == "xla":

        def stencil(p):
            p_ext = halo_extend(p, px, py)
            return apply_a_block(p_ext, a_ext, b_ext, h1, h2) * maskd

    else:
        raise ValueError(f"unknown stencil_impl: {stencil_impl!r}")

    def pdot(u, v):
        return lax.psum(jnp.sum(u * v), (AXIS_X, AXIS_Y)) * h1 * h2

    return stencil, pdot, d, maskd


def _shard_init(problem: Problem, px: int, py: int, bm: int, bn: int,
                pdot, d, rhs_blk, dtype, history: bool = False,
                precond=None, abft: bool = False, x0_blk=None,
                stencil=None):
    """The full PCG carry at iteration 0 on one shard — layout matches
    ``solver.pcg.init_state`` (k, w, r, p, zr, diff, converged,
    breakdown), with w/r/p as per-shard blocks and replicated scalars.
    ``history=True`` appends the four ``obs.convergence`` buffers —
    scattered from psum-reduced scalars, so they stay replicated too.
    ``precond`` swaps the diagonal preconditioner for a per-shard
    ``z = M⁻¹ r`` applier (``parallel.mg_sharded``'s V-cycle/Chebyshev
    closures — halo ppermutes only, no scalar collectives).
    ``abft=True`` appends the four ABFT shadow scalars
    (S_r, S_w, S_p_pred, sdc — ``resilience.abft``), anchored by one
    stacked psum at iteration 0 (one-time, off the per-iteration path).
    ``x0_blk`` warm-starts the carry (w = x0 with the TRUE per-shard
    residual r = rhs − A·x0 via ``stencil`` — the full-multigrid
    handoff's verified seed, ``parallel.mg_sharded``'s F-cycle)."""
    if x0_blk is None:
        # the zeros literal is device-invariant; mark it varying over the
        # mesh so the while_loop carry type matches the per-device updates
        w0 = pcast_varying(jnp.zeros((bm, bn), dtype), (AXIS_X, AXIS_Y))
        r0 = rhs_blk
    else:
        if stencil is None:
            raise ValueError("x0_blk warm start needs the shard stencil")
        w0 = x0_blk
        r0 = rhs_blk - stencil(x0_blk)
    z0 = apply_dinv(r0, d) if precond is None else precond(r0)
    p0 = z0
    zr0 = pdot(z0, r0)
    state = (
        jnp.asarray(0, jnp.int32),
        w0,
        r0,
        p0,
        zr0,
        jnp.asarray(jnp.inf, dtype),
        jnp.asarray(False),
        jnp.asarray(False),
    )
    if history and abft:
        raise ValueError("history capture and ABFT extend the same carry "
                         "tail; request one or the other")
    if history:
        state = state + history_init(problem.max_iterations, dtype)
    if abft:
        sums = lax.psum(
            jnp.stack([jnp.sum(r0), jnp.sum(p0)]), (AXIS_X, AXIS_Y)
        )
        state = state + (
            sums[0], jnp.asarray(0.0, dtype), sums[1], jnp.asarray(False)
        )
    return state


def _shard_advance(problem: Problem, stencil, pdot, d, state, dtype,
                   limit=None, history: bool = False, precond=None,
                   abft: bool = False, abft_c=None):
    """Advance the sharded PCG carry until convergence/breakdown or
    iteration ``limit`` (defaults to max_iterations). Chunking only moves
    the while_loop boundary, not the arithmetic — same contract as
    ``solver.pcg.advance`` (including the history contract: recording is
    pure extra stores of already-psum-reduced scalars — no additional
    collectives, no host traffic).

    ``precond`` replaces the diagonal preconditioner with a per-shard
    ``z = M⁻¹ r`` applier; the scalar-collective cadence is untouched —
    the convergence word stays the ONE stacked psum below, the denom
    psum stays the other, and any preconditioner communication is halo
    ppermutes inside ``precond`` itself (jaxpr-pinned in
    ``tests/test_mg.py``).

    ``abft=True`` runs the in-loop SDC checks of ``resilience.abft``
    over the 4-scalar-extended carry, with ``abft_c`` the per-shard
    checksum field ``A·1`` (built OUTSIDE the loop —
    ``abft.checksum_field``). Every checksum partial is stacked into the
    SAME convergence psum, so the collective cadence is byte-identical
    to the plain loop: 1 denom psum + 1 stacked psum per iteration,
    pinned from the jaxpr in ``tests/test_elastic.py``."""
    h1 = jnp.asarray(problem.h1, dtype)
    h2 = jnp.asarray(problem.h2, dtype)
    delta = jnp.asarray(problem.delta, dtype)
    weighted = problem.norm == "weighted"
    max_iter = (
        problem.max_iterations
        if limit is None
        else jnp.minimum(
            jnp.asarray(limit, jnp.int32), problem.max_iterations
        )
    )

    def cond(state):
        k, converged, breakdown = state[0], state[6], state[7]
        go = (k < max_iter) & ~converged & ~breakdown
        if abft:
            # a flagged carry stops the loop at once: every further
            # iteration would compute on (and amplify) the corruption,
            # and the guard is going to roll the whole chunk back anyway
            go = go & ~state[_SDC]
        return go

    if abft and (history or abft_c is None):
        raise ValueError(
            "abft needs the checksum field (abft_c) and excludes history "
            "capture — both extend the carry tail"
        )
    if abft:
        # the shadow-tail layout lives with resilience.abft; every
        # consumer (this loop, the guard's adapter, the meshguard)
        # addresses it through the same constants
        from poisson_ellipse_tpu.resilience.abft import (
            SDC as _SDC,
            SP_PRED as _SP,
            SR as _SR,
            SW as _SW,
        )

    def body(state):
        k, w, r, p, zr, _diff, _c, _bd = state[:8]
        ap = stencil(p)
        denom = pdot(ap, p)
        breakdown = denom < DENOM_GUARD
        alpha = zr / jnp.where(breakdown, 1.0, denom)

        w_new = w + alpha * p
        r_new = r - alpha * ap
        z = apply_dinv(r_new, d) if precond is None else precond(r_new)

        # one collective for both scalars (vs 2 of the reference's 3
        # Allreduces; the denominator one above is inherently sequential)
        dw = w_new - w
        if abft:
            # the ABFT partials ride the SAME stacked psum — every term
            # is a reduction over an array this body already produces or
            # reads (ap, r⁺, w⁺, p, z; c is the loop-invariant checksum
            # field), fused by XLA into the passes that materialize them
            partials = jnp.stack([
                jnp.sum(z * r_new), jnp.sum(dw * dw),
                jnp.sum(ap), jnp.sum(abft_c * p), jnp.sum(jnp.abs(ap)),
                jnp.sum(r_new), jnp.sum(jnp.abs(r_new)),
                jnp.sum(w_new), jnp.sum(jnp.abs(w_new)),
                jnp.sum(p), jnp.sum(jnp.abs(p)),
                jnp.sum(z),
            ])
            sums = lax.psum(partials, (AXIS_X, AXIS_Y))
            zr_sum, dw2 = sums[0], sums[1]
        else:
            partial_sums = jnp.stack([jnp.sum(z * r_new), jnp.sum(dw * dw)])
            zr_sum, dw2 = lax.psum(partial_sums, (AXIS_X, AXIS_Y))
        zr_new = zr_sum * h1 * h2
        diff = jnp.sqrt(dw2 * h1 * h2) if weighted else jnp.sqrt(dw2)
        converged = ~breakdown & (diff < delta)
        diff = jnp.where(breakdown, _diff, diff)

        beta = zr_new / zr
        p_new = z + beta * p

        w_out = jnp.where(breakdown, w, w_new)
        r_out = jnp.where(breakdown, r, r_new)
        p_out = jnp.where(breakdown | converged, p, p_new)
        zr_out = jnp.where(breakdown | converged, zr, zr_new)
        out = (k + 1, w_out, r_out, p_out, zr_out, diff, converged, breakdown)
        if history:
            # applied α is 0 on a breakdown iteration (update discarded)
            # — the same recording every engine's trace uses
            out = out + history_record(
                state[8:], k, zr_new, diff,
                jnp.where(breakdown, 0.0, alpha), beta,
            )
        if abft:
            from poisson_ellipse_tpu.resilience.abft import (
                ABFT_TINY,
                abft_rtol,
            )

            S_r, S_w, S_p_pred, sdc = (
                state[_SR], state[_SW], state[_SP], state[_SDC]
            )
            s_ap, s_cp, s_absap = sums[2], sums[3], sums[4]
            s_r, s_absr = sums[5], sums[6]
            s_w, s_absw = sums[7], sums[8]
            s_p, s_absp = sums[9], sums[10]
            s_z = sums[11]
            rtol = abft_rtol(dtype)
            aa = jnp.abs(alpha)
            # every check written as ~(drift <= tol): a NaN drift must
            # read as a violation, and NaN <= tol is False in IEEE
            ok_stencil = jnp.abs(s_ap - s_cp) <= rtol * (s_absap + ABFT_TINY)
            ok_r = jnp.abs(s_r - (S_r - alpha * s_ap)) <= rtol * (
                s_absr + aa * s_absap + ABFT_TINY
            )
            ok_w = jnp.abs(s_w - (S_w + alpha * s_p)) <= rtol * (
                s_absw + aa * s_absp + ABFT_TINY
            )
            ok_p = jnp.abs(s_p - S_p_pred) <= rtol * (s_absp + ABFT_TINY)
            ok_pos = zr > 0  # ⟨z, r⟩ is an energy product: > 0 until done
            fault = ~breakdown & ~(
                ok_stencil & ok_r & ok_w & ok_p & ok_pos
            )
            keep = lambda old, new: jnp.where(breakdown, old, new)
            out = out + (
                keep(S_r, s_r),
                keep(S_w, s_w),
                keep(S_p_pred, s_z + beta * s_p),
                sdc | fault,
            )
        return out

    return lax.while_loop(cond, body, state)


def _local_pcg(problem: Problem, px: int, py: int, bm: int, bn: int,
               a_ext, b_ext, rhs_blk, dtype, stencil_impl: str = "xla",
               interpret: bool = False, history: bool = False):
    """Per-device whole solve (init + advance to the iteration cap).
    Runs inside shard_map; a_ext/b_ext are the device's halo-extended
    (bm+2, bn+2) coefficient blocks, rhs_blk its owned (bm, bn) RHS
    block. With ``history`` the four replicated (cap,) trace buffers
    ride at the end of the returned tuple."""
    stencil, pdot, d, _maskd = _shard_ops(
        problem, px, py, bm, bn, a_ext, b_ext, dtype, stencil_impl, interpret
    )
    state0 = _shard_init(
        problem, px, py, bm, bn, pdot, d, rhs_blk, dtype, history=history
    )
    out = _shard_advance(
        problem, stencil, pdot, d, state0, dtype, history=history
    )
    k, w = out[0], out[1]
    diff, converged, breakdown = out[5], out[6], out[7]
    return (w, k, diff, converged, breakdown) + tuple(out[8:])


def build_sharded_solver(
    problem: Problem,
    mesh: Mesh | None = None,
    dtype=jnp.float32,
    assembly_mode: str = "host",
    stencil_impl: str = "xla",
    history: bool = False,
    geometry=None,
    theta=None,
):
    """Return (jitted solver_fn, args) for the mesh-sharded solve.

    ``history=True`` (classical loops only — "xla"/"pallas") makes the
    solver return ``(PCGResult, obs.ConvergenceTrace)``: the
    per-iteration (zr, diff, α, β) series recorded on device from the
    already-psum-reduced scalars — zero extra collectives, zero host
    traffic inside the loop.

    assembly_mode:
      "host"   — coefficients assembled once on the host in f64, cast, and
                 laid out over the mesh (args = the three sharded arrays;
                 their one-time coefficient halos are exchanged on device).
      "device" — every device assembles its own halo-extended block from
                 global indices inside shard_map, zero communication
                 (args = ()); use with f64 traces — see
                 ``ops.assembly.assemble_numpy`` for the f32 hazard.
    stencil_impl:
      "xla"    — XLA-fused block stencil (default).
      "pallas" — explicit Pallas stencil kernel per shard per iteration
                 (decomposition × device kernels in one program — the
                 stage4 composition; see ``_local_pcg``).
      "fused"  — the whole iteration as two Pallas kernels per shard
                 (K1 p-update+stencil+denom, K2 updates+partials) with a
                 stacked (z, p) halo exchange: 2 kernels + 2 psum +
                 4 ppermute per iteration (``parallel.fused_sharded``;
                 f32/bf16, host assembly only).
      "pipelined" — the Ghysels–Vanroose recurrence with ONE stacked
                 psum per iteration, overlapped by XLA with the halo
                 exchange + stencil (``parallel.pipelined_sharded``;
                 iteration counts within ±2 of "xla", host assembly
                 only — the collective-latency engine for multi-chip/
                 multi-host scale).
    """
    if mesh is None:
        mesh = make_mesh()
    if geometry is not None and assembly_mode != "host":
        raise ValueError(
            "SDF geometry assembles on the HOST in f64 (the quadrature "
            "path of ops.assembly); assembly_mode='device' traces the "
            "closed-form ellipse only"
        )
    if history and stencil_impl not in ("xla", "pallas"):
        raise ValueError(
            "history capture covers the classical sharded loops "
            f"('xla'/'pallas'); got stencil_impl={stencil_impl!r} — the "
            "fused/pipelined sharded iterations keep their scalars inside "
            "kernels/recurrences with their own carry layouts"
        )
    if stencil_impl == "pipelined":
        # the one-collective iteration — its own recurrence and carry
        # layout live in parallel.pipelined_sharded
        if assembly_mode != "host":
            raise ValueError(
                "stencil_impl='pipelined' assembles on the host (the "
                f"rounded-once operand set); got assembly_mode={assembly_mode!r}"
            )
        from poisson_ellipse_tpu.parallel.pipelined_sharded import (
            build_pipelined_sharded_solver,
        )

        return build_pipelined_sharded_solver(
            problem, mesh, dtype, geometry=geometry, theta=theta
        )
    if stencil_impl == "fused":
        # the two-kernel fused iteration composed with the mesh — its own
        # carry layout (rotated loop) and tile-aligned shard padding live
        # in parallel.fused_sharded
        if assembly_mode != "host":
            raise ValueError(
                "stencil_impl='fused' assembles on the host (the rounded-"
                f"once operand set); got assembly_mode={assembly_mode!r}"
            )
        from poisson_ellipse_tpu.parallel.fused_sharded import (
            build_fused_sharded_solver,
        )

        return build_fused_sharded_solver(
            problem, mesh, dtype, geometry=geometry, theta=theta
        )
    px = mesh.shape[AXIS_X]
    py = mesh.shape[AXIS_Y]
    # interpret is a property of the MESH devices, not the process default
    # backend: a TPU-default process dry-running on a virtual CPU mesh
    # (the driver's multichip gate) must interpret, and vice versa
    interpret = mesh.devices.flat[0].platform != "tpu"
    g1p, g2p = padded_dims(problem.node_shape, mesh)
    bm, bn = g1p // px, g2p // py
    spec = P(AXIS_X, AXIS_Y)
    # the four replicated (cap,) trace buffers, when history rides along
    out_specs = (spec, P(), P(), P(), P()) + ((P(),) * 4 if history else ())

    if assembly_mode == "host":

        def shard_fn(a_blk, b_blk, rhs_blk):
            # one-time coefficient halo exchange (the reference avoids this
            # by assembling a halo ring locally; both modes are provided)
            a_ext = halo_extend(a_blk, px, py)
            b_ext = halo_extend(b_blk, px, py)
            return _local_pcg(
                problem, px, py, bm, bn, a_ext, b_ext, rhs_blk, dtype,
                stencil_impl=stencil_impl, interpret=interpret,
                history=history,
            )

        # check_vma off only for the interpret-mode pallas stencil: its
        # internals mix varying refs with unvarying index values, which
        # the vma checker rejects (the kernel itself is per-shard pure);
        # compiled TPU runs keep full vma checking
        mapped = shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=out_specs,
            check_vma=not (stencil_impl == "pallas" and interpret),
        )

        args = _host_sharded_args(problem, mesh, dtype, g1p, g2p, spec,
                                  geometry=geometry, theta=theta)
    elif assembly_mode == "device":

        def shard_fn():
            ix = lax.axis_index(AXIS_X)
            iy = lax.axis_index(AXIS_Y)
            gi_ext = ix * bm - 1 + jnp.arange(bm + 2, dtype=jnp.int32)
            gj_ext = iy * bn - 1 + jnp.arange(bn + 2, dtype=jnp.int32)
            a_ext, b_ext = assembly.coefficients_at(problem, gi_ext, gj_ext, dtype)
            rhs_blk = assembly.rhs_at(
                problem, gi_ext[1:-1], gj_ext[1:-1], dtype
            )
            return _local_pcg(
                problem, px, py, bm, bn, a_ext, b_ext, rhs_blk, dtype,
                stencil_impl=stencil_impl, interpret=interpret,
                history=history,
            )

        mapped = shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(),
            out_specs=out_specs,
            check_vma=not (stencil_impl == "pallas" and interpret),
        )
        args = ()
    else:
        raise ValueError(f"unknown assembly_mode: {assembly_mode!r}")

    def solver(*arrays):
        out = mapped(*arrays)
        w_pad, k, diff, converged, breakdown = out[:5]
        result = PCGResult(
            w=w_pad[: problem.M + 1, : problem.N + 1],
            iters=k,
            diff=diff,
            converged=converged,
            breakdown=breakdown,
        )
        if history:
            return result, trace_of(out[5:], k)
        return result

    return jax.jit(solver), args


def build_sharded_stepper(
    problem: Problem,
    mesh: Mesh | None = None,
    dtype=jnp.float32,
    stencil_impl: str = "xla",
    abft: bool = False,
):
    """(init_fn, advance_fn) for chunked/resumable sharded solves.

    ``init_fn() -> state`` builds the iteration-0 carry; ``advance_fn(state,
    limit) -> state`` advances it until convergence/breakdown or iteration
    ``limit`` (a traced scalar: chunked runs pass k+chunk per dispatch
    without recompiling). The carry layout matches ``solver.pcg.init_state``
    — (k, w, r, p, zr, diff, converged, breakdown) — with w/r/p as global
    padded ``(g1p, g2p)`` arrays sharded ``P('x','y')`` over the mesh and
    scalars replicated, which is exactly what ``solver.checkpoint``
    persists through orbax (sharded carries save/restore with their
    shardings intact). Chunking only moves the while_loop boundary, not
    the arithmetic, so a chunked run converges in the same iteration count
    as ``build_sharded_solver``'s straight solve.

    The reference has no distributed checkpointing at all (SURVEY §5) —
    its MPI runs are start-to-finish; this is the subsystem the long
    sharded runs (the only ones long enough to need it) get natively.

    ``abft=True`` extends the carry with the four ABFT shadow scalars
    (``resilience.abft``) and runs the in-loop SDC checks; the checksum
    field ``A·1`` is built per dispatch, outside the loop, and the
    per-iteration collective cadence is byte-identical to abft=False.
    """
    if mesh is None:
        mesh = make_mesh()
    px = mesh.shape[AXIS_X]
    py = mesh.shape[AXIS_Y]
    interpret = mesh.devices.flat[0].platform != "tpu"
    g1p, g2p = padded_dims(problem.node_shape, mesh)
    bm, bn = g1p // px, g2p // py
    spec = P(AXIS_X, AXIS_Y)
    scalar = P()
    state_specs = (scalar, spec, spec, spec, scalar, scalar, scalar, scalar)
    if abft:
        state_specs = state_specs + (scalar,) * 4
    check_vma = not (stencil_impl == "pallas" and interpret)

    def init_shard(a_blk, b_blk, rhs_blk):
        a_ext = halo_extend(a_blk, px, py)
        b_ext = halo_extend(b_blk, px, py)
        _stencil, pdot, d, _maskd = _shard_ops(
            problem, px, py, bm, bn, a_ext, b_ext, dtype,
            stencil_impl, interpret,
        )
        return _shard_init(
            problem, px, py, bm, bn, pdot, d, rhs_blk, dtype, abft=abft
        )

    def advance_shard(a_blk, b_blk, state, limit):
        from poisson_ellipse_tpu.resilience.abft import checksum_field

        a_ext = halo_extend(a_blk, px, py)
        b_ext = halo_extend(b_blk, px, py)
        stencil, pdot, d, maskd = _shard_ops(
            problem, px, py, bm, bn, a_ext, b_ext, dtype,
            stencil_impl, interpret,
        )
        c = checksum_field(stencil, maskd) if abft else None
        return _shard_advance(
            problem, stencil, pdot, d, state, dtype, limit=limit,
            abft=abft, abft_c=c,
        )

    # no donation on either stepper half: a/b are re-fed every chunk, and
    # the carry cannot be donated because solver.checkpoint hands it to
    # orbax's *async* save — the serializer may still be reading the old
    # buffers while the next advance runs
    init_mapped = jax.jit(shard_map(  # tpulint: disable=TPU004
        init_shard,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=state_specs,
        check_vma=check_vma,
    ))
    advance_mapped = jax.jit(shard_map(  # tpulint: disable=TPU004
        advance_shard,
        mesh=mesh,
        in_specs=(spec, spec, state_specs, scalar),
        out_specs=state_specs,
        check_vma=check_vma,
    ))

    args = _host_sharded_args(problem, mesh, dtype, g1p, g2p, spec)

    def init_fn():
        return init_mapped(*args)

    def advance_fn(state, limit):
        # args[2] is the RHS — consumed by init only; the carry holds r
        return advance_mapped(
            args[0], args[1], state, jnp.asarray(limit, jnp.int32)
        )

    return init_fn, advance_fn


def build_sharded_recover(
    problem: Problem,
    mesh: Mesh | None = None,
    dtype=jnp.float32,
    stencil_impl: str = "xla",
    abft: bool = False,
):
    """Jitted true-residual restart over the sharded carry — the
    recovery primitive ``resilience.guard`` applies to mesh solves.

    ``recover_fn(state) -> state`` rebuilds r = rhs − A·w on every shard
    (one halo exchange + block stencil), the preconditioned residual and
    zr from ground truth, KEEPING the search direction p — the
    residual-replacement form that preserves oracle iteration parity
    (see ``resilience.guard``) — and clears the converged/breakdown
    flags. Same carry layout in and out as ``build_sharded_stepper``, so
    a recovered carry feeds straight back into ``advance_fn``. With
    ``abft`` the four shadow scalars are re-anchored to the rebuilt
    carry (one stacked psum — recovery is off the hot path).
    """
    if mesh is None:
        mesh = make_mesh()
    px = mesh.shape[AXIS_X]
    py = mesh.shape[AXIS_Y]
    interpret = mesh.devices.flat[0].platform != "tpu"
    g1p, g2p = padded_dims(problem.node_shape, mesh)
    bm, bn = g1p // px, g2p // py
    spec = P(AXIS_X, AXIS_Y)
    scalar = P()
    state_specs = (scalar, spec, spec, spec, scalar, scalar, scalar, scalar)
    if abft:
        state_specs = state_specs + (scalar,) * 4

    def recover_shard(a_blk, b_blk, rhs_blk, state):
        a_ext = halo_extend(a_blk, px, py)
        b_ext = halo_extend(b_blk, px, py)
        stencil, pdot, d, _maskd = _shard_ops(
            problem, px, py, bm, bn, a_ext, b_ext, dtype,
            stencil_impl, interpret,
        )
        k, w, _r, p, _zr, diff, _c, _bd = state[:8]
        r2 = rhs_blk - stencil(w)
        z2 = apply_dinv(r2, d)
        zr2 = pdot(z2, r2)
        out = (
            k, w, r2, p, zr2, diff,
            jnp.asarray(False), jnp.asarray(False),
        )
        if abft:
            sums = lax.psum(
                jnp.stack([jnp.sum(r2), jnp.sum(w), jnp.sum(p)]),
                (AXIS_X, AXIS_Y),
            )
            out = out + (sums[0], sums[1], sums[2], jnp.asarray(False))
        return out

    mapped = jax.jit(shard_map(  # tpulint: disable=TPU004
        recover_shard,
        mesh=mesh,
        in_specs=(spec, spec, spec, state_specs),
        out_specs=state_specs,
        check_vma=not (stencil_impl == "pallas" and interpret),
    ))
    args = _host_sharded_args(problem, mesh, dtype, g1p, g2p, spec)

    def recover_fn(state):
        return mapped(args[0], args[1], args[2], state)

    return recover_fn


def sharded_result_of(problem: Problem, state) -> PCGResult:
    """View a sharded PCG carry as a PCGResult (crops the shard padding;
    any ABFT shadow-scalar tail is ignored)."""
    k, w, _r, _p, _zr, diff, converged, breakdown = state[:8]
    return PCGResult(
        w=w[: problem.M + 1, : problem.N + 1],
        iters=k,
        diff=diff,
        converged=converged,
        breakdown=breakdown,
    )


def solve_sharded(
    problem: Problem,
    mesh: Mesh | None = None,
    dtype=jnp.float32,
    assembly_mode: str = "host",
    stencil_impl: str = "xla",
    history: bool = False,
):
    """Assemble, shard and solve over the mesh (all devices by default).
    ``history=True`` returns (PCGResult, obs.ConvergenceTrace)."""
    solver, args = build_sharded_solver(
        problem, mesh, dtype, assembly_mode, stencil_impl=stencil_impl,
        history=history,
    )
    return solver(*args)


def _pad_to(arr, g1p: int, g2p: int):
    return np.pad(
        arr, ((0, g1p - arr.shape[0]), (0, g2p - arr.shape[1]))
    )


def _host_sharded_args(problem: Problem, mesh: Mesh, dtype,
                       g1p: int, g2p: int, spec, geometry=None, theta=None):
    """Host-f64-assembled a/b/rhs, zero-padded to even shards and laid out
    over the mesh (the "host" assembly mode's operand set). ``geometry``/
    ``theta`` select the SDF quadrature assembly (``ops.assembly``)."""
    a, b, rhs = assembly.assemble_numpy(problem, geometry=geometry,
                                        theta=theta)
    np_dtype = assembly.numpy_dtype(dtype)
    sharding = NamedSharding(mesh, spec)
    return tuple(
        jax.device_put(_pad_to(arr, g1p, g2p).astype(np_dtype), sharding)
        for arr in (a, b, rhs)
    )
