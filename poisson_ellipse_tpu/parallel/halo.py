"""1-cell halo-ring exchange over the device mesh (reference layer L4).

TPU-native replacement for the reference's halo machinery: where stage2
packs first/last interior rows+columns into staging buffers and posts
``MPI_Irecv/Isend`` (``stage2-mpi/poisson_mpi_decomp.cpp:241-347``) and
stage4 additionally stages every halo through the host with
``cudaMemcpy``/``cudaMemcpy2D`` around blocking ``MPI_Sendrecv``
(``poisson_mpi_cuda2.cu:331-500``), here each direction is a single
``lax.ppermute`` of a boundary slice over ICI — device-to-device, no
packing, no host.

Design facts carried over from the reference (SURVEY §5):
- corners ride along: the y-direction exchange operates on the already
  x-extended block, so corner cells propagate in one round
  (``stage2:263-280``),
- missing neighbours (physical boundary, and here also mesh-padding edges)
  receive zeros — exactly the Dirichlet substitution of
  ``stage2:288-324``: ``lax.ppermute`` leaves non-receiving devices with
  zeros by construction, so the boundary condition costs nothing.

Must be called inside ``shard_map`` over a mesh with axes ('x', 'y').
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from poisson_ellipse_tpu.parallel.mesh import AXIS_X, AXIS_Y


def _shift_lo_to_hi(edge, axis_name: str, n: int):
    """Send each device's high edge to its successor; first device gets 0."""
    return lax.ppermute(edge, axis_name, [(i, i + 1) for i in range(n - 1)])


def _shift_hi_to_lo(edge, axis_name: str, n: int):
    """Send each device's low edge to its predecessor; last device gets 0."""
    return lax.ppermute(edge, axis_name, [(i + 1, i) for i in range(n - 1)])


def halo_extend(u, px: int, py: int, width: int = 1):
    """Extend a local (bm, bn) block to (bm+2w, bn+2w) with neighbour halos.

    Zeros appear wherever there is no neighbour (Dirichlet boundary /
    padding). One x-round then one y-round on the extended block, so the
    corner cells are correct after two rounds.

    ``width`` generalises the 5-point stencil's 1-cell ring to w-cell
    slabs — the same nearest-neighbour slab exchange that sequence/
    context parallelism (ring attention) performs on sequence shards, so
    this is the framework's reusable CP-style primitive (SURVEY §5);
    wider stencils or multi-step fusion set width>1. Requires
    width <= min(bm, bn).
    """
    if width < 1:
        raise ValueError("halo width must be >= 1")
    if width > min(u.shape):
        raise ValueError(
            f"halo width {width} exceeds block extent {min(u.shape)}"
        )
    lo_x = _shift_lo_to_hi(u[-width:, :], AXIS_X, px)
    hi_x = _shift_hi_to_lo(u[:width, :], AXIS_X, px)
    u = jnp.concatenate([lo_x, u, hi_x], axis=0)
    lo_y = _shift_lo_to_hi(u[:, -width:], AXIS_Y, py)
    hi_y = _shift_hi_to_lo(u[:, :width], AXIS_Y, py)
    return jnp.concatenate([lo_y, u, hi_y], axis=1)


def halo_extend_stacked(us, px: int, py: int, width: int = 1):
    """Halo exchange for k arrays in one message round.

    ``us`` is (k, bm, bn): k same-shape local blocks stacked on a leading
    axis; returns (k, bm+2w, bn+2w). vmap's collective batching keeps one
    ``ppermute`` per direction carrying the whole (k, w, ·) slab — so
    this is ``halo_extend``'s four messages for all k arrays together,
    halving the message count versus k separate exchanges, which matters
    on ICI where 1-cell halos are latency-bound, not bandwidth-bound.
    The fused-sharded engine uses this to ship the (z, p) pair per
    iteration (``parallel.fused_sharded``)."""
    return jax.vmap(lambda u: halo_extend(u, px, py, width=width))(us)
