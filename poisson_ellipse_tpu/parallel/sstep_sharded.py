"""Mesh-sharded s-step PCG: one s-deep halo + ONE psum per s iterations.

The communication ledger, per PCG iteration, engine by engine:

  classical (``pcg_sharded``)        4 ppermute + 2 psum
  pipelined (``pipelined_sharded``)  4 ppermute + 1 psum (stacked)
  s-step (here, s ∈ {2, 4})          4/s ppermute + **1/s psum**

One outer body advances s iterations (``ops.sstep_pcg``): it exchanges
ONE s-deep halo round — the (p, r, x) triple stacked into a single
4-ppermute slab exchange (``parallel.halo.halo_extend_stacked``; x rides
along so the residual-replacement rebuild ``r = rhs − A·x`` is local,
keeping the loop body's collective count independent of the replacement
cond) — builds the matrix-powers basis by applying the masked stencil
chain against per-depth interior masks and diagonals (all loop-invariant,
computed from the deep coefficient halos exchanged once per dispatch,
OUTSIDE the loop), reduces both Gram matrices plus the ABFT partials in
one stacked ``lax.psum``, and runs the s coordinate-space iterations
replicated (``ops.sstep_pcg.sstep_inner`` — zero further collectives).
The "exactly 1 psum + 4 ppermute per while body (= per s iterations)"
claim is jaxpr-pinned via ``obs.static_cost`` in ``tests/test_sstep.py``.
(With a sub-compute ``storage_dtype`` the exchange is one cell deeper —
(s+1) — so the p = z direction restart of ``ops.sstep_pcg`` stays local;
the collective *count* is unchanged.)

The carry layout is the classical sharded one — (k, w, r, p, zr, diff,
converged, breakdown) with (bm, bn) blocks and replicated scalars — so
``_shard_init``, ``build_sharded_recover`` and the guard's sharded
adapter machinery apply unchanged, and the ABFT shadow tail reuses
``resilience.abft``'s (S_r, S_w, S_p_pred, sdc) slots at block
granularity: shadow recurrences predict next-block column sums through
the basis coordinates (Σp⁺ = Σₘ p_c[m]·σₘ with σₘ = Σ basisₘ — the σ/τ
column-sum vectors ride the SAME Gram psum), and psum corruption is
caught by Gram-diagonal positivity (the diagonals are sums of squares:
a sign-flipped reduction is structurally negative). Both detectors ride
the existing collective — the zero-extra-collective ABFT stance of
``resilience.abft``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.ops.precision import (
    load as _pload,
    replace_every,
    resolve_storage_dtype,
    store as _pstore,
)
from poisson_ellipse_tpu.ops.sstep_pcg import (
    BASIS_SCALE,
    DEFAULT_S,
    SSTEP_CHOICES,
    basis_size,
    gram_dtype,
    shift_matrix,
    sstep_inner,
)
from poisson_ellipse_tpu.ops.stencil import apply_a_block, apply_dinv, diag_d_block
from poisson_ellipse_tpu.parallel.compat import shard_map
from poisson_ellipse_tpu.parallel.halo import halo_extend, halo_extend_stacked
from poisson_ellipse_tpu.parallel.mesh import AXIS_X, AXIS_Y, make_mesh, padded_dims
from poisson_ellipse_tpu.parallel.pcg_sharded import (
    _host_sharded_args,
    _shard_init,
    _shard_ops,
    sharded_result_of,
)
from poisson_ellipse_tpu.resilience.abft import ABFT_TINY, abft_rtol


def _crop(arr, m: int):
    """Remove ``m`` halo cells from each side of a 2-D block."""
    return arr[m:-m, m:-m] if m else arr


def make_sstep_parts(problem, mesh, dtype, s, storage_dtype=None,
                       abft: bool = False, geometry=None, theta=None):
    """Shared plumbing for the solver and stepper forms: per-shard init
    and block-advance closures over one mesh decomposition."""
    if s not in SSTEP_CHOICES:
        raise ValueError(f"s must be one of {SSTEP_CHOICES}, got {s}")
    if mesh is None:
        mesh = make_mesh()
    st = resolve_storage_dtype(storage_dtype, dtype)
    cadence = replace_every(st, dtype)
    # exchange depth: s for the basis; one deeper under sub-compute
    # storage so the p = z restart's z is available at depth s locally
    w_ex = s + (1 if st is not None else 0)
    zd = w_ex - 1  # the residual/z₀ depth
    px = mesh.shape[AXIS_X]
    py = mesh.shape[AXIS_Y]
    g1p, g2p = padded_dims(problem.node_shape, mesh)
    bm, bn = g1p // px, g2p // py
    if w_ex >= min(bm, bn):
        raise ValueError(
            f"{w_ex}-deep halos need blocks deeper than that; got "
            f"{bm}x{bn} blocks on a {px}x{py} mesh"
        )
    spec = P(AXIS_X, AXIS_Y)
    scalar = P()
    state_specs = (scalar, spec, spec, spec, scalar, scalar, scalar, scalar)
    if abft:
        state_specs = state_specs + (scalar,) * 4
    K = basis_size(s)
    iz = s + 1
    Bm = shift_matrix(s, dtype)
    h1 = jnp.asarray(problem.h1, dtype)
    h2 = jnp.asarray(problem.h2, dtype)
    hw = h1 * h2
    delta = jnp.asarray(problem.delta, dtype)
    weighted = problem.norm == "weighted"
    rtol = jnp.asarray(abft_rtol(st if st is not None else dtype), dtype)

    def depth_fields(a_deep, b_deep):
        """Per-depth loop-invariant (interior mask, masked diagonal) for
        q ∈ [0, w_ex−1] — global indices, locally computable (out-of-
        range indices fall outside the interior, the device-assembly
        convention)."""
        ix = lax.axis_index(AXIS_X)
        iy = lax.axis_index(AXIS_Y)
        masks, diags = [], []
        for q in range(w_ex):
            gi = ix * bm - q + jnp.arange(bm + 2 * q, dtype=jnp.int32)
            gj = iy * bn - q + jnp.arange(bn + 2 * q, dtype=jnp.int32)
            interior = assembly.interior_mask(problem, gi, gj)
            a_q1 = _crop(a_deep, w_ex - q - 1)  # depth q+1: diag's extent
            b_q1 = _crop(b_deep, w_ex - q - 1)
            d_q = jnp.where(interior, diag_d_block(a_q1, b_q1, h1, h2), 0.0)
            masks.append(interior.astype(dtype))
            diags.append(d_q)
        return masks, diags

    def init_shard(a_blk, b_blk, rhs_blk):
        a_ext = halo_extend(a_blk, px, py)
        b_ext = halo_extend(b_blk, px, py)
        _stencil, pdot, d, _maskd = _shard_ops(
            problem, px, py, bm, bn, a_ext, b_ext, dtype, "xla", False
        )
        state = _shard_init(
            problem, px, py, bm, bn, pdot, d, rhs_blk, dtype, abft=abft
        )
        if st is not None:
            state = (state[0],) + tuple(
                _pstore(v, st) for v in state[1:4]
            ) + state[4:]
        return state

    def advance_shard(a_blk, b_blk, rhs_blk, state, limit):
        # deep coefficient halos: exchanged once per DISPATCH, outside
        # the while body — per-depth masks/diags derive locally
        a_deep = halo_extend(a_blk, px, py, width=w_ex)
        b_deep = halo_extend(b_blk, px, py, width=w_ex)
        masks, diags = depth_fields(a_deep, b_deep)
        # rhs at the replacement rebuild's depth, also outside the loop
        rhs_ext = (
            halo_extend(rhs_blk, px, py, width=zd) if zd else rhs_blk
        )
        max_iter = jnp.minimum(
            jnp.asarray(limit, jnp.int32), problem.max_iterations
        )
        scale = jnp.asarray(1.0 / BASIS_SCALE, dtype)

        def chain(v_ext, q_in):
            """One Â = D⁻¹A application down the halo chain: depth q_in
            in, masked preconditioned depth q_in−1 out."""
            q = q_in - 1
            a_q = _crop(a_deep, w_ex - q_in)
            b_q = _crop(b_deep, w_ex - q_in)
            out = apply_a_block(v_ext, a_q, b_q, h1, h2) * masks[q]
            return apply_dinv(out, diags[q])

        def cond(state):
            k, converged, breakdown = state[0], state[6], state[7]
            go = (k < max_iter) & ~converged & ~breakdown
            if abft:
                # a flagged carry stops at once (the classical stance)
                go = go & ~state[11]
            return go

        def body(state):
            k, x_sv, r_sv, p_sv, _zr, diff0, conv0, bd0 = state[:8]
            x_own = _pload(x_sv, dtype, st)
            r_own = _pload(r_sv, dtype, st)
            p_own = _pload(p_sv, dtype, st)

            # THE block's halo round: (p, r, x) as one stacked deep slab
            # exchange — 4 ppermutes per s iterations
            ext = halo_extend_stacked(
                jnp.stack([p_own, r_own, x_own]), px, py, width=w_ex
            )
            p_ext = _crop(ext[0], w_ex - s)  # depth s: the basis root
            r_ext, x_ext = ext[1], ext[2]

            # residual replacement, entirely local: x travelled at depth
            # w_ex, so A·x is computable at depth zd without another
            # round. Containment form (a block whose s iterations span
            # a cadence multiple fires), not block-start equality —
            # chunk limits re-anchor block starts off the s-grid, and
            # an equality test would then never fire again
            km = k % cadence
            do = (k > 0) & ((km == 0) | (km > cadence - s))

            def replaced(_):
                ax = apply_a_block(
                    x_ext, a_deep, b_deep, h1, h2
                ) * masks[zd]
                return rhs_ext - ax

            r_base = lax.cond(
                do, replaced, lambda _: _crop(r_ext, 1), None
            )  # depth zd

            z0 = apply_dinv(r_base, diags[zd])
            p0 = p_ext
            if st is not None:
                # sub-compute storage: pair the tightened cadence with a
                # full p = z restart (ops.sstep_pcg's measured stance);
                # z0 is at depth s here (zd = s), so the restart is local
                p0 = jnp.where(do, z0, p0)

            # matrix-powers chains (masked, preconditioned, ρ-scaled)
            vs = [p0]
            for q in range(s, 0, -1):
                vs.append(chain(vs[-1], q) * scale)
            zs = [z0]
            for q in range(zd, zd - (s - 1), -1):
                zs.append(chain(zs[-1], q) * scale)
            # owned crops, stacked: (K, bm, bn)
            V = jnp.stack([_crop(v, (v.shape[0] - bm) // 2) for v in vs + zs])
            d0 = diags[0]
            # Gram partials accumulate at gram_dtype (f64 under x64) —
            # the measured s=4 parity requirement (ops.sstep_pcg
            # .gram_dtype); the widened entries ride the SAME psum (K²
            # scalars — collective count unchanged, bytes negligible)
            gd = gram_dtype(dtype)
            Vg = V.astype(gd)
            Vd = Vg * d0.astype(gd)

            # the block's ONE stacked psum: both Gram partials (+ ABFT)
            gm_loc = jnp.einsum("kij,lij->kl", Vg, Vd)
            ge_loc = jnp.einsum("kij,lij->kl", Vg, Vg)
            parts = [gm_loc.ravel(), ge_loc.ravel()]
            if abft:
                sigma_loc = jnp.sum(Vg, axis=(1, 2))      # σ: Σ basisₘ
                tau_loc = jnp.sum(Vd, axis=(1, 2))        # τ: Σ D·basisₘ
                extras = jnp.stack([
                    jnp.sum(x_own), jnp.sum(jnp.abs(x_own)),
                    jnp.sum(jnp.abs(p_own)), jnp.sum(jnp.abs(r_own)),
                ]).astype(gd)
                parts += [sigma_loc, tau_loc, extras]
            sums = lax.psum(jnp.concatenate(parts), (AXIS_X, AXIS_Y))
            Gm = sums[: K * K].reshape(K, K) * hw.astype(gd)
            Ge = sums[K * K : 2 * K * K].reshape(K, K)

            k_n, x_c, z_c, p_c, zr_n, diff_n, conv_n, bd_n = sstep_inner(
                Gm, Ge, Bm.astype(gd), s, k, max_iter, delta.astype(gd),
                hw.astype(gd), weighted, diff0.astype(gd), conv0, bd0, gd,
            )
            zr_n, diff_n = zr_n.astype(dtype), diff_n.astype(dtype)

            x_new = x_own + jnp.tensordot(x_c.astype(dtype), V, axes=1)
            z_new = jnp.tensordot(z_c.astype(dtype), V, axes=1)
            r_new = d0 * z_new
            p_new = jnp.tensordot(p_c.astype(dtype), V, axes=1)
            out = (
                k_n,
                _pstore(x_new, st), _pstore(r_new, st), _pstore(p_new, st),
                zr_n, diff_n, conv_n, bd_n,
            )
            if abft:
                S_r, S_x, S_p, sdc = state[8], state[9], state[10], state[11]
                off = 2 * K * K
                sigma = sums[off : off + K]
                tau = sums[off + K : off + 2 * K]
                s_x, s_absx = sums[off + 2 * K], sums[off + 2 * K + 1]
                s_absp, s_absr = sums[off + 2 * K + 2], sums[off + 2 * K + 3]
                # block-start checks against last block's predictions:
                # Σp = σ₀, Σr = τ_z₀ (r = D·z₀; skipped on replacement —
                # the rebuild legitimately changes r), Σx directly.
                # Written as ~(drift ≤ tol): NaN must read as violation.
                # Under sub-compute storage the replacement block ALSO
                # restarts p = z (the measured bf16 stance), so its Σp
                # legitimately breaks the prediction — skipped there.
                p_restarted = do if st is not None else jnp.asarray(False)
                ok_p = p_restarted | (
                    jnp.abs(sigma[0] - S_p) <= rtol * (s_absp + ABFT_TINY)
                )
                ok_r = do | (
                    jnp.abs(tau[iz] - S_r) <= rtol * (s_absr + ABFT_TINY)
                )
                ok_x = jnp.abs(s_x - S_x) <= rtol * (s_absx + ABFT_TINY)
                # Gram diagonals are sums of squares: a sign-flipped psum
                # (psum_corrupt) is structurally negative
                ok_gram = jnp.all(jnp.diagonal(Gm) >= 0.0) & jnp.all(
                    jnp.diagonal(Ge) >= 0.0
                )
                fault = ~bd_n & ~(ok_p & ok_r & ok_x & ok_gram)
                # next-block predictions through the coordinates
                keep = lambda old, new: jnp.where(bd_n, old, new)
                out = out + (
                    keep(S_r, (z_c @ tau).astype(dtype)),
                    keep(S_x, (s_x + x_c @ sigma).astype(dtype)),
                    keep(S_p, (p_c @ sigma).astype(dtype)),
                    sdc | fault,
                )
            return out

        return lax.while_loop(cond, body, state)

    init_mapped = jax.jit(shard_map(  # tpulint: disable=TPU004
        init_shard,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=state_specs,
    ))
    advance_mapped = jax.jit(shard_map(  # tpulint: disable=TPU004
        advance_shard,
        mesh=mesh,
        in_specs=(spec, spec, spec, state_specs, scalar),
        out_specs=state_specs,
    ))
    args = _host_sharded_args(problem, mesh, dtype, g1p, g2p, spec,
                              geometry=geometry, theta=theta)

    def init_fn(*arrays):
        use = arrays if arrays else args
        return init_mapped(*use[:3])

    def advance_fn(state, limit, arrays=None):
        use = arrays if arrays is not None else args
        lim = problem.max_iterations if limit is None else limit
        return advance_mapped(
            use[0], use[1], use[2], state, jnp.asarray(lim, jnp.int32)
        )

    return init_fn, advance_fn, args


def build_sstep_sharded_solver(
    problem: Problem,
    mesh: Mesh | None = None,
    dtype=jnp.float32,
    s: int = DEFAULT_S,
    storage_dtype=None,
    geometry=None,
    theta=None,
):
    """(jitted solver, args) for the whole mesh-sharded s-step solve.

    Args are the host-assembled (a, b, rhs) laid out over the mesh (the
    ``pcg_sharded`` "host" assembly mode); the result is a
    ``PCGResult`` with the shard padding cropped.
    """
    init_fn, advance_fn, args = make_sstep_parts(
        problem, mesh, dtype, s=s, storage_dtype=storage_dtype,
        geometry=geometry, theta=theta,
    )

    def solver(*arrays):
        state = advance_fn(init_fn(*arrays), None, arrays)
        return sharded_result_of(problem, state)

    return jax.jit(solver), args


def build_sstep_sharded_stepper(
    problem: Problem,
    mesh: Mesh | None = None,
    dtype=jnp.float32,
    s: int = DEFAULT_S,
    abft: bool = False,
    storage_dtype=None,
):
    """(init_fn, advance_fn) for chunked/guarded sharded s-step solves.

    Same contract as ``pcg_sharded.build_sharded_stepper`` — classical
    carry layout, traced ``limit`` honoured exactly (a mid-block limit
    masks the remaining inner steps and the next dispatch re-anchors the
    basis) — so the guard's sharded adapter, ``build_sharded_recover``
    and the checkpoint machinery compose unchanged. ``abft=True``
    appends the (S_r, S_w, S_p_pred, sdc) shadow tail (module
    docstring), anchored by ``_shard_init`` and re-anchored by
    ``build_sharded_recover`` exactly like the classical stepper's.
    """
    init_fn, advance_fn, _args = make_sstep_parts(
        problem, mesh, dtype, s=s, abft=abft, storage_dtype=storage_dtype
    )

    def init():
        return init_fn()

    def advance(state, limit):
        return advance_fn(state, limit)

    return init, advance


def solve_sstep_sharded(
    problem: Problem,
    mesh: Mesh | None = None,
    dtype=jnp.float32,
    s: int = DEFAULT_S,
    storage_dtype=None,
):
    """Assemble, shard and solve over the mesh with the s-step engine."""
    solver, args = build_sstep_sharded_solver(
        problem, mesh, dtype, s=s, storage_dtype=storage_dtype
    )
    return solver(*args)
