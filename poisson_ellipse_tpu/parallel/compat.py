"""JAX API compatibility layer for the sharding/Pallas surface.

The framework targets the current ``jax.shard_map`` + varying-mesh-axes
(vma) API, but must also run on older installs where ``shard_map`` still
lives in ``jax.experimental.shard_map``, the replication checker is the
``check_rep`` kwarg, ``lax.pcast`` does not exist, ``ShapeDtypeStruct``
has no ``vma`` parameter and the Mosaic compiler-params dataclass is
named ``TPUCompilerParams``. Every such call site in the package routes
through this module, so the version probe happens exactly once, at
import — and a future jax bump is absorbed here, not in six engines.

Pre-vma jax tracks replication implicitly (``check_rep``), so the vma
shims (``pcast_varying``, ``shape_dtype_struct``'s ``vma``) degrade to
no-ops there: the annotations they would install are only *read* by the
vma checker that those versions do not have.
"""

from __future__ import annotations

import inspect

import jax
from jax import lax

try:  # the promoted API (jax >= 0.4.34 exposes it; older raise)
    _shard_map = jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map

# the replication/vma checker kwarg was renamed check_rep -> check_vma
_CHECK_KW = (
    "check_vma"
    if "check_vma" in inspect.signature(_shard_map).parameters
    else "check_rep"
)

try:
    jax.ShapeDtypeStruct((1,), "float32", vma=frozenset())
    _SDS_HAS_VMA = True
except TypeError:
    _SDS_HAS_VMA = False

_HAS_PCAST = hasattr(lax, "pcast")


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the vma checker toggled portably.

    On vma-era jax the flag passes straight through. The pre-vma
    ``check_rep`` checker has no replication rule for ``lax.while_loop``
    — the construct at the heart of every solver here — so on those
    versions the checker is force-disabled (jax's own documented
    workaround); the full check still runs wherever the current API is
    installed.
    """
    if _CHECK_KW == "check_rep":
        check_vma = False
    return _shard_map(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        **{_CHECK_KW: check_vma},
    )


def distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized()`` where it exists; on older jax
    the same fact read from the distributed client's global state."""
    if hasattr(jax.distributed, "is_initialized"):
        return jax.distributed.is_initialized()
    try:
        from jax._src.distributed import global_state

        return global_state.client is not None
    except (ImportError, AttributeError):
        return False


def pcast_varying(x, axis_names):
    """Mark a device-invariant value as varying over ``axis_names``.

    ``lax.pcast(..., to="varying")`` where the vma system exists;
    identity elsewhere (implicit-replication jax needs no annotation for
    a while_loop carry to type-check against per-device updates).
    """
    if _HAS_PCAST:
        return lax.pcast(x, axis_names, to="varying")
    return x


def shape_dtype_struct(shape, dtype, vma=None):
    """``jax.ShapeDtypeStruct`` carrying a vma annotation when both the
    annotation and the running jax support it."""
    if vma is not None and _SDS_HAS_VMA:
        return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(vma))
    return jax.ShapeDtypeStruct(shape, dtype)


def tpu_compiler_params(**kwargs):
    """The Mosaic compiler-params dataclass under either of its names
    (``pltpu.CompilerParams``, formerly ``pltpu.TPUCompilerParams``)."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)
