"""Multi-host initialisation and mesh construction (DCN scale-out).

The reference scales across nodes with ``mpirun``-launched processes and
Spectrum MPI over the cluster fabric (``README.md:102``; SURVEY §2.7).
The TPU-native equivalent: one Python process per host calls
``jax.distributed.initialize`` (coordinator + process_id, typically all
inferred from the TPU pod metadata/launcher env), after which
``jax.devices()`` spans every host and the same ``Mesh`` + ``shard_map``
code from ``parallel.pcg_sharded`` runs unchanged — XLA routes the halo
``ppermute`` over ICI within a slice and DCN across slices; nothing in
the solver needs to know which.

Thin by design: the entire MPI lifecycle surface of the reference
(``MPI_Init/Comm_rank/Comm_size/Finalize``, ``poisson_mpi_cuda2.cu:
986-990,1036``) collapses into initialize()/shutdown() here.
"""

from __future__ import annotations

from typing import Optional

import jax

from poisson_ellipse_tpu.parallel.compat import distributed_is_initialized
from poisson_ellipse_tpu.parallel.mesh import make_mesh


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[list[int]] = None,
) -> None:
    """``MPI_Init`` analog. On TPU pods all arguments are usually inferred
    from the environment (TPU metadata / launcher-set variables); pass
    them explicitly for other fabrics.

    Call exactly once per process, before any other jax API touches the
    backend. Idempotence guard: a second call is a no-op rather than an
    error, matching how the reference tolerates only one MPI_Init.
    """
    if distributed_is_initialized():
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )


def shutdown_multihost() -> None:
    """``MPI_Finalize`` analog."""
    if distributed_is_initialized():
        jax.distributed.shutdown()


def global_mesh():
    """Near-square 2D mesh over every device of every host.

    ``jax.devices()`` is globally consistent across processes after
    ``initialize_multihost``, so each host builds the identical mesh —
    the multi-host replacement for the reference's per-rank
    ``choose_process_grid`` call (``stage2-mpi/poisson_mpi_decomp.cpp:
    60-64``).
    """
    return make_mesh(jax.devices())


def process_info() -> tuple[int, int]:
    """(process_id, num_processes) — the Comm_rank/Comm_size analog."""
    return jax.process_index(), jax.process_count()
