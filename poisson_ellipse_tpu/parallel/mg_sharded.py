"""Mesh-sharded mg-pcg / cheb-pcg: the V-cycle under shard_map.

The same classical sharded PCG loop as ``parallel.pcg_sharded`` — the
scalar-collective cadence is UNTOUCHED: one denom psum plus ONE stacked
convergence-word psum per iteration, exactly the classical discipline —
with the preconditioner swapped for the layout-generic V-cycle /
Chebyshev cores of ``mg`` running on per-shard blocks. Every piece of
preconditioner communication is a nearest-neighbour halo exchange
(``parallel.halo.halo_extend`` — 4 ``lax.ppermute``): Chebyshev steps
pay one halo per stencil application, transfers one halo each (the
9-point full-weighting gather and the odd-node bilinear straddle both
reach exactly one cell across the shard edge). ``halos_per_precond``
is the static budget; ``tests/test_mg.py`` pins the jaxpr's psum AND
ppermute counts against it via ``obs.static_cost``.

Level geometry: the fine node grid pads to a multiple of
``(px·2^{L−1}, py·2^{L−1})`` so every level's shard block stays even
and node-nested (coarse local (ic, jc) at fine local (2ic, 2jc) on the
same device — coarsening never moves data between shards). Level
coefficients are coarsened on the HOST in f64 from the same hierarchy
the single-chip engine uses (``mg.coarsen.coefficient_hierarchy`` — one
coarsening, two layouts), padded per level and laid out over the mesh.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from poisson_ellipse_tpu.mg import cheby, coarsen as mg_coarsen, vcycle
from poisson_ellipse_tpu.mg.transfer import prolong_block, restrict_block
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.ops.stencil import (
    apply_a_block,
    apply_dinv,
    diag_d_block,
)
from poisson_ellipse_tpu.parallel.compat import shard_map
from poisson_ellipse_tpu.parallel.halo import halo_extend
from poisson_ellipse_tpu.parallel.mesh import AXIS_X, AXIS_Y, make_mesh
from poisson_ellipse_tpu.parallel.pcg_sharded import (
    _shard_advance,
    _shard_init,
    _shard_ops,
)
from poisson_ellipse_tpu.solver.pcg import PCGResult


def halos_per_precond(levels: int, nu: int = vcycle.DEFAULT_NU,
                      coarse_degree: int = vcycle.DEFAULT_COARSE_DEGREE,
                      ) -> int:
    """Halo exchanges one preconditioner application costs (each is 4
    ppermutes). Per non-coarsest level: ν−1 pre-smooth applies + 1
    residual + 1 restrict + 1 prolong + ν post-smooth applies = 2ν+2;
    coarsest: degree−1 applies. The static budget the jaxpr pin checks."""
    if levels == 1:
        return coarse_degree - 1
    return (levels - 1) * (2 * nu + 2) + coarse_degree - 1


def mg_padded_dims(problem: Problem, mesh: Mesh, levels: int,
                   ) -> tuple[int, int]:
    """Fine padded dims divisible by (px·2^{L−1}, py·2^{L−1}).

    M divisible by 2^{L−1} (the level-count rule) makes the rounded-up
    size automatically ≥ M + 2^{L−1}, so every level's padded grid
    covers its node grid: g1p/2ˡ ≥ M/2ˡ + 1."""
    px = mesh.shape[AXIS_X]
    py = mesh.shape[AXIS_Y]
    ux = px << (levels - 1)
    uy = py << (levels - 1)
    g1, g2 = problem.node_shape
    return (-(-g1 // ux)) * ux, (-(-g2 // uy)) * uy


def _interior_mask(Ml: int, Nl: int, gi, gj):
    """Interior mask of a level's GLOBAL node grid at block indices
    (zeros the Dirichlet ring and all shard padding)."""
    return (
        ((gi >= 1) & (gi <= Ml - 1))[:, None]
        & ((gj >= 1) & (gj <= Nl - 1))[None, :]
    )


def build_mg_sharded_solver(
    problem: Problem,
    mesh: Mesh | None = None,
    dtype=jnp.float32,
    kind: str = "mg",
    config=None,
    history: bool = False,
):
    """(jitted solver_fn, args) for the mesh-sharded preconditioned solve.

    ``kind`` "mg" (V-cycle) or "cheb" (degree-k polynomial). The
    spectral interval comes from the same single-chip Lanczos probe the
    single-chip engines use (the operator — and so its spectrum — is
    mesh-independent), the hierarchy from the same host-f64 coarsening.
    Args are the per-level (a, b) arrays plus the fine RHS, all padded
    and laid out over the mesh.
    """
    from poisson_ellipse_tpu.mg.engine import resolve_config

    if mesh is None:
        mesh = make_mesh()
    if kind not in ("mg", "cheb"):
        raise ValueError(f"unknown preconditioner kind: {kind!r}")
    a0, b0, rhs0 = assembly.assemble(problem, dtype)
    cfg = config if config is not None else resolve_config(
        problem, a0, b0, rhs0, kind
    )
    # a supplied config with the dataclass-default degenerate interval
    # (lo=0.0) falls back to the Gershgorin interval instead of crashing
    # the Chebyshev setup at trace time — same stance as mg.engine
    lo, hi = cheby.clip_interval((cfg.lo, cfg.hi))
    if (lo, hi) != (cfg.lo, cfg.hi):
        cfg = dataclasses.replace(cfg, lo=lo, hi=hi)
    levels = cfg.levels if kind == "mg" else 1
    hier = mg_coarsen.coefficient_hierarchy(problem)[:levels]

    px = mesh.shape[AXIS_X]
    py = mesh.shape[AXIS_Y]
    interpret = mesh.devices.flat[0].platform != "tpu"
    g1p, g2p = mg_padded_dims(problem, mesh, levels)
    bm, bn = g1p // px, g2p // py
    spec = P(AXIS_X, AXIS_Y)
    sharding = NamedSharding(mesh, spec)
    np_dtype = assembly.numpy_dtype(dtype)

    def _pad_to(arr, r, c):
        return np.pad(arr, ((0, r - arr.shape[0]), (0, c - arr.shape[1])))

    # fine operands + one (a, b) pair per level, each padded to its own
    # level dims (divisible by the mesh by construction) and sharded
    args = [
        jax.device_put(
            _pad_to(arr, g1p, g2p).astype(np_dtype), sharding
        )
        for arr in (hier[0]["a"], hier[0]["b"],
                    assembly.assemble_numpy(problem)[2])
    ]
    for l in range(1, levels):
        for key in ("a", "b"):
            args.append(jax.device_put(
                _pad_to(hier[l][key], g1p >> l, g2p >> l).astype(np_dtype),
                sharding,
            ))
    args = tuple(args)

    smooth_lo, smooth_hi = cheby.smoother_interval(cfg.hi)

    def _make_precond(level_exts):
        """Block-layout LevelOps from the halo-extended per-level
        coefficient blocks, composed into the generic V-cycle core."""
        ops = []
        for l, (a_ext, b_ext) in enumerate(level_exts):
            Ml, Nl = hier[l]["M"], hier[l]["N"]
            h1 = jnp.asarray(hier[l]["h1"], dtype)
            h2 = jnp.asarray(hier[l]["h2"], dtype)
            bml, bnl = bm >> l, bn >> l
            ix = lax.axis_index(AXIS_X)
            iy = lax.axis_index(AXIS_Y)
            gi = ix * bml + jnp.arange(bml, dtype=jnp.int32)
            gj = iy * bnl + jnp.arange(bnl, dtype=jnp.int32)
            mask = _interior_mask(Ml, Nl, gi, gj).astype(dtype)
            d = jnp.where(
                mask.astype(bool), diag_d_block(a_ext, b_ext, h1, h2), 0.0
            )
            last = l == len(level_exts) - 1

            def make_apply(a_ext=a_ext, b_ext=b_ext, h1=h1, h2=h2,
                           mask=mask):
                return lambda x: (
                    apply_a_block(halo_extend(x, px, py), a_ext, b_ext,
                                  h1, h2) * mask
                )

            def make_dinv(d=d):
                return lambda x: apply_dinv(x, d)

            if last:
                restrict = prolong = None
            else:
                Mc, Nc = hier[l + 1]["M"], hier[l + 1]["N"]
                bmc, bnc = bml // 2, bnl // 2
                gic = ix * bmc + jnp.arange(bmc, dtype=jnp.int32)
                gjc = iy * bnc + jnp.arange(bnc, dtype=jnp.int32)
                cmask = _interior_mask(Mc, Nc, gic, gjc).astype(dtype)

                def restrict(r, cmask=cmask):
                    return restrict_block(halo_extend(r, px, py)) * cmask

                def prolong(ec, mask=mask, shape=(bml, bnl)):
                    return prolong_block(
                        halo_extend(ec, px, py), shape
                    ) * mask

            ops.append(vcycle.LevelOps(
                apply_a=make_apply(),
                dinv=make_dinv(),
                smooth_lo=smooth_lo,
                smooth_hi=cfg.hi,
                solve_lo=min(cfg.lo * (4.0 ** l), smooth_hi / 4.0),
                restrict=restrict,
                prolong=prolong,
            ))
        if kind == "cheb":
            fine = ops[0]
            return lambda r: cheby.chebyshev_apply(
                fine.apply_a, fine.dinv, r, cfg.lo, cfg.hi, cfg.cheb_degree
            )
        return vcycle.make_vcycle(
            ops, nu=cfg.nu, coarse_degree=cfg.coarse_degree
        )

    out_specs = (spec, P(), P(), P(), P()) + ((P(),) * 4 if history else ())

    def shard_fn(a_blk, b_blk, rhs_blk, *level_blks):
        # one halo exchange per level's coefficients, once per SOLVE
        # (the loop and the V-cycle reuse the extended blocks)
        level_exts = [(halo_extend(a_blk, px, py),
                       halo_extend(b_blk, px, py))]
        for l in range(1, levels):
            al, bl = level_blks[2 * (l - 1)], level_blks[2 * (l - 1) + 1]
            level_exts.append((halo_extend(al, px, py),
                               halo_extend(bl, px, py)))
        precond = _make_precond(level_exts)
        stencil, pdot, d = _shard_ops(
            problem, px, py, bm, bn, level_exts[0][0], level_exts[0][1],
            dtype, "xla", interpret,
        )
        state0 = _shard_init(
            problem, px, py, bm, bn, pdot, d, rhs_blk, dtype,
            history=history, precond=precond,
        )
        out = _shard_advance(
            problem, stencil, pdot, d, state0, dtype, history=history,
            precond=precond,
        )
        k, w = out[0], out[1]
        diff, converged, breakdown = out[5], out[6], out[7]
        return (w, k, diff, converged, breakdown) + tuple(out[8:])

    mapped = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(spec,) * len(args),
        out_specs=out_specs,
    )

    def solver(*arrays):
        out = mapped(*arrays)
        w_pad, k, diff, converged, breakdown = out[:5]
        result = PCGResult(
            w=w_pad[: problem.M + 1, : problem.N + 1],
            iters=k,
            diff=diff,
            converged=converged,
            breakdown=breakdown,
        )
        if history:
            from poisson_ellipse_tpu.obs.convergence import trace_of

            return result, trace_of(out[5:], k)
        return result

    return jax.jit(solver), args


def solve_mg_sharded(problem: Problem, mesh: Mesh | None = None,
                     dtype=jnp.float32, kind: str = "mg",
                     history: bool = False):
    """Assemble, shard and solve with the mesh V-cycle/Chebyshev."""
    solver, args = build_mg_sharded_solver(
        problem, mesh, dtype, kind=kind, history=history
    )
    return solver(*args)
