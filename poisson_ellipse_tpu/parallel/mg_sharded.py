"""Mesh-sharded mg-pcg / cheb-pcg: the V-cycle under shard_map.

The same classical sharded PCG loop as ``parallel.pcg_sharded`` — the
scalar-collective cadence is UNTOUCHED: one denom psum plus ONE stacked
convergence-word psum per iteration, exactly the classical discipline —
with the preconditioner swapped for the layout-generic V-cycle /
Chebyshev cores of ``mg`` running on per-shard blocks. Every piece of
preconditioner communication is a nearest-neighbour halo exchange
(``parallel.halo.halo_extend`` — 4 ``lax.ppermute``): Chebyshev steps
pay one halo per stencil application, transfers one halo each (the
9-point full-weighting gather and the odd-node bilinear straddle both
reach exactly one cell across the shard edge). ``halos_per_precond``
is the static budget; ``tests/test_mg.py`` pins the jaxpr's psum AND
ppermute counts against it via ``obs.static_cost``.

Level geometry: the fine node grid pads to a multiple of
``(px·2^{L−1}, py·2^{L−1})`` so every level's shard block stays even
and node-nested (coarse local (ic, jc) at fine local (2ic, 2jc) on the
same device — coarsening never moves data between shards). Level
coefficients are coarsened on the HOST in f64 from the same hierarchy
the single-chip engine uses (``mg.coarsen.coefficient_hierarchy`` — one
coarsening, two layouts), padded per level and laid out over the mesh.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from poisson_ellipse_tpu.mg import cheby, coarsen as mg_coarsen, vcycle
from poisson_ellipse_tpu.mg.transfer import prolong_block, restrict_block
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.ops.stencil import (
    apply_a_block,
    apply_dinv,
    diag_d_block,
)
from poisson_ellipse_tpu.parallel.compat import shard_map
from poisson_ellipse_tpu.parallel.halo import halo_extend
from poisson_ellipse_tpu.parallel.mesh import AXIS_X, AXIS_Y, make_mesh
from poisson_ellipse_tpu.parallel.pcg_sharded import (
    _shard_advance,
    _shard_init,
    _shard_ops,
)
from poisson_ellipse_tpu.solver.pcg import PCGResult


def halos_per_precond(levels: int, nu: int = vcycle.DEFAULT_NU,
                      coarse_degree: int = vcycle.DEFAULT_COARSE_DEGREE,
                      ) -> int:
    """Halo exchanges one preconditioner application costs (each is 4
    ppermutes). Per non-coarsest level: ν−1 pre-smooth applies + 1
    residual + 1 restrict + 1 prolong + ν post-smooth applies = 2ν+2;
    coarsest: degree−1 applies. The static budget the jaxpr pin checks."""
    if levels == 1:
        return coarse_degree - 1
    return (levels - 1) * (2 * nu + 2) + coarse_degree - 1


def mg_padded_dims(problem: Problem, mesh: Mesh, levels: int,
                   ) -> tuple[int, int]:
    """Fine padded dims divisible by (px·2^{L−1}, py·2^{L−1}).

    M divisible by 2^{L−1} (the level-count rule) makes the rounded-up
    size automatically ≥ M + 2^{L−1}, so every level's padded grid
    covers its node grid: g1p/2ˡ ≥ M/2ˡ + 1."""
    px = mesh.shape[AXIS_X]
    py = mesh.shape[AXIS_Y]
    ux = px << (levels - 1)
    uy = py << (levels - 1)
    g1, g2 = problem.node_shape
    return (-(-g1 // ux)) * ux, (-(-g2 // uy)) * uy


def _interior_mask(Ml: int, Nl: int, gi, gj):
    """Interior mask of a level's GLOBAL node grid at block indices
    (zeros the Dirichlet ring and all shard padding)."""
    return (
        ((gi >= 1) & (gi <= Ml - 1))[:, None]
        & ((gj >= 1) & (gj <= Nl - 1))[None, :]
    )


class _MgShardSetup:
    """Everything the mesh-preconditioned loop needs, factored once so
    the whole-solve form and the chunked stepper (the guard's resumable
    surface) cannot drift: level operands laid out over the mesh, the
    per-shard precond factory, and the geometry."""

    def __init__(self, problem: Problem, mesh: Mesh, dtype, kind: str,
                 config, geometry=None, theta=None):
        from poisson_ellipse_tpu.mg.engine import resolve_config

        if kind not in ("mg", "cheb"):
            raise ValueError(f"unknown preconditioner kind: {kind!r}")
        a0, b0, rhs0 = assembly.assemble(problem, dtype, geometry=geometry,
                                         theta=theta)
        cfg = config if config is not None else resolve_config(
            problem, a0, b0, rhs0, kind
        )
        # a supplied config with the dataclass-default degenerate interval
        # (lo=0.0) falls back to the Gershgorin interval instead of
        # crashing the Chebyshev setup at trace time — same stance as
        # mg.engine
        lo, hi = cheby.clip_interval((cfg.lo, cfg.hi))
        if (lo, hi) != (cfg.lo, cfg.hi):
            cfg = dataclasses.replace(cfg, lo=lo, hi=hi)
        self.problem = problem
        self.mesh = mesh
        self.dtype = dtype
        self.kind = kind
        self.cfg = cfg
        self.levels = cfg.levels if kind == "mg" else 1
        self.hier = mg_coarsen.coefficient_hierarchy(
            problem, geometry=geometry, theta=theta
        )[:self.levels]
        self.px = mesh.shape[AXIS_X]
        self.py = mesh.shape[AXIS_Y]
        self.interpret = mesh.devices.flat[0].platform != "tpu"
        self.g1p, self.g2p = mg_padded_dims(problem, mesh, self.levels)
        self.bm, self.bn = self.g1p // self.px, self.g2p // self.py
        self.spec = P(AXIS_X, AXIS_Y)
        sharding = NamedSharding(mesh, self.spec)
        np_dtype = assembly.numpy_dtype(dtype)

        def _pad_to(arr, r, c):
            return np.pad(
                arr, ((0, r - arr.shape[0]), (0, c - arr.shape[1]))
            )

        # fine operands + one (a, b) pair per level, each padded to its
        # own level dims (divisible by the mesh by construction), sharded
        args = [
            jax.device_put(
                _pad_to(arr, self.g1p, self.g2p).astype(np_dtype), sharding
            )
            for arr in (self.hier[0]["a"], self.hier[0]["b"],
                        assembly.assemble_numpy(problem, geometry=geometry,
                                                theta=theta)[2])
        ]
        for l in range(1, self.levels):
            for key in ("a", "b"):
                args.append(jax.device_put(
                    _pad_to(
                        self.hier[l][key], self.g1p >> l, self.g2p >> l
                    ).astype(np_dtype),
                    sharding,
                ))
        self.args = tuple(args)
        self.smooth_lo, self.smooth_hi = cheby.smoother_interval(cfg.hi)

    def extend_levels(self, a_blk, b_blk, level_blks):
        """One halo exchange per level's coefficients, once per dispatch
        (the loop and the V-cycle reuse the extended blocks)."""
        px, py = self.px, self.py
        level_exts = [(halo_extend(a_blk, px, py),
                       halo_extend(b_blk, px, py))]
        for l in range(1, self.levels):
            al, bl = level_blks[2 * (l - 1)], level_blks[2 * (l - 1) + 1]
            level_exts.append((halo_extend(al, px, py),
                               halo_extend(bl, px, py)))
        return level_exts

    def level_ops(self, level_exts) -> list[vcycle.LevelOps]:
        """Block-layout LevelOps from the halo-extended per-level
        coefficient blocks — the raw per-level closures both cycle
        shapes compose: ``make_precond`` into the V-cycle preconditioner
        and ``build_fmg_sharded_solver`` into the F-cycle."""
        px, py, bm, bn = self.px, self.py, self.bm, self.bn
        hier, cfg, dtype = self.hier, self.cfg, self.dtype
        smooth_lo, smooth_hi = self.smooth_lo, self.smooth_hi
        ops = []
        for l, (a_ext, b_ext) in enumerate(level_exts):
            Ml, Nl = hier[l]["M"], hier[l]["N"]
            h1 = jnp.asarray(hier[l]["h1"], dtype)
            h2 = jnp.asarray(hier[l]["h2"], dtype)
            bml, bnl = bm >> l, bn >> l
            ix = lax.axis_index(AXIS_X)
            iy = lax.axis_index(AXIS_Y)
            gi = ix * bml + jnp.arange(bml, dtype=jnp.int32)
            gj = iy * bnl + jnp.arange(bnl, dtype=jnp.int32)
            mask = _interior_mask(Ml, Nl, gi, gj).astype(dtype)
            d = jnp.where(
                mask.astype(bool), diag_d_block(a_ext, b_ext, h1, h2), 0.0
            )
            last = l == len(level_exts) - 1

            def make_apply(a_ext=a_ext, b_ext=b_ext, h1=h1, h2=h2,
                           mask=mask):
                return lambda x: (
                    apply_a_block(halo_extend(x, px, py), a_ext, b_ext,
                                  h1, h2) * mask
                )

            def make_dinv(d=d):
                return lambda x: apply_dinv(x, d)

            if last:
                restrict = prolong = None
            else:
                Mc, Nc = hier[l + 1]["M"], hier[l + 1]["N"]
                bmc, bnc = bml // 2, bnl // 2
                gic = ix * bmc + jnp.arange(bmc, dtype=jnp.int32)
                gjc = iy * bnc + jnp.arange(bnc, dtype=jnp.int32)
                cmask = _interior_mask(Mc, Nc, gic, gjc).astype(dtype)

                def restrict(r, cmask=cmask):
                    return restrict_block(halo_extend(r, px, py)) * cmask

                def prolong(ec, mask=mask, shape=(bml, bnl)):
                    return prolong_block(
                        halo_extend(ec, px, py), shape
                    ) * mask

            ops.append(vcycle.LevelOps(
                apply_a=make_apply(),
                dinv=make_dinv(),
                smooth_lo=smooth_lo,
                smooth_hi=cfg.hi,
                solve_lo=min(cfg.lo * (4.0 ** l), smooth_hi / 4.0),
                restrict=restrict,
                prolong=prolong,
            ))
        return ops

    def make_precond(self, level_exts):
        """The per-shard ``z = M⁻¹ r`` applier: the block LevelOps
        composed into the generic V-cycle core (or the standalone
        Chebyshev polynomial for kind="cheb")."""
        cfg = self.cfg
        ops = self.level_ops(level_exts)
        if self.kind == "cheb":
            fine = ops[0]
            return lambda r: cheby.chebyshev_apply(
                fine.apply_a, fine.dinv, r, cfg.lo, cfg.hi, cfg.cheb_degree
            )
        return vcycle.make_vcycle(
            ops, nu=cfg.nu, coarse_degree=cfg.coarse_degree
        )


def build_mg_sharded_solver(
    problem: Problem,
    mesh: Mesh | None = None,
    dtype=jnp.float32,
    kind: str = "mg",
    config=None,
    history: bool = False,
    geometry=None,
    theta=None,
):
    """(jitted solver_fn, args) for the mesh-sharded preconditioned solve.

    ``kind`` "mg" (V-cycle) or "cheb" (degree-k polynomial). The
    spectral interval comes from the same single-chip Lanczos probe the
    single-chip engines use (the operator — and so its spectrum — is
    mesh-independent), the hierarchy from the same host-f64 coarsening.
    Args are the per-level (a, b) arrays plus the fine RHS, all padded
    and laid out over the mesh.
    """
    if mesh is None:
        mesh = make_mesh()
    setup = _MgShardSetup(problem, mesh, dtype, kind, config,
                          geometry=geometry, theta=theta)
    px, py, bm, bn = setup.px, setup.py, setup.bm, setup.bn
    interpret = setup.interpret
    spec = setup.spec
    args = setup.args

    out_specs = (spec, P(), P(), P(), P()) + ((P(),) * 4 if history else ())

    def shard_fn(a_blk, b_blk, rhs_blk, *level_blks):
        level_exts = setup.extend_levels(a_blk, b_blk, level_blks)
        precond = setup.make_precond(level_exts)
        stencil, pdot, d, _maskd = _shard_ops(
            problem, px, py, bm, bn, level_exts[0][0], level_exts[0][1],
            dtype, "xla", interpret,
        )
        state0 = _shard_init(
            problem, px, py, bm, bn, pdot, d, rhs_blk, dtype,
            history=history, precond=precond,
        )
        out = _shard_advance(
            problem, stencil, pdot, d, state0, dtype, history=history,
            precond=precond,
        )
        k, w = out[0], out[1]
        diff, converged, breakdown = out[5], out[6], out[7]
        return (w, k, diff, converged, breakdown) + tuple(out[8:])

    mapped = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(spec,) * len(args),
        out_specs=out_specs,
    )

    def solver(*arrays):
        out = mapped(*arrays)
        w_pad, k, diff, converged, breakdown = out[:5]
        result = PCGResult(
            w=w_pad[: problem.M + 1, : problem.N + 1],
            iters=k,
            diff=diff,
            converged=converged,
            breakdown=breakdown,
        )
        if history:
            from poisson_ellipse_tpu.obs.convergence import trace_of

            return result, trace_of(out[5:], k)
        return result

    return jax.jit(solver), args


def build_mg_sharded_stepper(
    problem: Problem,
    mesh: Mesh | None = None,
    dtype=jnp.float32,
    kind: str = "mg",
    config=None,
    abft: bool = False,
):
    """(init_fn, advance_fn, recover_fn) for chunked/resumable
    mesh-preconditioned solves — the ``parallel.pcg_sharded.
    build_sharded_stepper`` contract with the V-cycle/Chebyshev in the
    ``z = M⁻¹r`` slot, which is what lets ``resilience.guard`` chunk,
    health-check and recover mg-pcg/cheb-pcg mesh solves exactly like
    the classical stepper (carry layout is shared; only the preconditioner
    and the per-level operands differ). ``abft=True`` appends the four
    ABFT shadow scalars and runs the in-loop SDC checks at the same
    collective cadence (``resilience.abft``).

    ``recover_fn`` is the true-residual restart under the SAME M —
    z and zr are rebuilt through the preconditioner, so the restarted
    recurrence still describes M⁻¹A (the guard's parity contract).
    """
    if mesh is None:
        mesh = make_mesh()
    setup = _MgShardSetup(problem, mesh, dtype, kind, config)
    px, py, bm, bn = setup.px, setup.py, setup.bm, setup.bn
    interpret = setup.interpret
    spec = setup.spec
    args = setup.args
    scalar = P()
    state_specs = (scalar, spec, spec, spec, scalar, scalar, scalar, scalar)
    if abft:
        state_specs = state_specs + (scalar,) * 4
    n_level_args = len(args) - 3

    def init_shard(a_blk, b_blk, rhs_blk, *level_blks):
        level_exts = setup.extend_levels(a_blk, b_blk, level_blks)
        precond = setup.make_precond(level_exts)
        _stencil, pdot, d, _maskd = _shard_ops(
            problem, px, py, bm, bn, level_exts[0][0], level_exts[0][1],
            dtype, "xla", interpret,
        )
        return _shard_init(
            problem, px, py, bm, bn, pdot, d, rhs_blk, dtype,
            precond=precond, abft=abft,
        )

    def advance_shard(a_blk, b_blk, state, limit, *level_blks):
        from poisson_ellipse_tpu.resilience.abft import checksum_field

        level_exts = setup.extend_levels(a_blk, b_blk, level_blks)
        precond = setup.make_precond(level_exts)
        stencil, pdot, d, maskd = _shard_ops(
            problem, px, py, bm, bn, level_exts[0][0], level_exts[0][1],
            dtype, "xla", interpret,
        )
        c = checksum_field(stencil, maskd) if abft else None
        return _shard_advance(
            problem, stencil, pdot, d, state, dtype, limit=limit,
            precond=precond, abft=abft, abft_c=c,
        )

    def recover_shard(a_blk, b_blk, rhs_blk, state, *level_blks):
        level_exts = setup.extend_levels(a_blk, b_blk, level_blks)
        precond = setup.make_precond(level_exts)
        stencil, pdot, _d, _maskd = _shard_ops(
            problem, px, py, bm, bn, level_exts[0][0], level_exts[0][1],
            dtype, "xla", interpret,
        )
        k, w, _r, p, _zr, diff, _c, _bd = state[:8]
        r2 = rhs_blk - stencil(w)
        z2 = precond(r2)
        zr2 = pdot(z2, r2)
        out = (
            k, w, r2, p, zr2, diff,
            jnp.asarray(False), jnp.asarray(False),
        )
        if abft:
            sums = lax.psum(
                jnp.stack([jnp.sum(r2), jnp.sum(w), jnp.sum(p)]),
                (AXIS_X, AXIS_Y),
            )
            out = out + (sums[0], sums[1], sums[2], jnp.asarray(False))
        return out

    level_specs = (spec,) * n_level_args
    # no donation on any half: operands are re-fed every chunk and the
    # carry doubles as the guard's rollback point
    init_mapped = jax.jit(shard_map(
        init_shard,
        mesh=mesh,
        in_specs=(spec, spec, spec) + level_specs,
        out_specs=state_specs,
    ))
    advance_mapped = jax.jit(shard_map(
        advance_shard,
        mesh=mesh,
        in_specs=(spec, spec, state_specs, scalar) + level_specs,
        out_specs=state_specs,
    ))
    recover_mapped = jax.jit(shard_map(
        recover_shard,
        mesh=mesh,
        in_specs=(spec, spec, spec, state_specs) + level_specs,
        out_specs=state_specs,
    ))

    def init_fn():
        return init_mapped(*args[:3], *args[3:])

    def advance_fn(state, limit):
        return advance_mapped(
            args[0], args[1], state, jnp.asarray(limit, jnp.int32),
            *args[3:],
        )

    def recover_fn(state):
        return recover_mapped(args[0], args[1], args[2], state, *args[3:])

    return init_fn, advance_fn, recover_fn


def solve_mg_sharded(problem: Problem, mesh: Mesh | None = None,
                     dtype=jnp.float32, kind: str = "mg",
                     history: bool = False):
    """Assemble, shard and solve with the mesh V-cycle/Chebyshev."""
    solver, args = build_mg_sharded_solver(
        problem, mesh, dtype, kind=kind, history=history
    )
    return solver(*args)


# -- full multigrid (the F-cycle solver), sharded ----------------------------


def halos_per_fcycle(levels: int, nu: int = vcycle.DEFAULT_NU,
                     coarse_degree: int = vcycle.DEFAULT_COARSE_DEGREE,
                     n_vcycles: int = 2) -> int:
    """Halo exchanges one sharded F-cycle costs (each 4 ppermutes) —
    the static collective budget the jaxpr pin in ``tests/test_fmg.py``
    checks via ``obs.static_cost``. Per level l < L−1: one RHS restrict
    + one prolong + n_vcycles × (1 residual apply + the V-cycle over
    levels[l:]); coarsest: the degree−1 direct sweep. The F-cycle adds
    ZERO scalar collectives — psums stay the handoff loop's classical
    cadence, exactly the mg-pcg discipline."""
    if levels == 1:
        return coarse_degree - 1
    total = coarse_degree - 1  # the coarsest direct sweep
    for l in range(levels - 1):
        total += 2  # restrict f_l down + prolong x_{l+1} up
        total += n_vcycles * (1 + halos_per_precond(
            levels - l, nu, coarse_degree
        ))
    return total


def build_fmg_sharded_solver(
    problem: Problem,
    mesh: Mesh | None = None,
    dtype=jnp.float32,
    config=None,
    geometry=None,
    theta=None,
):
    """(jitted solver_fn, args) for the mesh-sharded full-multigrid solve.

    The F-cycle of ``mg.fmg`` over the block LevelOps of
    :class:`_MgShardSetup` — per-level transfers and smoothing steps pay
    one halo exchange each (``halos_per_fcycle`` is the pinned budget),
    never a scalar collective — followed by the verified handoff: the
    classical sharded mg-pcg loop warm-started at the F-cycle solution
    (``_shard_init(x0_blk=...)`` rebuilds the TRUE per-shard residual),
    running to the same δ rule as every other engine. Level padding,
    coarsening and the Lanczos interval are exactly the mg-pcg setup's.

    ``config`` is an ``mg.fmg.FMGConfig`` (None: grid-derived defaults
    with the probed interval).
    """
    from poisson_ellipse_tpu.mg.fmg import (
        FMGConfig,
        make_fcycle,
        resolve_fmg_config,
    )

    if mesh is None:
        mesh = make_mesh()
    a0, b0, rhs0 = assembly.assemble(problem, dtype, geometry=geometry,
                                     theta=theta)
    fmg_cfg = resolve_fmg_config(problem, a0, b0, rhs0, config)
    assert isinstance(fmg_cfg, FMGConfig)
    setup = _MgShardSetup(problem, mesh, dtype, "mg",
                          fmg_cfg.precond_config(), geometry=geometry,
                          theta=theta)
    px, py, bm, bn = setup.px, setup.py, setup.bm, setup.bn
    interpret = setup.interpret
    spec = setup.spec
    args = setup.args

    def shard_fn(a_blk, b_blk, rhs_blk, *level_blks):
        level_exts = setup.extend_levels(a_blk, b_blk, level_blks)
        ops = setup.level_ops(level_exts)
        x0 = make_fcycle(
            ops, nu=fmg_cfg.nu, coarse_degree=fmg_cfg.coarse_degree,
            n_vcycles=fmg_cfg.n_vcycles,
        )(rhs_blk)
        precond = vcycle.make_vcycle(
            ops, nu=fmg_cfg.nu, coarse_degree=fmg_cfg.coarse_degree
        )
        stencil, pdot, d, _maskd = _shard_ops(
            problem, px, py, bm, bn, level_exts[0][0], level_exts[0][1],
            dtype, "xla", interpret,
        )
        state0 = _shard_init(
            problem, px, py, bm, bn, pdot, d, rhs_blk, dtype,
            precond=precond, x0_blk=x0, stencil=stencil,
        )
        out = _shard_advance(
            problem, stencil, pdot, d, state0, dtype, precond=precond,
        )
        k, w = out[0], out[1]
        diff, converged, breakdown = out[5], out[6], out[7]
        return (w, k, diff, converged, breakdown)

    mapped = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(spec,) * len(args),
        out_specs=(spec, P(), P(), P(), P()),
    )

    def solver(*arrays):
        w_pad, k, diff, converged, breakdown = mapped(*arrays)
        return PCGResult(
            w=w_pad[: problem.M + 1, : problem.N + 1],
            iters=k,
            diff=diff,
            converged=converged,
            breakdown=breakdown,
        )

    return jax.jit(solver), args
