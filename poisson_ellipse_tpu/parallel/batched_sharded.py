"""Lane-sharded batched solves: throughput scale-out at 1 psum/iter.

The spatial decomposition (``parallel.pcg_sharded`` and friends) splits
ONE problem's grid over the mesh and pays collectives for every global
dot — 2 psums/iteration classical, 1 pipelined. Serving throughput has a
better axis: the *lane* dimension of the batched engines is embarrassingly
parallel, so this module shards lanes over the mesh — every device owns
``lanes / n_devices`` whole problems and runs the production batched
iteration (``batch.batched_pcg.make_lane_step`` /
``batch.batched_pipelined.make_lane_step`` — the identical per-lane
arithmetic, not a reimplementation) on its local lanes.

Collective cost: the per-lane dot bundles never leave the device (each
lane's grid lives whole on its shard — there is nothing to reduce
across the mesh), so the ONLY collective is the loop's convergence word:
one scalar ``lax.psum`` of the local active-lane count per iteration,
which keeps every device in the same fused ``lax.while_loop`` until all
lanes everywhere are done. That is **exactly 1 psum per iteration
independent of the lane count and of the recurrence** — flat where the
spatially-sharded classical loop pays 2 psums for every single solve
(jaxpr-pinned in ``tests/test_batched.py``). For the batched-pipelined
composition the stacked (8, B_local) bundle rides entirely in local
VMEM/HBM; the psum'd word is one int32.

The price is straggler synchronisation: all devices iterate until the
slowest lane converges — the same whole-batch semantics the single-chip
batched loop has, made visible per-device. Mixed-difficulty lanes should
be binned by the caller (the compile-cache's lane buckets are the
natural binning boundary).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from poisson_ellipse_tpu.batch import batched_pcg, batched_pipelined
from poisson_ellipse_tpu.batch.batched_pcg import (
    BatchedPCGResult,
    apply_dinv_batched,
    batched_operands,
    diag_d_batched,
)
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.parallel.compat import pcast_varying, shard_map
from poisson_ellipse_tpu.parallel.mesh import AXIS_X, AXIS_Y, make_mesh

MESH_AXES = (AXIS_X, AXIS_Y)


def build_batched_sharded_solver(
    problem: Problem,
    mesh: Mesh | None = None,
    lanes: int | None = None,
    dtype=jnp.float32,
    pipelined: bool = False,
):
    """(jitted solver, args) for a lane-sharded batched solve.

    ``lanes`` must be a multiple of the mesh's device count (each device
    owns whole lanes; the compile-cache's lane buckets round requests up
    to exactly such multiples). ``args`` = (a, b, rhs): coefficients
    replicated, the (lanes, M+1, N+1) RHS stack sharded on its lane axis
    over every mesh device. The solver returns a per-lane
    :class:`BatchedPCGResult`, lane order preserved.
    """
    if mesh is None:
        mesh = make_mesh()
    n_devices = mesh.shape[AXIS_X] * mesh.shape[AXIS_Y]
    if lanes is None:
        lanes = n_devices
    if lanes % n_devices != 0:
        raise ValueError(
            f"lanes={lanes} must be a multiple of the mesh's {n_devices} "
            "devices (whole lanes per device; pad the request to the "
            "next lane bucket)"
        )
    h1 = jnp.asarray(problem.h1, dtype)
    h2 = jnp.asarray(problem.h2, dtype)
    delta = jnp.asarray(problem.delta, dtype)
    weighted = problem.norm == "weighted"
    max_iter = problem.max_iterations
    lane_spec = P(MESH_AXES)

    def shard_fn(a, b, rhs):
        # a/b replicated (shared geometry), rhs = this device's lanes
        a3, b3 = a[None], b[None]
        d = diag_d_batched(a3, b3, h1, h2)
        B_local = rhs.shape[0]
        if pipelined:
            step = batched_pipelined.make_lane_step(
                rhs, a3, b3, d, None, h1, h2, delta, weighted
            )
            r0 = rhs
            u0 = apply_dinv_batched(r0, d)
            w0 = batched_pipelined.apply_a_batched(u0, a3, b3, h1, h2)
            zeros = lambda: pcast_varying(jnp.zeros_like(rhs), MESH_AXES)
            lane_state = (
                jnp.asarray(0, jnp.int32),
                zeros(),  # x
                r0, u0, w0,
                zeros(), zeros(), zeros(),  # z, s, p
                pcast_varying(jnp.ones((B_local,), dtype), MESH_AXES),
                pcast_varying(jnp.full((B_local,), jnp.inf, dtype), MESH_AXES),
                pcast_varying(jnp.zeros((B_local,), bool), MESH_AXES),
                pcast_varying(jnp.zeros((B_local,), bool), MESH_AXES),
                pcast_varying(jnp.zeros((B_local,), bool), MESH_AXES),
                pcast_varying(jnp.zeros((B_local,), jnp.int32), MESH_AXES),
            )
            conv_i, bd_i, quar_i = 10, 11, 12
        else:
            step = batched_pcg.make_lane_step(
                a3, b3, d, None, h1, h2, delta, weighted
            )
            r0 = rhs
            z0 = apply_dinv_batched(r0, d)
            zr0 = jnp.sum(z0 * r0, axis=(1, 2)) * h1 * h2
            lane_state = (
                jnp.asarray(0, jnp.int32),
                pcast_varying(jnp.zeros_like(rhs), MESH_AXES),
                r0,
                z0,
                zr0,
                pcast_varying(jnp.full((B_local,), jnp.inf, dtype), MESH_AXES),
                pcast_varying(jnp.zeros((B_local,), bool), MESH_AXES),
                pcast_varying(jnp.zeros((B_local,), bool), MESH_AXES),
                pcast_varying(jnp.zeros((B_local,), bool), MESH_AXES),
                pcast_varying(jnp.zeros((B_local,), jnp.int32), MESH_AXES),
            )
            conv_i, bd_i, quar_i = 6, 7, 8

        def cond(carry):
            lane_state, n_active = carry
            return (lane_state[0] < max_iter) & (n_active > 0)

        def body(carry):
            lane_state, _ = carry
            new = step(lane_state)
            active = ~new[conv_i] & ~new[bd_i] & ~new[quar_i]
            # THE one collective of the iteration, lane-count-invariant:
            # the cross-device convergence word (dot bundles are
            # lane-local and need no psum at all)
            n_active = lax.psum(
                jnp.sum(active, dtype=jnp.int32), MESH_AXES
            )
            return new, n_active

        out, _ = lax.while_loop(
            cond, body, (lane_state, jnp.asarray(lanes, jnp.int32))
        )
        result = (
            batched_pipelined.result_of(out) if pipelined
            else batched_pcg.result_of(out)
        )
        return tuple(result)

    mapped = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(), P(), P(MESH_AXES, None, None)),
        out_specs=(
            P(MESH_AXES, None, None),  # w
            lane_spec, lane_spec, lane_spec, lane_spec, lane_spec,
        ),
    )

    a, b, rhs = batched_operands(problem, lanes, dtype)
    args = (
        jax.device_put(a, NamedSharding(mesh, P())),
        jax.device_put(b, NamedSharding(mesh, P())),
        jax.device_put(rhs, NamedSharding(mesh, P(MESH_AXES, None, None))),
    )

    def solver(a, b, rhs):
        return BatchedPCGResult(*mapped(a, b, rhs))

    # no donation: the build-once-call-many contract re-feeds these
    # operands on every dispatch (bench --repeat, chained solves)
    # tpulint: disable=TPU004
    return jax.jit(solver), args


def build_sharded_chunk_advance(
    bucket: tuple[int, int],
    mesh: Mesh | None = None,
    lanes: int | None = None,
    norm: str = "weighted",
    iter_ceiling: int = 1 << 30,
):
    """(jitted carry→carry chunk advance, proto problem) for the serve
    scheduler's lane-refill loop composed with the mesh.

    The refill machinery is host-side between-chunk work, so the traced
    loop body is untouched: this is the classical batched lane step
    (``batch.batched_pcg.make_lane_step`` — the identical per-lane
    arithmetic) sharded whole-lanes-per-device, advancing an existing
    carry up to a traced ``limit``. Per-lane operands, masks, spacings
    and δ are traced arguments (the scheduler's mixed-shape packing),
    so retire/refill/replay never retrace (the compute dtype rides on
    the operands, not on a parameter here). The ONLY collective is the
    convergence word — **exactly 1 psum per iteration**, lane-count- and
    refill-invariant (jaxpr-pinned in ``tests/test_serve.py``).

    Signature of the returned fn (matches the scheduler's single-device
    bucket advance): ``fn(a3, b3, mask, h1, h2, delta, state, limit)``
    where ``state`` is the classical batched carry and every per-lane
    array is sharded on its lane axis.
    """
    if mesh is None:
        mesh = make_mesh()
    n_devices = mesh.shape[AXIS_X] * mesh.shape[AXIS_Y]
    if lanes is None:
        lanes = n_devices
    if lanes % n_devices != 0:
        raise ValueError(
            f"lanes={lanes} must be a multiple of the mesh's {n_devices} "
            "devices (whole lanes per device)"
        )
    proto = Problem(
        M=bucket[0], N=bucket[1], norm=norm, max_iter=iter_ceiling
    )
    weighted = norm == "weighted"
    lane3 = P(MESH_AXES, None, None)
    lane1 = P(MESH_AXES)

    def shard_fn(a3, b3, mask, h1, h2, delta, state, limit):
        d = diag_d_batched(a3, b3, h1, h2, mask)
        step = batched_pcg.make_lane_step(
            a3, b3, d, mask, h1, h2, delta, weighted
        )
        bound = jnp.minimum(
            limit, jnp.asarray(proto.max_iterations, jnp.int32)
        )

        def active_count(lane_state):
            active = ~lane_state[6] & ~lane_state[7] & ~lane_state[8]
            return lax.psum(jnp.sum(active, dtype=jnp.int32), MESH_AXES)

        def cond(carry):
            lane_state, n_active = carry
            return (lane_state[0] < bound) & (n_active > 0)

        def body(carry):
            lane_state, _ = carry
            new = step(lane_state)
            # THE one collective of the iteration: the convergence word
            return new, active_count(new)

        out, _ = lax.while_loop(cond, body, (state, active_count(state)))
        return out

    state_specs = (
        P(),                           # k — replicated global clock
        lane3, lane3, lane3,           # w, r, p
        lane1, lane1,                  # zr, diff
        lane1, lane1, lane1, lane1,    # conv, bd, quar, iters
    )
    mapped = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(
            lane3, lane3, lane3, lane1, lane1, lane1, state_specs, P()
        ),
        out_specs=state_specs,
    )

    # no donation: the carry is re-read at every chunk boundary for the
    # scheduler's retire/refill host work
    return jax.jit(mapped), proto


def solve_batched_sharded(
    problem: Problem,
    lanes: int | None = None,
    mesh: Mesh | None = None,
    dtype=jnp.float32,
    pipelined: bool = False,
) -> BatchedPCGResult:
    """Assemble, lane-shard and solve over the mesh."""
    solver, args = build_batched_sharded_solver(
        problem, mesh, lanes, dtype, pipelined=pipelined
    )
    return solver(*args)
