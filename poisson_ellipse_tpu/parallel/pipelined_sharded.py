"""Pipelined sharded PCG: ONE stacked psum collective per iteration.

The plain sharded loop (``parallel.pcg_sharded``) pays 2 ``lax.psum``
latencies per iteration, and both sit on the critical path: denom must
arrive before the axpy updates, whose results feed the second collective.
On the north-star configuration (large grids over many chips/hosts) that
reduce→broadcast latency IS the iteration floor — the stencil arithmetic
is local and fast, the collectives are not.

This module composes the pipelined recurrence (``ops.pipelined_pcg``)
with the mesh: every inner product an iteration needs is computed from
vectors already in hand, stacked into one (8,) partials vector, and
issued as a SINGLE ``lax.psum``. Crucially the iteration's halo exchange
(4 ``lax.ppermute``) and stencil application consume none of that psum's
results, so XLA's scheduler overlaps the collective with the
neighbour-exchange + stencil compute — the same collective-fusion/overlap
shape that hides all-reduce latency in distributed training stacks.

Per iteration, per shard:

  1 stacked psum             all 8 dot partials, one collective
  1 halo exchange            m = M⁻¹w in 4 ppermutes   } independent of
  1 stencil                  n = A m                   } the psum: overlap
  scalar tail                β, α, breakdown/convergence
  7 fused axpy updates       z s p x r u w

versus 2 psums + 1 halo exchange for the classical sharded loop — half
the collectives, and the remaining one hidden behind compute. Residual
replacement (``ops.pipelined_pcg.REPLACE_EVERY``) runs on the same fixed
cadence with two stacked halo exchanges; it is outside the steady-state
iteration and adds no collectives.

Accuracy contract is the pipelined engine's (reordering, not bitwise):
iteration counts within ±2 of the sharded ``xla`` path on the oracle
grids, asserted in ``tests/test_pipelined.py`` — which also pins "exactly
one psum in the loop body" structurally, from the jaxpr.

``build_pipelined_sharded_stepper`` is the chunked/resumable form of the
same iteration (the ``build_sharded_stepper`` contract), which is what
lets ``resilience.guard`` chunk, health-check and roll back pipelined
mesh solves. With ``abft=True`` it runs the in-loop SDC checks of
``resilience.abft`` adapted to this recurrence's collective schedule:
the single psum fires BEFORE the axpy updates, so the residual-sum
recurrence check is *lagged one iteration* — iteration k+1's directly
reduced Σr is compared against the prediction
``Σr − α·(Σw + β·Σs)`` carried from iteration k — plus the γ-positivity
invariant (γ = ⟨r, M⁻¹r⟩ > 0 until convergence, the check that catches a
sign-flipped all-reduce). All extra partials ride the SAME stacked psum:
still exactly one collective per iteration, jaxpr-pinned.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.ops.pipelined_pcg import REPLACE_EVERY, _bundle
from poisson_ellipse_tpu.ops.stencil import apply_a_block, apply_dinv, diag_d_block
from poisson_ellipse_tpu.parallel.compat import pcast_varying, shard_map
from poisson_ellipse_tpu.parallel.halo import halo_extend, halo_extend_stacked
from poisson_ellipse_tpu.parallel.mesh import AXIS_X, AXIS_Y, make_mesh, padded_dims
from poisson_ellipse_tpu.parallel.pcg_sharded import _host_sharded_args
from poisson_ellipse_tpu.solver.pcg import DENOM_GUARD, PCGResult

MESH_AXES = (AXIS_X, AXIS_Y)

# indices of the ABFT tail appended to the pipelined sharded carry:
# (…, pred_r, scale_r, pred_p, scale_p, sdc) — the lagged checks of the
# module docstring (r-chain skips replacement iterations; the p-chain
# holds across them, since replacement treats p as ground truth)
PIPE_PRED, PIPE_SCALE, PIPE_PRED_P, PIPE_SCALE_P, PIPE_SDC = (
    12, 13, 14, 15, 16
)


def _pipelined_parts(problem: Problem, px: int, py: int, bm: int, bn: int,
                     a_blk, b_blk, rhs_blk, dtype, abft: bool = False):
    """(state0, body, cond_of) for one shard of the pipelined iteration
    — the single source both the whole-solve form and the chunked
    stepper trace, so they cannot drift. ``cond_of(limit)`` builds the
    loop condition against a (traced or static) iteration bound."""
    h1 = jnp.asarray(problem.h1, dtype)
    h2 = jnp.asarray(problem.h2, dtype)
    hw = h1 * h2
    delta_tol = jnp.asarray(problem.delta, dtype)
    weighted = problem.norm == "weighted"

    ix = lax.axis_index(AXIS_X)
    iy = lax.axis_index(AXIS_Y)
    gi = ix * bm + jnp.arange(bm, dtype=jnp.int32)
    gj = iy * bn + jnp.arange(bn, dtype=jnp.int32)
    interior = assembly.interior_mask(problem, gi, gj)

    # one-time coefficient halo exchange (loop invariant)
    a_ext = halo_extend(a_blk, px, py)
    b_ext = halo_extend(b_blk, px, py)
    d = jnp.where(interior, diag_d_block(a_ext, b_ext, h1, h2), 0.0)
    maskd = interior.astype(dtype)

    def stencil(v_ext):
        return apply_a_block(v_ext, a_ext, b_ext, h1, h2) * maskd

    def stencil_of(v):
        return stencil(halo_extend(v, px, py))

    def replace(k, x, r, u, w, z, s, p):
        """Residual replacement from ground-truth x and p: two
        stacked halo exchanges + four stencils, same cadence as the
        single-chip engine (no collectives — psum count per
        iteration stays at one)."""

        def rebuilt(_):
            xp_ext = halo_extend_stacked(jnp.stack([x, p]), px, py)
            r_t = rhs_blk - stencil(xp_ext[0])
            s_t = stencil(xp_ext[1])
            u_t = apply_dinv(r_t, d)
            q_t = apply_dinv(s_t, d)
            uq_ext = halo_extend_stacked(jnp.stack([u_t, q_t]), px, py)
            return (
                r_t, u_t, stencil(uq_ext[0]), stencil(uq_ext[1]), s_t
            )

        do = (k > 0) & (k % REPLACE_EVERY == 0)
        return lax.cond(do, rebuilt, lambda _: (r, u, w, z, s), None)

    r0 = rhs_blk
    u0 = apply_dinv(r0, d)
    w0 = stencil_of(u0)
    zeros = lambda: pcast_varying(jnp.zeros((bm, bn), dtype), MESH_AXES)
    state0 = (
        jnp.asarray(0, jnp.int32),
        zeros(),  # x
        r0, u0, w0,
        zeros(), zeros(), zeros(),  # z, s, p
        jnp.asarray(1.0, dtype),    # γ of the previous iteration
        jnp.asarray(jnp.inf, dtype),
        jnp.asarray(False),
        jnp.asarray(False),
    )
    if abft:
        state0 = state0 + (
            jnp.asarray(0.0, dtype),  # pred_r (checked from k=1 on)
            jnp.asarray(0.0, dtype),  # its drift scale
            jnp.asarray(0.0, dtype),  # pred_p
            jnp.asarray(0.0, dtype),  # its drift scale
            jnp.asarray(False),       # sdc
        )

    def cond_of(limit):
        def cond(state):
            k = state[0]
            converged, breakdown = state[10], state[11]
            go = (k < limit) & ~converged & ~breakdown
            if abft:
                # a flagged carry stops at once — the guard rolls the
                # chunk back; further iterations only amplify the flip
                go = go & ~state[PIPE_SDC]
            return go

        return cond

    def body(state):
        k, x, r, u, w, z, s, p, g_prev, diff_prev, _c, _bd = state[:12]
        r, u, w, z, s = replace(k, x, r, u, w, z, s, p)

        # THE one collective of the iteration: all partials in a
        # single stacked psum …
        partials = [jnp.sum(a_ * b_) for a_, b_ in _bundle(r, u, w, s, p)]
        if abft:
            # the ABFT partials ride the same psum — plain/abs sums of
            # vectors the bundle above already reads
            partials += [
                jnp.sum(r), jnp.sum(jnp.abs(r)),
                jnp.sum(w), jnp.sum(jnp.abs(w)),
                jnp.sum(s), jnp.sum(jnp.abs(s)),
                jnp.sum(p), jnp.sum(jnp.abs(p)),
                jnp.sum(u), jnp.sum(jnp.abs(u)),
            ]
        sums = lax.psum(jnp.stack(partials), MESH_AXES)
        # … which this halo exchange + stencil do NOT consume: XLA
        # overlaps the collective with the neighbour exchange and
        # the stencil compute
        m = apply_dinv(w, d)
        n = stencil_of(m)

        gamma = sums[0] * hw
        wu, wp, su, sp = sums[1], sums[2], sums[3], sums[4]
        uu, up, pp = sums[5], sums[6], sums[7]
        first = k == 0
        beta = jnp.where(
            first, 0.0, gamma / jnp.where(first, 1.0, g_prev)
        )
        denom = (wu + beta * (wp + su) + beta * beta * sp) * hw
        breakdown = denom < DENOM_GUARD
        alpha = gamma / jnp.where(breakdown, 1.0, denom)

        z_new = n + beta * z
        s_new = w + beta * s
        p_new = u + beta * p
        x_new = x + alpha * p_new
        r_new = r - alpha * s_new
        u_new = u - alpha * apply_dinv(s_new, d)
        w_new = w - alpha * z_new

        pp_new = uu + 2.0 * beta * up + beta * beta * pp
        dw2 = alpha * alpha * pp_new
        diff = jnp.sqrt(dw2 * hw) if weighted else jnp.sqrt(dw2)
        converged = ~breakdown & (diff < delta_tol)
        diff = jnp.where(breakdown, diff_prev, diff)

        keep = lambda old, new: jnp.where(breakdown, old, new)
        out = (
            k + 1,
            keep(x, x_new), keep(r, r_new), keep(u, u_new),
            keep(w, w_new), keep(z, z_new), keep(s, s_new),
            keep(p, p_new), keep(g_prev, gamma),
            diff, converged, breakdown,
        )
        if abft:
            from poisson_ellipse_tpu.resilience.abft import (
                ABFT_TINY,
                abft_rtol,
            )

            pred_r, scale_r, pred_p, scale_p, sdc = (
                state[PIPE_PRED:PIPE_SDC + 1]
            )
            s_r, s_absr = sums[8], sums[9]
            s_w, s_absw = sums[10], sums[11]
            s_s, s_abss = sums[12], sums[13]
            s_p, s_absp = sums[14], sums[15]
            s_u, s_absu = sums[16], sums[17]
            rtol = abft_rtol(dtype)
            # replacement legitimately rebuilds r away from the carried
            # prediction — skip the lagged r-check on those iterations
            # (the p-chain holds: replacement treats p as ground truth)
            replaced = (k > 0) & (k % REPLACE_EVERY == 0)
            ok_r = replaced | (
                jnp.abs(s_r - pred_r) <= rtol * (scale_r + ABFT_TINY)
            )
            ok_p = jnp.abs(s_p - pred_p) <= rtol * (scale_p + ABFT_TINY)
            ok_g = g_prev > 0  # γ is an energy product until convergence
            fault = (k > 0) & ~(ok_r & ok_p & ok_g)
            # next iteration's incoming r is r − α(w + βs) and incoming
            # p is u + βp: predict their sums (and the round-off scale
            # of each prediction) now
            pred_r_next = s_r - alpha * (s_w + beta * s_s)
            scale_r_next = s_absr + jnp.abs(alpha) * (
                s_absw + jnp.abs(beta) * s_abss
            )
            pred_p_next = s_u + beta * s_p
            scale_p_next = s_absu + jnp.abs(beta) * s_absp
            out = out + (
                keep(pred_r, pred_r_next),
                keep(scale_r, scale_r_next),
                keep(pred_p, pred_p_next),
                keep(scale_p, scale_p_next),
                sdc | fault,
            )
        return out

    return state0, body, cond_of


def build_pipelined_sharded_solver(
    problem: Problem,
    mesh: Mesh | None = None,
    dtype=jnp.float32,
    geometry=None,
    theta=None,
):
    """(jitted solver, args) for the pipelined mesh-sharded solve.

    Operands are host-assembled in f64 and rounded once (the fidelity
    contract every engine shares); args = the three (g1p, g2p) arrays
    laid out P('x', 'y') over the mesh, so ``solver(*args)`` slots into
    the same harness/bench protocol as ``build_sharded_solver``.
    """
    if mesh is None:
        mesh = make_mesh()
    px = mesh.shape[AXIS_X]
    py = mesh.shape[AXIS_Y]
    g1p, g2p = padded_dims(problem.node_shape, mesh)
    bm, bn = g1p // px, g2p // py
    spec = P(AXIS_X, AXIS_Y)
    max_iter = problem.max_iterations

    def shard_fn(a_blk, b_blk, rhs_blk):
        state0, body, cond_of = _pipelined_parts(
            problem, px, py, bm, bn, a_blk, b_blk, rhs_blk, dtype
        )
        out = lax.while_loop(cond_of(max_iter), body, state0)
        k, x = out[0], out[1]
        diff, converged, breakdown = out[9], out[10], out[11]
        return x, k, diff, converged, breakdown

    mapped = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=(spec, P(), P(), P(), P()),
    )

    args = _host_sharded_args(problem, mesh, dtype, g1p, g2p, spec,
                              geometry=geometry, theta=theta)

    def solver(*arrays):
        x_pad, k, diff, converged, breakdown = mapped(*arrays)
        return PCGResult(
            w=x_pad[: problem.M + 1, : problem.N + 1],
            iters=k,
            diff=diff,
            converged=converged,
            breakdown=breakdown,
        )

    # no donation: the build-once-call-many contract re-feeds these
    # operands on every dispatch (bench --repeat, chained solves)
    return jax.jit(solver), args


def build_pipelined_sharded_stepper(
    problem: Problem,
    mesh: Mesh | None = None,
    dtype=jnp.float32,
    abft: bool = False,
):
    """(init_fn, advance_fn) for chunked/resumable pipelined mesh solves
    — the ``build_sharded_stepper`` contract over the 12-field pipelined
    carry (x/r/u/w/z/s/p blocks sharded P('x','y'), γ/diff/flags
    replicated). Chunking only moves the while_loop boundary; the
    recurrence — including the fixed-cadence residual replacement, keyed
    on the carried absolute k — is untouched, so a chunked run converges
    in the same count as the straight solve. With ``abft`` the carry
    gains the three lagged-check scalars (module docstring) and the sdc
    flag rides out to the guard's chunk-boundary health read.
    """
    if mesh is None:
        mesh = make_mesh()
    px = mesh.shape[AXIS_X]
    py = mesh.shape[AXIS_Y]
    g1p, g2p = padded_dims(problem.node_shape, mesh)
    bm, bn = g1p // px, g2p // py
    spec = P(AXIS_X, AXIS_Y)
    scalar = P()
    state_specs = (
        (scalar,) + (spec,) * 7 + (scalar, scalar, scalar, scalar)
    )
    if abft:
        state_specs = state_specs + (scalar,) * 5
    max_iter = problem.max_iterations

    def init_shard(a_blk, b_blk, rhs_blk):
        state0, _body, _cond_of = _pipelined_parts(
            problem, px, py, bm, bn, a_blk, b_blk, rhs_blk, dtype,
            abft=abft,
        )
        return state0

    def advance_shard(a_blk, b_blk, rhs_blk, state, limit):
        _state0, body, cond_of = _pipelined_parts(
            problem, px, py, bm, bn, a_blk, b_blk, rhs_blk, dtype,
            abft=abft,
        )
        bound = jnp.minimum(jnp.asarray(limit, jnp.int32), max_iter)
        return lax.while_loop(cond_of(bound), body, state)

    # no donation on either half: operands are re-fed every chunk and
    # the carry doubles as the guard's rollback point
    init_mapped = jax.jit(shard_map(  # tpulint: disable=TPU004
        init_shard,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=state_specs,
    ))
    advance_mapped = jax.jit(shard_map(  # tpulint: disable=TPU004
        advance_shard,
        mesh=mesh,
        in_specs=(spec, spec, spec, state_specs, scalar),
        out_specs=state_specs,
    ))

    args = _host_sharded_args(problem, mesh, dtype, g1p, g2p, spec)

    def init_fn():
        return init_mapped(*args)

    def advance_fn(state, limit):
        return advance_mapped(
            args[0], args[1], args[2], state,
            jnp.asarray(limit, jnp.int32),
        )

    return init_fn, advance_fn


def pipelined_sharded_result_of(problem: Problem, state) -> PCGResult:
    """View a pipelined sharded carry as a PCGResult (crops padding; the
    ABFT tail, when present, is ignored)."""
    k, x = state[0], state[1]
    diff, converged, breakdown = state[9], state[10], state[11]
    return PCGResult(
        w=x[: problem.M + 1, : problem.N + 1],
        iters=k,
        diff=diff,
        converged=converged,
        breakdown=breakdown,
    )


def solve_pipelined_sharded(
    problem: Problem,
    mesh: Mesh | None = None,
    dtype=jnp.float32,
) -> PCGResult:
    """Assemble, shard and solve with the pipelined one-psum iteration."""
    solver, args = build_pipelined_sharded_solver(problem, mesh, dtype)
    return solver(*args)
