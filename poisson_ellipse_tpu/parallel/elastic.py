"""Elastic mesh surgery: shrink a device mesh and re-shard a live carry.

The reference's MPI stages die wholesale when any rank fails
(``MPI_Init``/``Finalize`` with no recovery surface — ``parallel.
multihost``'s docstring); XLA's SPMD programs are no kinder — a lost
device invalidates every array laid out over the mesh. What CAN survive
is the *state*: the PCG carry is a handful of global arrays plus
replicated scalars, and the solve's arithmetic is mesh-independent
(decomposition only changes the f.p. reduction grouping, an ulp-scale
effect pinned by the sharded-parity tests). So elasticity is three small
operations, all off the hot path:

- :func:`surviving_devices` / :func:`shrink_mesh` — rebuild the 2D mesh
  over whatever devices remain, factored near-square exactly like the
  original (``parallel.mesh.choose_process_grid``), so a 2×2 mesh that
  loses two devices resumes as 1×2, and one that loses a single device
  resumes 1×3.
- :func:`gather_state` — pull a sharded carry to host numpy (the only
  layout that survives the old mesh's death).
- :func:`reshard_state` — crop the old mesh's shard padding back to the
  node grid, re-pad to the NEW decomposition's even-shard dims (the same
  padding rule every sharded build uses — zero coefficients, exterior-
  Dirichlet behaviour), and lay the arrays out over the new mesh.

``resilience.meshguard`` composes these with the durable checkpoint
(``solver.checkpoint`` re-shards on resume via the same functions) into
the degraded-mesh recovery ladder; ``resilience.guard`` uses
:func:`reshard_state` to hand a preconditioned mesh carry (whose level
geometry pads differently) over to the classical stepper on fallback.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.parallel.mesh import (
    AXIS_X,
    AXIS_Y,
    make_mesh,
    padded_dims,
)
from poisson_ellipse_tpu.resilience.errors import DeviceLossError


def surviving_devices(mesh: Mesh, lost_ids) -> list:
    """The mesh's devices minus the lost ids (order preserved)."""
    lost = set(lost_ids)
    return [d for d in mesh.devices.flat if d.id not in lost]


def shrink_mesh(mesh: Mesh, lost_ids) -> Mesh:
    """A fresh near-square 2D mesh over the survivors.

    Raises the classified :class:`DeviceLossError` when nothing
    survives — the ladder's hard floor."""
    survivors = surviving_devices(mesh, lost_ids)
    if not survivors:
        raise DeviceLossError(
            f"all {mesh.devices.size} mesh devices lost ({sorted(set(lost_ids))})"
            " — no degraded mesh remains to resume on"
        )
    return make_mesh(survivors)


def gather_state(state) -> tuple:
    """A sharded carry as host numpy (scalars stay 0-d arrays)."""
    return tuple(np.asarray(x) for x in state)


def reshard_state(
    problem: Problem,
    state,
    mesh: Mesh,
    dtype,
    dims: tuple[int, int] | None = None,
):
    """Re-lay a classical 8-field carry out over ``mesh``.

    Grid fields (ndim == 2) are cropped to the node grid — dropping the
    OLD decomposition's zero padding, whatever it was — then zero-padded
    to ``dims`` (default: the new mesh's even-shard dims) and placed
    P('x','y'); scalars replicate. The padding carries zeros into fields
    that are zero there by construction (every sharded iterate is
    interior-masked), so a resharded carry advances exactly as the
    original decomposition's would, modulo psum reduction grouping (an
    ulp-scale reordering — the parity contract the tests pin).

    Any ABFT shadow tail is deliberately NOT accepted here: shadow sums
    must be re-anchored against the resharded arrays (the stepper's
    recover / a fresh anchor), never copied across a layout change.
    """
    if len(state) != 8:
        raise ValueError(
            f"reshard_state takes the classical 8-field carry, got "
            f"{len(state)} fields (strip/re-anchor any ABFT or history tail)"
        )
    g1, g2 = problem.node_shape
    g1p, g2p = padded_dims(problem.node_shape, mesh) if dims is None else dims
    grid_sharding = NamedSharding(mesh, P(AXIS_X, AXIS_Y))
    scalar_sharding = NamedSharding(mesh, P())
    out = []
    for x in gather_state(state):
        if x.ndim == 2:
            cropped = x[:g1, :g2]
            padded = np.pad(
                cropped, ((0, g1p - g1), (0, g2p - g2))
            ).astype(x.dtype)
            out.append(jax.device_put(padded, grid_sharding))
        else:
            out.append(jax.device_put(x, scalar_sharding))
    return tuple(out)
