"""Fused-sharded PCG: the two-kernel iteration composed with the mesh.

The true heir of the reference stage4's composition — a 2D rank
decomposition whose hot loop runs device *kernels* per rank, ringed by
halo exchange and scalar reductions (``gradient_solver_mpi``,
``poisson_mpi_cuda2.cu:846-939``: six CUDA kernel launches + MPI_Sendrecv
halos + three MPI_Allreduce per iteration). Here one PCG iteration on
every shard is:

  1 stacked halo exchange   (z, p) pair in 4 ``lax.ppermute``s
  K1  pn = z + beta*p; ap = A(pn); denominator partial   (one kernel)
  1 ``lax.psum``            denominator
  K2  alpha; w += alpha*pn; r -= alpha*ap; ||dw||^2;
      z = r * 1/D; (z, r) partials                       (one kernel)
  1 ``lax.psum``            [zr, ||dw||^2] batched as one collective

i.e. 2 kernels + 2 psum + 4 ppermute per iteration, versus the ~8 XLA
fusions the plain sharded loop emits per iteration — the same
launch-count fusion the single-chip fused engine performs
(``ops.fused_pcg``), now per shard inside ``jax.shard_map``.

Kernel structure: K2 is *reused verbatim* from the single-chip fused
engine (``ops.fused_pcg._k2_kernel`` — pure elementwise + reduction on
the owned block). K1 differs from the single-chip K1 only in how halos
arrive: on one chip the neighbour rows come from extra BlockSpecs of the
same array and the Dirichlet columns are zero by padding; on a mesh the
halos are real neighbour data delivered by ``halo_extend_stacked``, so
K1 runs on (bm+2, bn+2) halo-extended inputs DMA'd in aligned row
windows — the proven pattern of ``ops.pallas_kernels._stencil_kernel``
— and mirrors ``ops.stencil.apply_a_block``'s expression tree term for
term (each difference divided by h before combining), which is what
keeps iteration-count parity with the sharded XLA path.

Sharding layout: the global node grid is zero-padded so every shard is
(8, 128)-tile aligned — (bm, bn) = (g1p/px, g2p/py) with bm % 8 == 0,
bn % 128 == 0. Padding carries zero coefficients and RHS, so padded
nodes behave exactly like the exterior Dirichlet ring (the
``parallel.mesh.padded_dims`` invariant, tightened to Mosaic tiling).

f32/bf16 only (Pallas TPU has no f64 path); f64 sharded runs use the
XLA stencil path (``parallel.pcg_sharded``).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.ops.fused_pcg import (
    _k2_kernel,
    interior_normalized,
    rotated_cond,
    rotated_next_state,
    rotated_state0,
)
from poisson_ellipse_tpu.ops.pallas_kernels import _row_tile, round_up
from poisson_ellipse_tpu.parallel.compat import (
    pcast_varying,
    shape_dtype_struct,
    shard_map,
)
from poisson_ellipse_tpu.parallel.halo import halo_extend, halo_extend_stacked
from poisson_ellipse_tpu.parallel.mesh import AXIS_X, AXIS_Y, make_mesh
from poisson_ellipse_tpu.solver.pcg import DENOM_GUARD, PCGResult

MESH_AXES = (AXIS_X, AXIS_Y)

# _row_tile / round_up are the shared VMEM-tile heuristic of
# ops.pallas_kernels — one copy, so a future budget fix cannot diverge
# between the single-chip and sharded engines (bm is 8-aligned by the
# fused-sharded padding, which is what _row_tile's divisor scan needs).


def padded_dims_fused(node_shape, mesh: Mesh) -> tuple[int, int]:
    """Global node dims padded so every shard is Mosaic-tile aligned."""
    g1, g2 = node_shape
    px = mesh.shape[AXIS_X]
    py = mesh.shape[AXIS_Y]
    return round_up(g1, 8 * px), round_up(g2, 128 * py)


def _k1_kernel(h1, h2, tm, bn, n_tiles,
               beta_ref, d_ref, z_hbm, p_hbm, a_hbm, b_hbm,
               pn_out, ap_out, denom_out, z_s, p_s, a_s, b_s, sems, acc):
    """pn = z + beta*p; ap = A(pn) masked; denom partial — one row tile.

    Inputs are halo-extended blocks padded to (bm+8, bn+128): tile i's
    owned rows sit at extended rows i*tm+1 .. i*tm+tm, so the aligned
    (tm+8)-row window starting at i*tm covers the stencil's row halo.
    The stencil mirrors ``ops.stencil.apply_a_block`` term for term; the
    mask is d != 0 (d is the interior-masked operator diagonal), which
    keeps every iterate exactly zero on the ring/padding as the sharded
    XLA path's maskd multiply does.
    """
    i = pl.program_id(0)
    r0 = i * tm
    copies = [
        pltpu.make_async_copy(src.at[pl.ds(r0, tm + 8), :], dst, sems.at[k])
        for k, (src, dst) in enumerate(
            [(z_hbm, z_s), (p_hbm, p_s), (a_hbm, a_s), (b_hbm, b_s)]
        )
    ]
    for c in copies:
        c.start()
    for c in copies:
        c.wait()

    beta = beta_ref[0]
    # the updated direction on the (tm+2)-row stencil window, halo included
    pn_w = z_s[0 : tm + 2, :] + beta * p_s[0 : tm + 2, :]
    wc = pn_w[1 : tm + 1, 1 : bn + 1]
    ax = -(
        a_s[2 : tm + 2, 1 : bn + 1] * (pn_w[2 : tm + 2, 1 : bn + 1] - wc) / h1
        - a_s[1 : tm + 1, 1 : bn + 1] * (wc - pn_w[0:tm, 1 : bn + 1]) / h1
    ) / h1
    ay = -(
        b_s[1 : tm + 1, 2 : bn + 2] * (pn_w[1 : tm + 1, 2 : bn + 2] - wc) / h2
        - b_s[1 : tm + 1, 1 : bn + 1] * (wc - pn_w[1 : tm + 1, 0:bn]) / h2
    ) / h2
    d = d_ref[:]
    ap = jnp.where(d != 0.0, ax + ay, 0.0)

    pn_out[:] = wc
    ap_out[:] = ap

    @pl.when(i == 0)
    def _():
        acc[0] = jnp.zeros((), wc.dtype)

    acc[0] += jnp.sum(ap * wc)

    @pl.when(i == n_tiles - 1)
    def _():
        denom_out[0] = acc[0]


class _ShardKernels(NamedTuple):
    k1: callable
    k2: callable
    bm: int
    bn: int
    cols: int  # padded column count of the halo-extended operands


def build_shard_kernels(bm: int, bn: int, h1: float, h2: float, dtype,
                        interpret: bool) -> _ShardKernels:
    """K1/K2 pallas_call closures for one (bm, bn) shard.

    Outputs carry vma annotations over both mesh axes so the kernels
    type-check under shard_map's varying-mesh-axes analysis (same
    contract as ``ops.pallas_kernels.apply_a_block_pallas``'s ``vma``).
    """
    if bm % 8 or bn % 128:
        raise ValueError(
            f"fused-sharded shards must be (8, 128)-aligned, got ({bm}, {bn})"
        )
    itemsize = jnp.dtype(dtype).itemsize
    cols = bn + 128  # bn + 2 halo columns, rounded up to the lane tile
    vma = frozenset(MESH_AXES)

    # K1: 4 DMA windows of (tm+8, cols) + d/pn/ap blocks of (tm, bn)
    tm1 = _row_tile(bm, cols, itemsize, 7)
    n1 = bm // tm1
    blk1 = lambda: pl.BlockSpec(
        (tm1, bn), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    smem = lambda: pl.BlockSpec(memory_space=pltpu.SMEM)
    any_ = lambda: pl.BlockSpec(memory_space=pl.ANY)
    k1 = pl.pallas_call(
        functools.partial(_k1_kernel, float(h1), float(h2), tm1, bn, n1),
        grid=(n1,),
        in_specs=[smem(), blk1(), any_(), any_(), any_(), any_()],
        out_specs=(blk1(), blk1(), smem()),
        out_shape=(
            shape_dtype_struct((bm, bn), dtype, vma=vma),
            shape_dtype_struct((bm, bn), dtype, vma=vma),
            shape_dtype_struct((1,), dtype, vma=vma),
        ),
        scratch_shapes=[
            pltpu.VMEM((tm1 + 8, cols), dtype),
            pltpu.VMEM((tm1 + 8, cols), dtype),
            pltpu.VMEM((tm1 + 8, cols), dtype),
            pltpu.VMEM((tm1 + 8, cols), dtype),
            pltpu.SemaphoreType.DMA((4,)),
            pltpu.SMEM((1,), dtype),
        ],
        interpret=interpret,
    )

    # K2: the single-chip fused engine's kernel, verbatim, on the owned
    # block — 9 live (tm, bn) buffers (5 in, 3 out, + pipeline slack)
    tm2 = _row_tile(bm, bn, itemsize, 9)
    n2 = bm // tm2
    blk2 = lambda: pl.BlockSpec(
        (tm2, bn), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    k2 = pl.pallas_call(
        functools.partial(_k2_kernel, n2),
        grid=(n2,),
        in_specs=[smem(), smem(), blk2(), blk2(), blk2(), blk2(), blk2()],
        out_specs=(blk2(), blk2(), blk2(), smem()),
        out_shape=(
            shape_dtype_struct((bm, bn), dtype, vma=vma),
            shape_dtype_struct((bm, bn), dtype, vma=vma),
            shape_dtype_struct((bm, bn), dtype, vma=vma),
            shape_dtype_struct((2,), dtype, vma=vma),
        ),
        scratch_shapes=[pltpu.SMEM((2,), dtype)],
        interpret=interpret,
    )

    def call_k1(beta, d_blk, z_ext, p_ext, a_ext, b_ext):
        return k1(jnp.reshape(beta, (1,)), d_blk, z_ext, p_ext, a_ext, b_ext)

    def call_k2(zr, denom, w, r, pn, ap, dinv_blk):
        return k2(
            jnp.reshape(zr, (1,)), jnp.reshape(denom, (1,)),
            w, r, pn, ap, dinv_blk,
        )

    return _ShardKernels(k1=call_k1, k2=call_k2, bm=bm, bn=bn, cols=cols)


def _pad_ext(x_ext, cols: int):
    """Pad a (bm+2, bn+2) halo-extended block to the (bm+8, cols) layout
    K1's aligned DMA windows require (zeros: Dirichlet exterior)."""
    return jnp.pad(x_ext, ((0, 6), (0, cols - x_ext.shape[1])))


def _vary(x):
    """Broadcast a replicated scalar to mesh-varying, so kernel operand
    vma sets are uniform under shard_map's checker."""
    return pcast_varying(x, MESH_AXES)


def build_fused_sharded_solver(
    problem: Problem,
    mesh: Mesh | None = None,
    dtype=jnp.float32,
    interpret: bool | None = None,
    geometry=None,
    theta=None,
):
    """(jitted solver, args) for the fused two-kernel mesh-sharded solve.

    Operands are assembled on the host in f64 (the reference's assembly,
    ``fictitious_regions_setup_local``, ``poisson_mpi_cuda2.cu:146-192``)
    and rounded once to the run dtype — the same fidelity contract as
    every other engine, which is what preserves the published
    iteration-count oracles. args = (a, b, d, dinv, rhs), each a global
    (g1p, g2p) array laid out P('x', 'y') over the mesh.
    """
    if jnp.dtype(dtype).itemsize >= 8:
        raise ValueError(
            "fused-sharded supports f32/bf16; use stencil_impl='xla' for f64"
        )
    if mesh is None:
        mesh = make_mesh()
    px = mesh.shape[AXIS_X]
    py = mesh.shape[AXIS_Y]
    if interpret is None:
        interpret = mesh.devices.flat[0].platform != "tpu"
    g1p, g2p = padded_dims_fused(problem.node_shape, mesh)
    bm, bn = g1p // px, g2p // py
    kern = build_shard_kernels(
        bm, bn, problem.h1, problem.h2, dtype, interpret
    )

    h1 = jnp.asarray(problem.h1, dtype)
    h2 = jnp.asarray(problem.h2, dtype)
    delta = jnp.asarray(problem.delta, dtype)
    weighted = problem.norm == "weighted"
    max_iter = problem.max_iterations

    def pdot(u, v):
        return lax.psum(jnp.sum(u * v), MESH_AXES) * h1 * h2

    def shard_fn(a_blk, b_blk, d_blk, dinv_blk, rhs_blk):
        # one-time coefficient halo exchange + DMA-layout padding (loop
        # invariant: sits outside the while_loop)
        a_ext = _pad_ext(halo_extend(a_blk, px, py), kern.cols)
        b_ext = _pad_ext(halo_extend(b_blk, px, py), kern.cols)

        r0 = rhs_blk
        z0 = r0 * dinv_blk  # multiply by 1/D, as K2 does every iteration
        zr0 = pdot(z0, r0)
        varying_zeros = lambda: pcast_varying(
            jnp.zeros((bm, bn), dtype), MESH_AXES
        )
        state0 = rotated_state0(
            varying_zeros(), r0, z0, varying_zeros(), zr0, dtype
        )

        def body(s):
            _k, w, r, z, p, zr, beta, _diff, _c, _bd = s
            zp_ext = halo_extend_stacked(jnp.stack([z, p]), px, py)
            z_ext = _pad_ext(zp_ext[0], kern.cols)
            p_ext = _pad_ext(zp_ext[1], kern.cols)
            pn, ap, dpart = kern.k1(
                _vary(beta), d_blk, z_ext, p_ext, a_ext, b_ext
            )
            denom = lax.psum(dpart[0], MESH_AXES) * h1 * h2
            breakdown = denom < DENOM_GUARD
            w_new, r_new, z_new, sums = kern.k2(
                _vary(zr), _vary(denom), w, r, pn, ap, dinv_blk
            )
            psums = lax.psum(sums, MESH_AXES)
            return rotated_next_state(
                s, pn, w_new, r_new, z_new, psums[0] * h1 * h2, psums[1],
                breakdown, h1, h2, delta, weighted,
            )

        out = lax.while_loop(rotated_cond(max_iter), body, state0)
        k, w = out[0], out[1]
        diff, converged, breakdown = out[7], out[8], out[9]
        return w, k, diff, converged, breakdown

    spec = P(AXIS_X, AXIS_Y)
    mapped = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(spec,) * 5,
        out_specs=(spec, P(), P(), P(), P()),
        # interpret-mode pallas internals mix varying refs with unvarying
        # index values, which the vma checker rejects (same waiver as the
        # per-op pallas stencil path, parallel.pcg_sharded); compiled TPU
        # runs keep full vma checking
        check_vma=not interpret,
    )

    args = _fused_sharded_args(problem, mesh, dtype, g1p, g2p, spec,
                               geometry=geometry, theta=theta)

    def solver(a, b, d, dinv, rhs):
        w_pad, k, diff, converged, breakdown = mapped(a, b, d, dinv, rhs)
        return PCGResult(
            w=w_pad[: problem.M + 1, : problem.N + 1],
            iters=k,
            diff=diff,
            converged=converged,
            breakdown=breakdown,
        )

    # no donation: build-once-call-many — callers re-feed these operands
    # every dispatch (bench --repeat protocol)
    # tpulint: disable=TPU004
    return jax.jit(solver), args


def _fused_sharded_args(problem: Problem, mesh: Mesh, dtype,
                        g1p: int, g2p: int, spec, geometry=None,
                        theta=None):
    """Host-f64-assembled (a, b, d, dinv, rhs), rounded once, zero-padded
    to tile-aligned shards and laid out over the mesh.

    d/dinv come from ``ops.fused_pcg.interior_normalized`` — the shared
    normalised/guarded diagonal algebra — so K2's preconditioner multiply
    uses the identical rounded-once reciprocal as the single-chip fused
    engine (the two paths share the code, not a copy)."""
    a64, b64, rhs64 = assembly.assemble_numpy(problem, geometry=geometry,
                                              theta=theta)
    _an, _as, _bw, _be, d64, dinv64 = interior_normalized(problem, a64, b64)
    np_dtype = assembly.numpy_dtype(dtype)
    sharding = NamedSharding(mesh, spec)

    def put(arr):
        padded = np.pad(
            arr, ((0, g1p - arr.shape[0]), (0, g2p - arr.shape[1]))
        )
        return jax.device_put(padded.astype(np_dtype), sharding)

    return tuple(put(x) for x in (a64, b64, d64, dinv64, rhs64))


def solve_fused_sharded(
    problem: Problem,
    mesh: Mesh | None = None,
    dtype=jnp.float32,
    interpret: bool | None = None,
) -> PCGResult:
    """Assemble, shard and solve with the fused two-kernel iteration."""
    solver, args = build_fused_sharded_solver(
        problem, mesh, dtype, interpret=interpret
    )
    return solver(*args)
