"""Device-mesh construction (reference layer L2: process-grid + partition).

``choose_process_grid`` reproduces the reference's factorisation exactly
(``stage2-mpi/poisson_mpi_decomp.cpp:60-64``): Px = ⌊√size⌋ decremented to
the nearest divisor, Py = size/Px — a near-square grid with Px ≤ Py.

Where ``decompose_2d`` (``:75-111``) hands out blocks differing by ≤1 row
to low ranks, XLA sharding wants equal shards: we instead zero-pad the
global node grid up to a multiple of the mesh shape. The padding carries
zero coefficients and a zero RHS, so padded nodes behave exactly like the
exterior Dirichlet ring and never influence the interior solve.
"""

from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_X = "x"
AXIS_Y = "y"


def choose_process_grid(size: int) -> tuple[int, int]:
    """Factor ``size`` devices into a near-square (px, py), px ≤ py.

    Reference: ``stage2-mpi/poisson_mpi_decomp.cpp:60-64``.
    """
    if size < 1:
        raise ValueError("need at least one device")
    px = int(math.isqrt(size))
    while size % px:
        px -= 1
    return px, size // px


def make_mesh(devices=None) -> Mesh:
    """Build a 2D ('x', 'y') mesh over the given (or all) devices."""
    if devices is None:
        devices = jax.devices()
    px, py = choose_process_grid(len(devices))
    return Mesh(np.asarray(devices).reshape(px, py), (AXIS_X, AXIS_Y))


def padded_dims(problem_nodes: tuple[int, int], mesh: Mesh) -> tuple[int, int]:
    """Global node-grid dims padded up to multiples of the mesh shape."""
    g1, g2 = problem_nodes
    px = mesh.shape[AXIS_X]
    py = mesh.shape[AXIS_Y]
    return (-(-g1 // px) * px, -(-g2 // py) * py)
