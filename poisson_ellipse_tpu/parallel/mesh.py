"""Device-mesh construction (reference layer L2: process-grid + partition).

``choose_process_grid`` reproduces the reference's factorisation exactly
(``stage2-mpi/poisson_mpi_decomp.cpp:60-64``): Px = ⌊√size⌋ decremented to
the nearest divisor, Py = size/Px — a near-square grid with Px ≤ Py.

Where ``decompose_2d`` (``:75-111``) hands out blocks differing by ≤1 row
to low ranks, XLA sharding wants equal shards: we instead zero-pad the
global node grid up to a multiple of the mesh shape. The padding carries
zero coefficients and a zero RHS, so padded nodes behave exactly like the
exterior Dirichlet ring and never influence the interior solve.
"""

from __future__ import annotations

import math
import os

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_X = "x"
AXIS_Y = "y"


def virtual_cpu_devices(n: int):
    """Provision virtual CPU devices without touching the default backend.

    The order-sensitive ritual shared by the driver's multichip dryrun
    gate and the virtual-mesh benchmarks: XLA parses XLA_FLAGS exactly
    once, at the first backend initialisation, so the host-device-count
    flag must be in the environment before any device query; and the
    environment may pin JAX_PLATFORMS to a hardware plugin — under an
    explicit pin, backend discovery REQUIRES that plugin to come up, so a
    sick accelerator runtime would kill even ``jax.devices("cpu")``.
    Platform discovery is therefore restricted to the CPU client, which
    is all these paths need. Backend discovery is one-shot per process:
    after this call the whole process is CPU-only, so callers that need
    accelerator work afterwards must run this in a separate process.

    Returns the CPU client's device list. If XLA_FLAGS already pins a
    host-device count, that count wins (XLA reads the flag once);
    callers needing exactly ``n`` devices must check the length. If the
    flag is absent and some backend already initialised in this process,
    raises RuntimeError immediately (the env edit would be silently
    ignored) instead of letting callers hit a confusing downstream
    device-count error.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        # XLA parses XLA_FLAGS exactly once, at the first backend init: if
        # any backend already came up in this process, the flag edit below
        # would be silently ignored and the caller would only see a
        # confusing "need N devices" error far downstream — fail at the
        # cause instead, naming the ordering requirement.
        try:
            from jax._src import xla_bridge as _xb

            initialized = _xb.backends_are_initialized()
        except (ImportError, AttributeError):  # jax internals moved on
            initialized = False
        if initialized:
            raise RuntimeError(
                "virtual_cpu_devices must run before any JAX backend is "
                "initialized in this process (XLA reads XLA_FLAGS only at "
                "the first backend init, so setting the host-device-count "
                "flag now would be silently ineffective). Call it before "
                "any jax.devices()/jit work, or start the process with "
                f"XLA_FLAGS=--xla_force_host_platform_device_count={n}."
            )
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n}".strip()
        )
    jax.config.update("jax_platforms", "cpu")
    return jax.devices("cpu")


def choose_process_grid(size: int) -> tuple[int, int]:
    """Factor ``size`` devices into a near-square (px, py), px ≤ py.

    Reference: ``stage2-mpi/poisson_mpi_decomp.cpp:60-64``.
    """
    if size < 1:
        raise ValueError("need at least one device")
    px = int(math.isqrt(size))
    while size % px:
        px -= 1
    return px, size // px


def make_mesh(devices=None) -> Mesh:
    """Build a 2D ('x', 'y') mesh over the given (or all) devices."""
    if devices is None:
        devices = jax.devices()
    px, py = choose_process_grid(len(devices))
    return Mesh(np.asarray(devices).reshape(px, py), (AXIS_X, AXIS_Y))


def padded_dims_of(problem_nodes: tuple[int, int], px: int,
                   py: int) -> tuple[int, int]:
    """Global node-grid dims padded up to multiples of (px, py) — the
    shape-only form, usable when the mesh itself no longer exists (a
    checkpoint written by a dead mesh still names its shape)."""
    g1, g2 = problem_nodes
    return (-(-g1 // px) * px, -(-g2 // py) * py)


def padded_dims(problem_nodes: tuple[int, int], mesh: Mesh) -> tuple[int, int]:
    """Global node-grid dims padded up to multiples of the mesh shape."""
    return padded_dims_of(
        problem_nodes, mesh.shape[AXIS_X], mesh.shape[AXIS_Y]
    )
