"""Chebyshev polynomial application: the smoother AND the standalone rung.

One routine serves three roles — the V-cycle's pre/post smoother, its
coarsest-level solve, and the standalone ``cheb-pcg`` preconditioner —
because all three are the same object: a FIXED polynomial in D⁻¹A
applied through the first-kind Chebyshev three-term recurrence over a
target interval [lo, hi] (Saad §12.3; the smoother variant is Adams et
al.'s parallel-multigrid Chebyshev smoothing). Fixed degree is the
load-bearing property: the applier is a linear operator
``B = p(D⁻¹A) D⁻¹`` with B symmetric (D⁻¹ᐟ² p(D⁻¹ᐟ²AD⁻¹ᐟ²) D⁻¹ᐟ² — a
polynomial of a symmetric matrix), so standard PCG stays valid; an
adaptive/restarted variant would silently demand flexible CG.

Positivity (the SPD half) holds when ``hi`` covers λmax(D⁻¹A): the
residual polynomial q has |q| < 1 on (0, hi], so p(λ) = (1 − q(λ))/λ > 0
there. Below ``lo`` the polynomial merely damps less — an overestimated
λmin costs iterations, never definiteness — which is why the Lanczos
λmin estimate can ride a generous slack while λmax carries a hard
Gershgorin cap (``GERSHGORIN_LMAX``: the Jacobi-scaled 5-point M-matrix
has row radius ≤ 1 around center 1).

The recurrence is unrolled at trace time (degree is a static config per
grid bucket — tpulint TPU013's contract), all coefficients Python
floats baked into the compile: zero host syncs, zero collectives, one
stencil + one pointwise D⁻¹ per step.
"""

from __future__ import annotations

# provable upper bound on λmax(D⁻¹A) for the 5-point operator with
# positive face coefficients: Gershgorin row center 1, radius =
# (Σ off-diag)/d ≤ 1. The hard cap every Lanczos-derived hi is clipped to.
GERSHGORIN_LMAX = 2.0

# target interval fallback when no Lanczos trace is usable: the full
# Gershgorin interval with a generic ill-conditioning guess on the low
# side (harmless: below-lo eigenmodes stay positive, see module docstring)
FALLBACK_LO_FRAC = 1e-4


def chebyshev_apply(apply_op, dinv, r, lo: float, hi: float, degree: int,
                    x=None):
    """x ≈ A⁻¹ r by ``degree`` Chebyshev steps on D⁻¹A over [lo, hi].

    ``apply_op``/``dinv`` are the level's A· and D⁻¹· closures (global
    or block layout — the caller owns masking and halo exchange).
    ``x=None`` starts from zero (one A-application saved — the pre-
    smoother and preconditioner case); otherwise smooths the given
    iterate (the post-smoother case). A-applications: ``degree − 1``
    from zero, ``degree`` otherwise.
    """
    if degree < 1:
        raise ValueError("chebyshev degree must be >= 1")
    if not (0.0 < lo < hi):
        raise ValueError(f"need 0 < lo < hi, got [{lo}, {hi}]")
    theta = 0.5 * (hi + lo)
    delta = 0.5 * (hi - lo)
    sigma = theta / delta
    rho = 1.0 / sigma
    res = r if x is None else r - apply_op(x)
    d = dinv(res) * (1.0 / theta)
    x = d if x is None else x + d
    for _ in range(degree - 1):
        res = res - apply_op(d)
        rho_new = 1.0 / (2.0 * sigma - rho)
        d = (rho_new * rho) * d + (2.0 * rho_new / delta) * dinv(res)
        rho = rho_new
        x = x + d
    return x


def clip_interval(bounds: tuple[float, float] | None) -> tuple[float, float]:
    """A safe Chebyshev target interval from Lanczos bounds (or None).

    The high side is clipped to the Gershgorin cap (a Lanczos hi above 2
    is estimator noise — the true spectrum cannot reach it); a missing
    or degenerate estimate falls back to the full Gershgorin interval.
    """
    if bounds is None:
        return (FALLBACK_LO_FRAC * GERSHGORIN_LMAX, GERSHGORIN_LMAX)
    lo, hi = bounds
    hi = min(hi, GERSHGORIN_LMAX)
    if not (0.0 < lo < hi):
        return (FALLBACK_LO_FRAC * GERSHGORIN_LMAX, GERSHGORIN_LMAX)
    return lo, hi


def smoother_interval(hi: float, frac: float = 4.0) -> tuple[float, float]:
    """The smoothing band [hi/frac, hi]: damp the upper spectrum, leave
    the smooth modes to the coarse grid (frac = 4 is the standard 2D
    choice; modes below hi/frac are contracted by the coarse-grid
    correction instead)."""
    return hi / frac, hi
