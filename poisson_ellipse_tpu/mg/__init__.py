"""Geometric multigrid + Chebyshev preconditioning (ROADMAP item 1).

The iteration-count wall's killer: the reference's diagonal
preconditioner costs O(√κ) = O(grid) PCG iterations (546 @ 400×600 →
5889 @ 8192², BENCH_r05); the symmetric V-cycle here takes κ(M⁻¹A)
toward O(1). Layout-generic cores (``transfer``/``coarsen``/``cheby``/
``vcycle``) are shared by the single-chip engines (``engine`` —
registered as ``mg-pcg``/``cheb-pcg`` in ``solver.engine``) and the
mesh form (``parallel.mg_sharded``).
"""

from poisson_ellipse_tpu.mg.cheby import GERSHGORIN_LMAX, chebyshev_apply
from poisson_ellipse_tpu.mg.coarsen import (
    Level,
    build_hierarchy,
    coarsen_coefficients,
    num_levels,
)
from poisson_ellipse_tpu.mg.engine import (
    PrecondConfig,
    build_precond_solver,
    default_config,
    lanczos_bounds,
    make_precond,
    modeled_extra_passes,
)
from poisson_ellipse_tpu.mg.fmg import (
    FMGConfig,
    build_fmg_solver,
    make_fcycle,
    work_units_per_point,
)
from poisson_ellipse_tpu.mg.transfer import (
    prolong_bilinear,
    restrict_full_weighting,
)
from poisson_ellipse_tpu.mg.vcycle import LevelOps, make_vcycle

__all__ = [
    "FMGConfig",
    "GERSHGORIN_LMAX",
    "Level",
    "LevelOps",
    "PrecondConfig",
    "build_fmg_solver",
    "build_hierarchy",
    "build_precond_solver",
    "chebyshev_apply",
    "coarsen_coefficients",
    "default_config",
    "lanczos_bounds",
    "make_fcycle",
    "make_precond",
    "make_vcycle",
    "modeled_extra_passes",
    "num_levels",
    "prolong_bilinear",
    "restrict_full_weighting",
]
