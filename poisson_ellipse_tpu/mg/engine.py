"""The mg-pcg / cheb-pcg single-chip engines: build, bounds, cost model.

Both engines are the CLASSICAL fused PCG loop (``solver.pcg`` — same
carry, same stopping rule, same history contract, zero host syncs per
iteration) with the ``precond`` hook swapped from the reference's
diagonal to:

- **cheb-pcg** — the degree-k Chebyshev polynomial in D⁻¹A over the
  Lanczos-estimated spectral interval (``mg.cheby``): the cheap first
  rung. k stencil passes per iteration buy a ~k× iteration cut, so it
  mostly converts reduce→broadcast latency into streaming work — the
  win grows with grid size and mesh size.
- **mg-pcg** — the symmetric V-cycle over the coarsened-coefficient
  hierarchy (``mg.coarsen`` + ``mg.vcycle``) with Chebyshev smoothers:
  the iteration-count killer. κ(M⁻¹A) stops growing with the grid, so
  the 546 → 5889 iteration wall (BENCH_r05) flattens to O(10¹).

Eigenvalue bounds come from ONE source: a short diagonal-PCG probe
whose recorded α/β feed ``obs.spectrum.eigenvalue_bounds`` (the same
helper ``harness diagnose`` reports) — the Lanczos estimate the ROADMAP
telemetry already validated, clipped to the Gershgorin cap. The probe
is a build-time cost (one short jitted solve), cached per (problem,
dtype) alongside the hierarchy.

Setup (hierarchy + probe) happens at ``build_*`` time — the solver the
builders return is jitted once and dispatched many times, the
engine-zoo contract. Level count, smoother degree and Chebyshev degree
are STATIC per grid bucket (tpulint TPU013).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from poisson_ellipse_tpu.mg import cheby, coarsen, vcycle
from poisson_ellipse_tpu.mg.transfer import (
    prolong_bilinear,
    restrict_full_weighting,
)
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.ops.stencil import apply_a, apply_dinv
from poisson_ellipse_tpu.solver.pcg import pcg as run_pcg

# iterations of the diagonal-PCG bounds probe: enough Lanczos steps for
# a tight λmax (converges in ~10) and a usable λmin order of magnitude
PROBE_ITERS = 48

# standalone Chebyshev preconditioner degree: each PCG iteration pays
# degree−1 extra stencil passes for a ~degree× iteration cut — 12 keeps
# the wall-clock trade profitable while staying far from f32 recurrence
# round-off
DEFAULT_CHEB_DEGREE = 12


@dataclasses.dataclass(frozen=True)
class PrecondConfig:
    """Static preconditioner configuration for one grid bucket."""

    kind: str  # "mg" | "cheb"
    levels: int
    nu: int = vcycle.DEFAULT_NU
    coarse_degree: int = vcycle.DEFAULT_COARSE_DEGREE
    cheb_degree: int = DEFAULT_CHEB_DEGREE
    lo: float = 0.0  # Lanczos/Gershgorin interval actually used
    hi: float = cheby.GERSHGORIN_LMAX


def default_config(problem: Problem, kind: str) -> PrecondConfig:
    """The per-grid-bucket static config (level count from the grid)."""
    if kind not in ("mg", "cheb"):
        raise ValueError(f"unknown preconditioner kind: {kind!r}")
    levels = coarsen.num_levels(problem.M, problem.N) if kind == "mg" else 1
    return PrecondConfig(kind=kind, levels=levels)


def lanczos_bounds(problem: Problem, a, b, rhs,
                   probe_iters: int = PROBE_ITERS):
    """(λ_lo, λ_hi) of D⁻¹A from a short diagonal-PCG probe, or None.

    One jitted ``probe_iters``-capped history solve; the recorded α/β
    feed ``obs.spectrum.eigenvalue_bounds`` — the single shared Lanczos
    path, not a reimplementation. Build-time only, never on the hot path.
    """
    from poisson_ellipse_tpu.obs import spectrum as obs_spectrum

    probe = dataclasses.replace(
        problem, max_iter=min(probe_iters, problem.max_iterations)
    )
    # single-shot by design: the probe runs once per build, and the
    # operands are the caller's — not this jit's to donate
    _res, trace = jax.jit(  # tpulint: disable=TPU004,TPU006
        lambda a, b, rhs: run_pcg(probe, a, b, rhs, history=True)
    )(a, b, rhs)
    return obs_spectrum.eigenvalue_bounds(trace)


def resolve_config(problem: Problem, a, b, rhs, kind: str) -> PrecondConfig:
    """``default_config`` with the probe's spectral interval filled in."""
    cfg = default_config(problem, kind)
    lo, hi = cheby.clip_interval(lanczos_bounds(problem, a, b, rhs))
    return dataclasses.replace(cfg, lo=lo, hi=hi)


def _level_ops(levels: list[coarsen.Level], cfg: PrecondConfig,
               fine_a=None, fine_b=None) -> list[vcycle.LevelOps]:
    """Global-layout LevelOps per level. The finest level's stencil runs
    on the CALLER's operands (``fine_a``/``fine_b`` — the same arrays
    the PCG loop streams, so no duplicate resident copy of the big
    grid); coarse levels close over the hierarchy's baked arrays.

    The smoothing band is anchored at the probe's λ_hi on every level —
    coarsened coefficients keep the Gershgorin cap, and the Jacobi
    scaling keeps the upper edge essentially level-independent. The
    low edge at level l scales the fine λ_lo by 4ˡ (κ ∝ h⁻²), capped
    inside the band — only the coarsest solve interval consumes it, and
    an overestimate costs sweeps, never definiteness (``mg.cheby``).
    """
    smooth_lo, smooth_hi = cheby.smoother_interval(cfg.hi)
    out = []
    for l, lv in enumerate(levels):
        a = fine_a if (l == 0 and fine_a is not None) else lv.a
        b = fine_b if (l == 0 and fine_b is not None) else lv.b
        h1 = jnp.asarray(lv.h1, lv.d.dtype)
        h2 = jnp.asarray(lv.h2, lv.d.dtype)
        d = lv.d

        def make_apply(a=a, b=b, h1=h1, h2=h2):
            return lambda x: apply_a(x, a, b, h1, h2)

        def make_dinv(d=d):
            return lambda x: apply_dinv(x, d)

        solve_lo = min(cfg.lo * (4.0 ** l), smooth_hi / 4.0)
        last = l == len(levels) - 1
        fine_shape = lv.node_shape

        out.append(vcycle.LevelOps(
            apply_a=make_apply(),
            dinv=make_dinv(),
            smooth_lo=smooth_lo,
            smooth_hi=cfg.hi,
            solve_lo=solve_lo,
            restrict=None if last else restrict_full_weighting,
            prolong=None if last else (
                lambda uc, shape=fine_shape: prolong_bilinear(uc, shape)
            ),
        ))
    return out


def apply_overrides(cfg: PrecondConfig, overrides: dict | None,
                    max_levels: int) -> PrecondConfig:
    """A probed config with the autotuner's knob overrides applied —
    only the knobs the kind owns, levels clamped to what the grid can
    coarsen to. The spectral interval is untouched: knobs change the
    cycle shape, the probe stays the single source of the bounds."""
    if not overrides:
        return cfg
    fields = {"levels", "nu", "coarse_degree", "cheb_degree"}
    picked = {
        k: int(v) for k, v in overrides.items()
        if k in fields and v is not None
    }
    if "levels" in picked:
        picked["levels"] = max(1, min(picked["levels"], max_levels))
    return dataclasses.replace(cfg, **picked) if picked else cfg


def make_precond(problem: Problem, dtype=jnp.float32, kind: str = "mg",
                 config: PrecondConfig | None = None, operands=None,
                 geometry=None, theta=None, overrides: dict | None = None):
    """(precond_factory, config): the engine-facing build.

    ``precond_factory(a, b) -> (r -> M⁻¹ r)`` is called INSIDE the
    solver trace with the solve's own fine operands; the hierarchy and
    spectral interval are resolved here, once, on the host. ``operands``
    lets a caller that already assembled (a, b, rhs) skip the duplicate
    assembly (the guard's fallback path hands its own operands over).
    A supplied ``config`` carrying a degenerate interval (the dataclass
    default lo=0.0 — only ``resolve_config`` fills a probed one) is
    normalised through the Gershgorin fallback instead of crashing the
    Chebyshev setup at trace time. ``overrides`` applies the autotune
    registry's knobs (levels/ν/degrees) ON TOP of the probed config —
    the consult path of ``build_solver(engine="auto")``, so a tuned
    cheb_degree actually runs instead of decorating the registry.
    """
    a, b, rhs = (
        operands if operands is not None
        else assembly.assemble(problem, dtype, geometry=geometry,
                               theta=theta)
    )
    cfg = config if config is not None else resolve_config(
        problem, a, b, rhs, kind
    )
    cfg = apply_overrides(
        cfg, overrides, coarsen.num_levels(problem.M, problem.N)
    )
    lo, hi = cheby.clip_interval((cfg.lo, cfg.hi))
    if (lo, hi) != (cfg.lo, cfg.hi):
        cfg = dataclasses.replace(cfg, lo=lo, hi=hi)
    if cfg.kind == "cheb":
        hier = None
    else:
        hier = coarsen.build_hierarchy(
            problem, dtype, geometry=geometry, theta=theta
        )[: cfg.levels]

    def factory(fine_a, fine_b):
        if cfg.kind == "cheb":
            from poisson_ellipse_tpu.ops.stencil import diag_d

            h1 = jnp.asarray(problem.h1, dtype)
            h2 = jnp.asarray(problem.h2, dtype)
            d = diag_d(fine_a, fine_b, h1, h2)
            return lambda r: cheby.chebyshev_apply(
                lambda x: apply_a(x, fine_a, fine_b, h1, h2),
                lambda x: apply_dinv(x, d),
                r, cfg.lo, cfg.hi, cfg.cheb_degree,
            )
        ops = _level_ops(hier, cfg, fine_a=fine_a, fine_b=fine_b)
        return vcycle.make_vcycle(ops, nu=cfg.nu,
                                  coarse_degree=cfg.coarse_degree)

    return factory, cfg


def build_precond_solver(problem: Problem, engine: str, dtype=jnp.float32,
                         history: bool = False, geometry=None, theta=None,
                         overrides: dict | None = None):
    """(jitted solver, args, resolved engine) — the ``solver.engine``
    branch for ``mg-pcg`` / ``cheb-pcg``. Same contract as every other
    engine: args = the assembled (a, b, rhs), one fused while_loop, the
    ``PCGResult`` (+ optional ``ConvergenceTrace``) out. ``geometry``/
    ``theta`` flow into the fine assembly AND the coarsening hierarchy
    (``mg.coarsen``) so every level sees the same domain; ``overrides``
    is the autotune registry's knob dict (see ``make_precond``)."""
    from poisson_ellipse_tpu.solver.engine import PRECOND_KIND_BY_ENGINE

    a, b, rhs = assembly.assemble(problem, dtype, geometry=geometry,
                                  theta=theta)
    factory, _cfg = make_precond(
        problem, dtype, PRECOND_KIND_BY_ENGINE[engine],
        operands=(a, b, rhs), geometry=geometry, theta=theta,
        overrides=overrides,
    )

    # no donation: the build-once-call-many contract re-feeds these
    # operands on every dispatch (the timing protocols re-dispatch)
    solver = jax.jit(  # tpulint: disable=TPU004
        lambda a, b, rhs: run_pcg(
            problem, a, b, rhs, history=history, precond=factory(a, b)
        )
    )
    return solver, (a, b, rhs), engine


def modeled_extra_passes(problem: Problem, engine: str,
                         dtype=jnp.float32) -> float:
    """HBM array-passes the preconditioner adds per PCG iteration, for
    ``harness.roofline``'s traffic model. Each Chebyshev step streams
    one stencil application (4 passes: read x, a, b; write) plus the
    pointwise D⁻¹-scaled update (~3 passes); level-l arrays are 4⁻ˡ of
    the fine array. Transfers add ~2 fine-equivalent passes per level
    pair. A model, not a measurement — same stance as the rest of the
    roofline module. The preconditioner kind comes from the engine-
    capability table, so ``fmg`` (whose handoff loop IS the V-cycle-
    preconditioned loop) models like ``mg-pcg`` without a special case."""
    from poisson_ellipse_tpu.solver.engine import ENGINE_CAPS

    per_apply = 7.0
    cfg = default_config(problem, ENGINE_CAPS[engine]["precond_kind"])
    if cfg.kind == "cheb":
        return per_apply * max(cfg.cheb_degree - 1, 0) + 2.0
    applies = vcycle.stencil_applies_per_cycle(
        cfg.levels, cfg.nu, cfg.coarse_degree
    )
    passes = sum(n * per_apply * (0.25 ** l) for l, n in enumerate(applies))
    transfers = sum(2.0 * (0.25 ** l) for l in range(cfg.levels - 1))
    return passes + transfers
