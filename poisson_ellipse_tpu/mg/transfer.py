"""Grid-transfer operators: bilinear prolongation, full-weighting restriction.

Both layouts the framework's stencils support get a transfer pair:

- **global**: full (M+1, N+1) node grids with the Dirichlet ring at
  rows/cols 0, M, N (the single-chip engines' layout). Coarse and fine
  grids nest node-on-node: coarse node (I, J) sits at fine node
  (2I, 2J), which requires M and N even — ``mg.coarsen`` picks the
  level count so this holds at every level.
- **block**: one device's halo-extended block (the ``shard_map`` layout
  of ``parallel``): restriction consumes a halo-extended fine block,
  prolongation a halo-extended coarse block, so one
  ``parallel.halo.halo_extend`` round per transfer is the whole
  communication story (4 ``lax.ppermute``; no psum — the V-cycle adds
  ZERO scalar collectives to the PCG iteration).

The pair is a (scaled) adjoint: ``R = Pᵀ/4`` exactly, including the
boundary handling — both operators mask the Dirichlet ring of their
input and output, which makes the matrix identity hold on the full node
space, not just the interior (pinned as dense matrices in
``tests/test_mg.py``). The 1/4 is the standard 2D full-weighting scale
(Pᵀ's rows sum to 4); symmetry of the V-cycle preconditioner needs only
``R ∝ Pᵀ``, which this fixes by construction rather than by audit.
"""

from __future__ import annotations

import jax.numpy as jnp


def zero_ring(u):
    """Zero the outermost ring (the Dirichlet boundary of a node grid)."""
    return jnp.pad(u[1:-1, 1:-1], 1)


def _interleave(ce, cr, de, dr):
    """(m, n) corner values → the (2m, 2n) bilinear interleave.

    ``ce`` holds coarse values, ``cr``/``de``/``dr`` their right/down/
    diagonal neighbours; fine node (2i, 2j) gets ce, (2i, 2j+1) the
    x-average, (2i+1, 2j) the y-average, (2i+1, 2j+1) the 4-average.
    Built by stack-and-reshape rather than strided scatter: one fused
    elementwise pass, and no mixing of varying values into an unvarying
    zeros buffer under ``shard_map``'s vma checking.
    """
    m, n = ce.shape
    top = jnp.stack([ce, 0.5 * (ce + cr)], axis=-1).reshape(m, 2 * n)
    bot = jnp.stack(
        [0.5 * (ce + de), 0.25 * (ce + cr + de + dr)], axis=-1
    ).reshape(m, 2 * n)
    return jnp.stack([top, bot], axis=1).reshape(2 * m, 2 * n)


def prolong_bilinear(uc, fine_shape: tuple[int, int]):
    """Bilinear interpolation coarse (Mc+1, Nc+1) → fine (2Mc+1, 2Nc+1).

    Fine node (2I, 2J) receives the coarse value; odd fine nodes the
    2-point (edges) / 4-point (cell centers) averages. The coarse ring
    is masked first, so the operator's matrix has zero columns for ring
    coarse nodes and zero rows for ring fine nodes — the exact partner
    of :func:`restrict_full_weighting`'s masking.
    """
    uc = zero_ring(uc)
    u = _interleave(uc[:-1, :-1], uc[:-1, 1:], uc[1:, :-1], uc[1:, 1:])
    # the last fine row/col (2Mc, 2Nc) is the Dirichlet ring: the
    # masked coarse ring value, i.e. exactly zero
    return jnp.pad(u, ((0, 1), (0, 1)))


def restrict_full_weighting(uf):
    """Full-weighting restriction fine (M+1, N+1) → coarse (M/2+1, N/2+1).

    The 9-point stencil 1/16·[1 2 1; 2 4 2; 1 2 1] — exactly Pᵀ/4 of
    :func:`prolong_bilinear` (both rings masked; adjoint pinned as
    matrices in ``tests/test_mg.py``).
    """
    uf = zero_ring(uf)
    g1, g2 = uf.shape
    mc, nc = (g1 - 1) // 2, (g2 - 1) // 2
    up = jnp.pad(uf, 1)

    def tap(di: int, dj: int):
        # tap(di, dj)[I, J] = uf[2I + di, 2J + dj], zero off the grid
        return up[1 + di : 2 + di + 2 * mc : 2, 1 + dj : 2 + dj + 2 * nc : 2]

    out = 0.25 * (
        tap(0, 0)
        + 0.5 * (tap(-1, 0) + tap(1, 0) + tap(0, -1) + tap(0, 1))
        + 0.25 * (tap(-1, -1) + tap(-1, 1) + tap(1, -1) + tap(1, 1))
    )
    return zero_ring(out)


# -- block (shard_map) layout ------------------------------------------------


def restrict_block(uf_ext):
    """Full-weighting over one halo-extended fine block.

    (bm+2, bn+2) halo-extended fine block → (bm/2, bn/2) coarse block.
    Coarse local (ic, jc) sits at fine local (2ic, 2jc) — blocks stay
    aligned because the mg-sharded padding keeps every level's block
    even (``parallel.mg_sharded``). The 9-point gather reaches across
    the shard edge through the halo, so the one ``halo_extend`` the
    caller already paid is the entire communication. The caller masks
    the result with the coarse level's global-interior mask (the block
    twin of the global form's ring-zeroing).
    """
    bm, bn = uf_ext.shape[0] - 2, uf_ext.shape[1] - 2
    bmc, bnc = bm // 2, bn // 2

    def tap(di: int, dj: int):
        # tap(di, dj)[ic, jc] = fine_local[2ic + di, 2jc + dj]
        return uf_ext[
            1 + di : 2 + di + 2 * (bmc - 1) + 1 : 2,
            1 + dj : 2 + dj + 2 * (bnc - 1) + 1 : 2,
        ]

    return 0.25 * (
        tap(0, 0)
        + 0.5 * (tap(-1, 0) + tap(1, 0) + tap(0, -1) + tap(0, 1))
        + 0.25 * (tap(-1, -1) + tap(-1, 1) + tap(1, -1) + tap(1, 1))
    )


def prolong_block(uc_ext, fine_block_shape: tuple[int, int]):
    """Bilinear interpolation over one halo-extended coarse block.

    (bmc+2, bnc+2) halo-extended coarse block → (bm, bn) = (2bmc, 2bnc)
    fine block. Odd fine rows/cols straddle the high block edge, which
    the coarse halo supplies — again one ``halo_extend`` is the whole
    exchange. The caller masks with the fine level's interior mask.
    """
    bm, bn = fine_block_shape
    u = _interleave(
        uc_ext[1:-1, 1:-1], uc_ext[1:-1, 2:],
        uc_ext[2:, 1:-1], uc_ext[2:, 2:],
    )
    assert u.shape == (bm, bn), (u.shape, fine_block_shape)
    return u
