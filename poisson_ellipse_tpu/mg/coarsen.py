"""Fictitious-domain-aware coefficient coarsening + the level hierarchy.

The operator's difficulty is the ε-jump: face coefficients are 1 inside
the ellipse and 1/ε = 1/max(h1,h2)² outside (``ops/assembly.py``), four
to eight orders of magnitude at the published grids. A coarse operator
that arithmetic-averages across that jump overestimates the flux through
the interface by ~1/ε and the V-cycle stalls on interface modes, so the
coarsening here is the flux-preserving face average of the cell-centered
multigrid literature (Alcouffe et al.'s diffusion-coefficient MG; the
same choice Tatebe's MGCG setup makes for discontinuous coefficients):

- **harmonic** across the two fine faces stacked along the flux
  direction (serial resistors: the jump survives, the 1/ε side does not
  swamp the 1 side), then
- **arithmetic** (geometric-overlap weighted ¼, ½, ¼) across the three
  fine face strips the coarse face spans tangentially (parallel
  conductors).

A coarse face of a level-(l+1) grid at coarse node (I, J) covers fine
faces {2I−1, 2I} × {2J−1, 2J, 2J+1} of level l; the resulting
coefficients are strictly positive wherever the fine ones are, so every
coarse operator is again a 5-point SPD M-matrix with λ(D⁻¹A) ⊂ (0, 2]
by the same Gershgorin row argument as the fine level — SPD is pinned
numerically in ``tests/test_mg.py``, not assumed.

Coarsening runs on the HOST in float64 (the same rounded-once fidelity
stance as ``ops.assembly.assemble_numpy``: f32 coefficient noise is
amplified 1/ε by the blend law) and each level is cast to the solve
dtype exactly once.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.ops.stencil import diag_d

# levels stop when the next grid would fall below this many cells per
# side (the coarsest level is solved by a heavier Chebyshev sweep, so a
# handful of cells is enough) or exceed this depth (a static budget —
# level count must be a compile-time constant per grid bucket, tpulint
# TPU013's contract)
MIN_COARSE_CELLS = 4
MAX_LEVELS = 8


def _harm(u, v, xp):
    """Element-wise harmonic mean with the zero guard (zeros stay zero:
    an absent face — boundary ring, shard padding — must not conjure
    conductance)."""
    s = u + v
    safe = xp.where(s > 0, s, 1.0)
    return xp.where(s > 0, 2.0 * u * v / safe, 0.0)


def num_levels(M: int, N: int, max_levels: int = MAX_LEVELS,
               min_cells: int = MIN_COARSE_CELLS) -> int:
    """Static level count for an M×N grid (1 = no coarsening).

    Halving stops at odd cell counts (node-nested coarsening needs even
    M, N), below ``min_cells``, or at the ``max_levels`` budget.
    """
    levels = 1
    while (
        levels < max_levels
        and M % 2 == 0 and N % 2 == 0
        and M // 2 >= min_cells and N // 2 >= min_cells
    ):
        M //= 2
        N //= 2
        levels += 1
    return levels


def coarsen_coefficients(a, b, xp=np):
    """One level of face-coefficient coarsening: (M+1, N+1) → (M/2+1, N/2+1).

    ``a`` lives on vertical faces (flux along x): harmonic across rows
    {2I−1, 2I}, overlap-weighted arithmetic across columns
    {2J−1, 2J, 2J+1}; ``b`` symmetrically. Entries outside the valid
    face range stay zero (the assembly convention).
    """
    M, N = a.shape[0] - 1, a.shape[1] - 1
    if M % 2 or N % 2:
        raise ValueError(f"coarsening needs even cell counts, got {M}x{N}")
    mc, nc = M // 2, N // 2

    ha = _harm(a[1:M:2, :], a[2 : M + 1 : 2, :], xp)  # (mc, N+1)
    hap = xp.pad(ha, ((0, 0), (0, 1)))
    ac = (
        0.25 * hap[:, 1:N:2]
        + 0.5 * hap[:, 2 : N + 1 : 2]
        + 0.25 * hap[:, 3 : N + 2 : 2]
    )
    ac = xp.pad(ac, ((1, 0), (1, 0)))

    hb = _harm(b[:, 1:N:2], b[:, 2 : N + 1 : 2], xp)  # (M+1, nc)
    hbp = xp.pad(hb, ((0, 1), (0, 0)))
    bc = (
        0.25 * hbp[1:M:2, :]
        + 0.5 * hbp[2 : M + 1 : 2, :]
        + 0.25 * hbp[3 : M + 2 : 2, :]
    )
    bc = xp.pad(bc, ((1, 0), (1, 0)))
    assert ac.shape == (mc + 1, nc + 1) and bc.shape == (mc + 1, nc + 1)
    return ac, bc


@dataclasses.dataclass(frozen=True)
class Level:
    """One grid level's operator data (device arrays, solve dtype)."""

    M: int
    N: int
    h1: float
    h2: float
    a: jnp.ndarray
    b: jnp.ndarray
    d: jnp.ndarray  # diag of A, zero on the ring (the smoother's D)

    @property
    def node_shape(self) -> tuple[int, int]:
        return (self.M + 1, self.N + 1)


def coefficient_hierarchy(problem: Problem, geometry=None,
                          theta=None) -> list[dict]:
    """Host-f64 (a, b) per level, finest first — the shared source both
    the single-chip and the mg-sharded builders cast/lay out from.

    ``geometry``/``theta`` select the SDF quadrature assembly for the
    finest level (``ops.assembly``); the coarsening law is untouched —
    harmonic-then-arithmetic preserves strict positivity for ANY
    positive fine coefficients, so every coarse operator stays a
    5-point SPD M-matrix under composite SDFs exactly as under the
    closed-form ellipse (pinned in ``tests/test_geom.py``)."""
    a, b, _ = assembly.assemble_numpy(problem, geometry=geometry,
                                      theta=theta)
    levels = num_levels(problem.M, problem.N)
    out = [{
        "M": problem.M, "N": problem.N,
        "h1": problem.h1, "h2": problem.h2, "a": a, "b": b,
    }]
    for _ in range(levels - 1):
        prev = out[-1]
        ac, bc = coarsen_coefficients(prev["a"], prev["b"], np)
        out.append({
            "M": prev["M"] // 2, "N": prev["N"] // 2,
            "h1": prev["h1"] * 2.0, "h2": prev["h2"] * 2.0,
            "a": ac, "b": bc,
        })
    return out


def build_hierarchy(problem: Problem, dtype=jnp.float32, geometry=None,
                    theta=None) -> list[Level]:
    """The device-resident level list (finest first) for one chip.

    Coefficients are coarsened on the host in f64 and cast once; the
    per-level diagonal is computed in the solve dtype, matching the fine
    engine's ``diag_d``-of-cast-operands arithmetic exactly at level 0.
    """
    np_dtype = assembly.numpy_dtype(dtype)
    out = []
    for lv in coefficient_hierarchy(problem, geometry=geometry, theta=theta):
        a = jnp.asarray(lv["a"].astype(np_dtype))
        b = jnp.asarray(lv["b"].astype(np_dtype))
        h1 = jnp.asarray(lv["h1"], dtype)
        h2 = jnp.asarray(lv["h2"], dtype)
        out.append(Level(
            M=lv["M"], N=lv["N"], h1=lv["h1"], h2=lv["h2"],
            a=a, b=b, d=diag_d(a, b, h1, h2),
        ))
    return out
