"""The symmetric V-cycle, generic over layout — written once, run anywhere.

``make_vcycle`` consumes a list of per-level closure bundles
(:class:`LevelOps`) and returns the preconditioner applier
``z = M⁻¹ r``. The closures carry every layout decision — global node
grids for the single-chip engines, halo-exchanged shard blocks for the
mesh form (``parallel.mg_sharded``) — so the cycle structure, the
symmetry argument and the collective discipline live in exactly one
place instead of once per engine family.

Structure (Tatebe's multigrid-preconditioned CG):

    pre-smooth from zero:   x  = B r            (ν Chebyshev steps)
    coarse-grid correction: x += P Mc⁻¹ R (r − A x)
    post-smooth:            x  = x + B (r − A x) (ν steps, same B)

Symmetry is by construction, not luck: B = p(D⁻¹A)D⁻¹ is a symmetric
matrix (``mg.cheby``), R = Pᵀ/4 (``mg.transfer``), the coarse operator
is symmetric (5-point, coarsened coefficients), and Mc⁻¹ is recursively
the same shape with a pure-Chebyshev coarsest solve — so the A-adjoint
of the pre-smoothing error propagator I − BA is itself, and
M⁻¹ = M⁻ᵀ follows level by level. Fixed ν and degree keep M linear:
standard PCG remains valid (no flexible-CG escape hatch), asserted as
⟨M⁻¹x, y⟩ = ⟨x, M⁻¹y⟩ on random vectors in ``tests/test_mg.py``.

The recursion below is PYTHON recursion over a STATIC level list — it
unrolls into the one traced computation at compile time (the whole
V-cycle runs inside the PCG ``lax.while_loop`` body with zero host
syncs). Re-tracing per call — a level count that varies at run time —
is the recompile hazard tpulint TPU013 exists to flag.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

from poisson_ellipse_tpu.mg.cheby import chebyshev_apply

# pre/post smoothing degree and the coarsest-level Chebyshev "solve"
# degree: V(2,2) with a degree-~24 coarsest sweep is the classical
# robust default for coefficient-jump problems; both are static config
# per grid bucket (never data-dependent)
DEFAULT_NU = 2
DEFAULT_COARSE_DEGREE = 24


@dataclasses.dataclass(frozen=True)
class LevelOps:
    """One level's closures. ``restrict`` maps this level's residual to
    the NEXT level; ``prolong`` lifts the next level's correction back
    (both None on the coarsest). ``smooth_lo/hi`` is the Chebyshev
    smoothing band; ``solve_lo`` the coarsest level's full-interval low
    edge (used only when this level is last)."""

    apply_a: Callable
    dinv: Callable
    smooth_lo: float
    smooth_hi: float
    solve_lo: float
    restrict: Callable | None = None
    prolong: Callable | None = None


def make_vcycle(levels: list[LevelOps], nu: int = DEFAULT_NU,
                coarse_degree: int = DEFAULT_COARSE_DEGREE) -> Callable:
    """The ``z = M⁻¹ r`` applier for a static level list (finest first).

    A single level degenerates to one Chebyshev application (the
    standalone polynomial preconditioner with the smoothing band
    replaced by the full interval) — the mg engine on an uncoarsenable
    grid still returns a valid SPD preconditioner.
    """
    if not levels:
        raise ValueError("need at least one level")

    def cycle(l: int, r):
        ops = levels[l]
        if l == len(levels) - 1:
            # coarsest: a heavier Chebyshev sweep over the full interval
            # approximates the coarse solve — still a fixed polynomial,
            # still symmetric, no factorization, no host work
            return chebyshev_apply(
                ops.apply_a, ops.dinv, r, ops.solve_lo, ops.smooth_hi,
                coarse_degree,
            )
        x = chebyshev_apply(
            ops.apply_a, ops.dinv, r, ops.smooth_lo, ops.smooth_hi, nu
        )
        coarse_r = ops.restrict(r - ops.apply_a(x))
        x = x + ops.prolong(cycle(l + 1, coarse_r))
        return chebyshev_apply(
            ops.apply_a, ops.dinv, r, ops.smooth_lo, ops.smooth_hi, nu, x=x
        )

    return lambda r: cycle(0, r)


def stencil_applies_per_cycle(n_levels: int, nu: int = DEFAULT_NU,
                              coarse_degree: int = DEFAULT_COARSE_DEGREE,
                              ) -> list[int]:
    """A-applications per level for one V-cycle application, finest
    first — the static cost model ``harness.roofline`` and the halo
    accounting (``parallel.mg_sharded.halos_per_precond``) share.

    Per non-coarsest level: pre-smooth ν−1 (zero start), residual 1,
    post-smooth ν (nonzero start); coarsest: degree−1.
    """
    if n_levels == 1:
        return [coarse_degree - 1]
    return [2 * nu] * (n_levels - 1) + [coarse_degree - 1]
