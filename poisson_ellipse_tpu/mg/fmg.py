"""Full multigrid (FMG): the O(N) F-cycle solver with a verified handoff.

PR 8 made the V-cycle a *preconditioner* — the iteration count stopped
growing with the grid, but every solve still starts from zero and pays
O(10¹) fine-grid iterations. The F-cycle here is multigrid as the
*solver* (Brandt's classical full-multigrid result): nested iteration
from the coarsest level up —

    f_0 = rhs;  f_{l+1} = R f_l            (restrict the RHS down)
    x_L = Chebyshev-solve(f_L)             (coarsest: a fixed polynomial)
    for l = L−1 … 0:
        x_l  = P x_{l+1}                   (bilinear prolongation of the
                                            coarse solution = the fine
                                            initial guess)
        x_l += ν_f V-cycles on f_l − A x_l (error correction at level l)

Each level's correction costs a CONSTANT number of stencil applications
per point of that level, and level sizes shrink geometrically (4⁻ˡ in
2D), so the whole solve is O(N) work — constant work units per fine
grid point (:func:`work_units_per_point`, pinned ±20% across grid sizes
in ``tests/test_fmg.py``) — and reaches discretization-level accuracy
(l2-vs-analytic parity with mg-pcg, PAPER.md §0) in one pass.

Accuracy is VERIFIED, never assumed — the same discipline as the
guard's false-convergence check: the F-cycle solution seeds a
warm-started mg-pcg loop (``solver.pcg.init_state(x0=...)`` rebuilds
the TRUE residual r = rhs − A·x0) that runs until the step-norm rule
meets the requested δ. When the F-cycle already landed at
discretization accuracy the handoff exits after one verification
iteration; when it missed — a rough geometry, an adversarial RHS — the
handoff IS mg-pcg from a very good start, converging in the few
iterations the remaining error costs. ``PCGResult.iters`` counts the
handoff iterations (the F-cycle's work is static and reported by the
work-unit model, not the iteration counter).

The cycle is generic over layout exactly like ``mg.vcycle``: it
consumes the same :class:`~poisson_ellipse_tpu.mg.vcycle.LevelOps`
closure bundles, so the single-chip form (global node grids, built
here) and the mesh form (halo-exchanged shard blocks,
``parallel.mg_sharded.build_fmg_sharded_solver``) share one cycle
definition. Level count, ν and degrees are STATIC per grid bucket
(tpulint TPU013's contract); the tunable knobs register in
``solver.engine.ENGINE_CAPS`` and are what ``runtime.autotune`` turns.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from poisson_ellipse_tpu.mg import cheby, coarsen, vcycle
from poisson_ellipse_tpu.mg.engine import (
    PrecondConfig,
    _level_ops,
    resolve_config,
)
from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.solver.pcg import (
    advance,
    init_state,
    result_of,
)

# V-cycles applied per level after prolongation: 1 is Brandt's textbook
# F-cycle; 2 buys a safety margin against the ε-jump's interface modes
# for one extra work unit, keeping the handoff at ~1 verification
# iteration on the published grids. Static per grid bucket; the
# autotuner (runtime.autotune) may select 1 where the spectrum allows.
DEFAULT_FMG_VCYCLES = 2


@dataclasses.dataclass(frozen=True)
class FMGConfig:
    """Static F-cycle configuration for one grid bucket: the V-cycle
    knobs of :class:`~poisson_ellipse_tpu.mg.engine.PrecondConfig` plus
    the per-level correction count ``n_vcycles``."""

    levels: int
    nu: int = vcycle.DEFAULT_NU
    coarse_degree: int = vcycle.DEFAULT_COARSE_DEGREE
    n_vcycles: int = DEFAULT_FMG_VCYCLES
    lo: float = 0.0
    hi: float = cheby.GERSHGORIN_LMAX

    def precond_config(self) -> PrecondConfig:
        """The equivalent V-cycle preconditioner config (the handoff
        loop's M⁻¹ and the shared ``_level_ops`` builder's input)."""
        return PrecondConfig(
            kind="mg", levels=self.levels, nu=self.nu,
            coarse_degree=self.coarse_degree, lo=self.lo, hi=self.hi,
        )


def default_fmg_config(problem: Problem) -> FMGConfig:
    """The per-grid-bucket static config (level count from the grid)."""
    return FMGConfig(levels=coarsen.num_levels(problem.M, problem.N))


def config_from_knobs(problem: Problem, knobs: dict | None,
                      ) -> FMGConfig | None:
    """An FMGConfig from the autotune registry's knob dict (None when
    no knobs apply) — the consult path of ``build_solver`` and the
    tuner's own measurement, so a tuned n_vcycles/levels actually runs.
    Levels clamp to what the grid can coarsen to; the spectral interval
    stays the probe's (``resolve_fmg_config`` fills it)."""
    if not knobs:
        return None
    max_levels = coarsen.num_levels(problem.M, problem.N)
    levels = int(knobs.get("levels") or max_levels)
    return FMGConfig(
        levels=max(1, min(levels, max_levels)),
        nu=int(knobs.get("nu", vcycle.DEFAULT_NU)),
        coarse_degree=int(
            knobs.get("coarse_degree", vcycle.DEFAULT_COARSE_DEGREE)
        ),
        n_vcycles=int(knobs.get("n_vcycles", DEFAULT_FMG_VCYCLES)),
    )


def resolve_fmg_config(problem: Problem, a, b, rhs,
                       config: FMGConfig | None = None) -> FMGConfig:
    """``default_fmg_config`` with the Lanczos-probed spectral interval
    filled in (the same single shared probe path as ``mg.engine``). A
    supplied config keeps its knobs; only a degenerate interval (the
    dataclass default lo=0.0) is re-probed."""
    cfg = config if config is not None else default_fmg_config(problem)
    if cfg.lo > 0.0:
        return cfg
    probed = resolve_config(problem, a, b, rhs, "mg")
    return dataclasses.replace(cfg, lo=probed.lo, hi=probed.hi)


def make_fcycle(levels: list[vcycle.LevelOps],
                nu: int = vcycle.DEFAULT_NU,
                coarse_degree: int = vcycle.DEFAULT_COARSE_DEGREE,
                n_vcycles: int = DEFAULT_FMG_VCYCLES):
    """The ``x ≈ A⁻¹ rhs`` F-cycle applier for a static level list
    (finest first) — layout-generic like :func:`mg.vcycle.make_vcycle`.

    A single level degenerates to the coarsest Chebyshev sweep (the
    uncoarsenable-grid case, same stance as the V-cycle's). The Python
    recursion/loops below unroll at trace time over the STATIC level
    list — one traced computation, zero host syncs (TPU013's contract).
    """
    if not levels:
        raise ValueError("need at least one level")
    if n_vcycles < 0:
        raise ValueError("n_vcycles must be >= 0")

    def fcycle(rhs):
        # restrict the RHS down the hierarchy (one pass, reused below)
        fs = [rhs]
        for ops in levels[:-1]:
            fs.append(ops.restrict(fs[-1]))
        last = levels[-1]
        x = cheby.chebyshev_apply(
            last.apply_a, last.dinv, fs[-1], last.solve_lo, last.smooth_hi,
            coarse_degree,
        )
        for l in range(len(levels) - 2, -1, -1):
            ops = levels[l]
            x = ops.prolong(x)
            if n_vcycles:
                # a trace-time unroll over the STATIC level list — one
                # V-cycle closure per level of one traced computation,
                # not a per-call rebuild (the level count is a
                # compile-time constant per grid bucket)
                vc = vcycle.make_vcycle(
                    levels[l:],  # tpulint: disable=TPU013 — static unroll
                    nu=nu, coarse_degree=coarse_degree,
                )
                for _ in range(n_vcycles):
                    x = x + vc(fs[l] - ops.apply_a(x))
        return x

    return fcycle


def work_units_per_point(levels: int, nu: int = vcycle.DEFAULT_NU,
                         coarse_degree: int = vcycle.DEFAULT_COARSE_DEGREE,
                         n_vcycles: int = DEFAULT_FMG_VCYCLES) -> float:
    """Fine-grid-equivalent stencil applications per fine grid point for
    one F-cycle — the O(N) claim as a number.

    A stencil application at level l touches 4⁻ˡ of the fine points, so
    the geometric level sum is bounded by 4/3 of the finest level's
    count regardless of depth: the model the constant-work-per-point pin
    in ``tests/test_fmg.py`` holds across grid sizes (±20% — the
    coarsest Chebyshev sweep and the tail levels contribute the slack).
    """
    applies = [0.0] * levels
    # the correction V-cycles starting at each level l cost the V-cycle
    # ladder over levels[l:]; one residual evaluation precedes each
    for l in range(levels - 1):
        per_level = vcycle.stencil_applies_per_cycle(
            levels - l, nu, coarse_degree
        )
        for j, n in enumerate(per_level):
            applies[l + j] += n_vcycles * n
        applies[l] += n_vcycles  # the f_l − A x_l residual per V-cycle
    applies[levels - 1] += coarse_degree - 1  # the coarsest direct sweep
    return sum(n * (0.25 ** l) for l, n in enumerate(applies))


def build_fmg_solver(problem: Problem, dtype=jnp.float32,
                     history: bool = False, geometry=None, theta=None,
                     config: FMGConfig | None = None):
    """(jitted solver, args, "fmg") — the ``solver.engine`` branch.

    Same contract as every other engine: args = the assembled
    (a, b, rhs), ONE jitted computation (the F-cycle unrolls into the
    trace, the handoff is the fused mg-pcg while_loop), a ``PCGResult``
    out (+ ``ConvergenceTrace`` with ``history=True`` — the handoff
    loop's iterations, recorded by the shared ``obs.convergence``
    buffers). ``geometry``/``theta`` flow into the fine assembly AND
    the coarsening hierarchy, exactly as for mg-pcg.
    """
    a, b, rhs = assembly.assemble(problem, dtype, geometry=geometry,
                                  theta=theta)
    cfg = resolve_fmg_config(problem, a, b, rhs, config)
    hier = coarsen.build_hierarchy(
        problem, dtype, geometry=geometry, theta=theta
    )[: cfg.levels]
    pc = cfg.precond_config()

    def run(a, b, rhs):
        ops = _level_ops(hier, pc, fine_a=a, fine_b=b)
        x0 = make_fcycle(ops, nu=cfg.nu, coarse_degree=cfg.coarse_degree,
                         n_vcycles=cfg.n_vcycles)(rhs)
        # the verified handoff: mg-pcg warm-started at the F-cycle
        # solution — the loop's first iteration computes the realised
        # step norm against δ, so convergence is measured, not assumed
        precond = vcycle.make_vcycle(ops, nu=cfg.nu,
                                     coarse_degree=cfg.coarse_degree)
        state = init_state(problem, a, b, rhs, history=history,
                           precond=precond, x0=x0)
        state = advance(problem, a, b, rhs, state, history=history,
                        precond=precond)
        result = result_of(state)
        if history:
            from poisson_ellipse_tpu.obs.convergence import trace_of

            return result, trace_of(state[8:], result.iters)
        return result

    # no donation: the build-once-call-many contract re-feeds these
    # operands on every dispatch (the timing protocols re-dispatch)
    solver = jax.jit(run)  # tpulint: disable=TPU004
    return solver, (a, b, rhs), "fmg"


def fmg_initial_guess(problem: Problem, dtype=jnp.float32, geometry=None,
                      theta=None, config: FMGConfig | None = None):
    """One jitted F-cycle: (x0, (a, b, rhs), cfg) — the warm-start
    prelude the guard threads through ``_ClassicalAdapter(x0=...)`` so
    a guarded fmg run chunk-steps the handoff loop (health word,
    residual restart, the mg→cheb→diag ladder) from the F-cycle seed."""
    a, b, rhs = assembly.assemble(problem, dtype, geometry=geometry,
                                  theta=theta)
    cfg = resolve_fmg_config(problem, a, b, rhs, config)
    hier = coarsen.build_hierarchy(
        problem, dtype, geometry=geometry, theta=theta
    )[: cfg.levels]
    pc = cfg.precond_config()

    def fcycle(a, b, rhs):
        ops = _level_ops(hier, pc, fine_a=a, fine_b=b)
        return make_fcycle(ops, nu=cfg.nu, coarse_degree=cfg.coarse_degree,
                           n_vcycles=cfg.n_vcycles)(rhs)

    # single-shot by design: the prelude runs once per guarded build and
    # the operands are re-fed to the chunked adapter afterwards
    x0 = jax.jit(fcycle)(a, b, rhs)  # tpulint: disable=TPU006
    return x0, (a, b, rhs), cfg
