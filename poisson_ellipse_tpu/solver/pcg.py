"""Single-chip preconditioned conjugate gradients, fully on-device.

The reference's PCG drivers (sequential ``solve`` at
``stage0/Withoutopenmp1.cpp:106-172``; distributed ``gradient_solver_mpi`` at
``stage4-mpi+cuda/poisson_mpi_cuda2.cu:687-982``) keep the scalar recurrence
(α, β, convergence decision) on the **host**, costing the CUDA stage ≥3
device↔host round-trips per iteration (dot partials + diff partials) plus a
device sync after every kernel. Here the entire loop — stencil, dots, axpy
updates, preconditioner, stopping rule — is one ``lax.while_loop`` traced
into a single XLA computation: zero host↔device transfers per iteration,
which is exactly the north-star design of BASELINE.json.

Semantics preserved from the reference loop, in order
(``stage0/Withoutopenmp1.cpp:124-169``):
  1. Ap = A·p;  denom = (Ap, p);  breakdown-exit if denom < 1e-15
  2. α = zr/denom;  w += αp;  r −= αAp
  3. z = D⁻¹r;  zr_new = (z, r)
  4. diff = ‖w^{k+1} − w^k‖ (norm convention per Problem.norm);
     converged-exit if diff < δ
  5. β = zr_new/zr;  p = z + βp
The returned iteration count matches the reference's (count of loop bodies
entered, including the one that triggers the exit).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs.convergence import (
    history_init,
    history_record,
    trace_of,
)
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.ops.precision import (
    load as _load,
    resolve_storage_dtype,
    store as _store,
)
from poisson_ellipse_tpu.ops.reduction import grid_dot, grid_dots
from poisson_ellipse_tpu.ops.stencil import apply_a, apply_dinv, diag_d

# PCG breakdown guard on the (Ap, p) denominator (stage0/Withoutopenmp1.cpp:128).
DENOM_GUARD = 1e-15


class PCGResult(NamedTuple):
    """Solver output: solution grid, iterations, final step-norm, exit flags."""

    w: jax.Array
    iters: jax.Array
    diff: jax.Array
    converged: jax.Array
    breakdown: jax.Array


def init_state(problem: Problem, a, b, rhs, history: bool = False,
               precond=None, storage_dtype=None, x0=None,
               recycle: int | None = None):
    """The PCG carry at iteration 0 (the resumable solver state).

    Layout: (k, w, r, p, zr, diff, converged, breakdown) — everything the
    loop needs to continue, so a saved state resumes bit-identically
    (solver.checkpoint builds on this). With ``history=True`` the four
    ``obs.convergence`` buffers ((cap,) each) ride appended to the core
    carry; the core layout is untouched.

    ``precond`` is the optional ``z = M⁻¹ r`` applier (a linear SPD
    operator — the multigrid V-cycle / Chebyshev appliers of ``mg``);
    None keeps the reference's diagonal preconditioner exactly.

    ``storage_dtype`` (``ops.precision``) stores the carry's vector
    fields (w, r, p) at that width — bf16 halves their HBM footprint —
    while the scalar recurrence (zr, diff) stays at compute width; None
    is byte-identical to the pre-storage-axis carry.

    ``x0`` warm-starts the recurrence: w = x0 with the TRUE residual
    r = rhs − A·x0 — the full-multigrid handoff (``mg.fmg``) seeds the
    loop with the F-cycle solution and the loop *verifies* it against δ
    instead of trusting it. ``x0=None`` is byte-identical to the
    historical zero start (r = rhs, no stencil application).

    ``recycle`` appends a (cap, M+1, N+1) Lanczos-vector ring
    (``solver.recycle``) as the LAST carry element — after the history
    buffers when both ride — holding ``recycle`` basis vectors at
    compute width, slot 0 seeded with v₁ here. ``recycle=None`` leaves
    the carry untouched (jaxpr-pinned).
    """
    dtype = rhs.dtype
    st = resolve_storage_dtype(storage_dtype, dtype)
    h1 = jnp.asarray(problem.h1, dtype)
    h2 = jnp.asarray(problem.h2, dtype)
    d = diag_d(a, b, h1, h2)
    if x0 is None:
        w0, r0 = jnp.zeros_like(rhs), rhs
    else:
        w0, r0 = x0, rhs - apply_a(x0, a, b, h1, h2)
    z0 = apply_dinv(r0, d) if precond is None else precond(r0)
    zr0 = grid_dot(z0, r0, h1, h2)
    state = (
        jnp.asarray(0, jnp.int32),
        _store(w0, st),
        _store(r0, st),
        _store(z0, st),  # p0 = z0
        zr0,
        jnp.asarray(jnp.inf, dtype),
        jnp.asarray(False),
        jnp.asarray(False),
    )
    if history:
        state = state + history_init(problem.max_iterations, dtype)
    if recycle:
        from poisson_ellipse_tpu.solver.recycle import ring_init

        # slot 0 = v₁ = z₀/√(z₀,r₀), the first Lanczos basis vector of
        # M⁻¹A in the M-inner product (solver.recycle's capture contract)
        ring = ring_init(problem, int(recycle), dtype)
        ok = zr0 > 0
        v1 = z0 * lax.rsqrt(jnp.where(ok, zr0, 1.0))
        ring = ring.at[0].set(jnp.where(ok, v1, ring[0]))
        state = state + (ring,)
    return state


def advance(problem: Problem, a, b, rhs, state, limit=None, stencil: str = "xla",
            history: bool = False, precond=None, storage_dtype=None,
            recycle: int | None = None):
    """Advance the PCG carry until convergence/breakdown or iteration
    ``limit`` (defaults to max_iterations). Returns the new carry.

    Running in chunks (limit=k, k+K, …) is bit-identical to one straight
    run: chunking only moves the while_loop boundary, not the arithmetic.

    ``history=True`` expects/returns the extended carry of
    ``init_state(..., history=True)`` and scatters each iteration's
    (zr, diff, α, β) into the appended ``obs.convergence`` buffers —
    pure extra on-device stores, so the iterate trajectory is
    bit-identical to ``history=False`` (and with it off, the traced
    computation is exactly the historyless one: jaxpr-pinned).

    ``precond`` swaps the diagonal preconditioner for an arbitrary
    linear SPD ``z = M⁻¹ r`` applier (``mg``'s V-cycle / Chebyshev);
    None traces exactly the historical diagonal loop.

    ``storage_dtype`` runs the storage-vs-compute split of
    ``ops.precision``: the carry's vectors AND the streamed operands
    (a, b, D) live at storage width in HBM, every read upcasts to the
    compute dtype in the consumer (XLA fuses the convert — the HBM read
    stays storage-width), every store rounds back down. None traces the
    byte-identical full-width loop.

    ``recycle`` expects/returns the ring-extended carry of
    ``init_state(..., recycle=cap)`` and scatters each iteration's
    Lanczos basis vector (the scaled preconditioned residual) into the
    appended ring (``solver.recycle``'s Krylov-recycling capture) —
    pure extra on-device stores, the same DUS discipline as the history
    buffers, so the iterate trajectory is bit-identical either way;
    with it off the traced computation is exactly the ringless one
    (jaxpr-pinned).
    """
    dtype = rhs.dtype
    st = resolve_storage_dtype(storage_dtype, dtype)
    h1 = jnp.asarray(problem.h1, dtype)
    h2 = jnp.asarray(problem.h2, dtype)
    delta = jnp.asarray(problem.delta, dtype)
    # the bound may be a traced scalar (checkpointed runs pass k+chunk per
    # dispatch without recompiling)
    max_iter = (
        problem.max_iterations
        if limit is None
        else jnp.minimum(
            jnp.asarray(limit, jnp.int32), problem.max_iterations
        )
    )
    weighted = problem.norm == "weighted"

    if st is not None and precond is not None:
        raise ValueError(
            "storage_dtype covers the diagonal-preconditioned loops; the "
            "mg/cheb appliers carry their own full-width level hierarchy "
            "— run them at compute width"
        )
    d = diag_d(a, b, h1, h2)
    if st is not None:
        # operands stream at storage width too (the byte cut covers every
        # HBM pass, not just the carry); rounded ONCE here, upcast inside
        # the body so the loads stay narrow
        a_s, b_s, d_s = _store(a, st), _store(b, st), _store(d, st)
    else:
        a_s, b_s, d_s = a, b, d

    if stencil == "pallas":
        if st is not None:
            from poisson_ellipse_tpu.ops.pallas_kernels import (
                apply_a_mixed_pallas,
            )

            # the explicit mixed kernel: storage-width tiles DMA'd to
            # VMEM, upcast there, f32 stencil arithmetic, compute-width out
            apply_stencil = lambda p: apply_a_mixed_pallas(
                p, a_s, b_s, problem.h1, problem.h2, compute_dtype=dtype
            )
        else:
            from poisson_ellipse_tpu.ops.pallas_kernels import apply_a_pallas

            apply_stencil = lambda p: apply_a_pallas(
                p, a, b, problem.h1, problem.h2
            )
    elif stencil == "xla":
        apply_stencil = lambda p: apply_a(
            _load(p, dtype, st), _load(a_s, dtype, st),
            _load(b_s, dtype, st), h1, h2,
        )
    else:
        raise ValueError(f"unknown stencil: {stencil!r}")

    apply_precond = (
        (lambda r: apply_dinv(r, _load(d_s, dtype, st)))
        if precond is None else precond
    )

    def cond(state):
        k, converged, breakdown = state[0], state[6], state[7]
        return (k < max_iter) & ~converged & ~breakdown

    def body(state):
        k, w_s, r_s, p_s, zr, _diff, _c, _bd = state[:8]
        # tile-local upcast to compute width (fused into the consumers —
        # the HBM reads stay storage-width); identity when st is None
        w = _load(w_s, dtype, st)
        r = _load(r_s, dtype, st)
        p = _load(p_s, dtype, st)
        ap = apply_stencil(p_s)
        denom = grid_dot(ap, p, h1, h2)
        breakdown = denom < DENOM_GUARD
        alpha = zr / jnp.where(breakdown, 1.0, denom)

        w_new = w + alpha * p
        r_new = r - alpha * ap
        z = apply_precond(r_new)

        # ‖w^{k+1} − w^k‖ computed from the realised update (w_new − w), not
        # α·p, for bitwise parity with the reference's w/w_prev difference
        # (stage0/Withoutopenmp1.cpp:149-154; stage4 update_w_r_kernel
        # poisson_mpi_cuda2.cu:626-660). Both post-update sums ride one
        # fused reduction — the same one-reduction idiom the sharded loop
        # stacks into a single psum (values bit-identical to the separate
        # grid_dot/grid_sumsq calls).
        dw = w_new - w
        sums = grid_dots((z, r_new), (dw, dw))
        zr_new = sums[0] * h1 * h2
        dw2 = sums[1]
        diff = jnp.sqrt(dw2 * h1 * h2) if weighted else jnp.sqrt(dw2)
        # a breakdown iteration discards its update, so it cannot also claim
        # convergence; report the diff of the state actually retained
        converged = ~breakdown & (diff < delta)
        diff = jnp.where(breakdown, _diff, diff)

        beta = zr_new / zr
        p_new = z + beta * p

        # On breakdown the reference exits *before* touching w/r (stage0:128);
        # keep the pre-update iterates in that (rare, terminal) case.
        # Stores round back to storage width (identity when st is None).
        w_out = jnp.where(breakdown, w_s, _store(w_new, st))
        r_out = jnp.where(breakdown, r_s, _store(r_new, st))
        p_out = jnp.where(breakdown | converged, p_s, _store(p_new, st))
        zr_out = jnp.where(breakdown | converged, zr, zr_new)
        out = (k + 1, w_out, r_out, p_out, zr_out, diff, converged, breakdown)
        if history:
            # raw zr/β, carry-held diff, applied α (0 on a breakdown
            # iteration, whose update is discarded — every engine's trace
            # reports the same thing for the same event) —
            # obs.convergence's recording contract; pure stores, no
            # effect on the iterates
            out = out + history_record(
                state[8:12] if recycle else state[8:], k, zr_new, diff,
                jnp.where(breakdown, 0.0, alpha), beta,
            )
        if recycle:
            from poisson_ellipse_tpu.solver.recycle import ring_record

            # slot k+1 = v_{k+2} = (−1)^{k+1} z_{k+1}/√(z,r)_{k+1}: the
            # next Lanczos basis vector, from arrays this body already
            # materialises — the host-side harvest pairs the ring with
            # the trace's tridiagonal to form approximate Ritz vectors;
            # pure stores, no effect on the iterates
            zr_ok = zr_new > 0
            sign = jnp.where(k % 2 == 0, -1.0, 1.0).astype(dtype)
            v_next = sign * z * lax.rsqrt(jnp.where(zr_ok, zr_new, 1.0))
            out = out + (
                ring_record(state[-1], k + 1, v_next, ~breakdown & zr_ok),
            )
        return out

    return lax.while_loop(cond, body, state)


def result_of(state) -> PCGResult:
    """View a PCG carry (core or history-extended) as a PCGResult."""
    k, w = state[0], state[1]
    diff, converged, breakdown = state[5], state[6], state[7]
    return PCGResult(
        w=w, iters=k, diff=diff, converged=converged, breakdown=breakdown
    )


def pcg(problem: Problem, a, b, rhs, stencil: str = "xla",
        history: bool = False, precond=None, storage_dtype=None,
        x0=None, recycle: int | None = None):
    """Run PCG for pre-assembled coefficients. All inputs (M+1, N+1).

    Jit-safe with ``problem`` static; the while_loop carries
    (k, w, r, p, zr, diff, converged, breakdown) entirely on device.

    stencil: "xla" (padded-slice arithmetic, XLA-fused) or "pallas" (the
    explicit VMEM-tiled kernel, ``ops.pallas_kernels.apply_a_pallas``).
    The two agree to 1-2 ulps — not bitwise — so iteration counts may
    differ by a step on ill-conditioned grids.

    history=True returns ``(PCGResult, obs.ConvergenceTrace)`` — the
    per-iteration (zr, diff, α, β) series captured on device with zero
    extra host syncs; the iterates are bit-identical either way.

    precond: optional ``z = M⁻¹ r`` applier replacing the diagonal
    preconditioner (see ``advance``; ``mg`` builds the V-cycle and
    Chebyshev appliers this hook exists for).

    storage_dtype: the HBM storage width of the carry vectors and
    streamed operands (``ops.precision``; "bf16" halves the loop's HBM
    bytes, compute stays at ``rhs.dtype``). None = storage == compute,
    byte-identical to the historical loop. The product path for bf16 is
    the guard (``resilience.guard``), whose ladder recovers full-width
    accuracy; the raw engine converges to the storage dtype's floor.

    x0: optional warm start, verified by the TRUE residual at init (see
    ``init_state``) — a wrong x0 costs iterations, never correctness.
    None is byte-identical to the zero start.

    recycle: capacity of the on-device search-direction ring
    (``solver.recycle``). Requires ``history=True`` (the harvest pairs
    the stored directions with the trace's Lanczos coefficients);
    returns ``(PCGResult, ConvergenceTrace, ring)``. None traces
    exactly the ringless computation (jaxpr-pinned).
    """
    if recycle and not history:
        raise ValueError(
            "recycle requires history=True: the Ritz harvest pairs the "
            "direction ring with the trace's Lanczos coefficients"
        )
    state = advance(
        problem, a, b, rhs,
        init_state(problem, a, b, rhs, history=history, precond=precond,
                   storage_dtype=storage_dtype, x0=x0, recycle=recycle),
        stencil=stencil, history=history, precond=precond,
        storage_dtype=storage_dtype, recycle=recycle,
    )
    result = result_of(state)
    if recycle:
        return result, trace_of(state[8:12], result.iters), state[-1]
    if history:
        return result, trace_of(state[8:], result.iters)
    return result


def solve(problem: Problem, dtype=jnp.float32, stencil: str = "xla",
          history: bool = False, storage_dtype=None):
    """Assemble and solve on a single chip (the stage0-shaped entry point)."""
    a, b, rhs = assembly.assemble(problem, dtype)
    return pcg(problem, a, b, rhs, stencil=stencil, history=history,
               storage_dtype=storage_dtype)
