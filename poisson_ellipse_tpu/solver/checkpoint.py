"""Checkpoint / resume for long PCG solves (orbax-backed).

The reference has no checkpointing at all — solver state is never
serialised, runs are start-to-finish (SURVEY §5 "Checkpoint / resume:
None"). This subsystem adds it the TPU-native way: the PCG carry
(``solver.pcg.init_state`` layout) is saved through orbax every
``chunk`` iterations and a restart resumes exactly: chunking only moves
the ``lax.while_loop`` boundary, not the arithmetic, so a checkpointed
run converges in the same iteration count as a straight one (asserted in
tests; the iterates agree bitwise under one compilation and to the ulp
across jit boundaries).

A checkpoint records a fingerprint of the Problem + dtype; resuming onto
a different discretisation is refused rather than silently producing a
mixed-state solve.

Durability is layered (the resilience contract):

- orbax's own commit protocol makes each *step* atomic — a step is
  written under a temporary name and renamed into place only when
  complete, so a kill mid-save never yields a half-step that
  ``latest_step`` would pick up.
- On top of that, every finalized step gets an ``integrity.json``
  manifest (relative path → byte size), itself written
  temp-then-rename, covering the window orbax's commit cannot: silent
  corruption *after* commit (truncation by a dying filesystem, disk
  damage). ``resume=True`` verifies the newest step against its
  manifest before touching orbax; a corrupt/truncated step — or one
  whose orbax restore throws — is **quarantined** (renamed to
  ``quarantined-<step>`` with an ``obs.trace``
  ``recovery:checkpoint-quarantine`` event) and the previous step is
  used, instead of crashing mid-restore. Only when no step survives
  does the run restart from iteration 0.

Sharded solves checkpoint the same way: pass ``mesh=`` and the persisted
carry is the mesh-sharded global state (w/r/p laid out ``P('x','y')``,
scalars replicated) from ``parallel.pcg_sharded.build_sharded_stepper``.
Orbax saves/restores the arrays with their shardings intact, so a killed
multi-chip run resumes mid-solve on the same mesh — the runs long enough
to need checkpointing are exactly the big sharded ones.
"""

from __future__ import annotations

import dataclasses
import json
import os
import warnings
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs import trace as obs_trace
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.solver.pcg import (
    PCGResult,
    advance,
    init_state,
    result_of,
)

STATE_KEYS = ("k", "w", "r", "p", "zr", "diff", "converged", "breakdown")

# per-step integrity manifest (relative path -> byte size), written
# temp-then-rename once the step is finalized on disk
MANIFEST_NAME = "integrity.json"


class CheckpointMismatchError(ValueError):
    """Resume refused: the checkpoint was written by a different
    problem/dtype/stencil/mesh. Deliberate refusal, not corruption —
    never quarantined."""


def _write_json_atomic(path: str, payload: dict) -> None:
    """Write-temp-then-rename: a kill mid-write leaves the old file (or
    nothing), never a torn one — os.replace is atomic on POSIX."""
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(payload, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def _fingerprint(problem: Problem, dtype, stencil: str, mesh_shape) -> dict:
    fp = dataclasses.asdict(problem)
    fp["dtype"] = str(jnp.dtype(dtype))
    # the xla and pallas stencils agree only to 1-2 ulps, so resuming a
    # run under the other operator would be a silent mixed-arithmetic
    # solve — fingerprint it like the discretisation itself
    fp["stencil"] = stencil
    # mesh shape fixes both the shard padding (array shapes) and the psum
    # reduction grouping; a resume onto a different mesh would be a
    # silently different f.p. computation
    fp["mesh"] = list(mesh_shape)
    return fp


def _state_to_tree(state) -> dict:
    return dict(zip(STATE_KEYS, state))


def _tree_to_state(tree):
    return tuple(jnp.asarray(tree[k]) for k in STATE_KEYS)


class CheckpointingSolver:
    """Single-chip PCG that persists its carry every ``chunk`` iterations.

    >>> solver = CheckpointingSolver(problem, "/path/ckpts", chunk=500)
    >>> result = solver.run()          # resumes automatically if killed
    """

    def __init__(
        self,
        problem: Problem,
        directory: str,
        chunk: int = 500,
        dtype=jnp.float32,
        stencil: str = "xla",
        keep: int = 2,
        mesh=None,
    ):
        import orbax.checkpoint as ocp

        if chunk < 1:
            raise ValueError("chunk must be >= 1")
        self.problem = problem
        self.chunk = chunk
        self.dtype = dtype
        self.stencil = stencil
        self.mesh = mesh
        self.directory = os.path.abspath(directory)
        if mesh is None:
            self._a, self._b, self._rhs = assembly.assemble(problem, dtype)
            self._init = lambda: init_state(
                problem, self._a, self._b, self._rhs
            )
            # one compiled advance reused for every chunk: the bound rides
            # in as a traced scalar. Built once per solver *instance* by
            # design (the operands are captured at __init__), so the
            # per-call-closure hazard does not apply; the carry is not
            # donated because _save hands it to orbax's async serializer.
            self._advance = jax.jit(  # tpulint: disable=TPU006
                lambda state, limit: advance(
                    problem,
                    self._a,
                    self._b,
                    self._rhs,
                    state,
                    limit=limit,
                    stencil=stencil,
                )
            )
            mesh_shape = (1, 1)
        else:
            from poisson_ellipse_tpu.parallel.mesh import AXIS_X, AXIS_Y
            from poisson_ellipse_tpu.parallel.pcg_sharded import (
                build_sharded_stepper,
            )

            self._init, self._advance = build_sharded_stepper(
                problem, mesh, dtype, stencil_impl=stencil
            )
            mesh_shape = (mesh.shape[AXIS_X], mesh.shape[AXIS_Y])
        self._fp = _fingerprint(problem, dtype, stencil, mesh_shape)
        self._manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True
            ),
        )

    # -- persistence --------------------------------------------------------

    def latest_step(self) -> Optional[int]:
        return self._manager.latest_step()

    def _save(self, state) -> None:
        import orbax.checkpoint as ocp

        step = int(state[0])
        # async save: orbax snapshots the arrays and serialises in the
        # background while the next chunk runs; completion is awaited only
        # before a restore or at close()
        self._manager.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(_state_to_tree(state)),
                meta=ocp.args.JsonSave(self._fp),
            ),
        )
        # manifests for any PREVIOUS step that has finalized by now —
        # this piggybacks on the save cadence, so the async pipeline is
        # never stalled just to fingerprint files
        self._flush_manifests()

    # -- integrity / quarantine ---------------------------------------------

    def _step_dirs(self) -> list[int]:
        """Finalized step directories on disk, by number. Listed from
        the filesystem (not the manager's cached view) so quarantined
        steps drop out immediately."""
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        return sorted(
            int(n) for n in names
            if n.isdigit() and os.path.isdir(os.path.join(self.directory, n))
        )

    def _flush_manifests(self) -> None:
        for step in self._step_dirs():
            step_dir = os.path.join(self.directory, str(step))
            path = os.path.join(step_dir, MANIFEST_NAME)
            if os.path.exists(path):
                continue
            manifest = {}
            complete = True
            for dirpath, _dirnames, filenames in os.walk(step_dir):
                for name in filenames:
                    if name == MANIFEST_NAME or name.endswith(".tmp"):
                        continue
                    full = os.path.join(dirpath, name)
                    try:
                        manifest[os.path.relpath(full, step_dir)] = (
                            os.path.getsize(full)
                        )
                    except OSError:
                        complete = False  # still being written: next time
            if complete and manifest:
                _write_json_atomic(path, manifest)

    def _verify_step(self, step: int) -> Optional[str]:
        """None when the step's files match its manifest; else the
        defect. Steps without a manifest (pre-manifest checkpoints, or a
        kill before the next save cadence) pass here — the orbax restore
        attempt is their integrity check."""
        step_dir = os.path.join(self.directory, str(step))
        path = os.path.join(step_dir, MANIFEST_NAME)
        if not os.path.exists(path):
            return None
        try:
            with open(path, encoding="utf-8") as fh:
                manifest = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            return f"unreadable integrity manifest: {e}"
        for rel, size in manifest.items():
            full = os.path.join(step_dir, rel)
            if not os.path.exists(full):
                return f"missing file {rel}"
            actual = os.path.getsize(full)
            if actual != size:
                return f"{rel} is {actual} bytes, manifest says {size}"
        return None

    def _quarantine(self, step: int, reason: str) -> str:
        """Move a damaged step out of the step namespace (never delete —
        the bytes may still matter for a post-mortem) and trace it."""
        src = os.path.join(self.directory, str(step))
        dst = os.path.join(self.directory, f"quarantined-{step}")
        n = 0
        while os.path.exists(dst):
            n += 1
            dst = os.path.join(self.directory, f"quarantined-{step}.{n}")
        os.rename(src, dst)
        warnings.warn(
            f"checkpoint step {step} is corrupt ({reason}); quarantined to "
            f"{dst} — resuming from the previous step",
            RuntimeWarning,
            stacklevel=3,
        )
        obs_trace.event(
            "recovery:checkpoint-quarantine",
            step=step,
            reason=reason,
            moved_to=os.path.basename(dst),
        )
        return dst

    def _restore_latest_valid(self):
        """The newest step that verifies AND restores; damaged steps are
        quarantined and the next-older one is tried. None when no step
        survives (the caller starts from iteration 0)."""
        while True:
            steps = self._step_dirs()
            if not steps:
                return None
            step = steps[-1]
            reason = self._verify_step(step)
            if reason is None:
                try:
                    return self._restore(step)
                except CheckpointMismatchError:
                    raise  # deliberate refusal, not damage
                except Exception as e:  # tpulint: disable=TPU009 — recovery: quarantine + retry the older step
                    reason = f"restore failed: {type(e).__name__}: {e}"
            self._quarantine(step, reason)

    def _restore(self, step: int):
        import orbax.checkpoint as ocp

        self._manager.wait_until_finished()  # drain any in-flight save
        # metadata first: the fingerprint guard must fire before orbax
        # would trip on mismatched array shapes with an opaque error
        meta = self._manager.restore(
            step, args=ocp.args.Composite(meta=ocp.args.JsonRestore())
        )["meta"]
        if meta != self._fp:
            # A mesh-shape-only mismatch is the ELASTIC resume: the
            # carry's arithmetic is decomposition-independent (padding is
            # inert, psum grouping is an ulp-scale reorder), so a
            # checkpoint written on a mesh that no longer exists — the
            # degraded-mesh recovery's defining situation — re-shards
            # instead of refusing. Everything else (grid, dtype, stencil)
            # changes the *math* and still refuses loudly.
            drop = lambda fp: {k: v for k, v in fp.items() if k != "mesh"}
            if drop(meta) != drop(self._fp):
                raise CheckpointMismatchError(
                    "checkpoint was written by a different problem/dtype: "
                    f"saved {meta}, current {self._fp}"
                )
            return self._restore_resharded(step, meta)
        # the freshly initialised carry is the restore template: it carries
        # the exact dtypes, shapes and (for sharded runs) shardings the
        # arrays must come back with
        restored = self._manager.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(
                    _state_to_tree(self._init())
                ),
            ),
        )
        return _tree_to_state(restored["state"])

    def _restore_resharded(self, step: int, meta: dict):
        """Restore a step written under a DIFFERENT mesh shape: pull the
        arrays to host numpy against a template shaped by the saved
        fingerprint (the dead mesh's padded dims), crop the old shard
        padding, and re-lay the carry out over the current mesh (or the
        single chip). The save-on-2×2/resume-on-1×2 parity case in
        ``tests/test_checkpoint.py`` pins this path."""
        import orbax.checkpoint as ocp

        from poisson_ellipse_tpu.parallel.mesh import padded_dims_of

        old_px, old_py = meta["mesh"]
        g1p, g2p = padded_dims_of(self.problem.node_shape, old_px, old_py)
        np_dtype = assembly.numpy_dtype(self.dtype)
        template = {
            "k": np.zeros((), np.int32),
            "w": np.zeros((g1p, g2p), np_dtype),
            "r": np.zeros((g1p, g2p), np_dtype),
            "p": np.zeros((g1p, g2p), np_dtype),
            "zr": np.zeros((), np_dtype),
            "diff": np.zeros((), np_dtype),
            "converged": np.zeros((), bool),
            "breakdown": np.zeros((), bool),
        }
        restored = self._manager.restore(
            step,
            args=ocp.args.Composite(state=ocp.args.StandardRestore(template)),
        )
        host = _tree_to_state(
            {k: np.asarray(v) for k, v in restored["state"].items()}
        )
        obs_trace.event(
            "degrade:checkpoint-reshard",
            step=step,
            from_mesh=[old_px, old_py],
            to_mesh=self._fp["mesh"],
        )
        if self.mesh is not None:
            from poisson_ellipse_tpu.parallel.elastic import reshard_state

            return reshard_state(
                self.problem, host, self.mesh, self.dtype
            )
        g1, g2 = self.problem.node_shape
        return tuple(
            jnp.asarray(np.asarray(x)[:g1, :g2])
            if getattr(x, "ndim", 0) == 2 else jnp.asarray(x)
            for x in host
        )

    # -- the meshguard surface ----------------------------------------------
    # (public wrappers so resilience.meshguard can drive chunks itself —
    # per-chunk deadlines, fault consults — while this class keeps sole
    # ownership of durability: save cadence, manifests, quarantine)

    def initial_state(self):
        """A fresh iteration-0 carry on this solver's mesh/stepper."""
        return self._init()

    def save(self, state) -> None:
        """Persist the classical 8-field prefix of ``state`` (an ABFT or
        history tail is never checkpointed — shadow scalars must be
        re-anchored against whatever mesh the carry wakes up on)."""
        self._save(tuple(state[:8]))

    def restore_latest(self):
        """The newest valid step's carry re-laid-out for THIS solver's
        mesh (quarantining damage, re-sharding across mesh shapes), or
        None when nothing survives."""
        return self._restore_latest_valid()

    # -- driving ------------------------------------------------------------

    def run(self, resume: bool = True) -> PCGResult:
        """Solve to convergence, saving every ``chunk`` iterations.

        resume=True picks up from the newest VALID checkpoint in
        ``directory`` (a restart after a kill continues mid-solve) —
        corrupt/truncated steps are quarantined and older ones tried,
        so damage costs at most the iterations since the last good save;
        resume=False starts from iteration 0 regardless.
        """
        state = self._restore_latest_valid() if resume else None
        if state is None:
            state = self._init()

        max_iter = self.problem.max_iterations
        while True:
            k = int(state[0])
            done = (
                bool(state[6]) or bool(state[7]) or k >= max_iter
            )  # converged / breakdown / cap
            if done:
                break
            state = self._advance(
                state, jnp.asarray(k + self.chunk, jnp.int32)
            )
            self._save(state)
        if self.mesh is not None:
            from poisson_ellipse_tpu.parallel.pcg_sharded import (
                sharded_result_of,
            )

            # sharded carries hold the padded global grid; crop to nodes
            return sharded_result_of(self.problem, state)
        return result_of(state)

    def close(self) -> None:
        self._manager.wait_until_finished()
        # the final step's manifest: every save has landed by now
        self._flush_manifests()
        self._manager.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def solve_with_checkpoints(
    problem: Problem,
    directory: str,
    chunk: int = 500,
    dtype=jnp.float32,
    stencil: str = "xla",
    resume: bool = True,
    mesh=None,
) -> PCGResult:
    """One-call form of CheckpointingSolver."""
    with CheckpointingSolver(
        problem, directory, chunk=chunk, dtype=dtype, stencil=stencil,
        mesh=mesh,
    ) as solver:
        return solver.run(resume=resume)
