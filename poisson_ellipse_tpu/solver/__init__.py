"""Solver drivers (reference layer L5): the preconditioned conjugate-gradient
iteration as a fully on-device ``lax.while_loop``, resumable state, and
orbax-backed checkpointing."""

from poisson_ellipse_tpu.solver.checkpoint import (
    CheckpointingSolver,
    solve_with_checkpoints,
)
from poisson_ellipse_tpu.solver.engine import (
    ENGINES,
    build_solver,
    select_engine,
)
from poisson_ellipse_tpu.solver.pcg import (
    PCGResult,
    advance,
    init_state,
    pcg,
    result_of,
    solve,
)

__all__ = [
    "CheckpointingSolver",
    "ENGINES",
    "PCGResult",
    "advance",
    "build_solver",
    "init_state",
    "pcg",
    "result_of",
    "select_engine",
    "solve",
    "solve_with_checkpoints",
]
