"""Solver drivers (reference layer L5): the preconditioned conjugate-gradient
iteration as a fully on-device ``lax.while_loop``."""

from poisson_ellipse_tpu.solver.pcg import PCGResult, pcg, solve

__all__ = ["PCGResult", "pcg", "solve"]
