"""Krylov recycling: deflated warm starts for correlated solve streams.

The fleet's request mix is not i.i.d. — the same geometry family, grid
bucket and ε recur — yet only *executables* were amortized (warm pool,
autotuner); the math restarted cold every solve. Deflated/recycled CG
(Saad et al. 2000; Parks et al., GCRODR, 2006) fixes that: project out
the extremal modes that survive the diag/mg preconditioners — exactly
the cut-cell outliers the fictitious-domain blend creates and the
degenerate-cut clamp leaves behind — and the next related solve starts
past the part of the spectrum that was costing the iterations.

Pipeline, host-orchestrated around unchanged device loops:

1. **Capture** — the solve carries a bounded on-device ring of its
   Lanczos basis vectors (:func:`ring_init` / :func:`ring_record`, the
   same ``dynamic_update_slice`` discipline as ``obs.convergence``'s
   history buffers; ``recycle=None`` traces the byte-identical ringless
   loop). CG's preconditioned residuals ARE the Lanczos basis of M⁻¹A
   in the M-inner product up to sign and scale —
   v_{j+1} = (−1)^j z_j/√(z_j,r_j) — both already computed by the loop,
   so each slot is one scaled store of an array the body materialises
   anyway, in step-for-step alignment with the tridiagonal the trace's
   α/β coefficients reconstruct.
2. **Harvest** (:func:`harvest`, host-side) — ``obs.spectrum``'s
   ``ritz_decomposition`` (truncated to the ring's steps) gives the
   T_m eigenpairs; the ``extremal_indices`` rule picks the k outliers;
   W = P·Y turns the stored directions into approximate extremal Ritz
   vectors of M⁻¹A. Approximate is fine: the deflation below is an
   exact Galerkin projection onto span(W) *whatever* W is — basis
   quality buys iteration cut, never correctness.
3. **Deflate** (:func:`deflated_x0`) — the next related solve starts at
   ``x0 += W (WᵀAW)⁻¹ Wᵀ r₀``, fed through the existing
   ``init_state(x0=...)`` path, whose TRUE-residual initialisation
   (r = rhs − A·x0) verifies the seed instead of trusting it. A stale
   or poisoned basis therefore costs iterations, never a wrong answer
   (:func:`check_warm_start` flags those hits as ``recycle:bad-hit``).

The sharded form keeps the 1-stacked-psum/iteration discipline: the k
deflation dots Wᵀr₀ ride ONE stacked psum at init, outside the loop
(:func:`build_deflated_sharded_init`), contract-checked as the
``recycle`` capability row of ``analysis.contracts`` — the hot loop's
collective cadence is byte-identical to the undeflated solve.
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.obs import spectrum
from poisson_ellipse_tpu.obs import trace as obs_trace
from poisson_ellipse_tpu.ops.stencil import apply_a

# Default on-device ring capacity (Lanczos vectors stored) and deflation
# rank harvested from it. Deflating a mode with tiny θ amplifies any
# basis inaccuracy by the spectral spread, so the extremal pairs must be
# CONVERGED Ritz pairs before they pay: measured at 128², a 16-slot ring
# (11% of the 150-iteration solve) leaves λ_min at ~6e-3 relative
# eigen-residual and the warm start *loses* iterations, while 64 slots
# turn an ε=1% correlated follow-up from 80 iterations (plain warm
# start) into 1. Rule of thumb the default encodes: cap ≥ ~40% of the
# expected iteration count, k well under cap. Memory is cap full grids
# at compute width (ring_model_bytes) — opt-in per solve, so the big
# grids simply pass a smaller cap.
RECYCLE_CAP = 64
RECYCLE_K = 8

# A warm start whose true relative residual exceeds this is WORSE than
# starting cold (‖r₀‖/‖rhs‖ = 1 exactly at x0 = 0): a semantic-cache
# miss dressed as a hit. It still converges — init_state verifies by
# true residual — but the event lets the fleet see the cache misbehaving.
BAD_HIT_RATIO = 1.0

# Gram matrices (WᵀAW) more ill-conditioned than this mean the harvested
# directions were numerically dependent; the projection would amplify
# noise, so the harvest declines and the next solve runs cold.
GRAM_COND_LIMIT = 1e12


# -- on-device ring (the capture half) ---------------------------------------


def ring_init(problem: Problem, cap: int, dtype) -> jax.Array:
    """The zeroed (cap, M+1, N+1) Lanczos-vector ring carried through
    the solve loop — one full-grid slot per stored basis vector, at
    compute width (the harvest's Gram algebra needs the accuracy).
    ``init_state`` seeds slot 0 with v₁ = z₀/√(z₀,r₀)."""
    return jnp.zeros((int(cap),) + tuple(problem.node_shape), dtype)


def ring_record(ring: jax.Array, slot, v, valid) -> jax.Array:
    """Scatter Lanczos vector ``v`` into ``slot``, first ``cap`` slots
    only, skipped (slot kept) when ``valid`` is False.

    Same ``dynamic_update_slice`` discipline as ``obs.convergence``'s
    history buffers — pure on-device stores, nothing the loop waits on.
    Past the capacity the write degenerates to rewriting slot cap−1
    with its own value: slots stay step-aligned with the Lanczos
    reconstruction (slot j ↔ basis vector v_{j+1}) instead of wrapping
    into a misaligned window.
    """
    cap = ring.shape[0]
    s = jnp.minimum(slot, cap - 1)
    zero = jnp.zeros((), s.dtype)
    keep = lax.dynamic_slice(ring, (s, zero, zero), (1,) + ring.shape[1:])
    rec = jnp.where(
        valid & (slot < cap), v[None].astype(ring.dtype), keep
    )
    return lax.dynamic_update_slice(ring, rec, (s, zero, zero))


def ring_model_bytes(
    problem: Problem, cap: int = RECYCLE_CAP, dtype=jnp.float32
) -> int:
    """Modeled HBM footprint of the direction ring — the `harness
    inspect` line (cap full grids at compute width)."""
    m, n = problem.node_shape
    return int(cap) * int(m) * int(n) * int(jnp.dtype(dtype).itemsize)


# -- harvest + deflation (the host-side half) --------------------------------


class DeflationBasis(NamedTuple):
    """One harvested recycling basis: k approximate extremal Ritz
    vectors W (grid-normalised), their images AW = A·W, the Gram matrix
    G = WᵀAW in the grid inner product, and the Ritz values they carry
    (diagnostics — the deflated-interval predictor's k).

    Tied to the (a, b) operator it was harvested from; a basis applied
    to a *different* operator is exactly the bad-hit case the
    true-residual init absorbs.
    """

    w: jax.Array  # (k, M+1, N+1)
    aw: jax.Array  # (k, M+1, N+1)
    gram: np.ndarray  # (k, k), symmetric
    thetas: np.ndarray  # (k,) harvested Ritz values, ascending
    h1: float
    h2: float

    @property
    def rank(self) -> int:
        return int(self.w.shape[0])

    @property
    def nbytes(self) -> int:
        return int(self.w.nbytes) + int(self.aw.nbytes)


def harvest(
    problem: Problem, a, b, trace, ring, k: int = RECYCLE_K
) -> DeflationBasis | None:
    """Build the k-mode deflation basis from one solve's trace + ring.

    The Lanczos reconstruction is truncated to the ring's capacity
    (T_j is itself the j-step Lanczos matrix, so the eigenpairs match
    the basis vectors actually stored); ``extremal_indices`` picks the
    same modes the deflated predictor removes. Returns None when the
    trace is too short to leave a deflated remainder (k ≥ m) or the
    Gram matrix says the stored basis was numerically dependent — the
    caller runs cold, which is always safe.
    """
    cap = int(ring.shape[0])
    thetas, y = spectrum.ritz_decomposition(trace, max_steps=cap)
    m = int(thetas.size)
    k = int(k)
    if k <= 0 or m == 0 or k >= m:
        return None
    dtype = ring.dtype
    idx = spectrum.extremal_indices(m, k)
    yk = jnp.asarray(np.ascontiguousarray(y[:, idx]), dtype)  # (m, k)
    w = jnp.einsum("mk,mij->kij", yk, ring[:m])
    h1 = jnp.asarray(problem.h1, dtype)
    h2 = jnp.asarray(problem.h2, dtype)
    # grid-normalise each column: V·Y is M-orthonormal only up to the
    # ring's f32 rounding and truncation, and the Gram conditioning
    # check below must be scale-free (span unchanged)
    norms = jnp.sqrt(jnp.einsum("kij,kij->k", w, w) * h1 * h2)
    w = w / jnp.where(norms > 0, norms, 1.0)[:, None, None]
    aw = jax.vmap(lambda wi: apply_a(wi, a, b, h1, h2))(w)
    gram = np.asarray(
        jnp.einsum("kij,lij->kl", w, aw), dtype=np.float64
    ) * float(problem.h1) * float(problem.h2)
    gram = 0.5 * (gram + gram.T)
    if not np.all(np.isfinite(gram)):
        return None
    try:
        cond = np.linalg.cond(gram)
    except np.linalg.LinAlgError:
        return None
    if not np.isfinite(cond) or cond > GRAM_COND_LIMIT:
        return None
    return DeflationBasis(
        w=w,
        aw=aw,
        gram=gram,
        thetas=np.asarray(thetas[idx], dtype=np.float64),
        h1=float(problem.h1),
        h2=float(problem.h2),
    )


def deflated_x0(basis: DeflationBasis, rhs, x0=None, residual=None):
    """The deflated warm start ``x0 + W (WᵀAW)⁻¹ Wᵀ r₀``.

    ``r₀`` is ``rhs`` for the zero base (the common path), or the
    caller-supplied true ``residual`` when stacking on a nonzero ``x0``
    (a semantic-cache hit being deflated on top). The Galerkin solve is
    k×k host-side f64; a singular system returns None and the caller
    falls back to the undeflated start.
    """
    if residual is not None:
        r0 = residual
    elif x0 is None:
        r0 = rhs
    else:
        raise ValueError(
            "deflating on top of a nonzero x0 needs its TRUE residual "
            "(rhs - A@x0) — pass residual="
        )
    t = np.asarray(
        jnp.einsum("kij,ij->k", basis.w, r0), dtype=np.float64
    ) * basis.h1 * basis.h2
    try:
        c = np.linalg.solve(basis.gram, t)
    except np.linalg.LinAlgError:
        return None
    if not np.all(np.isfinite(c)):
        return None
    lift = jnp.einsum("k,kij->ij", jnp.asarray(c, rhs.dtype), basis.w)
    return lift if x0 is None else x0 + lift


def reproject_x0(problem: Problem, a, b, rhs, basis: DeflationBasis, w):
    """Restart-boundary re-projection: re-deflate a partially converged
    iterate against its TRUE residual (the guard's optional
    chunk-boundary hook — extremal components that CG reintroduced
    through rounding get projected back out). Returns ``w`` unchanged
    when the Galerkin solve declines."""
    dtype = rhs.dtype
    h1 = jnp.asarray(problem.h1, dtype)
    h2 = jnp.asarray(problem.h2, dtype)
    r = rhs - apply_a(w, a, b, h1, h2)
    out = deflated_x0(basis, rhs, x0=w, residual=r)
    return w if out is None else out


# -- warm-start admission (the bad-hit contract) -----------------------------


def warm_start_ratio(problem: Problem, a, b, rhs, x0) -> float:
    """‖rhs − A·x0‖ / ‖rhs‖ — the true relative residual of a proposed
    warm start, computed eagerly at admission time (never inside a
    loop). 0 = already solved, 1 = no better than cold."""
    dtype = rhs.dtype
    h1 = jnp.asarray(problem.h1, dtype)
    h2 = jnp.asarray(problem.h2, dtype)
    r = rhs - apply_a(x0, a, b, h1, h2)
    num = float(jnp.sqrt(jnp.sum(r * r)))
    den = float(jnp.sqrt(jnp.sum(rhs * rhs)))
    if den == 0.0:
        return math.inf if num > 0 else 0.0
    return num / den


def check_warm_start(
    problem: Problem, a, b, rhs, x0, source: str = "recycle",
    request_id: str | None = None,
):
    """Admit a proposed warm start, flagging bad hits.

    Returns ``(x0_to_use, ratio)``. A finite ratio keeps the seed even
    when it is worse than cold — the true-residual init makes a bad hit
    cost iterations, never correctness — but ratios over
    :data:`BAD_HIT_RATIO` emit a ``recycle:bad-hit`` trace event so the
    fleet can see a misbehaving cache without any solve going wrong. A
    non-finite seed (NaN/Inf contamination would poison the recurrence
    itself, not just the start) is dropped to a cold start, also
    flagged.
    """
    if x0 is None:
        return None, None
    ratio = warm_start_ratio(problem, a, b, rhs, x0)
    if not math.isfinite(ratio):
        obs_trace.event(
            "recycle:bad-hit", request_id=request_id, source=source,
            ratio=None, dropped=True,
        )
        return None, ratio
    if ratio > BAD_HIT_RATIO:
        obs_trace.event(
            "recycle:bad-hit", request_id=request_id, source=source,
            ratio=ratio, dropped=False,
        )
    return x0, ratio


# -- sharded deflated init (the 1-psum/iter discipline) ----------------------


def build_deflated_sharded_init(
    problem: Problem,
    mesh=None,
    dtype=jnp.float32,
    stencil_impl: str = "xla",
):
    """Jitted ``init_fn(a, b, rhs, w_basis, ginv) -> carry``: the
    sharded iteration-0 carry warm-started by a k-mode deflation basis.

    ``w_basis`` is the (k, g1p, g2p) basis sharded ``P(None, 'x', 'y')``
    (every device holds its block of every mode); ``ginv`` the
    replicated k×k inverse Gram (:func:`sharded_basis_args` builds
    both). The k deflation dots Wᵀ·rhs fold into ONE stacked psum — the
    same idiom as the loop's stacked convergence psum — so the whole
    deflated init costs exactly 2 psums (the stack + zr₀) for ANY k,
    and the loop it hands off to is byte-identical to the undeflated
    one: 1 denom psum + 1 stacked psum per iteration. Both facts are
    the ``recycle`` capability row of ``analysis.contracts``, pinned
    from the jaxpr.
    """
    from jax.sharding import PartitionSpec as P

    from poisson_ellipse_tpu.parallel.compat import shard_map
    from poisson_ellipse_tpu.parallel.halo import halo_extend
    from poisson_ellipse_tpu.parallel.mesh import (
        AXIS_X,
        AXIS_Y,
        make_mesh,
        padded_dims,
    )
    from poisson_ellipse_tpu.parallel.pcg_sharded import (
        _shard_init,
        _shard_ops,
    )

    if mesh is None:
        mesh = make_mesh()
    px = mesh.shape[AXIS_X]
    py = mesh.shape[AXIS_Y]
    interpret = mesh.devices.flat[0].platform != "tpu"
    g1p, g2p = padded_dims(problem.node_shape, mesh)
    bm, bn = g1p // px, g2p // py
    spec = P(AXIS_X, AXIS_Y)
    scalar = P()
    basis_spec = P(None, AXIS_X, AXIS_Y)
    state_specs = (scalar, spec, spec, spec, scalar, scalar, scalar, scalar)

    def init_shard(a_blk, b_blk, rhs_blk, wb_blk, ginv):
        a_ext = halo_extend(a_blk, px, py)
        b_ext = halo_extend(b_blk, px, py)
        stencil, pdot, d, _maskd = _shard_ops(
            problem, px, py, bm, bn, a_ext, b_ext, dtype,
            stencil_impl, interpret,
        )
        h1 = jnp.asarray(problem.h1, dtype)
        h2 = jnp.asarray(problem.h2, dtype)
        # the k deflation dots Wᵀ·rhs as ONE stacked psum (the
        # convergence-word idiom — k partials, one collective); issued
        # here rather than parallel/ because the recycle contract cell
        # pins THIS init's psum count from the jaxpr — the budget the
        # collective-modules fence exists to protect is checked at the
        # source
        partials = jnp.einsum("kij,ij->k", wb_blk, rhs_blk)
        t = lax.psum(  # tpulint: disable=TPU020
            partials, (AXIS_X, AXIS_Y)
        ) * h1 * h2
        c = ginv @ t
        x0_blk = jnp.einsum("k,kij->ij", c, wb_blk)
        return _shard_init(
            problem, px, py, bm, bn, pdot, d, rhs_blk, dtype,
            x0_blk=x0_blk, stencil=stencil,
        )

    # no donation: the basis is the whole point of recycling — reused
    # across every solve of the correlated stream — and a/b/rhs are the
    # caller's long-lived sharded operands
    return jax.jit(shard_map(  # tpulint: disable=TPU004
        init_shard,
        mesh=mesh,
        in_specs=(spec, spec, spec, basis_spec, scalar),
        out_specs=state_specs,
        check_vma=not (stencil_impl == "pallas" and interpret),
    ))


def sharded_basis_args(basis: DeflationBasis, problem: Problem, mesh=None,
                       dtype=jnp.float32):
    """(w_basis, ginv) device arrays for
    :func:`build_deflated_sharded_init` — the basis zero-padded to the
    mesh's (g1p, g2p) shard grid and laid out ``P(None, 'x', 'y')``, and
    the k×k inverse Gram replicated. Zero padding is exact: padded nodes
    are outside every mode's support, so the folded dots see only real
    grid."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from poisson_ellipse_tpu.parallel.mesh import (
        AXIS_X,
        AXIS_Y,
        make_mesh,
        padded_dims,
    )

    if mesh is None:
        mesh = make_mesh()
    g1p, g2p = padded_dims(problem.node_shape, mesh)
    k, m, n = basis.w.shape
    w_pad = jnp.zeros((k, g1p, g2p), dtype)
    w_pad = w_pad.at[:, :m, :n].set(basis.w.astype(dtype))
    w_basis = jax.device_put(
        w_pad, NamedSharding(mesh, P(None, AXIS_X, AXIS_Y))
    )
    ginv = jax.device_put(
        jnp.asarray(np.linalg.inv(basis.gram), dtype),
        NamedSharding(mesh, P()),
    )
    return w_basis, ginv
