"""Engine selection: one entry point over the single-chip solver engines.

The reference's ``main`` always runs its fastest implementation — stage4
launches every CUDA kernel each iteration (``poisson_mpi_cuda2.cu:985-1038``,
``:846-939``). The TPU framework has five single-chip engines with different
capacity/perf envelopes; this module is the policy that picks the fastest
one that fits, so every product entry point (bench, CLI, harness) gets the
best path by default:

  engine       capacity (f32)                measured vs XLA (bench chip)
  ---------    ---------------------------   ----------------------------
  resident     whole solve in VMEM           4.0-5.8x  (<= ~1100x1650)
  streamed     state in VMEM, ops streamed   1.6-2.0x  (<= ~2400x3200)
  xl           state AND ops tile-streamed   ~1.2x     (any grid size)
  fused        two-kernel HBM iteration      ~1.2x     (small-mid grids)
  xla          lax.while_loop, XLA-fused     1.0x      (any grid, any dtype)
  pallas       XLA loop + per-op Pallas      ~1.0x     (comparison engine:
               stencil kernel                           stage4's kernel-per-
                                                        op structure)
  pipelined    Ghysels-Vanroose recurrence:  ~1.0x     (any grid, any dtype;
               ONE fused dot bundle/iter,              iters within +-2 of
               stencil overlaps it                     xla, not bitwise)
  pipelined-   pipelined recurrence driving  ~1.0x     (f32/bf16; the
  pallas       the fused stencil+partials              one-VMEM-pass form
               Pallas kernel                           of the same loop)
  batched      B independent lanes in ONE    per-lane  (lanes= selects B;
               fused while_loop, per-lane    cost      the throughput
               masked updates + quarantine   amortised engine — batch.*)
  batched-     the same lanes through the    as above  (one stacked (8,B)
  pipelined    pipelined recurrence                    dot bundle/iter)
  mg-pcg       classical loop, z = V-cycle   O(10¹)    (the iteration-
               over coarsened coefficients   iters at  count killer —
               w/ Chebyshev smoothers        any grid  mg.*; ~8× more
                                                       HBM/iter)
  cheb-pcg     classical loop, z = degree-k  ~k× fewer (the cheap first
               Chebyshev polynomial in D⁻¹A  iters     rung; bounds from
                                                       obs.spectrum)
  sstep        s-step (communication-        ~1.0x     (s∈{2,4} iters per
               avoiding) recurrence:                   matrix-powers round;
               matrix-powers basis + Gram              sharded: 1 psum +
               in ONE stacked reduction                one s-deep halo per
               per s iterations                        s iters — the mesh-
                                                       latency frontier)
  sstep-       the same blocks driving the   ~1.0x     (storage_dtype= runs
  pallas       Pallas stencil chain                    the mixed kernels)
  fmg          ONE full-multigrid F-cycle    O(N)      (the asymptotic-work
               + the VERIFIED mg-pcg         work,     killer — mg.fmg;
               handoff against δ             const/pt  handoff iters ~ 1)

Every STORAGE_ENGINES member additionally takes ``storage_dtype=`` —
bf16 state/operand storage with f32 compute (``ops.precision``), the
HBM-bandwidth lever; accuracy is recovered through the guard's
bf16→f32→f64 escalation ladder (``resilience.guard``), not assumed.

Policy (``select_engine``): resident if the whole working set fits VMEM;
else streamed if the state fits; else xl. f64 always takes xla — the
Pallas engines are f32/bf16 (TPU f64 is emulated, and the XLA path is the
only one with an f64 story). ``fused`` never wins outright on the bench
chip so auto never picks it, but it remains selectable for comparison.
The ``pipelined`` pair restructures the *recurrence* (one fused reduction
per iteration instead of two serialized ones — ``ops.pipelined_pcg``);
on one chip that trades ~2x the streamed passes for half the
reduce→broadcast barriers, a wash at the bench grids, so auto never
picks it either — its payoff is the sharded path, where the single
stacked psum halves the collectives per iteration
(``parallel.pipelined_sharded``) and it IS the mesh engine of choice at
collective-latency-bound scale. Iteration counts land within ±2 of xla
(a documented reordering, not bitwise — see ``ops.pipelined_pcg``).

Past the streamed gate (~2400x3200 f32; e.g. the 4096² north-star grid,
whose state alone is ~200 MB) solves are HBM-bandwidth-bound; the xl
kernel restructures the iteration below the XLA loop's traffic floor
(z-state + deferred w-update: ~12.1 array-passes/iter vs ~13, at a
higher achieved fraction of peak — measured 4.28 s vs 5.16 s at 4096²).
The framework's *scaling* answer at that size remains the sharded mesh
path (``parallel.pcg_sharded``), which divides the state over devices
until it is VMEM-resident again.
"""

from __future__ import annotations

import jax.numpy as jnp

from poisson_ellipse_tpu.models.problem import Problem
from poisson_ellipse_tpu.ops import assembly
from poisson_ellipse_tpu.solver.pcg import PCGResult, pcg

# the Pallas engine modules import solver.pcg at their top level (which
# runs this package's __init__), so they are imported lazily here

# ONE engine-capability table: every per-engine fact the framework used
# to scatter across parallel tuples (the old ENGINES / STORAGE_ENGINES /
# HISTORY_ENGINES / PRECOND_KIND_BY_ENGINE / auto-ladder quintet, each
# hand-maintained) lives in exactly one row here, and every consumer —
# build_solver's dispatch, the guard, the harness, obs.static_cost AND
# the autotuner (runtime.autotune, which reads ``tunables``) — derives
# from it. Registering a new engine means adding ONE row.
#
#   family    — "loop" (XLA while_loop), "megakernel" (VMEM scalar
#               state), "batched" (per-lane), "precond" (V-cycle/Cheb
#               preconditioned classical loop), "sstep", "fmg"
#   storage   — accepts the storage-vs-compute split (ops.precision)
#   history   — can record the obs.convergence buffers
#   capacity  — rung on the "auto" capacity ladder (0 = tried first),
#               None = auto never picks it (opt-in engines)
#   precond_kind — the mg.* preconditioner kind the engine's modeled
#               extra traffic / fallback ladder keys on (None = diag)
#   tunables  — the engine's autotunable knobs with their static
#               defaults (what runtime.autotune turns and what tpulint
#               TPU019 fences from being hardcoded at call sites)
#   contracts — the engine's jaxpr-level structural guarantees, checked
#               by the declarative contract matrix (analysis.contracts;
#               `python -m poisson_ellipse_tpu.analysis`). Keys are
#               deviations from analysis.contracts.CONTRACT_DEFAULTS:
#                 sharded_psum     — psums per sharded while body
#                                    (None = the engine has no sharded
#                                    form; the matrix skips that cell)
#                 sharded_halo     — halo exchanges per sharded body
#                                    (each is 4 ppermutes), "precond"
#                                    = stencil + the V-cycle/Chebyshev
#                                    budget (mg_sharded.
#                                    halos_per_precond), None = the
#                                    count is deliberately unpinned
#                                    (pipelined's replacement branch)
#                 batched_psum/_halo — the lane-sharded cadence
#                 abft             — the ABFT stepper must add ZERO
#                                    collectives (on/off identity)
#                 guard            — the guard adapter family whose
#                                    chunk advance must trace the
#                                    byte-identical unguarded jaxpr
#                 storage_identity — storage_dtype=None must trace the
#                                    byte-identical pre-storage jaxpr
#                 storage_narrow   — a bf16-storage body must widen on
#                                    load and narrow on store
#                 history_resident — history=True stays device-resident
#                                    (no callbacks), history=False adds
#                                    no dynamic_update_slice
#                 fcycle_budget    — whole-trace ppermute budget
#                                    (halos_per_fcycle) applies
#                 fleet_chaos      — the kill→rejoin fleet drill's
#                                    survivability invariants hold and
#                                    the chaos verdict is sensitive to
#                                    each of them
#                 recycle          — recycle=None/x0=None trace the
#                                    byte-identical default jaxpr and
#                                    the sharded deflated init folds k
#                                    deflation dots into one stacked
#                                    psum (2 total, zero loop bodies)
#               A row WITHOUT this key is itself a finding: registering
#               an engine means declaring its structural contract.
ENGINE_CAPS = {
    "resident": dict(family="megakernel", storage=False, history=False,
                     capacity=0, precond_kind=None, tunables={},
                     contracts={}),
    "streamed": dict(family="megakernel", storage=True, history=False,
                     capacity=1, precond_kind=None, tunables={},
                     contracts={}),
    "xl": dict(family="megakernel", storage=True, history=False,
               capacity=2, precond_kind=None, tunables={},
               contracts={}),
    "xla": dict(family="loop", storage=True, history=True,
                capacity=3, precond_kind=None, tunables={},
                contracts=dict(sharded_psum=2, sharded_halo=1, abft=True,
                               guard="classical", storage_identity=True,
                               storage_narrow=True, history_resident=True,
                               fleet_chaos=True, recycle=True)),
    "fused": dict(family="loop", storage=False, history=True,
                  capacity=None, precond_kind=None, tunables={},
                  contracts=dict(sharded_psum=2, sharded_halo=1,
                                 history_resident=True)),
    "pallas": dict(family="loop", storage=True, history=True,
                   capacity=None, precond_kind=None, tunables={},
                   contracts=dict(sharded_psum=2, sharded_halo=1,
                                  history_resident=True)),
    "pipelined": dict(family="loop", storage=True, history=True,
                      capacity=None, precond_kind=None, tunables={},
                      contracts=dict(sharded_psum=1, abft=True,
                                     guard="pipelined",
                                     storage_identity=True,
                                     storage_narrow=True,
                                     history_resident=True)),
    "pipelined-pallas": dict(family="loop", storage=True, history=True,
                             capacity=None, precond_kind=None, tunables={},
                             contracts=dict(history_resident=True)),
    "batched": dict(family="batched", storage=True, history=False,
                    capacity=None, precond_kind=None,
                    tunables={"chunk": 16},
                    contracts=dict(batched_psum=1, batched_halo=0)),
    "batched-pipelined": dict(family="batched", storage=False,
                              history=False, capacity=None,
                              precond_kind=None, tunables={"chunk": 16},
                              contracts=dict(batched_psum=1,
                                             batched_halo=0)),
    "mg-pcg": dict(family="precond", storage=False, history=True,
                   capacity=None, precond_kind="mg",
                   tunables={"levels": None, "nu": 2, "coarse_degree": 24},
                   contracts=dict(sharded_psum=2, sharded_halo="precond",
                                  abft=True)),
    "cheb-pcg": dict(family="precond", storage=False, history=True,
                     capacity=None, precond_kind="cheb",
                     tunables={"cheb_degree": 12},
                     contracts=dict(sharded_psum=2, sharded_halo="precond",
                                    abft=True)),
    "sstep": dict(family="sstep", storage=True, history=False,
                  capacity=None, precond_kind=None,
                  tunables={"sstep_s": 4},
                  contracts=dict(sharded_psum=1, sharded_halo=1, abft=True,
                                 storage_narrow=True)),
    "sstep-pallas": dict(family="sstep", storage=True, history=False,
                         capacity=None, precond_kind=None,
                         tunables={"sstep_s": 4},
                         contracts={}),
    # full multigrid as the SOLVER (mg.fmg): one O(N) F-cycle + the
    # verified mg-pcg handoff. precond_kind "mg" keys its traffic model
    # and guard fallback ladder on the V-cycle's; family "fmg" keeps it
    # out of the precond dispatch branch (it has its own builder).
    "fmg": dict(family="fmg", storage=False, history=True,
                capacity=None, precond_kind="mg",
                tunables={"levels": None, "nu": 2, "coarse_degree": 24,
                          "n_vcycles": 2},
                contracts=dict(sharded_psum=2, sharded_halo="precond",
                               fcycle_budget=True)),
}

# engines with a mesh-sharded form (a declared sharded collective
# cadence): the tuple obs.static_cost and the harness gate sharded-mode
# requests against — derived from the contract metadata, not
# hand-maintained alongside it.
SHARDED_ENGINES = tuple(
    e for e, c in ENGINE_CAPS.items()
    if c["contracts"].get("sharded_psum") is not None
)

ENGINES = ("auto",) + tuple(ENGINE_CAPS)

# the s-step (communication-avoiding) engines: s iterations per
# matrix-powers round, ONE stacked reduction (and, sharded, ONE psum +
# one s-deep halo) per s iterations — ops.sstep_pcg /
# parallel.sstep_sharded. "auto" never picks them (opt-in, like the
# preconditioner engines): their payoff is collective latency and HBM
# passes at mesh/bandwidth-bound scale, not small-grid wall clock.
SSTEP_ENGINES = tuple(
    e for e, c in ENGINE_CAPS.items() if c["family"] == "sstep"
)

# engines that accept the storage-vs-compute split (ops.precision):
# state and/or streamed operands at bf16 width in HBM, f32 compute.
# The loop engines narrow everything; streamed/xl narrow their operand
# streams (their state is VMEM-resident / kept full-width); batched
# narrows the lane fields. The guard's escalation ladder (bf16→f32→f64)
# is the product path for accuracy recovery (resilience.guard).
STORAGE_ENGINES = tuple(
    e for e, c in ENGINE_CAPS.items() if c["storage"]
)

# the preconditioner engines (mg.*): the classical fused loop with the
# diagonal preconditioner swapped for the multigrid V-cycle / Chebyshev
# polynomial — same PCGResult contract, O(grid)→O(1)-ish iteration
# counts. "auto" never picks them by default: auto optimises
# per-iteration cost at a FIXED iteration count; these change the
# iteration count itself and are opt-in per run/bench — unless the
# autotuner has a persisted, regression-gated winner for the shape
# (runtime.autotune; consulted below). The engine-name ↔ mg-kind
# mapping derives from the capability table — every consumer (harness,
# guard, static_cost, mg.engine) imports it from here, once.
PRECOND_KIND_BY_ENGINE = {
    e: c["precond_kind"] for e, c in ENGINE_CAPS.items()
    if c["family"] == "precond"
}
PRECOND_ENGINE_BY_KIND = {v: k for k, v in PRECOND_KIND_BY_ENGINE.items()}
PRECOND_ENGINES = tuple(PRECOND_KIND_BY_ENGINE)

# the lane-batched throughput engines (batch.*): one dispatch runs
# ``lanes`` independent solves; results are per-lane (BatchedPCGResult)
BATCHED_ENGINES = tuple(
    e for e, c in ENGINE_CAPS.items() if c["family"] == "batched"
)

# engines that can record on-device convergence history
# (``history=True`` → (PCGResult, obs.ConvergenceTrace)): the XLA-loop
# engines. The VMEM mega-kernels keep their scalars in kernel scratch,
# the batched engines carry per-lane recurrences — neither records.
# "auto" resolves to xla under history=True. The single source of truth
# for every history consumer (harness diagnose, obs.spectrum callers).
HISTORY_ENGINES = ("auto",) + tuple(
    e for e, c in ENGINE_CAPS.items() if c["history"]
)

# the runtime capacity ladder "auto" walks (and _warm_with_degradation
# degrades down on RESOURCE_EXHAUSTED): capability-table rungs in order
CAPACITY_LADDER = tuple(sorted(
    (e for e, c in ENGINE_CAPS.items() if c["capacity"] is not None),
    key=lambda e: ENGINE_CAPS[e]["capacity"],
))


def select_engine(problem: Problem, dtype=jnp.float32, device=None) -> str:
    """The concrete engine "auto" resolves to for this problem/dtype.

    The capacity gates scale with ``device``'s VMEM size
    (``utils.device``'s device_kind table; default: the default-backend
    device), so a larger-VMEM part keeps the resident/streamed engines
    up to proportionally larger grids instead of silently under-
    selecting with the bench part's budgets.
    """
    from poisson_ellipse_tpu.ops.resident_pcg import fits_resident
    from poisson_ellipse_tpu.ops.streamed_pcg import fits_streamed

    if jnp.dtype(dtype).itemsize >= 8:
        return "xla"
    if fits_resident(problem, dtype, device):
        return "resident"
    if fits_streamed(problem, dtype, device):
        return "streamed"
    # past the streamed gate the state itself exceeds VMEM: the xl
    # kernel streams state AND operands (12.1 passes/iter at ~72% of
    # HBM peak vs the XLA loop's 13 at ~67% — measured 4.28 s vs 5.16 s
    # at the 4096² north-star grid)
    return "xl"


def build_solver(
    problem: Problem, engine: str = "auto", dtype=jnp.float32, interpret=None,
    history: bool = False, lanes: int = 1, geometry=None, theta=None,
    validate_geometry: bool = True, storage_dtype=None, sstep_s: int = 4,
    tuned_knobs: dict | None = None,
):
    """(jitted solver, args, resolved_engine) for a single-chip solve.

    ``geometry`` selects an arbitrary SDF domain (a ``geom.sdf`` shape
    or its JSON spec): the operands are assembled through the bisection
    quadrature (``geom.quadrature``) with the degenerate-cut clamp at
    ``theta``, and — unless ``validate_geometry=False`` — the
    admissibility gate (``geom.validate``) runs FIRST, raising the
    classified ``InvalidGeometryError`` (exit 8) before anything is
    built or dispatched. ``geometry=None`` (default) keeps the
    closed-form ellipse bit-identical to every pre-geometry release.
    Every engine accepts the same ``geometry=``; the assembly is a
    host-side operand fact, not an engine property.

    ``lanes`` selects the batch width of the lane-batched engines
    (``batched`` / ``batched-pipelined``): their solver runs ``lanes``
    independent problems per dispatch — args end with a lane-stacked
    RHS — and returns a per-lane :class:`~poisson_ellipse_tpu.batch.
    BatchedPCGResult` instead of a ``PCGResult``. Every other engine
    requires ``lanes == 1``.

    All engines share the PCGResult contract and the f64-host-assembled,
    rounded-once operand fidelity, so swapping engines changes speed, not
    iteration counts (verified against the published oracles).

    ``history=True`` builds the solver in convergence-telemetry form: it
    returns ``(PCGResult, obs.ConvergenceTrace)`` with the per-iteration
    (zr, diff, α, β) series recorded on device (``obs.convergence``).
    Supported by the XLA-loop engines (xla, pallas, fused, pipelined,
    pipelined-pallas) — the VMEM mega-kernel engines (resident, streamed,
    xl) keep their scalars in kernel scratch, so "auto" with history
    resolves to xla (the reference-trajectory engine) and an explicit
    mega-kernel request fails loudly.

    ``tuned_knobs`` is the autotune registry's knob dict for this shape
    (``runtime.autotune``): the multigrid builders apply
    levels/ν/degrees/n_vcycles, the s-step branch reads sstep_s —
    passed explicitly by the tuner's measurement path and filled
    automatically when "auto" consults a persisted config, so the
    configuration that was scored is the configuration that runs.

    "auto" degrades gracefully: the capacity gates are budgets measured
    on the bench part, so on a chip with a different VMEM size a selected
    Pallas engine could fail Mosaic compilation — auto AOT-compiles the
    pick and falls down the chain (resident → streamed → xl → xla; xla
    cannot fail this way) instead of surfacing an opaque compile error.
    Explicitly requested engines still fail loudly.
    """
    if lanes != 1 and engine not in BATCHED_ENGINES:
        raise ValueError(
            f"engine {engine!r} runs one solve per dispatch; lanes={lanes} "
            "needs the lane-batched engines ('batched' / "
            "'batched-pipelined')"
        )
    if storage_dtype is not None:
        from poisson_ellipse_tpu.ops.precision import resolve_storage_dtype

        # resolve early: a bad name or a widening request fails here,
        # and storage == compute normalises to None (the identity path)
        storage_dtype = resolve_storage_dtype(storage_dtype, dtype)
    if storage_dtype is not None and engine not in STORAGE_ENGINES:
        raise ValueError(
            f"engine {engine!r} has no storage-dtype form; choose from "
            f"{', '.join(STORAGE_ENGINES)} (or drop --storage-dtype)"
        )
    if geometry is not None:
        from poisson_ellipse_tpu.geom import sdf as geom_sdf
        from poisson_ellipse_tpu.geom import validate as geom_validate

        if isinstance(geometry, dict):
            geometry = geom_sdf.from_spec(geometry)  # classifies malformed
        if validate_geometry:
            # the admissibility gate: a bad problem fails HERE, with the
            # classified exit-8 error, before any build/compile/dispatch
            geom_validate.validate(problem, geometry, theta=theta)
    if engine in BATCHED_ENGINES:
        if history:
            raise ValueError(
                "the batched engines carry per-lane scalar recurrences, "
                "not the obs.convergence ring buffers; use a single-lane "
                "engine for history=True"
            )
        if lanes < 1:
            raise ValueError("lanes must be >= 1")
        import jax

        from poisson_ellipse_tpu.batch import (
            batched_operands,
            pcg_batched,
            pcg_batched_pipelined,
        )

        if engine == "batched":
            run = lambda a, b, rhs: pcg_batched(
                problem, a, b, rhs, storage_dtype=storage_dtype
            )
        else:
            run = lambda a, b, rhs: pcg_batched_pipelined(problem, a, b, rhs)
        args = batched_operands(problem, lanes, dtype, geometry=geometry,
                                theta=theta)
        # no donation: the build-once-call-many contract re-feeds these
        # operands on every dispatch (the timing protocols re-dispatch)
        solver = jax.jit(run)
        return solver, args, engine
    if engine == "auto":
        # the autotuner's persisted, regression-gated winner for this
        # shape (runtime.autotune) overrides the static capacity ladder
        # — only when a tuned registry exists next to the XLA cache and
        # holds this key; otherwise the historical ladder is untouched
        from poisson_ellipse_tpu.runtime import autotune

        tuned = autotune.lookup(problem, dtype, storage_dtype=storage_dtype,
                                geometry=geometry)
        if tuned is not None and tuned.engine in ENGINE_CAPS:
            caps = ENGINE_CAPS[tuned.engine]
            if ((not history or caps["history"])
                    and (storage_dtype is None or caps["storage"])
                    and caps["family"] not in ("batched",)):
                engine = tuned.engine
                # the FULL knob dict rides along: the multigrid/sstep
                # builders below apply it, so the tuned configuration
                # is what actually runs, not just the engine name
                tuned_knobs = dict(tuned.knobs)
                if "sstep_s" in tuned_knobs:
                    sstep_s = int(tuned_knobs["sstep_s"])
    if engine == "auto" and history:
        # the mega-kernel engines auto would pick cannot record: take the
        # reference-trajectory engine instead of failing a telemetry ask
        engine = "xla"
    if history and engine in ENGINES and engine not in HISTORY_ENGINES:
        raise ValueError(
            f"engine {engine!r} keeps its scalar recurrence in VMEM kernel "
            "scratch and cannot record history; use one of "
            f"{', '.join(HISTORY_ENGINES[1:])} (or engine='auto', which "
            "resolves to xla under history=True)"
        )
    if engine == "auto":
        import jax

        chain = CAPACITY_LADDER
        chain = chain[chain.index(select_engine(problem, dtype)):]
        last_err = None
        for cand in chain:
            try:
                # the gate already ran above — don't re-validate per rung
                solver, args, _ = build_solver(
                    problem, cand, dtype, interpret, geometry=geometry,
                    theta=theta, validate_geometry=False,
                )
                if cand != "xla" and jax.default_backend() == "tpu":
                    # force Mosaic compilation now, where we can catch it.
                    # The jit dispatch cache is shared with this AOT
                    # lowering (verified on the bench chip: first solver
                    # call after this line dispatches in ~1 ms, no
                    # recompile), so the probe costs nothing extra.
                    solver.lower(*args).compile()
                return solver, args, cand
            except Exception as e:  # tpulint: disable=TPU009 — chain: warn, degrade, re-raise at exhaustion
                last_err = e
                if cand != chain[-1]:
                    import warnings

                    # degrade, but never silently: a genuine bug in an
                    # engine build would otherwise read as a 4-6x slowdown
                    warnings.warn(
                        f"engine {cand!r} failed to build/compile for "
                        f"{problem.M}x{problem.N} ({type(e).__name__}: "
                        f"{e}); falling back",
                        RuntimeWarning,
                        stacklevel=2,
                    )
        raise last_err  # unreachable: the xla build has no capacity gate
    if engine == "resident":
        from poisson_ellipse_tpu.ops.resident_pcg import build_resident_solver

        solver, args = build_resident_solver(
            problem, dtype, interpret=interpret, geometry=geometry,
            theta=theta,
        )
    elif engine == "streamed":
        from poisson_ellipse_tpu.ops.streamed_pcg import build_streamed_solver

        solver, args = build_streamed_solver(
            problem, dtype, interpret=interpret, geometry=geometry,
            theta=theta, storage_dtype=storage_dtype,
        )
    elif engine == "fused":
        from poisson_ellipse_tpu.ops.fused_pcg import build_fused_solver

        solver, args = build_fused_solver(
            problem, dtype, interpret=interpret, history=history,
            geometry=geometry, theta=theta,
        )
    elif engine == "xl":
        from poisson_ellipse_tpu.ops.xl_pcg import build_xl_solver

        solver, args = build_xl_solver(
            problem, dtype, interpret=interpret, geometry=geometry,
            theta=theta, storage_dtype=storage_dtype,
        )
    elif engine == "fmg":
        # full multigrid as the solver: one O(N) F-cycle (nested
        # iteration over the coarsened hierarchy) + the verified
        # warm-started mg-pcg handoff against δ (mg.fmg); tuned knobs
        # (levels/ν/coarse_degree/n_vcycles) become the F-cycle config
        from poisson_ellipse_tpu.mg.fmg import (
            build_fmg_solver,
            config_from_knobs,
        )

        solver, args, _ = build_fmg_solver(
            problem, dtype, history=history, geometry=geometry,
            theta=theta, config=config_from_knobs(problem, tuned_knobs),
        )
    elif engine in PRECOND_ENGINES:
        # the multigrid / Chebyshev preconditioned classical loop: the
        # hierarchy + Lanczos bounds are resolved at build time, the
        # V-cycle/polynomial runs inside the fused while_loop
        # (mg.engine); tuned knobs override the probed config's cycle
        # shape (the interval stays the probe's)
        from poisson_ellipse_tpu.mg.engine import build_precond_solver

        solver, args, _ = build_precond_solver(
            problem, engine, dtype, history=history, geometry=geometry,
            theta=theta, overrides=tuned_knobs,
        )
    elif engine in ("pipelined", "pipelined-pallas"):
        from poisson_ellipse_tpu.ops.pipelined_pcg import pcg_pipelined

        import jax

        a, b, rhs = assembly.assemble(problem, dtype, geometry=geometry,
                                      theta=theta)
        stencil = "pallas" if engine == "pipelined-pallas" else "xla"
        # no donation: same build-once-call-many contract as the xla path
        solver = jax.jit(  # tpulint: disable=TPU004
            lambda a, b, rhs: pcg_pipelined(
                problem, a, b, rhs, stencil=stencil, interpret=interpret,
                history=history, storage_dtype=storage_dtype,
            )
        )
        args = (a, b, rhs)
    elif engine in SSTEP_ENGINES:
        from poisson_ellipse_tpu.ops.sstep_pcg import pcg_sstep

        import jax

        if history:
            raise ValueError(
                "the s-step engines advance in coordinate blocks and do "
                "not record the per-iteration obs.convergence buffers; "
                "use a HISTORY_ENGINES engine for history=True"
            )
        a, b, rhs = assembly.assemble(problem, dtype, geometry=geometry,
                                      theta=theta)
        stencil = "pallas" if engine == "sstep-pallas" else "xla"
        solver = jax.jit(  # tpulint: disable=TPU004
            lambda a, b, rhs: pcg_sstep(
                problem, a, b, rhs, s=sstep_s, stencil=stencil,
                interpret=interpret, storage_dtype=storage_dtype,
            )
        )
        args = (a, b, rhs)
    elif engine in ("xla", "pallas"):
        # "pallas" = the XLA while_loop driving the per-op Pallas stencil
        # kernel (stage4's one-kernel-per-op structure on one chip)
        import jax

        a, b, rhs = assembly.assemble(problem, dtype, geometry=geometry,
                                      theta=theta)
        stencil = engine
        # no donation: the build-once-call-many contract re-feeds these
        # operands on every dispatch (bench --repeat, chained solves)
        solver = jax.jit(  # tpulint: disable=TPU004
            lambda a, b, rhs: pcg(
                problem, a, b, rhs, stencil=stencil, history=history,
                storage_dtype=storage_dtype,
            )
        )
        args = (a, b, rhs)
    else:
        raise ValueError(f"unknown engine: {engine!r} (choose from {ENGINES})")
    return solver, args, engine


def solve(
    problem: Problem, engine: str = "auto", dtype=jnp.float32, interpret=None,
    history: bool = False, lanes: int = 1, geometry=None, theta=None,
    validate_geometry: bool = True, storage_dtype=None, sstep_s: int = 4,
):
    """Assemble and solve single-chip with the selected engine.

    ``history=True`` returns ``(PCGResult, obs.ConvergenceTrace)`` — the
    on-device per-iteration convergence telemetry (see ``build_solver``).
    ``lanes`` selects the batch width of the batched engines, whose
    result is per-lane (see ``build_solver``). ``geometry``/``theta``
    select an arbitrary SDF domain through the admissibility gate (see
    ``build_solver``; exit-8 classified rejection before dispatch).
    """
    solver, args, _ = build_solver(
        problem, engine, dtype, interpret=interpret, history=history,
        lanes=lanes, geometry=geometry, theta=theta,
        validate_geometry=validate_geometry, storage_dtype=storage_dtype,
        sstep_s=sstep_s,
    )
    return solver(*args)
