"""Minimal SARIF 2.1.0 writer/reader shared by tpulint and the contract
matrix CLI.

SARIF is the one format both GitHub code scanning and most CI annotators
ingest natively, so both static passes emit the same subset: one ``run``
per tool, one ``result`` per finding with a physical location. The
reader inverts exactly what the writer emits — the round-trip the tests
pin — and deliberately nothing more (full SARIF is a spec, not a
weekend).

Pure stdlib on purpose: the linter never imports JAX, and this module is
imported from the lint CLI.
"""

from __future__ import annotations

import json
from typing import Iterable, Optional

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def sarif_result(
    rule_id: str,
    message: str,
    *,
    path: Optional[str] = None,
    line: int = 1,
    col: int = 1,
    level: str = "error",
) -> dict:
    """One SARIF ``result`` object; ``path=None`` emits no location
    (matrix cells have no source file — the cell id is the rule)."""
    result: dict = {
        "ruleId": rule_id,
        "level": level,
        "message": {"text": message},
    }
    if path is not None:
        result["locations"] = [
            {
                "physicalLocation": {
                    "artifactLocation": {"uri": path},
                    "region": {"startLine": line, "startColumn": col},
                }
            }
        ]
    return result


def sarif_report(
    tool_name: str,
    results: Iterable[dict],
    *,
    rules: Optional[dict] = None,
    information_uri: str = "",
) -> dict:
    """The SARIF document: one run, the given results. ``rules`` maps
    rule id -> short description (the driver's rule table)."""
    driver: dict = {"name": tool_name, "rules": []}
    if information_uri:
        driver["informationUri"] = information_uri
    if rules:
        driver["rules"] = [
            {
                "id": rule_id,
                "shortDescription": {"text": text},
            }
            for rule_id, text in sorted(rules.items())
        ]
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{"tool": {"driver": driver}, "results": list(results)}],
    }


def findings_to_sarif(findings, tool_name: str = "tpulint",
                      rules: Optional[dict] = None) -> dict:
    """tpulint ``Finding``s -> SARIF document."""
    return sarif_report(
        tool_name,
        (
            sarif_result(
                f.code, f.message, path=f.path, line=f.line, col=f.col
            )
            for f in findings
        ),
        rules=rules,
    )


def sarif_findings(doc) -> list[tuple[str, str, int, int, str]]:
    """Invert :func:`findings_to_sarif`: (path, code, line, col, message)
    per result — the round-trip read the tests and baseline tooling use.
    Accepts a parsed document or a JSON string."""
    if isinstance(doc, str):
        doc = json.loads(doc)
    out = []
    for run in doc.get("runs", []):
        for result in run.get("results", []):
            locs = result.get("locations") or [{}]
            phys = locs[0].get("physicalLocation", {})
            path = phys.get("artifactLocation", {}).get("uri", "")
            region = phys.get("region", {})
            out.append(
                (
                    path,
                    result.get("ruleId", ""),
                    int(region.get("startLine", 1)),
                    int(region.get("startColumn", 1)),
                    result.get("message", {}).get("text", ""),
                )
            )
    return out
