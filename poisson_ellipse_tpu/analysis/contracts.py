"""Declarative engine contracts over traced jaxprs.

Every structural guarantee the engine zoo advertises — the pipelined
loop's ONE stacked psum per iteration, the classical 2-psum/4-ppermute
cadence, the s-step body's one reduction per s iterations, the V-cycle's
``halos_per_precond`` ppermute budget, ABFT-on/off collective identity,
the guard's byte-identical chunk advance, ``storage_dtype=None`` byte
identity, history-off costing zero — is one *contract*: a named
predicate over a traced computation, with its expected values derived
from ``solver.engine.ENGINE_CAPS``'s per-row ``contracts`` metadata.
An engine registered without that metadata is itself a finding
(``engine-metadata``): declaring the structural contract is part of
registering the engine.

The checks are ``jax.make_jaxpr``/``jax.eval_shape`` based — abstract
tracing through the real product builders, no solver compiles, no
devices beyond the host CPU mesh. Tests call :func:`assert_contract`
(the one-line form of the old hand-written jaxpr pins); the matrix
runner (``analysis.matrix`` / ``python -m poisson_ellipse_tpu.analysis``)
sweeps every applicable (engine × axis) cell.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from poisson_ellipse_tpu.analysis import jaxpr_scan
from poisson_ellipse_tpu.models.problem import Problem

# deviations-from-default schema for ENGINE_CAPS rows' ``contracts``
# key; an unknown key in a row is a finding (a typo'd contract would
# otherwise silently never run)
CONTRACT_DEFAULTS: dict = {
    # single-chip trace carries zero collective primitives
    "single_collective_free": True,
    # sharded while-body cadence: psums per body (None = no sharded form)
    "sharded_psum": None,
    # halo exchanges per sharded body, 4 ppermutes each; "precond" =
    # stencil + halos_per_precond(cfg); None = deliberately unpinned
    "sharded_halo": None,
    # lane-sharded (batched mesh) cadence
    "batched_psum": None,
    "batched_halo": None,
    # the ABFT stepper adds ZERO collectives (on/off identity)
    "abft": False,
    # guard adapter family whose chunk advance traces byte-identically
    "guard": None,
    # storage_dtype=None traces the byte-identical pre-storage jaxpr
    "storage_identity": False,
    # bf16 storage: the body widens on load and narrows on store
    "storage_narrow": False,
    # history=True stays device-resident; history=False adds no DUS
    "history_resident": False,
    # fmg: whole-trace ppermute budget (halos_per_fcycle) applies
    "fcycle_budget": False,
    # fleet survivability: the kill→rejoin chaos drill's invariants
    # (zero-lost, zero-double, no cross-epoch co-ownership, no silent
    # starvation) hold, and the verdict is sensitive to each of them
    "fleet_chaos": False,
    # Krylov recycling (solver.recycle): recycle=None/x0=None trace the
    # byte-identical default jaxpr, and the sharded deflated init folds
    # its k deflation dots into ONE stacked psum — 2 psums total (the
    # stack + zr₀), zero while bodies, for ANY k
    "recycle": False,
}

# classical carry width: the history-off loop must keep the original
# 8-tuple carry (a 9th outvar means the telemetry leaked into the
# default path) — a property of the classical recurrence, keyed here
# because only the classical engine pins it
_HISTORY_OUTVARS = {"xla": 8}

# contract kind -> one-line description (the --list-contracts table and
# the README row source)
CONTRACT_KINDS = {
    "engine-metadata": (
        "every ENGINE_CAPS row declares a contracts dict with known keys"
    ),
    "single-collective-free": (
        "single-chip trace holds zero collective primitives"
    ),
    "collective-cadence": (
        "sharded while-body psum/ppermute counts match the declared "
        "cadence (halo budgets via halos_per_precond where declared)"
    ),
    "batched-cadence": (
        "lane-sharded while body holds exactly the declared collectives "
        "(one convergence-word psum, zero ppermutes)"
    ),
    "abft-identity": (
        "the ABFT stepper's per-body collective counts equal the "
        "unchecked stepper's — fault detection adds zero collectives"
    ),
    "guard-overhead": (
        "the guard adapter's chunk advance traces the byte-identical "
        "jaxpr of the unguarded advance"
    ),
    "storage-identity": (
        "storage_dtype=None traces the byte-identical pre-storage jaxpr"
    ),
    "storage-narrow": (
        "a bf16-storage loop body widens narrow state on load and "
        "narrows on store (no narrow leg under full-width builds)"
    ),
    "history-free": (
        "history=False traces the byte-identical default jaxpr with no "
        "dynamic_update_slice (and the original carry width)"
    ),
    "history-resident": (
        "history=True records via dynamic_update_slice with no host "
        "callbacks — device-resident telemetry"
    ),
    "fcycle-budget": (
        "the sharded F-cycle's whole-trace ppermute total equals the "
        "halos_per_fcycle budget — no hidden exchanges"
    ),
    "fleet-chaos": (
        "a kill→rejoin fleet drill completes every request exactly once "
        "with no cross-epoch co-ownership, and the chaos verdict is "
        "sensitive to every survivability invariant field"
    ),
    "recycle-deflation": (
        "recycle=None/x0=None trace the byte-identical default jaxpr; "
        "the sharded deflated init holds exactly 2 psums (k dots folded "
        "into one stack) and zero while bodies for any k"
    ),
}


@dataclasses.dataclass(frozen=True)
class Violation:
    """One broken contract at one matrix cell."""

    kind: str
    engine: str
    message: str

    def render(self) -> str:
        return f"{self.engine}: {self.kind}: {self.message}"


@dataclasses.dataclass(frozen=True)
class ContractResult:
    """The outcome of one contract check at one cell."""

    kind: str
    engine: str
    status: str  # "pass" | "fail"
    expected: object = None
    actual: object = None
    violations: tuple[Violation, ...] = ()


def engine_contract_spec(engine: str, caps: Optional[dict] = None) -> dict:
    """The engine's full contract spec: row metadata over the defaults.

    Raises ``KeyError`` for an unregistered engine and ``ValueError``
    for a row without ``contracts`` metadata or with unknown keys — the
    same conditions the ``engine-metadata`` contract reports as
    findings.
    """
    if caps is None:
        from poisson_ellipse_tpu.solver.engine import ENGINE_CAPS

        caps = ENGINE_CAPS
    row = caps[engine]
    if "contracts" not in row:
        raise ValueError(
            f"engine {engine!r} is registered without contract metadata "
            "(ENGINE_CAPS row has no 'contracts' key)"
        )
    declared = row["contracts"]
    unknown = set(declared) - set(CONTRACT_DEFAULTS)
    if unknown:
        raise ValueError(
            f"engine {engine!r} declares unknown contract key(s): "
            f"{', '.join(sorted(unknown))}"
        )
    spec = dict(CONTRACT_DEFAULTS)
    spec.update(declared)
    return spec


def check_engine_metadata(caps: Optional[dict] = None) -> list[Violation]:
    """The registration gate: every row carries well-formed contract
    metadata. A new engine without it is named here, before any cell
    runs."""
    if caps is None:
        from poisson_ellipse_tpu.solver.engine import ENGINE_CAPS

        caps = ENGINE_CAPS
    out: list[Violation] = []
    for engine in caps:
        try:
            engine_contract_spec(engine, caps)
        except ValueError as e:
            out.append(Violation("engine-metadata", engine, str(e)))
    return out


# -- builders (the real product entry points, traced abstractly) -------------


def _mesh(mesh_shape):
    from poisson_ellipse_tpu.harness.run import resolve_mesh

    return resolve_mesh(tuple(mesh_shape))


def _build_single(problem: Problem, engine: str, dtype, **kw):
    from poisson_ellipse_tpu.solver.engine import ENGINE_CAPS, build_solver

    if ENGINE_CAPS[engine]["family"] == "batched":
        kw.setdefault("lanes", 2)
    solver, args, _ = build_solver(problem, engine, dtype, **kw)
    return solver, args


def _build_sharded(problem: Problem, engine: str, dtype, mesh_shape,
                   sstep_s: int = 4):
    from poisson_ellipse_tpu.obs.static_cost import _build

    return _build(problem, engine, dtype, "sharded", tuple(mesh_shape),
                  sstep_s=sstep_s)


def _abstract_state(init_fn):
    # the pins read the JAXPR only — eval_shape keeps the stepper state
    # abstract, so nothing is compiled or dispatched to shape the trace
    return jax.eval_shape(init_fn)


def _build_stepper(problem: Problem, engine: str, dtype, mesh, abft: bool,
                   sstep_s: int = 4):
    if engine in ("xla", "pallas", "fused"):
        from poisson_ellipse_tpu.parallel.pcg_sharded import (
            build_sharded_stepper,
        )

        return build_sharded_stepper(problem, mesh, dtype, abft=abft)
    if engine == "pipelined":
        from poisson_ellipse_tpu.parallel.pipelined_sharded import (
            build_pipelined_sharded_stepper,
        )

        return build_pipelined_sharded_stepper(problem, mesh, dtype, abft=abft)
    if engine in ("mg-pcg", "cheb-pcg"):
        from poisson_ellipse_tpu.parallel.mg_sharded import (
            build_mg_sharded_stepper,
        )
        from poisson_ellipse_tpu.solver.engine import PRECOND_KIND_BY_ENGINE

        init, adv, _rec = build_mg_sharded_stepper(
            problem, mesh, dtype, kind=PRECOND_KIND_BY_ENGINE[engine],
            abft=abft,
        )
        return init, adv
    if engine == "sstep":
        from poisson_ellipse_tpu.parallel.sstep_sharded import (
            build_sstep_sharded_stepper,
        )

        return build_sstep_sharded_stepper(problem, mesh, dtype, s=sstep_s,
                                           abft=abft)
    raise ValueError(f"engine {engine!r} has no sharded stepper form")


def _precond_halos(problem: Problem, engine: str) -> int:
    """Halo exchanges per body for the preconditioned loops: the fine
    stencil + the V-cycle/Chebyshev budget — exactly the expression the
    hand-written pins used."""
    from poisson_ellipse_tpu.mg.engine import default_config
    from poisson_ellipse_tpu.parallel.mg_sharded import halos_per_precond

    if engine == "fmg":
        from poisson_ellipse_tpu.mg import coarsen

        return 1 + halos_per_precond(coarsen.num_levels(problem.M, problem.N))
    kind = {"mg-pcg": "mg", "cheb-pcg": "cheb"}[engine]
    cfg = default_config(problem, kind)
    return 1 + halos_per_precond(
        cfg.levels,
        cfg.nu,
        cfg.coarse_degree if kind == "mg" else cfg.cheb_degree,
    )


# -- the contract checks -----------------------------------------------------


def _result(kind, engine, expected, actual, messages) -> ContractResult:
    violations = tuple(Violation(kind, engine, m) for m in messages)
    return ContractResult(
        kind=kind,
        engine=engine,
        status="fail" if violations else "pass",
        expected=expected,
        actual=actual,
        violations=violations,
    )


def _check_single_collective_free(engine, spec, problem, dtype, **_):
    solver, args = _build_single(problem, engine, dtype)
    counts = jaxpr_scan.count_primitives(
        jaxpr_scan.trace(solver, args).jaxpr, jaxpr_scan.COLLECTIVE_PRIMS
    )
    total = {k: v for k, v in counts.items() if v}
    msgs = (
        [f"single-chip trace holds collectives: {total}"] if total else []
    )
    return _result(
        "single-collective-free", engine, {}, total, msgs
    )


def _cadence_expected(engine, spec, problem, sstep_s):
    """(psum, ppermute-or-None) per sharded while body, derived from the
    contracts row — the exact values the hand pins asserted."""
    psum = spec["sharded_psum"]
    halo = spec["sharded_halo"]
    if halo is None:
        return psum, None
    if halo == "precond":
        return psum, 4 * _precond_halos(problem, engine)
    return psum, 4 * int(halo)


def _check_collective_cadence(engine, spec, problem, dtype, mesh_shape,
                              sstep_s=4, expect=None, **_):
    solver, args = _build_sharded(problem, engine, dtype, mesh_shape,
                                  sstep_s=sstep_s)
    counts = jaxpr_scan.loop_primitive_counts(solver, args)
    psum = counts.get("psum", 0) + counts.get("psum_invariant", 0)
    ppermute = counts.get("ppermute", 0)
    want_psum, want_pp = (
        expect if expect is not None
        else _cadence_expected(engine, spec, problem, sstep_s)
    )
    msgs = []
    if psum != want_psum:
        msgs.append(
            f"sharded while body holds {psum} psum(s), contract says "
            f"{want_psum} (counts: {counts})"
        )
    if want_pp is not None and ppermute != want_pp:
        msgs.append(
            f"sharded while body holds {ppermute} ppermute(s), contract "
            f"says {want_pp} (counts: {counts})"
        )
    return _result(
        "collective-cadence", engine,
        {"psum": want_psum, "ppermute": want_pp},
        {"psum": psum, "ppermute": ppermute}, msgs,
    )


def _check_batched_cadence(engine, spec, problem, dtype, mesh_shape,
                           lanes=4, expect=None, **_):
    from poisson_ellipse_tpu.parallel.batched_sharded import (
        build_batched_sharded_solver,
    )

    mesh = _mesh(mesh_shape)
    solver, args = build_batched_sharded_solver(
        problem, mesh, lanes=lanes, dtype=dtype,
        pipelined=(engine == "batched-pipelined"),
    )
    counts = jaxpr_scan.loop_primitive_counts(solver, args)
    psum = counts.get("psum", 0) + counts.get("psum_invariant", 0)
    ppermute = counts.get("ppermute", 0)
    want_psum, want_pp = (
        expect if expect is not None
        else (spec["batched_psum"], 4 * int(spec["batched_halo"]))
    )
    msgs = []
    if psum != want_psum:
        msgs.append(
            f"lane-sharded while body holds {psum} psum(s), contract "
            f"says {want_psum} (counts: {counts})"
        )
    if ppermute != want_pp:
        msgs.append(
            f"lane-sharded while body holds {ppermute} ppermute(s), "
            f"contract says {want_pp} (counts: {counts})"
        )
    return _result(
        "batched-cadence", engine,
        {"psum": want_psum, "ppermute": want_pp},
        {"psum": psum, "ppermute": ppermute}, msgs,
    )


def _check_abft_identity(engine, spec, problem, dtype, mesh_shape,
                         sstep_s=4, **_):
    mesh = _mesh(mesh_shape)
    per_flag = {}
    for flag in (False, True):
        init_fn, advance_fn = _build_stepper(problem, engine, dtype, mesh,
                                             abft=flag, sstep_s=sstep_s)
        state = _abstract_state(init_fn)
        per_flag[flag] = jaxpr_scan.loop_collectives(advance_fn, (state, 10))
    msgs = []
    if per_flag[True] != per_flag[False]:
        msgs.append(
            f"ABFT changes the per-body collectives: off={per_flag[False]} "
            f"on={per_flag[True]}"
        )
    want_psum = spec["sharded_psum"]
    if want_psum is not None and per_flag[True][0] != want_psum:
        msgs.append(
            f"ABFT stepper body holds {per_flag[True][0]} psum(s), "
            f"contract says {want_psum}"
        )
    return _result(
        "abft-identity", engine,
        {"off==on": True, "psum": want_psum},
        {"off": per_flag[False], "on": per_flag[True]}, msgs,
    )


def _check_guard_overhead(engine, spec, problem, dtype, **_):
    from poisson_ellipse_tpu.resilience.guard import (
        _ClassicalAdapter,
        _PipelinedAdapter,
    )

    lim = jax.ShapeDtypeStruct((), jnp.int32)
    if spec["guard"] == "classical":
        from poisson_ellipse_tpu.solver.pcg import advance as plain_advance

        adapter = _ClassicalAdapter(problem, dtype)
    else:
        from poisson_ellipse_tpu.ops.pipelined_pcg import (
            advance as plain_advance,
        )

        adapter = _PipelinedAdapter(problem, dtype)
    a, b, rhs = adapter._operands
    state = _abstract_state(adapter.init)
    guarded = jaxpr_scan.trace_text(adapter.advance_fn, (state, lim))
    plain = jaxpr_scan.trace_text(
        lambda s, l: plain_advance(problem, a, b, rhs, s, limit=l),
        (state, lim),
    )
    msgs = (
        []
        if guarded == plain
        else [
            "guard adapter advance jaxpr differs from the unguarded "
            "advance (zero-overhead-when-healthy broken)"
        ]
    )
    return _result(
        "guard-overhead", engine, {"identical": True},
        {"identical": guarded == plain}, msgs,
    )


def _storage_pair(engine, problem, dtype):
    """(default trace, storage_dtype=None trace) through the ops-level
    recurrence — the byte-identity the storage axis promised."""
    from poisson_ellipse_tpu.ops import assembly

    a, b, rhs = assembly.assemble(problem, dtype)
    if engine == "pipelined":
        from poisson_ellipse_tpu.ops.pipelined_pcg import pcg_pipelined as fn
    else:
        from poisson_ellipse_tpu.solver.pcg import pcg as fn
    base = jaxpr_scan.trace_text(lambda *o: fn(problem, *o), (a, b, rhs))
    none = jaxpr_scan.trace_text(
        lambda *o: fn(problem, *o, storage_dtype=None), (a, b, rhs)
    )
    return base, none


def _check_storage_identity(engine, spec, problem, dtype, **_):
    base, none = _storage_pair(engine, problem, dtype)
    msgs = (
        []
        if base == none
        else [
            "storage_dtype=None traces a different jaxpr than the "
            "pre-storage path (the free-when-off axis regressed)"
        ]
    )
    return _result(
        "storage-identity", engine, {"identical": True},
        {"identical": base == none}, msgs,
    )


def _check_storage_narrow(engine, spec, problem, dtype, sstep_s=4, **_):
    solver, args = _build_single(problem, engine, dtype,
                                 storage_dtype="bf16")
    closed = jaxpr_scan.trace(solver, args)
    bodies = jaxpr_scan.while_bodies(closed.jaxpr)
    pairs = [p for body in bodies for p in
             jaxpr_scan.convert_dtype_pairs(body)]
    widens = any(src == "bfloat16" and dst != "bfloat16"
                 for src, dst in pairs)
    narrows = any(dst == "bfloat16" and src != "bfloat16"
                  for src, dst in pairs)
    msgs = []
    if not widens:
        msgs.append(
            "bf16-storage loop body never widens a narrow value — the "
            "compute path is running at storage width"
        )
    if not narrows:
        msgs.append(
            "bf16-storage loop body never narrows back to storage — the "
            "state is being carried at full width (no bandwidth cut)"
        )
    return _result(
        "storage-narrow", engine, {"widens": True, "narrows": True},
        {"widens": widens, "narrows": narrows}, msgs,
    )


def _check_history_free(engine, spec, problem, dtype, **_):
    solver_default, args = _build_single(problem, engine, dtype)
    solver_off, _ = _build_single(problem, engine, dtype, history=False)
    base = jaxpr_scan.trace_text(solver_default, args)
    off = jaxpr_scan.trace_text(solver_off, args)
    msgs = []
    if base != off:
        msgs.append(
            "history=False traces a different jaxpr than the default "
            "build — the telemetry axis is not free when off"
        )
    if "dynamic_update_slice" in base:
        msgs.append(
            "the default (history-off) trace contains "
            "dynamic_update_slice — recording leaked into the hot path"
        )
    want_outvars = _HISTORY_OUTVARS.get(engine)
    got_whiles, got_outvars = None, None
    if want_outvars is not None:
        bodies = jaxpr_scan.while_bodies(
            jaxpr_scan.trace(solver_default, args).jaxpr
        )
        got_whiles = len(bodies)
        if got_whiles != 1:
            msgs.append(
                f"expected exactly 1 while loop in the default trace, "
                f"found {got_whiles}"
            )
        else:
            got_outvars = len(bodies[0].outvars)
            if got_outvars != want_outvars:
                msgs.append(
                    f"history-off carry widened: {got_outvars} outvars, "
                    f"contract says {want_outvars}"
                )
    return _result(
        "history-free", engine,
        {"identical": True, "dus": False, "outvars": want_outvars},
        {"identical": base == off, "dus": "dynamic_update_slice" in base,
         "outvars": got_outvars}, msgs,
    )


def _check_history_resident(engine, spec, problem, dtype, **_):
    solver, args = _build_single(problem, engine, dtype, history=True)
    text = jaxpr_scan.trace_text(solver, args)
    msgs = []
    if "dynamic_update_slice" not in text:
        msgs.append(
            "history=True trace holds no dynamic_update_slice — the "
            "on-device recording buffers are gone"
        )
    for host_prim in ("callback", "device_get"):
        if host_prim in text:
            msgs.append(
                f"history=True trace contains {host_prim!r} — telemetry "
                "must stay device-resident (zero host syncs)"
            )
    return _result(
        "history-resident", engine,
        {"dus": True, "callbacks": False},
        {"dus": "dynamic_update_slice" in text,
         "callbacks": any(p in text for p in ("callback", "device_get"))},
        msgs,
    )


def _check_fcycle_budget(engine, spec, problem, dtype, mesh_shape, **_):
    from poisson_ellipse_tpu.mg import coarsen
    from poisson_ellipse_tpu.mg.fmg import DEFAULT_FMG_VCYCLES
    from poisson_ellipse_tpu.parallel.mg_sharded import (
        halos_per_fcycle,
        halos_per_precond,
    )

    solver, args = _build_sharded(problem, engine, dtype, mesh_shape)
    closed = jaxpr_scan.trace(solver, args)
    total = jaxpr_scan.count_primitives(closed.jaxpr, ("ppermute",))
    levels = coarsen.num_levels(problem.M, problem.N)
    fcycle = halos_per_fcycle(levels, n_vcycles=DEFAULT_FMG_VCYCLES)
    per_loop = 1 + halos_per_precond(levels)
    # budget: levels' (a, b) coefficient extensions (two exchanges per
    # level, once per dispatch), ONE F-cycle, init's precond+stencil,
    # and the handoff-loop body — exactly the hand pin's expression
    want = 4 * (2 * levels + fcycle + 2 * per_loop)
    got = total["ppermute"]
    msgs = (
        []
        if got == want
        else [
            f"whole-trace ppermute total {got} != budget {want} "
            f"(levels={levels}, fcycle={fcycle}) — a hidden exchange"
        ]
    )
    return _result(
        "fcycle-budget", engine, {"ppermute_total": want},
        {"ppermute_total": got}, msgs,
    )


# the fleet invariant fields ChaosReport.ok must fold over — the
# sensitivity probe poisons each one and demands the verdict flips
_FLEET_INVARIANT_PROBES = {
    "lost": ["chaos-0000"],
    "double_completed": ["chaos-0000"],
    "unclassified": ["chaos-0000"],
    "grad_missing_payload": ["chaos-0000"],
    "co_owned": ["chaos-0000"],
    "starved_silent": ["batch"],
}


def _check_fleet_chaos(engine, spec, problem, dtype, expect=None, **_):
    """Two prongs. (1) Verdict sensitivity: ``ChaosReport.ok`` must go
    False when any survivability invariant field is poisoned — a verdict
    that ignored co-ownership or silent starvation would let the chaos
    gate rot while still reading green. (2) A live kill→rejoin drill on
    the tiny grid must come back ok with the rejoin and handoff actually
    executed (a drill that never exercises the ladder proves nothing).

    ``expect`` (a dict of report-field overrides, applied to the live
    drill's report before judging) is the injected-violation hook the
    fire fixtures use.
    """
    import os
    import tempfile

    from poisson_ellipse_tpu.serve.chaos import ChaosReport, run_chaos

    del spec, dtype
    msgs = []
    base = dict(
        n_requests=1, outcomes={}, counts={}, lost=[],
        double_completed=[], unclassified=[], replayed=0, killed=True,
        faults_fired=0, wall_s=0.0,
    )
    insensitive = [
        name
        for name, poison in _FLEET_INVARIANT_PROBES.items()
        if ChaosReport(**{**base, name: poison}).ok
    ]
    if insensitive:
        msgs.append(
            "ChaosReport.ok ignores invariant field(s) "
            f"{', '.join(insensitive)} — a broken drill would read ok"
        )
    with tempfile.TemporaryDirectory() as tmp:
        report = run_chaos(
            n_requests=6, seed=0, grids=((problem.M, problem.N),),
            chunk=2, journal_path=os.path.join(tmp, "chaos.jsonl"),
            nan_request=None, oom_request=None,
            replicas=2, replica_kill=2, replica_rejoin=4,
        )
    if expect:
        report = dataclasses.replace(report, **dict(expect))
    if not report.ok:
        evidence = {
            name: getattr(report, name)
            for name in _FLEET_INVARIANT_PROBES
            if getattr(report, name)
        }
        msgs.append(
            f"kill→rejoin drill broke its invariants: {evidence}"
        )
    if report.rejoins < 1:
        msgs.append(
            f"drill executed {report.rejoins} rejoin(s); the ladder "
            "never ran, so the verdict pins nothing"
        )
    if report.handoffs < 1:
        msgs.append(
            f"drill executed {report.handoffs} handoff(s); the kill "
            "never orphaned work, so adoption went unexercised"
        )
    return _result(
        "fleet-chaos", engine,
        {"insensitive": [], "ok": True, "rejoins_min": 1,
         "handoffs_min": 1},
        {"insensitive": insensitive, "ok": report.ok,
         "rejoins": report.rejoins, "handoffs": report.handoffs},
        msgs,
    )


def _check_recycle_deflation(engine, spec, problem, dtype, mesh_shape,
                             **_):
    """Both halves of the recycling contract. Off-path: ``recycle=None``
    + ``x0=None`` must trace the byte-identical jaxpr of the default
    solve (the ring capture is free when off). On-path: the sharded
    deflated init (``solver.recycle.build_deflated_sharded_init``) must
    hold exactly 2 psums — the k deflation dots Wᵀ·rhs folded into ONE
    stacked psum, plus the carry's zr₀ — and ZERO while bodies,
    independent of k (deflation lives entirely outside the loop; the
    advance cadence is the collective-cadence cell's, unchanged)."""
    from poisson_ellipse_tpu.ops import assembly
    from poisson_ellipse_tpu.parallel.mesh import padded_dims
    from poisson_ellipse_tpu.solver import recycle
    from poisson_ellipse_tpu.solver.pcg import pcg

    a, b, rhs = assembly.assemble(problem, dtype)
    base = jaxpr_scan.trace_text(lambda *o: pcg(problem, *o), (a, b, rhs))
    off = jaxpr_scan.trace_text(
        lambda *o: pcg(problem, *o, x0=None, recycle=None), (a, b, rhs)
    )
    identical = base == off
    msgs = []
    if not identical:
        msgs.append(
            "recycle=None/x0=None traces a different jaxpr than the "
            "default solve — the capture axis is not free when off"
        )
    mesh = _mesh(mesh_shape)
    g1p, g2p = padded_dims(problem.node_shape, mesh)
    init_fn = recycle.build_deflated_sharded_init(
        problem, mesh=mesh, dtype=dtype
    )
    grid = jax.ShapeDtypeStruct((g1p, g2p), dtype)
    per_k = {}
    for k in (2, 8):
        closed = jaxpr_scan.trace(
            init_fn,
            (grid, grid, grid,
             jax.ShapeDtypeStruct((k, g1p, g2p), dtype),
             jax.ShapeDtypeStruct((k, k), dtype)),
        )
        counts = jaxpr_scan.count_primitives(
            closed.jaxpr, jaxpr_scan.COLLECTIVE_PRIMS
        )
        psum = counts.get("psum", 0) + counts.get("psum_invariant", 0)
        bodies = len(jaxpr_scan.while_bodies(closed.jaxpr))
        per_k[k] = {"psum": psum, "whiles": bodies}
        if psum != 2:
            msgs.append(
                f"deflated sharded init holds {psum} psum(s) at k={k}; "
                "the fold promises exactly 2 (stacked Wᵀr + zr₀) for "
                "any k"
            )
        if bodies != 0:
            msgs.append(
                f"deflated sharded init holds {bodies} while bodies at "
                f"k={k}; deflation must stay entirely outside the loop"
            )
    return _result(
        "recycle-deflation", engine,
        {"identical": True, "init_psums": 2, "init_whiles": 0},
        {"identical": identical, "per_k": per_k}, msgs,
    )


_CHECKERS = {
    "single-collective-free": _check_single_collective_free,
    "collective-cadence": _check_collective_cadence,
    "batched-cadence": _check_batched_cadence,
    "abft-identity": _check_abft_identity,
    "guard-overhead": _check_guard_overhead,
    "storage-identity": _check_storage_identity,
    "storage-narrow": _check_storage_narrow,
    "history-free": _check_history_free,
    "history-resident": _check_history_resident,
    "fcycle-budget": _check_fcycle_budget,
    "fleet-chaos": _check_fleet_chaos,
    "recycle-deflation": _check_recycle_deflation,
}


def contract_applies(kind: str, engine: str,
                     caps: Optional[dict] = None) -> bool:
    """Whether ``kind`` is declared for ``engine`` — the applicability
    the matrix enumerates (a cell that does not apply is skipped with a
    reason, not silently dropped)."""
    spec = engine_contract_spec(engine, caps)
    return {
        "engine-metadata": True,
        "single-collective-free": spec["single_collective_free"],
        "collective-cadence": spec["sharded_psum"] is not None,
        "batched-cadence": spec["batched_psum"] is not None,
        "abft-identity": spec["abft"],
        "guard-overhead": spec["guard"] is not None,
        "storage-identity": spec["storage_identity"],
        "storage-narrow": spec["storage_narrow"],
        "history-free": spec["history_resident"],
        "history-resident": spec["history_resident"],
        "fcycle-budget": spec["fcycle_budget"],
        "fleet-chaos": spec["fleet_chaos"],
        "recycle-deflation": spec["recycle"],
    }[kind]


def default_problem(engine: str) -> Problem:
    """The tiny trace grid: 16×16 everywhere (the fmg pin's size; counts
    are grid-independent, budgets are derived per grid)."""
    del engine
    return Problem(M=16, N=16)


def check_contract(
    kind: str,
    engine: str,
    *,
    problem: Optional[Problem] = None,
    dtype=jnp.float32,
    mesh_shape: tuple[int, int] = (1, 2),
    expect=None,
    **kw,
) -> ContractResult:
    """Run one contract at one cell; returns the :class:`ContractResult`.

    ``expect`` overrides the ENGINE_CAPS-derived expected values (the
    injected-violation fixtures use it to prove a contract fires); the
    product path always derives from the capability table.
    """
    if kind not in CONTRACT_KINDS:
        raise ValueError(
            f"unknown contract kind {kind!r} "
            f"(known: {', '.join(sorted(CONTRACT_KINDS))})"
        )
    if kind == "engine-metadata":
        violations = tuple(check_engine_metadata())
        return ContractResult(
            kind=kind, engine=engine,
            status="fail" if violations else "pass",
            violations=violations,
        )
    spec = engine_contract_spec(engine)
    if not contract_applies(kind, engine):
        raise ValueError(
            f"contract {kind!r} does not apply to engine {engine!r} "
            "(not declared in its ENGINE_CAPS contracts row)"
        )
    if problem is None:
        problem = default_problem(engine)
    return _CHECKERS[kind](
        engine, spec, problem, dtype, mesh_shape=mesh_shape, expect=expect,
        **kw,
    )


def assert_contract(kind: str, engine: str, **kw) -> ContractResult:
    """The one-line test form: raise ``AssertionError`` naming every
    violation; return the result for callers that also want the facts."""
    result = check_contract(kind, engine, **kw)
    if result.violations:
        raise AssertionError(
            "; ".join(v.render() for v in result.violations)
        )
    return result
