"""CLI: ``python -m poisson_ellipse_tpu.analysis`` — the contract matrix.

Runs the full ENGINE_CAPS-derived engine × axis sweep on a tiny grid,
by abstract tracing only, on the CPU backend (forced here — the checker
needs no accelerator, and CI must not wait for one)::

    python -m poisson_ellipse_tpu.analysis                     # text
    python -m poisson_ellipse_tpu.analysis --format json
    python -m poisson_ellipse_tpu.analysis --format sarif -o out.sarif
    python -m poisson_ellipse_tpu.analysis --engine pipelined --axis sharded
    python -m poisson_ellipse_tpu.analysis --list-contracts

Exit status mirrors tpulint: 0 clean (including suppressed cells),
1 contract violations, 2 a cell errored out / bad usage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _force_cpu() -> None:
    """Pin the CPU backend with a virtual mesh BEFORE jax initialises —
    the same order-sensitive ritual the test conftest and the driver
    dryrun use (parallel.mesh.virtual_cpu_devices)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    from poisson_ellipse_tpu.parallel.mesh import virtual_cpu_devices

    virtual_cpu_devices(8)
    jax.config.update("jax_enable_x64", True)


def main(argv=None) -> int:
    from poisson_ellipse_tpu.analysis.contracts import CONTRACT_KINDS

    parser = argparse.ArgumentParser(
        prog="python -m poisson_ellipse_tpu.analysis",
        description="Jaxpr-level engine-contract matrix (expected values "
        "from solver.engine.ENGINE_CAPS; suppress cells via "
        "[tool.engine_contracts] in pyproject.toml).",
    )
    parser.add_argument(
        "--engine", action="append", default=None,
        help="restrict to an engine (repeatable; default: every "
        "ENGINE_CAPS row)",
    )
    parser.add_argument(
        "--axis", action="append", default=None,
        choices=None, help="restrict to an axis (repeatable): single, "
        "sharded, batched, guarded, abft, storage, history",
    )
    parser.add_argument(
        "--grid", type=int, nargs=2, default=None, metavar=("M", "N"),
        help="trace grid (default 16 16)",
    )
    parser.add_argument(
        "--mesh", type=int, nargs=2, default=(1, 2), metavar=("PX", "PY"),
        help="mesh shape for the sharded cells (default 1 2)",
    )
    parser.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text",
        help="report format (default text)",
    )
    parser.add_argument(
        "-o", "--output", default=None,
        help="write the report to a file instead of stdout (text summary "
        "still prints)",
    )
    parser.add_argument(
        "--no-suppressions", action="store_true",
        help="ignore [tool.engine_contracts] suppress entries",
    )
    parser.add_argument(
        "--hash", action="store_true",
        help="print the canonical report hash (what bench rounds embed)",
    )
    parser.add_argument(
        "--list-contracts", action="store_true",
        help="print the contract-kind table and exit",
    )
    args = parser.parse_args(argv)

    if args.list_contracts:
        for kind, desc in CONTRACT_KINDS.items():
            print(f"{kind:24s} {desc}")
        return 0

    _force_cpu()
    from poisson_ellipse_tpu.analysis import matrix
    from poisson_ellipse_tpu.models.problem import Problem

    problem = Problem(M=args.grid[0], N=args.grid[1]) if args.grid else None
    try:
        report = matrix.run_matrix(
            tuple(args.engine) if args.engine else None,
            tuple(args.axis) if args.axis else None,
            problem=problem,
            mesh_shape=tuple(args.mesh),
            suppressions={} if args.no_suppressions else None,
        )
    except SystemExit as e:  # malformed suppress entry = bad usage
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.format == "json":
        rendered = json.dumps(report, indent=2, sort_keys=True)
    elif args.format == "sarif":
        rendered = json.dumps(
            matrix.report_to_sarif(report), indent=2, sort_keys=True
        )
    else:
        rendered = matrix.render_report(report)
    if args.output:
        with open(args.output, "w", encoding="utf-8") as f:
            f.write(rendered + "\n")
        print(matrix.render_report(report))
    else:
        print(rendered)
    if args.hash:
        print(f"report-hash: {matrix.report_hash(report)}")
    return matrix.exit_code(report)


if __name__ == "__main__":
    sys.exit(main())
