"""Jaxpr traversal: the one walk every structural pin reads through.

Every performance guarantee this framework advertises is *structural* —
1 stacked psum per pipelined iteration, a 4-``ppermute`` halo ring, no
``dynamic_update_slice`` when history is off, byte-identical jaxprs
across axes that claim to be free. Those facts live in the traced
computation, and this module is the single reader: ``jax.make_jaxpr``
based (abstract tracing only — no compiles, no devices), recursing into
every sub-jaxpr an equation carries (``while``/``cond``/``scan``/
``pjit``/``custom_*``/Pallas kernels alike, via the params walk).

``obs.static_cost`` consumes these primitives for its per-engine cost
reports, and ``analysis.contracts`` consumes them for the declarative
contract matrix — one traversal, two read paths, zero drift.
"""

from __future__ import annotations

import jax

# the collective primitives worth budgeting on a TPU mesh
# (psum_invariant is newer-jax spelling riding the same wire as psum)
COLLECTIVE_PRIMS = (
    "psum",
    "psum_invariant",
    "ppermute",
    "all_gather",
    "reduce_scatter",
    "all_to_all",
)


def subjaxprs(eqn):
    """Every sub-jaxpr hanging off one equation's params.

    Covers ``while`` (``cond_jaxpr``/``body_jaxpr``), ``cond``
    (``branches``), ``scan``/``pjit``/``closed_call`` (``jaxpr``),
    ``custom_jvp``/``custom_vjp`` and ``pallas_call`` — anything whose
    params hold an object with ``.eqns`` (open jaxpr) or ``.jaxpr.eqns``
    (closed jaxpr), scalar or in a list/tuple.
    """
    for v in eqn.params.values():
        vals = v if isinstance(v, (list, tuple)) else [v]
        for x in vals:
            if hasattr(x, "eqns"):
                yield x
            elif hasattr(x, "jaxpr") and hasattr(x.jaxpr, "eqns"):
                yield x.jaxpr


def walk_eqns(jaxpr):
    """Every equation in ``jaxpr``, recursively (depth-first, document
    order), including those inside sub-jaxprs."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in subjaxprs(eqn):
            yield from walk_eqns(sub)


def count_primitives(jaxpr, names: tuple[str, ...]) -> dict[str, int]:
    """Occurrences of each named primitive in ``jaxpr``, recursively."""
    counts = {name: 0 for name in names}
    for eqn in walk_eqns(jaxpr):
        if eqn.primitive.name in counts:
            counts[eqn.primitive.name] += 1
    return counts


def while_bodies(jaxpr):
    """Every ``while_loop`` body jaxpr in ``jaxpr`` (outermost-first),
    found recursively — nested loops and loops inside ``cond`` branches
    or ``pjit`` calls included."""
    out = []
    for eqn in walk_eqns(jaxpr):
        if eqn.primitive.name == "while":
            body = eqn.params["body_jaxpr"]
            out.append(body.jaxpr if hasattr(body, "jaxpr") else body)
    return out


def trace(fn, args):
    """``fn``'s closed jaxpr over abstract ``args`` — no compile, no
    execution, no devices."""
    return jax.make_jaxpr(fn)(*args)


def trace_text(fn, args) -> str:
    """The jaxpr's printed form — the byte-for-byte identity currency of
    the structural-identity pins (``storage_dtype=None``, guarded vs
    unguarded)."""
    return str(trace(fn, args))


def while_body_primitive_counts(fn, args, names: tuple[str, ...]) -> list[dict]:
    """Primitive counts inside each ``while_loop`` body of ``fn``'s
    jaxpr (one dict per loop, outermost-first)."""
    closed = trace(fn, args)
    return [count_primitives(body, names) for body in while_bodies(closed.jaxpr)]


def loop_primitive_counts(
    fn, args, names: tuple[str, ...] = COLLECTIVE_PRIMS
) -> dict[str, int]:
    """Per-iteration primitive counts: the sum over all while bodies.

    The solvers hold exactly one hot ``while_loop``; summing keeps the
    answer right if an engine ever splits its iteration across two.
    """
    merged = {name: 0 for name in names}
    for body in while_body_primitive_counts(fn, args, names):
        for name, n in body.items():
            merged[name] += n
    return merged


def loop_collectives(fn, args) -> tuple[int, int]:
    """(psum, ppermute) per while body, with the ``psum_invariant``
    spelling folded into psum (one collective on the wire). The compact
    pair every cadence pin compares."""
    counts = loop_primitive_counts(fn, args)
    return (
        counts.get("psum", 0) + counts.get("psum_invariant", 0),
        counts.get("ppermute", 0),
    )


def convert_dtype_pairs(jaxpr) -> list[tuple[str, str]]:
    """(src, dst) dtype-name pairs of every ``convert_element_type`` in
    ``jaxpr``, recursively — the storage-vs-compute seam reader: a
    narrow-storage build must widen on the HBM-read side and narrow on
    the store side; a full-width build must carry no narrow leg at all.
    """
    pairs: list[tuple[str, str]] = []
    for eqn in walk_eqns(jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        dst = eqn.params.get("new_dtype")
        try:
            src = eqn.invars[0].aval.dtype
        except (AttributeError, IndexError):
            continue
        pairs.append((str(src), str(dst)))
    return pairs
